"""Perf attribution + regression sentinel (ISSUE 6): per-stage
self-time breakdown (obs/profile.py + GET /profile + `tdn profile`),
on-demand device capture (GET /debug/profile), structured JSON logging
(obs/log.py), the int8 warmup payoff gauge, and tools/bench_gate.py.

The loopback acceptance path: a served engine hit through GrpcClient
must yield a /profile breakdown whose stage shares sum to within 5% of
the measured root-span wall time — for both the Process and Generate
wire paths. The bench gate must fail a synthetic >5% host-fed
regression, pass a -4% one, skip cleanly across backends, and exit
zero on the checked-in r04->r05 pair only in report-only mode.
"""

import dataclasses
import importlib.util
import io
import json
import logging
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request
import zipfile

import numpy as np
import pytest

from tpu_dist_nn.obs.profile import (
    HANDLER_STAGE,
    SpanRecord,
    compute_self_times,
    format_profile_table,
    profile_snapshot,
)
from tpu_dist_nn.obs.trace import TRACER, Tracer

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH_GATE = os.path.join(REPO_ROOT, "tools", "bench_gate.py")


def _load_bench_gate():
    spec = importlib.util.spec_from_file_location("bench_gate", BENCH_GATE)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _get(url: str, timeout: float = 10.0) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ------------------------------------------------------ self-time math


def _rec(name, span_id, parent_id, t0, dur, trace="t1"):
    return SpanRecord(name, trace, span_id, parent_id, t0, dur)


def test_self_time_nests_time_nested_siblings():
    """decode.step spans hang off the handler by parent id but run
    INSIDE the decode phase span — the innermost-cover sweep must
    attribute them there, not double-count them against the root."""
    records = [
        _rec("rpc.Generate", "root", None, 0.0, 10.0),
        _rec("decode", "dec", "root", 2.0, 8.0),
        # parented to root, contained in dec:
        _rec("decode.step", "s1", "root", 3.0, 1.0),
        _rec("decode.step", "s2", "root", 5.0, 1.0),
    ]
    selfs = compute_self_times(records)
    assert selfs["s1"] == pytest.approx(1.0)
    assert selfs["s2"] == pytest.approx(1.0)
    assert selfs["dec"] == pytest.approx(6.0)   # 8 - two 1s steps
    assert selfs["root"] == pytest.approx(2.0)  # 10 - dec's 8
    # Self times partition the root wall exactly.
    assert sum(selfs.values()) == pytest.approx(10.0)


def test_self_time_partitions_partially_overlapping_siblings():
    """Two rows of one Generate request decode concurrently in
    different slots: their phase spans partially overlap. The sweep
    still partitions the covered wall exactly once."""
    records = [
        _rec("rpc.Generate", "root", None, 0.0, 10.0),
        _rec("decode", "d0", "root", 1.0, 5.0),   # [1, 6]
        _rec("decode", "d1", "root", 4.0, 5.0),   # [4, 9] — overlaps d0
        _rec("decode.step", "s1", "root", 4.5, 1.0),  # inside both
    ]
    selfs = compute_self_times(records)
    assert selfs["s1"] == pytest.approx(1.0)
    # Overlap region [4, 6] belongs to d1 (latest start), minus the
    # step; d0 keeps [1, 4].
    assert selfs["d0"] == pytest.approx(3.0)
    assert selfs["d1"] == pytest.approx(4.0)
    assert selfs["root"] == pytest.approx(2.0)  # [0,1] + [9,10]
    assert sum(selfs.values()) == pytest.approx(10.0)


def test_self_time_handles_children_leaking_past_parent():
    records = [
        _rec("root", "r", None, 0.0, 4.0),
        # cross-thread child measured slightly past the parent's end
        _rec("fetch", "f", "r", 3.0, 2.0),
    ]
    selfs = compute_self_times(records)
    assert selfs["r"] == pytest.approx(3.0)
    assert selfs["f"] == pytest.approx(2.0)
    # Total covered time [0, 5] partitions exactly.
    assert sum(selfs.values()) == pytest.approx(5.0)


def test_profile_snapshot_shares_sum_and_window():
    t = Tracer(capacity=256, sample_rate=1.0, exemplar_slots=0)
    root = t.start("rpc.Process")
    time.sleep(0.02)
    t.record_span("queue_wait", root.ctx, root.t0, 0.008)
    t.record_span("fetch", root.ctx, root.t0 + 0.008, 0.008)
    root.end()
    doc = profile_snapshot(t, top=3)
    assert doc["traces"] == 1
    m = doc["methods"]["Process"]
    assert 0.95 <= m["share_sum"] <= 1.05
    stages = {s["stage"] for s in m["stages"]}
    assert {"queue_wait", "fetch", HANDLER_STAGE} <= stages
    assert m["slowest"] and len(m["slowest"][0]["trace_id"]) == 32
    # A window entirely in the future excludes the trace.
    later = time.monotonic() + 100.0
    empty = profile_snapshot(t, window=1.0, now=later)
    assert empty["traces"] == 0 and empty["methods"] == {}
    # The table renderer covers both shapes without crashing.
    assert "Process" in format_profile_table(doc)
    assert "no completed request traces" in format_profile_table(empty)


def test_client_spans_are_not_attribution_roots():
    """Loopback double-count guard: a client.Process span containing
    the handler must not become a second root for the same wall."""
    t = Tracer(capacity=64, sample_rate=1.0, exemplar_slots=0)
    client = t.start("client.Process")
    handler = t.start("rpc.Process", parent=client.ctx)
    time.sleep(0.005)
    handler.end()
    client.end()
    doc = profile_snapshot(t)
    assert doc["traces"] == 1
    assert set(doc["methods"]) == {"Process"}


# ------------------------------------------------- serving loopback


class FakeEngine:
    """input_dim + infer — all serve_engine requires (the test_trace
    pattern); a small sleep gives every stage measurable width."""

    def __init__(self, dim=8):
        self.model = dataclasses.make_dataclass("M", ["input_dim"])(dim)

    def infer(self, x):
        time.sleep(0.002)
        return np.asarray(x) * 3.0


def _profile_over_http(params="") -> dict:
    from tpu_dist_nn.obs import start_http_server

    server = start_http_server(0, host="127.0.0.1")
    try:
        status, body = _get(
            f"http://127.0.0.1:{server.port}/profile{params}"
        )
        assert status == 200
        return json.loads(body)
    finally:
        server.close()


def _assert_shares_match_walls(doc: dict, method: str) -> None:
    """The acceptance bar: stage shares sum to within 5% of the
    measured root wall, and the wall matches the recorder's spans."""
    m = doc["methods"][method]
    assert 0.95 <= m["share_sum"] <= 1.05, m
    roots = [
        s for s in TRACER.snapshot()
        if s.name == f"rpc.{method}" and s.dur is not None
    ]
    measured = sum(s.dur for s in roots)
    assert m["wall_seconds_total"] == pytest.approx(measured, rel=0.05)
    assert m["traces"] == len(roots)


def test_loopback_profile_process_shares_sum_to_wall():
    from tpu_dist_nn.serving import GrpcClient, serve_engine

    TRACER.reset()
    TRACER.configure(sample_rate=1.0)
    engine = FakeEngine(dim=8)
    server, port = serve_engine(engine, 0, host="127.0.0.1", coalesce=True)
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        for _ in range(4):
            client.process(np.full((3, 8), 2.0))
        client.close()
    finally:
        server.stop(0)
    doc = _profile_over_http()
    _assert_shares_match_walls(doc, "Process")
    stages = {s["stage"] for s in doc["methods"]["Process"]["stages"]}
    assert {"queue_wait", "stage", "launch", "fetch", "decode",
            "encode", HANDLER_STAGE} <= stages, stages


def test_loopback_profile_generate_shares_sum_to_wall():
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.serving import GrpcClient, serve_lm_generate

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(3), cfg)
    TRACER.reset()
    TRACER.configure(sample_rate=1.0)
    server, port = serve_lm_generate(
        params, cfg, 0, max_new_tokens=6, prompt_len=8, host="127.0.0.1",
        gen_slots=2, warm_rows=1,
    )
    try:
        assert server.scheduler is not None  # continuous path
        client = GrpcClient(f"127.0.0.1:{port}")
        rng = np.random.default_rng(0)
        for _ in range(3):
            client.generate(rng.integers(0, 64, (2, 8)))
        client.close()
    finally:
        server.stop(0)
    doc = _profile_over_http()
    _assert_shares_match_walls(doc, "Generate")
    stages = {s["stage"] for s in doc["methods"]["Generate"]["stages"]}
    assert {"queue_wait", "prefill", "decode", "decode.step",
            HANDLER_STAGE} <= stages, stages


def test_profile_route_rejects_garbled_params():
    from tpu_dist_nn.obs import start_http_server

    server = start_http_server(0, host="127.0.0.1")
    try:
        status, body = _get(
            f"http://127.0.0.1:{server.port}/profile?window=soon"
        )
        assert status == 400 and b"window" in body
    finally:
        server.close()


# -------------------------------------------- device capture endpoint


def test_debug_profile_capture_endpoint():
    from tpu_dist_nn.obs import start_http_server

    server = start_http_server(0, host="127.0.0.1")
    base = f"http://127.0.0.1:{server.port}"
    try:
        status, body = _get(f"{base}/debug/profile?seconds=0.2",
                            timeout=60.0)
        # 200 + a loadable zip where jax.profiler works; a JSON 503 is
        # the documented graceful degrade on profiler-less backends.
        assert status in (200, 503), (status, body[:200])
        if status == 200:
            zf = zipfile.ZipFile(io.BytesIO(body))
            assert zf.namelist(), "capture zip must not be empty"
        else:
            assert b"error" in body
        # Bounded and validated windows.
        status, body = _get(f"{base}/debug/profile?seconds=soon")
        assert status == 400
        status, body = _get(f"{base}/debug/profile?seconds=1e9")
        assert status == 400
    finally:
        server.close()


# ------------------------------------------------------- tdn profile


def test_cli_profile_table_and_json(capsys):
    from tpu_dist_nn.cli import main
    from tpu_dist_nn.obs import start_http_server

    TRACER.reset()
    TRACER.configure(sample_rate=1.0)
    root = TRACER.start("rpc.Process")
    time.sleep(0.01)
    TRACER.record_span("fetch", root.ctx, root.t0, 0.006)
    root.end()
    server = start_http_server(0, host="127.0.0.1")
    try:
        rc = main(["profile", "--target", f"127.0.0.1:{server.port}"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "== Process" in out and "fetch" in out
        assert HANDLER_STAGE in out
        rc = main(["profile", "--target", f"127.0.0.1:{server.port}",
                   "--json", "--window", "3600", "--top", "2"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["methods"]["Process"]["traces"] >= 1
    finally:
        server.close()


def test_cli_profile_connection_error_is_user_error(capsys):
    from tpu_dist_nn.cli import main

    rc = main(["profile", "--target", "127.0.0.1:1", "--timeout", "0.5"])
    assert rc == 2
    assert "could not fetch" in capsys.readouterr().err


def test_cli_profile_capture_surfaces_endpoint_reason(capsys):
    """An HTTP-error degrade from /debug/profile must surface the
    endpoint's JSON reason, not a bare status line."""
    from tpu_dist_nn.cli import main
    from tpu_dist_nn.obs import start_http_server

    TRACER.reset()
    TRACER.configure(sample_rate=1.0)
    TRACER.start("rpc.Process").end()
    server = start_http_server(0, host="127.0.0.1")
    try:
        rc = main(["profile", "--target", f"127.0.0.1:{server.port}",
                   "--capture-seconds", "1e9"])  # over the endpoint cap
        assert rc == 2
        err = capsys.readouterr().err
        assert "device capture unavailable" in err
        assert "seconds must be in" in err  # the endpoint's own reason
    finally:
        server.close()


# ------------------------------------------------- structured logging


def _capture_records(structured=True):
    """A StructuredLogger wired to an in-memory stream, JSON-formatted."""
    from tpu_dist_nn.obs.log import JsonFormatter, get_logger

    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonFormatter())
    logger = logging.getLogger(f"tdn_test_log_{time.monotonic_ns()}")
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    logger.handlers[:] = [handler]
    return get_logger(logger.name), stream


def test_json_log_records_are_parseable_events():
    slog, stream = _capture_records()
    slog.info("server.start", port=5101, method="Process",
              note="two words")
    line = stream.getvalue().strip()
    doc = json.loads(line)
    assert doc["event"] == "server.start"
    assert doc["level"] == "info"
    assert doc["port"] == 5101 and doc["method"] == "Process"
    assert doc["note"] == "two words"
    assert isinstance(doc["ts"], float)


def test_json_log_reserved_keys_nest_instead_of_clobbering():
    slog, stream = _capture_records()
    slog.warning("odd.event", level="deep", value=3)
    doc = json.loads(stream.getvalue().strip())
    assert doc["level"] == "warning"          # envelope wins
    assert doc["fields"]["level"] == "deep"   # field preserved
    assert doc["value"] == 3


def test_log_correlates_with_active_span():
    slog, stream = _capture_records()
    tracer = Tracer(capacity=8, sample_rate=1.0, exemplar_slots=0)
    span = tracer.start("rpc.Process")
    with tracer.activate(span):
        slog.info("inside.span")
    span.end()
    slog.info("outside.span")
    lines = [json.loads(ln) for ln in stream.getvalue().strip().splitlines()]
    assert lines[0]["trace_id"] == span.trace_id
    assert lines[0]["span_id"] == span.span_id
    assert "trace_id" not in lines[1]


def test_log_exception_carries_traceback():
    slog, stream = _capture_records()
    try:
        raise RuntimeError("boom")
    except RuntimeError:
        slog.exception("gen.step_failed", active_slots=3)
    doc = json.loads(stream.getvalue().strip())
    assert doc["event"] == "gen.step_failed"
    assert doc["active_slots"] == 3
    assert "RuntimeError: boom" in doc["exc"]


def test_token_bucket_rate_limit_counts_suppressed():
    from tpu_dist_nn.obs.log import _TokenBucket

    b = _TokenBucket(rate=1.0, burst=2)
    assert b.allow("k", now=0.0) == (True, 0)
    assert b.allow("k", now=0.0) == (True, 0)
    assert b.allow("k", now=0.0) == (False, 0)   # bucket empty
    assert b.allow("k", now=0.1) == (False, 0)
    # A second elapses: one token back, and the gap is reported.
    allowed, suppressed = b.allow("k", now=1.2)
    assert allowed and suppressed == 2
    # Independent keys do not share a bucket.
    assert b.allow("other", now=1.2) == (True, 0)


def test_structured_logger_drops_when_bucket_denies():
    from tpu_dist_nn.obs.log import StructuredLogger, _TokenBucket

    slog, stream = _capture_records()
    limited = StructuredLogger(slog._logger, _TokenBucket(rate=0.001,
                                                          burst=1))
    for _ in range(5):
        limited.warning("storm.event", x=1)
    lines = stream.getvalue().strip().splitlines()
    assert len(lines) == 1


def test_plain_records_degrade_to_json_under_formatter():
    from tpu_dist_nn.obs.log import JsonFormatter

    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.setFormatter(JsonFormatter())
    logger = logging.getLogger(f"tdn_test_plain_{time.monotonic_ns()}")
    logger.setLevel(logging.INFO)
    logger.propagate = False
    logger.handlers[:] = [handler]
    logger.info("plain %s message", "formatted")
    doc = json.loads(stream.getvalue().strip())
    assert doc["event"] == "plain formatted message"


# ------------------------------------------------- int8 warmup payoff


def test_quantized_warm_measures_int8_speedup_ratio():
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.obs.registry import REGISTRY
    from tpu_dist_nn.testing.factories import random_model

    model = random_model([8, 6, 4], seed=0)
    engine = Engine.up(model, quantize="int8", warmup=False)
    try:
        warmed = engine.warm_buckets(2)
        assert warmed == [1, 2]
        gauge = REGISTRY.get("tdn_int8_speedup_ratio")
        assert gauge is not None
        ratio = gauge.labels().value
        assert ratio > 0
        # Direct calls report the same figure they publish.
        again = engine.measure_int8_speedup(rows=2)
        assert again > 0
        assert gauge.labels().value == pytest.approx(again)
    finally:
        engine.down()


def test_unquantized_engine_skips_int8_measure():
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.testing.factories import random_model

    engine = Engine.up(random_model([8, 6, 4], seed=1), warmup=False)
    try:
        assert engine.measure_int8_speedup() is None
    finally:
        engine.down()


def test_int8_warm_measure_runs_once_and_honors_env_gate(monkeypatch):
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.testing.factories import random_model

    # Env gate: the automatic warm-time measurement can be disabled
    # (the f32-arm compile is not free on real hardware).
    monkeypatch.setenv("TDN_INT8_WARMUP_MEASURE", "0")
    engine = Engine.up(random_model([8, 6, 4], seed=2), quantize="int8",
                       warmup=False)
    try:
        calls = []
        monkeypatch.setattr(
            engine, "measure_int8_speedup",
            lambda rows=None: calls.append(rows) or 1.0,
        )
        engine.warm_buckets(2)
        assert calls == []
        # Gate back on: first warm measures, a re-warm does not.
        monkeypatch.setenv("TDN_INT8_WARMUP_MEASURE", "1")
        engine._warm_buckets.clear()
        engine.warm_buckets(2)
        assert len(calls) == 1
        engine._int8_measured = True  # what the real measure records
        engine._warm_buckets.clear()
        engine.warm_buckets(2)
        assert len(calls) == 1, "re-warm must not re-measure"
    finally:
        engine.down()


# ---------------------------------------------------------- bench gate


def _round(value=100000.0, *, backend="cpu", device=250000.0,
           rps=1000.0, gen_rps=60.0, ttft=12.0, prefix_rps=70.0,
           prefix_ttft=40.0) -> dict:
    return {
        "value": value,
        "device_resident_samples_per_sec": device,
        "backend": backend,
        "serving": {
            "coalesced": {"rps": rps},
            "generate": {"requests_per_s": gen_rps,
                         "ttft_p99_ms": ttft},
            "generate_prefix": {"rps": prefix_rps,
                                "ttft_p99_ms": prefix_ttft},
        },
    }


def test_bench_gate_passes_small_regression_fails_big():
    gate = _load_bench_gate()
    prev = _round(100000.0)
    ok = gate.compare(prev, _round(96000.0))       # -4%
    assert ok["regressions"] == []
    assert not any(r.get("failed") for r in ok["metrics"])
    bad = gate.compare(prev, _round(94000.0))      # -6%
    assert bad["regressions"] == ["host_fed_samples_per_sec"]
    row = next(r for r in bad["metrics"]
               if r["metric"] == "host_fed_samples_per_sec")
    assert row["failed"] and row["regression"] == pytest.approx(0.06)


def test_bench_gate_improvements_never_fail():
    gate = _load_bench_gate()
    v = gate.compare(_round(100000.0),
                     _round(150000.0, device=500000.0, rps=2000.0,
                            gen_rps=100.0, ttft=5.0))
    assert v["regressions"] == []


def test_bench_gate_ttft_gates_the_lower_is_better_direction():
    gate = _load_bench_gate()
    v = gate.compare(_round(ttft=10.0), _round(ttft=11.0))  # +10% TTFT
    assert v["regressions"] == ["generate_ttft_p99_ms"]
    # TTFT down 10% is an improvement, not a regression.
    v = gate.compare(_round(ttft=10.0), _round(ttft=9.0))
    assert v["regressions"] == []


def test_bench_gate_skips_cleanly_when_backends_differ():
    gate = _load_bench_gate()
    v = gate.compare(_round(backend="cpu-fallback"),
                     _round(50000.0, backend="tpu v4"))
    assert "skipped" in v and "backend" in v["skipped"]
    assert "metrics" not in v


def test_bench_gate_skips_absent_metrics_per_metric():
    gate = _load_bench_gate()
    prev = _round()
    cur = _round(96000.0)
    del cur["serving"]["generate"]
    v = gate.compare(prev, cur)
    skipped = {r["metric"] for r in v["metrics"] if "skipped" in r}
    assert {"generate_rps", "generate_ttft_p99_ms"} <= skipped
    assert v["regressions"] == []


def test_bench_gate_gates_shared_prefix_metrics_both_directions():
    gate = _load_bench_gate()
    prev = _round()
    # The shared-prefix rps dropping >5% fails; its TTFT p99 RISING
    # >5% fails (lower-is-better direction).
    v = gate.compare(prev, _round(prefix_rps=60.0))
    assert v["regressions"] == ["gen_prefix_rps"]
    v = gate.compare(prev, _round(prefix_ttft=45.0))
    assert v["regressions"] == ["gen_prefix_ttft_p99_ms"]
    # Improvements on both never fail.
    v = gate.compare(prev, _round(prefix_rps=90.0, prefix_ttft=30.0))
    assert v["regressions"] == []
    # Rounds that predate the generate_prefix section skip per-metric.
    old = _round()
    del old["serving"]["generate_prefix"]
    v = gate.compare(old, _round())
    skipped = {r["metric"] for r in v["metrics"] if "skipped" in r}
    assert {"gen_prefix_rps", "gen_prefix_ttft_p99_ms"} <= skipped
    assert v["regressions"] == []


def test_bench_gate_attribution_folds_profile_into_report():
    gate = _load_bench_gate()
    verdict = gate.compare(_round(), _round(90000.0))
    profile = {"methods": {"Process": {
        "traces": 10,
        "stages": [{"stage": "fetch", "share": 0.6, "p99_s": 0.004}],
    }}}
    report = gate.render_report(verdict, "cur.json", "prev.json", profile)
    assert "REGRESSED" in report
    assert "fetch 60.0%" in report


def test_bench_gate_report_only_on_checked_in_rounds():
    """The quick-tier smoke from the issue: the checked-in r04->r05
    pair (which carries a real serving regression) exits ZERO in
    report-only mode and NONZERO in enforce mode."""
    base = [sys.executable, BENCH_GATE,
            "--current", os.path.join(REPO_ROOT, "BENCH_r05.json"),
            "--previous", os.path.join(REPO_ROOT, "BENCH_r04.json")]
    report = subprocess.run(
        base + ["--report-only", "--json"], capture_output=True, text=True,
    )
    assert report.returncode == 0, report.stderr
    assert "host_fed_samples_per_sec" in report.stdout
    verdict = json.loads(report.stdout.strip().splitlines()[-1])
    assert verdict["report_only"] is True
    enforced = subprocess.run(base, capture_output=True, text=True)
    assert enforced.returncode == 1
    assert "REGRESSED" in enforced.stdout


def test_bench_gate_enforce_fails_synthetic_regression(tmp_path):
    """Enforce mode on a synthetic >5% host-fed regression exits
    nonzero; the same pair at -4% exits zero."""
    prev = tmp_path / "BENCH_r01.json"
    prev.write_text(json.dumps({"parsed": _round(100000.0)}))

    def run(cur_value):
        cur = tmp_path / "BENCH_r02.json"
        cur.write_text(json.dumps({"parsed": _round(cur_value)}))
        return subprocess.run(
            [sys.executable, BENCH_GATE, "--dir", str(tmp_path)],
            capture_output=True, text=True,
        )

    failing = run(90000.0)   # -10% host-fed
    assert failing.returncode == 1, failing.stdout + failing.stderr
    assert "host_fed_samples_per_sec" in failing.stdout
    passing = run(96000.0)   # -4%
    assert passing.returncode == 0, passing.stdout + passing.stderr


def test_bench_gate_explicit_previous_needs_only_one_round(tmp_path):
    """--previous pointing outside --dir must not demand a second
    discoverable round (the CI-checkout-with-one-artifact case)."""
    (tmp_path / "BENCH_r02.json").write_text(
        json.dumps({"parsed": _round(96000.0)})
    )
    prev = tmp_path / "elsewhere_prev.json"
    prev.write_text(json.dumps({"parsed": _round(100000.0)}))
    proc = subprocess.run(
        [sys.executable, BENCH_GATE, "--dir", str(tmp_path),
         "--previous", str(prev)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "host_fed_samples_per_sec" in proc.stdout


def test_bench_gate_usage_errors_exit_two(tmp_path):
    proc = subprocess.run(
        [sys.executable, BENCH_GATE, "--current", "nope.json",
         "--previous", "also_nope.json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2
    proc = subprocess.run(
        [sys.executable, BENCH_GATE, "--dir", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2  # no rounds to discover


# ------------------------------------------- best-of-history gate mode


def test_bench_gate_history_fails_checked_in_host_fed_drift():
    """The ISSUE-10 quick-tier smoke: r02->r05 host-fed drifted −3%/
    round — under the pairwise 5% threshold every single time — and
    compounded to −15% vs the r02 best. Best-of-history mode must fail
    that trajectory on the CHECKED-IN rounds (r01's error record is
    skipped, not fatal)."""
    # --current is PINNED to r05: once a later (recovered) round is
    # checked in, discovery would gate that instead and the drift this
    # smoke exists to reproduce would vanish.
    proc = subprocess.run(
        [sys.executable, BENCH_GATE, "--history", "BENCH_r*.json",
         "--dir", REPO_ROOT, "--json",
         "--current", os.path.join(REPO_ROOT, "BENCH_r05.json")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    assert verdict["mode"] == "best-of-history"
    assert "host_fed_samples_per_sec" in verdict["regressions"]
    row = next(r for r in verdict["metrics"]
               if r["metric"] == "host_fed_samples_per_sec")
    # The bar is the r02 high-water mark, not the r04 predecessor.
    assert row["best_round"] == "BENCH_r02.json"
    assert row["regression"] > 0.10
    # r01 (failed round, no payload) was skipped without killing the run.
    assert "BENCH_r01.json" not in verdict["history_rounds"]
    # Report-only still exits 0 on the same trajectory.
    report = subprocess.run(
        [sys.executable, BENCH_GATE, "--history", "BENCH_r*.json",
         "--dir", REPO_ROOT, "--report-only",
         "--current", os.path.join(REPO_ROOT, "BENCH_r05.json")],
        capture_output=True, text=True,
    )
    assert report.returncode == 0, report.stdout + report.stderr


def test_bench_gate_history_passes_flat_trajectory(tmp_path):
    """A flat (or improving) trajectory with per-round jitter under
    the threshold passes: best-of-history is a drift gate, not a
    noise amplifier."""
    for i, v in enumerate([100000.0, 99000.0, 101000.0, 99500.0], 1):
        (tmp_path / f"BENCH_r{i:02d}.json").write_text(
            json.dumps({"parsed": _round(v)})
        )
    proc = subprocess.run(
        [sys.executable, BENCH_GATE, "--history", "BENCH_r*.json",
         "--dir", str(tmp_path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "all gated metrics within threshold" in proc.stdout


def test_bench_gate_history_compounding_drift_fails_where_pairwise_passes():
    """The boiling-frog unit case: −3%/round for 5 rounds. Every
    pairwise diff is green; best-of-history fails."""
    gate = _load_bench_gate()
    values = [100000.0]
    for _ in range(4):
        values.append(values[-1] * 0.97)
    rounds = [(f"BENCH_r{i:02d}.json", _round(v))
              for i, v in enumerate(values, 1)]
    cur = rounds[-1][1]
    # Pairwise: green.
    pair = gate.compare(rounds[-2][1], cur)
    assert pair["regressions"] == []
    # Best-of-history: −11.5% vs r01's high-water mark — fails.
    hist = gate.compare_history(rounds[:-1], cur)
    assert "host_fed_samples_per_sec" in hist["regressions"]
    row = next(r for r in hist["metrics"]
               if r["metric"] == "host_fed_samples_per_sec")
    assert row["best_round"] == "BENCH_r01.json"


def test_bench_gate_history_skips_other_backend_rounds_per_round():
    """History legitimately spans a backend flap: rounds from another
    backend are excluded per-ROUND; only when NO same-backend history
    exists does the whole gate skip."""
    gate = _load_bench_gate()
    history = [
        ("BENCH_r01.json", _round(500000.0, backend="tpu v4")),
        ("BENCH_r02.json", _round(100000.0, backend="cpu")),
    ]
    cur = _round(98000.0, backend="cpu")
    v = gate.compare_history(history, cur)
    assert v["history_rounds"] == ["BENCH_r02.json"]
    assert v["regressions"] == []  # −2% vs the cpu best, tpu best ignored
    all_tpu = [("BENCH_r01.json", _round(backend="tpu v4"))]
    v = gate.compare_history(all_tpu, cur)
    assert "skipped" in v and "backend" in v["skipped"]


def test_bench_gate_history_lower_is_better_uses_min_as_best():
    gate = _load_bench_gate()
    history = [
        ("BENCH_r01.json", _round(ttft=20.0)),
        ("BENCH_r02.json", _round(ttft=10.0)),  # the TTFT high-water mark
        ("BENCH_r03.json", _round(ttft=18.0)),
    ]
    v = gate.compare_history(history, _round(ttft=11.0))
    assert "generate_ttft_p99_ms" in v["regressions"]
    row = next(r for r in v["metrics"]
               if r["metric"] == "generate_ttft_p99_ms")
    assert row["best_round"] == "BENCH_r02.json"
    # Matching the best passes.
    v = gate.compare_history(history, _round(ttft=10.0))
    assert v["regressions"] == []
