"""Resilient serving (ISSUE 4): retries with jittered backoff, circuit
breaker, admission control, graceful drain — all proven under the
deterministic fault-injection harness (tpu_dist_nn/testing/faults.py).

Conventions: no injected sleep exceeds 0.05 s, every jitter draw is
seeded, and fault schedules are call-indexed plans — a failure here
replays bit-for-bit. Engine paths use the mesh-free-constructed REAL
Engine (this container's jax lacks the mesh API Engine.up needs —
test_batcher_pipeline's convention); wire behavior runs over a real
loopback gRPC hop.
"""

import threading
import time

import numpy as np
import pytest

from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.serving import (
    CircuitBreaker,
    GracefulDrain,
    GrpcClient,
    RetryPolicy,
    serve_engine,
)
from tpu_dist_nn.testing import faults
from tpu_dist_nn.utils.errors import (
    FrameworkError,
    ResourceExhaustedError,
    UnavailableError,
)
from tests.test_batcher_pipeline import AsyncFakeEngine, _mesh_free_engine


def _fast_policy(**kw):
    """Default classification/attempts, test-speed delays, seeded
    jitter (the suite's no-sleeps-over-0.05s rule)."""
    kw.setdefault("base_delay", 0.002)
    kw.setdefault("max_delay", 0.02)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


def _counter(name, **labels):
    m = REGISTRY.get(name)
    if m is None:
        return 0.0
    return m.labels(**labels).value


def _bg(fn):
    """Run ``fn`` on a daemon thread, capturing result or exception."""
    out = {}

    def run():
        try:
            out["val"] = fn()
        except Exception as e:  # noqa: BLE001 — the test inspects it
            out["err"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t, out


# ------------------------------------------------------------ RetryPolicy


def test_retry_policy_backoff_full_jitter_deterministic():
    a = RetryPolicy(base_delay=0.05, max_delay=0.4, seed=7)
    b = RetryPolicy(base_delay=0.05, max_delay=0.4, seed=7)
    seq_a = [a.backoff(i) for i in range(1, 8)]
    seq_b = [b.backoff(i) for i in range(1, 8)]
    assert seq_a == seq_b, "seeded jitter must replay exactly"
    for i, d in enumerate(seq_a, start=1):
        cap = min(0.4, 0.05 * 2 ** (i - 1))
        assert 0.0 <= d <= cap, (i, d, cap)
    # A different seed draws a different schedule (it IS jitter).
    assert seq_a != [RetryPolicy(base_delay=0.05, max_delay=0.4,
                                 seed=8).backoff(i) for i in range(1, 8)]
    with pytest.raises(ValueError, match="max_attempts"):
        RetryPolicy(max_attempts=0)


def test_retry_policy_classification():
    import grpc

    p = RetryPolicy()
    assert p.retryable(grpc.StatusCode.UNAVAILABLE)
    assert p.retryable(grpc.StatusCode.DEADLINE_EXCEEDED)
    assert not p.retryable(grpc.StatusCode.INVALID_ARGUMENT)
    assert not p.retryable(grpc.StatusCode.INTERNAL)
    assert not p.retryable(grpc.StatusCode.RESOURCE_EXHAUSTED)
    # String codes (the FrameworkError taxonomy) classify identically.
    assert p.retryable("UNAVAILABLE") and not p.retryable("INTERNAL")
    assert not p.retryable(None)


def test_resource_exhausted_error_taxonomy():
    e = ResourceExhaustedError("queue full", stage=1)
    assert e.code == "RESOURCE_EXHAUSTED"
    assert isinstance(e, FrameworkError) and isinstance(e, RuntimeError)
    assert "[stage 1]" in str(e)


# ------------------------------------------------------------- fault plans


def test_fault_plan_is_deterministic_and_validates():
    plan = faults.FaultPlan(at={2: faults.delay(0.0)}, every=3,
                            fault=faults.unavailable())
    kinds = [plan.next_fault() for _ in range(6)]
    assert kinds[0] is None and kinds[3] is None and kinds[4] is None
    assert kinds[1].kind == "delay"
    assert kinds[2].error is UnavailableError
    assert kinds[5].error is UnavailableError
    assert plan.calls == 6 and plan.fired == 3
    with pytest.raises(ValueError, match="every"):
        faults.FaultPlan(every=0, fault=faults.unavailable())
    with pytest.raises(ValueError, match="fault"):
        faults.FaultPlan(every=2)


def test_fault_wrap_and_engine_hooks_fire():
    plan = faults.FaultPlan(every=2, fault=faults.internal("boom"))
    calls = []
    fn = faults.wrap(lambda x: calls.append(x) or x, plan)
    assert fn(1) == 1
    with pytest.raises(Exception, match="boom"):
        fn(2)
    assert calls == [1]  # the faulted call never reached the wrapped fn

    # Engine hook points are first class: attach, fire, clear.
    eng = _mesh_free_engine()
    launch = faults.FaultPlan(every=1, fault=faults.unavailable())
    faults.inject_engine_faults(eng, launch=launch)
    with pytest.raises(UnavailableError):
        eng.infer(np.zeros((1, 8)))
    faults.clear_engine_faults(eng)
    assert eng.infer(np.zeros((1, 8))).shape == (1, 4)
    assert launch.calls == 1


# ------------------------------------------------- client retries (loopback)


def test_client_retries_complete_100_of_100_with_faulty_launches():
    """The acceptance gate: every 3rd engine launch dies UNAVAILABLE,
    yet a retrying client completes 100/100 requests against the real
    loopback server, with the recovery visible in
    tdn_client_retries_total."""
    eng = _mesh_free_engine()
    eng.infer(np.zeros((1, 8)))  # compile before injecting faults
    plan = faults.FaultPlan(every=3, fault=faults.unavailable())
    faults.inject_engine_faults(eng, launch=plan)
    server, port = serve_engine(eng, 0, host="127.0.0.1", coalesce=True)
    before = _counter("tdn_client_retries_total", method="Process")
    try:
        client = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                            retry=_fast_policy(), breaker=None)
        for i in range(100):
            out = client.process(np.full((1, 8), float(i % 5)))
            assert out.shape == (1, 4) and np.isfinite(out).all()
        client.close()
    finally:
        server.stop(0)
    retried = _counter("tdn_client_retries_total", method="Process") - before
    # 100 successes need ~50 extra launch attempts (every 3rd dies).
    assert plan.fired >= 30
    assert retried >= plan.fired, (retried, plan.fired)


def test_same_faults_without_retries_fail():
    """The control arm: identical 1-in-3 fault plan, retries disabled —
    the run must NOT complete (what the retry layer is buying)."""
    import grpc

    eng = _mesh_free_engine()
    eng.infer(np.zeros((1, 8)))
    plan = faults.FaultPlan(every=3, fault=faults.unavailable())
    faults.inject_engine_faults(eng, launch=plan)
    server, port = serve_engine(eng, 0, host="127.0.0.1", coalesce=True)
    try:
        client = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                            retry=None, breaker=None)
        codes = []
        for i in range(9):
            try:
                client.process(np.zeros((1, 8)))
                codes.append(None)
            except grpc.RpcError as e:
                codes.append(e.code())
        client.close()
    finally:
        server.stop(0)
    assert codes.count(grpc.StatusCode.UNAVAILABLE) == 3, codes
    # Deterministic plan: exactly every 3rd launch (requests are serial).
    assert codes[2] == codes[5] == codes[8] == grpc.StatusCode.UNAVAILABLE


def test_retry_budget_never_exceeds_original_timeout():
    """Budget exhaustion mid-retry: against a permanently-UNAVAILABLE
    target, attempts stop when the CALLER's timeout is spent — long
    before max_attempts — and the last real status surfaces."""
    import grpc

    plan = faults.FaultPlan(every=1, fault=faults.unavailable())
    server, port = serve_engine(
        AsyncFakeEngine(), 0, host="127.0.0.1", coalesce=True,
        interceptors=(faults.FaultInterceptor(plan),),
    )
    try:
        client = GrpcClient(
            f"127.0.0.1:{port}", timeout=0.3,
            retry=RetryPolicy(max_attempts=50, base_delay=0.02,
                              max_delay=0.02, seed=1),
            breaker=None,
        )
        t0 = time.monotonic()
        with pytest.raises(grpc.RpcError) as e:
            client.process(np.zeros((1, 8)))
        elapsed = time.monotonic() - t0
        client.close()
    finally:
        server.stop(0)
    assert e.value.code() in (grpc.StatusCode.UNAVAILABLE,
                              grpc.StatusCode.DEADLINE_EXCEEDED)
    # Stopped by the 0.3s budget (with scheduler slack), not by the
    # 50-attempt limit.
    assert elapsed < 1.5, elapsed
    assert 2 <= plan.calls < 50, plan.calls


# --------------------------------------------------------- circuit breaker


def test_breaker_cycle_closed_open_half_open_closed():
    clk = [0.0]
    br = CircuitBreaker("unit-target", failure_threshold=3,
                        cooldown_seconds=5.0, clock=lambda: clk[0])
    gauge = REGISTRY.get("tdn_breaker_state").labels(target="unit-target")
    assert br.state == CircuitBreaker.CLOSED and br.allow()
    for _ in range(3):
        br.record_failure()
    assert br.state == CircuitBreaker.OPEN and gauge.value == 2.0
    assert not br.allow(), "open breaker must fail fast"
    clk[0] = 5.0  # cooldown elapsed: next caller becomes the probe
    assert br.allow()
    assert br.state == CircuitBreaker.HALF_OPEN and gauge.value == 1.0
    assert not br.allow(), "one probe at a time while half-open"
    br.record_failure()  # probe failed: re-open for a fresh cooldown
    assert br.state == CircuitBreaker.OPEN and not br.allow()
    clk[0] = 10.0
    assert br.allow()
    br.record_success()  # probe succeeded: close
    assert br.state == CircuitBreaker.CLOSED and gauge.value == 0.0
    assert br.allow()


def test_breaker_fails_fast_through_client():
    """After threshold consecutive retryable failures the NEXT call
    fails fast with UnavailableError and never touches the wire."""
    import grpc

    plan = faults.FaultPlan(every=1, fault=faults.unavailable())
    server, port = serve_engine(
        AsyncFakeEngine(), 0, host="127.0.0.1", coalesce=True,
        interceptors=(faults.FaultInterceptor(plan),),
    )
    try:
        br = CircuitBreaker(f"bft-{port}", failure_threshold=2,
                            cooldown_seconds=60.0)
        client = GrpcClient(f"127.0.0.1:{port}", timeout=5.0,
                            retry=None, breaker=br)
        for _ in range(2):
            with pytest.raises(grpc.RpcError):
                client.process(np.zeros((1, 8)))
        wire_calls = plan.calls
        with pytest.raises(UnavailableError, match="circuit breaker open"):
            client.process(np.zeros((1, 8)))
        assert plan.calls == wire_calls, "open breaker must not hit the wire"
        client.close()
    finally:
        server.stop(0)


def test_breaker_ignores_non_retryable_failures():
    """INVALID_ARGUMENT says nothing about target health: it must not
    trip the breaker (a bad client would otherwise open the circuit
    for every well-formed one)."""
    import grpc

    eng = AsyncFakeEngine(dim=8)
    server, port = serve_engine(eng, 0, host="127.0.0.1", coalesce=True)
    try:
        br = CircuitBreaker(f"nrf-{port}", failure_threshold=2,
                            cooldown_seconds=60.0)
        client = GrpcClient(f"127.0.0.1:{port}", timeout=5.0,
                            retry=None, breaker=br)
        for _ in range(4):
            with pytest.raises(grpc.RpcError) as e:
                client.process(np.zeros((1, 5)))  # engine wants 8
            assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert br.state == CircuitBreaker.CLOSED
        out = client.process(np.zeros((2, 8)))  # still flows
        assert out.shape == (2, 8)
        client.close()
    finally:
        server.stop(0)


def test_breaker_half_open_probe_answered_non_transiently_recovers():
    """A half-open probe answered with a NON-transient status proves the
    target is reachable: the breaker must close, not wedge in half-open
    with the probe slot held forever."""
    import grpc

    plan = faults.FaultPlan(at={1: faults.unavailable(),
                                2: faults.unavailable()})
    server, port = serve_engine(
        AsyncFakeEngine(dim=8), 0, host="127.0.0.1",
        interceptors=(faults.FaultInterceptor(plan),),
    )
    try:
        br = CircuitBreaker(f"hop-{port}", failure_threshold=2,
                            cooldown_seconds=0.0)  # half-open immediately
        client = GrpcClient(f"127.0.0.1:{port}", timeout=5.0,
                            retry=None, breaker=br)
        for _ in range(2):
            with pytest.raises(grpc.RpcError):
                client.process(np.zeros((1, 8)))
        assert br.state == CircuitBreaker.OPEN
        # The probe: a bad request → INVALID_ARGUMENT from a live server.
        with pytest.raises(grpc.RpcError) as e:
            client.process(np.zeros((1, 5)))
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert br.state == CircuitBreaker.CLOSED
        assert client.process(np.ones((1, 8))).shape == (1, 8)
        client.close()
    finally:
        server.stop(0)


def test_for_target_shares_one_instance_first_config_wins():
    a = CircuitBreaker.for_target("ft-shared", failure_threshold=3)
    b = CircuitBreaker.for_target("ft-shared", failure_threshold=9)
    assert a is b and b.failure_threshold == 3  # cache hit keeps config
    CircuitBreaker.evict("ft-shared")
    c = CircuitBreaker.for_target("ft-shared", failure_threshold=9)
    assert c is not a and c.failure_threshold == 9


def test_half_open_probe_slot_ages_out_if_prober_vanishes():
    """A prober that dies between allow() and record_* must not wedge
    the breaker: the probe slot expires after a cooldown and the next
    caller becomes the probe."""
    clk = [0.0]
    br = CircuitBreaker("vanish", failure_threshold=1,
                        cooldown_seconds=2.0, clock=lambda: clk[0])
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    clk[0] = 2.0
    assert br.allow()  # probe granted... and the prober vanishes
    assert not br.allow()  # slot held while the probe is fresh
    clk[0] = 4.0  # probe aged out: the slot is reclaimable
    assert br.allow()
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED


def test_overshot_backoff_reraises_last_real_error():
    """A backoff sleep that overshoots the budget must re-raise the last
    REAL outcome instead of issuing a ~0ms phantom attempt (which would
    fail client-side and count a failure the server never saw)."""
    import grpc

    plan = faults.FaultPlan(every=1, fault=faults.unavailable())
    server, port = serve_engine(
        AsyncFakeEngine(dim=8), 0, host="127.0.0.1",
        interceptors=(faults.FaultInterceptor(plan),),
    )
    try:
        client = GrpcClient(
            f"127.0.0.1:{port}", timeout=0.05,
            retry=RetryPolicy(max_attempts=5, base_delay=0.001,
                              max_delay=0.001, seed=0,
                              sleep=lambda d: time.sleep(0.05)),
            breaker=None,
        )
        with pytest.raises(grpc.RpcError) as e:
            client.process(np.zeros((1, 8)))
        assert e.value.code() == grpc.StatusCode.UNAVAILABLE
        assert plan.calls == 1, "no phantom near-zero-deadline attempt"
        client.close()
    finally:
        server.stop(0)


# -------------------------------------------------------- admission control


def test_shed_at_watermark_surfaces_resource_exhausted():
    """Past --max-pending-rows the server fast-fails RESOURCE_EXHAUSTED
    through the real gRPC hop instead of queueing unboundedly; admitted
    requests still complete once the device unwedges."""
    import grpc

    eng = AsyncFakeEngine(dim=8)
    eng.gate.clear()  # wedge the fetch: batches stall 'on the device'
    server, port = serve_engine(
        eng, 0, host="127.0.0.1", coalesce=True, max_pending_rows=4,
        submit_timeout=10.0, pipeline_depth=1,
    )
    before = _counter("tdn_batcher_shed_total", method="Process")
    clients, threads = [], []
    try:
        def call(value):
            c = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                           retry=None, breaker=None)
            clients.append(c)
            return c.process(np.full((2, 8), value))

        # r1 occupies the (serial) batcher inside the wedged fetch...
        t1, o1 = _bg(lambda: call(1.0))
        assert eng.fetch_entered.wait(5.0)
        # ...r2 + r3 fill the queue exactly to the 4-row watermark.
        t2, o2 = _bg(lambda: call(2.0))
        t3, o3 = _bg(lambda: call(3.0))
        deadline = time.monotonic() + 5.0
        while (server.batcher.pending_rows < 4
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert server.batcher.pending_rows == 4
        threads.extend([t1, t2, t3])

        # The runtime sampler publishes the ledger the watermark gates.
        from tpu_dist_nn.obs import RuntimeSampler
        from tpu_dist_nn.obs.registry import Registry

        reg = Registry()
        sampler = RuntimeSampler(interval=30.0, registry=reg)
        sampler.add_batcher(server.batcher, method="Process")
        sampler.sample_once()
        g = reg.get("tdn_batcher_pending_rows").labels(method="Process")
        assert g.value == 4.0

        # r4 would pass the watermark: shed NOW, not queued.
        c4 = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                        retry=None, breaker=None)
        clients.append(c4)
        with pytest.raises(grpc.RpcError) as e:
            c4.process(np.full((2, 8), 4.0))
        assert e.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert "watermark" in e.value.details()
        assert server.batcher.shed_total == 1
        assert _counter("tdn_batcher_shed_total",
                        method="Process") == before + 1

        # Unwedge: every ADMITTED request completes correctly.
        eng.gate.set()
        for t in threads:
            t.join(timeout=10.0)
        for o, v in ((o1, 1.0), (o2, 2.0), (o3, 3.0)):
            assert "err" not in o, o
            np.testing.assert_array_equal(o["val"], np.full((2, 8), v * 2.0))
    finally:
        eng.gate.set()
        server.stop(0)
        for c in clients:
            c.close()


def test_oversized_request_admitted_when_queue_empty():
    # The watermark bounds BACKLOG, not batch size: a lone request
    # larger than the watermark must still be servable.
    from tpu_dist_nn.serving.server import _Batcher

    eng = AsyncFakeEngine(dim=8)
    b = _Batcher(eng, max_pending_rows=4)
    try:
        out = b.submit(np.ones((16, 8)), timeout=5.0)
        assert out.shape == (16, 8)
    finally:
        b.close()


# ----------------------------------------------------------- graceful drain


def test_graceful_drain_completes_inflight_and_flips_health():
    import grpc

    eng = AsyncFakeEngine(dim=8)
    eng.gate.clear()
    server, port = serve_engine(eng, 0, host="127.0.0.1", coalesce=True)
    drain = GracefulDrain(grace_seconds=5.0)
    drain.add_server(server)
    health = drain.wrap_health(lambda: {"ready": True, "devices": 1})
    h0 = health()
    assert h0.pop("boot_id")  # per-process identity rides every payload
    assert h0 == {"ready": True, "devices": 1, "draining": False}
    client = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                        retry=None, breaker=None)
    try:
        t, o = _bg(lambda: client.process(np.full((3, 8), 2.0)))
        assert eng.fetch_entered.wait(5.0)  # request is in flight

        ev = drain.begin()
        # 1. /healthz flips NOT_SERVING the moment draining starts.
        h = health()
        assert h["ready"] is False and h["draining"] is True
        assert _counter("tdn_server_draining") == 1.0
        # begin() is idempotent (signal handler + teardown both call).
        assert drain.begin() is ev

        # 2. NEW work is refused while draining.
        c2 = GrpcClient(f"127.0.0.1:{port}", timeout=2.0,
                        retry=None, breaker=None)
        with pytest.raises(grpc.RpcError) as e:
            c2.process(np.zeros((1, 8)))
        assert e.value.code() in (grpc.StatusCode.UNAVAILABLE,
                                  grpc.StatusCode.CANCELLED)
        c2.close()

        # 3. The in-flight request COMPLETES (the drain's whole point).
        eng.gate.set()
        assert drain.wait(5.0), "drain never completed"
        t.join(timeout=5.0)
        assert "err" not in o, o.get("err")
        np.testing.assert_array_equal(o["val"], np.full((3, 8), 4.0))
        assert _counter("tdn_server_draining") == 0.0
    finally:
        eng.gate.set()
        client.close()
        server.stop(0)


def test_drain_without_servers_completes_immediately():
    drain = GracefulDrain(grace_seconds=0.1)
    assert not drain.draining.is_set()
    drain.begin()
    assert drain.wait(1.0) and drain.draining.is_set()


def test_wrap_health_keeps_draining_marker_when_probe_raises():
    """Mid-drain the engine may already be down; a raising health probe
    must not erase the draining marker the load balancer keys on."""

    def boom():
        raise RuntimeError("engine is down")

    drain = GracefulDrain(grace_seconds=0.1)
    health = drain.wrap_health(boom)
    with pytest.raises(RuntimeError):
        health()  # not draining: the probe's failure IS the report
    drain.begin()
    body = health()
    assert body["ready"] is False and body["draining"] is True
    assert "error" in body


# ------------------------------------------------------- batcher close fix


def test_post_close_submit_raises_immediately():
    from tpu_dist_nn.serving.server import _Batcher

    b = _Batcher(AsyncFakeEngine(dim=8))
    b.close()
    t0 = time.monotonic()
    with pytest.raises(UnavailableError):
        b.submit(np.zeros((1, 8)), timeout=30.0)
    assert time.monotonic() - t0 < 0.5, "post-close submit must not wait"


def test_close_fails_pending_entries_over_to_waiters():
    """A wedged dispatch at close time: entries still queued must fail
    over to their waiters as UNAVAILABLE now — not sit out their full
    submit timeout against a batcher that is already gone."""
    import dataclasses

    from tpu_dist_nn.serving.server import _Batcher

    entered = threading.Event()
    release = threading.Event()

    class WedgedLaunchEngine:
        model = dataclasses.make_dataclass("M", ["input_dim"])(8)

        def infer(self, x):
            entered.set()
            release.wait(10.0)
            return np.asarray(x)

    b = _Batcher(WedgedLaunchEngine(), submit_timeout=30.0)
    try:
        t1, o1 = _bg(lambda: b.submit(np.full((1, 8), 1.0), timeout=30.0))
        assert entered.wait(5.0)  # r1 popped, wedged inside the launch
        t2, o2 = _bg(lambda: b.submit(np.full((1, 8), 2.0), timeout=30.0))
        deadline = time.monotonic() + 5.0
        while not b._pending and time.monotonic() < deadline:
            time.sleep(0.005)
        assert b._pending, "r2 never queued"

        t0 = time.monotonic()
        b.close(timeout=0.2)  # dispatch is wedged: join times out
        t2.join(timeout=2.0)
        assert time.monotonic() - t0 < 3.0
        assert isinstance(o2.get("err"), UnavailableError), o2
        assert b.pending_rows == 0
    finally:
        release.set()
        t1.join(timeout=5.0)


# ------------------------------------------------------------ wait_for_ready


def test_wait_for_ready_maps_to_unavailable_on_dead_target():
    t0 = time.monotonic()
    with pytest.raises(UnavailableError, match="not ready"):
        GrpcClient("127.0.0.1:1", wait_for_ready=True, ready_timeout=0.3,
                   retry=None, breaker=None)
    assert time.monotonic() - t0 < 3.0


def test_wait_for_ready_connects_to_live_server():
    server, port = serve_engine(AsyncFakeEngine(dim=8), 0, host="127.0.0.1")
    try:
        client = GrpcClient(f"127.0.0.1:{port}", wait_for_ready=True,
                            ready_timeout=5.0, retry=None, breaker=None)
        out = client.process(np.ones((2, 8)))
        np.testing.assert_array_equal(out, np.full((2, 8), 2.0))
        client.close()
    finally:
        server.stop(0)


# -------------------------------------------------------- interceptor seam


def test_fault_interceptor_errors_exactly_the_nth_request():
    import grpc

    plan = faults.FaultPlan(every=2, fault=faults.unavailable())
    server, port = serve_engine(
        AsyncFakeEngine(dim=8), 0, host="127.0.0.1",
        interceptors=(faults.FaultInterceptor(plan),),
    )
    try:
        client = GrpcClient(f"127.0.0.1:{port}", timeout=5.0,
                            retry=None, breaker=None)
        assert client.process(np.ones((1, 8))).shape == (1, 8)
        with pytest.raises(grpc.RpcError) as e:
            client.process(np.ones((1, 8)))
        assert e.value.code() == grpc.StatusCode.UNAVAILABLE
        assert client.process(np.ones((1, 8))).shape == (1, 8)
        client.close()
    finally:
        server.stop(0)


# --------------------------------------------------- quick-tier chaos smoke


def test_chaos_smoke_quick_tier_recovers_via_retries():
    """The < 10 s chaos gate: in-process server with a 1-in-3 launch
    fault plan; a default-policy retrying client finishes 30/30 and the
    recovery is scrapeable on the REAL /metrics endpoint."""
    import urllib.request

    from tpu_dist_nn.obs import parse_prometheus_text, start_http_server

    eng = _mesh_free_engine()
    eng.infer(np.zeros((1, 8)))  # compile before injecting faults
    plan = faults.FaultPlan(every=3, fault=faults.unavailable())
    faults.inject_engine_faults(eng, launch=plan)
    server, port = serve_engine(eng, 0, host="127.0.0.1", coalesce=True)
    metrics = start_http_server(0, host="127.0.0.1")

    def scrape():
        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics.port}/metrics", timeout=5.0
        ) as r:
            return parse_prometheus_text(r.read().decode())

    key = 'tdn_client_retries_total{method="Process"}'
    before = scrape().get(key, 0)
    try:
        client = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                            retry=_fast_policy(), breaker=None)
        for i in range(30):
            out = client.process(np.full((1, 8), float(i % 3)))
            assert out.shape == (1, 4) and np.isfinite(out).all()
        client.close()
        after = scrape()
        assert after.get(key, 0) > before, "retries must be scrapeable"
        assert plan.fired >= 9
    finally:
        server.stop(0)
        metrics.close()


# ------------------------------------- continuous-batching chaos (ISSUE 5)


def _lm_setup():
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=24,
    )
    return cfg, init_transformer(jax.random.key(3), cfg)


def test_continuous_chaos_step_faults_hooks_and_recovery():
    """The continuous decode scheduler wears the faults.py hook points
    the Engine does: a launch-plan fault on the step kernel fails the
    RESIDENT rows over as UNAVAILABLE (their sampling position in the
    stream is gone — not silently replayable), the wire surfaces it
    retryably, a default retrying client recovers with the exact
    greedy tokens, and the fetch hook sees every step."""
    import grpc as _grpc

    from tpu_dist_nn.models.generate import generate
    from tpu_dist_nn.serving import serve_lm_generate

    cfg, params = _lm_setup()
    prompts = np.arange(8, dtype=np.int64)[None, :] % 7
    ref = np.asarray(generate(params, cfg, prompts, 6))

    server, port = serve_lm_generate(
        params, cfg, 0, max_new_tokens=6, prompt_len=8,
        host="127.0.0.1", gen_slots=2, warm_rows=1,
    )
    sched = server.scheduler
    assert sched is not None
    launch_plan = faults.FaultPlan(at={2: faults.unavailable()})
    fetch_plan = faults.FaultPlan()  # counts step fetches, no faults
    faults.inject_engine_faults(sched, launch=launch_plan,
                                fetch=fetch_plan)
    try:
        # No-retry client sees the mid-decode fault as UNAVAILABLE.
        bare = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                          retry=None, breaker=None)
        with pytest.raises(_grpc.RpcError) as e:
            bare.generate(prompts)
        assert e.value.code() == _grpc.StatusCode.UNAVAILABLE
        assert launch_plan.fired == 1
        bare.close()
        # The scheduler recovered: slots freed, later requests serve —
        # and a retrying client would have absorbed the fault entirely.
        retrying = GrpcClient(f"127.0.0.1:{port}", timeout=15.0,
                              retry=_fast_policy(), breaker=None)
        out = retrying.generate(prompts)
        np.testing.assert_array_equal(out[:, 8:], ref)
        assert sched.slots_active == 0
        assert fetch_plan.calls > 0, "fetch hook must see step fetches"
        retrying.close()
    finally:
        faults.clear_engine_faults(sched)
        server.stop(0)


def test_continuous_graceful_drain_completes_backlog_then_refuses():
    """GracefulDrain over the continuous endpoint honors the _Batcher
    drain contract: begin() mid-burst lets the resident decode AND the
    queued backlog complete inside the grace window (in-flight RPCs
    include queued ones — a healthy drain loses nothing), the drained
    event fires, the scheduler's loop thread is gone, and new work is
    refused. (The complementary wedged-path proof — close() failing
    still-pending waiters over as UnavailableError — is deterministic
    in-process: test_continuous.py::
    test_close_fails_pending_over_and_post_close_submit_raises.)"""
    import grpc as _grpc

    from tpu_dist_nn.serving import serve_lm_generate

    cfg, params = _lm_setup()
    server, port = serve_lm_generate(
        params, cfg, 0, max_new_tokens=16, prompt_len=8,
        host="127.0.0.1", gen_slots=1, warm_rows=1,
    )
    drain = GracefulDrain(grace_seconds=30.0)
    drain.add_server(server)
    oks, errs = [], []
    lock = threading.Lock()

    def worker(i):
        c = GrpcClient(f"127.0.0.1:{port}", timeout=30.0,
                       retry=None, breaker=None)
        try:
            out = c.generate(np.full((1, 8), i % 5))
            with lock:
                oks.append(out)
        except _grpc.RpcError as e:
            with lock:
                errs.append(e)
        finally:
            c.close()

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(6)
    ]
    for t in threads:
        t.start()
    sched = server.scheduler
    deadline = time.monotonic() + 10
    # One row resident in the single slot, several queued behind it —
    # the drain begins with real work in BOTH states.
    while ((sched.rows_total < 1 or sched.pending_rows < 3)
           and time.monotonic() < deadline):
        time.sleep(0.002)
    assert sched.pending_rows >= 3, "burst never queued"
    drain.begin()
    assert drain.drained.wait(30.0)
    for t in threads:
        t.join(30)
    assert not errs, [str(e)[:120] for e in errs[:2]]
    assert len(oks) == 6, "a healthy drain completes the whole backlog"
    assert sched.pending_rows == 0
    # The post-grace close runs on its own thread (the wrapped stop's
    # _close_after_drain); give it a moment to land.
    deadline = time.monotonic() + 10
    while sched._thread.is_alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not sched._thread.is_alive(), "drain must close the scheduler"
    # The drained endpoint refuses new work.
    late = GrpcClient(f"127.0.0.1:{port}", timeout=2.0,
                      retry=None, breaker=None)
    with pytest.raises(_grpc.RpcError):
        late.generate(np.zeros((1, 8)))
    late.close()


# ------------------------------------------------------------------- CLI


def test_cli_help_lists_resilience_flags(capsys):
    from tpu_dist_nn.cli import main

    with pytest.raises(SystemExit) as e:
        main(["up", "--help"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "--max-pending-rows" in out and "--drain-grace-seconds" in out
    with pytest.raises(SystemExit) as e:
        main(["infer", "--help"])
    assert e.value.code == 0
    assert "--retry-max-attempts" in capsys.readouterr().out
    with pytest.raises(SystemExit) as e:
        main(["lm", "--help"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "--max-pending-rows" in out and "--drain-grace-seconds" in out


def test_cli_infer_client_retries_through_faulty_server(tmp_path, capsys):
    """`tdn infer --target --retry-max-attempts`: the CLI client rides
    the retry policy through a server that kills every 3rd request."""
    import json

    from tpu_dist_nn.cli import main

    plan = faults.FaultPlan(every=3, fault=faults.unavailable())
    server, port = serve_engine(
        AsyncFakeEngine(dim=8), 0, host="127.0.0.1",
        interceptors=(faults.FaultInterceptor(plan),),
    )
    examples = {
        "examples": [
            {"input": list(np.full(8, float(i))), "label": -1}
            for i in range(4)
        ]
    }
    path = tmp_path / "ex.json"
    path.write_text(json.dumps(examples))
    try:
        rc = main([
            "infer", "--inputs", str(path),
            "--target", f"127.0.0.1:{port}", "--batch-size", "1",
            "--retry-max-attempts", "3",
        ])
    finally:
        server.stop(0)
    assert rc == 0
    assert "Total inference time" in capsys.readouterr().out
    assert plan.fired >= 1  # the 3rd RPC really was killed (and retried)
