"""Pipeline x expert parallelism: MoE through the pipeline.

`tdn lm --experts E --stages S` used to reject ("MoE pipelines are not
implemented"). Now MoE blocks pipeline over `stage` with experts
sharded over `expert` inside each stage (all_to_all dispatch in the
stage body — legal by the disjoint-axis rule), batch over
(data, expert). Parity oracle: the grouped single-chip moe_lm_loss
with n_groups = microbatches * data * expert — each (microbatch,
shard) pair is one routing group, so both paths run the same grouped
math exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.parallel.expert_parallel import (
    MoEConfig,
    init_moe_transformer,
    make_pipeline_ep_lm_loss,
    moe_lm_loss,
    shard_blocks_pp_ep,
    unshard_blocks_pp_ep,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh

CFG = MoEConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
    max_seq_len=16, n_experts=4, router_top_k=1,
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), jnp.int32)


def test_pp_ep_shard_roundtrip():
    params = init_moe_transformer(jax.random.key(0), CFG)
    staged = shard_blocks_pp_ep(params["blocks"], num_stages=2, n_ep=2)
    # L=4, E=4: EP-sharded (S, n_ep, L/S, E/n_ep, ...), replicated (S, L/S, ...).
    assert staged["w_up"].shape[:4] == (2, 2, 2, 2)
    assert staged["w_router"].shape[:2] == (2, 2)
    back = unshard_blocks_pp_ep(staged)
    for k, v in params["blocks"].items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(back[k]))


@pytest.mark.parametrize("stage,expert,data,M", [(2, 2, 2, 1), (2, 2, 1, 2), (2, 4, 1, 1)])
def test_pp_ep_loss_and_grads_match_grouped_oracle(stage, expert, data, M):
    mesh = build_mesh(MeshSpec(stage=stage, expert=expert, data=data))
    params = init_moe_transformer(jax.random.key(1), CFG)
    n_groups = M * expert * data
    tokens = _tokens(batch=2 * n_groups, seq=17, seed=2)

    loss_pp = make_pipeline_ep_lm_loss(
        mesh, CFG, num_stages=stage, num_microbatches=M
    )
    params_pp = dict(
        params, blocks=shard_blocks_pp_ep(params["blocks"], stage, expert)
    )
    v_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(params_pp, tokens)
    v_ref, g_ref = jax.jit(
        jax.value_and_grad(
            lambda p, t: moe_lm_loss(p, t, CFG, n_groups=n_groups)
        )
    )(params, tokens)
    np.testing.assert_allclose(float(v_ref), float(v_pp), rtol=1e-5)

    g_blocks = unshard_blocks_pp_ep(g_pp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_pp[k]), rtol=5e-4, atol=1e-5,
        )


@pytest.mark.parametrize("stage,expert,data,M", [(2, 2, 2, 1), (2, 2, 1, 2)])
def test_pp_ep_1f1b_grads_match_grouped_oracle(stage, expert, data, M):
    # MoE through the MEMORY-FLAT schedule: the 1F1B executor's aux
    # channel carries the router load-balancing loss (pre-scaled,
    # cotangent 1.0 through the recompute-vjp) — loss AND grads must
    # match the grouped single-chip oracle exactly like the gpipe path.
    from tpu_dist_nn.parallel.expert_parallel import (
        make_pipeline_ep_lm_1f1b_grad,
    )

    mesh = build_mesh(MeshSpec(stage=stage, expert=expert, data=data))
    params = init_moe_transformer(jax.random.key(7), CFG)
    n_groups = M * expert * data
    tokens = _tokens(batch=2 * n_groups, seq=17, seed=8)

    vag = make_pipeline_ep_lm_1f1b_grad(
        mesh, CFG, num_stages=stage, num_microbatches=M
    )
    params_pp = dict(
        params, blocks=shard_blocks_pp_ep(params["blocks"], stage, expert)
    )
    v_pp, g_pp = jax.jit(vag)(params_pp, tokens)
    v_ref, g_ref = jax.jit(
        jax.value_and_grad(
            lambda p, t: moe_lm_loss(p, t, CFG, n_groups=n_groups)
        )
    )(params, tokens)
    np.testing.assert_allclose(float(v_ref), float(v_pp), rtol=1e-5)

    g_blocks = unshard_blocks_pp_ep(g_pp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_pp[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )


def test_pp_ep_train_step_runs():
    import optax

    from tpu_dist_nn.train.lm_trainer import make_pipeline_moe_lm_train_step

    mesh = build_mesh(MeshSpec(stage=2, expert=2, data=2))
    params = init_moe_transformer(jax.random.key(3), CFG)
    params_pp = dict(
        params, blocks=shard_blocks_pp_ep(params["blocks"], 2, 2)
    )
    optimizer = optax.adam(1e-2)
    step = make_pipeline_moe_lm_train_step(mesh, CFG, 2, 2, optimizer)
    tokens = _tokens(batch=8, seq=17, seed=4)
    new_params, _, loss = step(params_pp, optimizer.init(params_pp), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_up"]),
        np.asarray(params_pp["blocks"]["w_up"]),
    )


@pytest.mark.parametrize("variant", ["interleaved", "zb"])
def test_pp_ep_tables_grads_match_grouped_oracle(variant):
    # MoE on the TABLE executors: virtual chunks (and the zero-bubble
    # split backward, where the aux's input grad rides BWD_B and its
    # weight grad BWD_W) — loss AND grads must match the grouped
    # single-chip oracle.
    from tpu_dist_nn.parallel.expert_parallel import (
        make_pipeline_ep_lm_interleaved_grad,
        make_pipeline_ep_lm_zb_grad,
        shard_blocks_interleaved_ep,
        unshard_blocks_interleaved_ep,
    )

    S, v, expert, data, M = 2, 2, 2, 1, 2
    mesh = build_mesh(MeshSpec(stage=S, expert=expert, data=data))
    params = init_moe_transformer(jax.random.key(11), CFG)
    n_groups = M * expert * data
    tokens = _tokens(batch=2 * n_groups, seq=17, seed=12)

    make = (
        make_pipeline_ep_lm_interleaved_grad
        if variant == "interleaved" else make_pipeline_ep_lm_zb_grad
    )
    vag = make(mesh, CFG, num_virtual=v, num_microbatches=M)
    params_v = dict(
        params,
        blocks=shard_blocks_interleaved_ep(params["blocks"], S, v, expert),
    )
    v_pp, g_pp = jax.jit(vag)(params_v, tokens)
    v_ref, g_ref = jax.jit(
        jax.value_and_grad(
            lambda p, t: moe_lm_loss(p, t, CFG, n_groups=n_groups)
        )
    )(params, tokens)
    np.testing.assert_allclose(float(v_ref), float(v_pp), rtol=1e-5)

    g_blocks = unshard_blocks_interleaved_ep(g_pp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_pp[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )


def test_pp_ep_interleaved_shard_roundtrip():
    from tpu_dist_nn.parallel.expert_parallel import (
        shard_blocks_interleaved_ep,
        unshard_blocks_interleaved_ep,
    )

    params = init_moe_transformer(jax.random.key(13), CFG)
    staged = shard_blocks_interleaved_ep(params["blocks"], 2, 2, 2)
    # L=4, E=4, S=2, v=2: sharded (S, v, n_ep, L/V, E/n_ep, ...),
    # replicated (S, v, L/V, ...).
    assert staged["w_up"].shape[:5] == (2, 2, 2, 1, 2)
    assert staged["w_router"].shape[:3] == (2, 2, 1)
    back = unshard_blocks_interleaved_ep(staged)
    for k, v in params["blocks"].items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(back[k]))


def test_pp_ep_1f1b_train_step_and_cli(capsys):
    import optax

    from tpu_dist_nn.cli import main
    from tpu_dist_nn.train.lm_trainer import make_pipeline_moe_lm_train_step

    mesh = build_mesh(MeshSpec(stage=2, expert=2, data=2))
    params = init_moe_transformer(jax.random.key(9), CFG)
    params_pp = dict(
        params, blocks=shard_blocks_pp_ep(params["blocks"], 2, 2)
    )
    optimizer = optax.adam(1e-2)
    step = make_pipeline_moe_lm_train_step(
        mesh, CFG, 2, 2, optimizer, schedule="1f1b"
    )
    tokens = _tokens(batch=8, seq=17, seed=10)
    new_params, _, loss = step(params_pp, optimizer.init(params_pp), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_up"]),
        np.asarray(params_pp["blocks"]["w_up"]),
    )
    # End to end: tdn lm --experts --stages --schedule 1f1b and zb.
    for sched in ("1f1b", "zb"):
        rc = main([
            "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
            "--seq-len", "16", "--d-model", "16", "--heads", "2",
            "--layers", "2", "--experts", "2", "--expert-parallel", "2",
            "--stages", "2", "--microbatches", "2", "--schedule", sched,
        ])
        assert rc == 0, sched
        assert "perplexity" in capsys.readouterr().out


def test_pp_ep_validates_batch_divisibility():
    mesh = build_mesh(MeshSpec(stage=2, expert=2, data=2))
    loss = make_pipeline_ep_lm_loss(mesh, CFG, 2, 2)
    params = init_moe_transformer(jax.random.key(0), CFG)
    params_pp = dict(
        params, blocks=shard_blocks_pp_ep(params["blocks"], 2, 2)
    )
    with pytest.raises(ValueError, match="not divisible"):
        loss(params_pp, _tokens(batch=6, seq=17))


def test_cli_lm_moe_pipeline(tmp_path, capsys):
    # The previously rejected flag combination end to end.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "16", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--experts", "2", "--expert-parallel", "2",
        "--stages", "2", "--microbatches", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "perplexity" in out