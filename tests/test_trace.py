"""Request-scoped distributed tracing (ISSUE 3): span recorder, wire
context propagation, Chrome trace-event export.

The loopback acceptance path: a served engine with tracing enabled,
hit through ``GrpcClient``, must yield ONE trace id spanning client
span -> server handler -> batcher stages (queue_wait / stage / launch /
fetch), with child durations summing inside the handler span, and the
``/trace`` export must pass the Chrome trace-event schema check (the
Perfetto-loadability bar). Recorder mechanics (ring eviction,
slowest-exemplar retention, sampling edges 0.0/1.0) are covered
directly on a private Tracer.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from tpu_dist_nn.obs import trace as trace_mod
from tpu_dist_nn.obs.trace import (
    TRACE_HEADER,
    TRACE_ID_HEADER,
    TRACER,
    TIMEOUT_HEADER,
    SpanContext,
    Tracer,
)


def _get(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def validate_chrome_trace(doc: dict) -> None:
    """The Chrome trace-event schema check (the CI satellite): every
    event carries ph/pid/tid/name, every non-metadata event a numeric
    ts (plus dur for complete events), and ts is monotonic within each
    (pid, tid) track — the properties Perfetto's importer requires."""
    assert isinstance(doc, dict), "export must be a JSON object"
    assert "traceEvents" in doc, "export must carry traceEvents"
    assert isinstance(doc["traceEvents"], list)
    track_last: dict[tuple, float] = {}
    for ev in doc["traceEvents"]:
        for key in ("ph", "name", "pid", "tid"):
            assert key in ev, f"event missing required key {key!r}: {ev}"
        if ev["ph"] == "M":
            continue  # metadata events carry no timestamp
        assert "ts" in ev, f"event missing ts: {ev}"
        assert isinstance(ev["ts"], (int, float))
        if ev["ph"] == "X":
            assert "dur" in ev and ev["dur"] >= 0
        track = (ev["pid"], ev["tid"])
        last = track_last.get(track)
        assert last is None or ev["ts"] >= last, (
            f"ts not monotonic within track {track}: {ev['ts']} < {last}"
        )
        track_last[track] = ev["ts"]


# ------------------------------------------------------------- context


def test_span_context_header_round_trip():
    ctx = SpanContext("ab" * 16, "cd" * 8, sampled=True)
    parsed = SpanContext.from_header(ctx.header())
    assert parsed is not None
    assert parsed.trace_id == ctx.trace_id
    assert parsed.span_id == ctx.span_id
    assert parsed.sampled is True
    assert parsed.remote is True  # off the wire = remote parent
    off = SpanContext("ab" * 16, "cd" * 8, sampled=False)
    assert SpanContext.from_header(off.header()).sampled is False


def test_malformed_headers_parse_to_none():
    for bad in (None, "", "nonsense", "a-b", "a-b-c-d",
                "short-0011223344556677-01",
                "g" * 32 + "-" + "0" * 16 + "-01",   # non-hex trace id
                "0" * 32 + "-" + "0" * 16 + "-zz",   # non-hex flags
                # int(s, 16) lookalikes that are NOT canonical hex:
                "0x" + "a" * 30 + "-" + "0" * 16 + "-01",
                "1_" + "a" * 30 + "-" + "0" * 16 + "-01",
                "+" + "a" * 31 + "-" + "0" * 16 + "-01"):
        assert SpanContext.from_header(bad) is None, bad


# ------------------------------------------------------------ recorder


def test_spans_record_with_parent_links():
    t = Tracer(capacity=64, sample_rate=1.0)
    root = t.start("root")
    with t.span("child", root.ctx) as child:
        child.annotate("note")
    root.end()
    spans = t.snapshot()
    assert [s.name for s in spans] == ["child", "root"]
    c, r = spans
    assert c.trace_id == r.trace_id
    assert c.parent_id == r.span_id
    assert r.parent_id is None
    assert c.annotations and c.annotations[0][1] == "note"
    assert r.dur is not None and r.dur >= c.dur >= 0


def test_record_span_retroactive_cross_thread_form():
    t = Tracer(capacity=16, sample_rate=1.0)
    root = t.start("root")
    t0 = time.monotonic() - 0.5
    sp = t.record_span("queue_wait", root.ctx, t0, 0.25,
                       attrs={"rows": 3},
                       annotations=[(t0 + 0.1, "popped")])
    assert sp is not None and sp.dur == 0.25 and sp.attrs["rows"] == 3
    # Unsampled / missing parents record nothing (the rate-0 fast path).
    assert t.record_span("x", None, t0, 0.1) is None
    off = SpanContext("0" * 32, "1" * 16, sampled=False)
    assert t.record_span("x", off, t0, 0.1) is None


def test_ring_eviction_bounds_buffer_and_counts_drops():
    t = Tracer(capacity=8, sample_rate=1.0, exemplar_slots=0)
    for i in range(20):
        t.start(f"s{i}").end()
    assert t.buffer_len() == 8
    assert t.dropped_total == 12
    # The ring keeps the newest spans, oldest first in the snapshot.
    assert [s.name for s in t.snapshot()] == [f"s{i}" for i in range(12, 20)]
    assert [s.name for s in t.snapshot(limit=3)] == ["s17", "s18", "s19"]


def test_slowest_exemplar_traces_survive_eviction():
    t = Tracer(capacity=8, sample_rate=1.0, exemplar_slots=2)
    # One slow trace: a root with a child, with a dominating duration.
    slow_root = t.start("slow_root")
    t.record_span("slow_child", slow_root.ctx,
                  time.monotonic() - 0.9, 0.4)
    slow_root.t0 = time.monotonic() - 1.0  # make it decisively slowest
    slow_root.end()
    # Flood the ring with fast spans until the slow trace is evicted.
    for i in range(50):
        t.start(f"fast{i}").end()
    names = {s.name for s in t.snapshot()}
    assert "slow_root" in names and "slow_child" in names, (
        "slowest-trace exemplar must survive arbitrary ring churn"
    )
    # And the export keeps them too.
    doc = t.chrome_trace()
    validate_chrome_trace(doc)
    exported = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"slow_root", "slow_child"} <= exported


def test_one_exemplar_slot_per_trace_in_loopback_shape():
    # A same-process client root and its wire-joined handler span are
    # BOTH locally rooted; they must share one exemplar slot (the
    # fuller, outermost capture wins), not burn two on one trace.
    t = Tracer(capacity=32, sample_rate=1.0, exemplar_slots=4)
    client = t.start("client.Process")
    handler = t.start("rpc.Process",
                      parent=SpanContext.from_header(client.ctx.header()))
    time.sleep(0.002)
    handler.end()   # wire-joined local root: takes a slot
    client.end()    # outer root, same trace: must REPLACE, not append
    assert len(t._exemplars) == 1
    dur, tid, spans = t._exemplars[0]
    assert tid == client.trace_id
    assert {s.name for s in spans} == {"client.Process", "rpc.Process"}
    assert dur == pytest.approx(client.dur)


def test_sampling_rate_edge_cases():
    # Rate 0: nothing records, every span is the no-op form, and the
    # not-sampled decision is what a child would inherit.
    t0 = Tracer(capacity=16, sample_rate=0.0)
    for _ in range(50):
        sp = t0.start("root")
        assert sp.sampled is False
        child = t0.start("child", parent=sp.ctx)
        assert child.sampled is False
        child.end()
        sp.end()
    assert t0.buffer_len() == 0 and len(t0.snapshot()) == 0
    # Rate 1: everything records.
    t1 = Tracer(capacity=256, sample_rate=1.0)
    for _ in range(50):
        t1.start("root").end()
    assert t1.buffer_len() == 50
    # Rate 0 is the PROCESS kill switch: even a sampled remote parent
    # cannot force recording (a stock client at rate 1.0 must not
    # control a server that explicitly disabled tracing).
    remote = SpanContext.from_header(
        SpanContext("ab" * 16, "cd" * 8, sampled=True).header()
    )
    sp = t0.start("handler", parent=remote)
    assert sp.sampled is False
    sp.end()
    assert t0.buffer_len() == 0
    # At a nonzero local rate the remote decision is inherited both
    # ways: sampled joins the trace, unsampled stays dark.
    joined = t1.start("handler", parent=remote)
    assert joined.sampled is True and joined.ctx.trace_id == remote.trace_id
    joined.end()
    dark = t1.start("handler", parent=SpanContext.from_header(
        SpanContext("ab" * 16, "cd" * 8, sampled=False).header()
    ))
    assert dark.sampled is False
    dark.end()
    with pytest.raises(ValueError):
        t1.configure(sample_rate=1.5)


def test_garbled_env_sample_rate_degrades_to_default(monkeypatch):
    # The process TRACER is built at import time: a bad env value must
    # warn and fall back, never crash every tdn command with a float()
    # traceback.
    for bad in ("50%", "", "soon", "2", "-0.5"):
        monkeypatch.setenv("TDN_TRACE_SAMPLE_RATE", bad)
        assert Tracer(capacity=4).sample_rate == 1.0, bad
    monkeypatch.setenv("TDN_TRACE_SAMPLE_RATE", "0.25")
    assert Tracer(capacity=4).sample_rate == 0.25
    monkeypatch.delenv("TDN_TRACE_SAMPLE_RATE")
    assert Tracer(capacity=4).sample_rate == 1.0


def test_unsampled_spans_still_carry_ids_for_propagation():
    t = Tracer(sample_rate=0.0)
    sp = t.start("root")
    assert len(sp.ctx.trace_id) == 32 and len(sp.ctx.span_id) == 16
    parsed = SpanContext.from_header(sp.ctx.header())
    assert parsed is not None and parsed.sampled is False


def test_annotation_sink_and_active_guard():
    assert trace_mod.active() is False
    trace_mod.annotate("goes nowhere")  # must be a silent no-op
    with trace_mod.annotation_sink() as notes:
        assert trace_mod.active() is True
        trace_mod.annotate("captured")
    assert trace_mod.active() is False
    assert [text for _, text in notes] == ["captured"]
    # An activated span takes precedence over a sink.
    t = Tracer(sample_rate=1.0)
    sp = t.start("op")
    with t.activate(sp):
        assert trace_mod.active() is True
        trace_mod.annotate("on the span")
    sp.end()
    assert [text for _, text in sp.annotations] == ["on the span"]


def test_chrome_trace_export_schema():
    # The quick-tier schema gate: a representative export — nested
    # spans, multiple threads, annotations — passes the validator and
    # round-trips through JSON. (exemplar_slots=0 so the limit
    # assertion below counts ring spans only.)
    t = Tracer(capacity=64, sample_rate=1.0, exemplar_slots=0)

    def work():
        root = t.start("request")
        with t.span("decode", root.ctx):
            time.sleep(0.001)
        with t.span("compute", root.ctx) as c:
            c.annotate("compile_cache_miss")
        root.end()

    threads = [threading.Thread(target=work) for _ in range(3)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    work()
    doc = json.loads(t.render_json())
    validate_chrome_trace(doc)
    events = doc["traceEvents"]
    assert {e["name"] for e in events if e["ph"] == "X"} == {
        "request", "decode", "compute",
    }
    assert any(e["ph"] == "i" and e["name"] == "compile_cache_miss"
               for e in events)
    assert any(e["ph"] == "M" and e["name"] == "thread_name"
               for e in events)
    assert doc["displayTimeUnit"] == "ms"
    # limit applies to the ring (metadata events always accompany).
    limited = t.chrome_trace(limit=2)
    assert len([e for e in limited["traceEvents"] if e["ph"] == "X"]) == 2


# ---------------------------------------------------- serving loopback


class FakeEngine:
    """input_dim + infer — all serve_engine requires (the test_obs
    pattern); a small sleep gives every pipeline stage measurable
    width."""

    def __init__(self, dim=8):
        self.model = dataclasses.make_dataclass("M", ["input_dim"])(dim)

    def infer(self, x):
        time.sleep(0.002)
        return np.asarray(x) * 3.0


def _spans_by_trace(spans, trace_id):
    return [s for s in spans if s.trace_id == trace_id]


def test_loopback_round_trip_is_one_trace_tree():
    """The acceptance path: client.Process -> rpc.Process handler ->
    queue_wait/stage/launch/fetch, ONE trace id, child durations
    summing to within the handler span."""
    from tpu_dist_nn.serving import GrpcClient, serve_engine

    TRACER.reset()
    TRACER.configure(sample_rate=1.0)
    engine = FakeEngine(dim=8)
    server, port = serve_engine(engine, 0, host="127.0.0.1", coalesce=True)
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        out = client.process(np.full((3, 8), 2.0))
        client.close()
        assert np.allclose(out, 6.0)
    finally:
        server.stop(0)
    spans = TRACER.snapshot()
    clients = [s for s in spans if s.name == "client.Process"]
    assert len(clients) == 1
    trace_id = clients[0].trace_id
    tree = _spans_by_trace(spans, trace_id)
    names = {s.name for s in tree}
    # The full span taxonomy of one served request.
    assert {"client.Process", "rpc.Process", "decode", "queue_wait",
            "stage", "launch", "fetch", "encode"} <= names, names
    handler = next(s for s in tree if s.name == "rpc.Process")
    # Wire propagation: the handler is a child of the client span.
    assert handler.parent_id == clients[0].span_id
    assert handler.parent_remote is True
    # Every pipeline span hangs off the handler.
    children = [s for s in tree
                if s.name in ("decode", "queue_wait", "stage", "launch",
                              "fetch", "encode")]
    assert all(c.parent_id == handler.span_id for c in children)
    # Durations: the pipeline stages sum to within the handler span
    # (each stage ran inside the handler's submit window).
    stage_sum = sum(c.dur for c in children)
    assert stage_sum <= handler.dur * 1.05 + 1e-3, (
        f"child spans ({stage_sum:.6f}s) exceed handler "
        f"({handler.dur:.6f}s)"
    )
    assert handler.dur <= clients[0].dur * 1.05 + 1e-3
    assert handler.attrs.get("rows") == 3
    fetch = next(s for s in tree if s.name == "fetch")
    assert fetch.attrs.get("rows") == 3


def test_sample_rate_zero_records_nothing_on_serving_path():
    from tpu_dist_nn.serving import GrpcClient, serve_engine

    TRACER.reset()
    TRACER.configure(sample_rate=0.0)
    try:
        engine = FakeEngine(dim=8)
        server, port = serve_engine(engine, 0, host="127.0.0.1",
                                    coalesce=True)
        try:
            client = GrpcClient(f"127.0.0.1:{port}")
            for _ in range(3):
                client.process(np.ones((2, 8)))
            client.close()
        finally:
            server.stop(0)
        assert TRACER.buffer_len() == 0
        assert len(TRACER.snapshot()) == 0
    finally:
        TRACER.configure(sample_rate=1.0)


def test_client_error_names_the_server_trace():
    import grpc

    from tpu_dist_nn.serving import GrpcClient, serve_engine

    TRACER.reset()
    TRACER.configure(sample_rate=1.0)
    engine = FakeEngine(dim=8)
    server, port = serve_engine(engine, 0, host="127.0.0.1", coalesce=True)
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        with pytest.raises(grpc.RpcError) as e:
            client.process(np.zeros((1, 5)))  # engine wants 8 features
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        # The raised error names the server-side trace to pull.
        tid = getattr(e.value, "server_trace_id", None)
        assert tid is not None and len(tid) == 32
        # And that id really is a recorded server-side handler span.
        handlers = [s for s in TRACER.snapshot()
                    if s.name == "rpc.Process" and s.trace_id == tid]
        assert handlers, "server handler span missing for reported trace"
        client.close()
    finally:
        server.stop(0)


def test_timeout_hint_bounds_the_batcher_budget():
    """The deadline-hint satellite, at the unit level: the server-side
    budget honors min(grpc deadline, x-tdn-timeout-ms hint), and a
    garbled hint degrades instead of failing the RPC."""
    from tpu_dist_nn.serving.server import _request_span

    class Ctx:
        def __init__(self, md, remaining=None):
            self._md = md
            self._remaining = remaining
            self.trailing = None

        def invocation_metadata(self):
            return self._md

        def time_remaining(self):
            return self._remaining

        def set_trailing_metadata(self, md):
            self.trailing = md

    TRACER.configure(sample_rate=1.0)
    ctx = SpanContext("ab" * 16, "cd" * 8, sampled=True)
    span, budget, md = _request_span(
        Ctx([(TRACE_HEADER, ctx.header()), (TIMEOUT_HEADER, "1500")],
            remaining=30.0),
        "Process",
    )
    span.end()
    assert budget == pytest.approx(1.5)
    assert span.ctx.trace_id == ctx.trace_id  # joined the caller's trace
    # The parsed metadata dict rides back too (the router reads
    # x-tdn-session from it).
    assert md[TIMEOUT_HEADER] == "1500"
    # The hint alone (a proxy rewrote the deadline away).
    span, budget, _md = _request_span(Ctx([(TIMEOUT_HEADER, "250")]),
                                      "Process")
    span.end()
    assert budget == pytest.approx(0.25)
    # Garbled hint: no budget, no crash; trailing metadata still names
    # the trace.
    fake = Ctx([(TIMEOUT_HEADER, "soon")])
    span, budget, _md = _request_span(fake, "Process")
    span.end()
    assert budget is None
    assert fake.trailing and fake.trailing[0][0] == TRACE_ID_HEADER


# --------------------------------------------------- /trace + tdn trace


def test_trace_route_exports_chrome_schema(tmp_path):
    from tpu_dist_nn.obs import start_http_server

    tracer = Tracer(capacity=64, sample_rate=1.0, exemplar_slots=0)
    root = tracer.start("request")
    with tracer.span("work", root.ctx):
        pass
    root.end()
    server = start_http_server(0, host="127.0.0.1")
    # The route serves the PROCESS tracer by default; inject ours.
    server._tracer = tracer
    try:
        doc = json.loads(_get(f"http://127.0.0.1:{server.port}/trace"))
        validate_chrome_trace(doc)
        assert {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"} == {
            "request", "work",
        }
        limited = json.loads(
            _get(f"http://127.0.0.1:{server.port}/trace?limit=1")
        )
        validate_chrome_trace(limited)
        assert len([e for e in limited["traceEvents"]
                    if e["ph"] == "X"]) == 1
        # A bad limit is a 400, not a stack trace.
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as err:
            _get(f"http://127.0.0.1:{server.port}/trace?limit=soon")
        assert err.value.code == 400

        # The CLI verb: pulls the same route, writes a loadable file.
        from tpu_dist_nn.cli import main

        out = tmp_path / "trace.json"
        rc = main(["trace", "--target", f"127.0.0.1:{server.port}",
                   "-o", str(out)])
        assert rc == 0
        saved = json.loads(out.read_text())
        validate_chrome_trace(saved)
    finally:
        server.close()


def test_cli_trace_reports_summary(tmp_path, capsys):
    from tpu_dist_nn.cli import main
    from tpu_dist_nn.obs import start_http_server

    tracer = Tracer(capacity=16, sample_rate=1.0)
    tracer.start("slow_one").end()
    server = start_http_server(0, host="127.0.0.1")
    server._tracer = tracer
    try:
        out = tmp_path / "t.json"
        rc = main(["trace", "--target", f"127.0.0.1:{server.port}",
                   "-o", str(out)])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert report["out"] == str(out)
        assert report["spans"] == 1 and report["traces"] == 1
        assert report["slowest"][0]["name"] == "slow_one"
    finally:
        server.close()


# -------------------------------------------------- tracer self-metrics


def test_runtime_sampler_publishes_tracer_self_metrics():
    from tpu_dist_nn.obs import Registry, RuntimeSampler

    reg = Registry()
    tracer = Tracer(capacity=4, sample_rate=1.0, exemplar_slots=0)
    sampler = RuntimeSampler(registry=reg)
    sampler.add_tracer(tracer)
    for i in range(6):  # 2 drops
        tracer.start(f"s{i}").end()
    sampler.sample_once()
    assert reg.get("tdn_trace_buffer_spans").labels().value == 4
    dropped = reg.get("tdn_trace_spans_dropped_total")
    assert dropped.labels().value == 2
    # Counter semantics: the next sample adds only the delta.
    for i in range(3):
        tracer.start(f"t{i}").end()
    sampler.sample_once()
    assert dropped.labels().value == 5


# ------------------------------------------------ trainer run tracing


def test_classifier_training_emits_epoch_spans():
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.models.fcnn import init_fcnn
    from tpu_dist_nn.train.trainer import TrainConfig, train_fcnn

    import jax

    TRACER.reset()
    TRACER.configure(sample_rate=1.0)
    params = init_fcnn(jax.random.key(0), [8, 6, 4])
    data = synthetic_mnist(64, dim=8, num_classes=4, seed=0)
    train_fcnn(params, data, TrainConfig(epochs=2, batch_size=16))
    spans = TRACER.snapshot()
    roots = [s for s in spans if s.name == "train.classifier"]
    assert len(roots) == 1
    epochs = [s for s in spans
              if s.name == "epoch" and s.trace_id == roots[0].trace_id]
    assert [s.attrs["epoch"] for s in epochs] == [0, 1]
    assert all(s.parent_id == roots[0].span_id for s in epochs)
    assert all("loss" in s.attrs for s in epochs)
