"""Multi-replica data plane (ISSUE 8): load-aware router + replica
pool with session affinity.

Placement policy (p2c over blended load, staleness fallback,
rendezvous cold-pool hashing, breaker gating) is unit-tested directly
on the pool; wire behavior — spread, failover on a killed replica,
zero-downtime rolling restart, session affinity, trace propagation —
runs over real loopback gRPC hops. Fake replica engines follow the
test_batcher_pipeline convention (this jax lacks the mesh API
Engine.up needs); the Generate-path failover test uses the real
continuous scheduler on a toy LM.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from tests.test_batcher_pipeline import AsyncFakeEngine
from tpu_dist_nn.obs import start_http_server
from tpu_dist_nn.obs.exposition import parse_prometheus_text
from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.serving import (
    CircuitBreaker,
    GracefulDrain,
    GrpcClient,
    ReplicaPool,
    serve_engine,
    serve_router,
)
from tpu_dist_nn.serving.pool import ACTIVE, DRAINING
from tpu_dist_nn.serving.router import admin_routes, router_health
from tpu_dist_nn.testing import faults


def _counter_total(name: str) -> float:
    m = REGISTRY.get(name)
    if m is None:
        return 0.0
    return float(sum(child.value for _, child in m.samples()))


def _fresh_targets(*names):
    """Synthetic targets with clean breaker registry entries (tests
    share the process-global CircuitBreaker registry)."""
    for n in names:
        CircuitBreaker.evict(n)
    return names


# ------------------------------------------------------------ placement


def test_p2c_places_on_less_loaded_replica():
    a, b = _fresh_targets("p2c:a", "p2c:b")
    pool = ReplicaPool([a, b], seed=0)
    ra, rb = pool.replicas()
    # Outstanding-only load (no scrapes): p2c with two candidates
    # compares both every draw, so the less loaded one always wins.
    for _ in range(5):
        pool.begin(ra)
    picks = {pool.place().target for _ in range(20)}
    assert picks == {b}
    # Load flips, placement follows.
    for _ in range(12):
        pool.begin(rb)
    picks = {pool.place().target for _ in range(20)}
    assert picks == {a}


def test_gauge_load_is_staleness_bounded():
    a, b = _fresh_targets("stale:a", "stale:b")
    pool = ReplicaPool([a, b], seed=0, load_staleness=5.0)
    ra, rb = pool.replicas()
    now = time.monotonic()
    # Fresh gauges say A is backlogged (pending rows dominate its
    # otherwise-equal outstanding count).
    ra.pending_rows, ra.scraped_at = 500.0, now
    rb.pending_rows, rb.scraped_at = 0.0, now
    assert {pool.place().target for _ in range(20)} == {b}
    # The same gauge view gone stale is IGNORED: outstanding (now
    # higher on B) decides instead.
    ra.scraped_at = rb.scraped_at = now - 60.0
    for _ in range(3):
        pool.begin(rb)
    assert {pool.place().target for _ in range(20)} == {a}


def test_occupancy_gauge_counts_toward_load():
    a, b = _fresh_targets("occ:a", "occ:b")
    pool = ReplicaPool([a, b], seed=0, occupancy_weight=32.0)
    ra, rb = pool.replicas()
    now = time.monotonic()
    ra.pending_rows, ra.occupancy, ra.scraped_at = 0.0, 1.0, now
    rb.pending_rows, rb.occupancy, rb.scraped_at = 0.0, 0.0, now
    # A full decode slot ladder (occupancy 1.0) outweighs an idle one.
    assert {pool.place().target for _ in range(20)} == {b}


def test_session_affinity_pins_until_unplaceable():
    a, b = _fresh_targets("sess:a", "sess:b")
    pool = ReplicaPool([a, b], seed=0)
    first = pool.place(session_key="s1")
    pool.pin("s1", first.target)
    # Load the pinned replica heavily: affinity still wins (the KV
    # state lives there; p2c is for unpinned traffic).
    for _ in range(10):
        pool.begin(first)
    assert all(
        pool.place(session_key="s1").target == first.target
        for _ in range(10)
    )
    # Unpinnable (draining) -> re-placed onto the other replica.
    pool.drain(first.target)
    other = pool.place(session_key="s1")
    assert other is not None and other.target != first.target


def test_rendezvous_fallback_spreads_cold_sessions_consistently():
    targets = _fresh_targets("rdv:a", "rdv:b", "rdv:c")
    pool = ReplicaPool(targets, seed=0)
    # No gauge data, no outstanding: session first-placements use
    # rendezvous hashing — stable per session and spread across the
    # fleet (a second pool over the same targets maps identically).
    keys = [f"session-{i}" for i in range(24)]
    placed = {k: pool.place(session_key=k).target for k in keys}
    assert {placed[k] for k in keys} == set(targets), \
        "24 sessions over 3 replicas must touch every replica"
    pool2 = ReplicaPool(targets, seed=99)
    assert all(
        pool2.place(session_key=k).target == placed[k] for k in keys
    ), "rendezvous placement must not depend on pool instance or seed"


def test_open_breaker_skipped_then_probed_after_cooldown():
    a, b = _fresh_targets("brk:a", "brk:b")
    t = [0.0]
    br = CircuitBreaker.for_target(
        a, failure_threshold=1, cooldown_seconds=10.0, clock=lambda: t[0]
    )
    pool = ReplicaPool([a, b], seed=0)
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    # Open breaker: never placed.
    assert {pool.place().target for _ in range(10)} == {b}
    # Cooldown elapsed: exactly one request rides the half-open probe.
    t[0] = 11.0
    assert pool.place().target == a
    assert {pool.place().target for _ in range(5)} == {b}, \
        "only ONE probe per cooldown"
    br.record_success()
    assert pool.place(exclude={b}).target == a


def test_replica_healthy_gauge_tracks_breaker_state():
    """Regression: the gauge's contract is '0 = draining, removed, or
    breaker-open', but breakers open at request time in the router —
    only membership changes ever wrote the gauge, so a hard-down
    replica the pool had stopped placing on kept reporting healthy=1.
    The scrape tick must reconcile the gauge with the breaker."""
    from tpu_dist_nn.serving.pool import REPLICA_HEALTHY

    a, b = _fresh_targets("hgauge:a", "hgauge:b")
    t = [0.0]
    br = CircuitBreaker.for_target(
        a, failure_threshold=1, cooldown_seconds=10.0, clock=lambda: t[0]
    )
    pool = ReplicaPool([a, b], seed=0)
    assert REPLICA_HEALTHY.labels(replica=a).value == 1.0
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    # Breaker opened at request time: the gauge catches up on the
    # next scrape tick, not only on membership changes.
    pool.scrape_once()
    assert REPLICA_HEALTHY.labels(replica=a).value == 0.0
    assert REPLICA_HEALTHY.labels(replica=b).value == 1.0
    # Recovery: the half-open probe succeeds, breaker closes, the
    # next tick restores healthy=1.
    t[0] = 11.0
    assert br.allow()
    br.record_success()
    pool.scrape_once()
    assert REPLICA_HEALTHY.labels(replica=a).value == 1.0
    pool.close()
    CircuitBreaker.evict(a)
    CircuitBreaker.evict(b)


# ------------------------------------ breaker registry eviction (satellite)


def test_pool_remove_evicts_breaker_registry_for_reused_address():
    (t,) = _fresh_targets("evict:a")
    pool = ReplicaPool([t], seed=0)
    old = pool.replicas()[0].breaker
    for _ in range(old.failure_threshold):
        old.record_failure()
    assert old.state == CircuitBreaker.OPEN
    pool.remove(t)
    # The registry entry is PRUNED (the regression: it never was), so
    # a respawned server on the reused address starts closed.
    assert t not in CircuitBreaker._registry
    # ... and the tdn_breaker_state series goes with it: a departed
    # target's stale last value must not sit on /metrics forever.
    from tpu_dist_nn.serving.resilience import BREAKER_STATE
    assert (t,) not in dict(BREAKER_STATE.samples())
    fresh = CircuitBreaker.for_target(t)
    assert (t,) in dict(BREAKER_STATE.samples())  # recreated live
    assert fresh is not old and fresh.state == CircuitBreaker.CLOSED
    # undrain() after a rolling restart resets the same way.
    pool2 = ReplicaPool([t], seed=0)
    br2 = pool2.replicas()[0].breaker
    for _ in range(br2.failure_threshold):
        br2.record_failure()
    pool2.drain(t)
    assert pool2.undrain(t)
    assert pool2.replicas()[0].breaker.state == CircuitBreaker.CLOSED
    CircuitBreaker.evict(t)


def test_undrain_refuses_active_replica():
    """Regression: undrain() on a never-drained ACTIVE replica wiped a
    live breaker and its load view — a hard-down replica the breaker
    correctly opened on re-entered rotation off a typo'd admin call."""
    (t,) = _fresh_targets("undrainactive:a")
    pool = ReplicaPool([t], seed=0)
    rep = pool.replicas()[0]
    old = rep.breaker
    for _ in range(old.failure_threshold):
        old.record_failure()
    assert old.state == CircuitBreaker.OPEN
    assert not pool.undrain(t)
    assert rep.breaker is old and old.state == CircuitBreaker.OPEN
    pool.close()


def test_remove_retires_request_counter_series():
    """Membership churn retires the per-replica
    tdn_router_requests_total children too — the same unbounded
    label-growth class the gauges already handle (a long-lived process
    cycling pools over ephemeral-port replicas must not accumulate
    dead counter series forever)."""
    from tpu_dist_nn.serving.router import ROUTER_REQUESTS

    (t,) = _fresh_targets("retirereq:a")
    pool = ReplicaPool([t], seed=0)
    ROUTER_REQUESTS.labels(replica=t, outcome="ok").inc()
    ROUTER_REQUESTS.labels(replica=t, outcome="UNAVAILABLE").inc()
    pool.remove(t)
    assert not [k for k, _ in ROUTER_REQUESTS.samples() if k[0] == t]
    pool.close()


class _FakeChildProc:
    """Duck-typed stand-in for a pool-spawned subprocess handle."""

    def __init__(self):
        self.terminated = False

    def poll(self):
        return 0 if self.terminated else None

    def terminate(self):
        self.terminated = True

    def wait(self, timeout=None):
        if not self.terminated:
            raise RuntimeError("still running")
        return 0

    def kill(self):
        self.terminated = True


def test_pool_remove_terminates_spawned_child():
    """Regression: remove() popped the entry without terminating a
    pool-spawned child — the live engine kept serving on its ports
    forever, and once popped even close()'s sweep could no longer
    reach it ('pool-spawned children are OWNED by the pool')."""
    (t,) = _fresh_targets("rmspawn:a")
    pool = ReplicaPool([t], seed=0)
    fake = _FakeChildProc()
    pool.replicas()[0].proc = fake
    pool.remove(t)
    assert fake.terminated, "removed replica's child was orphaned"
    pool.close()


def test_admin_drain_not_undone_by_ready_scrape():
    """Regression: an admin-drained STATIC replica (no subprocess to
    SIGTERM) keeps answering ready on /healthz — the scrape loop must
    NOT auto-undrain it, or `--drain-replica` reverts within one
    scrape tick. Rejoin happens only after the drain was OBSERVED:
    draining:true scraped, or the replica went unreachable (restart),
    then ready again."""
    a, b = _fresh_targets("stillready:a", "stillready:b")
    state = {"draining": False, "ready": True}
    msrv = start_http_server(0, host="127.0.0.1",
                             health_fn=lambda: dict(state))
    try:
        pool = ReplicaPool([a, b],
                           [f"127.0.0.1:{msrv.port}", None], seed=0)
        assert pool.drain(a)
        # The replica never began restarting: ready scrapes must keep
        # it OUT of rotation.
        for _ in range(3):
            pool.scrape_once()
            assert pool.replicas()[0].state == DRAINING
        assert {pool.place().target for _ in range(5)} == {b}
        # ONE lost probe is a blip (GC pause, timeout on a busy but
        # still-running replica) — ready right after must NOT rejoin.
        good_port = msrv.port
        pool.replicas()[0].metrics_target = "127.0.0.1:1"  # unreachable
        pool.scrape_once()
        assert pool.replicas()[0].state == DRAINING
        pool.replicas()[0].metrics_target = f"127.0.0.1:{good_port}"
        pool.scrape_once()
        assert pool.replicas()[0].state == DRAINING, \
            "single unreachable blip must not count as drain observed"
        # Operator restarts it: a SUSTAINED down window (2+ ticks) IS
        # the restart being observed...
        pool.replicas()[0].metrics_target = "127.0.0.1:1"
        pool.scrape_once()
        pool.scrape_once()
        assert pool.replicas()[0].state == DRAINING
        # ...and the restarted server's ready scrape rejoins it.
        pool.replicas()[0].metrics_target = f"127.0.0.1:{good_port}"
        pool.scrape_once()
        assert pool.replicas()[0].state == ACTIVE
        pool.close()
    finally:
        msrv.close()
        CircuitBreaker.evict(a)


def test_fast_restart_detected_via_boot_id_change():
    """A restart faster than the scraper's timing detectors (the
    draining:true window AND the downtime both fell between ticks)
    is still observed: /healthz carries a per-process boot_id
    (GracefulDrain.wrap_health), and a DRAINING replica answering
    ready under a NEW identity IS the drain having completed. Same
    identity answering ready stays out of rotation (the operator's
    --drain-replica is not undone)."""
    a, b = _fresh_targets("bootid:a", "bootid:b")
    state = {"draining": False, "ready": True, "boot_id": "boot-1"}
    msrv = start_http_server(0, host="127.0.0.1",
                             health_fn=lambda: dict(state))
    try:
        pool = ReplicaPool([a, b],
                           [f"127.0.0.1:{msrv.port}", None], seed=0)
        pool.scrape_once()  # records boot-1 while ACTIVE
        assert pool.replicas()[0].boot_id == "boot-1"
        assert pool.drain(a)
        pool.scrape_once()  # same process, still ready: no rejoin
        assert pool.replicas()[0].state == DRAINING
        state["boot_id"] = "boot-2"  # restart between two ticks
        pool.scrape_once()
        assert pool.replicas()[0].state == ACTIVE
        pool.close()
    finally:
        msrv.close()
        CircuitBreaker.evict(a)


def test_wrap_health_carries_boot_id():
    from tpu_dist_nn.serving.resilience import BOOT_ID

    drain = GracefulDrain(grace_seconds=0.1)
    assert drain.wrap_health()()["boot_id"] == BOOT_ID
    # An engine health_fn that sets its own value wins (setdefault).
    assert drain.wrap_health(lambda: {"ready": True, "boot_id": "x"})()[
        "boot_id"] == "x"


def test_spawn_local_refuses_after_close():
    """Regression (orphan race): spawn_local on a closing pool would
    Popen a child that close()'s sweep can never see. The pre-spawn
    gate refuses outright."""
    (t,) = _fresh_targets("spawnclosed:a")
    pool = ReplicaPool([t], seed=0)
    pool.close()
    with pytest.raises(RuntimeError, match="closed"):
        pool.spawn_local("model.json")


def test_scrape_survives_garbled_healthz_body():
    """Regression: a 200 /healthz whose body is not JSON (proxy error
    page, misconfigured port) or not a dict (bare ``null``) must not
    raise out of scrape_once — it crashed pool.start() at router
    bring-up and aborted every later tick's reconcile pass fleet-wide.
    Something ANSWERED, so it is neither a drain observation nor a
    rejoin signal; the health view simply stays unknown for the tick."""
    import http.server

    body = {"value": b"<html>502 Bad Gateway</html>"}

    class Garbled(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", str(len(body["value"])))
            self.end_headers()
            self.wfile.write(body["value"])

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Garbled)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    a, b = _fresh_targets("garbled:a", "garbled:b")
    try:
        pool = ReplicaPool([a, b],
                           [f"127.0.0.1:{srv.server_address[1]}", None],
                           seed=0)
        pool.scrape_once()  # must not raise
        rep = pool.replicas()[0]
        assert rep.state == ACTIVE
        body["value"] = b"null"  # valid JSON, not a dict
        pool.scrape_once()  # must not raise either
        assert rep.state == ACTIVE
        body["value"] = b"\xff\xfe<html>502</html>"  # not even UTF-8
        pool.scrape_once()  # UnicodeDecodeError must not escape
        assert rep.state == ACTIVE
        # Nor does a garbled answer observe (or undo) a drain: the
        # admin-drained replica stays out of rotation.
        assert pool.drain(a)
        pool.scrape_once()
        pool.scrape_once()
        assert rep.state == DRAINING and not rep.drain_observed
        pool.close()
    finally:
        srv.shutdown()
        CircuitBreaker.evict(a)


# ------------------------------------------------------- loopback serving


def _replica_fleet(n, dim=8, dispatch_seconds=0.002):
    """n loopback fake-engine replicas; per-row dispatch cost so one
    replica is launch-bound (the spread has something to win)."""
    engines, servers, targets = [], [], []
    for _ in range(n):
        e = AsyncFakeEngine(dim=dim, dispatch_seconds=dispatch_seconds,
                            per_row=True)
        srv, port = serve_engine(e, 0, host="127.0.0.1")
        engines.append(e)
        servers.append(srv)
        targets.append(f"127.0.0.1:{port}")
    return engines, servers, targets


def test_router_loopback_spreads_load_and_exposes_metrics():
    """The quick-tier smoke: p2c over 2 in-process replicas spreads a
    concurrent burst (both replicas serve > 25% of rows) and the
    router's /metrics exposes the tdn_router_* family."""
    engines, servers, targets = _replica_fleet(2)
    pool = ReplicaPool(targets, seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    metrics = start_http_server(0, host="127.0.0.1",
                                health_fn=router_health(pool))
    outs = {}
    lock = threading.Lock()

    def worker(i):
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=15.0, breaker=None)
        mine = [c.process(np.full((1, 8), float(i))) for _ in range(8)]
        c.close()
        with lock:
            outs[i] = mine

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    try:
        assert len(outs) == 8
        for i, mine in outs.items():
            assert len(mine) == 8
            for o in mine:
                np.testing.assert_allclose(o, np.full((1, 8), 2.0 * i))
        served = [sum(len(r) for r in e.dispatched_rows) for e in engines]
        total = sum(served)
        # >= not ==: the batcher rounds coalesced batches up to bucket
        # sizes, so dispatched rows include occasional zero-pad tails
        # (3 requests coalescing into a 4-bucket). Exactly-one-reply is
        # asserted above per worker; this counts launch-side work.
        assert total >= 64
        assert min(served) / total > 0.25, (
            f"p2c must spread the burst; got {served}"
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics.port}/metrics", timeout=5.0
        ) as r:
            parsed = parse_prometheus_text(r.read().decode())
        for t in targets:
            key = f'tdn_router_requests_total{{replica="{t}",outcome="ok"}}'
            assert parsed.get(key, 0) > 0, f"missing series {key}"
        assert parsed.get("tdn_router_placement_seconds_count", 0) >= 64
        for t in targets:
            assert parsed.get(
                f'tdn_router_replica_healthy{{replica="{t}"}}'
            ) == 1.0
        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics.port}/healthz", timeout=5.0
        ) as r:
            health = json.loads(r.read().decode())
        assert health["ready"] and health["role"] == "router"
    finally:
        metrics.close()
        rsrv.stop(0)
        for s in servers:
            s.stop(0)
        pool.close()
        for t in targets:
            CircuitBreaker.evict(t)


def test_replica_kill_mid_burst_fails_over_without_loss():
    """Chaos: one of three replicas dies mid-burst. Every request
    completes via router failover (clients carry NO retry policy — the
    fleet absorbs the loss), tdn_router_failovers_total rises, and
    each request yields exactly one reply."""
    engines, servers, targets = _replica_fleet(3)
    pool = ReplicaPool(targets, seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    failovers0 = _counter_total("tdn_router_failovers_total")
    outs = {}
    errs = []
    lock = threading.Lock()
    started = threading.Event()

    def worker(i):
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=30.0,
                       retry=None, breaker=None)
        mine = []
        try:
            for k in range(10):
                mine.append(c.process(np.full((1, 8), float(i * 100 + k))))
                started.set()
        except Exception as e:  # noqa: BLE001 — the test inspects it
            with lock:
                errs.append(f"{type(e).__name__}: {e}"[:200])
        finally:
            c.close()
            with lock:
                outs[i] = mine

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    assert started.wait(15.0), "burst never started"
    servers[0].stop(None)  # hard kill, no grace: in-flight RPCs die too
    for t in threads:
        t.join(60)
    try:
        assert not errs, errs[:3]
        assert len(outs) == 6
        for i, mine in outs.items():
            # Exactly one reply per request, each bit-correct — a
            # failover can recompute, but must never double-deliver.
            assert len(mine) == 10
            for k, o in enumerate(mine):
                np.testing.assert_allclose(
                    o, np.full((1, 8), 2.0 * (i * 100 + k))
                )
        assert _counter_total("tdn_router_failovers_total") > failovers0, \
            "the kill must be visible as failovers"
    finally:
        rsrv.stop(0)
        for s in servers[1:]:
            s.stop(0)
        pool.close()
        for t in targets:
            CircuitBreaker.evict(t)


def test_rolling_restart_zero_dropped_requests():
    """The zero-downtime choreography over a live burst: each replica
    in turn is drained (stop placing -> outstanding hits zero ->
    server restarted on the SAME address -> re-admitted with a fresh
    breaker). No request is dropped or duplicated across the full
    cycle."""
    engines, servers, targets = _replica_fleet(3, dispatch_seconds=0.001)
    pool = ReplicaPool(targets, seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    stop = threading.Event()
    counts = {}
    errs = []
    lock = threading.Lock()

    def worker(i):
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=30.0,
                       retry=None, breaker=None)
        n = 0
        try:
            while not stop.is_set():
                out = c.process(np.full((1, 8), float(i)))
                np.testing.assert_allclose(out, np.full((1, 8), 2.0 * i))
                n += 1
        except Exception as e:  # noqa: BLE001 — zero tolerated
            with lock:
                errs.append(f"{type(e).__name__}: {e}"[:200])
        finally:
            c.close()
            with lock:
                counts[i] = n

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    try:
        for idx, target in enumerate(targets):
            host, port = target.rsplit(":", 1)
            assert pool.drain(target)
            assert pool.wait_drained(target, timeout=20.0), \
                f"{target} never quiesced"
            servers[idx].stop(grace=5.0).wait(10.0)
            # Restart on the REUSED address (grpc sets SO_REUSEADDR);
            # a fresh engine models the restarted process.
            engines[idx] = AsyncFakeEngine(dim=8, dispatch_seconds=0.001,
                                           per_row=True)
            servers[idx], bound = serve_engine(
                engines[idx], int(port), host=host
            )
            assert bound == int(port)
            assert pool.undrain(target)
            time.sleep(0.05)  # let the burst exercise the rejoined replica
        time.sleep(0.1)
    finally:
        stop.set()
        for t in threads:
            t.join(30)
        rsrv.stop(0)
        for s in servers:
            s.stop(0)
        pool.close()
        for t in targets:
            CircuitBreaker.evict(t)
    assert not errs, errs[:3]
    assert all(n > 0 for n in counts.values())
    # Every restarted replica rejoined and served part of the burst.
    for e in engines:
        assert len(e.dispatched_rows) > 0, \
            "a restarted replica never received traffic after rejoin"


def test_healthz_scrape_drives_drain_and_rejoin():
    """The scrape half of the choreography: a replica whose /healthz
    reports draining:true stops receiving placements with NO admin
    call (the operator SIGTERMed it directly); when the restarted
    server answers ready again, the pool re-admits it with a fresh
    breaker."""
    a, b = _fresh_targets("scrape:a", "scrape:b")
    state = {"draining": False, "ready": True}

    def health():
        return dict(state)

    msrv = start_http_server(0, host="127.0.0.1", health_fn=health)
    try:
        pool = ReplicaPool(
            [a, b], [f"127.0.0.1:{msrv.port}", None], seed=0
        )
        pool.scrape_once()
        assert pool.replicas()[0].state == ACTIVE
        # SIGTERM landed on the replica: its own GracefulDrain flips
        # /healthz (wrap_health semantics: ready False, draining True).
        state.update(draining=True, ready=False)
        pool.scrape_once()
        rep = pool.replicas()[0]
        assert rep.state == DRAINING and rep.reported_draining
        assert {pool.place().target for _ in range(5)} == {b}
        # Trip the breaker while down; the restart must not inherit it.
        old = rep.breaker
        for _ in range(old.failure_threshold):
            old.record_failure()
        state.update(draining=False, ready=True)
        pool.scrape_once()
        rep = pool.replicas()[0]
        assert rep.state == ACTIVE
        assert rep.breaker.state == CircuitBreaker.CLOSED
        assert rep.breaker is not old
        pool.close()
    finally:
        msrv.close()
        CircuitBreaker.evict(a)


def test_drain_not_reverted_by_metrics_scrape_blip():
    """Regression: one blown /metrics fetch on an admin-drained STATIC
    replica set drain_observed (the 'unreachable = process exited'
    heuristic fired on a single endpoint failure), so the very next
    ready scrape auto-undrained the replica the operator just drained.
    /healthz reachability is the arbiter of 'exited'."""
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/healthz"):
                body = b'{"ready": true, "draining": false}'
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(500)  # the metrics fetch blows up

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    (a,) = _fresh_targets("blip:a")
    pool = ReplicaPool([a], [f"127.0.0.1:{srv.server_address[1]}"],
                       seed=0)
    try:
        assert pool.drain(a)
        rep = pool.replicas()[0]
        pool.scrape_once()  # metrics 500s, healthz answers ready
        assert not rep.drain_observed, \
            "a metrics blip is not a drain observation"
        assert rep.state == DRAINING, \
            "admin drain must survive a metrics scrape blip"
        pool.scrape_once()  # nor does a second ready scrape undrain
        assert rep.state == DRAINING
    finally:
        srv.shutdown()
        pool.close()
        CircuitBreaker.evict(a)


def test_failover_tries_every_placeable_replica_before_abort():
    """Regression: the attempt cap was the client-oriented
    policy.max_attempts=3 regardless of fleet size — on a pool where
    3 replicas died together (breakers still closed, and dead-fast
    failures keep their outstanding at 0 so p2c PREFERS them) a
    request aborted UNAVAILABLE with healthy replicas never tried.
    Every replica in the request's view gets at least one shot."""
    import grpc

    from tpu_dist_nn.serving.router import Router

    targets = _fresh_targets("fleet:d1", "fleet:d2", "fleet:d3",
                             "fleet:ok")
    pool = ReplicaPool(list(targets), seed=0)
    healthy = "fleet:ok"

    class _Unavail(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return "replica down"

    calls = []

    def make_call(r):
        def call(method, payload, *, timeout=None, metadata=()):
            calls.append(r.target)
            if r.target != healthy:
                raise _Unavail()
            return b"reply"

        return call

    for rep in pool.replicas():
        rep.call = make_call(rep)
        if rep.target == healthy:
            # p2c must prefer the dead replicas: the healthy one looks
            # maximally loaded, the dead ones fail fast at 0.
            rep.outstanding = 1000

    class Ctx:
        def invocation_metadata(self):
            return ()

        def time_remaining(self):
            return None

        def set_trailing_metadata(self, md):
            pass

        def abort(self, code, msg):
            raise AssertionError(f"aborted {code}: {msg}")

    router = Router(pool)
    assert router.handle("Process", b"req", Ctx()) == b"reply"
    assert calls[-1] == healthy
    assert len(set(calls[:-1])) == 3, "all three dead replicas tried"
    pool.close()
    for t in targets:
        CircuitBreaker.evict(t)


# -------------------------------------------- session affinity on the wire


def test_generate_failover_and_session_affinity_over_wire():
    """Generate over the router: a replica answering UNAVAILABLE to
    everything (fault interceptor) is transparently failed over; the
    greedy tokens match the single-server reference exactly, each
    request yields ONE output, and the session key pins follow-ups to
    the surviving replica."""
    import jax

    from tpu_dist_nn.models.generate import generate
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.serving import SESSION_HEADER, serve_lm_generate

    assert SESSION_HEADER == "x-tdn-session"
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(3), cfg)
    prompts = (np.arange(8, dtype=np.int64)[None, :] % 7)
    ref = np.asarray(generate(params, cfg, prompts, 6))

    # Replica A rejects EVERY request; replica B serves.
    plan = faults.FaultPlan(every=1, fault=faults.unavailable())
    srv_a, port_a = serve_lm_generate(
        params, cfg, 0, max_new_tokens=6, prompt_len=8, host="127.0.0.1",
        gen_slots=2, warm_rows=1,
        interceptors=(faults.FaultInterceptor(plan),),
    )
    srv_b, port_b = serve_lm_generate(
        params, cfg, 0, max_new_tokens=6, prompt_len=8, host="127.0.0.1",
        gen_slots=2, warm_rows=1,
    )
    ta, tb = f"127.0.0.1:{port_a}", f"127.0.0.1:{port_b}"
    CircuitBreaker.evict(ta)
    CircuitBreaker.evict(tb)
    pool = ReplicaPool([ta, tb], seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    failovers0 = _counter_total("tdn_router_failovers_total")
    try:
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=30.0, retry=None,
                       breaker=None, session_key="chat-42")
        outs = [c.generate(prompts) for _ in range(3)]
        c.close()
        assert len(outs) == 3
        for out in outs:
            np.testing.assert_array_equal(out[:, 8:], ref)
        # The session ended up pinned to the replica that actually
        # served it — follow-ups skip the faulty replica entirely.
        assert pool.pinned("chat-42") == tb
        if plan.fired:
            assert _counter_total("tdn_router_failovers_total") > failovers0
    finally:
        rsrv.stop(0)
        srv_a.stop(0)
        srv_b.stop(0)
        pool.close()
        CircuitBreaker.evict(ta)
        CircuitBreaker.evict(tb)


def test_same_replica_retry_is_not_a_failover():
    """Regression: tdn_router_failovers_total means 're-placed onto
    ANOTHER replica'. A single-replica pool retrying the same replica
    after a transient fault (and succeeding) must not count."""
    e = AsyncFakeEngine(dim=8, dispatch_seconds=0.0, per_row=True)
    plan = faults.FaultPlan(at={1: faults.unavailable()})
    srv, port = serve_engine(
        e, 0, host="127.0.0.1",
        interceptors=(faults.FaultInterceptor(plan),),
    )
    (t,) = _fresh_targets(f"127.0.0.1:{port}")
    pool = ReplicaPool([t], seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    failovers0 = _counter_total("tdn_router_failovers_total")
    try:
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=15.0, retry=None,
                       breaker=None)
        out = c.process(np.full((1, 8), 3.0))
        c.close()
        np.testing.assert_allclose(out, np.full((1, 8), 6.0))
        assert plan.fired == 1, "the injected fault must have fired"
        assert _counter_total("tdn_router_failovers_total") == failovers0, \
            "a same-replica retry is not a failover"
    finally:
        rsrv.stop(0)
        srv.stop(0)
        pool.close()
        CircuitBreaker.evict(t)


def test_backoff_paces_same_replica_retries_despite_draining_peer():
    """Regression: retry_same_set was computed over ALL registered
    targets, so any unplaceable (draining / breaker-open) replica in
    the pool suppressed the jittered backoff forever and the router
    hammered the one struggling replica back-to-back with zero delay.
    The set must be built from PLACEABLE replicas."""
    from tpu_dist_nn.serving.resilience import RetryPolicy

    e = AsyncFakeEngine(dim=8, dispatch_seconds=0.0, per_row=True)
    plan = faults.FaultPlan(every=1, fault=faults.unavailable())
    srv, port = serve_engine(
        e, 0, host="127.0.0.1",
        interceptors=(faults.FaultInterceptor(plan),),
    )
    a, b = _fresh_targets(f"127.0.0.1:{port}", "backoff:drained")
    pool = ReplicaPool([a, b], seed=0)
    pool.drain(b)  # unplaceable peer that place() will never return
    sleeps = []
    policy = RetryPolicy(max_attempts=3, base_delay=0.001,
                         max_delay=0.002, seed=7,
                         sleep=lambda s: sleeps.append(s))
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1", retry=policy)
    try:
        import grpc as _grpc

        c = GrpcClient(f"127.0.0.1:{rport}", timeout=10.0,
                       retry=None, breaker=None)
        with pytest.raises(_grpc.RpcError) as err:
            c.process(np.full((1, 8), 3.0))
        c.close()
        assert err.value.code() == _grpc.StatusCode.UNAVAILABLE
        assert plan.fired == 3, "all attempts must have hit replica a"
        assert sleeps, (
            "same-replica retries must be paced by the backoff even "
            "while a draining replica is registered"
        )
    finally:
        rsrv.stop(0)
        srv.stop(0)
        pool.close()
        CircuitBreaker.evict(a)
        CircuitBreaker.evict(b)


def test_router_propagates_deterministic_status_without_failover():
    """INVALID_ARGUMENT is the replica's verdict, not a replica
    failure: the router propagates it verbatim and does NOT fail over
    (another replica would say the same thing)."""
    import grpc as _grpc

    engines, servers, targets = _replica_fleet(2)
    pool = ReplicaPool(targets, seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    failovers0 = _counter_total("tdn_router_failovers_total")
    try:
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=10.0,
                       retry=None, breaker=None)
        with pytest.raises(_grpc.RpcError) as e:
            c.process(np.zeros((1, 5)))  # wrong width for dim=8
        assert e.value.code() == _grpc.StatusCode.INVALID_ARGUMENT
        assert "(N, 8)" in (e.value.details() or "")
        c.close()
        assert _counter_total("tdn_router_failovers_total") == failovers0
        # Reachability evidence: the verdict must not have opened the
        # breaker of the replica that answered.
        assert all(
            r.breaker.state == CircuitBreaker.CLOSED
            for r in pool.replicas()
        )
    finally:
        rsrv.stop(0)
        for s in servers:
            s.stop(0)
        pool.close()
        for t in targets:
            CircuitBreaker.evict(t)


def test_router_trace_propagation_names_router_stages():
    """The router hop joins the caller's trace: one trace id spans
    client -> router (router.forward) -> replica handler, so /profile
    attributes router time as its own stage."""
    from tpu_dist_nn.obs.trace import TRACER

    engines, servers, targets = _replica_fleet(1)
    pool = ReplicaPool(targets, seed=0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    try:
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=10.0, breaker=None)
        c.process(np.ones((1, 8)))
        c.close()
        doc = json.loads(TRACER.render_json(None))
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        by_name = {}
        for s in spans:
            by_name.setdefault(s["name"], []).append(s)
        assert "router.forward" in by_name
        fwd = by_name["router.forward"][-1]
        trace_id = fwd["args"]["trace_id"]
        names_in_trace = {
            s["name"] for s in spans
            if s["args"].get("trace_id") == trace_id
        }
        # Client span, router root + forward, and the replica's own
        # handler tree all share ONE trace id.
        assert {"client.Process", "rpc.Process",
                "router.forward"} <= names_in_trace
        assert fwd["args"]["replica"] == targets[0]
    finally:
        rsrv.stop(0)
        for s in servers:
            s.stop(0)
        pool.close()
        for t in targets:
            CircuitBreaker.evict(t)


# ------------------------------------------------------- admin + aggregate


def test_admin_routes_drain_undrain_and_cli_client(capsys):
    engines, servers, targets = _replica_fleet(2)
    pool = ReplicaPool(targets, seed=0)
    from tpu_dist_nn.serving.router import admin_post_routes

    msrv = start_http_server(
        0, host="127.0.0.1", health_fn=router_health(pool),
        routes=admin_routes(pool),
        post_routes=admin_post_routes(pool),
    )
    try:
        from tpu_dist_nn.cli import main

        admin = f"127.0.0.1:{msrv.port}"
        rc = main(["router", "--admin", admin, "--list-replicas"])
        assert rc == 0
        snap = json.loads(capsys.readouterr().out.strip())
        assert {s["target"] for s in snap} == set(targets)
        rc = main(["router", "--admin", admin,
                   "--drain-replica", targets[0]])
        assert rc == 0
        assert json.loads(capsys.readouterr().out.strip())["draining"]
        assert pool.replicas()[0].state == DRAINING
        assert {pool.place().target for _ in range(5)} == {targets[1]}
        rc = main(["router", "--admin", admin,
                   "--undrain-replica", targets[0]])
        assert rc == 0
        assert json.loads(capsys.readouterr().out.strip())["active"]
        assert pool.replicas()[0].state == ACTIVE
        # Unknown replica: a clean 404-shaped error, not a traceback —
        # and the route's JSON verdict surfaces in the message instead
        # of a generic "could not fetch" (the operator must be able to
        # tell a typo'd replica name from a down router).
        rc = main(["router", "--admin", admin,
                   "--drain-replica", "nope:1"])
        assert rc == 2
        err = capsys.readouterr().err
        assert "HTTP 404" in err and '"draining": false' in err
    finally:
        msrv.close()
        for s in servers:
            s.stop(0)
        pool.close()
        for t in targets:
            CircuitBreaker.evict(t)


def test_aggregate_fleet_sums_counters_keeps_gauges_per_source():
    from tpu_dist_nn.cli import _aggregate_fleet

    router = {
        "__type__:tdn_router_requests_total": "counter",
        'tdn_router_requests_total{replica="a",outcome="ok"}': 5.0,
        "__type__:tdn_host_rss_bytes": "gauge",
        "tdn_host_rss_bytes": 100.0,
    }
    rep_a = {
        "__type__:tdn_rpc_requests_total": "counter",
        'tdn_rpc_requests_total{method="Process"}': 5.0,
        "__type__:tdn_host_rss_bytes": "gauge",
        "tdn_host_rss_bytes": 200.0,
        "__type__:tdn_batch_wait_seconds": "histogram",
        'tdn_batch_wait_seconds_count{method="Process"}': 5.0,
    }
    rep_b = {
        "__type__:tdn_rpc_requests_total": "counter",
        'tdn_rpc_requests_total{method="Process"}': 7.0,
        "__type__:tdn_host_rss_bytes": "gauge",
        "tdn_host_rss_bytes": 300.0,
        "__type__:tdn_batch_wait_seconds": "histogram",
        'tdn_batch_wait_seconds_count{method="Process"}': 7.0,
    }
    agg = _aggregate_fleet({"router": router, "a": rep_a, "b": rep_b})
    assert agg["summed"][
        'tdn_rpc_requests_total{method="Process"}'
    ] == 12.0
    assert agg["summed"][
        'tdn_batch_wait_seconds_count{method="Process"}'
    ] == 12.0
    assert agg["gauges"]["tdn_host_rss_bytes"] == {
        "router": 100.0, "a": 200.0, "b": 300.0,
    }


def test_cli_metrics_aggregate_scrapes_router_and_replicas(capsys):
    """`tdn metrics --target <router> --aggregate`: fleet discovery via
    /router/replicas, one command for router + every replica. Replica
    endpoints use private registries so the summed counters are real
    per-process series, not the shared test-process registry twice."""
    from tpu_dist_nn.cli import main
    from tpu_dist_nn.obs.registry import Registry

    regs = [Registry(), Registry()]
    for i, reg in enumerate(regs):
        reg.counter(
            "tdn_rpc_requests_total", "rpcs", labels=("method",)
        ).labels(method="Process").inc(10 * (i + 1))
        reg.gauge("tdn_batcher_queue_depth", "depth",
                  labels=("method",)).labels(method="Process").set(i + 1)
    rep_srvs = [
        start_http_server(0, host="127.0.0.1", registry=reg)
        for reg in regs
    ]
    a, b = _fresh_targets("agg:a", "agg:b")
    pool = ReplicaPool(
        [a, b],
        [f"127.0.0.1:{s.port}" for s in rep_srvs],
    )
    # Private registry for the router endpoint too: the global test-
    # process registry carries series from every other test.
    router_srv = start_http_server(
        0, host="127.0.0.1", registry=Registry(),
        routes=admin_routes(pool),
    )
    try:
        rc = main(["metrics", "--target",
                   f"127.0.0.1:{router_srv.port}", "--aggregate"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "router + 2 replica" in out
        assert '[sum] tdn_rpc_requests_total{method="Process"} = 30' in out
        assert ('[gauge] tdn_batcher_queue_depth{method="Process"} '
                f'@{a} = 1') in out
        assert ('[gauge] tdn_batcher_queue_depth{method="Process"} '
                f'@{b} = 2') in out
    finally:
        router_srv.close()
        for s in rep_srvs:
            s.close()
        pool.close()
        CircuitBreaker.evict(a)
        CircuitBreaker.evict(b)


# ------------------------------------------------------------ sampler + CLI


def test_runtime_sampler_publishes_pool_gauges():
    from tpu_dist_nn.obs import RuntimeSampler
    from tpu_dist_nn.obs.registry import Registry
    from tpu_dist_nn.serving.pool import REPLICA_HEALTHY

    a, b = _fresh_targets("smp:a", "smp:b")
    pool = ReplicaPool([a, b], seed=0)
    ra, _rb = pool.replicas()
    pool.begin(ra)
    ra.pending_rows = 17.0
    reg = Registry()
    sampler = RuntimeSampler(registry=reg)
    sampler.add_pool(pool)
    sampler.sample_once()
    out = reg.get("tdn_router_replica_outstanding")
    assert out.labels(replica=a).value == 1.0
    assert out.labels(replica=b).value == 0.0
    pend = reg.get("tdn_router_replica_pending_rows")
    assert pend.labels(replica=a).value == 17.0
    # Membership churn retires the dead series (regression: the
    # outstanding=1 phantom survived remove() at its last value
    # forever, and the label set grew unboundedly).
    pool.remove(a)
    sampler.sample_once()
    assert (a,) not in dict(out.samples())
    assert (a,) not in dict(pend.samples())
    assert (a,) not in dict(REPLICA_HEALTHY.samples())
    assert out.labels(replica=b).value == 0.0
    pool.close()
    CircuitBreaker.evict(a)
    CircuitBreaker.evict(b)


def test_scrape_once_fans_out_not_serial():
    """Regression: replicas were scraped serially, so a few wedged
    hosts (each costing up to 2x scrape_timeout of blocked HTTP) aged
    every HEALTHY replica's gauges past the staleness bound — p2c
    silently degraded fleet-wide. One tick must cost max(replica),
    not sum(replica)."""
    a, b, c = _fresh_targets("fan:a", "fan:b", "fan:c")
    pool = ReplicaPool([a, b, c], seed=0)
    seen = []

    def slow_scrape(rep):
        seen.append(rep.target)
        time.sleep(0.2)

    pool._scrape_one = slow_scrape
    t0 = time.monotonic()
    pool.scrape_once()
    dt = time.monotonic() - t0
    assert sorted(seen) == sorted([a, b, c])
    assert dt < 0.45, f"serial scrape: 3 x 0.2s took {dt:.2f}s"
    pool.close()
    for t in (a, b, c):
        CircuitBreaker.evict(t)


def test_cli_router_rejects_duplicate_replicas(capsys):
    """Regression: ReplicaPool.add() dedups on target, so a duplicate
    in --replicas silently ran the fleet at N-1 AND shifted every
    later --replica-metrics endpoint onto the wrong replica — the
    silent-misconfiguration class the parallel-list check fails
    loudly."""
    from tpu_dist_nn.cli import main

    rc = main(["router", "--replicas", "r:1,r:1,r:2",
               "--replica-metrics", "m:1,m:2,m:3"])
    assert rc == 2
    assert "duplicate" in capsys.readouterr().err


def test_cli_help_lists_router_and_session_flags(capsys):
    from tpu_dist_nn.cli import main

    with pytest.raises(SystemExit) as e:
        main(["router", "--help"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    for flag in ("--replicas", "--spawn", "--drain-replica",
                 "--scrape-interval", "--load-staleness"):
        assert flag in out
    with pytest.raises(SystemExit) as e:
        main(["infer", "--help"])
    assert e.value.code == 0
    assert "--session-key" in capsys.readouterr().out
    with pytest.raises(SystemExit) as e:
        main(["metrics", "--help"])
    assert e.value.code == 0
    assert "--aggregate" in capsys.readouterr().out
    # Serve mode without replicas is a clean user error.
    assert main(["router"]) == 2
    assert main(["router", "--spawn", "2"]) == 2
    assert main(["router", "--drain-replica", "x"]) == 2  # no --admin
    # --replica-metrics must be parallel to --replicas (a count
    # mismatch would silently leave tail replicas unscraped).
    assert main(["router", "--replicas", "a:1,b:2",
                 "--replica-metrics", "m:1"]) == 2


def test_spawn_argv_shape():
    """The subprocess command `--spawn` launches (the slow end-to-end
    spawn itself is exercised operationally, not in tier-1)."""
    import sys as _sys

    pool = ReplicaPool([], seed=0)
    # spawn_local builds `python -m tpu_dist_nn.cli up --config ...
    # --grpc-port 0 --metrics-port 0`; verify via a stub Popen.
    import subprocess
    import tpu_dist_nn.serving.pool as pool_mod

    captured = {}

    class FakeProc:
        stdout = None

        def __init__(self, argv, **kw):
            captured["argv"] = argv

        def poll(self):
            return None

        def terminate(self):
            pass

    real_popen = subprocess.Popen
    real_reader = pool_mod._read_child_ports
    subprocess.Popen = FakeProc
    pool_mod._read_child_ports = lambda proc, timeout: {
        "grpc_port": 5101, "metrics_port": 9100,
    }
    try:
        rep = pool.spawn_local("model.json",
                               extra_args=["--serve-warm-rows", "8"])
    finally:
        subprocess.Popen = real_popen
        pool_mod._read_child_ports = real_reader
    argv = captured["argv"]
    assert argv[0] == _sys.executable
    assert argv[1:4] == ["-m", "tpu_dist_nn.cli", "up"]
    assert "--config" in argv and "model.json" in argv
    assert "--grpc-port" in argv and "--metrics-port" in argv
    assert rep.target == "127.0.0.1:5101"
    assert rep.metrics_target == "127.0.0.1:9100"
    # The respawn argv reuses the now-known ports (reused address).
    assert "5101" in rep.spawn_argv and "9100" in rep.spawn_argv
    pool.close()
    CircuitBreaker.evict(rep.target)


def test_scrape_respawns_exited_spawned_replica():
    """Regression: admin-draining a POOL-SPAWNED replica SIGTERMed the
    child but nothing ever respawned it — the fleet ran at N-1 forever.
    The scrape loop must respawn an exited spawned replica on the same
    address so the ready scrape rejoins it (the other half of the
    rolling restart `--drain-replica` promises)."""
    import subprocess
    import sys as _sys

    import tpu_dist_nn.serving.pool as pool_mod

    (t,) = _fresh_targets("respawn:a")
    pool = ReplicaPool([t], seed=0)
    (rep,) = pool.replicas()

    class ExitedProc:
        def poll(self):
            return 0  # the drained child has exited

    spawned = []

    class FakeProc:
        def __init__(self, argv, **kw):
            spawned.append(argv)

        def poll(self):
            return None

        def terminate(self):
            pass

    rep.proc = ExitedProc()
    rep.spawn_argv = [_sys.executable, "-m", "tpu_dist_nn.cli", "up",
                      "--config", "m.json", "--grpc-port", "5101",
                      "--metrics-port", "9100"]
    pool.drain(t, signal_process=False)
    real_popen = subprocess.Popen
    real_reader = pool_mod._read_child_ports
    subprocess.Popen = FakeProc
    proc_at_port_wait = []

    def fake_reader(proc, timeout):
        # The child must already be on rep.proc while the port wait is
        # in flight: router shutdown mid-boot terminates rep.proc, and
        # a child parked in a local only there would be orphaned
        # holding the reused ports.
        proc_at_port_wait.append(rep.proc)
        return {"grpc_port": 5101, "metrics_port": 9100}

    pool_mod._read_child_ports = fake_reader
    try:
        pool.scrape_once()
        # The respawn runs on its own thread (a minutes-long engine
        # boot must not freeze scraping for the other replicas) —
        # wait for it before un-monkeypatching.
        deadline = time.monotonic() + 5.0
        while rep.respawning and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        subprocess.Popen = real_popen
        pool_mod._read_child_ports = real_reader
    assert spawned == [rep.spawn_argv], "the exited child must respawn"
    assert isinstance(rep.proc, FakeProc)
    assert [type(p) for p in proc_at_port_wait] == [FakeProc], \
        "rep.proc must carry the booting child BEFORE the port wait"
    assert rep.drain_observed, "the exit IS the drain being observed"
    assert not rep.respawning
    assert rep.state == DRAINING  # rejoin waits for the ready scrape
    # A second scrape must not double-spawn the now-running child.
    pool.scrape_once()
    assert len(spawned) == 1
    pool.close()
    CircuitBreaker.evict(t)


def test_scrape_respawns_crashed_active_replica():
    """Regression: auto-respawn was gated on state == DRAINING, so a
    spawned child that CRASHED (OOM/segfault — still ACTIVE when
    poll() returned) was never respawned: the dead target kept being
    placed until its breaker opened, then the fleet sat at N-1
    forever. A crash routes through the same drain-rejoin
    choreography as a rolling restart."""
    import subprocess
    import sys as _sys

    import tpu_dist_nn.serving.pool as pool_mod

    (t,) = _fresh_targets("crash:a")
    pool = ReplicaPool([t], seed=0)
    (rep,) = pool.replicas()

    class CrashedProc:
        def poll(self):
            return -11  # SIGSEGV, no drain ran

    spawned = []

    class FakeProc:
        def __init__(self, argv, **kw):
            spawned.append(argv)

        def poll(self):
            return None

    rep.proc = CrashedProc()
    rep.spawn_argv = [_sys.executable, "-m", "tpu_dist_nn.cli", "up",
                      "--config", "m.json", "--grpc-port", "5103",
                      "--metrics-port", "9103"]
    assert rep.state == ACTIVE
    real_popen = subprocess.Popen
    real_reader = pool_mod._read_child_ports
    subprocess.Popen = FakeProc
    pool_mod._read_child_ports = lambda proc, timeout: {
        "grpc_port": 5103, "metrics_port": 9103,
    }
    try:
        pool.scrape_once()
        deadline = time.monotonic() + 5.0
        while rep.respawning and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        subprocess.Popen = real_popen
        pool_mod._read_child_ports = real_reader
    assert spawned == [rep.spawn_argv], "a crashed child must respawn"
    assert isinstance(rep.proc, FakeProc)
    # Placement stops until the restarted server's ready scrape
    # rejoins it (fresh breaker) — same choreography as a drain.
    assert rep.state == DRAINING and rep.drain_observed
    assert pool.place() is None
    pool.close()
    CircuitBreaker.evict(t)


def test_failed_respawn_backs_off():
    """A crash-looping child (bad config, stolen port) must not become
    a hot spawn loop: a FAILED respawn pauses further attempts for a
    backoff window."""
    import subprocess
    import sys as _sys

    import tpu_dist_nn.serving.pool as pool_mod

    (t,) = _fresh_targets("crashloop:a")
    pool = ReplicaPool([t], seed=0)
    (rep,) = pool.replicas()

    spawned = []

    class DeadProc:
        def __init__(self, argv=None, **kw):
            if argv is not None:
                spawned.append(argv)

        def poll(self):
            return 1  # exits immediately, never prints ports

    def failing_reader(proc, timeout):
        raise RuntimeError("child exited before printing its ports")

    rep.proc = DeadProc()
    rep.spawn_argv = [_sys.executable, "-m", "tpu_dist_nn.cli", "up",
                      "--config", "bad.json"]
    pool.drain(t, signal_process=False)
    real_popen = subprocess.Popen
    real_reader = pool_mod._read_child_ports
    subprocess.Popen = DeadProc
    pool_mod._read_child_ports = failing_reader
    try:
        pool.scrape_once()
        deadline = time.monotonic() + 5.0
        while rep.respawning and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(spawned) == 1
        assert rep.respawn_backoff_until > time.monotonic()
        # Within the backoff window: no second spawn attempt.
        pool.scrape_once()
        time.sleep(0.05)
        assert len(spawned) == 1, "failed respawn must back off"
    finally:
        subprocess.Popen = real_popen
        pool_mod._read_child_ports = real_reader
    pool.close()
    CircuitBreaker.evict(t)


def test_respawn_aborts_when_pool_stopping():
    """A respawn thread still in its pre-spawn window when the pool
    shuts down must NOT spawn: the child would be born after cleanup
    already terminated rep.proc (the OLD exited process) and be
    orphaned holding the reused ports."""
    import subprocess
    import sys as _sys

    import tpu_dist_nn.serving.pool as pool_mod

    (t,) = _fresh_targets("stopspawn:a")
    pool = ReplicaPool([t], seed=0)
    (rep,) = pool.replicas()

    class ExitedProc:
        def poll(self):
            return 0

    spawned = []

    class FakeProc:
        def __init__(self, argv, **kw):
            spawned.append(argv)

        def poll(self):
            return None

    rep.proc = ExitedProc()
    rep.spawn_argv = [_sys.executable, "-m", "tpu_dist_nn.cli", "up",
                      "--config", "m.json"]
    pool.drain(t, signal_process=False)
    pool._stop.set()  # shutdown began
    real_popen = subprocess.Popen
    real_reader = pool_mod._read_child_ports
    subprocess.Popen = FakeProc
    pool_mod._read_child_ports = lambda proc, timeout: {
        "grpc_port": 1, "metrics_port": 2,
    }
    try:
        pool.scrape_once()
        deadline = time.monotonic() + 5.0
        while rep.respawning and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        subprocess.Popen = real_popen
        pool_mod._read_child_ports = real_reader
    assert spawned == [], "no child may spawn once shutdown began"
    assert not rep.respawning
    pool.close()
    CircuitBreaker.evict(t)


def test_pool_close_releases_process_global_state():
    """Regression: close() left the per-target process-global claims
    behind — breaker registry entries, tdn_breaker_state and
    tdn_router_replica_healthy series — so a process cycling pools
    over ephemeral-port replicas (bench, tests) accumulated dead
    series forever, and a later pool on a reused address inherited
    the dead incumbent's breaker history."""
    from tpu_dist_nn.serving.pool import REPLICA_HEALTHY
    from tpu_dist_nn.serving.resilience import BREAKER_STATE

    a, b = _fresh_targets("closeg:a", "closeg:b")
    pool = ReplicaPool([a, b], seed=0)
    for _ in range(pool.replicas()[0].breaker.failure_threshold):
        pool.replicas()[0].breaker.record_failure()
    assert (a,) in dict(REPLICA_HEALTHY.samples())
    assert a in CircuitBreaker._registry

    # A pool-spawned child is OWNED by the pool: close() must reap it
    # (library callers don't get cmd_router's CLI cleanup).
    class LiveProc:
        def __init__(self):
            self.terminated = False

        def poll(self):
            return 0 if self.terminated else None

        def terminate(self):
            self.terminated = True

        def wait(self, timeout=None):
            return 0

    child = LiveProc()
    pool.replicas()[0].proc = child
    pool.close()
    assert child.terminated, "close() must reap pool-spawned children"
    for t in (a, b):
        assert (t,) not in dict(REPLICA_HEALTHY.samples())
        assert (t,) not in dict(BREAKER_STATE.samples())
        assert t not in CircuitBreaker._registry
    # A new pool on the reused address starts with a CLOSED breaker.
    pool2 = ReplicaPool([a], seed=0)
    assert pool2.replicas()[0].breaker.state == CircuitBreaker.CLOSED
    pool2.close()


def test_restart_replica_parks_child_before_port_wait():
    """Regression: restart_replica assigned rep.proc only AFTER the
    up-to-180s port wait — router shutdown mid-boot terminated the OLD
    exited process handle and orphaned the new child on the reused
    ports (the same bug fixed in the scrape loop's auto-respawn)."""
    import subprocess
    import sys as _sys

    import tpu_dist_nn.serving.pool as pool_mod

    (t,) = _fresh_targets("restartpark:a")
    pool = ReplicaPool([t], seed=0)
    (rep,) = pool.replicas()

    class OldProc:
        def poll(self):
            return 0

        def wait(self, timeout=None):
            return 0

        def terminate(self):
            pass

    class FakeProc:
        def __init__(self, argv, **kw):
            pass

        def poll(self):
            return None

    rep.proc = OldProc()
    rep.spawn_argv = [_sys.executable, "-m", "tpu_dist_nn.cli", "up",
                      "--config", "m.json", "--grpc-port", "5102",
                      "--metrics-port", "9102"]
    proc_at_port_wait = []

    def fake_reader(proc, timeout):
        proc_at_port_wait.append(type(rep.proc))
        return {"grpc_port": 5102, "metrics_port": 9102}

    real_popen = subprocess.Popen
    real_reader = pool_mod._read_child_ports
    subprocess.Popen = FakeProc
    pool_mod._read_child_ports = fake_reader
    try:
        assert pool.restart_replica(t, grace=0.5)
    finally:
        subprocess.Popen = real_popen
        pool_mod._read_child_ports = real_reader
    assert proc_at_port_wait == [FakeProc], \
        "rep.proc must carry the booting child BEFORE the port wait"
    assert isinstance(rep.proc, FakeProc)
    assert rep.state == ACTIVE  # rejoined with a fresh breaker
    pool.close()
    CircuitBreaker.evict(t)


def test_restart_replica_true_when_scrape_rejoins_first():
    """Regression: undrain() refusing non-DRAINING replicas made
    restart_replica's final undrain() return False whenever the scrape
    loop's auto-rejoin observed the restarted server's ready /healthz
    first — a fully successful restart reported as failure (callers
    honoring the bool contract would retry or alert)."""
    import subprocess
    import sys as _sys

    import tpu_dist_nn.serving.pool as pool_mod

    (t,) = _fresh_targets("restartrace:a")
    pool = ReplicaPool([t], seed=0)
    (rep,) = pool.replicas()
    rep.proc = _FakeChildProc()
    rep.proc.terminated = True  # old child already exited
    rep.spawn_argv = [_sys.executable, "-m", "tpu_dist_nn.cli", "up",
                      "--config", "m.json", "--grpc-port", "5103",
                      "--metrics-port", "9103"]

    class FakeProc:
        def __init__(self, argv, **kw):
            pass

        def poll(self):
            return None

    def fake_reader(proc, timeout):
        # The scrape tick observes the restarted server ready and
        # auto-rejoins at the same moment the ports print.
        rep.drain_observed = True
        assert pool.undrain(t)
        return {"grpc_port": 5103, "metrics_port": 9103}

    real_popen = subprocess.Popen
    real_reader = pool_mod._read_child_ports
    subprocess.Popen = FakeProc
    pool_mod._read_child_ports = fake_reader
    try:
        assert pool.restart_replica(t, grace=0.5), \
            "a restart the scraper already rejoined is still a success"
    finally:
        subprocess.Popen = real_popen
        pool_mod._read_child_ports = real_reader
    assert rep.state == ACTIVE
    pool.close()
    CircuitBreaker.evict(t)


def test_forward_timeout_caps_deadline_less_forwards():
    """Regression: a deadline-less caller (no gRPC deadline, no
    x-tdn-timeout-ms hint) forwarded with timeout=None — a replica
    that accepts TCP but never answers held a router worker thread
    forever, and 32 such wedged forwards stalled the whole front door
    (the engine path bounds these via the batcher's submit_timeout)."""
    from tpu_dist_nn.serving.router import Router

    (t,) = _fresh_targets("fwdcap:a")
    pool = ReplicaPool([t], seed=0)
    (rep,) = pool.replicas()
    seen = []

    def capture_call(method, payload, *, timeout=None, metadata=()):
        seen.append(timeout)
        return b"reply"

    rep.call = capture_call

    class Ctx:
        def invocation_metadata(self):
            return ()

        def time_remaining(self):
            return None

        def set_trailing_metadata(self, md):
            pass

    router = Router(pool, forward_timeout=45.0)
    assert router.handle("Process", b"req", Ctx()) == b"reply"
    assert seen == [45.0], "deadline-less forward must be capped"
    # A caller-supplied budget still wins over the cap.
    class DeadlineCtx(Ctx):
        def time_remaining(self):
            return 9.0

    seen.clear()
    assert router.handle("Process", b"req", DeadlineCtx()) == b"reply"
    assert seen and seen[0] is not None and seen[0] <= 9.0
    pool.close()
    CircuitBreaker.evict(t)


# ------------------------------------------------------------- bench gate


def test_bench_gate_router_rps_skip_and_fail():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_gate",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "bench_gate.py"),
    )
    bench_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_gate)
    base = {"backend": "cpu", "value": 100.0}
    prev_no_router = dict(base, serving={"coalesced": {"rps": 50.0}})
    cur = dict(base, serving={
        "coalesced": {"rps": 50.0}, "router": {"rps": 300.0},
    })
    verdict = bench_gate.compare(prev_no_router, cur)
    rows = {r["metric"]: r for r in verdict["metrics"]}
    assert "skipped" in rows["router_rps"], \
        "rounds predating the router section must skip, not fail"
    prev = dict(base, serving={"router": {"rps": 300.0}})
    cur_reg = dict(base, serving={"router": {"rps": 250.0}})
    verdict = bench_gate.compare(prev, cur_reg)
    assert "router_rps" in verdict["regressions"]
    cur_ok = dict(base, serving={"router": {"rps": 296.0}})
    verdict = bench_gate.compare(prev, cur_ok)
    assert "router_rps" not in verdict["regressions"]
