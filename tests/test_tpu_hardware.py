"""Real-hardware parity gates — skipped on the CPU test mesh.

The CPU suite pins exact tolerances under
``JAX_DEFAULT_MATMUL_PRECISION=highest``; on a real TPU the default f32
matmul precision differs from the float64 oracle by ~1e-3 relative
(bf16-accumulated MXU passes). These tests encode that documented
tolerance policy (SURVEY.md §7 hard part 3) against the actual chip,
plus compile/parity checks for the Pallas kernels that only lower via
Mosaic there. Run manually on a TPU host:
``TDN_TEST_TPU=1 python -m pytest tests/test_tpu_hardware.py``
(without the env var the conftest forces the CPU backend and every test
here skips).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="needs a real TPU backend"
)

from tpu_dist_nn.models.fcnn import forward, init_fcnn, spec_from_params  # noqa: E402
from tpu_dist_nn.testing.oracle import oracle_forward_batch  # noqa: E402

TPU_RTOL = 2e-3  # default-precision f32 MXU vs float64 oracle
TPU_ATOL = 2e-3


def test_forward_parity_vs_oracle_on_device():
    params = init_fcnn(jax.random.key(0), [784, 128, 64, 10])
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    x = np.random.default_rng(0).uniform(0, 1, (64, 784)).astype(np.float32)
    got = np.asarray(jax.jit(forward)(params, jnp.asarray(x)))
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=TPU_RTOL, atol=TPU_ATOL)


def test_fused_chain_matches_jnp_on_device():
    from tpu_dist_nn.kernels.fused_dense import fcnn_fused_forward

    params = init_fcnn(jax.random.key(1), [784, 128, 64, 10])
    x = jnp.asarray(
        np.random.default_rng(1).uniform(0, 1, (256, 784)), jnp.float32
    )
    got = np.asarray(
        fcnn_fused_forward(params, x, activations=("relu", "relu", "softmax"))
    )
    want = np.asarray(forward(params, x))
    np.testing.assert_allclose(got, want, rtol=TPU_RTOL, atol=TPU_ATOL)


def test_flash_attention_matches_reference_on_device():
    from tpu_dist_nn.kernels.flash_attention import flash_attention
    from tpu_dist_nn.models.transformer import dot_product_attention

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.normal(size=(2, 128, 4, 32)) * 0.5, jnp.float32)
    k = jnp.asarray(rng.normal(size=(2, 128, 4, 32)) * 0.5, jnp.float32)
    v = jnp.asarray(rng.normal(size=(2, 128, 4, 32)) * 0.5, jnp.float32)
    got = np.asarray(flash_attention(q, k, v, causal=True))
    want = np.asarray(dot_product_attention(q, k, v, causal=True))
    # The MXU path rounds through bf16 (8 mantissa bits ≈ 4e-3 rel);
    # observed worst case is 1 element in 32k just over 2e-3.
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


def test_conv_kernel_matches_lax_on_device():
    from jax import lax

    from tpu_dist_nn.kernels.conv2d import fused_conv2d

    rng = np.random.default_rng(3)
    imgs = jnp.asarray(rng.normal(size=(64, 16, 16, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(3, 3, 32, 64)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    got = fused_conv2d(imgs, w, b, padding="same", activation="relu",
                       pool_window=(2, 2))
    conv = lax.conv_general_dilated(
        imgs, w, window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + b
    want = lax.reduce_window(
        jnp.maximum(conv, 0.0), -jnp.inf, lax.max,
        window_dimensions=(1, 2, 2, 1), window_strides=(1, 2, 2, 1),
        padding="VALID",
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=TPU_RTOL, atol=TPU_ATOL
    )


def test_int8_chain_accuracy_preserving_on_device():
    from tpu_dist_nn.kernels.quantized import (
        fcnn_quantized_forward,
        quantize_fcnn,
    )

    params = init_fcnn(jax.random.key(2), [784, 128, 64, 10])
    x = jnp.asarray(
        np.random.default_rng(4).uniform(0, 1, (512, 784)), jnp.float32
    )
    qp = quantize_fcnn(params)
    # prefer_kernel=True: this gate exists to prove the Pallas int8
    # chain on hardware; the measured-width dispatch would route the
    # flagship's tiny layers to the jnp chain.
    got = np.asarray(
        fcnn_quantized_forward(qp, x, activations=("relu", "relu", "softmax"),
                               prefer_kernel=True)
    ).argmax(-1)
    want = np.asarray(forward(params, x)).argmax(-1)
    # Int8 is lossy; the serving gate is argmax agreement, not values.
    assert (got == want).mean() > 0.97
