"""KV-cached autoregressive decoding: greedy parity vs the
teacher-forced full forward, sampling reproducibility, and bounds."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.generate import decode_step, generate, prefill
from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    forward,
    init_transformer,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=3, d_ff=64, max_seq_len=48
)


def _prompt(batch, t, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, t)), jnp.int32)


def test_prefill_logits_match_forward():
    params = init_transformer(jax.random.key(0), CFG)
    tokens = _prompt(2, 12)
    logits, cache = prefill(params, tokens, CFG, max_len=20)
    ref = forward(params, tokens, CFG)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
    assert cache["k"].shape == (3, 2, 20, 4, 8)


def test_greedy_generation_matches_teacher_forced_oracle():
    params = init_transformer(jax.random.key(1), CFG)
    prompt = _prompt(2, 8, seed=2)
    n_new = 10
    got = generate(params, CFG, prompt, n_new)

    # Oracle: grow the sequence one token at a time through the full
    # batched forward (no cache) and take argmax each step. Jitted per
    # length: the growing-shape eager loop re-executes op-by-op every
    # run, while the 10 small compiles land in the persistent cache.
    jfwd = jax.jit(forward, static_argnums=2)
    seq = prompt
    want = []
    for _ in range(n_new):
        logits = jfwd(params, seq, CFG)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    want = jnp.stack(want, axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generation_is_jittable():
    params = init_transformer(jax.random.key(1), CFG)
    prompt = _prompt(2, 8, seed=2)
    eager = generate(params, CFG, prompt, 6)
    jitted = jax.jit(
        lambda p, t: generate(p, CFG, t, 6)
    )(params, prompt)
    np.testing.assert_array_equal(np.asarray(eager), np.asarray(jitted))


def test_sampling_reproducible_and_varies_with_key():
    params = init_transformer(jax.random.key(3), CFG)
    prompt = _prompt(2, 6, seed=4)
    a = generate(params, CFG, prompt, 8, temperature=1.0, key=jax.random.key(7))
    b = generate(params, CFG, prompt, 8, temperature=1.0, key=jax.random.key(7))
    c = generate(params, CFG, prompt, 8, temperature=1.0, key=jax.random.key(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))
    assert int(a.min()) >= 0 and int(a.max()) < CFG.vocab_size


def test_generate_boundary_total_fits_positional_table():
    # T + N == max_seq_len + 1 is VALID: the decode loop embeds
    # positions 0..T+N-2 only (the last sampled token is returned, not
    # fed back), so the positional table is never over-indexed. The
    # shared validator must accept what the decoders accept (ADVICE r5).
    params = init_transformer(jax.random.key(0), CFG)
    out = generate(params, CFG, _prompt(1, 40), CFG.max_seq_len + 1 - 40)
    assert out.shape == (1, CFG.max_seq_len + 1 - 40)
    assert int(out.min()) >= 0 and int(out.max()) < CFG.vocab_size


def test_generate_bounds_and_key_requirements():
    params = init_transformer(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(params, CFG, _prompt(1, 40), 20)
    with pytest.raises(ValueError, match="PRNG key"):
        generate(params, CFG, _prompt(1, 4), 4, temperature=0.5)
    with pytest.raises(ValueError, match="temperature"):
        generate(params, CFG, _prompt(1, 4), 4, temperature=-0.5)
    with pytest.raises(ValueError, match="max_new_tokens"):
        generate(params, CFG, _prompt(1, 4), 0)
    import dataclasses

    noncausal = dataclasses.replace(CFG, causal=False)
    with pytest.raises(ValueError, match="causal"):
        generate(params, noncausal, _prompt(1, 4), 4)


def test_generate_single_token():
    params = init_transformer(jax.random.key(1), CFG)
    prompt = _prompt(2, 8, seed=2)
    got = generate(params, CFG, prompt, 1)
    want = jnp.argmax(forward(params, prompt, CFG)[:, -1], -1)
    np.testing.assert_array_equal(np.asarray(got[:, 0]), np.asarray(want))


def test_decode_step_updates_cache_in_place_positions():
    params = init_transformer(jax.random.key(0), CFG)
    tokens = _prompt(1, 4)
    _, cache = prefill(params, tokens, CFG, max_len=10)
    before = np.asarray(cache["k"][:, :, 4])
    assert np.all(before == 0)  # position 4 still empty
    _, cache = decode_step(
        params, cache, jnp.int32(4), tokens[:, 0], CFG
    )
    after = np.asarray(cache["k"][:, :, 4])
    assert np.any(after != 0)  # now written
    # Earlier positions untouched.
    np.testing.assert_array_equal(
        np.asarray(cache["k"][:, :, :4]),
        np.asarray(prefill(params, tokens, CFG, max_len=10)[1]["k"][:, :, :4]),
    )


def test_top_k_restricts_candidates():
    from tpu_dist_nn.models.generate import _truncate_logits

    logits = jnp.asarray([[1.0, 5.0, 3.0, 4.0, 2.0]])
    out = np.asarray(_truncate_logits(logits, top_k=2, top_p=None))
    neg = np.finfo(np.float32).min
    np.testing.assert_array_equal(out[0] > neg, [False, True, False, True, False])


def test_top_p_keeps_minimal_nucleus():
    from tpu_dist_nn.models.generate import _truncate_logits

    # softmax of [0, ln4, ln5, ln1e-3-ish]: probs ~ [.1, .4, .5, ~0]
    logits = jnp.log(jnp.asarray([[1.0, 4.0, 5.0, 1e-3]]))
    out = np.asarray(_truncate_logits(logits, top_k=None, top_p=0.85))
    neg = np.finfo(np.float32).min
    # Nucleus at p=0.85: {5.0 (.5), 4.0 (.4)} reaches 0.9 >= 0.85 with
    # the previous mass 0.5 < 0.85; the 0.1 and ~0 tokens are cut.
    np.testing.assert_array_equal(out[0] > neg, [False, True, True, False])
    # p=1.0 keeps everything.
    full = np.asarray(_truncate_logits(logits, top_k=None, top_p=1.0))
    assert (full[0] > neg).all()


def test_generate_top_k_one_is_greedy():
    cfg = CFG
    params = init_transformer(jax.random.key(0), cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    greedy = np.asarray(generate(params, cfg, prompt, 8))
    topk1 = np.asarray(
        generate(params, cfg, prompt, 8, temperature=1.0, top_k=1,
                 key=jax.random.key(7))
    )
    np.testing.assert_array_equal(greedy, topk1)


def test_generate_top_k_samples_within_set():
    cfg = CFG
    params = init_transformer(jax.random.key(0), cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    # Every emitted token must be among the 2 highest-logit tokens for
    # its position, verified by teacher-forcing the full sequence
    # through the batched forward (high temperature would escape the
    # set immediately if the mask were broken).
    out = np.asarray(
        generate(params, cfg, prompt, 8, temperature=4.0, top_k=2,
                 key=jax.random.key(3))
    )
    seq = np.concatenate([np.asarray(prompt), out], axis=1)
    logits = np.asarray(forward(params, jnp.asarray(seq), cfg))
    T = prompt.shape[1]
    for i in range(out.shape[1]):
        step_logits = logits[0, T - 1 + i]
        top2 = np.argsort(step_logits)[-2:]
        assert out[0, i] in top2, (i, out[0, i], top2)


def test_greedy_rejects_truncation_flags():
    params = init_transformer(jax.random.key(0), CFG)
    prompt = jnp.asarray([[1]], jnp.int32)
    with pytest.raises(ValueError, match="greedy"):
        generate(params, CFG, prompt, 2, temperature=0.0, top_k=5)


def test_generate_validates_top_k_top_p():
    cfg = CFG
    params = init_transformer(jax.random.key(0), cfg)
    prompt = jnp.asarray([[1]], jnp.int32)
    with pytest.raises(ValueError, match="top_k"):
        generate(params, cfg, prompt, 2, temperature=1.0, top_k=0,
                 key=jax.random.key(0))
    with pytest.raises(ValueError, match="top_p"):
        generate(params, cfg, prompt, 2, temperature=1.0, top_p=1.5,
                 key=jax.random.key(0))


def test_generate_eos_freezes_rows_and_pads():
    # Stop-token semantics under the static shape: pick a token the
    # greedy decode ACTUALLY emits mid-stream for row 0, rerun with it
    # as eos_id — the prefix through the stop token is unchanged, the
    # tail is all pad (eos_id), and rows that never emit it are
    # untouched (per-row done-mask, not a batch-wide abort).
    params = init_transformer(jax.random.key(1), CFG)
    prompt = _prompt(2, 8, seed=2)
    base = np.asarray(generate(params, CFG, prompt, 10))
    eos = int(base[0, 3])
    out = np.asarray(generate(params, CFG, prompt, 10, eos_id=eos))
    np.testing.assert_array_equal(out[0, :4], base[0, :4])
    assert (out[0, 4:] == eos).all()
    for r in range(1, 2):
        first = np.flatnonzero(base[r] == eos)
        if first.size == 0:
            np.testing.assert_array_equal(out[r], base[r])


def test_generate_eos_validated():
    params = init_transformer(jax.random.key(1), CFG)
    with pytest.raises(ValueError, match="eos_id"):
        generate(params, CFG, _prompt(1, 4), 4, eos_id=CFG.vocab_size)
    with pytest.raises(ValueError, match="eos_id"):
        generate(params, CFG, _prompt(1, 4), 4, eos_id=-1)


# ---------------------------------------------------------------------------
# Slot-wise decoding (the continuous-batching kernels)
# ---------------------------------------------------------------------------


def test_decode_step_slots_matches_scalar_decode_step():
    # With a uniform position vector and every slot active, the
    # slot-wise step IS the batched scalar step: identical logits and
    # identical cache writes (the masked-select write lands the same
    # values dynamic_update_slice does).
    from tpu_dist_nn.models.generate import decode_step_slots

    params = init_transformer(jax.random.key(0), CFG)
    prompts = _prompt(4, 8, seed=3)
    _, cache = prefill(params, prompts, CFG, max_len=13)
    tok = prompts[:, 0]
    ref_logits, ref_cache = decode_step(
        params, cache, jnp.int32(8), tok, CFG
    )
    got_logits, got_cache = decode_step_slots(
        params, cache, jnp.full((4,), 8, jnp.int32), tok, CFG
    )
    np.testing.assert_array_equal(
        np.asarray(ref_logits), np.asarray(got_logits)
    )
    np.testing.assert_array_equal(
        np.asarray(ref_cache["k"]), np.asarray(got_cache["k"])
    )
    np.testing.assert_array_equal(
        np.asarray(ref_cache["v"]), np.asarray(got_cache["v"])
    )


def test_decode_step_slots_staggered_positions_match_oracle():
    # The point of the per-slot pos vector: slots at DIFFERENT depths
    # advance in one launch. Slot 0 is 3 tokens ahead of slot 1 (walked
    # there with slot 1 masked inactive); a joint step must match the
    # teacher-forced full forward of each slot's own sequence.
    from tpu_dist_nn.models.generate import (
        decode_step_slots,
        init_slot_cache,
        prefill_into_cache,
    )
    from tpu_dist_nn.models.transformer import forward

    params = init_transformer(jax.random.key(5), CFG)
    T, S = 6, 2
    prompts = _prompt(S, T, seed=6)
    cache = init_slot_cache(CFG, S, 16)

    # Admit slot 0 and walk it 3 greedy steps alone (slot 1 inactive).
    logits0, cache = prefill_into_cache(params, CFG, cache, 0, prompts[:1])
    seq0 = list(np.asarray(prompts[0]))
    tok = jnp.array([int(jnp.argmax(logits0[0])), 0], jnp.int32)
    seq0.append(int(tok[0]))
    pos = jnp.array([T, 0], jnp.int32)
    active = jnp.array([True, False])
    for _ in range(3):
        logits, cache = decode_step_slots(params, cache, pos, tok, CFG,
                                          active=active)
        nxt = int(jnp.argmax(logits[0]))
        seq0.append(nxt)
        tok = jnp.array([nxt, 0], jnp.int32)
        pos = pos + jnp.array([1, 0], jnp.int32)

    # Admit slot 1 mid-flight, then step BOTH in one launch.
    logits1, cache = prefill_into_cache(params, CFG, cache, 1, prompts[1:])
    seq1 = list(np.asarray(prompts[1])) + [int(jnp.argmax(logits1[0]))]
    tok = jnp.array([seq0[-1], seq1[-1]], jnp.int32)
    pos = jnp.array([T + 3, T], jnp.int32)
    logits, cache = decode_step_slots(
        params, cache, pos, tok, CFG, active=jnp.array([True, True])
    )
    for s, seq in ((0, seq0), (1, seq1)):
        ref = forward(params, jnp.asarray([seq], jnp.int32), CFG)[0, -1]
        np.testing.assert_allclose(
            np.asarray(logits[s]), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_prefill_into_cache_lands_slot_and_clears_stale():
    # Admission into an arbitrary slot index: the chosen slot's FULL
    # extent is overwritten (a reused slot cannot leak its previous
    # occupant's K/V — the stale tail is zeroed by the prefill pad) and
    # every other slot's contents are untouched.
    from tpu_dist_nn.models.generate import (
        init_slot_cache,
        prefill_into_cache,
    )

    params = init_transformer(jax.random.key(0), CFG)
    prompts = _prompt(3, 8, seed=7)
    cache = init_slot_cache(CFG, 3, 12)
    cache = {k: v + 7.5 for k, v in cache.items()}  # stale garbage
    before_k = np.asarray(cache["k"])
    logits, cache = prefill_into_cache(params, CFG, cache, 1, prompts[1:2])
    # Parity with the batch prefill's row 1 — including the zero pad.
    _, ref = prefill(params, prompts, CFG, max_len=12)
    np.testing.assert_array_equal(
        np.asarray(cache["k"][:, 1]), np.asarray(ref["k"][:, 1])
    )
    assert np.all(np.asarray(cache["k"][:, 1, 8:]) == 0)
    # Slots 0 and 2 keep their garbage (untouched by the slot write).
    np.testing.assert_array_equal(np.asarray(cache["k"][:, 0]), before_k[:, 0])
    np.testing.assert_array_equal(np.asarray(cache["k"][:, 2]), before_k[:, 2])
    # And the returned logits sample the same first token the full
    # generate() would.
    want = np.asarray(generate(params, CFG, prompts[1:2], 1))[0, 0]
    assert int(jnp.argmax(logits[0])) == want


def test_slot_cache_bounds_validated():
    from tpu_dist_nn.models.generate import init_slot_cache

    with pytest.raises(ValueError, match="slots"):
        init_slot_cache(CFG, 0, 8)
    with pytest.raises(ValueError, match="max_len"):
        init_slot_cache(CFG, 2, CFG.max_seq_len + 1)


def test_prefill_chunk_into_cache_bitwise_matches_monolithic():
    # The chunk kernel IS the monolithic prefill when the chunk covers
    # the whole prompt — and splitting the prompt across chunk calls
    # must land the exact same logits and cache bytes (the continuous
    # scheduler's cache-on/cache-off bit-parity anchor rides on this).
    from tpu_dist_nn.models.generate import (
        init_slot_cache,
        prefill_chunk_into_cache,
        prefill_into_cache,
    )

    params = init_transformer(jax.random.key(0), CFG)
    T = 8
    prompts = _prompt(1, T, seed=8)
    cache0 = init_slot_cache(CFG, 3, 12)
    ref_logits, ref_cache = prefill_into_cache(params, CFG, cache0, 1, prompts)
    # One whole-prompt chunk.
    lg, c = prefill_chunk_into_cache(params, CFG, cache0, 1, prompts, 0)
    np.testing.assert_array_equal(np.asarray(lg), np.asarray(ref_logits))
    np.testing.assert_array_equal(
        np.asarray(c["k"][:, 1, :T]), np.asarray(ref_cache["k"][:, 1, :T])
    )
    # Split 3 + 5: the second chunk attends to the first's K/V.
    lg2, c2 = prefill_chunk_into_cache(
        params, CFG, cache0, 1, prompts[:, :3], 0
    )
    lg2, c2 = prefill_chunk_into_cache(params, CFG, c2, 1, prompts[:, 3:], 3)
    np.testing.assert_array_equal(np.asarray(lg2), np.asarray(ref_logits))
    np.testing.assert_array_equal(
        np.asarray(c2["k"][:, 1, :T]), np.asarray(ref_cache["k"][:, 1, :T])
    )
    np.testing.assert_array_equal(
        np.asarray(c2["v"][:, 1, :T]), np.asarray(ref_cache["v"][:, 1, :T])
    )


def test_copy_cache_slot_full_extent_and_isolation():
    # The prefix-cache transfer primitive: dst becomes a bit-exact copy
    # of src's whole extent; every other slot is untouched; and both
    # indices are traced (one compile serves any src/dst pair).
    from tpu_dist_nn.models.generate import (
        copy_cache_slot,
        init_slot_cache,
        prefill_chunk_into_cache,
    )

    params = init_transformer(jax.random.key(1), CFG)
    prompts = _prompt(1, 8, seed=9)
    cache = init_slot_cache(CFG, 3, 12)
    cache = {k: v + 2.5 for k, v in cache.items()}  # distinguishable
    _, cache = prefill_chunk_into_cache(params, CFG, cache, 2, prompts, 0)
    before = {k: np.asarray(v).copy() for k, v in cache.items()}
    out = copy_cache_slot(cache, 2, 0)
    for part in ("k", "v"):
        np.testing.assert_array_equal(
            np.asarray(out[part][:, 0]), before[part][:, 2]
        )
        np.testing.assert_array_equal(  # src and bystander untouched
            np.asarray(out[part][:, 1]), before[part][:, 1]
        )
        np.testing.assert_array_equal(
            np.asarray(out[part][:, 2]), before[part][:, 2]
        )


def test_prefill_chunk_after_copied_prefix_matches_monolithic():
    # The COW admission path end-to-end at the kernel level: prefix
    # prefilled into a POOL slot, copied into a request slot, suffix
    # chunked on top — last-position logits and the request slot's
    # prompt extent must be bit-identical to a monolithic prefill.
    from tpu_dist_nn.models.generate import (
        copy_cache_slot,
        init_slot_cache,
        prefill_chunk_into_cache,
        prefill_into_cache,
    )

    params = init_transformer(jax.random.key(2), CFG)
    T, pool_slot, req_slot = 8, 2, 0
    prompts = _prompt(1, T, seed=10)
    cache0 = init_slot_cache(CFG, 3, 12)
    ref_logits, ref_cache = prefill_into_cache(
        params, CFG, cache0, req_slot, prompts
    )
    _, c = prefill_chunk_into_cache(
        params, CFG, cache0, pool_slot, prompts[:, :4], 0
    )
    c = copy_cache_slot(c, pool_slot, req_slot)
    lg, c = prefill_chunk_into_cache(
        params, CFG, c, req_slot, prompts[:, 4:], 4
    )
    np.testing.assert_array_equal(np.asarray(lg), np.asarray(ref_logits))
    np.testing.assert_array_equal(
        np.asarray(c["k"][:, req_slot, :T]),
        np.asarray(ref_cache["k"][:, req_slot, :T]),
    )


# ---------------------------------------------------------------------------
# Tensor-parallel decode
# ---------------------------------------------------------------------------


def _tp_setup(n_heads=4, n_layers=2):
    from tpu_dist_nn.models.transformer import TransformerConfig, init_transformer
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.tensor_parallel import tp_shard_blocks

    cfg = TransformerConfig(
        vocab_size=31, d_model=16, n_heads=n_heads, n_layers=n_layers,
        d_ff=32, max_seq_len=24,
    )
    mesh = build_mesh(MeshSpec(model=2, data=2))
    params = init_transformer(jax.random.key(7), cfg)
    params_tp = dict(params, blocks=tp_shard_blocks(params["blocks"], cfg, 2))
    return cfg, mesh, params, params_tp


def test_tp_generate_greedy_matches_single_chip():
    from tpu_dist_nn.models.generate import generate
    from tpu_dist_nn.parallel.tp_generate import tp_generate

    cfg, mesh, params, params_tp = _tp_setup()
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 6)), jnp.int32)
    ref = generate(params, cfg, prompt, 8)
    out = tp_generate(mesh, params_tp, cfg, prompt, 8)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # Single-token edge case.
    np.testing.assert_array_equal(
        np.asarray(tp_generate(mesh, params_tp, cfg, prompt, 1)),
        np.asarray(generate(params, cfg, prompt, 1)),
    )


def test_tp_generate_sampled_is_valid_and_deterministic():
    from tpu_dist_nn.parallel.tp_generate import tp_generate

    cfg, mesh, _, params_tp = _tp_setup()
    rng = np.random.default_rng(1)
    prompt = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    key = jax.random.key(3)
    a = tp_generate(mesh, params_tp, cfg, prompt, 6,
                    temperature=0.8, top_k=10, key=key)
    b = tp_generate(mesh, params_tp, cfg, prompt, 6,
                    temperature=0.8, top_k=10, key=key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.asarray(a).shape == (2, 6)
    assert (np.asarray(a) >= 0).all() and (np.asarray(a) < cfg.vocab_size).all()


def test_tp_generate_rejects_indivisible_heads():
    from tpu_dist_nn.parallel.tp_generate import tp_generate

    cfg, mesh, _, params_tp = _tp_setup()
    import dataclasses

    bad = dataclasses.replace(cfg, n_heads=3, d_model=18, d_ff=36)
    with pytest.raises(ValueError, match="divisible"):
        tp_generate(mesh, params_tp, bad, jnp.zeros((2, 3), jnp.int32), 2)


def test_tp_generate_data_shards_sample_independently():
    """Same prompt in every row, data axis 2: rows in different shards
    must NOT draw identical noise (the key folds in the shard index)."""
    from tpu_dist_nn.parallel.tp_generate import tp_generate

    cfg, mesh, _, params_tp = _tp_setup()
    prompt = jnp.tile(jnp.asarray([[1, 2, 3, 4]], jnp.int32), (4, 1))
    out = np.asarray(
        tp_generate(mesh, params_tp, cfg, prompt, 8,
                    temperature=1.0, key=jax.random.key(5))
    )
    # Rows 0/1 live on shard 0, rows 2/3 on shard 1. Identical outputs
    # across shards would mean correlated sampling.
    assert not np.array_equal(out[0], out[2]) or not np.array_equal(out[1], out[3])


def test_tp_generate_rejects_bad_top_p():
    from tpu_dist_nn.parallel.tp_generate import tp_generate

    cfg, mesh, _, params_tp = _tp_setup()
    prompt = jnp.zeros((2, 3), jnp.int32)
    with pytest.raises(ValueError, match="top_p"):
        tp_generate(mesh, params_tp, cfg, prompt, 2, temperature=1.0,
                    top_p=1.5, key=jax.random.key(0))


def test_pipeline_generate_matches_single_chip():
    # Pipelined decode: generation IN the training placement (blocks
    # sharded over `stage`, per-stage KV caches, activations on the
    # stage ring, token psum-broadcast back to the embedding) must be
    # token-for-token the single-chip greedy decode.
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pp_generate import make_pipeline_generate
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(51), cfg)
    rng = np.random.default_rng(52)
    prompt = jnp.asarray(rng.integers(0, 64, (4, 8)), jnp.int32)

    ref = generate(params, cfg, prompt, max_new_tokens=10, temperature=0.0)

    for stage, data in [(2, 2), (4, 1)]:
        mesh = build_mesh(MeshSpec(stage=stage, data=data))
        fn = make_pipeline_generate(mesh, cfg, stage, max_new_tokens=10)
        params_pp = dict(params, blocks=shard_blocks(params["blocks"], stage))
        out = jax.jit(fn)(params_pp, prompt)
        np.testing.assert_array_equal(np.asarray(out[:, 8:]), np.asarray(ref))
        np.testing.assert_array_equal(np.asarray(out[:, :8]), np.asarray(prompt))

    # N=1 short-circuit parity.
    ref1 = generate(params, cfg, prompt, max_new_tokens=1, temperature=0.0)
    mesh = build_mesh(MeshSpec(stage=2, data=1))
    fn1 = make_pipeline_generate(mesh, cfg, 2, max_new_tokens=1)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    out1 = jax.jit(fn1)(params_pp, prompt)
    np.testing.assert_array_equal(np.asarray(out1[:, 8:]), np.asarray(ref1))


def test_cli_lm_sample_pipeline_stages(capsys):
    # tdn lm --sample-pipeline-stages: train, then decode IN the
    # pipeline placement; greedy-only and flag-compatibility rejections.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "24", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--sample-bytes", "6", "--prompt", "ab",
        "--sample-pipeline-stages", "2", "--temperature", "0",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "sample" in out
    # temperature > 0 sampling works through the pipelined decoder too
    # (the single-chip key schedule is reproduced exactly).
    rc = main([
        "--platform", "cpu", "lm", "--steps", "1", "--batch-size", "4",
        "--seq-len", "24", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--sample-bytes", "4", "--prompt", "ab",
        "--sample-pipeline-stages", "2", "--temperature", "0.8",
    ])
    assert rc == 0
    assert "sample" in capsys.readouterr().out
    # without --sample-bytes the flag rejects eagerly.
    assert main([
        "--platform", "cpu", "lm", "--steps", "1",
        "--sample-pipeline-stages", "2",
    ]) != 0


def test_pipeline_generate_overlapped_matches_single_chip():
    # Continuous-batching-style pipelined decode: G request groups
    # round-robin through the stage ring (steady state: one token
    # leaves the pipe per tick, no redundant compute). Every group's
    # stream must equal decoding its rows alone on one chip.
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pp_generate import (
        make_pipeline_generate_overlapped,
    )
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(61), cfg)
    rng = np.random.default_rng(62)
    G, Bg, T, N = 4, 2, 8, 9
    prompts = jnp.asarray(rng.integers(0, 64, (G, Bg, T)), jnp.int32)

    refs = [
        np.asarray(generate(params, cfg, prompts[g], N, temperature=0.0))
        for g in range(G)
    ]

    for stage, data in [(2, 2), (4, 1)]:
        mesh = build_mesh(MeshSpec(stage=stage, data=data))
        fn = make_pipeline_generate_overlapped(
            mesh, cfg, stage, max_new_tokens=N, num_groups=G
        )
        params_pp = dict(params, blocks=shard_blocks(params["blocks"], stage))
        out = np.asarray(jax.jit(fn)(params_pp, prompts))
        assert out.shape == (G, Bg, T + N)
        for g in range(G):
            np.testing.assert_array_equal(out[g, :, :T], np.asarray(prompts[g]))
            np.testing.assert_array_equal(out[g, :, T:], refs[g], err_msg=str(g))

    # G < S rejected; N=1 short-circuit parity.
    mesh = build_mesh(MeshSpec(stage=4, data=1))
    with pytest.raises(ValueError, match="num_groups"):
        make_pipeline_generate_overlapped(mesh, cfg, 4, 5, num_groups=2)
    fn1 = make_pipeline_generate_overlapped(mesh, cfg, 4, 1, num_groups=4)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 4))
    out1 = np.asarray(jax.jit(fn1)(params_pp, prompts))
    for g in range(G):
        ref1 = np.asarray(generate(params, cfg, prompts[g], 1, temperature=0.0))
        np.testing.assert_array_equal(out1[g, :, T:], ref1, err_msg=str(g))


def test_pipeline_generate_sampled_matches_single_chip():
    # Sampling at temperature > 0: the pipelined decoders reproduce the
    # single-chip KEY SCHEDULE (first from `key`, step n from
    # split(fold_in(key, 1), N-1)[n]), so streams match key-for-key.
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pp_generate import (
        make_pipeline_generate,
        make_pipeline_generate_overlapped,
    )
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(71), cfg)
    rng = np.random.default_rng(72)
    G, Bg, T, N = 2, 2, 8, 7
    prompts = jnp.asarray(rng.integers(0, 64, (G, Bg, T)), jnp.int32)
    key = jax.random.key(9)

    refs = [
        np.asarray(generate(params, cfg, prompts[g], N, temperature=1.0,
                            top_k=8, key=key))
        for g in range(G)
    ]

    mesh = build_mesh(MeshSpec(stage=2, data=1))
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))

    fn = make_pipeline_generate(mesh, cfg, 2, N, temperature=1.0, top_k=8)
    for g in range(G):
        out = np.asarray(fn(params_pp, prompts[g], key=key))
        np.testing.assert_array_equal(out[:, T:], refs[g], err_msg=str(g))

    fno = make_pipeline_generate_overlapped(
        mesh, cfg, 2, N, num_groups=G, temperature=1.0, top_k=8
    )
    out = np.asarray(fno(params_pp, prompts, key=key))
    for g in range(G):
        np.testing.assert_array_equal(out[g, :, T:], refs[g], err_msg=str(g))

    # temperature > 0 without a key rejects.
    with pytest.raises(ValueError, match="PRNG key"):
        fn(params_pp, prompts[0])


def test_pipeline_generate_data_shards_sample_independently():
    # ADVICE r4 (medium): sampled pipelined decode on a data > 1 mesh
    # must fold the data-shard index into the key (tp_generate.py's
    # rule) — identical keys would draw identical gumbel noise on
    # every shard, duplicating continuations at matching local
    # indices. Same-prompt rows in different shards must diverge.
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pp_generate import (
        make_pipeline_generate,
        make_pipeline_generate_overlapped,
    )
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(81), cfg)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    mesh = build_mesh(MeshSpec(stage=2, data=2))
    N = 8

    # Rows 0/1 on data shard 0, rows 2/3 on shard 1 — identical prompts.
    prompt = jnp.tile(jnp.asarray([[3, 1, 4, 1, 5, 9]], jnp.int32), (4, 1))
    fn = make_pipeline_generate(mesh, cfg, 2, N, temperature=1.0)
    out = np.asarray(fn(params_pp, prompt, key=jax.random.key(5)))
    assert (not np.array_equal(out[0], out[2])
            or not np.array_equal(out[1], out[3]))

    # Same property through the overlapped decoder (Bg shards on data).
    prompts = jnp.tile(
        jnp.asarray([[2, 7, 1, 8, 2, 8]], jnp.int32), (2, 4, 1)
    )  # (G=2, Bg=4, T=6)
    fno = make_pipeline_generate_overlapped(
        mesh, cfg, 2, N, num_groups=2, temperature=1.0
    )
    outo = np.asarray(fno(params_pp, prompts, key=jax.random.key(5)))
    assert (not np.array_equal(outo[0, 0], outo[0, 2])
            or not np.array_equal(outo[0, 1], outo[0, 3]))


def test_pipeline_generate_shares_validator_contract():
    # ADVICE r4 (low): the pipelined wrappers route through
    # validate_generate_args — the same contract as the single-chip /
    # tp paths — instead of ad-hoc checks that drifted (they accepted
    # T + N == max_seq_len + 1 and silently ignored top_k at
    # temperature 0).
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pp_generate import (
        make_pipeline_generate,
        make_pipeline_generate_overlapped,
    )
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(91), cfg)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    mesh = build_mesh(MeshSpec(stage=2, data=1))
    prompt = jnp.zeros((2, 8), jnp.int32)

    # T + N == max_seq_len + 2 (one past the boundary: the decoders
    # embed total-1 positions, so T + N == max_seq_len + 1 is valid):
    # single-chip rejects; pipelined must too.
    fn = make_pipeline_generate(mesh, cfg, 2, max_new_tokens=18)
    with pytest.raises(ValueError, match="max_seq_len"):
        fn(params_pp, prompt)

    # top_k at temperature == 0 would be silently ignored — reject.
    fnk = make_pipeline_generate(mesh, cfg, 2, 4, temperature=0.0, top_k=5)
    with pytest.raises(ValueError, match="top_k"):
        fnk(params_pp, prompt)

    # Same contract through the overlapped wrapper.
    prompts = jnp.zeros((2, 2, 8), jnp.int32)
    fno = make_pipeline_generate_overlapped(
        mesh, cfg, 2, 18, num_groups=2
    )
    with pytest.raises(ValueError, match="max_seq_len"):
        fno(params_pp, prompts)
    fnob = make_pipeline_generate_overlapped(
        mesh, cfg, 2, 4, num_groups=2, temperature=1.0, top_p=1.5
    )
    with pytest.raises(ValueError, match="top_p"):
        fnob(params_pp, prompts, key=jax.random.key(0))
