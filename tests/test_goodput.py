"""Goodput & MFU accounting plane (ISSUE 14).

The contract under test is CONSERVATION: every recorded launch's FLOPs
split exactly into ``useful + pad == total`` (integer arithmetic, no
float slop) across the batcher (bucket pad rows), the continuous
scheduler (idle/mid-prefill slot lanes, attention tails), and the
static run-to-completion decode (EOS-frozen steps) — plus the
peak-calibration unification with bench.py, the ``/goodput`` endpoint,
the timeseries/`tdn top`/bench_gate satellites, and the accounting
overhead staying within noise.
"""

import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpu_dist_nn.obs.exposition import MetricsServer, parse_prometheus_text
from tpu_dist_nn.obs.goodput import (
    GOODPUT,
    GoodputTracker,
    LMFlopModel,
    PEAK_FLOPS,
    device_peak_flops,
    fcnn_flops_per_row,
    host_calibration_gflops,
    resolve_peak,
)
from tpu_dist_nn.obs.registry import Registry


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5.0
    ) as r:
        return r.read()


def _delta(after: dict, before: dict, *keys):
    node_a, node_b = after, before
    for k in keys:
        node_a = node_a[k]
        # A path/stage absent from the earlier snapshot is a 0
        # baseline (its first record created the key).
        node_b = node_b.get(k, {}) if isinstance(node_b, dict) else node_b
    return node_a - (node_b if isinstance(node_b, (int, float)) else 0)


# ------------------------------------------------------- FLOP models


def test_fcnn_flops_per_row_counts_matmuls():
    assert fcnn_flops_per_row([784, 128, 64, 10]) == 2 * (
        784 * 128 + 128 * 64 + 64 * 10
    )
    assert fcnn_flops_per_row([16]) == 0


def test_lm_model_identities_are_exact_ints():
    m = LMFlopModel(3, 32, 64, 48, 19)
    # A fully-live step (pos = extent - 1) has no attention tail.
    assert m.step_useful_flops(m.M - 1) == m.step_flops()
    assert m.step_useful_flops(0) < m.step_flops()
    # steps_useful_sum is the closed form of the per-step sum.
    assert m.steps_useful_sum(7, 5) == sum(
        m.step_useful_flops(p) for p in range(7, 12)
    )
    assert m.steps_useful_sum(7, 0) == 0
    # A final whole-extent chunk is fully live except nothing: its
    # static cost still spans the full key ladder.
    assert m.chunk_useful_flops(0, 4, final=True) <= m.chunk_flops(4)
    # Span cost = sum of its chunk launches.
    assert m.prefill_chunks_flops(0, 10, 4) == (
        2 * m.chunk_flops(4) + m.chunk_flops(2)
    )
    assert m.prefill_chunks_flops(0, 10, None) == m.chunk_flops(10)


# ------------------------------------------------ peak calibration


def test_peak_calibration_is_shared_with_bench():
    """Satellite 1: bench.py's calibration/peak table ARE goodput's —
    identity, not copies, so the two can never diverge."""
    import bench

    assert bench._PEAK_FLOPS is PEAK_FLOPS
    assert bench._host_calibration is host_calibration_gflops
    assert bench._peak_flops is device_peak_flops


def test_ensure_peak_scales_by_device_count_and_keeps_max():
    """The ledger records whole multi-device launches, so the peak
    must be per-device x placement size — and the largest configured
    footprint wins (MFU stays conservative across engines)."""
    t = GoodputTracker(registry=Registry())
    assert t.ensure_peak(device_kind="v5p", device_count=4) == 4 * 459e12
    assert t.snapshot()["peak_source"] == "table:v5p x4"
    # A smaller later placement must not shrink the denominator...
    assert t.ensure_peak(device_kind="v5p", device_count=1) == 4 * 459e12
    # ...but a larger one raises it.
    assert t.ensure_peak(device_kind="v5p", device_count=8) == 8 * 459e12
    t2 = GoodputTracker(registry=Registry())
    assert t2.ensure_peak(device_kind="v4") == 275e12
    assert t2.snapshot()["peak_source"] == "table:v4"


def test_peak_resolution_table_then_measured_host():
    peak, source = resolve_peak("TPU v5e lite")
    assert peak == 197e12 and source == "table:TPU v5e lite"
    peak, source = resolve_peak(None)
    assert peak > 0 and source == "measured-host-blas"
    # Cached: a second resolve returns the same measurement.
    assert resolve_peak("unknown-kind")[0] == peak


# ------------------------------------------------------ conservation


def test_decode_step_conservation_exact():
    m = LMFlopModel(2, 32, 64, 48, 11)
    for active_pos, idle, mid in (
        ([3, 7], 1, 1), ([], 4, 0), ([0, 1, 2, 10], 0, 0), ([5], 0, 3),
    ):
        t = GoodputTracker(registry=Registry())
        t.record_decode_step(m, active_pos, idle, mid)
        snap = t.snapshot()
        slots = len(active_pos) + idle + mid
        assert snap["flops"]["useful"] + snap["flops"]["pad"] \
            == slots * m.step_flops()
        assert snap["flops"]["total"] == slots * m.step_flops()
        if idle:
            assert snap["pad_reasons"]["idle_slot"] == idle * m.step_flops()
        if mid:
            assert snap["pad_reasons"]["mid_prefill_slot"] \
                == mid * m.step_flops()


def test_prefill_chunk_conservation_and_tail():
    m = LMFlopModel(2, 32, 64, 48, 11)
    t = GoodputTracker(registry=Registry())
    t.record_prefill_chunk(m, 0, 4, final=False)
    t.record_prefill_chunk(m, 4, 4, final=True)
    snap = t.snapshot()
    total = 2 * m.chunk_flops(4)
    assert snap["flops"]["total"] == total
    assert snap["flops"]["useful"] + snap["flops"]["pad"] == total
    assert snap["pad_reasons"]["chunk_tail"] == snap["flops"]["pad"]
    assert snap["stages"]["prefill"]["launches"] == 2


def test_static_generate_accounting_eos_frozen_exact():
    """Run-to-completion accounting: bucket pad rows cost their full
    ride, post-EOS positions are eos_frozen pad, and the whole launch
    conserves to the FLOP."""
    m = LMFlopModel(2, 32, 64, 48, 11)
    T, width = 8, 12
    out = np.zeros((3, width), np.int64)
    out[0, T:] = [5, 9, 9, 9]  # eos=9 as 2nd token -> 2 useful tokens
    out[1, T:] = [1, 2, 3, 4]  # no eos -> all 4 useful
    t = GoodputTracker(registry=Registry())
    t.record_static_generate(m, out, 2, 3, T, 9)
    snap = t.snapshot()
    steps = width - T - 1
    row_total = m.chunk_flops(T) + steps * m.step_flops()
    assert snap["flops"]["total"] == 3 * row_total
    assert snap["flops"]["useful"] + snap["flops"]["pad"] \
        == snap["flops"]["total"]
    # The bucket pad row costs its whole prefill + decode.
    assert snap["pad_reasons"]["pad_rows"] == row_total
    # Row 0 froze after its EOS: steps produce tokens 2..4, tokens 3-4
    # are post-EOS -> 2 frozen steps.
    assert snap["pad_reasons"]["eos_frozen"] == 2 * m.step_flops()
    # Without an eos_id nothing can freeze.
    t2 = GoodputTracker(registry=Registry())
    t2.record_static_generate(m, out, 2, 3, T, None)
    assert "eos_frozen" not in t2.snapshot()["pad_reasons"]
    assert t2.snapshot()["flops"]["total"] == 3 * row_total


def test_disabled_tracker_records_nothing():
    m = LMFlopModel(1, 8, 16, 8, 4)
    t = GoodputTracker(registry=Registry())
    t.enabled = False
    t.record_rows(100, 4, 3, path="batcher")
    t.record_decode_step(m, [1], 1, 0)
    t.record_prefill_chunk(m, 0, 2, final=True)
    t.record_prefix_saved(1000)
    snap = t.snapshot()
    assert snap["flops"]["total"] == 0 and snap["launches"] == 0
    assert snap["flops"]["prefix_saved"] == 0


def test_mfu_tick_and_pad_ratio_gauges():
    reg = Registry()
    t = GoodputTracker(registry=reg)
    t.set_peak(1e9, "test")
    t.tick(now=100.0)
    t.record_rows(500_000, 4, 3, path="batcher")
    t.tick(now=101.0)
    # 3 useful rows x 500k FLOPs over 1s against a 1 GFLOPS peak.
    mfu = reg.get("tdn_mfu_ratio").labels().value
    assert mfu == pytest.approx(1_500_000 / 1e9)
    pad = reg.get("tdn_pad_ratio").labels(path="batcher").value
    assert pad == pytest.approx(0.25)
    # Idle window: MFU decays to 0, cumulative pad ratio holds.
    t.tick(now=102.0)
    assert reg.get("tdn_mfu_ratio").labels().value == 0.0
    assert reg.get("tdn_pad_ratio").labels(path="batcher").value \
        == pytest.approx(0.25)


# --------------------------------------------------- serving paths


def test_engine_direct_infer_counts_all_useful():
    import jax

    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params

    params = init_fcnn(jax.random.key(0), [16, 8, 4])
    engine = Engine.up(spec_from_params(params, ["relu", "softmax"]))
    fpr = engine._flops_per_row
    assert fpr == 2 * (16 * 8 + 8 * 4)
    g0 = GOODPUT.snapshot()
    engine.infer(np.zeros((3, 16)))
    g1 = GOODPUT.snapshot()
    assert _delta(g1, g0, "flops", "useful") == 3 * fpr
    assert _delta(g1, g0, "flops", "pad") == 0
    assert g1["peak_flops"] and g1["peak_source"]


def test_loopback_serving_pad_accounting_exact():
    """The quick-tier smoke (acceptance): odd row counts force bucket
    pad on the loopback wire, useful + pad == total EXACTLY, and the
    /goodput endpoint's shares sum to 1."""
    import jax

    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params
    from tpu_dist_nn.serving.server import GrpcClient, serve_engine

    params = init_fcnn(jax.random.key(0), [16, 8, 4])
    engine = Engine.up(spec_from_params(params, ["relu", "softmax"]))
    fpr = engine._flops_per_row
    srv, port = serve_engine(engine, 0, host="127.0.0.1", warm_rows=8)
    mserver = MetricsServer(0, host="127.0.0.1", goodput=GOODPUT)
    client = GrpcClient(f"127.0.0.1:{port}")
    try:
        g0 = GOODPUT.snapshot()
        client.process(np.zeros((3, 16)))  # 3 rows -> pow2 bucket of 4
        client.process(np.zeros((5, 16)))  # 5 rows -> bucket of 8
        g1 = GOODPUT.snapshot()
        du = _delta(g1, g0, "flops", "useful")
        dp = _delta(g1, g0, "flops", "pad")
        assert du == 8 * fpr, "3 + 5 useful rows"
        assert dp == 4 * fpr, "1 + 3 bucket pad rows"
        assert du + dp == _delta(g1, g0, "flops", "total")
        assert _delta(g1, g0, "paths", "batcher", "pad") == 4 * fpr
        doc = json.loads(_get(mserver.port, "/goodput"))
        assert doc["flops"]["useful"] + doc["flops"]["pad"] \
            == doc["flops"]["total"]
        assert doc["shares"]["useful"] + doc["shares"]["pad"] \
            == pytest.approx(1.0)
        assert sum(s["share"] for s in doc["stages"].values()) \
            == pytest.approx(1.0)
        # The registry counter mirrors the ledger.
        parsed = parse_prometheus_text(_get(mserver.port, "/metrics").decode())
        assert parsed['tdn_goodput_flops_total{kind="useful"}'] \
            == doc["flops"]["useful"]
        assert parsed['tdn_goodput_flops_total{kind="pad"}'] \
            == doc["flops"]["pad"]
    finally:
        client.close()
        mserver.close()
        srv.stop(0)


def test_goodput_endpoint_404_until_attached():
    mserver = MetricsServer(0, host="127.0.0.1")
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(mserver.port, "/goodput")
        assert exc.value.code == 404
        mserver.attach(goodput=GoodputTracker(registry=Registry()))
        doc = json.loads(_get(mserver.port, "/goodput"))
        assert doc["flops"]["total"] == 0
    finally:
        mserver.close()


def test_continuous_scheduler_conservation_and_prefix_savings():
    """Iteration-level accounting over the REAL kernels: every step
    launch books all S slot lanes (idle + mid-prefill lanes as pad),
    every chunk launch books its static cost, a shared-prefix hit
    records savings — and the whole run conserves exactly against the
    scheduler's own launch counters."""
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.serving.continuous import ContinuousScheduler

    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                            n_layers=1, d_ff=32, max_seq_len=16)
    params = init_transformer(jax.random.key(0), cfg)
    g0 = GOODPUT.snapshot()
    sched = ContinuousScheduler(params, cfg, slots=2, prompt_len=8,
                                max_new_tokens=4, prefix_cache_blocks=2,
                                prefill_chunk=4)
    try:
        prompt = np.zeros((1, 8), np.int32)
        sched.submit(prompt)
        sched.submit(prompt)  # same prompt -> prefix hit on admission
    finally:
        sched.close()
    g1 = GOODPUT.snapshot()
    m = sched._gp_model
    du = _delta(g1, g0, "flops", "useful")
    dp = _delta(g1, g0, "flops", "pad")
    # Conservation against the scheduler's own launch ledger: every
    # chunk here is size 4 (T=8, chunk=4; a hit resumes at tier 4).
    expected = (
        sched.prefill_chunks_total * m.chunk_flops(4)
        + sched.steps_total * sched.slots * m.step_flops()
    )
    assert du + dp == expected
    assert du > 0 and dp > 0
    saved = _delta(g1, g0, "flops", "prefix_saved")
    assert saved == m.prefill_chunks_flops(0, 4, 4), \
        "the admission hit skipped exactly the 4-token prefix chunk"
    reasons = {
        k: g1["pad_reasons"].get(k, 0) - g0["pad_reasons"].get(k, 0)
        for k in g1["pad_reasons"]
    }
    assert reasons.get("idle_slot", 0) > 0, \
        "a 2-slot ladder decoding <2 rows at times must book idle lanes"


def test_static_generate_loopback_records():
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.serving.server import GrpcClient, serve_lm_generate

    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                            n_layers=1, d_ff=32, max_seq_len=16)
    params = init_transformer(jax.random.key(0), cfg)
    srv, port = serve_lm_generate(params, cfg, 0, max_new_tokens=4,
                                  prompt_len=8, host="127.0.0.1",
                                  scheduler="static")
    client = GrpcClient(f"127.0.0.1:{port}")
    try:
        g0 = GOODPUT.snapshot()
        client.generate(np.zeros((1, 8)))
        g1 = GOODPUT.snapshot()
        m = LMFlopModel.from_config(cfg, 8 + 4 - 1)
        row_total = m.chunk_flops(8) + (4 - 1) * m.step_flops()
        assert _delta(g1, g0, "flops", "total") == row_total
        assert _delta(g1, g0, "flops", "useful") \
            + _delta(g1, g0, "flops", "pad") == row_total
    finally:
        client.close()
        srv.stop(0)


# ------------------------------------------------------- satellites


def test_timeseries_goodput_families_and_counter_reset():
    """Satellite: DEFAULT_FAMILIES carries the goodput families; the
    ring records a real tracker's series and delta() restarts from the
    new value across a simulated counter reset (process restart)."""
    from tpu_dist_nn.obs.timeseries import DEFAULT_FAMILIES, TimeSeriesRing

    for fam in ("tdn_goodput_flops_total", "tdn_mfu_ratio",
                "tdn_pad_ratio", "tdn_prefix_flops_saved_total"):
        assert fam in DEFAULT_FAMILIES
    reg = Registry()
    tracker = GoodputTracker(registry=reg)
    tracker.set_peak(1e9, "test")
    ring = TimeSeriesRing(resolution=1.0, families=DEFAULT_FAMILIES,
                          registry=reg)
    t0 = 1000.0
    tracker.record_rows(1000, 4, 3, path="batcher")
    tracker.tick(now=t0)
    ring.collect(now=t0)
    tracker.record_rows(1000, 4, 4, path="batcher")
    tracker.tick(now=t0 + 5)
    ring.collect(now=t0 + 5)
    key = 'tdn_goodput_flops_total{kind="useful"}'
    delta, covered = ring.delta(key, window=60, now=t0 + 5)
    assert delta == 4000.0 and covered == 5.0
    assert 'tdn_mfu_ratio' in ring.series("tdn_mfu_ratio")
    assert any(k.startswith("tdn_pad_ratio{") for k in ring.keys())
    # Simulated restart: the cumulative series drops to a fresh
    # process's small value — delta() restarts from the new value
    # instead of going negative.
    ring.record(key, 500.0, family="tdn_goodput_flops_total",
                now=t0 + 10)
    delta, _ = ring.delta(key, window=60, now=t0 + 10)
    assert delta == 500.0


def test_top_renders_mfu_pad_columns_fleet_and_single():
    """Satellite: the MFU/pad column renders in both modes (pure
    render_frame), with '-' for sources that predate the plane."""
    from tpu_dist_nn.obs.top import render_frame

    row = {
        "source": "replica 127.0.0.1:5101", "state": "active",
        "rps": 10.0, "p50_ms": 1.0, "p99_ms": 2.0, "pending": 0.0,
        "slots": 2.0, "occupancy": 0.5, "prefix_hit": None,
        "mfu": 0.1234, "pad_ratio": 0.25, "spark": [1, 2],
        "mfu_spark": [0.1, 0.2, 0.1],
    }
    old = {
        "source": "replica old", "state": "active", "rps": 1.0,
        "p50_ms": 1.0, "p99_ms": 2.0, "pending": 0.0, "slots": 0.0,
        "occupancy": 0.0, "prefix_hit": None, "spark": None,
    }
    for fleet in (True, False):
        state = {"target": "t", "fleet": fleet, "at": 0.0,
                 "rows": [row, old], "slo": None}
        frame = render_frame(state, color=False)
        assert "mfu%" in frame and "pad%" in frame
        assert "12.34" in frame, "mfu renders as percent"
        assert "25" in frame, "pad ratio renders as percent"


def test_cli_top_iterations_reads_goodput_from_live_endpoint(capsys):
    """Satellite: the --iterations CI path against a real endpoint
    whose registry carries the goodput families."""
    from tpu_dist_nn.cli import main
    from tpu_dist_nn.obs import start_http_server
    from tpu_dist_nn.obs.registry import REGISTRY

    REGISTRY.gauge(
        "tdn_mfu_ratio", "useful FLOP rate over peak",
    ).set(0.42)
    srv = start_http_server(0, host="127.0.0.1")
    try:
        rc = main(["top", "--target", f"127.0.0.1:{srv.port}",
                   "--iterations", "1", "--interval", "0.05",
                   "--no-color"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "mfu%" in out
        assert "42.00" in out, "the live gauge lands in the column"
    finally:
        srv.close()


def test_fleet_goodput_merge_recomputes_from_sums():
    from tpu_dist_nn.obs.collect import merge_goodput

    docs = {
        "replica a": {
            "mfu": 0.2, "pad_ratio": 0.5, "peak_flops": 100.0,
            "peak_source": "test", "launches": 2,
            "flops": {"useful": 50, "pad": 50, "prefix_saved": 5},
            "stages": {"infer": {"useful": 50, "pad": 50, "launches": 2}},
            "pad_reasons": {"pad_rows": 50},
        },
        "replica b": {
            "mfu": 0.1, "pad_ratio": 0.0, "peak_flops": 300.0,
            "peak_source": "test", "launches": 1,
            "flops": {"useful": 150, "pad": 0, "prefix_saved": 0},
            "stages": {"decode": {"useful": 150, "pad": 0, "launches": 1}},
            "pad_reasons": {},
        },
        "router": {"error": "no tracker"},  # non-goodput doc: skipped
    }
    merged = merge_goodput(docs)
    assert merged["flops"] == {"useful": 200, "pad": 50, "total": 250,
                               "prefix_saved": 5}
    assert merged["pad_ratio"] == pytest.approx(50 / 250)
    # Fleet MFU = sum(mfu_i * peak_i) / sum(peak_i).
    assert merged["mfu"] == pytest.approx((0.2 * 100 + 0.1 * 300) / 400)
    assert merged["stages"]["infer"]["share"] == pytest.approx(100 / 250)
    assert set(merged["sources"]) == {"replica a", "replica b"}


def test_bench_gate_serving_mfu_and_pad_ratio_skip_and_fail():
    """Satellite: rounds predating ISSUE 14 skip per-metric; a lower
    mfu or a higher pad_ratio past threshold fails."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_gate",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "bench_gate.py"),
    )
    bench_gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_gate)
    base = {"backend": "cpu", "value": 100.0}
    prev_no_section = dict(base, serving={"coalesced": {"rps": 50.0}})
    cur = dict(base, serving={
        "goodput": {"mfu": 0.02, "pad_ratio": 0.2},
    })
    verdict = bench_gate.compare(prev_no_section, cur)
    rows = {r["metric"]: r for r in verdict["metrics"]}
    assert "skipped" in rows["serving_mfu"]
    assert "skipped" in rows["serving_pad_ratio"]
    prev = dict(base, serving={"goodput": {"mfu": 0.02, "pad_ratio": 0.2}})
    cur_reg = dict(base,
                   serving={"goodput": {"mfu": 0.015, "pad_ratio": 0.3}})
    verdict = bench_gate.compare(prev, cur_reg)
    assert "serving_mfu" in verdict["regressions"], \
        "mfu is higher-is-better"
    assert "serving_pad_ratio" in verdict["regressions"], \
        "pad_ratio is lower-is-better"
    cur_ok = dict(base,
                  serving={"goodput": {"mfu": 0.021, "pad_ratio": 0.19}})
    verdict = bench_gate.compare(prev, cur_ok)
    assert verdict["regressions"] == []


def test_goodput_overhead_smoke_accounting_within_noise():
    """Acceptance: the armed-vs-disarmed accounting A/B — a few
    integer adds per launch must stay within noise of free (the bench
    targets >= 0.95; the CI bound is looser for shared-box jitter) and
    the armed arm must actually have recorded launches."""
    import jax

    import bench
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params

    params = init_fcnn(jax.random.key(0), [16, 8, 4])
    engine = Engine.up(spec_from_params(params, ["relu", "softmax"]))
    res = bench.goodput_overhead_bench(
        clients=4, rpcs_per_client=8, rows_per_rpc=3, repeats=2,
        engine=engine,
    )
    assert GOODPUT.enabled, "the A/B must restore the armed default"
    assert res["armed_launches_recorded"] > 0
    assert res["ratio_raw"] >= 0.8, res
    assert res["ratio"] <= 1.0
