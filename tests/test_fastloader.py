"""Native data-loader primitives: parity with numpy, fallback paths,
error handling, and the batch_iterator integration."""

import numpy as np
import pytest

from tpu_dist_nn.data.feed import batch_iterator
from tpu_dist_nn.native.fastloader import gather_normalize_u8, gather_rows
from tpu_dist_nn.native.loader import get_library

native_available = get_library() is not None


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.uint8, np.int32])
def test_gather_rows_matches_numpy(dtype):
    rng = np.random.default_rng(0)
    x = (rng.uniform(0, 255, (500, 37))).astype(dtype)
    idx = rng.permutation(500)[:128]
    np.testing.assert_array_equal(gather_rows(x, idx), x[idx])


def test_gather_rows_threads_and_big_batch():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((4096, 784)).astype(np.float32)
    idx = rng.permutation(4096)
    np.testing.assert_array_equal(gather_rows(x, idx, n_threads=4), x[idx])


def test_gather_rows_noncontiguous_falls_back():
    rng = np.random.default_rng(2)
    x = rng.standard_normal((100, 64)).astype(np.float32)[:, ::2]
    assert not x.flags.c_contiguous
    idx = np.arange(50)
    np.testing.assert_array_equal(gather_rows(x, idx), x[idx])


@pytest.mark.skipif(not native_available, reason="native lib unavailable")
def test_gather_rows_out_of_range_raises():
    x = np.zeros((10, 4), np.float32)
    with pytest.raises(IndexError):
        gather_rows(x, np.array([0, 10]))


def test_gather_rows_negative_indices_wrap_like_numpy():
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    idx = np.array([-1, -10, 3])
    np.testing.assert_array_equal(gather_rows(x, idx), x[idx])


def test_gather_rows_out_of_range_negative_raises_on_both_paths():
    # -11 on a 10-row array must raise (numpy semantics), and must NOT
    # double-wrap to -1 on the numpy fallback path.
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    with pytest.raises(IndexError):
        gather_rows(x, np.array([-11]))
    with pytest.raises(IndexError):
        gather_rows(x[:, ::2], np.array([-11]))  # non-contiguous fallback


def test_gather_rows_float_indices_rejected():
    x = np.zeros((10, 4), np.float32)
    with pytest.raises(IndexError, match="must be integers"):
        gather_rows(x, np.array([1.7]))


def test_gather_rows_zero_columns():
    x = np.empty((100, 0), np.float32)
    out = gather_rows(x, np.arange(32))
    assert out.shape == (32, 0)


def test_gather_normalize_rejects_wrong_dtype_without_lib_too():
    # The dtype check must run before the native/fallback branch so
    # behavior is environment-independent.
    x = np.zeros((10, 4), np.float32)
    with pytest.raises(TypeError):
        gather_normalize_u8(x, np.arange(4), 1.0)


def test_gather_normalize_u8_matches_numpy():
    rng = np.random.default_rng(3)
    x = rng.integers(0, 256, (300, 784)).astype(np.uint8)
    idx = rng.permutation(300)[:64]
    got = gather_normalize_u8(x, idx, 1.0 / 255.0)
    want = x[idx].astype(np.float32) / 255.0
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, rtol=1e-7)


def test_batch_iterator_shuffle_uses_gather_and_matches_reference():
    rng = np.random.default_rng(4)
    x = rng.standard_normal((130, 8)).astype(np.float32)
    y = rng.integers(0, 3, 130)
    batches = list(batch_iterator(x, y, 32, shuffle=True, seed=7))
    # Same permutation as the documented contract.
    order = np.random.default_rng(7).permutation(130)
    got_x = np.concatenate([b[0] for b in batches])
    got_y = np.concatenate([b[1] for b in batches])
    np.testing.assert_array_equal(got_x, x[order])
    np.testing.assert_array_equal(got_y, y[order])


def test_batch_iterator_unshuffled_is_view():
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    batches = list(batch_iterator(x, batch_size=4))
    assert np.shares_memory(batches[0], x)  # zero-copy view
    np.testing.assert_array_equal(np.concatenate(batches), x)
