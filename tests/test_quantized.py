"""Int8 quantized inference: dequant error bounds, jnp-vs-Pallas exact
agreement, closeness to the f32 forward, and end-to-end classifier
accuracy parity."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist_nn.data.datasets import synthetic_mnist
from tpu_dist_nn.kernels.quantized import (
    fcnn_quantized_forward,
    forward_quantized,
    quantize_fcnn,
)
from tpu_dist_nn.models.fcnn import forward, init_fcnn


def _params_and_x(sizes=(24, 32, 16, 4), batch=64, seed=0):
    params = init_fcnn(jax.random.key(seed), list(sizes))
    rng = np.random.default_rng(seed)
    x = rng.uniform(0, 1, (batch, sizes[0])).astype(np.float32)
    return params, jnp.asarray(x)


def test_weight_quantization_roundtrip_error_bounded():
    params, _ = _params_and_x()
    q = quantize_fcnn(params)
    for p, qp in zip(params, q):
        w = np.asarray(p["w"], np.float32)
        deq = np.asarray(qp["wq"], np.float32) * np.asarray(qp["scale"])
        # Symmetric int8: max error <= scale/2 per channel.
        bound = np.broadcast_to(
            np.asarray(qp["scale"])[None, :] * 0.5 + 1e-8, w.shape
        )
        np.testing.assert_array_less(np.abs(w - deq), bound)
        assert qp["wq"].dtype == jnp.int8


def test_quantized_forward_close_to_f32():
    params, x = _params_and_x()
    q = quantize_fcnn(params)
    ref = forward(params, x)
    got = forward_quantized(q, x)
    # Probabilities (softmax outputs) should agree to ~1e-2.
    assert float(jnp.max(jnp.abs(got - ref))) < 2e-2
    np.testing.assert_array_equal(
        np.argmax(np.asarray(got), -1), np.argmax(np.asarray(ref), -1)
    )


def test_pallas_chain_matches_jnp_reference_exactly():
    params, x = _params_and_x(batch=100)  # ragged vs block_b
    q = quantize_fcnn(params)
    ref = forward_quantized(q, x)
    got = fcnn_quantized_forward(q, x, block_b=32)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=1e-6, atol=1e-7
    )


def test_quantized_classifier_accuracy_parity():
    # Train a small f32 classifier, quantize, and check accuracy holds.
    from tpu_dist_nn.train.trainer import TrainConfig, train_fcnn

    data = synthetic_mnist(800, num_classes=4, dim=24, noise=0.25, seed=0)
    train, test = data.split(0.8, seed=1)
    params = init_fcnn(jax.random.key(0), [24, 32, 4])
    params, _ = train_fcnn(params, train, TrainConfig(epochs=20, batch_size=32))

    x = jnp.asarray(test.x, jnp.float32)
    acc_f32 = float(
        np.mean(np.argmax(np.asarray(forward(params, x)), -1) == test.y)
    )
    q = quantize_fcnn(params)
    acc_q = float(
        np.mean(np.argmax(np.asarray(fcnn_quantized_forward(q, x)), -1) == test.y)
    )
    assert acc_f32 > 0.85
    assert acc_q >= acc_f32 - 0.02  # int8 costs at most 2 points
