"""Int8 quantized inference: dequant error bounds, jnp-vs-Pallas exact
agreement, closeness to the f32 forward, and end-to-end classifier
accuracy parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.data.datasets import synthetic_mnist
from tpu_dist_nn.kernels.quantized import (
    fcnn_quantized_forward,
    forward_quantized,
    quantize_fcnn,
)
from tpu_dist_nn.models.fcnn import forward, init_fcnn


@pytest.fixture(autouse=True)
def _pin_int8_serving(monkeypatch):
    """This module tests the int8 SERVING path. The warm-time
    auto-fallback (Engine.measure_int8_speedup) reroutes serving to
    f32 wherever int8 measures slower — which includes this CPU box —
    and that would silently swap the path under test (and make the
    tight int8-vs-int8 parity comparisons flaky on measurement noise).
    Pin the fallback off; the fallback itself is tested explicitly
    below, re-enabling it per-test."""
    monkeypatch.setenv("TDN_INT8_AUTO", "0")


def _params_and_x(sizes=(24, 32, 16, 4), batch=64, seed=0):
    params = init_fcnn(jax.random.key(seed), list(sizes))
    rng = np.random.default_rng(seed)
    x = rng.uniform(0, 1, (batch, sizes[0])).astype(np.float32)
    return params, jnp.asarray(x)


def test_weight_quantization_roundtrip_error_bounded():
    params, _ = _params_and_x()
    q = quantize_fcnn(params)
    for p, qp in zip(params, q):
        w = np.asarray(p["w"], np.float32)
        deq = np.asarray(qp["wq"], np.float32) * np.asarray(qp["scale"])
        # Symmetric int8: max error <= scale/2 per channel.
        bound = np.broadcast_to(
            np.asarray(qp["scale"])[None, :] * 0.5 + 1e-8, w.shape
        )
        np.testing.assert_array_less(np.abs(w - deq), bound)
        assert qp["wq"].dtype == jnp.int8


def test_quantized_forward_close_to_f32():
    params, x = _params_and_x()
    q = quantize_fcnn(params)
    ref = forward(params, x)
    got = forward_quantized(q, x)
    # Probabilities (softmax outputs) should agree to ~1e-2.
    assert float(jnp.max(jnp.abs(got - ref))) < 2e-2
    np.testing.assert_array_equal(
        np.argmax(np.asarray(got), -1), np.argmax(np.asarray(ref), -1)
    )


def test_pallas_chain_matches_jnp_reference_exactly():
    params, x = _params_and_x(batch=100)  # ragged vs block_b
    q = quantize_fcnn(params)
    ref = forward_quantized(q, x)
    # prefer_kernel=True: the measured-width dispatch would route these
    # tiny layers to the jnp chain (making the comparison vacuous).
    got = fcnn_quantized_forward(q, x, block_b=32, prefer_kernel=True)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=1e-6, atol=1e-7
    )


def test_quantized_classifier_accuracy_parity():
    # Train a small f32 classifier, quantize, and check accuracy holds.
    from tpu_dist_nn.train.trainer import TrainConfig, train_fcnn

    data = synthetic_mnist(800, num_classes=4, dim=24, noise=0.25, seed=0)
    train, test = data.split(0.8, seed=1)
    params = init_fcnn(jax.random.key(0), [24, 32, 4])
    params, _ = train_fcnn(params, train, TrainConfig(epochs=20, batch_size=32))

    x = jnp.asarray(test.x, jnp.float32)
    acc_f32 = float(
        np.mean(np.argmax(np.asarray(forward(params, x)), -1) == test.y)
    )
    q = quantize_fcnn(params)
    acc_q = float(
        np.mean(np.argmax(np.asarray(fcnn_quantized_forward(q, x)), -1) == test.y)
    )
    assert acc_f32 > 0.85
    assert acc_q >= acc_f32 - 0.02  # int8 costs at most 2 points


def test_engine_serves_quantized(tmp_path):
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params
    from tpu_dist_nn.utils.errors import InvalidArgumentError

    params, x = _params_and_x(batch=20)
    acts = ["relu", "relu", "softmax"]
    model = spec_from_params(params, acts)
    p = tmp_path / "m.json"
    save_model(model, p)

    ref = Engine.up(p).infer(np.asarray(x))
    eng = Engine.up(p, quantize="int8")
    got = eng.infer(np.asarray(x))
    np.testing.assert_array_equal(got.argmax(-1), ref.argmax(-1))
    assert float(np.max(np.abs(got - ref))) < 2e-2

    with pytest.raises(InvalidArgumentError, match="unknown quantize"):
        Engine.up(p, quantize="int4")


def test_int8_auto_disable_routes_serving_to_f32(tmp_path, monkeypatch):
    # The auto-fallback closing the BENCH int8_vs_f32 regression: when
    # the warmup payoff measurement finds int8 SLOWER than f32, serving
    # launches reroute to the f32 path (outputs become bit-identical to
    # an unquantized engine's) instead of shipping the measured loss.
    import time

    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params

    params, x = _params_and_x(batch=20)
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    p = tmp_path / "m.json"
    save_model(model, p)
    x = np.asarray(x)

    # Real timings on this box legitimately measure int8 slower, which
    # would auto-disable at bring-up; skip the up-time measurement so
    # this test drives the decision DETERMINISTICALLY below.
    monkeypatch.setenv("TDN_INT8_WARMUP_MEASURE", "0")
    monkeypatch.setenv("TDN_INT8_AUTO", "1")
    f32 = Engine.up(p).infer(x)
    eng = Engine.up(p, quantize="int8")
    int8_out = eng.infer(x)
    assert float(np.max(np.abs(int8_out - f32))) > 0  # paths distinct

    # Deterministically make the int8 arm measure slower: the f32 arm
    # runs with the quantized state cleared (_q is None), so a sleep
    # keyed on _q penalizes exactly the int8 launches.
    orig_infer = Engine.infer

    def biased_infer(self, xb, **kw):
        if self._q is not None:
            time.sleep(0.01)
        return orig_infer(self, xb, **kw)

    monkeypatch.setattr(Engine, "infer", biased_infer)
    ratio = eng.measure_int8_speedup(rows=4)
    monkeypatch.setattr(Engine, "infer", orig_infer)
    assert ratio is not None and ratio < 1.0
    assert eng.int8_auto_disabled
    rerouted = eng.infer(x)
    np.testing.assert_array_equal(rerouted, f32)  # the f32 path, exactly
    # Re-measurement times the REAL int8 path (the gate is lifted for
    # its timed arm), and a favorable result re-enables serving int8.
    monkeypatch.setattr(
        Engine, "infer",
        lambda self, xb, **kw: (
            time.sleep(0.01 if self._q is None else 0.0),
            orig_infer(self, xb, **kw),
        )[1],
    )
    ratio2 = eng.measure_int8_speedup(rows=4)
    assert ratio2 is not None and ratio2 > 1.0
    assert not eng.int8_auto_disabled


def test_int8_auto_disable_env_opt_out(tmp_path, monkeypatch):
    import time

    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params

    params, x = _params_and_x(batch=8)
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    p = tmp_path / "m.json"
    save_model(model, p)
    eng = Engine.up(p, quantize="int8")
    int8_out = eng.infer(np.asarray(x))

    monkeypatch.setenv("TDN_INT8_AUTO", "0")
    orig_infer = Engine.infer

    def biased_infer(self, xb, **kw):
        if self._q is not None:
            time.sleep(0.01)
        return orig_infer(self, xb, **kw)

    monkeypatch.setattr(Engine, "infer", biased_infer)
    ratio = eng.measure_int8_speedup(rows=4)
    monkeypatch.setattr(Engine, "infer", orig_infer)
    assert ratio is not None and ratio < 1.0
    assert not eng.int8_auto_disabled  # opted out: int8 keeps serving
    np.testing.assert_array_equal(eng.infer(np.asarray(x)), int8_out)


def test_engine_serves_quantized_pipelined(tmp_path):
    # int8 composed with the padded pipeline executor (VERDICT r1 weak
    # item 5): per-stage quantized blocks under the GPipe schedule must
    # agree with the f32 pipeline to int8 tolerance, including when the
    # data axis is also active.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params

    params, x = _params_and_x(batch=24)
    acts = ["relu", "relu", "softmax"]
    model = spec_from_params(params, acts)
    p = tmp_path / "m.json"
    save_model(model, p)

    ref = Engine.up(p, [1, 1, 1]).infer(np.asarray(x))
    eng = Engine.up(p, [1, 1, 1], quantize="int8")
    assert eng.pipelined and eng._q_pp is not None
    got = eng.infer(np.asarray(x))
    np.testing.assert_array_equal(got.argmax(-1), ref.argmax(-1))
    assert float(np.max(np.abs(got - ref))) < 2e-2

    eng_dp = Engine.up(p, [1, 1, 1], data_parallel=2, quantize="int8")
    got_dp = eng_dp.infer(np.asarray(x))
    assert float(np.max(np.abs(got_dp - got))) < 1e-5  # same int8 math


def test_engine_serves_quantized_interleaved(tmp_path):
    # int8 x virtual stages (the last quantize composition hole,
    # previously an explicit rejection): quantized chunk blocks under
    # the forward-only table schedule must agree EXACTLY with the
    # chunk-per-device quantized pipeline (same int8 arithmetic, only
    # the placement differs) and with the f32 engine to int8 tolerance.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params

    import jax as _jax

    params = init_fcnn(_jax.random.key(0), [12, 10, 10, 10, 8])
    rng = np.random.default_rng(1)
    x = rng.uniform(0.0, 1.0, (24, 12))
    acts = ["relu", "relu", "relu", "softmax"]
    model = spec_from_params(params, acts)
    p = tmp_path / "m.json"
    save_model(model, p)

    ref_f32 = Engine.up(p, [1, 1, 1, 1], virtual_stages=2).infer(x)
    ref_int8 = Engine.up(p, [1, 1, 1, 1], quantize="int8").infer(x)
    eng = Engine.up(p, [1, 1, 1, 1], virtual_stages=2, quantize="int8")
    assert eng.pipelined and eng._q_pp is not None and eng.virtual_stages == 2
    got = eng.infer(x)
    np.testing.assert_allclose(got, ref_int8, rtol=0, atol=1e-5)
    assert float(np.max(np.abs(got - ref_f32))) < 2e-2
    np.testing.assert_array_equal(got.argmax(-1), ref_f32.argmax(-1))


def test_engine_serves_quantized_data_parallel(tmp_path):
    # int8 on the single-stage data-sharded placement: batch sharded
    # over the data axis, quantized chain under jit.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params

    params, x = _params_and_x(batch=24)
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    p = tmp_path / "m.json"
    save_model(model, p)

    ref = Engine.up(p, quantize="int8").infer(np.asarray(x))
    eng = Engine.up(p, data_parallel=4, quantize="int8")
    assert eng.data_sharded and eng._q is not None
    got = eng.infer(np.asarray(x))
    # Same arithmetic as the single-chip jnp path (sharding only moves
    # where rows compute): exact agreement.
    np.testing.assert_allclose(got, ref, rtol=0, atol=1e-6)


def test_quantize_rejects_conv_models():
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.models.network import init_conv_mlp
    from tpu_dist_nn.utils.errors import InvalidArgumentError

    model = init_conv_mlp(
        jax.random.key(0), in_shape=(6, 6, 1), conv_filters=(4,),
        hidden=(8,), num_classes=3,
    )
    with pytest.raises(InvalidArgumentError, match="dense"):
        Engine.up(model, quantize="int8")


def test_cli_infer_quantized(tmp_path, capsys):
    from tpu_dist_nn.cli import main as cli_main
    from tpu_dist_nn.core.schema import save_examples, save_model
    from tpu_dist_nn.models.fcnn import spec_from_params

    params, x = _params_and_x(batch=10)
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    mp = tmp_path / "m.json"
    save_model(model, mp)
    ip = tmp_path / "e.json"
    save_examples(np.asarray(x), np.zeros(len(x), np.int64), ip)
    rc = cli_main([
        "infer", "--config", str(mp), "--inputs", str(ip),
        "--batch-size", "4", "--quantize", "int8",
    ])
    assert rc == 0
    assert "Total inference time" in capsys.readouterr().out


def test_engine_quantized_serves_trained_weights(tmp_path):
    # After train(), the int8 path must track the new weights, not the
    # bring-up copy.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params
    from tpu_dist_nn.train.trainer import TrainConfig

    data = synthetic_mnist(600, num_classes=4, dim=24, noise=0.25, seed=0)
    train, test = data.split(0.8, seed=1)
    params = init_fcnn(jax.random.key(5), [24, 16, 4])
    model = spec_from_params(params, ["relu", "softmax"])
    p = tmp_path / "m.json"
    save_model(model, p)

    eng = Engine.up(p, quantize="int8")
    before = float(
        np.mean(eng.infer(test.x).argmax(-1) == test.y)
    )
    eng.train(train, TrainConfig(epochs=15, batch_size=32))
    after = float(
        np.mean(eng.infer(test.x).argmax(-1) == test.y)
    )
    assert after > before + 0.2  # training must reach the served path
    eng.down()
    assert eng._q is None


def test_engine_quantized_pipelined_serves_trained_weights(tmp_path):
    # Pipelined int8 engine: after train(), the per-stage quantized
    # blocks must track the trained weights too.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params
    from tpu_dist_nn.train.trainer import TrainConfig

    data = synthetic_mnist(600, num_classes=4, dim=24, noise=0.25, seed=0)
    train, test = data.split(0.8, seed=1)
    params = init_fcnn(jax.random.key(5), [24, 16, 4])
    model = spec_from_params(params, ["relu", "softmax"])
    p = tmp_path / "m.json"
    save_model(model, p)

    eng = Engine.up(p, [1, 1], quantize="int8")
    assert eng.pipelined and eng._q_pp is not None
    before = float(np.mean(eng.infer(test.x).argmax(-1) == test.y))
    eng.train(train, TrainConfig(epochs=15, batch_size=32))
    after = float(np.mean(eng.infer(test.x).argmax(-1) == test.y))
    assert after > before + 0.2  # training must reach the served path
    eng.down()
    assert eng._q_pp is None


def test_quantize_honors_metadata_distribution(tmp_path):
    # A pipelined export carries layer_distribution metadata; quantized
    # serving now honors it (int8 composes with the pipeline executor).
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.fcnn import spec_from_params

    params, x = _params_and_x(batch=8)
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    model.metadata["layer_distribution"] = [1, 1, 1]
    p = tmp_path / "m.json"
    save_model(model, p)
    eng = Engine.up(p, quantize="int8")
    assert eng.pipelined and eng._q_pp is not None
    assert eng.infer(np.asarray(x)).shape == (8, 4)


def test_pipeline_filler_slots_pass_through_exactly():
    # A stage with fewer real layers than L must NOT round-trip its
    # activations through per-row int8 at the identity filler slots
    # (ADVICE r2): the pipelined int8 path agrees with the single-chip
    # int8 path to float tolerance, not just the 2e-2 int8 bound.
    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.kernels.quantized import quantize_pipeline_weights
    from tpu_dist_nn.models.fcnn import spec_from_params
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pipeline import (
        build_pipeline_params,
        pipeline_forward_quantized,
    )

    params, x = _params_and_x(sizes=(24, 32, 16, 4), batch=16)
    acts = ["relu", "relu", "softmax"]
    model = spec_from_params(params, acts)
    # Distribution [2, 1]: stage 1 gets one real layer + one identity
    # filler slot (L = 2).
    stages = partition_model(model, [2, 1])
    pp = build_pipeline_params(stages)
    q = quantize_pipeline_weights(pp.weights)
    mesh = build_mesh(MeshSpec(stage=2))
    got = pipeline_forward_quantized(mesh, q, pp.meta, np.asarray(x))
    want = forward_quantized(quantize_fcnn(params), x, acts)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
