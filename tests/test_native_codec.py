"""Native C++ JSON codec tests: parity with the pure-Python schema path.

The codec plays the protobuf-C++-fast-path role of the reference
(dist_nn_pb2.py:32): same results as the Python loaders, just faster.
These tests require the native build (g++ is in the image); the
fallback path is exercised by flipping TDN_NATIVE in a subprocess.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_dist_nn.core.schema import (
    ModelSpec,
    load_examples,
    load_model,
    save_examples,
    save_model,
)
from tpu_dist_nn.native import (
    native_available,
    parse_examples,
    parse_model_layers,
    write_examples,
)
from tpu_dist_nn.testing.factories import random_model

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native codec unavailable (no g++?)"
)


def _model_json(tmp_path, model):
    path = tmp_path / "model.json"
    save_model(model, path)
    return path


def test_model_parse_matches_python(tmp_path):
    model = random_model([7, 5, 4, 3], seed=1)
    model.metadata["inference_metrics"] = {"accuracy": 0.97, "f1_score": 0.96}
    model.metadata["note"] = "layers \"quoted\" text"
    path = _model_json(tmp_path, model)

    native = load_model(path)  # native path (available per skipif)
    env = dict(os.environ, TDN_NATIVE="0")
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "from tpu_dist_nn.core.schema import load_model;"
         f"m = load_model({str(path)!r});"
         "import json, numpy as np;"
         "print(json.dumps([[l.weights.tolist(), l.biases.tolist(),"
         " l.activation, l.type_tag] for l in m.layers]));"
         "print(json.dumps(m.metadata))"],
        env=env, capture_output=True, text=True, check=True,
    )
    py_layers = json.loads(out.stdout.splitlines()[0])
    py_meta = json.loads(out.stdout.splitlines()[1])
    assert len(native.layers) == len(py_layers)
    for nat, (w, b, act, tag) in zip(native.layers, py_layers):
        np.testing.assert_array_equal(nat.weights, np.asarray(w))
        np.testing.assert_array_equal(nat.biases, np.asarray(b))
        assert nat.activation == act and nat.type_tag == tag
    assert native.metadata == py_meta


def test_examples_roundtrip_and_parity(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.uniform(-3, 3, (17, 9))
    y = rng.integers(0, 5, 17).astype(np.int32)
    path = tmp_path / "ex.json"
    save_examples(x, y, path)  # native writer
    x2, y2 = load_examples(path)  # native reader
    np.testing.assert_array_equal(x, x2)
    np.testing.assert_array_equal(y, y2)
    # The file is plain JSON any consumer can read (public contract).
    obj = json.loads(path.read_text())
    assert len(obj["examples"]) == 17
    np.testing.assert_allclose(obj["examples"][3]["input"], x[3])


def test_examples_nested_input_and_missing_label():
    blob = json.dumps({"examples": [
        {"input": [[0.5, 1.5], [2.5, 3.5]], "label": 2},
        {"input": [1, 2, 3, 4]},
    ]}).encode()
    x, y = parse_examples(blob)
    np.testing.assert_array_equal(x, [[0.5, 1.5, 2.5, 3.5], [1, 2, 3, 4]])
    assert y.tolist() == [2, -1]  # missing label → -1 (load_examples parity)


def test_malformed_inputs_raise():
    with pytest.raises(ValueError, match="inconsistent input dimensions"):
        parse_examples(b'{"examples": [{"input": [1]}, {"input": [1, 2]}]}')
    with pytest.raises(ValueError, match="equal weight counts"):
        parse_model_layers(json.dumps({"layers": [{"neurons": [
            {"weights": [1.0], "bias": 0.0},
            {"weights": [1.0, 2.0], "bias": 0.0},
        ]}]}).encode())
    with pytest.raises(ValueError, match="no layers"):
        parse_model_layers(b'{"layers": []}')
    with pytest.raises(ValueError):
        parse_examples(b'{"examples": [{"input": [1, 2}]}')


def test_conv_model_falls_back_to_python(tmp_path):
    """Non-dense layers are out of the native codec's scope: it signals
    fallback and the Python path loads them (scheme: protobuf C++ vs
    pure-Python descriptor selection)."""
    obj = {"layers": [
        {"type": "conv2d", "in_shape": [2, 2, 1], "kernel_size": [1, 1],
         "stride": [1, 1], "padding": "same", "activation": "relu",
         "weights": [[[[1.0]]]], "bias": [0.0]},
    ]}
    assert parse_model_layers(json.dumps(obj).encode()) is None
    path = tmp_path / "conv.json"
    path.write_text(json.dumps(obj))
    model = load_model(path)  # full loader silently uses the Python path
    assert model.layers[0].kind == "conv2d"


def test_write_examples_float_roundtrip_exact():
    """%.17g must round-trip float64 bit-exactly through re-parse."""
    tricky = np.array([[0.1, 1e-308, 1.7976931348623157e308, -0.0,
                        2.220446049250313e-16, 3.141592653589793]])
    data = write_examples(tricky, np.array([0], np.int32))
    x, _ = parse_examples(data)
    np.testing.assert_array_equal(x, tricky)


def test_pure_python_fallback_subprocess(tmp_path):
    """TDN_NATIVE=0 must serve the same loader API from pure Python."""
    model = random_model([4, 3, 2], seed=2)
    path = _model_json(tmp_path, model)
    ex_path = tmp_path / "ex.json"
    save_examples(np.ones((2, 4)), np.zeros(2, np.int32), ex_path)
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "from tpu_dist_nn.native import native_available;"
         "assert not native_available();"
         "from tpu_dist_nn.core.schema import load_model, load_examples;"
         f"m = load_model({str(path)!r}); x, y = load_examples({str(ex_path)!r});"
         "print(len(m.layers), x.shape, y.tolist())"],
        env=dict(os.environ, TDN_NATIVE="0"),
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "2 (2, 4) [0, 0]"


def test_non_ascii_metadata_before_layers(tmp_path):
    """Byte-offset splice must survive multi-byte UTF-8 before layers."""
    model = random_model([3, 2], seed=4)
    obj = {"name": "café modèle ✓", **model.to_json_dict()}
    path = tmp_path / "utf8.json"
    path.write_text(json.dumps(obj, ensure_ascii=False), encoding="utf-8")
    loaded = load_model(path)
    assert loaded.metadata["name"] == "café modèle ✓"
    np.testing.assert_array_equal(loaded.layers[0].weights, model.layers[0].weights)


def test_empty_and_ragged_examples_save(tmp_path):
    """Empty dataset writes {\"examples\": []}; ragged inputs fall back
    to the Python per-row path instead of crashing."""
    p = tmp_path / "empty.json"
    save_examples(np.zeros((0, 5)), np.zeros((0,), np.int32), p)
    assert json.loads(p.read_text()) == {"examples": []}
    x0, y0 = load_examples(p)
    assert x0.shape[0] == 0 and y0.shape == (0,)

    ragged = [np.ones((2, 3)), np.ones(6)]  # same flat size, different shape
    p2 = tmp_path / "ragged.json"
    save_examples(ragged, np.zeros(2, np.int32), p2)
    x2, _ = load_examples(p2)
    np.testing.assert_array_equal(x2, np.ones((2, 6)))


def test_nested_weights_rejected_native():
    obj = {"layers": [{"neurons": [
        {"weights": [[1.0, 2.0], [3.0, 4.0]], "bias": 0.0}]}]}
    with pytest.raises(ValueError, match="flat array"):
        parse_model_layers(json.dumps(obj).encode())


def test_fuzz_model_roundtrip_native_vs_python():
    # Randomized models: native parse vs pure-python parse must agree
    # bit-for-bit on shapes, values, and activations (the C++ codec is
    # the fast path for the same schema, never a different dialect).
    import json

    from tpu_dist_nn.core.schema import ModelSpec
    from tpu_dist_nn.testing.factories import random_model

    rng = np.random.default_rng(0)
    acts = ["relu", "sigmoid", "tanh", "softmax", "linear", "weird-name"]
    for trial in range(25):
        depth = int(rng.integers(1, 5))
        sizes = [int(rng.integers(1, 9)) for _ in range(depth + 1)]
        layer_acts = [str(rng.choice(acts)) for _ in range(depth)]
        model = random_model(sizes, activations=layer_acts, seed=trial)
        blob = json.dumps(model.to_json_dict()).encode()
        native_layers, _span = parse_model_layers(blob)
        ref = ModelSpec.from_json_dict(json.loads(blob))
        assert len(native_layers) == len(ref.layers)
        for nat, r in zip(native_layers, ref.layers):
            np.testing.assert_array_equal(nat["weights"], r.weights)
            np.testing.assert_array_equal(nat["biases"], r.biases)
            assert nat["activation"] == r.activation


def test_fuzz_examples_roundtrip_native(tmp_path):
    rng = np.random.default_rng(1)
    for trial in range(10):
        n = int(rng.integers(1, 40))
        d = int(rng.integers(1, 30))
        x = rng.uniform(-5, 5, (n, d))
        y = rng.integers(0, 10, n)
        p = tmp_path / f"ex_{trial}.json"
        save_examples(x, y, p)
        x2, y2 = load_examples(p)
        # Bit-exact: the writer uses %.17g precisely so f64 survives.
        np.testing.assert_array_equal(x2, x)
        np.testing.assert_array_equal(y2, y)
