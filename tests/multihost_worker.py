"""Worker process for the REAL multi-host tests (test_multihost_real.py).

Each worker is a fresh interpreter that joins a 2-process JAX job over a
localhost coordinator (the gloo CPU collectives transport that
``initialize_multihost`` configures), runs one scenario, and prints
machine-checkable ``RESULT <json>`` lines the parent asserts on. This is
the true analogue of the reference's production topology — N cooperating
processes on one machine (its N containers on one bridge network,
run_grpc_fcnn.py:83-155) — where the virtual-device tests only emulate
the device count inside one process.
"""

import json
import os
import sys


def main() -> int:
    scenario, pid, port = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    from tpu_dist_nn.parallel.multihost import initialize_multihost

    topo = initialize_multihost(f"localhost:{port}", 2, pid)
    assert topo.num_processes == 2, topo
    assert topo.global_device_count == 8, topo

    out = globals()[f"scenario_{scenario}"]()
    print(f"RESULT {json.dumps({'pid': pid, **out})}", flush=True)
    return 0


def scenario_collectives() -> dict:
    """Cross-process psum ground truth: a global array spanning both
    processes' devices reduces to the full-set sum on every host."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_dist_nn.data.feed import global_batch, shard_for_host
    from tpu_dist_nn.parallel.mesh import AXIS_DATA, MeshSpec, build_mesh

    mesh = build_mesh(MeshSpec(data=8))
    rows = np.arange(32, dtype=np.float32).reshape(8, 4)
    local = shard_for_host(rows)
    ga = global_batch(mesh, P(AXIS_DATA), local)
    total = float(jax.jit(lambda a: a.sum())(ga))
    return {"sum": total, "expect": float(rows.sum())}


def scenario_train_pipelined(schedule: str = "gpipe") -> dict:
    """Data-parallel pipelined training across processes: both hosts must
    see the IDENTICAL loss stream and end with identical weights, equal
    to the single-process result on the same global data (computed in
    the parent)."""
    import numpy as np

    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.data.datasets import Dataset
    from tpu_dist_nn.data.feed import shard_for_host
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.multihost import to_host_numpy
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.train.pipeline_trainer import TrainConfig, train_pipelined

    mesh = build_mesh(MeshSpec(stage=2, data=4))
    model = random_model([12, 10, 6], seed=0)
    params = build_pipeline_params(partition_model(model, [1, 1]))
    full = _global_dataset()
    sx, sy = shard_for_host(full.x, full.y)
    data = Dataset(sx, sy, full.num_classes)
    cfg = TrainConfig(epochs=2, batch_size=32, learning_rate=1e-2, seed=0)
    params, history = train_pipelined(
        params, mesh, data, cfg, num_microbatches=4, eval_data=full,
        schedule=schedule,
    )
    w = to_host_numpy(params.weights.w)
    return {
        "losses": [round(h["loss"], 6) for h in history],
        "eval_acc": history[-1]["eval"]["accuracy"],
        "w_digest": float(np.abs(w).sum()),
        "w00": float(w[0, 0, 0, 0]),
    }


def scenario_train_pipelined_1f1b() -> dict:
    return scenario_train_pipelined("1f1b")


def scenario_train_lm_pipelined() -> dict:
    """Pipelined LM training across processes with the global-batch
    feed; both hosts must report the identical loss stream."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_dist_nn.data.feed import global_batch, shard_for_host
    from tpu_dist_nn.models.transformer import TransformerConfig, init_transformer
    from tpu_dist_nn.parallel.mesh import AXIS_DATA, MeshSpec, build_mesh
    from tpu_dist_nn.parallel.multihost import to_host_numpy
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm
    import jax

    mesh = build_mesh(MeshSpec(stage=2, data=4))
    cfg = TransformerConfig(
        vocab_size=31, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_seq_len=12
    )
    params = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    rows = rng.integers(0, cfg.vocab_size, (64, 13)).astype(np.int32)
    local_rows = shard_for_host(rows)
    batches = [local_rows[i * 8:(i + 1) * 8] for i in range(4)]
    globalize = lambda b: global_batch(mesh, P(AXIS_DATA, None), b)  # noqa: E731
    params, history = train_lm(
        params, cfg, batches,
        LMTrainConfig(steps=4, log_every=1),
        mesh=mesh, num_stages=2, num_microbatches=2, globalize=globalize,
    )
    tok = to_host_numpy(params["tok_embed"])
    return {
        "losses": [round(h["loss"], 6) for h in history],
        "tok_digest": float(np.abs(tok).sum()),
    }


def scenario_train_lm_3d() -> dict:
    """PP x TP x DP across REAL processes, under BOTH wire layouts.

    Phase 1 — the production layout (`build_mesh`: data outermost, so
    the DATA-axis gradient all-reduce is what rides the DCN transport
    while stage ppermutes and Megatron psums stay intra-host — the
    canonical DCN/ICI split the mesh module documents). Phase 2 — a
    hand-made mesh with STAGE outermost, so every tick's forward and
    backward inter-stage ppermute hand-off crosses the process
    boundary instead. Same math either way: both hosts must see one
    identical loss stream across BOTH layouts, proving the 3D step is
    wire-placement-invariant on the real 2-process topology."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from tpu_dist_nn.data.feed import global_batch, shard_for_host
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.parallel.mesh import (
        AXIS_DATA,
        AXIS_MODEL,
        AXIS_STAGE,
        MeshSpec,
        build_mesh,
    )
    from tpu_dist_nn.parallel.multihost import to_host_numpy
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks_pp_tp
    from tpu_dist_nn.train.lm_trainer import (
        LMTrainConfig,
        make_pipeline_lm_train_step,
        train_lm,
    )

    cfg = TransformerConfig(
        vocab_size=31, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=12,
    )
    base = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    rows = rng.integers(0, cfg.vocab_size, (64, 13)).astype(np.int32)
    local_rows = shard_for_host(rows)

    meshes = {
        # data outermost: DCN carries the data all-reduce.
        "dcn_data": build_mesh(MeshSpec(stage=2, model=2, data=2)),
        # stage outermost: DCN carries every inter-stage ppermute.
        # (Auto axis types, like build_mesh: jax 0.9's make_mesh
        # defaults to Explicit, which flips eager ops into
        # sharding-in-types mode.)
        "dcn_stage": jax.make_mesh(
            (2, 2, 2), (AXIS_STAGE, AXIS_MODEL, AXIS_DATA),
            axis_types=(jax.sharding.AxisType.Auto,) * 3,
        ),
    }
    only = os.environ.get("TDN_3D_ONLY")
    if only:
        meshes = {only: meshes[only]}
    out = {}
    for name, mesh in meshes.items():
        params = dict(
            base, blocks=shard_blocks_pp_tp(base["blocks"], cfg, 2, 2)
        )
        if name == "dcn_data":
            # data spans the hosts: per-process stripes through the
            # global-batch assembler (the production feed).
            batches = [local_rows[i * 8:(i + 1) * 8] for i in range(4)]
            globalize = lambda b, m=mesh: global_batch(  # noqa: E731
                m, P(AXIS_DATA, None), b
            )
        else:
            # stage spans the hosts: BOTH data shards live on every
            # process, so per-process stripes would feed different
            # rows into replicated shards (the documented
            # N-diverging-models hazard). Every host supplies the
            # FULL global batch; make_array_from_callback slices each
            # addressable shard out of it.
            from jax.sharding import NamedSharding

            # The same global batches the dcn_data feed assembles:
            # [process 0's stripe; process 1's stripe] per step.
            batches = [
                np.concatenate(
                    [rows[i * 8:(i + 1) * 8],
                     rows[32 + i * 8:32 + (i + 1) * 8]]
                )
                for i in range(4)
            ]
            sharding = NamedSharding(mesh, P(AXIS_DATA, None))
            globalize = lambda b, sh=sharding: jax.make_array_from_callback(  # noqa: E731
                b.shape, sh, lambda idx, bb=b: bb[idx]
            )
        step_fn = lambda opt, m=mesh: make_pipeline_lm_train_step(  # noqa: E731
            m, cfg, 2, 2, opt, schedule="1f1b", tensor_parallel=2
        )
        params, history = train_lm(
            params, cfg, batches,
            LMTrainConfig(steps=4, log_every=1),
            mesh=mesh, num_stages=2, num_microbatches=2,
            globalize=globalize, step_fn=step_fn,
        )
        tok = to_host_numpy(params["tok_embed"])
        out[f"losses_{name}"] = [round(h["loss"], 6) for h in history]
        out[f"tok_digest_{name}"] = float(np.abs(tok).sum())
    return out


def scenario_step_parity() -> dict:
    """ONE optimizer step on a FIXED global batch: loss and updated
    weights are row-partition-invariant, so this must match the parent's
    single-process step bit-for-tolerance — exact numerical parity of
    the cross-host path."""
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.data.feed import global_batch, shard_for_host
    from tpu_dist_nn.parallel.mesh import AXIS_DATA, MeshSpec, build_mesh
    from tpu_dist_nn.parallel.multihost import to_host_numpy
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.train.pipeline_trainer import (
        make_pipeline_train_step,
        prepare_pipeline_batch,
    )

    mesh = build_mesh(MeshSpec(stage=2, data=4))
    model = random_model([12, 10, 6], seed=0)
    params = build_pipeline_params(partition_model(model, [1, 1]))
    full = _global_dataset()
    x, y = shard_for_host(full.x[:32], full.y[:32])
    xs, labels, mask = prepare_pipeline_batch(params.meta, x, y, 4, 2)
    xs, labels, mask = global_batch(
        mesh, (P(None, AXIS_DATA, None), P(None, AXIS_DATA), P(None, AXIS_DATA)),
        xs, labels, mask,
    )
    opt = optax.adam(1e-2)
    step = make_pipeline_train_step(mesh, params.meta, 4, opt)
    w, _, loss = step(params.weights, opt.init(params.weights), xs, labels, mask)
    wn = to_host_numpy(w.w)
    return {"loss": float(loss), "w_digest": float(np.abs(wn).sum())}


def scenario_train_lm_zero1(make_name: str = "make_zero_lm_train_step") -> dict:
    """ZeRO-1 / FSDP data-parallel LM training across processes with the
    global-batch feed: identical loss streams on both hosts."""
    import numpy as np
    from jax.sharding import PartitionSpec as P
    import jax
    import optax

    from tpu_dist_nn.data.feed import global_batch, shard_for_host
    from tpu_dist_nn.models.transformer import TransformerConfig, init_transformer
    from tpu_dist_nn.parallel.mesh import AXIS_DATA, MeshSpec, build_mesh
    from tpu_dist_nn.parallel import zero
    from tpu_dist_nn.parallel.multihost import to_host_numpy

    mesh = build_mesh(MeshSpec(data=8))
    cfg = TransformerConfig(
        vocab_size=29, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_seq_len=12
    )
    params = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(2)
    rows = rng.integers(0, cfg.vocab_size, (64, 13)).astype(np.int32)
    local = shard_for_host(rows)
    optimizer = optax.adam(1e-3)
    step = getattr(zero, make_name)(mesh, cfg, optimizer, params)
    opt_state = step.init_opt_state(params)
    losses = []
    for i in range(3):
        batch = global_batch(mesh, P(AXIS_DATA, None), local[i * 8:(i + 1) * 8])
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(round(float(loss), 6))
    tok = to_host_numpy(params["tok_embed"])
    return {"losses": losses, "tok_digest": float(np.abs(np.asarray(tok)).sum())}


def scenario_train_lm_fsdp() -> dict:
    return scenario_train_lm_zero1("make_fsdp_lm_train_step")


def scenario_pipeline_infer_crosshost() -> dict:
    """Pure cross-host pipeline inference: 8 stages spanning 2 processes
    (4 each), data axis 1 — batches replicate across hosts and the
    hand-off rides the inter-process links; outputs must be identical
    on every host."""
    import numpy as np

    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.multihost import to_host_numpy
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params, pipeline_forward
    from tpu_dist_nn.testing.factories import random_model

    mesh = build_mesh(MeshSpec(stage=8, data=1))
    model = random_model([20, 18, 16, 14, 12, 10, 8, 7, 6], seed=11)
    params = build_pipeline_params(partition_model(model, [1] * 8))
    rng = np.random.default_rng(4)
    x = rng.uniform(0, 1, (12, 20)).astype(np.float32)
    out = to_host_numpy(pipeline_forward(mesh, params, x, num_microbatches=2))
    return {
        "digest": float(np.abs(out).sum()),
        "row0": [round(float(v), 8) for v in out[0]],
    }


def scenario_checkpoint_resume() -> dict:
    """Multi-host checkpoint round trip with NON-shared filesystems:
    only process 0's directory receives files (save_pytree gathers on
    every process, writes on 0), and resume_or_init must broadcast the
    restored state so a host with an empty directory resumes in sync
    instead of silently restarting from scratch."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_dist_nn.checkpoint.store import (
        AsyncCheckpointManager,
        resume_or_init,
    )

    pid = jax.process_index()
    # DIFFERENT directory per process = no shared FS.
    d = tempfile.mkdtemp(prefix=f"tdn_mh_ck_p{pid}_")
    mgr = AsyncCheckpointManager(d, keep=2)
    state = {"w": jnp.arange(8.0) * (1.0 + pid * 0.0), "step_marker": jnp.ones(())}
    # Both processes call save in lockstep (the collective contract).
    saved = {"w": state["w"] * 3.0, "step_marker": state["step_marker"] * 7.0}
    mgr.save(5, saved, metadata={"note": "mh"})
    mgr.wait()
    n_files = len(list(__import__("pathlib").Path(d).glob("ckpt_*")))
    # Fresh manager on the same per-process dir: process 1's is empty.
    mgr2 = AsyncCheckpointManager(d, keep=2)
    step, restored = resume_or_init(mgr2, state)
    return {
        "n_files": n_files,
        "step": step,
        "w_digest": float(np.abs(np.asarray(restored["w"])).sum()),
        "marker": float(np.asarray(restored["step_marker"])),
    }


def scenario_checkpoint_resume_zero1() -> dict:
    """Multi-host ZeRO-1 save AND resume with non-shared filesystems:
    the opt state is jitted with sharded out_shardings, so its leaves
    span the processes — on process 1 they are NON-addressable. Saving
    gathers (fine); the regression under test is resume_or_init, whose
    broadcast on non-source processes must build its payload from leaf
    METADATA (np.zeros_like on a non-addressable array raises). Ends
    with a retention-window violation that must raise the SAME
    ValueError on BOTH processes (the validation verdict is broadcast
    after the gather; a process-0-only raise would hang the peer in the
    collective)."""
    import tempfile

    import jax
    import numpy as np
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_dist_nn.checkpoint.store import AsyncCheckpointManager, resume_or_init
    from tpu_dist_nn.data.feed import global_batch, shard_for_host
    from tpu_dist_nn.models.transformer import TransformerConfig, init_transformer
    from tpu_dist_nn.parallel import zero
    from tpu_dist_nn.parallel.mesh import AXIS_DATA, MeshSpec, build_mesh
    from tpu_dist_nn.parallel.multihost import to_host_numpy

    pid = jax.process_index()
    mesh = build_mesh(MeshSpec(data=8))
    cfg = TransformerConfig(
        vocab_size=29, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_seq_len=12
    )
    params = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(3)
    rows = rng.integers(0, cfg.vocab_size, (16, 13)).astype(np.int32)
    local = shard_for_host(rows)
    step_fn = zero.make_zero_lm_train_step(mesh, cfg, optax.adam(1e-3), params)
    opt_state = step_fn.init_opt_state(params)  # sharded across processes
    batch = global_batch(mesh, P(AXIS_DATA, None), local)
    params, opt_state, _ = step_fn(params, opt_state, batch)

    d = tempfile.mkdtemp(prefix=f"tdn_mh_z1_p{pid}_")  # no shared FS
    mgr = AsyncCheckpointManager(d, keep=2)
    state = {"params": params, "opt_state": opt_state}
    mgr.save(7, state)
    mgr.wait()

    # Fresh manager; the template is the LIVE sharded state — its
    # opt-state leaves are non-addressable on process 1.
    mgr2 = AsyncCheckpointManager(d, keep=2)
    step, restored = resume_or_init(mgr2, state)
    tok = np.abs(np.asarray(restored["params"]["tok_embed"])).sum()
    saved_tok = np.abs(np.asarray(to_host_numpy(params["tok_embed"]))).sum()

    # Retention violation: keep=2 with steps {7, 9} on disk makes step 1
    # too old. Only process 0's manifest knows that; both must raise.
    mgr2.save(9, state)
    mgr2.wait()
    retention_raised = False
    try:
        mgr2.save(1, state)
    except ValueError:
        retention_raised = True
    mgr2.wait()
    return {
        "step": step,
        "tok_digest": float(tok),
        "saved_tok_digest": float(saved_tok),
        "retention_raised": retention_raised,
    }


def scenario_checkpoint_io_failure_agreed() -> dict:
    """A checkpoint-write IO failure on process 0 (the only writer)
    must raise on BOTH processes — not leave process 1 marching into
    the next training-step collective alone. Induced by pointing
    process 0's writer at a directory that vanished between saves
    (chmod tricks don't bite: tests run as root)."""
    import pathlib
    import tempfile

    import jax
    import numpy as np

    from tpu_dist_nn.checkpoint.store import CheckpointManager

    pid = jax.process_index()
    d = tempfile.mkdtemp(prefix=f"tdn_mh_io_p{pid}_")
    mgr = CheckpointManager(d, keep=2)
    mgr.save(1, {"w": np.ones(4) * (pid + 1)})
    first_ok = mgr.latest_step() == (1 if pid == 0 else None)

    if pid == 0:
        mgr.directory = pathlib.Path(d) / "vanished"  # mkstemp will fail
    raised = False
    try:
        mgr.save(2, {"w": np.ones(4)})
    except ValueError:
        raised = True
    return {"first_ok": bool(first_ok), "raised": raised}


def _global_dataset():
    from tpu_dist_nn.data.datasets import Dataset
    import numpy as np

    rng = np.random.default_rng(7)
    x = rng.uniform(0, 1, (256, 12)).astype(np.float32)
    y = rng.integers(0, 6, 256)
    return Dataset(x, y, 6)


if __name__ == "__main__":
    sys.exit(main())
