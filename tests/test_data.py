"""Data layer tests: synthetic dataset, IDX parsing, feeding."""

import struct

import numpy as np
import pytest

from tpu_dist_nn.core.schema import load_examples
from tpu_dist_nn.data import (
    Dataset,
    batch_iterator,
    device_prefetch,
    load_mnist_idx,
    synthetic_mnist,
)


def test_synthetic_dataset_shapes_and_range():
    ds = synthetic_mnist(200, num_classes=10, dim=784, seed=3)
    assert ds.x.shape == (200, 784) and ds.y.shape == (200,)
    assert ds.x.min() >= 0.0 and ds.x.max() <= 1.0
    assert set(np.unique(ds.y)) <= set(range(10))
    # Deterministic given the seed.
    ds2 = synthetic_mnist(200, num_classes=10, dim=784, seed=3)
    np.testing.assert_array_equal(ds.x, ds2.x)


def test_split_and_examples_round_trip(tmp_path):
    ds = synthetic_mnist(100, num_classes=4, dim=8, seed=1)
    train, test = ds.split(0.8, seed=0)
    assert len(train) == 80 and len(test) == 20
    p = tmp_path / "examples.json"
    test.to_examples_json(p)
    x, y = load_examples(p)
    np.testing.assert_allclose(x, test.x)
    np.testing.assert_array_equal(y, test.y)


def test_idx_round_trip(tmp_path):
    # Write MNIST-format IDX files and parse them back.
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (5, 4, 4), dtype=np.uint8)
    labels = rng.integers(0, 10, 5, dtype=np.uint8)
    (tmp_path / "train-images-idx3-ubyte").write_bytes(
        struct.pack(">IIII", 0x0803, 5, 4, 4) + images.tobytes()
    )
    (tmp_path / "train-labels-idx1-ubyte").write_bytes(
        struct.pack(">II", 0x0801, 5) + labels.tobytes()
    )
    ds = load_mnist_idx(tmp_path, "train")
    assert ds.x.shape == (5, 16)
    assert ds.x.dtype == np.float32
    np.testing.assert_allclose(ds.x, images.reshape(5, 16) / 255.0, rtol=1e-6)
    np.testing.assert_array_equal(ds.y, labels)


def test_idx_gzipped_round_trip(tmp_path):
    # The MNIST mirrors distribute .gz; they must load without a
    # pre-gunzip step.
    import gzip

    rng = np.random.default_rng(1)
    images = rng.integers(0, 256, (3, 4, 4), dtype=np.uint8)
    labels = rng.integers(0, 10, 3, dtype=np.uint8)
    (tmp_path / "t10k-images-idx3-ubyte.gz").write_bytes(
        gzip.compress(struct.pack(">IIII", 0x0803, 3, 4, 4) + images.tobytes())
    )
    (tmp_path / "t10k-labels-idx1-ubyte.gz").write_bytes(
        gzip.compress(struct.pack(">II", 0x0801, 3) + labels.tobytes())
    )
    ds = load_mnist_idx(tmp_path, "test")
    assert ds.x.shape == (3, 16)
    np.testing.assert_array_equal(ds.y, labels)


def test_idx_missing_files_error_is_explicit(tmp_path):
    # Missing real data must surface acquisition guidance, never fall
    # back to synthetic silently (VERDICT r1 missing item 2).
    with pytest.raises(FileNotFoundError, match="docs/MNIST.md"):
        load_mnist_idx(tmp_path / "nope", "train")


def test_idx_bad_magic(tmp_path):
    (tmp_path / "train-images-idx3-ubyte").write_bytes(
        struct.pack(">IIII", 0x9999, 1, 2, 2) + b"\x00" * 4
    )
    with pytest.raises(ValueError, match="magic"):
        load_mnist_idx(tmp_path, "train")


def test_batch_iterator_drop_remainder():
    x = np.arange(10)[:, None]
    batches = list(batch_iterator(x, batch_size=4, drop_remainder=True))
    assert [len(b) for b in batches] == [4, 4]
    batches = list(batch_iterator(x, batch_size=4))
    assert [len(b) for b in batches] == [4, 4, 2]


def test_batch_iterator_shuffle_covers_all():
    x = np.arange(20)
    seen = np.concatenate(list(batch_iterator(x, batch_size=6, shuffle=True, seed=1)))
    assert sorted(seen.tolist()) == list(range(20))


def test_device_prefetch_order():
    x = np.arange(12).reshape(6, 2)
    out = list(device_prefetch(batch_iterator(x, batch_size=2), depth=3))
    np.testing.assert_array_equal(np.concatenate([np.asarray(b) for b in out]), x)


def test_dataset_length_mismatch():
    with pytest.raises(ValueError):
        Dataset(np.zeros((3, 2)), np.zeros(4, dtype=np.int32), 2)


def test_synthetic_fashion_mnist_shapes_and_determinism():
    from tpu_dist_nn.data.datasets import synthetic_fashion_mnist

    a = synthetic_fashion_mnist(64, num_classes=10, dim=784, seed=3)
    b = synthetic_fashion_mnist(64, num_classes=10, dim=784, seed=3)
    assert a.x.shape == (64, 784) and a.y.shape == (64,)
    assert a.x.min() >= 0.0 and a.x.max() <= 1.0
    np.testing.assert_array_equal(a.x, b.x)
    np.testing.assert_array_equal(a.y, b.y)
    # Distinct from the digit-style synthetic data at the same seed.
    from tpu_dist_nn.data.datasets import synthetic_mnist

    c = synthetic_mnist(64, dim=784, seed=3)
    assert not np.allclose(a.x, c.x)


def test_shard_for_host_single_process_identity():
    from tpu_dist_nn.data.feed import shard_for_host

    x = np.arange(12).reshape(6, 2)
    y = np.arange(6)
    gx, gy = shard_for_host(x, y)
    np.testing.assert_array_equal(gx, x)
    np.testing.assert_array_equal(gy, y)
    np.testing.assert_array_equal(shard_for_host(x), x)
    with pytest.raises(ValueError, match="leading dim"):
        shard_for_host(x, np.arange(5))
