"""Cotangent-stash split backward: parity vs AD and the W-tick
contract (pure GEMMs). See parallel/split_backward.py and docs/PERF.md
"Do ticks translate to time?"."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    block_apply,
    init_transformer,
)
from tpu_dist_nn.parallel.split_backward import (
    block_backward_split,
    block_weight_grads,
    chunk_backward_split,
    chunk_weight_grads,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
    max_seq_len=32,
)


def _setup(seed=3):
    params = init_transformer(jax.random.key(seed), CFG)
    rng = np.random.default_rng(seed + 1)
    x = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
    dy = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
    return params["blocks"], x, dy


def test_block_split_backward_matches_ad():
    # dx + small grads from B, big-weight grads from the deferred W
    # GEMMs: together they must equal jax.vjp of block_apply exactly
    # (the sub-op math stays INSIDE jax.vjp — only the weight
    # applications are hand-split).
    blocks, x, dy = _setup()
    block0 = jax.tree.map(lambda a: a[0], blocks)

    _, ref_vjp = jax.vjp(lambda b, xx: block_apply(b, xx, CFG), block0, x)
    ref_db, ref_dx = ref_vjp(dy)
    dx, d_small, wstash = jax.jit(
        lambda b, xx, cot: block_backward_split(b, xx, cot, CFG)
    )(block0, x, dy)
    d_big = jax.jit(block_weight_grads)(wstash)

    np.testing.assert_allclose(
        np.asarray(dx), np.asarray(ref_dx), rtol=5e-4, atol=1e-5
    )
    for k, v in {**d_small, **d_big}.items():
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(ref_db[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )
    # Every block param is covered by exactly one half.
    assert set(d_small) | set(d_big) == set(block0)


def test_chunk_split_backward_matches_ad():
    blocks, x, dy = _setup(seed=9)

    def chunk_fwd(bs, xx):
        def body(c, blk):
            return block_apply(blk, c, CFG), None

        y, _ = jax.lax.scan(body, xx, bs)
        return y

    _, ref_vjp = jax.vjp(chunk_fwd, blocks, x)
    ref_db, ref_dx = ref_vjp(dy)
    dx, d_smalls, wstashes = jax.jit(
        lambda bs, xx, cot: chunk_backward_split(bs, xx, cot, CFG)
    )(blocks, x, dy)
    d_bigs = jax.jit(chunk_weight_grads)(wstashes)

    np.testing.assert_allclose(
        np.asarray(dx), np.asarray(ref_dx), rtol=5e-4, atol=1e-5
    )
    for k, v in {**d_smalls, **d_bigs}.items():
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(ref_db[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )


def test_chunk_split_backward_matches_ad_with_flash_attn(monkeypatch):
    # ADVICE r5 (low): the stash split's parity was CI-tested with the
    # jnp reference attention core only — the flash kernel's custom VJP
    # rode jax.vjp of the weight-free core UNTESTED in zb-stash mode,
    # so a flash-VJP regression would surface only as silent training-
    # quality drift. Force the shape-aware dispatch onto the flash
    # kernel (FLASH_MIN_SEQ override; interpret-mode Pallas off-TPU)
    # and require chunk_backward_split + chunk_weight_grads to equal
    # jax.vjp of the chunk forward built on the SAME attn_fn.
    # (any `import ... flash_attention` attribute lookup resolves to
    # the FUNCTION re-exported by kernels/__init__, not the module)
    import importlib

    fa = importlib.import_module("tpu_dist_nn.kernels.flash_attention")
    monkeypatch.setattr(fa, "FLASH_MIN_SEQ", 8)
    attn_fn = fa.select_attention
    # Sanity: at T=16 >= the overridden threshold the dispatch really
    # selects flash (a silently-reverted override would turn this test
    # back into the already-covered reference parity).
    blocks, x, dy = _setup(seed=11)
    assert x.shape[1] >= fa.FLASH_MIN_SEQ

    def chunk_fwd(bs, xx):
        def body(c, blk):
            return block_apply(blk, c, CFG, attn_fn), None

        y, _ = jax.lax.scan(body, xx, bs)
        return y

    _, ref_vjp = jax.vjp(chunk_fwd, blocks, x)
    ref_db, ref_dx = ref_vjp(dy)
    dx, d_smalls, wstashes = jax.jit(
        lambda bs, xx, cot: chunk_backward_split(
            bs, xx, cot, CFG, attn_fn
        )
    )(blocks, x, dy)
    d_bigs = jax.jit(chunk_weight_grads)(wstashes)

    # Flash accumulates in a different order than the materialized
    # reference; both sides here run flash, so AD tolerances apply.
    np.testing.assert_allclose(
        np.asarray(dx), np.asarray(ref_dx), rtol=5e-4, atol=2e-5
    )
    for k, v in {**d_smalls, **d_bigs}.items():
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(ref_db[k]), rtol=5e-4, atol=2e-5,
            err_msg=k,
        )


def test_w_tick_is_pure_gemms():
    # The W-tick contract the canonical ZB accounting assumes: the
    # jaxpr of block_weight_grads contains contractions and reshapes
    # only — no exp/erf/rsqrt (no softmax, gelu, layernorm — i.e. no
    # forward recompute and no backward backbone).
    blocks, x, dy = _setup(seed=5)
    block0 = jax.tree.map(lambda a: a[0], blocks)
    _, _, wstash = block_backward_split(block0, x, dy, CFG)
    jaxpr = jax.make_jaxpr(block_weight_grads)(wstash)
    prims = {eqn.primitive.name for eqn in jaxpr.jaxpr.eqns}
    forbidden = {"exp", "erf", "rsqrt", "logistic", "tanh", "div",
                 "reduce_max", "custom_vjp_call"}
    assert not (prims & forbidden), (
        f"W tick is not pure GEMMs: {sorted(prims & forbidden)}"
    )
    assert "dot_general" in prims
