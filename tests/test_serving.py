"""Wire-compatible gRPC serving: codec, server, client, CLI.

The server speaks the reference's exact protocol (dist_nn.proto:
Matrix of float64 Rows, LayerService.Process) so the reference's own
client can drive this framework. Codec parity is checked against REAL
protoc-generated stubs when protoc is available.
"""

import json
import shutil
import subprocess
import sys
import time

import numpy as np
import pytest

from tpu_dist_nn.serving.wire import decode_matrix, encode_matrix


def test_codec_round_trip():
    rng = np.random.default_rng(0)
    for shape in [(1, 4), (7, 13), (3, 1), (0, 0)]:
        x = rng.normal(size=shape)
        out = decode_matrix(encode_matrix(x))
        if x.size:
            np.testing.assert_array_equal(out, x)


def test_codec_rejects_ragged_and_bad_input():
    with pytest.raises(ValueError, match="2-D"):
        encode_matrix(np.zeros(3))
    # Hand-build a ragged matrix: one 2-wide row, one 1-wide row.
    r2 = b"\x0a\x10" + np.zeros(2).tobytes()
    r1 = b"\x0a\x08" + np.zeros(1).tobytes()
    ragged = b"\x0a" + bytes([len(r2)]) + r2 + b"\x0a" + bytes([len(r1)]) + r1
    with pytest.raises(ValueError, match="ragged"):
        decode_matrix(ragged)


@pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc not available")
def test_codec_parity_with_protoc_stubs(tmp_path):
    """Our bytes parse with real generated stubs and vice versa."""
    proto = tmp_path / "dist_nn.proto"
    proto.write_text(
        'syntax = "proto3";\npackage dist_nn;\n'
        "message Row { repeated double values = 1; }\n"
        "message Matrix { repeated Row rows = 1; }\n"
    )
    subprocess.run(
        ["protoc", f"-I{tmp_path}", f"--python_out={tmp_path}", str(proto)],
        check=True,
    )
    sys.path.insert(0, str(tmp_path))
    try:
        try:
            import dist_nn_pb2  # noqa: F401
        except Exception as e:  # gencode/runtime version skew
            pytest.skip(f"generated stubs unusable: {e}")
        rng = np.random.default_rng(1)
        x = rng.normal(size=(5, 3))
        # Their parser reads our bytes.
        m = dist_nn_pb2.Matrix()
        m.ParseFromString(encode_matrix(x))
        theirs = np.array([list(r.values) for r in m.rows])
        np.testing.assert_array_equal(theirs, x)
        # Our parser reads their bytes.
        m2 = dist_nn_pb2.Matrix()
        for row in x:
            m2.rows.add().values.extend(row.tolist())
        np.testing.assert_array_equal(decode_matrix(m2.SerializeToString()), x)
    finally:
        sys.path.remove(str(tmp_path))


@pytest.fixture(scope="module")
def served_engine(tmp_path_factory):
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.serving import serve_engine
    from tpu_dist_nn.testing.factories import random_model

    model = random_model([12, 10, 6], seed=3)
    path = tmp_path_factory.mktemp("serve") / "model.json"
    save_model(model, path)
    engine = Engine.up(str(path), [1, 1])
    server, port = serve_engine(engine, 0)
    yield engine, port, str(path)
    server.stop(grace=0.5)
    engine.down()


def test_grpc_round_trip_matches_local(served_engine):
    from tpu_dist_nn.serving import GrpcClient

    engine, port, _ = served_engine
    rng = np.random.default_rng(2)
    x = rng.uniform(0, 1, (17, 12))
    client = GrpcClient(f"127.0.0.1:{port}")
    try:
        remote = client.process(x)
        local = engine.infer(x)
        np.testing.assert_allclose(remote, local, rtol=1e-6, atol=1e-9)
        single = client.process(x[:1])
        np.testing.assert_allclose(single, local[:1], rtol=1e-6, atol=1e-9)
    finally:
        client.close()


def test_grpc_dim_mismatch_is_invalid_argument(served_engine):
    import grpc

    from tpu_dist_nn.serving import GrpcClient

    _, port, _ = served_engine
    client = GrpcClient(f"127.0.0.1:{port}")
    try:
        with pytest.raises(grpc.RpcError) as e:
            client.process(np.zeros((2, 5)))  # model wants 12 features
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
    finally:
        client.close()


def test_cli_client_against_server(served_engine, tmp_path, capsys):
    from tpu_dist_nn.cli import main as cli_main

    engine, port, _ = served_engine
    rng = np.random.default_rng(4)
    x = rng.uniform(0, 1, (9, 12))
    labels = engine.infer(x).argmax(-1)  # server's own argmax => accuracy 1.0
    examples = {
        "examples": [
            {"input": xi.tolist(), "label": int(li)} for xi, li in zip(x, labels)
        ]
    }
    path = tmp_path / "ex.json"
    path.write_text(json.dumps(examples))
    rc = cli_main([
        "infer", "--inputs", str(path),
        "--target", f"127.0.0.1:{port}", "--batch-size", "4",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "accuracy 1.0000" in out
    # Bare --port with no --config is the reference client's addressing.
    rc = cli_main(["infer", "0", "--inputs", str(path), "--port", str(port)])
    assert rc == 0
    assert "predicted" in capsys.readouterr().out


def test_codec_fuzz_round_trip_and_malformed_robustness():
    """Random shapes/values round-trip exactly; malformed byte streams
    raise ValueError (never crash or hang) — the server maps these to
    INVALID_ARGUMENT."""
    rng = np.random.default_rng(7)
    for _ in range(50):
        n, d = int(rng.integers(1, 20)), int(rng.integers(1, 40))
        x = rng.normal(scale=10.0 ** rng.integers(-3, 4), size=(n, d))
        np.testing.assert_array_equal(decode_matrix(encode_matrix(x)), x)
    base = encode_matrix(rng.normal(size=(3, 5)))
    for _ in range(200):
        b = bytearray(base)
        op = rng.integers(0, 3)
        if op == 0 and len(b) > 1:          # truncate
            b = b[: int(rng.integers(1, len(b)))]
        elif op == 1:                        # bit-flip
            i = int(rng.integers(0, len(b)))
            b[i] ^= 1 << int(rng.integers(0, 8))
        else:                                # garbage append
            b += bytes(rng.integers(0, 256, int(rng.integers(1, 16))))
        try:
            out = decode_matrix(bytes(b))
            assert out.ndim == 2  # decoded fine — acceptable
        except ValueError:
            pass  # rejected cleanly — acceptable


def test_server_survives_concurrent_clients(served_engine):
    """The reference's concurrency model is a 10-thread pool
    (grpc_node.py:169); hammer the server from 8 threads and require
    every reply correct."""
    from concurrent.futures import ThreadPoolExecutor

    from tpu_dist_nn.serving import GrpcClient

    engine, port, _ = served_engine
    rng = np.random.default_rng(9)
    x = rng.uniform(0, 1, (11, 12))
    expect = engine.infer(x)

    def one(_):
        client = GrpcClient(f"127.0.0.1:{port}")
        try:
            return client.process(x)
        finally:
            client.close()

    with ThreadPoolExecutor(max_workers=8) as pool:
        for out in pool.map(one, range(16)):
            np.testing.assert_allclose(out, expect, rtol=1e-6, atol=1e-9)


def test_codec_rejects_truncated_length_fields():
    """A length-delimited field claiming more bytes than remain must
    raise (real protobuf parsers reject truncated messages; silently
    decoding a short row would compute on corrupt data)."""
    x = np.arange(6.0).reshape(1, 6)
    full = encode_matrix(x)
    # Cut INSIDE the payload but on an 8-byte boundary: lengths still
    # claim 6 doubles, only 4 remain.
    cut = full[: len(full) - 16]
    with pytest.raises(ValueError, match="truncated"):
        decode_matrix(cut)


def test_doctor_serving_round_trip(capsys):
    import json as _json

    from tpu_dist_nn.cli import main as cli_main

    rc = cli_main(["doctor", "--serving"])
    out = _json.loads(capsys.readouterr().out)
    assert rc == 0 and out["healthy"]
    assert out["serving"]["round_trip"] is True


def test_doctor_serving_failure_is_unhealthy(capsys, monkeypatch):
    """A broken serving stack must fail the health verdict, not default
    to healthy through the error path."""
    import json as _json

    import tpu_dist_nn.serving as serving_pkg
    from tpu_dist_nn.cli import main as cli_main

    def boom(*a, **k):
        raise RuntimeError("serving stack broken")

    monkeypatch.setattr(serving_pkg, "serve_engine", boom)
    rc = cli_main(["doctor", "--serving"])
    out = _json.loads(capsys.readouterr().out)
    assert rc == 1 and out["healthy"] is False
    assert out["serving"]["round_trip"] is False


class _SlowEngine:
    """Fake engine with a fixed per-LAUNCH cost — models the device
    dispatch latency that request coalescing amortizes (on the real
    tunneled TPU each engine.infer pays a host->device round trip; on
    the CPU test host that cost is near zero, so the mechanism is
    benchmarked against a controlled launch cost instead)."""

    def __init__(self, launch_seconds=0.010, dim=8):
        import dataclasses
        self.launch_seconds = launch_seconds
        self.launches = 0
        self.model = dataclasses.make_dataclass("M", ["input_dim"])(dim)

    def infer(self, x):
        import time as _t
        self.launches += 1
        _t.sleep(self.launch_seconds)
        return np.asarray(x) * 2.0


def _round_trip_rounds(port, rows, rounds):
    import time as _t
    from concurrent.futures import ThreadPoolExecutor

    from tpu_dist_nn.serving import GrpcClient

    clients = [GrpcClient(f"127.0.0.1:{port}") for _ in range(len(rows))]
    with ThreadPoolExecutor(max_workers=len(rows)) as ex:
        def volley():
            return list(
                ex.map(lambda cr: cr[0].process(cr[1]), zip(clients, rows))
            )

        volley()  # warm
        t0 = _t.monotonic()
        outs = [volley() for _ in range(rounds)]
        dt = _t.monotonic() - t0
    for c in clients:
        c.close()
    return dt / rounds, outs[-1]


def test_coalescing_beats_lock_when_launches_dominate():
    # VERDICT r2 item 4's bar: >2x aggregate throughput for 10
    # concurrent single-row clients vs the serialized engine lock, in
    # the regime coalescing targets (launch-cost-bound serving).
    from tpu_dist_nn.serving import serve_engine

    rows = [np.full((1, 8), i, np.float64) for i in range(10)]

    eng_lock = _SlowEngine()
    server, port = serve_engine(eng_lock, 0, host="127.0.0.1", coalesce=False)
    t_lock, _ = _round_trip_rounds(port, rows, rounds=5)
    server.stop(0)

    eng_co = _SlowEngine()
    server, port = serve_engine(eng_co, 0, host="127.0.0.1", coalesce=True)
    t_co, outs = _round_trip_rounds(port, rows, rounds=5)
    stats = (server.batcher.requests_total, server.batcher.batches_total)
    server.stop(0)

    # Wire parity: every client got exactly its own rows back.
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(out, rows[i] * 2.0)
    # The HARD gate is structural — far fewer device launches than
    # requests (the quantity coalescing controls); the wall-clock ratio
    # (10 serial launches vs ~3-4 coalesced per volley, 100ms vs
    # ~30-40ms at 10ms/launch) is additionally asserted with margin for
    # scheduler jitter on a loaded 1-core runner.
    assert stats[1] < stats[0] / 2, stats
    assert t_lock / t_co > 2.0, (
        f"speedup {t_lock / t_co:.2f}x "
        f"(lock {t_lock*1e3:.1f}ms, coalesced {t_co*1e3:.1f}ms)"
    )


def test_coalescing_real_engine_parity_and_no_regression(served_engine):
    # The real engine behind the coalescing path: concurrent mixed-size
    # requests each get exactly their own slice of the shared batch.
    from tpu_dist_nn.serving import serve_engine

    engine, _, _ = served_engine
    server, port = serve_engine(
        engine, 0, host="127.0.0.1", coalesce=True, warm_rows=16
    )
    try:
        rng = np.random.default_rng(7)
        dim = engine.model.input_dim
        rows = [rng.uniform(0, 1, (1 + i % 3, dim)) for i in range(10)]
        _, outs = _round_trip_rounds(port, rows, rounds=3)
        for i, out in enumerate(outs):
            want = np.asarray(engine.infer(rows[i]), np.float64)
            np.testing.assert_allclose(out, want, rtol=1e-6, atol=1e-9)
        assert server.batcher.batches_total < server.batcher.requests_total
    finally:
        server.stop(0)


def test_coalescing_dim_mismatch_fails_alone(served_engine):
    # A wrong-width request must abort with INVALID_ARGUMENT without
    # poisoning the shared batch of concurrent good requests.
    import grpc

    from tpu_dist_nn.serving import GrpcClient, serve_engine

    engine, _, _ = served_engine
    server, port = serve_engine(engine, 0, host="127.0.0.1", coalesce=True)
    try:
        dim = engine.model.input_dim
        good = GrpcClient(f"127.0.0.1:{port}")
        bad = GrpcClient(f"127.0.0.1:{port}")
        with pytest.raises(grpc.RpcError) as e:
            bad.process(np.zeros((1, dim + 3)))
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        out = good.process(np.zeros((2, dim)))
        assert out.shape[0] == 2
    finally:
        server.stop(0)


def test_batcher_submit_timeout_on_wedged_engine():
    # A wedged engine (the tunneled-TPU hang mode) must surface as
    # DEADLINE_EXCEEDED on the affected RPCs instead of blocking the
    # gRPC worker thread forever — an unbounded wait would eventually
    # strand every worker and leave the server unable to return errors.
    import threading
    import time

    import grpc

    from tpu_dist_nn.serving import GrpcClient, serve_engine

    release = threading.Event()

    class WedgedEngine:
        def __init__(self):
            import dataclasses
            self.model = dataclasses.make_dataclass("M", ["input_dim"])(8)
            self.seen_rows = []

        def infer(self, x):
            release.wait(10.0)  # wedge until the test releases it
            self.seen_rows.append(np.asarray(x)[:, 0].tolist())
            return np.asarray(x)

    eng = WedgedEngine()
    server, port = serve_engine(
        eng, 0, host="127.0.0.1", coalesce=True, submit_timeout=0.3,
    )
    try:
        client = GrpcClient(f"127.0.0.1:{port}", timeout=5.0)
        t0 = time.monotonic()
        # First request occupies the batcher thread (wedged in infer);
        # the second sits in _pending, times out, and must be DISCARDED
        # at pop time rather than computed after recovery.
        waiter = threading.Thread(
            target=lambda: pytest.raises(grpc.RpcError, client.process,
                                         np.zeros((1, 8))),
            daemon=True,
        )
        waiter.start()
        time.sleep(0.05)  # let request 1 reach the wedged infer
        c2 = GrpcClient(f"127.0.0.1:{port}", timeout=5.0)
        with pytest.raises(grpc.RpcError) as exc_info:
            c2.process(np.full((1, 8), 7.0))
        assert exc_info.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
        # The bound must come from submit_timeout (0.3s), not the
        # client's 5s RPC deadline.
        assert time.monotonic() - t0 < 3.0
        waiter.join(timeout=5.0)

        # Unwedge: a fresh request must succeed, and the abandoned rows
        # (value 7.0) must never have been computed.
        release.set()
        out = c2.process(np.full((1, 8), 3.0))
        np.testing.assert_array_equal(out, np.full((1, 8), 3.0))
        assert not any(7.0 in rows for rows in eng.seen_rows), eng.seen_rows
        client.close()
        c2.close()
    finally:
        release.set()
        server.stop(0)


def test_batcher_width_guard_without_declared_input_dim():
    # Engine without model.input_dim: the handler cannot pre-validate,
    # so the batcher groups coalesced requests by feature width and
    # launches per group — a wrong-width request gets the ENGINE's own
    # dim error while concurrent well-formed requests still succeed.
    import grpc

    from tpu_dist_nn.serving import GrpcClient, serve_engine
    from tpu_dist_nn.utils.errors import InvalidArgumentError

    class NoDimEngine:
        def infer(self, x):
            x = np.asarray(x)
            if x.shape[1] != 8:
                raise InvalidArgumentError(
                    f"expected input of shape (N, 8), got {tuple(x.shape)}"
                )
            return x + 1.0

    server, port = serve_engine(NoDimEngine(), 0, host="127.0.0.1",
                                coalesce=True)
    try:
        from concurrent.futures import ThreadPoolExecutor

        clients = [GrpcClient(f"127.0.0.1:{port}") for _ in range(4)]
        xs = [np.zeros((1, 8)), np.zeros((1, 8)), np.zeros((1, 5)),
              np.zeros((1, 8))]

        def call(i):
            try:
                return clients[i].process(xs[i])
            except grpc.RpcError as e:
                return e

        with ThreadPoolExecutor(max_workers=4) as ex:
            outs = list(ex.map(call, range(4)))
        # The 5-wide request fails with the engine's dim error no
        # matter which batch it joined; every 8-wide request succeeds.
        assert isinstance(outs[2], grpc.RpcError)
        assert outs[2].code() == grpc.StatusCode.INVALID_ARGUMENT
        for i in (0, 1, 3):
            assert isinstance(outs[i], np.ndarray), outs[i]
            np.testing.assert_array_equal(outs[i], np.ones((1, 8)))
    finally:
        server.stop(0)


# ---- LM generation serving (VERDICT r5: the continuous-batching
# decoder behind the serving layer)


def _gen_setup():
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=24,
    )
    return cfg, init_transformer(jax.random.key(7), cfg)


def test_serve_generate_pipelined_parity_and_coalescing():
    # The overlapped round-robin pipelined decoder behind the gRPC
    # endpoint: token-for-token equal to the single-chip greedy decode,
    # and concurrent requests coalesce into the decoder's group slots
    # (batches < requests).
    from concurrent.futures import ThreadPoolExecutor

    from tpu_dist_nn.models.generate import generate
    from tpu_dist_nn.serving import GrpcClient, serve_lm_generate

    cfg, params = _gen_setup()
    rng = np.random.default_rng(8)
    prompts = rng.integers(0, 64, (8, 8))
    ref = np.asarray(generate(params, cfg, prompts, 6, temperature=0.0))

    server, port = serve_lm_generate(
        params, cfg, 0, max_new_tokens=6, prompt_len=8, num_stages=2,
        num_groups=2, host="127.0.0.1", warm_rows=8,
    )
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        out = client.generate(prompts)
        np.testing.assert_array_equal(out[:, :8], prompts)
        np.testing.assert_array_equal(out[:, 8:], ref)

        # Concurrency: one-row requests from many clients coalesce.
        clients = [GrpcClient(f"127.0.0.1:{port}") for _ in range(8)]

        def call(i):
            return clients[i].generate(prompts[i:i + 1])

        with ThreadPoolExecutor(max_workers=8) as ex:
            outs = list(ex.map(call, range(8)))
        for i, o in enumerate(outs):
            np.testing.assert_array_equal(o[0, 8:], ref[i])
        b = server.batcher
        assert b.requests_total >= 9 and b.batches_total < b.requests_total
    finally:
        server.stop(0)


def test_serve_generate_single_chip_and_validation():
    import grpc as _grpc

    from tpu_dist_nn.models.generate import generate
    from tpu_dist_nn.serving import GrpcClient, serve_lm_generate

    cfg, params = _gen_setup()
    rng = np.random.default_rng(9)
    prompts = rng.integers(0, 64, (3, 8))
    ref = np.asarray(generate(params, cfg, prompts, 4, temperature=0.0))
    server, port = serve_lm_generate(
        params, cfg, 0, max_new_tokens=4, prompt_len=8, host="127.0.0.1",
    )
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        np.testing.assert_array_equal(client.generate(prompts)[:, 8:], ref)
        # Wrong prompt length and non-integer ids fail ALONE with
        # INVALID_ARGUMENT (the reference's status taxonomy).
        with pytest.raises(_grpc.RpcError) as e:
            client.generate(np.zeros((1, 5)))
        assert e.value.code() == _grpc.StatusCode.INVALID_ARGUMENT
        with pytest.raises(_grpc.RpcError) as e:
            client.generate(np.full((1, 8), 0.5))
        assert e.value.code() == _grpc.StatusCode.INVALID_ARGUMENT
        with pytest.raises(_grpc.RpcError) as e:
            client.generate(np.full((1, 8), 99))
        assert e.value.code() == _grpc.StatusCode.INVALID_ARGUMENT
    finally:
        server.stop(0)


def test_serve_generate_validates_sampling_combo_at_construction():
    # ADVICE r5: a bad sampling combination must fail at server
    # construction with the validator's clear message, not surface as
    # per-RPC INTERNAL from inside the decode runner.
    from tpu_dist_nn.serving import serve_lm_generate

    cfg, params = _gen_setup()
    with pytest.raises(ValueError, match="top_k"):
        serve_lm_generate(
            params, cfg, 0, max_new_tokens=4, prompt_len=8,
            temperature=0.0, top_k=5, host="127.0.0.1",
        )
    with pytest.raises(ValueError, match="max_seq_len"):
        serve_lm_generate(
            params, cfg, 0, max_new_tokens=18, prompt_len=8,
            host="127.0.0.1",
        )
    # The boundary the decoders actually support (total-1 positions)
    # constructs fine: prompt 8 + new 17 on max_seq_len 24.
    server, port = serve_lm_generate(
        params, cfg, 0, max_new_tokens=17, prompt_len=8, host="127.0.0.1",
    )
    server.stop(0)


def test_serve_generate_sampled_draws_fresh_continuations():
    # temperature > 0: repeated identical prompts must NOT replay the
    # same continuation (the endpoint folds a batch counter into the
    # key) — and every returned id stays in-vocab.
    from tpu_dist_nn.serving import GrpcClient, serve_lm_generate

    cfg, params = _gen_setup()
    prompts = np.full((2, 8), 3)
    server, port = serve_lm_generate(
        params, cfg, 0, max_new_tokens=8, prompt_len=8,
        temperature=1.0, host="127.0.0.1",
    )
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        a = client.generate(prompts)
        bb = client.generate(prompts)
        assert not np.array_equal(a, bb)
        assert (a[:, 8:] >= 0).all() and (a[:, 8:] < 64).all()
    finally:
        server.stop(0)


def test_cli_lm_serve_generate_end_to_end():
    # `tdn lm --serve-generate`: train, serve, decode over the wire —
    # the port comes from the JSON line printed before blocking.
    import socket
    import threading

    from tpu_dist_nn.serving import GrpcClient

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    from tpu_dist_nn.cli import main

    t = threading.Thread(
        target=main,
        args=([
            "--platform", "cpu", "lm", "--steps", "2", "--batch-size",
            "4", "--seq-len", "24", "--d-model", "16", "--heads", "2",
            "--layers", "2", "--serve-generate", str(port),
            "--serve-stages", "2", "--serve-prompt-len", "8",
            "--serve-new-tokens", "4", "--temperature", "0",
            "--serve-seconds", "20", "--eval-batches", "4",
        ],),
        daemon=True,
    )
    t.start()
    client = GrpcClient(f"127.0.0.1:{port}", timeout=15.0)
    prompts = np.full((2, 8), 7)
    deadline = time.monotonic() + 90
    out = None
    while time.monotonic() < deadline:
        try:
            out = client.generate(prompts)
            break
        except Exception:
            time.sleep(1.0)
    assert out is not None, "server never came up"
    assert out.shape == (2, 12)
    assert (out[:, :8] == 7).all()
