"""ZB-H1 zero-bubble schedule: split-backward tables + executor parity.

The design claim being proven: the table executor is schedule-agnostic
(interleaved.py docstring), so zero-bubble arrives as ONE new table
builder (schedule_table.build_zero_bubble) — the builder splits
backward into input-grad (BWD_B, critical path) and weight-grad
(BWD_W, no consumer) and parks W ops in bubble ticks. Structure is
verified by the symbolic replay at build time; these tests add the
bubble accounting (halved vs 1F1B), the memory bound, numerical grad
parity vs single-chip AD, and the schedule x sharding composition.
"""

import jax
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    init_transformer,
    lm_loss,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.schedule_table import (
    BWD_B,
    BWD_W,
    build_interleaved_1f1b,
    build_zero_bubble,
)
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_lm_zb_grad,
    shard_blocks_interleaved,
    unshard_blocks_interleaved,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq_len=16
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return np.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), np.int32)


@pytest.mark.parametrize("S,v,M", [(2, 1, 4), (4, 1, 8), (3, 1, 5), (2, 2, 4), (1, 1, 3)])
def test_zb_tables_build_and_verify(S, v, M):
    tb = build_zero_bubble(S, v, M)  # verify_tables runs inside
    # Split accounting: 3 ops per (chunk, microbatch).
    assert int((tb.op != 0).sum()) == 3 * S * v * M
    assert int((tb.op == BWD_B).sum()) == int((tb.op == BWD_W).sum())


def test_zb_halves_the_1f1b_bubble():
    """The headline: at v=1 the ZB-H1 bubble is S-1 ticks — HALF of
    1F1B's 2(S-1) — and the win comes precisely from decoupling W
    (the coupled control arm, same split accounting, stays at
    2(S-1))."""
    for S, M in [(2, 4), (4, 8), (8, 16)]:
        zb = build_zero_bubble(S, 1, M)
        coupled = build_zero_bubble(S, 1, M, couple_w=True)
        fb = build_interleaved_1f1b(S, 1, M)
        assert zb.bubble_ticks == S - 1, (S, M, zb.bubble_ticks)
        assert coupled.bubble_ticks == 2 * (S - 1), (S, M, coupled.bubble_ticks)
        assert fb.bubble_ticks == 2 * (S - 1), (S, M, fb.bubble_ticks)


def test_zb_memory_stays_o_stages():
    """ZB-H1's price is memory held longer, not more of it
    asymptotically: the W-backlog cap keeps the input stash (held
    F -> W) within ~3S slots and the cotangent stash (B -> W) within
    ~S, both INDEPENDENT of the microbatch count (without the cap the
    steady state defers every W to the drain and the stash is M)."""
    for S, M in [(2, 16), (4, 32), (8, 32), (4, 64)]:
        tb = build_zero_bubble(S, 1, M)
        assert tb.stash_slots <= 3 * S, (S, M, tb.stash_slots)
        assert tb.dybuf_slots <= S + 1, (S, M, tb.dybuf_slots)


@pytest.mark.parametrize("S,v,M,data", [(2, 1, 4, 2), (4, 1, 4, 2), (2, 2, 4, 1)])
def test_zb_grads_match_single_chip(S, v, M, data):
    mesh = build_mesh(MeshSpec(stage=S, data=data))
    params = init_transformer(jax.random.key(1), CFG)
    tokens = _tokens(batch=M * 2 * max(1, data // 2), seq=16, seed=2)

    vag = make_pipeline_lm_zb_grad(mesh, CFG, num_virtual=v, num_microbatches=M)
    params_v = dict(
        params, blocks=shard_blocks_interleaved(params["blocks"], S, v)
    )
    loss_zb, g = jax.jit(vag)(params_v, tokens)
    loss_ref, gref = jax.jit(
        jax.value_and_grad(lm_loss), static_argnums=2
    )(params, tokens, CFG)
    np.testing.assert_allclose(float(loss_ref), float(loss_zb), rtol=1e-5)
    g_blocks = unshard_blocks_interleaved(g["blocks"])
    for k in gref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(gref[k]), np.asarray(g[k]), rtol=5e-4, atol=1e-5,
        )


def test_zb_tp_grads_match_single_chip():
    # The full matrix: zero-bubble x Megatron TP (the split W op adds
    # no wire traffic, so the model-invariance argument carries over).
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_tp_lm_zb_grad,
        shard_blocks_interleaved_tp,
        unshard_blocks_interleaved_tp,
    )

    S, model, v = 2, 2, 1
    mesh = build_mesh(MeshSpec(stage=S, model=model, data=2))
    params = init_transformer(jax.random.key(3), CFG)
    tokens = _tokens(batch=8, seq=16, seed=4)

    vag = make_pipeline_tp_lm_zb_grad(mesh, CFG, num_virtual=v, num_microbatches=2)
    params_3d = dict(
        params,
        blocks=shard_blocks_interleaved_tp(params["blocks"], CFG, S, v, model),
    )
    loss_zb, g = jax.jit(vag)(params_3d, tokens)
    loss_ref, gref = jax.jit(
        jax.value_and_grad(lm_loss), static_argnums=2
    )(params, tokens, CFG)
    np.testing.assert_allclose(float(loss_ref), float(loss_zb), rtol=1e-5)
    g_blocks = unshard_blocks_interleaved_tp(g["blocks"], CFG)
    for k in gref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5,
        )


def test_zb_is_lm_only():
    # The dense classifier pipeline has no split-backward executor:
    # schedule='zb' must be rejected there, not silently trained as
    # gpipe (which would let a user benchmark the wrong schedule).
    import optax

    from tpu_dist_nn.train.pipeline_trainer import make_pipeline_train_step

    with pytest.raises(ValueError, match="zb.*LM|transformer LM"):
        make_pipeline_train_step(None, None, 2, optax.adam(1e-3), schedule="zb")


def test_zb_train_step_runs():
    import optax

    from tpu_dist_nn.train.lm_trainer import make_pipeline_lm_train_step

    S = 2
    mesh = build_mesh(MeshSpec(stage=S, data=2))
    params = init_transformer(jax.random.key(5), CFG)
    params_v = dict(
        params, blocks=shard_blocks_interleaved(params["blocks"], S, 1)
    )
    optimizer = optax.adam(1e-2)
    step = make_pipeline_lm_train_step(
        mesh, CFG, S, 2, optimizer, schedule="zb", num_virtual=1
    )
    tokens = _tokens(batch=8, seq=16, seed=6)
    new_params, _, loss = step(params_v, optimizer.init(params_v), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_qkv"]),
        np.asarray(params_v["blocks"]["w_qkv"]),
    )


# ---- zb-stash: the cotangent-stash split (round 5) ----


@pytest.mark.parametrize("stage,v,M", [(2, 1, 4), (4, 1, 4), (2, 2, 2)])
def test_zb_stash_grads_match_single_chip(stage, v, M):
    # The TRUE zero-bubble executor: ZB-H1 tables with BWD_B stashing
    # per-op (act, cot) pairs and BWD_W as pure dW GEMMs (no forward
    # recompute — parallel/split_backward.py). Loss AND grads must
    # equal single-chip AD exactly like every other schedule.
    from tpu_dist_nn.models.transformer import lm_loss
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_lm_zb_stash_grad,
        unshard_blocks_interleaved,
    )

    params = init_transformer(jax.random.key(31), CFG)
    tokens = _tokens(batch=8, seq=16, seed=32)
    v_ref, g_ref = jax.jit(jax.value_and_grad(
        lambda p, t: lm_loss(p, t, CFG)
    ))(params, tokens)

    mesh = build_mesh(MeshSpec(stage=stage))
    p_st = dict(
        params, blocks=shard_blocks_interleaved(params["blocks"], stage, v)
    )
    vag = make_pipeline_lm_zb_stash_grad(mesh, CFG, v, M)
    val, g = jax.jit(vag)(p_st, tokens)
    np.testing.assert_allclose(float(val), float(v_ref), rtol=1e-5)
    g_blocks = unshard_blocks_interleaved(g["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )


def test_zb_stash_train_step_and_cli(capsys):
    import optax

    from tpu_dist_nn.cli import main
    from tpu_dist_nn.train.lm_trainer import make_pipeline_lm_train_step

    S = 2
    mesh = build_mesh(MeshSpec(stage=S, data=2))
    params = init_transformer(jax.random.key(7), CFG)
    params_v = dict(
        params, blocks=shard_blocks_interleaved(params["blocks"], S, 1)
    )
    optimizer = optax.adam(1e-2)
    step = make_pipeline_lm_train_step(
        mesh, CFG, S, 2, optimizer, schedule="zb-stash", num_virtual=1
    )
    tokens = _tokens(batch=8, seq=16, seed=8)
    new_params, _, loss = step(params_v, optimizer.init(params_v), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_qkv"]),
        np.asarray(params_v["blocks"]["w_qkv"]),
    )

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "8",
        "--seq-len", "24", "--d-model", "16", "--heads", "2",
        "--layers", "4", "--stages", "2", "--microbatches", "4",
        "--schedule", "zb-stash", "--eval-batches", "2",
    ])
    assert rc == 0
    assert "final_train_loss" in capsys.readouterr().out


def test_zb_stash_rejects_compositions():
    # Dense-LM only: the stash split knows the dense block structure.
    import optax

    from tpu_dist_nn.train.lm_trainer import (
        make_pipeline_lm_train_step,
        make_pipeline_moe_lm_train_step,
        make_pipeline_sp_lm_train_step,
    )

    mesh = build_mesh(MeshSpec(stage=2, model=2))
    with pytest.raises(ValueError, match="dense-LM only"):
        make_pipeline_lm_train_step(
            mesh, CFG, 2, 2, optax.adam(1e-3), schedule="zb-stash",
            tensor_parallel=2,
        )
    mesh_sp = build_mesh(MeshSpec(stage=2, seq=2))
    with pytest.raises(ValueError, match="dense-LM only"):
        make_pipeline_sp_lm_train_step(
            mesh_sp, CFG, 2, 2, optax.adam(1e-3), schedule="zb-stash"
        )
    with pytest.raises(ValueError, match="dense-LM only"):
        make_pipeline_moe_lm_train_step(
            mesh, None, 2, 2, optax.adam(1e-3), schedule="zb-stash"
        )
