"""ZB-V: zero-bubble on the V-shape placement.

The placement claim the executor design makes — "any future schedule
is a new table builder" — is stressed here harder than by ZB-H1: the
V placement's second leg sends FORWARD activations on the reverse
ring, its apex hand-off is device-LOCAL (the self loopback channel),
and one device can receive on multiple physical channels in one tick
(the channel-major receive tables). Structure is verified by the
symbolic replay at build time (which models all three channels);
these tests add the bubble accounting vs the same-granularity
alternatives, placement properties, grad parity vs single-chip AD,
and the trainer/CLI wiring.
"""

import jax
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    init_transformer,
    lm_loss,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.schedule_table import (
    BWD_B,
    BWD_W,
    FWD,
    build_interleaved_1f1b,
    build_zb_v,
    build_zero_bubble,
)
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_lm_zb_v_grad,
    shard_blocks_vshape,
    unshard_blocks_vshape,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=8, d_ff=64, max_seq_len=16
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return np.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), np.int32)


@pytest.mark.parametrize("S,M", [(2, 2), (2, 4), (4, 4), (4, 8), (3, 5), (8, 8)])
def test_zb_v_tables_build_and_verify(S, M):
    tb = build_zb_v(S, M)  # symbolic replay runs inside
    V = 2 * S
    assert tb.placement == "vshape"
    assert tb.num_chunks == V
    # Split accounting: 3 ops per (chunk, microbatch).
    assert int((tb.op != 0).sum()) == 3 * V * M
    assert int((tb.op == BWD_B).sum()) == int((tb.op == BWD_W).sum())


def test_zb_v_beats_same_granularity_schedules():
    """The headline measurement, at the SAME chunk granularity (v=2 —
    every schedule here runs 2S chunks of L/(2S) layers, so a tick
    costs the same wall time): ZB-V's bubble is S-1 chunk-ticks
    INDEPENDENT of M, always < interleaved 1F1B's 2(S-1), and <=
    ZB-H1's everywhere — strictly smaller in the small-M regime
    (M = S: H1 pays 2S-3) where H1 hasn't amortized its warmup, equal
    once M grows past it. Measured, not asserted from the paper."""
    for S, M, h1_strict in [(2, 4, False), (4, 4, True), (8, 8, True),
                            (4, 8, False)]:
        vshape = build_zb_v(S, M)
        h1 = build_zero_bubble(S, 2, M)
        il = build_interleaved_1f1b(S, 2, M)
        assert vshape.bubble_ticks == S - 1, (S, M, vshape.bubble_ticks)
        assert vshape.bubble_ticks <= h1.bubble_ticks, (
            S, M, vshape.bubble_ticks, h1.bubble_ticks,
        )
        if h1_strict:
            assert vshape.bubble_ticks < h1.bubble_ticks, (
                S, M, vshape.bubble_ticks, h1.bubble_ticks,
            )
        assert vshape.bubble_ticks < il.bubble_ticks, (
            S, M, vshape.bubble_ticks, il.bubble_ticks,
        )
        # ...at comparable memory: same-order stash footprint.
        assert vshape.stash_slots <= h1.stash_slots + S


def test_zb_v_placement_properties():
    """What the V buys structurally: chunk 0 (input feed) and chunk
    V-1 (loss tail) are co-located on device 0, and the apex hand-off
    (chunk S-1 -> S) crosses no wire (self loopback)."""
    S, M = 4, 4
    tb = build_zb_v(S, M)
    assert tb.dev_of_chunk(0) == 0
    assert tb.dev_of_chunk(2 * S - 1) == 0
    assert tb.dev_of_chunk(S - 1) == S - 1 and tb.dev_of_chunk(S) == S - 1
    # The self channel is actually used (the apex FWD hand-off) and
    # feed/tail sit on device 0's rows.
    assert (tb.selfch_dst >= 0).any()
    feeds = (tb.op == FWD) & (tb.abuf_read == -1)
    assert feeds[0].any() and not feeds[1:].any()
    tails = ((tb.op == BWD_B)) & (tb.gbuf_read == -1)
    assert tails[0].any() and not tails[1:].any()


def test_schedule_viz_renders():
    # The ASCII renderer exercises the routing accessors on both
    # placements (tools/schedule_viz.py).
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "schedule_viz",
        pathlib.Path(__file__).parent.parent / "tools" / "schedule_viz.py",
    )
    viz = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(viz)
    out = viz.render(build_zb_v(4, 4))
    assert "placement=vshape" in out and "o" in out and "<" in out
    out = viz.render(build_zero_bubble(4, 2, 4))
    assert "placement=megatron" in out and "<" not in out


def test_zb_v_shard_roundtrip():
    params = init_transformer(jax.random.key(0), CFG)
    staged = shard_blocks_vshape(params["blocks"], 2)
    # L=8, S=2: (S, 2, L/(2S)=2, ...)
    assert staged["w_qkv"].shape[:3] == (2, 2, 2)
    back = unshard_blocks_vshape(staged)
    for k, v in params["blocks"].items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(back[k]))
    with pytest.raises(ValueError, match="divisible"):
        shard_blocks_vshape(params["blocks"], 3)


@pytest.mark.parametrize("S,M,data", [(2, 2, 2), (4, 4, 2)])
def test_zb_v_grads_match_single_chip(S, M, data):
    mesh = build_mesh(MeshSpec(stage=S, data=data))
    params = init_transformer(jax.random.key(1), CFG)
    tokens = _tokens(batch=M * 2 * max(1, data // 2), seq=16, seed=2)

    vag = make_pipeline_lm_zb_v_grad(mesh, CFG, num_microbatches=M)
    params_v = dict(params, blocks=shard_blocks_vshape(params["blocks"], S))
    loss_v, g = jax.jit(vag)(params_v, tokens)
    loss_ref, gref = jax.jit(
        jax.value_and_grad(lm_loss), static_argnums=2
    )(params, tokens, CFG)
    np.testing.assert_allclose(float(loss_ref), float(loss_v), rtol=1e-5)
    g_blocks = unshard_blocks_vshape(g["blocks"])
    for k in gref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(gref[k]), np.asarray(g[k]), rtol=5e-4, atol=1e-5,
        )


def _masked_ce(params, tokens):
    from tpu_dist_nn.models.transformer import forward

    logits = forward(params, tokens, CFG)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(np.float32), axis=-1)
    targets = tokens[:, 1:]
    import jax.numpy as jnp

    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


@pytest.mark.parametrize("composition", ["tp", "sp", "tp_sp"])
def test_zb_v_compositions_match_single_chip(composition):
    # The V-placement tables at 2/3/4D: TP psums, the SP ring's
    # group-local rotation, and their conjunction all execute inside
    # the V schedule's switch branches — same disjoint-axis arguments
    # as the other schedules, now on cross-ring/self wires. Grad
    # parity vs single-chip AD through the shared oracles.
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_sp_lm_zb_v_grad,
        make_pipeline_tp_lm_zb_v_grad,
        make_pipeline_tp_sp_lm_zb_v_grad,
        shard_blocks_vshape_tp,
        unshard_blocks_vshape_tp,
    )

    params = init_transformer(jax.random.key(5), CFG)
    tokens = np.asarray(_tokens(batch=4, seq=16, seed=6))
    import jax.numpy as jnp

    tokens = jnp.asarray(tokens)
    if composition == "tp":
        mesh = build_mesh(MeshSpec(stage=2, model=2, data=2))
        vag = make_pipeline_tp_lm_zb_v_grad(mesh, CFG, num_microbatches=2)
        params_v = dict(
            params, blocks=shard_blocks_vshape_tp(params["blocks"], CFG, 2, 2)
        )
        loss_ref, g_ref = jax.jit(jax.value_and_grad(lm_loss), static_argnums=2)(
            params, tokens, CFG
        )
        unshard = lambda b: unshard_blocks_vshape_tp(b, CFG)  # noqa: E731
    elif composition == "sp":
        mesh = build_mesh(MeshSpec(stage=2, seq=2, data=2))
        vag = make_pipeline_sp_lm_zb_v_grad(
            mesh, CFG, num_microbatches=2, mode="ring"
        )
        params_v = dict(params, blocks=shard_blocks_vshape(params["blocks"], 2))
        loss_ref, g_ref = jax.jit(jax.value_and_grad(_masked_ce))(params, tokens)
        unshard = unshard_blocks_vshape
    else:
        mesh = build_mesh(MeshSpec(stage=2, model=2, seq=2))
        vag = make_pipeline_tp_sp_lm_zb_v_grad(
            mesh, CFG, num_microbatches=2, mode="ring"
        )
        params_v = dict(
            params, blocks=shard_blocks_vshape_tp(params["blocks"], CFG, 2, 2)
        )
        loss_ref, g_ref = jax.jit(jax.value_and_grad(_masked_ce))(params, tokens)
        unshard = lambda b: unshard_blocks_vshape_tp(b, CFG)  # noqa: E731

    loss_v, g_v = jax.jit(vag)(params_v, tokens)
    np.testing.assert_allclose(float(loss_ref), float(loss_v), rtol=1e-5)
    g_blocks = unshard(g_v["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_v[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )


def test_zb_v_ep_matches_grouped_oracle():
    # ZB-V x expert parallelism: the aux channel on the V tables.
    from tpu_dist_nn.parallel.expert_parallel import (
        MoEConfig,
        init_moe_transformer,
        make_pipeline_ep_lm_zb_v_grad,
        moe_lm_loss,
        shard_blocks_vshape_ep,
        unshard_blocks_vshape_ep,
    )

    ECFG = MoEConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=32, n_experts=4, router_top_k=1,
    )
    S, expert, M = 2, 2, 2
    mesh = build_mesh(MeshSpec(stage=S, expert=expert, data=1))
    params = init_moe_transformer(jax.random.key(7), ECFG)
    n_groups = M * expert
    rng = np.random.default_rng(8)
    import jax.numpy as jnp

    tokens = jnp.asarray(
        rng.integers(0, ECFG.vocab_size, (2 * n_groups, 17)), jnp.int32
    )

    vag = make_pipeline_ep_lm_zb_v_grad(mesh, ECFG, num_microbatches=M)
    params_v = dict(
        params, blocks=shard_blocks_vshape_ep(params["blocks"], S, expert)
    )
    v_pp, g_pp = jax.jit(vag)(params_v, tokens)
    v_ref, g_ref = jax.jit(
        jax.value_and_grad(
            lambda p, t: moe_lm_loss(p, t, ECFG, n_groups=n_groups)
        )
    )(params, tokens)
    np.testing.assert_allclose(float(v_ref), float(v_pp), rtol=1e-5)
    g_blocks = unshard_blocks_vshape_ep(g_pp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )


def test_zb_v_train_step_and_cli(capsys):
    import optax

    from tpu_dist_nn.cli import main
    from tpu_dist_nn.train.lm_trainer import make_pipeline_lm_train_step

    mesh = build_mesh(MeshSpec(stage=2, data=2))
    params = init_transformer(jax.random.key(3), CFG)
    params_v = dict(params, blocks=shard_blocks_vshape(params["blocks"], 2))
    optimizer = optax.adam(1e-2)
    step = make_pipeline_lm_train_step(
        mesh, CFG, 2, 2, optimizer, schedule="zb-v"
    )
    tokens = _tokens(batch=8, seq=16, seed=4)
    new_params, _, loss = step(params_v, optimizer.init(params_v), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_qkv"]),
        np.asarray(params_v["blocks"]["w_qkv"]),
    )
    # ZB-V x TP trains through the trainer API too.
    from tpu_dist_nn.parallel.transformer_pipeline import (
        shard_blocks_vshape_tp,
    )

    mesh_tp = build_mesh(MeshSpec(stage=2, model=2, data=2))
    step_tp = make_pipeline_lm_train_step(
        mesh_tp, CFG, 2, 2, optimizer, schedule="zb-v", tensor_parallel=2
    )
    params_tp = dict(
        params, blocks=shard_blocks_vshape_tp(params["blocks"], CFG, 2, 2)
    )
    _, _, loss_tp = step_tp(params_tp, optimizer.init(params_tp), tokens)
    assert np.isfinite(float(loss_tp)) and float(loss_tp) > 0
    # End to end: tdn lm --schedule zb-v (8 layers over 2 stages x 2
    # legs); the trained params come back unsharded.
    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "16", "--d-model", "16", "--heads", "2",
        "--layers", "8", "--stages", "2", "--microbatches", "2",
        "--schedule", "zb-v",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out
