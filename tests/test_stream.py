"""Streaming plane (ISSUE 16: serving/stream.py + GenerateStream):
frame codec, TokenStream channel semantics (sent-cursor dedupe,
overflow-cancel, terminal ordering), streamed-vs-unary greedy bit
parity over the loopback wire (incl. EOS freeze and per-request
budgets), the router-hop quick smoke (first token before retirement),
cancel-storm slot/prefix-ref reclamation, mid-stream replica-kill
replay-resume with exactly-once delivery, and the hedging exemption."""

import time

import grpc
import jax
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from tpu_dist_nn.serving.continuous import ContinuousScheduler
from tpu_dist_nn.serving.server import GrpcClient, serve_lm_generate
from tpu_dist_nn.serving.stream import TokenStream
from tpu_dist_nn.serving.wire import (
    decode_frame,
    encode_end_frame,
    encode_token_frame,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    max_seq_len=24,
)
PARAMS = init_transformer(jax.random.key(7), CFG)
T, N = 8, 10


def _prompt(seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, (1, T))


def _drain(stream, timeout=30.0):
    """Consume a TokenStream in-process: (tokens list, end dict)."""
    toks, end = [], None
    while True:
        ev = stream.next_event(timeout)
        assert ev is not None, "stream stalled"
        kind, data = ev
        if kind == "tokens":
            toks.extend(data)
        else:
            end = data
            break
    return toks, end


# ------------------------------------------------------------- codec


def test_frame_codec_roundtrips_and_rejects_garbage():
    kind, ids = decode_frame(encode_token_frame([0, 5, 63, 1 << 20]))
    assert kind == "tokens" and ids == [0, 5, 63, 1 << 20]
    kind, data = decode_frame(encode_end_frame("eos", "OK", "done"))
    assert kind == "end"
    assert data == {"reason": "eos", "code": "OK", "message": "done"}
    # Empty strings survive the roundtrip (the common END payload).
    assert decode_frame(encode_end_frame("max_tokens"))[1] == {
        "reason": "max_tokens", "code": "", "message": ""}
    with pytest.raises(ValueError):
        decode_frame(b"")
    with pytest.raises(ValueError):
        decode_frame(bytes((9, 1, 2)))  # unknown frame type
    with pytest.raises(ValueError):
        decode_frame(encode_token_frame([1, 2, 300])[:-1])  # truncated
    with pytest.raises(ValueError):
        decode_frame(encode_token_frame([1]) + b"\x00")  # trailing


# ------------------------------------------------- TokenStream channel


def test_token_stream_cursor_dedupes_replayed_prefix():
    # publish() receives the FULL known-token list every time (the
    # scheduler hands it occ["tokens"]); the sent cursor must emit
    # each token exactly once even when the prefix is republished
    # (preemption replay, failover resume).
    s = TokenStream()
    assert s.publish([1, 2, 3])
    assert s.publish([1, 2, 3, 4])
    assert s.next_event(1.0) == ("tokens", [1, 2, 3, 4])
    assert s.delivered == 4
    assert s.publish([1, 2, 3, 4]) and s.next_event(0.02) is None
    # seed(): the client already holds 2 tokens (resume), so only the
    # unseen suffix flows.
    s2 = TokenStream()
    s2.seed(2)
    assert s2.publish([7, 8, 9])
    assert s2.next_event(1.0) == ("tokens", [9])


def test_token_stream_terminal_after_pending_and_first_finish_wins():
    s = TokenStream()
    s.publish([1, 2])
    s.finish("eos")
    s.finish("max_tokens", message="late loser")
    # Pending tokens drain BEFORE the terminal, and the first finish
    # wins — the ordering the handler's flush loop relies on.
    assert s.next_event(1.0) == ("tokens", [1, 2])
    assert s.next_event(1.0) == (
        "end", {"reason": "eos", "code": "", "message": ""})


def test_token_stream_overflow_and_cancel_flip_the_channel():
    s = TokenStream(max_buffer=2)
    assert s.publish([1, 2]) is True
    assert s.publish([1, 2, 3, 4, 5]) is False  # consumer wedged
    assert s.cancelled
    s2 = TokenStream()
    s2.cancel()
    assert s2.publish([1]) is False  # scheduler's cue to reap the row
    kind, data = s2.next_event(1.0)
    assert kind == "end" and data["code"] == "CANCELLED"


# ------------------------------------------------------ wire parity


def test_streamed_greedy_bit_identical_to_unary_loopback():
    # Acceptance core: at temperature 0 the streamed tokens are the
    # unary Generate tail, bit for bit, through the real wire —
    # including EOS freeze (early retire on eos_id).
    prompt = _prompt(1)
    srv, port = serve_lm_generate(
        PARAMS, CFG, 0, max_new_tokens=N, prompt_len=T,
        host="127.0.0.1",
    )
    try:
        c = GrpcClient(f"127.0.0.1:{port}")
        want = c.generate(prompt)[0, T:]
        reply = c.generate_stream(prompt)
        got = np.asarray(list(reply))
        np.testing.assert_array_equal(got, want)
        assert reply.finish["reason"] == "max_tokens"
        # Satellite: the server trace id rides the INITIAL metadata —
        # available while the stream is still flowing.
        assert reply.trace_id
        c.close()
    finally:
        srv.stop(0)
    # EOS freeze: pick an eos the reference actually emits mid-stream,
    # re-serve with it, and the stream must retire early at exactly
    # the unary truncation point.
    eos = int(want[N // 2])
    srv, port = serve_lm_generate(
        PARAMS, CFG, 0, max_new_tokens=N, prompt_len=T,
        host="127.0.0.1", eos_id=eos,
    )
    try:
        c = GrpcClient(f"127.0.0.1:{port}")
        tail = c.generate(prompt)[0, T:]
        stop = int(np.argmax(tail == eos))
        reply = c.generate_stream(prompt)
        got = np.asarray(list(reply))
        np.testing.assert_array_equal(got, tail[:stop + 1])
        assert reply.finish["reason"] == "eos"
        c.close()
    finally:
        srv.stop(0)


def test_stream_per_request_budget_matches_unary():
    # Per-request max_new_tokens caps the stream exactly like the
    # unary path: same tokens, "max_tokens" terminal at the cap.
    sched = ContinuousScheduler(
        PARAMS, CFG, slots=2, prompt_len=T, max_new_tokens=N,
    )
    try:
        prompt = _prompt(2)
        want = sched.submit(prompt, max_new_tokens=4)[0, T:T + 4]
        stream = sched.submit_stream(prompt, max_new_tokens=4)
        toks, end = _drain(stream)
        np.testing.assert_array_equal(np.asarray(toks), want)
        assert end["reason"] == "max_tokens" and len(toks) == 4
    finally:
        sched.close()


# ----------------------------------------------------- router smokes


def _lm_replicas(n):
    servers, targets = [], []
    for _ in range(n):
        srv, port = serve_lm_generate(
            PARAMS, CFG, 0, max_new_tokens=N, prompt_len=T,
            host="127.0.0.1",
        )
        servers.append(srv)
        targets.append(f"127.0.0.1:{port}")
    return servers, targets


def _teardown(rsrv, servers, pool, targets):
    from tpu_dist_nn.serving.resilience import CircuitBreaker

    rsrv.stop(0)
    for s in servers:
        s.stop(0)
    pool.close()
    for t in targets:
        CircuitBreaker.evict(t)


def test_stream_first_token_before_retirement_through_router():
    # The quick-tier smoke: a stream through the ROUTER hop delivers
    # its first token while the row is still decoding (streaming's
    # reason to exist — run-to-completion could only return at
    # retirement), and the full stream bit-matches unary Generate
    # through the same hop.
    from tpu_dist_nn.serving.pool import ReplicaPool
    from tpu_dist_nn.serving.router import serve_router

    servers, targets = _lm_replicas(1)
    pool = ReplicaPool(targets, scrape_interval=30.0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    try:
        c = GrpcClient(f"127.0.0.1:{rport}")
        prompt = _prompt(3)
        want = c.generate(prompt)[0, T:]
        reply = c.generate_stream(prompt)
        it = iter(reply)
        first = next(it)
        # The first token crossed two hops while the request still
        # owns its decode slot: delivery is mid-generation, not
        # post-retirement.
        assert servers[0].scheduler.slots_active >= 1
        got = np.asarray([first] + list(it))
        np.testing.assert_array_equal(got, want)
        assert reply.finish["reason"] == "max_tokens"
        assert reply.trace_id
        c.close()
    finally:
        _teardown(rsrv, servers, pool, targets)


def test_cancel_storm_releases_slots_and_prefix_refs():
    # Satellite: a client abandoning mid-stream must free the decode
    # slot and drop prefix-cache refs at the next scheduler iteration
    # — a storm of cancels leaves slots_active (the
    # tdn_gen_slots_active source) at 0 with every block refcount 0.
    srv, port = serve_lm_generate(
        PARAMS, CFG, 0, max_new_tokens=16, prompt_len=T,
        host="127.0.0.1", gen_slots=2, prefix_cache_blocks=4,
    )
    sched = srv.scheduler
    try:
        c = GrpcClient(f"127.0.0.1:{port}")
        for i in range(4):
            reply = c.generate_stream(_prompt(10 + i))
            it = iter(reply)
            next(it)  # first token: the row is live in a slot
            reply.cancel()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            if (sched.slots_active == 0
                    and not any(sched._pool._refs)):
                break
            time.sleep(0.05)
        assert sched.slots_active == 0
        assert not any(sched._pool._refs), "leaked prefix-cache refs"
        c.close()
    finally:
        srv.stop(0)


def test_mid_stream_replica_kill_resumes_exactly_once():
    # The failover acceptance: kill the serving replica mid-stream
    # (injected UNAVAILABLE under the decode loop) and the router
    # re-places with the delivered prefix as forced-token replay —
    # the client sees every token exactly once, bit-identical to an
    # unkilled run, across the replica switch.
    from tpu_dist_nn.serving.pool import ReplicaPool
    from tpu_dist_nn.serving.router import (
        ROUTER_STREAM_RESUMES,
        serve_router,
    )
    from tpu_dist_nn.testing import faults

    servers, targets = _lm_replicas(2)
    pool = ReplicaPool(targets, scrape_interval=30.0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    try:
        prompt = _prompt(4)
        # Reference from the healthy replica directly: both replicas
        # hold the same params, so temp-0 output is fleet-invariant.
        ref_c = GrpcClient(targets[1])
        want = ref_c.generate(prompt)[0, T:]
        ref_c.close()

        resumed_before = sum(
            c.value for _, c in ROUTER_STREAM_RESUMES.samples())
        # Pin the session to replica 0, then blow it up mid-decode.
        pool.pin("doomed", targets[0])
        plan = faults.FaultPlan(at={4: faults.unavailable()})
        servers[0].scheduler.launch_hook = plan.fire

        c = GrpcClient(f"127.0.0.1:{rport}", session_key="doomed")
        reply = c.generate_stream(prompt)
        got = np.asarray(list(reply))
        np.testing.assert_array_equal(got, want)
        assert reply.finish["reason"] == "max_tokens"
        resumed_after = sum(
            c.value for _, c in ROUTER_STREAM_RESUMES.samples())
        assert resumed_after >= resumed_before + 1
        c.close()
    finally:
        _teardown(rsrv, servers, pool, targets)


# -------------------------------------------------- hedging exemption


def test_hedge_policy_rejects_generate_stream():
    from tpu_dist_nn.serving.router import HedgePolicy

    with pytest.raises(ValueError, match="replay-resume"):
        HedgePolicy(methods=("Process", "GenerateStream"))
    HedgePolicy(methods=("Process", "Generate"))  # still fine


def test_static_endpoint_leaves_stream_unimplemented():
    # The static run-to-completion path has no step-granular tokens to
    # stream: GenerateStream stays unregistered and the client gets
    # the honest UNIMPLEMENTED, not a buffered imitation.
    srv, port = serve_lm_generate(
        PARAMS, CFG, 0, max_new_tokens=N, prompt_len=T,
        host="127.0.0.1", scheduler="static",
    )
    try:
        c = GrpcClient(f"127.0.0.1:{port}")
        with pytest.raises(grpc.RpcError) as ei:
            list(c.generate_stream(_prompt(5)))
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED
        c.close()
    finally:
        srv.stop(0)
