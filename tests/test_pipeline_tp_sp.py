"""PP x TP x SP: the full Megatron-LM long-context deployment shape
(pipeline depth x tensor width x sequence length x data batch) in one
hand-rolled schedule — 1F1B, interleaved, and ZB-H1 variants, both SP
modes. Parity target: single-chip AD of the sp masking convention
(the same oracle the pairwise pp x sp and pp x tp tests pin, so all
compositions agree transitively).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    forward,
    init_transformer,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_tp_sp_lm_1f1b_grad,
    make_pipeline_tp_sp_lm_interleaved_grad,
    make_pipeline_tp_sp_lm_zb_grad,
    shard_blocks_interleaved_tp,
    shard_blocks_pp_tp,
    unshard_blocks_interleaved_tp,
    unshard_blocks_pp_tp,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq_len=16
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), jnp.int32)


def _masked_ce(params, tokens):
    logits = forward(params, tokens, CFG)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    targets = tokens[:, 1:]
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def _check(loss_v, g_v, g_blocks, params, tokens):
    loss_ref, g_ref = jax.jit(jax.value_and_grad(_masked_ce))(params, tokens)
    np.testing.assert_allclose(float(loss_ref), float(loss_v), rtol=1e-5)
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_v[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )


@pytest.mark.parametrize("mode", ["ring", "ulysses"])
def test_pp_tp_sp_1f1b_grads_match_single_chip(mode):
    # stage=2 x model=2 x seq=2: TP psums and SP attention collectives
    # execute inside the same switch branches; grads must equal
    # single-chip AD. (ulysses: Hl = 4/2 = 2 heads, seq=2 divides.)
    mesh = build_mesh(MeshSpec(stage=2, model=2, seq=2))
    params = init_transformer(jax.random.key(17), CFG)
    tokens = _tokens(batch=4, seq=16, seed=18)

    vag = make_pipeline_tp_sp_lm_1f1b_grad(
        mesh, CFG, num_stages=2, num_microbatches=2, mode=mode
    )
    params_v = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, 2, 2)
    )
    loss_v, g_v = jax.jit(vag)(params_v, tokens)
    g_blocks = unshard_blocks_pp_tp(g_v["blocks"], CFG)
    _check(loss_v, g_v, g_blocks, params, tokens)


@pytest.mark.parametrize("variant", ["interleaved", "zb"])
def test_pp_tp_sp_tables_grads_match_single_chip(variant):
    # The table-driven executors at 4D: virtual chunks x TP x SP (ring
    # via the group-local rotation) on stage=2 x model=2 x seq=2.
    mesh = build_mesh(MeshSpec(stage=2, model=2, seq=2))
    params = init_transformer(jax.random.key(19), CFG)
    tokens = _tokens(batch=4, seq=16, seed=20)

    make = (
        make_pipeline_tp_sp_lm_interleaved_grad
        if variant == "interleaved" else make_pipeline_tp_sp_lm_zb_grad
    )
    vag = make(mesh, CFG, num_virtual=2, num_microbatches=2, mode="ring")
    params_v = dict(
        params,
        blocks=shard_blocks_interleaved_tp(params["blocks"], CFG, 2, 2, 2),
    )
    loss_v, g_v = jax.jit(vag)(params_v, tokens)
    g_blocks = unshard_blocks_interleaved_tp(g_v["blocks"], CFG)
    _check(loss_v, g_v, g_blocks, params, tokens)


def test_pp_tp_sp_train_step_updates():
    import optax

    from tpu_dist_nn.train.lm_trainer import make_pipeline_sp_lm_train_step

    mesh = build_mesh(MeshSpec(stage=2, model=2, seq=2))
    params = init_transformer(jax.random.key(23), CFG)
    params_v = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, 2, 2)
    )
    optimizer = optax.adam(1e-2)
    step = make_pipeline_sp_lm_train_step(
        mesh, CFG, 2, 2, optimizer, mode="ring", schedule="1f1b",
        tensor_parallel=2,
    )
    tokens = _tokens(batch=4, seq=16, seed=24)
    new_params, _, loss = step(params_v, optimizer.init(params_v), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_qkv"]),
        np.asarray(params_v["blocks"]["w_qkv"]),
    )
    # gpipe 3-way (AD through the forward schedule) trains too.
    step_g = make_pipeline_sp_lm_train_step(
        mesh, CFG, 2, 2, optimizer, mode="ring", schedule="gpipe",
        tensor_parallel=2,
    )
    new_params_g, _, loss_g = step_g(
        params_v, optimizer.init(params_v), tokens
    )
    assert np.isfinite(float(loss_g)) and float(loss_g) > 0


def test_pp_tp_sp_gpipe_loss_matches_single_chip():
    # The gpipe member of the 3-way family shares the masked-CE oracle:
    # loss and grads through AD must match single-chip AD.
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_tp_sp_lm_loss,
    )

    mesh = build_mesh(MeshSpec(stage=2, model=2, seq=2))
    params = init_transformer(jax.random.key(29), CFG)
    tokens = _tokens(batch=4, seq=16, seed=30)

    loss_fn = make_pipeline_tp_sp_lm_loss(
        mesh, CFG, num_stages=2, num_microbatches=2, mode="ring"
    )
    params_v = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, 2, 2)
    )
    loss_v, g_v = jax.jit(jax.value_and_grad(loss_fn))(params_v, tokens)
    g_blocks = unshard_blocks_pp_tp(g_v["blocks"], CFG)
    _check(loss_v, g_v, g_blocks, params, tokens)


def test_cli_lm_tensor_parallel(capsys):
    # --tensor-parallel as a flag: pp x tp (gpipe) and the full
    # PP x TP x SP 1F1B through the CLI; eager rejections for the
    # unsupported shapes.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "16", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--stages", "2", "--tensor-parallel", "2",
        "--microbatches", "2",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--stages", "2", "--tensor-parallel", "2",
        "--seq-parallel", "2", "--schedule", "1f1b", "--microbatches", "2",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out

    # Eager rejections: no stages; heads not divisible.
    assert main([
        "--platform", "cpu", "lm", "--steps", "1", "--tensor-parallel", "2",
    ]) != 0
    assert main([
        "--platform", "cpu", "lm", "--steps", "1", "--stages", "2",
        "--tensor-parallel", "2", "--heads", "3",
    ]) != 0


def test_cli_lm_pp_sp_zb(capsys):
    # The table schedules through the CLI's pp x sp path (previously
    # "gpipe or 1f1b" only): zb trains end to end on real text.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--stages", "2", "--seq-parallel", "2",
        "--schedule", "zb", "--microbatches", "2",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out
