"""Wire-codec fast lane: vectorized/scalar equivalence, fallback
contract, decode-into-staging, and the codec A/B smoke (ISSUE 10).

The fast path's contract is exact: for ANY byte string, the vectorized
decoder must produce a byte-identical result — or raise the same error
— as the general per-row parser; for ANY array, the vectorized encoder
must emit byte-identical wire bytes to the legacy per-row encoder.
These tests fuzz both directions and drive every documented fallback
trigger (unpacked fixed64 rows, interleaved unknown fields, truncated
payloads, ragged widths, non-uniform headers).
"""

import threading

import numpy as np
import pytest

from tpu_dist_nn.obs.registry import REGISTRY
from tpu_dist_nn.serving.wire import (
    WireMatrix,
    decode_matrix,
    decode_matrix_into,
    decode_matrix_lazy,
    decode_matrix_scalar,
    encode_matrix,
    encode_matrix_scalar,
)


def _counter(name):
    return REGISTRY.get(name).labels().value


def _encode_unpacked(x):
    """proto2-style writer: one fixed64 field per value (legal, never
    fast-path-shaped)."""
    parts = []
    for row in np.asarray(x, np.float64):
        body = b"".join(b"\x09" + np.float64(v).tobytes() for v in row)
        parts.append(b"\x0a" + bytes([len(body)]) + body)
    return b"".join(parts)


def _encode_with_unknown_fields(x):
    """Conforming message with an unknown varint field interleaved
    between rows (field 2, wire type 0) — parsers must skip it."""
    out = bytearray()
    for row in np.asarray(x, np.float64):
        payload = row.tobytes()
        body = b"\x0a" + bytes([len(payload)]) + payload
        out += b"\x0a" + bytes([len(body)]) + body
        out += b"\x10\x2a"  # field 2 varint 42
    return bytes(out)


# ------------------------------------------------------------ equivalence


def test_encode_vectorized_matches_scalar_bytes_exactly():
    rng = np.random.default_rng(0)
    for shape in [(1, 1), (1, 784), (2, 3), (7, 13), (64, 784), (3, 0),
                  (0, 0), (33, 1), (256, 16)]:
        x = rng.normal(scale=10.0 ** rng.integers(-4, 5), size=shape)
        assert encode_matrix(x) == encode_matrix_scalar(x), shape
        # Engine-dtype input: the codec owns the one f64 cast, and the
        # bytes must match the scalar path's pre-cast pipeline.
        x32 = x.astype(np.float32)
        assert encode_matrix(x32) == encode_matrix_scalar(x32), shape
    # Integer input (the Generate client's token ids).
    ids = rng.integers(0, 1 << 20, (5, 9))
    assert encode_matrix(ids) == encode_matrix_scalar(ids)
    # Non-contiguous input encodes by value, not by memory layout.
    base = rng.normal(size=(8, 20))
    view = base[::2, ::3]
    assert encode_matrix(view) == encode_matrix_scalar(np.ascontiguousarray(view))


def test_decode_fast_path_matches_scalar_on_random_shapes():
    rng = np.random.default_rng(1)
    for _ in range(50):
        n, d = int(rng.integers(1, 40)), int(rng.integers(0, 50))
        x = rng.normal(scale=10.0 ** rng.integers(-3, 4), size=(n, d))
        wire = encode_matrix(x)
        fast = decode_matrix(wire)
        general = decode_matrix_scalar(wire)
        assert fast.shape == general.shape == (n, d)
        np.testing.assert_array_equal(fast, general)
        # dtype-landing parity too (the serving path's engine dtype).
        np.testing.assert_array_equal(
            decode_matrix(wire, dtype=np.float32),
            decode_matrix_scalar(wire, dtype=np.float32),
        )


def test_decode_fuzz_fast_and_scalar_agree_on_mutated_bytes():
    """Random truncations/bit-flips/appends: both parsers must agree —
    same array or both raise ValueError. The fast path may only ever
    DECLINE to a fallback, never diverge."""
    rng = np.random.default_rng(2)
    base = encode_matrix(rng.normal(size=(5, 7)))
    for _ in range(400):
        b = bytearray(base)
        op = rng.integers(0, 3)
        if op == 0 and len(b) > 1:
            b = b[: int(rng.integers(1, len(b)))]
        elif op == 1:
            i = int(rng.integers(0, len(b)))
            b[i] ^= 1 << int(rng.integers(0, 8))
        else:
            b += bytes(rng.integers(0, 256, int(rng.integers(1, 16))))
        data = bytes(b)
        try:
            general = decode_matrix_scalar(data)
            g_err = None
        except ValueError as e:
            general, g_err = None, str(e)
        try:
            fast = decode_matrix(data)
            f_err = None
        except ValueError as e:
            fast, f_err = None, str(e)
        assert (g_err is None) == (f_err is None), (g_err, f_err)
        if g_err is None:
            np.testing.assert_array_equal(fast, general)
        else:
            assert f_err == g_err


# -------------------------------------------------------- fallback triggers


def test_fallback_unpacked_fixed64_rows_decode_identically():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(4, 6))
    wire = _encode_unpacked(x)
    before = _counter("tdn_wire_decode_fallback_total")
    np.testing.assert_array_equal(decode_matrix(wire), x)
    assert _counter("tdn_wire_decode_fallback_total") == before + 1
    # The lazy entry point falls back to a fully-decoded ndarray.
    out = decode_matrix_lazy(wire, dtype=np.float32)
    assert isinstance(out, np.ndarray)
    np.testing.assert_array_equal(out, x.astype(np.float32))


def test_fallback_interleaved_unknown_fields_decode_identically():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(3, 5))
    wire = _encode_with_unknown_fields(x)
    np.testing.assert_array_equal(decode_matrix(wire), x)
    np.testing.assert_array_equal(decode_matrix_scalar(wire), x)


def test_fallback_truncated_payload_raises_same_error():
    x = np.arange(12.0).reshape(2, 6)
    wire = encode_matrix(x)
    cut = wire[:-16]  # lengths still claim 6 doubles; only 4 remain
    with pytest.raises(ValueError, match="truncated"):
        decode_matrix(cut)
    with pytest.raises(ValueError, match="truncated"):
        decode_matrix_scalar(cut)
    with pytest.raises(ValueError, match="truncated"):
        decode_matrix_lazy(cut)


def test_fallback_ragged_widths_raise_same_error():
    r2 = b"\x0a\x10" + np.zeros(2).tobytes()
    r1 = b"\x0a\x08" + np.zeros(1).tobytes()
    ragged = (b"\x0a" + bytes([len(r2)]) + r2
              + b"\x0a" + bytes([len(r1)]) + r1)
    for fn in (decode_matrix, decode_matrix_scalar, decode_matrix_lazy):
        with pytest.raises(ValueError, match="ragged"):
            fn(ragged)


def test_fast_counter_ticks_and_uniform_rows_stay_fast():
    rng = np.random.default_rng(5)
    wire = encode_matrix(rng.normal(size=(9, 4)))
    fast0 = _counter("tdn_wire_decode_fast_total")
    fb0 = _counter("tdn_wire_decode_fallback_total")
    decode_matrix(wire)
    assert isinstance(decode_matrix_lazy(wire), WireMatrix)
    assert _counter("tdn_wire_decode_fast_total") == fast0 + 2
    assert _counter("tdn_wire_decode_fallback_total") == fb0


def test_protoc_shaped_single_and_multi_row_messages_hit_fast_path():
    """Bytes built the way protoc's serializer emits them (minimal
    varints, packed field 1) must probe fast — the whole point is that
    the reference's own clients ride the fast lane."""
    for n, d in [(1, 3), (2, 3), (17, 784)]:
        x = np.arange(n * d, dtype=np.float64).reshape(n, d)
        wire = encode_matrix_scalar(x)  # scalar = the protoc layout
        assert isinstance(decode_matrix_lazy(wire), WireMatrix), (n, d)


# --------------------------------------------------- decode-into-staging


def test_decode_into_lands_rows_at_offset_in_target_dtype():
    rng = np.random.default_rng(6)
    x = rng.normal(size=(4, 7))
    staging = np.full((10, 7), -1.0, np.float32)
    n = decode_matrix_into(encode_matrix(x), staging, row_offset=3)
    assert n == 4
    np.testing.assert_array_equal(staging[3:7], x.astype(np.float32))
    assert (staging[:3] == -1.0).all() and (staging[7:] == -1.0).all()
    # The fallback layout lands through the same call.
    n = decode_matrix_into(_encode_unpacked(x), staging, row_offset=0)
    assert n == 4
    np.testing.assert_array_equal(staging[0:4], x.astype(np.float32))


def test_decode_into_rejects_width_mismatch_and_overflow():
    x = np.zeros((2, 5))
    with pytest.raises(ValueError, match="width"):
        decode_matrix_into(encode_matrix(x), np.zeros((4, 6)))
    with pytest.raises(ValueError, match="overflow"):
        decode_matrix_into(encode_matrix(x), np.zeros((2, 5)), row_offset=1)


def test_wire_matrix_shape_len_array_and_read_into():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(3, 8))
    w = decode_matrix_lazy(encode_matrix(x), dtype=np.float32)
    assert isinstance(w, WireMatrix)
    assert len(w) == 3 and w.shape == (3, 8) and w.ndim == 2
    assert w.dtype == np.float32
    # np.asarray materializes through __array__ in the carried dtype.
    np.testing.assert_array_equal(np.asarray(w), x.astype(np.float32))
    buf = np.zeros((8, 8), np.float32)
    assert w.read_into(buf, 2) == 3
    np.testing.assert_array_equal(buf[2:5], x.astype(np.float32))
    with pytest.raises(ValueError, match="width"):
        w.read_into(np.zeros((8, 9), np.float32))
    with pytest.raises(ValueError, match="overflow"):
        w.read_into(buf, 6)


def test_single_row_lazy_matrix_broadcasts_into_staging():
    # n == 1 rides a contiguous offset-frombuffer view (no reshape);
    # it must still land correctly in a staging slot.
    x = np.arange(5.0).reshape(1, 5) * 1.5
    w = decode_matrix_lazy(encode_matrix(x))
    assert isinstance(w, WireMatrix) and w.shape == (1, 5)
    buf = np.zeros((4, 5))
    w.read_into(buf, 1)
    np.testing.assert_array_equal(buf[1], x[0])


def test_batcher_stages_wire_matrices_straight_into_bucket_buffer():
    """End-to-end through the real _Batcher: WireMatrix submissions
    coalesce with ndarray submissions, results fan out correctly, and
    the decode happened straight into the staging buffer (the fake
    engine sees one contiguous engine-dtype batch)."""
    from tpu_dist_nn.serving.server import _Batcher

    seen = []

    class Echo:
        def infer(self, x):
            seen.append(np.asarray(x).copy())
            return np.asarray(x) * 2.0

    b = _Batcher(Echo(), submit_timeout=10.0, pipeline_depth=1)
    try:
        rng = np.random.default_rng(8)
        x1 = rng.normal(size=(2, 6)).astype(np.float32)
        x2 = rng.normal(size=(3, 6)).astype(np.float32)
        w1 = decode_matrix_lazy(encode_matrix(x1), dtype=np.float32)
        outs = {}
        t1 = threading.Thread(
            target=lambda: outs.__setitem__(1, b.submit(w1))
        )
        t2 = threading.Thread(
            target=lambda: outs.__setitem__(2, b.submit(x2))
        )
        t1.start(), t2.start()
        t1.join(5.0), t2.join(5.0)
        np.testing.assert_allclose(outs[1], x1 * 2.0, rtol=1e-6)
        np.testing.assert_allclose(outs[2], x2 * 2.0, rtol=1e-6)
        for batch in seen:
            assert batch.dtype == np.float32
    finally:
        b.close()


def test_single_wire_matrix_request_stages_rather_than_zero_copies():
    """A lone WireMatrix on a bucket boundary must still go through
    the staging buffer (there is no caller array to zero-copy-launch);
    the launch sees a real ndarray."""
    from tpu_dist_nn.serving.server import _Batcher

    launched = []

    class Echo:
        def infer(self, x):
            launched.append(x)
            return np.asarray(x) * 1.0

    b = _Batcher(Echo(), submit_timeout=10.0, pipeline_depth=1)
    try:
        x = np.arange(8.0, dtype=np.float32).reshape(2, 4)
        w = decode_matrix_lazy(encode_matrix(x), dtype=np.float32)
        out = b.submit(w)  # 2 rows == pow2 bucket boundary
        np.testing.assert_array_equal(out, x)
        assert isinstance(launched[0], np.ndarray)
    finally:
        b.close()


# ------------------------------------------------------------- bench A/B


def test_bench_wire_smoke_vectorized_beats_scalar():
    """The ISSUE-10 CI satellite: the codec-only A/B must show the
    vectorized path >= the scalar path at EVERY benched shape (reduced
    reps keep the smoke fast; the structural wins are 1.5-40x, far
    above rep-count noise)."""
    import bench

    wb = bench.wire_bench(reps=3)
    assert wb["shapes"], "no shapes benched"
    for row in wb["shapes"]:
        assert row["speedup"] >= 1.0, (
            f"vectorized codec lost to scalar at shape {row['shape']}: "
            f"{row}"
        )
    assert wb["min_speedup"] >= 1.0


def test_loopback_serving_round_trip_rides_fast_path():
    """A real GrpcClient -> server -> engine loop must keep every hop
    on the fast lane: the fallback counter does not move, the fast
    counter does, and results match the engine exactly."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from tpu_dist_nn.serving.server import GrpcClient, serve_engine

    class TinyEngine:
        dtype = np.float32

        class model:
            input_dim = 6

        def infer(self, x):
            return np.asarray(x, np.float32) + 1.0

    eng = TinyEngine()
    server, port = serve_engine(eng, 0, host="127.0.0.1", coalesce=True)
    try:
        fb0 = _counter("tdn_wire_decode_fallback_total")
        fast0 = _counter("tdn_wire_decode_fast_total")
        client = GrpcClient(f"127.0.0.1:{port}")
        try:
            x = np.arange(18.0).reshape(3, 6)
            out = client.process(x)
            np.testing.assert_allclose(out, x + 1.0, rtol=1e-6)
        finally:
            client.close()
        assert _counter("tdn_wire_decode_fallback_total") == fb0
        # Server decode + client reply decode both probed fast.
        assert _counter("tdn_wire_decode_fast_total") >= fast0 + 2
    finally:
        server.stop(0)
