"""The shipped examples stay runnable (reference C10 toolchain parity)."""

import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1] / "examples"))


def test_centralized_experiments_smoke(tmp_path, capsys):
    import centralized_experiments as ce

    from tpu_dist_nn.data.datasets import synthetic_mnist

    full = synthetic_mnist(600, dim=64)
    data, eval_data = full.split(0.9)
    acc = ce.experiment_linear_softmax(data, eval_data)
    assert 0.0 <= acc <= 1.0
    params, metrics = ce.experiment_serving_mlp(data, eval_data)
    assert set(metrics) >= {"accuracy", "precision", "recall", "f1_score"}
    ce.experiment_per_sample_latency(params, eval_data, n=5)
    out = tmp_path / "model.json"
    obj = ce.experiment_export(params, metrics, out)
    assert obj["inference_metrics"] == metrics
    assert len(obj["layers"]) == 3
    nbytes = ce.experiment_payload_size(data)
    assert nbytes == 64 * 8
    assert "[e]" in capsys.readouterr().out


def test_centralized_experiments_on_real_digits(tmp_path):
    # C10 closure: the experiment suite on the vendored REAL digits —
    # the accuracies are genuine held-out numbers, not synthetic ~1.0s.
    import centralized_experiments as ce

    from tpu_dist_nn.data.datasets import real_digits

    data, eval_data = real_digits("train"), real_digits("test")
    # Short linear run (full budget asserted in the example itself).
    acc = ce.experiment_linear_softmax(data, eval_data, epochs=30)
    assert acc > 0.85
    params, metrics = ce.experiment_serving_mlp(data, eval_data)
    assert metrics["accuracy"] > 0.9  # real generalization, real data
    obj = ce.experiment_export(params, metrics, tmp_path / "m.json")
    assert obj["inference_metrics"]["accuracy"] == metrics["accuracy"]


def test_deep_pipeline_8stage_experiment(tmp_path):
    # BASELINE configs[2] closure (artifacts/deep_pipeline_r04): the
    # 8-layer MLP trains THROUGH the one-layer-per-stage 8-device
    # pipeline on real digits, exports, re-serves at three placements,
    # and the deep placement's latency overhead tracks the tick model.
    import deep_pipeline_8stage as dp

    record = dp.run(str(tmp_path / "deep8.json"), epochs=6)
    assert record["placement"]["num_stages"] == 8
    assert record["held_out_accuracy"] > 0.85  # real data, short budget
    lat = record["step_latency"]
    assert lat["deep_8stage"]["num_stages"] == 8
    assert lat["shallow_3stage"]["num_stages"] == 3
    assert lat["single_chip"]["num_stages"] == 1
    for block in lat.values():
        assert block["p50_per_stage_s"] > 0
    # Deeper pipeline, same model: more fill/drain ticks per step.
    # Wall-clock ordering on 8 virtual devices sharing one core is
    # contention-sensitive (observed inverted once under a saturated
    # box while the full suite shared the host with TPU compiles), so
    # one fresh re-measurement is allowed before declaring failure —
    # latency only, from the already-exported model; no retraining.
    if not lat["deep_8stage"]["p50_s"] > lat["shallow_3stage"]["p50_s"]:
        from tpu_dist_nn.api.engine import Engine
        from tpu_dist_nn.core import load_model

        m = load_model(str(tmp_path / "deep8.json"))
        lat = {
            "deep_8stage": Engine.up(m, dp.DEEP_DIST).step_latency(256, 30),
            "shallow_3stage": Engine.up(
                m, dp.SHALLOW_DIST).step_latency(256, 30),
        }
    assert lat["deep_8stage"]["p50_s"] > lat["shallow_3stage"]["p50_s"]


def test_four_d_training_example(tmp_path, capsys, monkeypatch):
    # The 4D composition example (artifacts/four_d_r04): PP x TP x SP
    # trains on real text under all four schedules and their
    # trajectories agree to float tolerance. Short step budget for CI.
    import runpy

    import pytest

    out = tmp_path / "four_d.json"
    monkeypatch.setattr(
        sys, "argv", ["four_d_training.py", "--steps", "2",
                      "--out", str(out)],
    )
    with pytest.raises(SystemExit) as exc:
        runpy.run_path(
            str(Path(__file__).resolve().parents[1] / "examples"
                / "four_d_training.py"),
            run_name="__main__",
        )
    assert exc.value.code == 0
    record = json.loads(out.read_text())
    assert record["final_loss_spread_across_schedules"] < 1e-3
    assert set(record["schedules"]) == {"gpipe", "1f1b", "interleaved", "zb"}


def test_pp_decode_throughput_example(tmp_path, capsys, monkeypatch):
    # Overlapped vs masked pipelined decode (artifacts/pp_decode_r04):
    # identical outputs, overlapped faster or equal (wall-clock on a
    # contended CI box is noisy, so the assertion is outputs + record
    # shape; the committed artifact carries the measured 2.55x).
    import runpy

    import pytest

    out = tmp_path / "pp_decode.json"
    monkeypatch.setattr(
        sys, "argv", ["pp_decode_throughput.py", "--out", str(out),
                      "--repeat", "1"],
    )
    with pytest.raises(SystemExit) as exc:
        runpy.run_path(
            str(Path(__file__).resolve().parents[1] / "examples"
                / "pp_decode_throughput.py"),
            run_name="__main__",
        )
    assert exc.value.code == 0
    record = json.loads(out.read_text())
    assert record["identical_outputs"] is True
    assert record["overlapped_round_robin"]["tokens_per_s"] > 0
