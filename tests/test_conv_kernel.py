"""Pallas conv2d(+maxpool) kernel parity (BASELINE configs[3]).

Interpreter mode on the CPU test mesh, same as the other kernels; the
oracle and the lax conv path are the two independent references.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax import lax

from tpu_dist_nn.kernels.conv2d import fused_conv2d


def _lax_conv(imgs, w, b, stride, padding, act):
    out = lax.conv_general_dilated(
        imgs, w, window_strides=stride, padding=padding.upper(),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    ) + b
    if act == "relu":
        out = jnp.maximum(out, 0.0)
    return out


@pytest.mark.parametrize("padding", ["valid", "same"])
@pytest.mark.parametrize("stride", [(1, 1), (2, 2)])
def test_conv_matches_lax(padding, stride):
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.normal(size=(5, 9, 9, 3)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(3, 3, 3, 7)) * 0.3, jnp.float32)
    b = jnp.asarray(rng.normal(size=(7,)), jnp.float32)
    got = fused_conv2d(imgs, w, b, stride=stride, padding=padding,
                       activation="relu")
    want = _lax_conv(imgs, w, b, stride, padding, "relu")
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-5)


def test_conv_fused_pool_matches_unfused():
    rng = np.random.default_rng(1)
    imgs = jnp.asarray(rng.normal(size=(4, 8, 8, 2)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(3, 3, 2, 6)) * 0.3, jnp.float32)
    b = jnp.asarray(rng.normal(size=(6,)), jnp.float32)
    got = fused_conv2d(imgs, w, b, padding="valid", activation="relu",
                       pool_window=(2, 2))
    conv = _lax_conv(imgs, w, b, (1, 1), "valid", "relu")
    want = lax.reduce_window(
        conv, -jnp.inf, lax.max,
        window_dimensions=(1, 2, 2, 1), window_strides=(1, 2, 2, 1),
        padding="VALID",
    )
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-5)


def test_conv_pool_overlapping_stride():
    rng = np.random.default_rng(2)
    imgs = jnp.asarray(rng.normal(size=(3, 7, 7, 2)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(2, 2, 2, 4)) * 0.4, jnp.float32)
    b = jnp.zeros((4,), jnp.float32)
    got = fused_conv2d(imgs, w, b, padding="valid", activation="linear",
                       pool_window=(3, 3), pool_stride=(1, 1))
    conv = _lax_conv(imgs, w, b, (1, 1), "valid", "linear")
    want = lax.reduce_window(
        conv, -jnp.inf, lax.max,
        window_dimensions=(1, 3, 3, 1), window_strides=(1, 1, 1, 1),
        padding="VALID",
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-5)


def test_uneven_batch_tiles():
    rng = np.random.default_rng(3)
    imgs = jnp.asarray(rng.normal(size=(5, 6, 6, 2)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(3, 3, 2, 3)) * 0.3, jnp.float32)
    b = jnp.zeros((3,), jnp.float32)
    got = fused_conv2d(imgs, w, b, padding="valid", activation="relu",
                       block_b=2)  # 3 tiles, last partial
    want = _lax_conv(imgs, w, b, (1, 1), "valid", "relu")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-5)


def test_shape_mismatch_rejected():
    imgs = jnp.zeros((2, 5, 5, 3), jnp.float32)
    w = jnp.zeros((3, 3, 4, 6), jnp.float32)
    with pytest.raises(ValueError, match="shape mismatch"):
        fused_conv2d(imgs, w, jnp.zeros((6,), jnp.float32))


def test_network_forward_pallas_flag_matches_oracle(monkeypatch):
    # Route the conv+pool hybrid model through the Pallas path and
    # check parity against the float64 oracle end-to-end.
    import tpu_dist_nn.models.network as network
    from tpu_dist_nn.models.network import (
        build_network,
        init_conv_mlp,
        network_forward,
    )
    from tpu_dist_nn.testing.oracle import oracle_forward_batch

    monkeypatch.setattr(network, "_PALLAS_CONV", True)
    model = init_conv_mlp(
        jax.random.key(0), in_shape=(8, 8, 2), conv_filters=(4, 5),
        hidden=(10,), num_classes=3, pool_after_conv=True,
    )
    plan, params = build_network(model)
    x = np.random.default_rng(4).uniform(0, 1, (6, model.input_dim))
    got = np.asarray(network_forward(plan, params, jnp.asarray(x, jnp.float32)))
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_overlapping_pool_phase_decimation():
    # pool_stride > 1 with stride != window: exercises the phase
    # reshape + tail-concat decimation path (not the stride==1 shortcut).
    rng = np.random.default_rng(5)
    imgs = jnp.asarray(rng.normal(size=(3, 11, 11, 2)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(2, 2, 2, 4)) * 0.4, jnp.float32)
    b = jnp.zeros((4,), jnp.float32)
    got = fused_conv2d(imgs, w, b, padding="valid", activation="relu",
                       pool_window=(3, 3), pool_stride=(2, 2))
    conv = _lax_conv(imgs, w, b, (1, 1), "valid", "relu")
    want = lax.reduce_window(
        conv, -jnp.inf, lax.max,
        window_dimensions=(1, 3, 3, 1), window_strides=(1, 2, 2, 1),
        padding="VALID",
    )
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=1e-5)


def test_training_unaffected_by_pallas_flag(monkeypatch):
    # TDN_PALLAS_CONV must not break the training entry: pallas_call
    # has no reverse-mode autodiff, so network_logits stays on lax ops.
    import tpu_dist_nn.models.network as network
    from tpu_dist_nn.models.network import (
        build_network,
        init_conv_mlp,
        network_logits,
    )

    monkeypatch.setattr(network, "_PALLAS_CONV", True)
    model = init_conv_mlp(
        jax.random.key(2), in_shape=(6, 6, 2), conv_filters=(3,),
        hidden=(8,), num_classes=3, pool_after_conv=True,
    )
    plan, params = build_network(model)
    x = jnp.asarray(np.random.default_rng(6).uniform(0, 1, (4, model.input_dim)),
                    jnp.float32)

    def loss(params):
        return jnp.mean(network_logits(plan, params, x) ** 2)

    grads = jax.grad(loss)(params)
    assert any(float(jnp.abs(g).sum()) > 0
               for layer in grads for g in layer.values())


def test_pallas_path_activation_semantics_match_default(monkeypatch):
    # Unknown/case-variant activation names: the reference treats them
    # as linear (grpc_node.py:72-73); the Pallas route must not diverge.
    import tpu_dist_nn.models.network as network
    from tpu_dist_nn.core.schema import Conv2DSpec, LayerSpec, ModelSpec
    from tpu_dist_nn.models.network import build_network, network_forward

    rng = np.random.default_rng(7)
    conv = Conv2DSpec(
        in_shape=(6, 6, 2),
        weights=rng.normal(size=(3, 3, 2, 4)) * 0.3,
        biases=np.zeros(4),
        stride=(1, 1),
        padding="valid",
        activation="ReLU-Custom",  # unknown -> linear, both paths
    )
    dense = LayerSpec(
        weights=rng.normal(size=(conv.out_dim, 3)) * 0.3,
        biases=np.zeros(3),
        activation="softmax",
        type_tag="output",
    )
    model = ModelSpec(layers=[conv, dense])
    plan, params = build_network(model)
    x = jnp.asarray(rng.uniform(0, 1, (4, model.input_dim)), jnp.float32)

    monkeypatch.setattr(network, "_PALLAS_CONV", False)
    want = np.asarray(network_forward(plan, params, x))
    monkeypatch.setattr(network, "_PALLAS_CONV", True)
    got = np.asarray(network_forward(plan, params, x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)
