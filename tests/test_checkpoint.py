"""Checkpoint/resume subsystem tests (SURVEY.md §5: the reference's only
persistence is the JSON model file; the native fast path is new)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_dist_nn.checkpoint import CheckpointManager, restore_pytree, save_pytree
from tpu_dist_nn.data.datasets import synthetic_mnist
from tpu_dist_nn.models.fcnn import init_fcnn
from tpu_dist_nn.train.trainer import TrainConfig, train_fcnn


def _state(seed=0):
    params = init_fcnn(jax.random.key(seed), [6, 5, 3])
    wb = [{"w": p["w"], "b": p["b"]} for p in params]
    optimizer = optax.adam(1e-3)
    return {"params": wb, "opt_state": optimizer.init(wb)}


def _tree_equal(a, b):
    flat_a, _ = jax.tree_util.tree_flatten(a)
    flat_b, _ = jax.tree_util.tree_flatten(b)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(flat_a, flat_b))


def test_pytree_roundtrip(tmp_path):
    state = _state()
    path = tmp_path / "state.msgpack"
    save_pytree(state, path)
    template = _state(seed=1)  # different values, same structure
    restored = restore_pytree(template, path)
    assert _tree_equal(state, restored)


def test_manager_latest_and_retention(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=2)
    assert mgr.latest_step() is None
    for step in (1, 2, 3):
        mgr.save(step, {"x": np.full((2,), float(step))})
    assert mgr.latest_step() == 3
    assert mgr.steps() == [2, 3]  # step 1 pruned
    # Pruned file really gone; kept files really present.
    files = sorted(p.name for p in tmp_path.glob("ckpt_*.msgpack"))
    assert files == ["ckpt_00000002.msgpack", "ckpt_00000003.msgpack"]
    step, state = mgr.restore({"x": np.zeros((2,))})
    assert step == 3 and state["x"][0] == 3.0


def test_manager_restore_specific_step_and_missing(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(5, {"x": np.ones(1)})
    step, state = mgr.restore({"x": np.zeros(1)}, step=5)
    assert step == 5 and state["x"][0] == 1.0
    with pytest.raises(FileNotFoundError):
        mgr.restore({"x": np.zeros(1)}, step=9)
    assert CheckpointManager(tmp_path / "empty").restore_or_none({"x": np.zeros(1)}) is None


def test_manifest_records_metadata(tmp_path):
    mgr = CheckpointManager(tmp_path)
    mgr.save(1, {"x": np.zeros(1)}, metadata={"loss": 0.5})
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert manifest["metadata"]["1"]["loss"] == 0.5


def test_train_resume_matches_uninterrupted(tmp_path):
    """Train 1 epoch + checkpoint, then resume for 2 more; the result
    must equal a straight 3-epoch run (identical per-epoch shuffles)."""
    data = synthetic_mnist(192, num_classes=4, dim=12, seed=3)
    params0 = init_fcnn(jax.random.key(0), [12, 8, 4])

    full_params, full_hist = train_fcnn(
        params0, data, TrainConfig(epochs=3, batch_size=32, seed=7)
    )

    mgr = CheckpointManager(tmp_path / "ck")
    train_fcnn(params0, data, TrainConfig(epochs=1, batch_size=32, seed=7),
               checkpoints=mgr)
    assert mgr.latest_step() == 1
    resumed_params, resumed_hist = train_fcnn(
        params0, data, TrainConfig(epochs=3, batch_size=32, seed=7),
        checkpoints=mgr,
    )
    assert mgr.latest_step() == 3
    assert len(resumed_hist) == 2  # epochs 1..2 only re-run
    for a, b in zip(jax.tree_util.tree_leaves(full_params),
                    jax.tree_util.tree_leaves(resumed_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7)


def test_resume_noop_when_complete(tmp_path):
    """Resuming a finished run re-trains nothing."""
    data = synthetic_mnist(96, num_classes=4, dim=12, seed=3)
    params0 = init_fcnn(jax.random.key(0), [12, 8, 4])
    mgr = CheckpointManager(tmp_path)
    cfg = TrainConfig(epochs=2, batch_size=32, seed=7)
    train_fcnn(params0, data, cfg, checkpoints=mgr)
    _, hist = train_fcnn(params0, data, cfg, checkpoints=mgr)
    assert hist == []


def test_pipelined_train_resume(tmp_path):
    """Pipeline-parallel training checkpoints and resumes to the same
    weights as an uninterrupted run (mesh-placed leaves round-trip)."""
    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.train import train_pipelined

    data = synthetic_mnist(192, num_classes=4, dim=12, noise=0.25, seed=3)
    model = random_model([12, 8, 4], seed=6, scale=1.0)
    mesh = build_mesh(MeshSpec(stage=2, data=2))
    cfg = TrainConfig(epochs=3, batch_size=48, seed=7)

    pp0 = build_pipeline_params(partition_model(model, [1, 1]))
    full, _ = train_pipelined(pp0, mesh, data, cfg, num_microbatches=2)

    mgr = CheckpointManager(tmp_path)
    pp1 = build_pipeline_params(partition_model(model, [1, 1]))
    train_pipelined(pp1, mesh, data, TrainConfig(epochs=1, batch_size=48, seed=7),
                    num_microbatches=2, checkpoints=mgr)
    assert mgr.latest_step() == 1
    pp2 = build_pipeline_params(partition_model(model, [1, 1]))
    resumed, hist = train_pipelined(pp2, mesh, data, cfg,
                                    num_microbatches=2, checkpoints=mgr)
    assert len(hist) == 2
    np.testing.assert_allclose(
        np.asarray(resumed.weights.w), np.asarray(full.weights.w),
        rtol=1e-6, atol=1e-7,
    )


def test_restore_falls_back_past_missing_newest(tmp_path):
    """A lost newest file falls back to the newest intact checkpoint;
    an all-files-lost manifest raises instead of silently restarting."""
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(1, {"x": np.ones(1)})
    mgr.save(2, {"x": np.full((1,), 2.0)})
    (tmp_path / "ckpt_00000002.msgpack").unlink()
    step, state = mgr.restore({"x": np.zeros(1)})
    assert step == 1 and state["x"][0] == 1.0
    (tmp_path / "ckpt_00000001.msgpack").unlink()
    with pytest.raises(RuntimeError):
        mgr.restore({"x": np.zeros(1)})


def test_metadata_pruned_with_checkpoint(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=1)
    mgr.save(1, {"x": np.zeros(1)}, metadata={"loss": 1.0})
    mgr.save(2, {"x": np.zeros(1)}, metadata={"loss": 0.5})
    manifest = json.loads((tmp_path / "manifest.json").read_text())
    assert "1" not in manifest.get("metadata", {})
    assert manifest["metadata"]["2"]["loss"] == 0.5


def test_save_older_than_retention_window_rejected(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=2)
    mgr.save(5, {"x": np.zeros(1)})
    mgr.save(6, {"x": np.zeros(1)})
    with pytest.raises(ValueError, match="retention window"):
        mgr.save(1, {"x": np.zeros(1)})
    assert mgr.steps() == [5, 6]


def test_async_manager_saves_and_restores(tmp_path):
    from tpu_dist_nn.checkpoint import AsyncCheckpointManager

    mgr = AsyncCheckpointManager(tmp_path, keep=2)
    state = {"w": np.arange(6.0).reshape(2, 3)}
    for step in (1, 2, 3):
        mgr.save(step, {"w": state["w"] * step}, metadata={"step": step})
    mgr.wait()
    assert mgr.steps() == [2, 3]  # retention applied in order
    got_step, got = mgr.restore({"w": np.zeros((2, 3))})
    assert got_step == 3
    np.testing.assert_allclose(got["w"], state["w"] * 3)
    mgr.close()


def test_async_manager_restore_flushes_pending(tmp_path):
    from tpu_dist_nn.checkpoint import AsyncCheckpointManager

    mgr = AsyncCheckpointManager(tmp_path, keep=3)
    mgr.save(7, {"w": np.ones(4)})
    # No explicit wait: restore must see the enqueued save.
    step, got = mgr.restore({"w": np.zeros(4)})
    assert step == 7
    np.testing.assert_allclose(got["w"], np.ones(4))
    mgr.close()


def test_async_manager_surfaces_worker_errors(tmp_path):
    from tpu_dist_nn.checkpoint import AsyncCheckpointManager

    mgr = AsyncCheckpointManager(tmp_path, keep=1)
    mgr.save(5, {"w": np.ones(2)})
    mgr.wait()
    # An out-of-retention step now fails fast on the CALLER thread
    # (validation runs before enqueue so multi-host jobs agree on the
    # verdict instead of hanging; see store._agree_valid).
    with pytest.raises(ValueError, match="retention"):
        mgr.save(1, {"w": np.ones(2)})
    # A failure inside the WORKER (filesystem half) still surfaces on
    # wait(), not silently vanishing.
    boom = RuntimeError("disk on fire")

    def exploding_save_local(step, state, metadata=None):
        raise boom

    mgr._save_local = exploding_save_local
    mgr.save(6, {"w": np.ones(2)})
    with pytest.raises(RuntimeError, match="disk on fire"):
        mgr.wait()
    mgr.close()


def test_lm_training_with_async_checkpoints_resumes(tmp_path):
    from tpu_dist_nn.checkpoint import AsyncCheckpointManager
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16,
    )
    tcfg = LMTrainConfig(steps=4, batch_size=4, seq_len=16, log_every=2)
    rows = np.random.default_rng(0).integers(0, 32, (64, 17)).astype(np.int32)

    def batches():
        rng = np.random.default_rng(1)
        while True:
            yield rows[rng.integers(0, len(rows), 4)]

    params = init_transformer(jax.random.key(0), cfg)
    mgr = AsyncCheckpointManager(tmp_path, keep=3)
    _, history = train_lm(params, cfg, batches(), tcfg, checkpoints=mgr,
                          checkpoint_every=2)
    assert mgr.latest_step() == 4  # flushed before return
    # A fresh manager resumes from the durable step.
    mgr2 = AsyncCheckpointManager(tmp_path, keep=3)
    _, history2 = train_lm(params, cfg, batches(), tcfg, checkpoints=mgr2,
                           checkpoint_every=2)
    assert history2 == [] or history2[0]["step"] > 2
    mgr.close(); mgr2.close()


def test_async_save_after_close_raises(tmp_path):
    from tpu_dist_nn.checkpoint import AsyncCheckpointManager

    mgr = AsyncCheckpointManager(tmp_path)
    mgr.close()
    with pytest.raises(RuntimeError, match="closed"):
        mgr.save(1, {"w": np.ones(2)})


def test_flush_runs_when_training_raises(tmp_path):
    # Crash-resume guarantee: a save enqueued before the loop dies must
    # still be durable.
    from tpu_dist_nn.checkpoint import AsyncCheckpointManager
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq_len=16,
    )
    tcfg = LMTrainConfig(steps=6, batch_size=4, seq_len=16, log_every=2)
    good = np.random.default_rng(0).integers(0, 32, (4, 17)).astype(np.int32)

    def batches():
        yield good
        yield good
        raise RuntimeError("simulated data-pipeline crash")

    params = init_transformer(jax.random.key(0), cfg)
    mgr = AsyncCheckpointManager(tmp_path, keep=3)
    with pytest.raises(RuntimeError, match="simulated"):
        train_lm(params, cfg, batches(), tcfg, checkpoints=mgr,
                 checkpoint_every=2)
    assert mgr.latest_step() == 2  # the enqueued save landed
    mgr.close()


def test_orbax_store_roundtrip_and_trainer_resume(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from tpu_dist_nn.checkpoint.orbax_store import OrbaxCheckpointManager
    from tpu_dist_nn.checkpoint.store import resume_or_init

    mgr = OrbaxCheckpointManager(tmp_path / "ck", keep=2)
    state = {"w": np.arange(6.0).reshape(2, 3)}
    for step in (1, 2, 3):
        mgr.save(step, {"w": state["w"] * step, "step": np.full((), step, np.int32)})
    mgr.wait()
    assert mgr.steps() == [2, 3]  # retention
    got_step, got = mgr.restore(
        {"w": np.zeros((2, 3)), "step": np.zeros((), np.int32)}
    )
    assert got_step == 3
    np.testing.assert_allclose(np.asarray(got["w"]), state["w"] * 3)
    # The shared trainer-resume helper accepts it unchanged.
    step, resumed = resume_or_init(
        mgr, {"w": np.zeros((2, 3)), "step": np.zeros((), np.int32)}
    )
    assert step == 3
    np.testing.assert_allclose(np.asarray(resumed["w"]), state["w"] * 3)
    mgr.close()


def test_orbax_store_empty_dir_fresh_start(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from tpu_dist_nn.checkpoint.orbax_store import OrbaxCheckpointManager

    mgr = OrbaxCheckpointManager(tmp_path / "empty")
    assert mgr.restore_or_none({"w": np.zeros(2)}) is None
    mgr.close()


def test_restore_structure_mismatch_is_explained(tmp_path):
    # A checkpoint written under one trainer layout restored into a
    # different template must fail with the operative fact, not a
    # cryptic flax state-dict error (layouts changed across rounds).
    from tpu_dist_nn.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path)
    mgr.save(1, {"params": [np.ones(3), np.ones(2)]})
    with pytest.raises(ValueError, match="checkpoint-dir"):
        mgr.restore({"params": [np.ones(3), np.ones(2), np.ones(4)]}, 1)


def test_hetero_clip_with_grad_accum_rejected():
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.models.network import init_conv_mlp
    from tpu_dist_nn.parallel.hetero_pipeline import HeteroPipeline, train_hetero
    from tpu_dist_nn.train.trainer import TrainConfig
    import jax

    model = init_conv_mlp(
        jax.random.key(0), in_shape=(6, 6, 1), conv_filters=(4,),
        hidden=(8,), num_classes=3,
    )
    data = synthetic_mnist(48, num_classes=3, dim=model.input_dim, seed=0)
    hp = HeteroPipeline(model, [2, len(model.layers) - 2])
    with pytest.raises(ValueError, match="grad_accum"):
        train_hetero(
            hp, data,
            TrainConfig(epochs=1, batch_size=24, clip_norm=1.0, grad_accum=2),
        )


def test_flush_unwinding_skips_agreement_broadcast(tmp_path, monkeypatch):
    # On the exception path, flush must stay collective-free: the peers
    # may still be mid-step, and a broadcast here would pair with a
    # mismatched collective and hang (ADVICE r2, store.flush docstring).
    from tpu_dist_nn.checkpoint import AsyncCheckpointManager
    from tpu_dist_nn.checkpoint import store as store_mod

    calls = []
    monkeypatch.setattr(jax, "process_count", lambda: 2)

    from jax.experimental import multihost_utils

    def _broadcast(x):
        calls.append(x)
        return x

    monkeypatch.setattr(multihost_utils, "broadcast_one_to_all", _broadcast)

    mgr = AsyncCheckpointManager(tmp_path)
    # Unwinding: wait() runs (saves durable) but no collective is issued.
    store_mod.flush(mgr, unwinding=True)
    assert calls == []
    # Clean exit: the agreement broadcast runs.
    store_mod.flush(mgr)
    assert len(calls) == 1
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    mgr.close()
