"""Silent-corruption defense plane (ISSUE 19: serving/integrity.py +
pool quarantine choreography): checkpoint fingerprints detect a single
flipped bit and gate the orbax restore path, the numeric guard fails
exactly the poisoned rows (unaffected rows ship bit-identical) and
surfaces DATA_LOSS on the wire, canary goldens are stable across prober
restarts, the quarantine lifecycle runs detect -> drain-refusal ->
evidence bundle -> reverify-readmit (+ the operator's force break-glass
and the three-strikes guard verdict), shadow spot-checks arbitrate a
reply-byte tamper down to the guilty replica, and the checked-in
corruption drill scenario quarantines exactly the planted replica."""

import os
import time

import numpy as np
import pytest

from tpu_dist_nn.serving import integrity
from tpu_dist_nn.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _engine_available() -> bool:
    """The seed's Engine/mesh layer needs jax.sharding.AxisType (and
    jax.shard_map); on older jax every Engine.up fails at import —
    the real-engine variants skip rather than re-report a known
    environment gap (the test_obs.py convention)."""
    try:
        from jax.sharding import AxisType  # noqa: F401

        return True
    except ImportError:
        return False


@pytest.fixture(autouse=True)
def _armed_guard():
    """Every test here assumes the guard is armed (the bench A/B and a
    TDN_INTEGRITY_GUARD=0 environment may have disarmed the process
    singleton); restore whatever the session had."""
    prev = integrity.GUARD.enabled
    integrity.GUARD.enabled = True
    yield
    integrity.GUARD.enabled = prev


# ------------------------------------------------ fingerprints (rung 1)


def test_array_checksum_and_fingerprint_detect_bitflip():
    rng = np.random.default_rng(0)
    tree = {
        "w": rng.normal(size=(4, 6)),
        "b": rng.normal(size=(6,)),
    }
    fp = integrity.fingerprint_tree(tree)
    assert fp["count"] == 2
    assert integrity.verify_tree(tree, fp) == []
    # Same values, fresh buffers -> same fingerprint (it hashes bytes,
    # not identities).
    copy = {k: v.copy() for k, v in tree.items()}
    assert integrity.fingerprint_tree(copy)["model"] == fp["model"]
    # One flipped mantissa bit — far below any tolerance a numeric
    # check would use — must change the array's checksum, the model
    # fingerprint, and be NAMED by verify_tree.
    index, bit = faults.bitflip_array(copy["w"], seed=3)
    assert bit < 8  # low mantissa: corrupts, does not explode
    assert integrity.fingerprint_tree(copy)["model"] != fp["model"]
    mismatches = integrity.verify_tree(copy, fp)
    assert len(mismatches) == 1 and mismatches[0].startswith("w:")
    # dtype is part of the digest: an f32 cast of identical values must
    # not collide with the f64 original.
    assert integrity.array_checksum(
        tree["b"].astype(np.float32)
    ) != integrity.array_checksum(tree["b"])


def test_fingerprint_structure_drift_reported_both_directions():
    tree = {"w": np.ones((2, 2)), "b": np.zeros(3)}
    fp = integrity.fingerprint_tree(tree)
    # A truncated restore (missing array) and a renamed/extra array are
    # both corruption, not tolerable drift.
    missing = {"w": tree["w"]}
    assert any("missing from restored state" in m
               for m in integrity.verify_tree(missing, fp))
    extra = dict(tree, v=np.ones(1))
    assert any("not in saved fingerprint" in m
               for m in integrity.verify_tree(extra, fp))


def test_orbax_round_trip_verifies_and_tamper_fails_data_loss(tmp_path):
    pytest.importorskip("orbax.checkpoint")
    from tpu_dist_nn.checkpoint.orbax_store import OrbaxCheckpointManager
    from tpu_dist_nn.utils.errors import IntegrityError

    state = {"w": np.arange(6.0).reshape(2, 3), "b": np.ones(3)}
    template = {"w": np.zeros((2, 3)), "b": np.zeros(3)}

    mgr = OrbaxCheckpointManager(tmp_path / "ck", keep=3)
    try:
        # Honest round trip: the fingerprint is written into the
        # checkpoint metadata at save and verified clean at restore.
        mgr.save(1, state)
        mgr.wait()
        meta = mgr.read_metadata(1)
        assert meta is not None and "integrity" in meta
        assert meta["integrity"]["model"] == \
            integrity.fingerprint_tree(state)["model"]
        step, got = mgr.restore(template, 1)
        assert step == 1
        np.testing.assert_array_equal(np.asarray(got["w"]), state["w"])

        # Corrupt read: a checkpoint whose bytes disagree with the
        # fingerprint written at save time (simulated by saving the
        # fingerprint of a bit-flipped twin — save() setdefaults, so an
        # explicit metadata fingerprint wins) fails LOUDLY at load.
        flipped = {k: v.copy() for k, v in state.items()}
        faults.bitflip_array(flipped["w"], seed=9)
        mgr.save(2, state,
                 metadata={"integrity": integrity.fingerprint_tree(flipped)})
        mgr.wait()
        with pytest.raises(IntegrityError, match="w:"):
            mgr.restore(template, 2)
        # verify=False is the forensics opt-out on a known-corrupt step.
        step, got = mgr.restore(template, 2, verify=False)
        assert step == 2
    finally:
        mgr.close()


# ------------------------------------- numeric guard (rung 2) + engine


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """Two real engines of the SAME weights, each behind its own gRPC
    server — replica A is the corruption victim (tests attach/clear its
    launch_hook), replica B stays golden."""
    if not _engine_available():
        pytest.skip("jax too old for the Engine mesh layer "
                    "(no jax.sharding.AxisType)")
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.serving import serve_engine
    from tpu_dist_nn.testing.factories import random_model

    model = random_model([12, 10, 6], seed=3)
    path = tmp_path_factory.mktemp("integrity") / "model.json"
    save_model(model, path)
    eng_a = Engine.up(str(path), [1, 1])
    eng_b = Engine.up(str(path), [1, 1])
    server_a, port_a = serve_engine(eng_a, 0)
    server_b, port_b = serve_engine(eng_b, 0)
    # Warm the compile caches so canary-probe timeouts never race a jit.
    warm = np.zeros((2, 12))
    eng_a.infer(warm.copy())
    eng_b.infer(warm.copy())
    yield {"eng_a": eng_a, "eng_b": eng_b,
           "port_a": port_a, "port_b": port_b, "path": str(path)}
    server_a.stop(grace=0.5)
    server_b.stop(grace=0.5)
    eng_a.down()
    eng_b.down()


def test_guard_partial_rows_failover_bit_parity(fleet):
    """The guard's core contract: poisoned rows fail, unaffected rows
    in the SAME launch ship bit-identical to a clean run."""
    eng = fleet["eng_a"]
    rng = np.random.default_rng(11)
    x = rng.uniform(0, 1, (3, 12))
    clean = eng.infer(x.copy())
    eng.launch_hook = faults.nan_launch(rows=(1,))
    try:
        pending = eng.infer_async(x.copy())
        out = eng.fetch(pending)
    finally:
        eng.launch_hook = None
    bad = pending.bad_rows
    assert bad is not None and bad.tolist() == [False, True, False]
    assert np.isnan(out[1]).all()
    # Bit parity, not allclose: the unaffected rows rode the same
    # launch shape, so they must be the SAME bytes.
    assert np.array_equal(out[0], clean[0])
    assert np.array_equal(out[2], clean[2])


def test_guard_all_rows_poisoned_fails_the_launch(fleet):
    from tpu_dist_nn.utils.errors import IntegrityError

    eng = fleet["eng_a"]
    x = np.random.default_rng(12).uniform(0, 1, (3, 12))
    eng.launch_hook = faults.nan_launch(rows=(0, 1, 2))
    try:
        with pytest.raises(IntegrityError, match="numeric guard"):
            eng.fetch(eng.infer_async(x))
    finally:
        eng.launch_hook = None


def test_direct_infer_raises_on_any_bad_row(fleet):
    """engine.infer() is ONE request: row-level failover collapses to
    request granularity — a partially poisoned direct call must raise,
    never hand the caller a batch with NaN rows hidden inside."""
    from tpu_dist_nn.utils.errors import IntegrityError

    eng = fleet["eng_a"]
    x = np.random.default_rng(13).uniform(0, 1, (3, 12))
    eng.launch_hook = faults.nan_launch(rows=(1,))
    try:
        with pytest.raises(IntegrityError, match="numeric guard"):
            eng.infer(x)
    finally:
        eng.launch_hook = None


def test_wire_poisoned_request_is_data_loss_clean_request_ships(fleet):
    import grpc

    from tpu_dist_nn.serving import GrpcClient

    eng = fleet["eng_a"]
    client = GrpcClient(f"127.0.0.1:{fleet['port_a']}")
    try:
        x = np.random.default_rng(14).uniform(0, 1, (1, 12))
        eng.launch_hook = faults.nan_launch(rows=(0,))
        try:
            with pytest.raises(grpc.RpcError) as e:
                client.process(x)
            assert e.value.code() == grpc.StatusCode.DATA_LOSS
        finally:
            eng.launch_hook = None
        # The replica is not broken, only that launch was: the next
        # request ships normally (the router's failover + strike
        # accounting owns the replica-level consequence).
        out = client.process(x)
        assert np.isfinite(out).all()
    finally:
        client.close()


def test_guard_mask_semantics_and_disable_opt_outs():
    g = integrity.NumericGuard(enabled=True, abs_limit=1e8)
    out = np.ones((4, 3))
    out[1, 2] = np.nan
    out[3, 0] = 1e9  # finite but absurd: past abs_limit
    assert g.bad_rows(out).tolist() == [False, True, False, True]
    # Non-float, empty, and 0-d outputs are not the guard's domain.
    assert g.bad_rows(np.ones((2, 2), dtype=np.int64)) is None
    assert g.bad_rows(np.ones((0, 3))) is None
    assert g.bad_rows(np.float64(np.nan)) is None
    assert integrity.NumericGuard(enabled=False).bad_rows(out) is None


# ------------------------------------------------------ canary (rung 3)


class _FakeRep:
    """The prober's minimal replica surface: .call + .target. ``mangle``
    post-processes the deterministic reply (the tamper arm)."""

    def __init__(self, target, mangle=None, per_call_s=0.0):
        self.target = target
        self._mangle = mangle
        self._per_call_s = per_call_s
        self.calls = 0

    def call(self, method, payload, *, timeout=None, metadata=()):
        self.calls += 1
        if self._per_call_s:
            time.sleep(self._per_call_s)
        reply = b"reply:" + method.encode() + b":" + payload
        if self._mangle is not None:
            reply = self._mangle(reply)
        return reply


def _tamper_last_byte(reply: bytes) -> bytes:
    b = bytearray(reply)
    b[-1] ^= 0x01  # the wire float's low-order bits: decodes, lies
    return bytes(b)


def test_canary_golden_stable_across_prober_restarts():
    """The canary input is a constant of the system (CANARY_SEED), so a
    restarted prober — a new router process — regenerates the SAME
    payload and converges on the SAME golden digest. No state handoff
    needed for the golden to survive restarts."""
    p1 = integrity.CanaryProber(dim=8, timeout=1.0)
    p2 = integrity.CanaryProber(dim=8, timeout=1.0)
    assert p1._payloads["Process"] == p2._payloads["Process"]

    rep = _FakeRep("10.0.0.1:9")
    verdict, ev = p1.probe(rep)
    assert verdict is True and ev.get("methods") == ["Process"]
    verdict, _ = p2.probe(rep)  # the "restarted" prober
    assert verdict is True
    assert p1.golden == p2.golden
    assert p1.snapshot()["golden_source"]["Process"] == rep.target

    # A different seed is a DIFFERENT canary — the fleet-wide constant
    # is what makes digests comparable at all.
    assert integrity.CanaryProber(
        dim=8, seed=integrity.CANARY_SEED + 1, timeout=1.0
    )._payloads["Process"] != p1._payloads["Process"]


def test_canary_flags_tampered_reply_and_transport_is_not_a_verdict():
    prober = integrity.CanaryProber(dim=8, timeout=1.0)
    honest = _FakeRep("good:1")
    liar = _FakeRep("bad:1", mangle=_tamper_last_byte)
    assert prober.probe(honest)[0] is True  # establishes the golden

    verdict, ev = prober.probe(liar)
    assert verdict is False
    assert ev["golden"] == prober.golden["Process"]
    assert ev["golden_source"] == honest.target
    assert ev["digest"] != ev["golden"]

    class _Dead:
        target = "dead:1"

        def call(self, *a, **k):
            raise ConnectionError("refused")

    # Unreachable is the breaker's problem: verdict None, not False.
    verdict, ev = prober.probe(_Dead())
    assert verdict is None and "error" in ev


# ------------------------------------------- quarantine choreography


def test_quarantine_lifecycle_detect_drain_refusal_evidence_reverify(fleet):
    """The full ladder against two REAL replicas: verdict -> placement
    stops + evidence bundle, drain refuses to bypass the quarantine,
    reverify refuses while the replica is still corrupt, readmits once
    it answers on-golden again, three guard strikes re-quarantine, and
    force=True is the operator's break-glass."""
    from tpu_dist_nn.serving.pool import ReplicaPool

    target_a = f"127.0.0.1:{fleet['port_a']}"
    target_b = f"127.0.0.1:{fleet['port_b']}"
    pool = ReplicaPool([target_a, target_b], seed=5)
    try:
        prober = integrity.CanaryProber(dim=12, timeout=10.0)
        pool.canary = prober
        rep_b = next(r for r in pool.replicas() if r.target == target_b)
        verdict, _ = prober.probe(rep_b)  # golden from the healthy side
        assert verdict is True

        events = []
        pool.on_quarantine = lambda t, r, e: events.append((t, r, dict(e)))

        # Detect: the verdict moves A out of rotation and freezes the
        # evidence through the incident hook.
        assert pool.quarantine(target_a, reason="drill",
                               evidence={"planted": True}) is True
        assert pool.quarantine(target_a, reason="drill") is False  # no-op
        snap = {s["target"]: s for s in pool.snapshot()}
        assert snap[target_a]["state"] == "quarantined"
        assert snap[target_a]["quarantine_reason"] == "drill"
        assert events == [(target_a, "drill", {"planted": True})]

        # Quarantine dominates drain: the drain path would auto-rejoin
        # on the next ready scrape, bypassing reverify.
        assert pool.drain(target_a) is False
        for _ in range(12):
            placed = pool.place()
            assert placed is not None and placed.target == target_b

        # Reverify refuses while A still computes wrong: every canary
        # row poisoned -> the guard fails the probe launch -> no
        # on-golden answer, no readmission.
        fleet["eng_a"].launch_hook = faults.nan_launch(rows=(0, 1))
        try:
            res = pool.unquarantine(target_a)
            assert res["ok"] is False
            assert res["checks"]["canary"]["ok"] is False
        finally:
            fleet["eng_a"].launch_hook = None

        # Fault cleared -> the canary answers on-golden -> readmitted
        # with strikes reset and placement restored.
        res = pool.unquarantine(target_a)
        assert res["ok"] is True and res["checks"]["canary"]["ok"] is True
        snap = {s["target"]: s for s in pool.snapshot()}
        assert snap[target_a]["state"] == "active"
        assert snap[target_a].get("integrity_strikes", 0) == 0

        # Three observed INTEGRITY replies = the guard verdict: the
        # router's strike counter quarantines without any probe.
        for _ in range(pool.guard_quarantine_threshold):
            pool.note_integrity_error(target_a)
        snap = {s["target"]: s for s in pool.snapshot()}
        assert snap[target_a]["state"] == "quarantined"
        assert snap[target_a]["quarantine_reason"] == "guard"
        assert events[-1][1] == "guard"
        assert events[-1][2]["integrity_errors"] == \
            pool.guard_quarantine_threshold

        # Break-glass: force skips the checks (and says so).
        res = pool.unquarantine(target_a, force=True)
        assert res["ok"] is True and res["forced"] is True
    finally:
        pool.close(grace=0.5)


# --------------------------------------------- spot-checking (rung 4)


class _FakePool:
    """The SpotChecker's minimal pool surface over _FakeRep shadows."""

    def __init__(self, reps):
        self._reps = list(reps)
        self.begun = []

    def replicas(self):
        return list(self._reps)

    def place(self, session_key=None, exclude=frozenset()):
        for r in self._reps:
            if r.target not in exclude:
                return r
        return None

    def begin(self, rep):
        self.begun.append(rep.target)

    def done(self, rep):
        pass


def test_spotcheck_tamper_mismatch_arbitrates_to_guilty_replica():
    """Two replicas disagree on a real request's bytes; disagreement
    alone cannot convict, so the checker canary-probes BOTH and indicts
    only the one answering off-golden."""
    honest = _FakeRep("good:2")
    liar = _FakeRep("bad:2", mangle=_tamper_last_byte)
    pool = _FakePool([honest, liar])
    prober = integrity.CanaryProber(dim=4, timeout=1.0)
    assert prober.probe(honest)[0] is True  # golden established

    verdicts = []
    checker = integrity.SpotChecker(
        pool, rate=1.0, seed=21, timeout=1.0, canary=prober,
        on_verdict=lambda t, reason, ev: verdicts.append((t, reason, ev)),
    )
    # Only Process traffic is shadowed (Generate is stateful).
    assert checker.maybe_check("Generate", b"p", b"r", liar.target) is False

    # The liar served a real request; its tampered reply disagrees with
    # the honest shadow's bytes.
    payload = b"real-request-payload"
    tampered_reply = liar.call("Process", payload)
    assert checker.maybe_check(
        "Process", payload, tampered_reply, liar.target
    ) is True
    deadline = time.monotonic() + 5.0
    while not verdicts and time.monotonic() < deadline:
        time.sleep(0.01)

    assert [(t, r) for t, r, _ in verdicts] == [(liar.target, "spotcheck")]
    ev = verdicts[0][2]
    assert ev["detector"] == "spotcheck"
    assert ev["disagreed_with"] == honest.target
    assert checker.mismatches == 1
    # The shadow went through the pool's load accounting, excluded from
    # the primary.
    assert pool.begun == [honest.target]


def test_spotcheck_match_is_silent_and_rate_zero_never_samples():
    honest = _FakeRep("good:3")
    twin = _FakeRep("good:4")
    pool = _FakePool([twin, honest])
    verdicts = []
    checker = integrity.SpotChecker(
        pool, rate=1.0, seed=2, timeout=1.0,
        canary=None, on_verdict=lambda *a: verdicts.append(a),
    )
    payload = b"agreeing-payload"
    reply = honest.call("Process", payload)
    assert checker.maybe_check("Process", payload, reply,
                               honest.target) is True
    deadline = time.monotonic() + 5.0
    while checker._inflight and time.monotonic() < deadline:
        time.sleep(0.01)
    assert checker.mismatches == 0 and verdicts == []

    never = integrity.SpotChecker(pool, rate=0.0, seed=2)
    assert never.maybe_check("Process", b"p", b"r", honest.target) is False
    with pytest.raises(ValueError):
        integrity.SpotChecker(pool, rate=1.5)


# ------------------------------------------------- end-to-end drill


def test_corruption_drill_scenario_quarantines_exactly_one():
    """The checked-in corruption cell end-to-end: replica 0 poisons
    every launch, the guard fails them DATA_LOSS, the router fails over
    (clients keep getting answers — zero wrong bytes shipped), three
    strikes quarantine exactly that replica, and the availability SLO
    holds on the surviving pair."""
    from tpu_dist_nn.obs import replay as R

    verdict = R.run_scenario_file(
        os.path.join(REPO, "scenarios", "silent_corruption_quarantine.json"),
        quick_scale=0.5,
    )
    assert verdict["passed"] is True
    integ = verdict["integrity"]
    assert integ["passed"] is True
    assert [q["reason"] for q in integ["quarantined"]] == ["guard"]
    assert integ["quarantined"][0]["strikes"] >= 3
    # The guard fired (faults_fired counts the poisoned launches) and
    # the client-side replay saw NO errors: every request that landed
    # on the corrupt replica failed over to a clean answer.
    assert verdict["faults_fired"] > 0
    assert verdict["replay"]["errors"] == {}
    assert all(o["passed"] for o in verdict["objectives"])


def test_decode_step_guard_fails_bad_slot_alone():
    """The in-launch decode guard: a slot whose step comes back not-ok
    fails over ALONE with IntegrityError mid-generation; the other
    resident slot's stream is untouched and completes. Driven through
    the injected-kernel scheduler by replacing the internal ``_step``
    with one that returns the 3-tuple an ok vector rides on (the public
    ``step_fn`` seam stays 2-tuple — construction wraps it to ok=None,
    which must leave the guard disarmed)."""
    import threading

    from tpu_dist_nn.serving.continuous import ContinuousScheduler
    from tpu_dist_nn.utils.errors import IntegrityError

    T, N = 4, 40  # a long budget: the victim pair overlaps for ~200ms

    def fake_prefill(params, cache, slot, tokens, start, key):
        return np.int32(1), cache

    def fake_step(params, cache, pos, active, tok, key):
        time.sleep(0.005)
        return np.asarray(tok) + 1, cache

    sched = ContinuousScheduler(
        None, None, prefill_fn=fake_prefill, step_fn=fake_step,
        slots=2, prompt_len=T, max_new_tokens=N,
    )
    wrapped = sched._step
    try:
        # The ctor-wrapped seam reports ok=None: guard disarmed, a
        # plain submit completes even with GUARD force-enabled.
        out = sched.submit(np.ones((1, T), np.int32), max_new_tokens=2)
        assert out.shape == (1, T + N)

        def poisoned(params, cache, pos, active, tok, key):
            toks, _ok, cache = wrapped(params, cache, pos, active, tok, key)
            ok = np.ones(2, bool)
            if active[0] and active[1]:  # both resident: indict slot 1
                ok[1] = False
            return toks, ok, cache

        sched._step = poisoned
        outs, errs = [], []

        def caller(seed):
            try:
                outs.append(sched.submit(np.full((1, T), seed, np.int32)))
            except Exception as e:  # noqa: BLE001 — collected
                errs.append(e)

        threads = [threading.Thread(target=caller, args=(s,))
                   for s in (3, 4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        # Exactly one row was indicted (whichever bound slot 1) and the
        # other finished its full budget despite sharing every launch
        # with the poisoned slot.
        assert len(errs) == 1 and isinstance(errs[0], IntegrityError)
        assert "slot 1" in str(errs[0])
        assert len(outs) == 1 and outs[0].shape == (1, T + N)
    finally:
        sched._step = wrapped
        sched.close(timeout=5.0)
