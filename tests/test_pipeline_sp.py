"""Pipeline x sequence parallelism: long context through the pipeline.

The composition `tdn lm --stages S --seq-parallel N` used to reject —
blocks pipelined over `stage`, each microbatch's sequence dim sharded
over `seq` with ring/Ulysses attention inside the stage, batch over
`data`. Parity target: the single-chip forward on full rows and the
position-0-masked CE (the sp-only loss's convention), so pp x sp,
sp-only, and single-chip are all numerically comparable.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    forward,
    init_transformer,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_sp_lm_forward,
    make_pipeline_sp_lm_loss,
    shard_blocks,
    unshard_blocks,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq_len=16
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), jnp.int32)


def _masked_ce(params, tokens):
    """Single-chip reference with the sp masking convention: full rows
    in, score positions 0..T-2 against targets 1..T-1."""
    logits = forward(params, tokens, CFG)
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    targets = tokens[:, 1:]
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


@pytest.mark.parametrize("stage,seq,data,mode", [
    (2, 2, 2, "ring"),
    (2, 4, 1, "ring"),
    (2, 2, 2, "ulysses"),
])
def test_pp_sp_forward_matches_single_chip(stage, seq, data, mode):
    mesh = build_mesh(MeshSpec(stage=stage, seq=seq, data=data))
    params = init_transformer(jax.random.key(1), CFG)
    tokens = _tokens(batch=8, seq=16, seed=2)

    ref = forward(params, tokens, CFG)
    fwd = make_pipeline_sp_lm_forward(
        mesh, CFG, num_stages=stage, num_microbatches=2, mode=mode
    )
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], stage))
    out = jax.jit(fwd)(params_pp, tokens)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-5, atol=2e-5
    )


def test_pp_sp_loss_and_grads_match_single_chip():
    stage, seq, data = 2, 2, 2
    mesh = build_mesh(MeshSpec(stage=stage, seq=seq, data=data))
    params = init_transformer(jax.random.key(3), CFG)
    tokens = _tokens(batch=8, seq=16, seed=4)

    loss_fn = make_pipeline_sp_lm_loss(
        mesh, CFG, num_stages=stage, num_microbatches=2
    )
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], stage))
    loss_pp, g_pp = jax.jit(jax.value_and_grad(loss_fn))(params_pp, tokens)
    loss_ref, g_ref = jax.jit(jax.value_and_grad(_masked_ce))(params, tokens)
    np.testing.assert_allclose(float(loss_ref), float(loss_pp), rtol=1e-5)

    g_blocks = unshard_blocks(g_pp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_pp[k]), rtol=5e-4, atol=1e-5,
        )


def test_pp_sp_agrees_with_sp_only():
    # Transitivity anchor: pp x sp equals the existing sp-only path on
    # the same tokens (both use the masked-CE convention).
    from tpu_dist_nn.parallel.ring_attention import make_seq_parallel_lm_loss

    params = init_transformer(jax.random.key(5), CFG)
    tokens = _tokens(batch=4, seq=16, seed=6)

    pp_mesh = build_mesh(MeshSpec(stage=2, seq=2, data=2))
    loss_pp = make_pipeline_sp_lm_loss(pp_mesh, CFG, 2, 2)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    v_pp = float(jax.jit(loss_pp)(params_pp, tokens))

    sp_mesh = build_mesh(MeshSpec(seq=4, data=2))
    loss_sp = make_seq_parallel_lm_loss(sp_mesh, CFG)
    v_sp = float(jax.jit(loss_sp)(params, tokens))
    np.testing.assert_allclose(v_sp, v_pp, rtol=1e-5)


def test_pp_sp_validates_divisibility():
    mesh = build_mesh(MeshSpec(stage=2, seq=2, data=2))
    fwd = make_pipeline_sp_lm_forward(mesh, CFG, 2, 2)
    params = init_transformer(jax.random.key(0), CFG)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    with pytest.raises(ValueError, match="not divisible by seq axis"):
        fwd(params_pp, _tokens(batch=4, seq=15))
    with pytest.raises(ValueError, match="microbatches"):
        fwd(params_pp, _tokens(batch=3, seq=16))


def test_pp_sp_train_step_runs():
    import optax

    from tpu_dist_nn.train.lm_trainer import make_pipeline_sp_lm_train_step

    mesh = build_mesh(MeshSpec(stage=2, seq=2, data=2))
    params = init_transformer(jax.random.key(7), CFG)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    optimizer = optax.adam(1e-2)
    step = make_pipeline_sp_lm_train_step(mesh, CFG, 2, 2, optimizer)
    tokens = _tokens(batch=8, seq=16, seed=8)
    new_params, _, loss = step(params_pp, optimizer.init(params_pp), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_qkv"]),
        np.asarray(params_pp["blocks"]["w_qkv"]),
    )


def test_cli_lm_pp_sp(tmp_path, capsys):
    # The previously rejected flag combination end to end: tdn lm
    # --stages 2 --seq-parallel 2 trains and reports metrics.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--stages", "2", "--seq-parallel", "2",
        "--microbatches", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "perplexity" in out

@pytest.mark.parametrize("seq,data,mode", [
    (2, 2, "ulysses"),
    (4, 1, "ulysses"),
    (2, 2, "ring"),
    (4, 1, "ring"),
])
def test_pp_sp_1f1b_grads_match_single_chip(seq, data, mode):
    # 1F1B x SP: the memory-flat schedule with sequence-parallel
    # attention in the stage bodies — loss and grads must equal
    # single-chip AD of the masked CE (the same oracle the gpipe
    # pp x sp path is pinned to, so all three agree transitively).
    # Ulysses runs its all_to_alls unchanged; the ring swaps its
    # ppermute K/V rotation for the branch-safe group-local
    # reduce-scatter (_rotate_one_hop_group_local) — ppermute inside
    # the switch computes wrong values (tools/repro_ring_1f1b.py).
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_sp_lm_1f1b_grad,
    )

    mesh = build_mesh(MeshSpec(stage=2, seq=seq, data=data))
    params = init_transformer(jax.random.key(11), CFG)
    tokens = _tokens(batch=8, seq=16, seed=12)

    vag = make_pipeline_sp_lm_1f1b_grad(
        mesh, CFG, num_stages=2, num_microbatches=2, mode=mode
    )
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    loss_pp, g_pp = jax.jit(vag)(params_pp, tokens)
    loss_ref, g_ref = jax.jit(jax.value_and_grad(_masked_ce))(params, tokens)
    np.testing.assert_allclose(float(loss_ref), float(loss_pp), rtol=1e-5)

    g_blocks = unshard_blocks(g_pp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_pp[k]), rtol=5e-4, atol=1e-5,
        )


def test_ring_collective_rotation_matches_ppermute():
    # The branch-safe rotation is numerically the ppermute ring: same
    # attention outputs outside any schedule, where both are legal.
    from jax.sharding import PartitionSpec as P

    from tpu_dist_nn.models.transformer import dot_product_attention
    from tpu_dist_nn.parallel.ring_attention import ring_attention

    rng = np.random.default_rng(21)
    B, T, H, Dh = 2, 16, 4, 8
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, T, H, Dh)), jnp.float32)
        for _ in range(3)
    )
    ref = dot_product_attention(q, k, v, causal=True)
    mesh = build_mesh(MeshSpec(seq=4))
    for rotate in ("ppermute", "collective"):
        fn = jax.jit(jax.shard_map(
            lambda q, k, v, _r=rotate: ring_attention(
                q, k, v, causal=True, rotate=_r
            ),
            mesh=mesh,
            in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"),
        ))
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(fn(q, k, v)),
            rtol=2e-5, atol=2e-5, err_msg=rotate,
        )
    with pytest.raises(ValueError, match="rotate mode"):
        ring_attention(q, k, v, causal=True, rotate="bogus")


def test_cli_lm_pp_sp_1f1b(capsys):
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--stages", "2", "--seq-parallel", "2",
        "--sp-mode", "ulysses", "--schedule", "1f1b",
        "--microbatches", "2",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out
    # ring + 1f1b trains too (the in-schedule ring uses the
    # branch-safe group-local rotation).
    rc = main([
        "--platform", "cpu", "lm", "--steps", "1", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--stages", "2", "--seq-parallel", "2",
        "--schedule", "1f1b", "--microbatches", "2",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out


@pytest.mark.parametrize("variant,mode", [
    ("interleaved", "ulysses"),
    ("zb", "ulysses"),
    ("interleaved", "ring"),
    ("zb", "ring"),
])
def test_pp_sp_interleaved_and_zb_grads_match_single_chip(variant, mode):
    # The table-driven executors x SP: interleaved virtual stages and
    # the zero-bubble split backward both play back with
    # sequence-parallel attention in the chunk bodies — grads must
    # equal single-chip AD of the masked CE, completing the
    # schedule x SP row of the composition matrix. The ring rows use
    # the branch-safe group-local rotation (the table executor has the
    # same lax.switch structure ppermute misbehaves in).
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_sp_lm_interleaved_grad,
        make_pipeline_sp_lm_zb_grad,
        shard_blocks_interleaved,
        unshard_blocks_interleaved,
    )

    S, v = 2, 2
    mesh = build_mesh(MeshSpec(stage=S, seq=2, data=2))
    params = init_transformer(jax.random.key(13), CFG)
    tokens = _tokens(batch=8, seq=16, seed=14)

    make = (
        make_pipeline_sp_lm_interleaved_grad
        if variant == "interleaved" else make_pipeline_sp_lm_zb_grad
    )
    vag = make(mesh, CFG, num_virtual=v, num_microbatches=2, mode=mode)
    params_v = dict(
        params, blocks=shard_blocks_interleaved(params["blocks"], S, v)
    )
    loss_v, g_v = jax.jit(vag)(params_v, tokens)
    loss_ref, g_ref = jax.jit(jax.value_and_grad(_masked_ce))(params, tokens)
    np.testing.assert_allclose(float(loss_ref), float(loss_v), rtol=1e-5)

    g_blocks = unshard_blocks_interleaved(g_v["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_v[k]), rtol=5e-4, atol=1e-5,
        )


