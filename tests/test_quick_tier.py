"""Meta-guards for the `-m quick` tier (conftest.QUICK_TESTS).

The quick tier is a curated list; lists rot. These tests make the rot
loud: every test module must contribute at least one quick test, and
every curated entry must still resolve to a real test in its module —
a renamed or deleted test fails here instead of silently shrinking the
tier's coverage.
"""

import glob
import os
import re

from tests.conftest import QUICK_TESTS

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


def _modules():
    return sorted(
        os.path.basename(p)[:-3]
        for p in glob.glob(os.path.join(TESTS_DIR, "test_*.py"))
    )


def test_every_module_has_a_quick_entry():
    missing = [m for m in _modules() if m not in QUICK_TESTS]
    assert not missing, (
        f"test modules without a quick-tier entry: {missing} — add "
        "representatives to tests/conftest.py QUICK_TESTS"
    )


def test_every_quick_entry_resolves():
    stale = []
    for module, entries in QUICK_TESTS.items():
        path = os.path.join(TESTS_DIR, module + ".py")
        if not os.path.isfile(path):
            stale.append(f"{module}: module missing")
            continue
        src = open(path).read()
        for entry in entries:
            if entry == "*":
                continue
            bare = entry.split("[")[0]
            if not re.search(rf"def {re.escape(bare)}\(", src):
                stale.append(f"{module}::{entry}")
    assert not stale, f"quick-tier entries that no longer resolve: {stale}"


def test_bracketed_quick_entries_match_collected_ids():
    # A source-regex check cannot see parametrize ids: renaming a
    # param (e.g. [4-2] -> [expert4-groups2]) would silently drop the
    # entry from the tier while the bare-name check still passes. This
    # collects the bracketed modules for real (subprocess — collection
    # imports them) and requires every bracketed id to exist.
    import subprocess
    import sys

    bracketed = {
        module: [e for e in entries if "[" in e]
        for module, entries in QUICK_TESTS.items()
        if any("[" in e for e in entries)
    }
    files = [os.path.join(TESTS_DIR, m + ".py") for m in bracketed]
    out = subprocess.run(
        [sys.executable, "-m", "pytest", "--collect-only", "-q",
         "-p", "no:cacheprovider", *files],
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(TESTS_DIR),
    )
    collected = set()
    for line in out.stdout.splitlines():
        if "::" in line:
            # Final segment only (class-scoped tests carry
            # `file::Class::name[id]`) — the same name-based matching
            # conftest's marker application uses.
            collected.add(line.strip().rsplit("::", 1)[1])
    assert collected, f"collection produced nothing:\n{out.stdout[-2000:]}"
    missing = [
        f"{m}::{e}"
        for m, entries in bracketed.items()
        for e in entries
        if e not in collected
    ]
    assert not missing, (
        f"bracketed quick-tier ids not collected (param ids changed?): "
        f"{missing}"
    )
