"""Flash-attention kernel parity vs the jnp reference attention —
forward and custom-VJP backward, causal and bidirectional, ragged
lengths, and as an attn_fn swapped into the transformer block."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.kernels.flash_attention import flash_attention
from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    dot_product_attention,
    forward,
    init_transformer,
    lm_loss,
)


def _qkv(B, T, H, Dh, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, T, H, Dh)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("T", [32, 48])  # 48: ragged (pads to block)
def test_forward_matches_reference(causal, T):
    q, k, v = _qkv(2, T, 2, 16)
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_reference(causal):
    q, k, v = _qkv(2, 32, 2, 8, seed=1)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=16, block_k=16) ** 2
        )

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_grads_match_reference_ragged_causal():
    # T=24 with block 16 -> padded to 32; padded keys must not leak
    # into outputs or gradients.
    q, k, v = _qkv(1, 24, 2, 8, seed=2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=16, block_k=16) ** 2
        )

    np.testing.assert_allclose(
        float(loss_ref(q, k, v)), float(loss_flash(q, k, v)), rtol=1e-5
    )
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_out):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
        )


def test_swaps_into_transformer_forward_and_loss():
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=32,
    )
    params = init_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (2, 32)), jnp.int32
    )

    def flash_fn(q, k, v, *, causal):
        return flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)

    ref_logits = forward(params, tokens, cfg)
    out_logits = forward(params, tokens, cfg, attn_fn=flash_fn)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(out_logits), rtol=2e-4, atol=2e-4
    )

    # jitted: eager grad-through-interpret-mode-pallas is the suite's
    # slowest single test otherwise (and never hits the compile cache).
    g_ref = jax.jit(jax.grad(lm_loss), static_argnums=2)(params, tokens, cfg)
    g_out = jax.jit(
        jax.grad(lambda p, t: lm_loss(p, t, cfg, attn_fn=flash_fn))
    )(params, tokens)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_out)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        )


@pytest.mark.parametrize("bq,bk", [(16, 8), (8, 16), (32, 8)])
def test_mismatched_block_sizes_with_ragged_length(bq, bk):
    # T=40 doesn't divide either block size; padding must extend to a
    # common multiple of both or keys/rows are silently dropped.
    q, k, v = _qkv(1, 40, 2, 8, seed=3)
    ref = dot_product_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block_q=bq, block_k=bk)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-5, atol=2e-5
    )


def test_rejects_mismatched_shapes():
    q, k, v = _qkv(1, 16, 2, 8)
    with pytest.raises(ValueError, match="must match"):
        flash_attention(q, k[:, :8], v, causal=True)
