"""1F1B schedule: numerical parity with the GPipe-AD training path.

Both schedules must compute the identical (loss, grads) — masked mean
CE through the padded stage chain — so a user can switch schedules for
the memory profile without changing training semantics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_dist_nn.core.schema import partition_model
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.one_f_one_b import compiled_1f1b_grad
from tpu_dist_nn.parallel.pipeline import (
    build_pipeline_params,
    compiled_pipeline,
)
from tpu_dist_nn.testing.factories import random_model
from tpu_dist_nn.train.pipeline_trainer import (
    make_pipeline_train_step,
    prepare_pipeline_batch,
)


def _gpipe_loss_and_grad(mesh, params, num_microbatches, xs, labels, mask):
    weights, meta = params
    apply = compiled_pipeline(mesh, meta, num_microbatches, True, weights.w.dtype)

    def loss_fn(w):
        logits = apply(w, xs)
        logp = jax.nn.log_softmax(logits, axis=-1)
        flat = labels.reshape(-1)
        ll = jnp.take_along_axis(logp, flat[:, None], axis=-1)[:, 0]
        return -(ll * mask.reshape(-1)).sum() / mask.sum()

    # jitted: eager grad never hits the persistent compile cache
    return jax.jit(jax.value_and_grad(loss_fn))(weights)


def _setup(dims, distribution, stage, data, n_rows, num_microbatches, seed=0):
    mesh = build_mesh(MeshSpec(stage=stage, data=data))
    model = random_model(dims, seed=seed)
    params = build_pipeline_params(partition_model(model, distribution))
    rng = np.random.default_rng(seed + 1)
    x = rng.normal(size=(n_rows, dims[0])).astype(np.float32)
    y = rng.integers(0, dims[-1], size=n_rows)
    xs, labels, lmask = prepare_pipeline_batch(
        params.meta, x, y, num_microbatches, data
    )
    return mesh, params, jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(lmask)


@pytest.mark.parametrize(
    "dims,distribution,stage,data,mbatches,rows",
    [
        ([12, 10, 8, 6], [1, 1, 1], 3, 2, 4, 24),      # canonical 3-stage
        ([9, 7, 5], [2], 1, 4, 2, 16),                 # single stage (no hops)
        ([12, 10, 8, 6, 4], [2, 2], 2, 4, 6, 48),      # multi-layer stages
        ([12, 10, 8, 6], [1, 1, 1], 3, 2, 2, 12),      # M < S (short pipeline)
        ([12, 10, 8, 6], [1, 1, 1], 3, 1, 1, 3),       # M = 1 degenerate
    ],
)
def test_1f1b_matches_gpipe_grads(dims, distribution, stage, data, mbatches, rows):
    mesh, params, xs, labels, lmask = _setup(
        dims, distribution, stage, data, rows, mbatches
    )
    loss_g, grads_g = _gpipe_loss_and_grad(mesh, params, mbatches, xs, labels, lmask)
    run = compiled_1f1b_grad(mesh, params.meta, mbatches, jnp.float32)
    loss_f, grads_f = run(params.weights, xs, labels, lmask)

    np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-5)
    w_mask, b_mask = params.meta.grad_masks()
    # Compare within the real-layer blocks; outside them the GPipe path
    # produces nonzero identity-filler grads that the trainer masks away.
    np.testing.assert_allclose(
        np.asarray(grads_f.w) * w_mask,
        np.asarray(grads_g.w) * w_mask,
        rtol=1e-4,
        atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(grads_f.b) * b_mask,
        np.asarray(grads_g.b) * b_mask,
        rtol=1e-4,
        atol=1e-6,
    )


def test_1f1b_train_step_matches_gpipe():
    """One full optimizer step under each schedule lands on the same weights."""
    dims, distribution, stage, data, mbatches, rows = [12, 10, 8, 6], [1, 1, 1], 3, 2, 4, 24
    mesh, params, xs, labels, lmask = _setup(
        dims, distribution, stage, data, rows, mbatches
    )
    opt = optax.adam(1e-3)
    results = {}
    for schedule in ("gpipe", "1f1b"):
        step = make_pipeline_train_step(
            mesh, params.meta, mbatches, opt, schedule=schedule
        )
        state = opt.init(params.weights)
        w, _, loss = step(params.weights, state, xs, labels, lmask)
        results[schedule] = (np.asarray(w.w), np.asarray(w.b), float(loss))
    w_mask, b_mask = params.meta.grad_masks()
    np.testing.assert_allclose(results["1f1b"][2], results["gpipe"][2], rtol=1e-5)
    np.testing.assert_allclose(
        results["1f1b"][0] * w_mask, results["gpipe"][0] * w_mask, rtol=1e-4, atol=1e-6
    )
    np.testing.assert_allclose(
        results["1f1b"][1] * b_mask, results["gpipe"][1] * b_mask, rtol=1e-4, atol=1e-6
    )


def test_1f1b_rejects_unknown_schedule():
    mesh, params, *_ = _setup([9, 7, 5], [1, 1], 2, 2, 8, 2)
    with pytest.raises(ValueError, match="schedule"):
        make_pipeline_train_step(
            mesh, params.meta, 2, optax.adam(1e-3), schedule="pipedream"
        )


# ---------------------------------------------------------------------------
# Transformer LM pipeline on the generic 1F1B executor
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("remat", [False, True])
def test_lm_1f1b_matches_gpipe(remat):
    from tpu_dist_nn.models.transformer import TransformerConfig, init_transformer
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_lm_1f1b_grad,
        make_pipeline_lm_loss,
        shard_blocks,
    )

    cfg = TransformerConfig(
        vocab_size=37, d_model=16, n_heads=2, n_layers=4, d_ff=32,
        max_seq_len=12, remat=remat,
    )
    stages, data, mbatches = 2, 2, 4
    mesh = build_mesh(MeshSpec(stage=stages, data=data))
    params = init_transformer(jax.random.key(0), cfg)
    params = dict(params, blocks=shard_blocks(params["blocks"], stages))
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (mbatches * data * 2, 13)), jnp.int32)

    loss_fn = make_pipeline_lm_loss(mesh, cfg, stages, mbatches)
    loss_g, grads_g = jax.jit(jax.value_and_grad(loss_fn))(params, tokens)
    vag = jax.jit(make_pipeline_lm_1f1b_grad(mesh, cfg, stages, mbatches))
    loss_f, grads_f = vag(params, tokens)

    np.testing.assert_allclose(float(loss_f), float(loss_g), rtol=1e-5)
    flat_g = jax.tree.flatten_with_path(grads_g)[0]
    flat_f = jax.tree.flatten_with_path(grads_f)[0]
    for (path_g, leaf_g), (path_f, leaf_f) in zip(flat_g, flat_f):
        assert path_g == path_f
        np.testing.assert_allclose(
            np.asarray(leaf_f), np.asarray(leaf_g), rtol=2e-4, atol=1e-6,
            err_msg=str(path_g),
        )


def test_1f1b_memory_flat_in_microbatches():
    """The schedule's point: XLA-reported temp memory for the GPipe-AD
    step grows with the microbatch count M, the 1F1B step's does not
    (ring-buffer stash of min(S, M) inputs + recompute)."""
    dims, stage, data = [64, 64, 64, 64, 32], 4, 2
    mesh = build_mesh(MeshSpec(stage=stage, data=data))
    params = build_pipeline_params(
        partition_model(random_model(dims, seed=0), [1, 1, 1, 1])
    )
    opt = optax.adam(1e-3)

    def temp_bytes(schedule, M):
        rows = M * data * 8
        rng = np.random.default_rng(0)
        x = rng.normal(size=(rows, dims[0])).astype(np.float32)
        y = rng.integers(0, dims[-1], size=rows)
        xs, labels, mask = prepare_pipeline_batch(params.meta, x, y, M, data)
        step = make_pipeline_train_step(mesh, params.meta, M, opt, schedule=schedule)
        args = (
            params.weights, opt.init(params.weights),
            jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(mask),
        )
        mem = jax.jit(step).lower(*args).compile().memory_analysis()
        return mem.temp_size_in_bytes

    g4, g32 = temp_bytes("gpipe", 4), temp_bytes("gpipe", 32)
    f4, f32 = temp_bytes("1f1b", 4), temp_bytes("1f1b", 32)
    # GPipe-AD stashes per-tick activations: 8x the microbatches should
    # grow temp memory severalfold. 1F1B must stay (near) flat — allow
    # 50% slack for XLA scheduling noise — and beat GPipe at large M.
    assert g32 > 2 * g4, (g4, g32)
    assert f32 < 1.5 * f4, (f4, f32)
    assert f32 < g32 / 2, (f32, g32)


def test_1f1b_rejected_on_non_pipelined_lm():
    from tpu_dist_nn.models.transformer import TransformerConfig, init_transformer
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg = TransformerConfig(
        vocab_size=16, d_model=8, n_heads=2, n_layers=2, d_ff=16, max_seq_len=8
    )
    params = init_transformer(jax.random.key(0), cfg)
    rows = np.zeros((4, 9), np.int32)
    with pytest.raises(ValueError, match="pipelined dense LM"):
        train_lm(params, cfg, [rows], LMTrainConfig(steps=1), schedule="1f1b")
