"""Interleaved (virtual-stage) 1F1B: schedule compiler + executor parity.

The schedule tables are verified structurally at build time
(schedule_table.verify_tables replays them symbolically); these tests
add the numerical layer — the executor's (loss, grads) must equal plain
single-chip AD of the same model — plus bubble-optimality and layout
round-trip checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    init_transformer,
    lm_loss,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.schedule_table import build_interleaved_1f1b
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_lm_interleaved_grad,
    shard_blocks_interleaved,
    unshard_blocks_interleaved,
)


@pytest.mark.parametrize(
    "S,v,M",
    [(2, 1, 4), (2, 2, 4), (4, 2, 8), (3, 2, 5), (2, 3, 1), (1, 2, 3)],
)
def test_schedule_tables_build_and_verify(S, v, M):
    tb = build_interleaved_1f1b(S, v, M)  # verify_tables runs inside
    assert tb.ticks >= 2 * M * v
    # Stash is bounded by chunks in flight, far below the M*v total ops.
    assert tb.stash_slots <= S * v + S


def test_schedule_tables_large_v_converges():
    """The convergence safety bound must scale with V = S*v: S=16, v=8,
    M=1 needs ~128 forward ticks, which a bound linear in S alone
    spuriously rejected. Both builders must handle large-V shapes."""
    from tpu_dist_nn.parallel.schedule_table import build_interleaved_forward

    tb = build_interleaved_forward(16, 8, 1)
    assert tb.ticks >= 16 * 8  # at least V ticks to traverse the ring
    tb2 = build_interleaved_1f1b(16, 8, 1)
    assert tb2.ticks >= 2 * 16 * 8
    tb3 = build_interleaved_forward(8, 8, 1)  # previously a 64-vs-80 margin
    assert tb3.ticks >= 64


def test_megatron_order_hits_optimal_bubble():
    """With M % S == 0 the bubble must be the interleaved optimum
    2(S-1) chunk-ticks — v times less than contiguous-chunk 1F1B."""
    for S, v, M in [(2, 2, 4), (4, 2, 8), (4, 4, 8)]:
        tb = build_interleaved_1f1b(S, v, M)
        assert tb.bubble_ticks == 2 * (S - 1), (S, v, M, tb.bubble_ticks)


def test_shard_blocks_interleaved_round_trip():
    cfg = TransformerConfig(
        vocab_size=17, d_model=8, n_heads=2, n_layers=8, d_ff=16, max_seq_len=8
    )
    params = init_transformer(jax.random.key(0), cfg)
    staged = shard_blocks_interleaved(params["blocks"], 2, 2)
    assert jax.tree.leaves(staged)[0].shape[:3] == (2, 2, 2)
    back = unshard_blocks_interleaved(staged)
    for k in params["blocks"]:
        np.testing.assert_array_equal(back[k], params["blocks"][k])


@pytest.mark.parametrize("S,v,M,remat", [(2, 2, 4, False), (2, 2, 4, True), (2, 1, 2, False)])
def test_interleaved_lm_grads_match_single_chip(S, v, M, remat):
    cfg = TransformerConfig(
        vocab_size=29, d_model=16, n_heads=2, n_layers=S * v * 1, d_ff=32,
        max_seq_len=10, remat=remat,
    )
    mesh = build_mesh(MeshSpec(stage=S, data=2))
    params = init_transformer(jax.random.key(1), cfg)
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (M * 2 * 2, 11)), jnp.int32
    )

    loss_ref, grads_ref = jax.jit(
        jax.value_and_grad(lambda p, t: lm_loss(p, t, cfg))
    )(params, tokens)

    params_il = dict(params, blocks=shard_blocks_interleaved(params["blocks"], S, v))
    vag = jax.jit(make_pipeline_lm_interleaved_grad(mesh, cfg, v, M))
    loss_il, grads_il = vag(params_il, tokens)
    grads_il = dict(grads_il, blocks=unshard_blocks_interleaved(grads_il["blocks"]))

    np.testing.assert_allclose(float(loss_il), float(loss_ref), rtol=1e-5)
    flat_ref = jax.tree.flatten_with_path(grads_ref)[0]
    flat_il = jax.tree.flatten_with_path(grads_il)[0]
    for (path_r, leaf_r), (path_i, leaf_i) in zip(flat_ref, flat_il):
        assert path_r == path_i
        np.testing.assert_allclose(
            np.asarray(leaf_i), np.asarray(leaf_r), rtol=5e-4, atol=1e-6,
            err_msg=str(path_r),
        )


def test_interleaved_dense_chain_matches_gpipe():
    """Dense padded-chain chunks on the table executor: loss/grads match
    the GPipe-AD path run over the same V-chunk pipeline on V devices'
    worth of stages collapsed to S devices x v virtual."""
    import optax

    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.one_f_one_b import compiled_interleaved_dense_grad
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params, compiled_pipeline
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.train.pipeline_trainer import (
        make_pipeline_train_step,
        prepare_pipeline_batch,
    )

    S, v, M, data = 2, 2, 4, 2
    dims = [12, 10, 8, 6, 4]
    model = random_model(dims, seed=2)
    params = build_pipeline_params(partition_model(model, [1, 1, 1, 1]))  # V=4 chunks
    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, dims[0])).astype(np.float32)
    y = rng.integers(0, dims[-1], size=32)

    # Reference: GPipe-AD over a 4-stage mesh (the same 4 chunks, one per device).
    mesh_v = build_mesh(MeshSpec(stage=4, data=2))
    xs, labels, mask = prepare_pipeline_batch(params.meta, x, y, M, 2)
    apply = compiled_pipeline(mesh_v, params.meta, M, True, jnp.float32)

    def loss_fn(w):
        logits = apply(w, jnp.asarray(xs))
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels.reshape(-1)[:, None], axis=-1)[:, 0]
        return -(ll * mask.reshape(-1)).sum() / mask.sum()

    loss_ref, grads_ref = jax.jit(jax.value_and_grad(loss_fn))(params.weights)

    # Interleaved: same 4 chunks on 2 devices x 2 virtual.
    mesh_s = build_mesh(MeshSpec(stage=S, data=data))
    run = compiled_interleaved_dense_grad(mesh_s, params.meta, v, M, jnp.float32)
    loss_il, grads_il = run(
        params.weights, jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(mask)
    )

    np.testing.assert_allclose(float(loss_il), float(loss_ref), rtol=1e-5)
    w_mask, b_mask = params.meta.grad_masks()
    np.testing.assert_allclose(
        np.asarray(grads_il.w) * w_mask, np.asarray(grads_ref.w) * w_mask,
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(grads_il.b) * b_mask, np.asarray(grads_ref.b) * b_mask,
        rtol=1e-4, atol=1e-6,
    )

    # Full optimizer step through make_pipeline_train_step.
    opt = optax.adam(1e-3)
    step = make_pipeline_train_step(
        mesh_s, params.meta, M, opt, schedule="interleaved", num_virtual=v
    )
    w2, _, loss2 = step(
        params.weights, opt.init(params.weights),
        jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(mask),
    )
    np.testing.assert_allclose(float(loss2), float(loss_ref), rtol=1e-5)


def test_interleaved_dense_chunk_count_mismatch():
    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.one_f_one_b import compiled_interleaved_dense_grad
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model

    params = build_pipeline_params(
        partition_model(random_model([8, 6, 4], seed=0), [1, 1])
    )
    mesh = build_mesh(MeshSpec(stage=2, data=2))
    with pytest.raises(ValueError, match="distribution"):
        compiled_interleaved_dense_grad(mesh, params.meta, 2, 4, jnp.float32)


def test_engine_interleaved_inference_parity(tmp_path):
    # VERDICT r2 item 7: the interleaved (virtual-stage) schedule on the
    # ENGINE inference path. A 4-chunk dense model on 2 stage devices
    # (v=2) must reproduce the plain pipelined engine bit-for-bit.
    import numpy as np

    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.testing.factories import random_model

    model = random_model([12, 10, 8, 6, 4], seed=11)
    path = tmp_path / "m.json"
    save_model(model, path)
    x = np.random.default_rng(12).uniform(0, 1, (23, 12))

    ref = Engine.up(path, [1, 1, 1, 1]).infer(x)
    eng = Engine.up(path, [1, 1, 1, 1], virtual_stages=2, data_parallel=2)
    assert eng.placement()["virtual_stages"] == 2
    assert eng.placement()["devices"] == 4  # 2 stage devices x 2 data
    got = eng.infer(x)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-8)
    # (Engine-level interleaved TRAINING is covered by
    # test_engine_interleaved_dense_training_matches_gpipe.)


def test_cli_infer_virtual_stages(tmp_path, capsys):
    import numpy as np

    from tpu_dist_nn.cli import main
    from tpu_dist_nn.core.schema import save_examples, save_model
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.testing.oracle import oracle_forward_batch

    model = random_model([10, 8, 8, 6, 4], seed=13)
    mp = tmp_path / "m.json"
    save_model(model, mp)
    rng = np.random.default_rng(14)
    x = rng.uniform(0, 1, (12, 10))
    labels = oracle_forward_batch(model, x).argmax(-1)
    save_examples(x, labels, tmp_path / "e.json")
    rc = main([
        "infer", "--config", str(mp), "--inputs", str(tmp_path / "e.json"),
        "--distribution", "1,1,1,1", "--virtual-stages", "2",
    ])
    assert rc == 0
    assert "accuracy 1.0000" in capsys.readouterr().out


def test_forward_table_builder_rejects_and_verifies():
    from tpu_dist_nn.parallel.schedule_table import (
        build_interleaved_forward,
        verify_tables,
    )

    with pytest.raises(ValueError, match=">= 1"):
        build_interleaved_forward(0, 2, 2)
    # A healthy table re-verifies (the builder already did once).
    tb = build_interleaved_forward(2, 3, 5)
    verify_tables(tb, forward_only=True)
    assert tb.num_chunks == 6 and tb.ticks >= 5 * 3


def test_engine_virtual_stages_validation_and_degrade(tmp_path):
    import numpy as np

    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.utils.errors import InvalidArgumentError

    model = random_model([12, 10, 8, 6, 4], seed=15)
    path = tmp_path / "m.json"
    save_model(model, path)

    with pytest.raises(InvalidArgumentError, match=">= 1"):
        Engine.up(path, [1, 1, 1, 1], virtual_stages=0)
    with pytest.raises(InvalidArgumentError, match="divisible"):
        Engine.up(path, [2, 1, 1], virtual_stages=2)

    # Device shortage degrades to single-chip (the plain placement's
    # contract), it does not hard-fail.
    eng = Engine.up(path, [1, 1, 1, 1], virtual_stages=2, data_parallel=8)
    assert not eng.pipelined and eng.virtual_stages == 1
    x = np.random.default_rng(16).uniform(0, 1, (5, 12))
    assert eng.infer(x).shape == (5, 4)


def test_engine_interleaved_dense_training_matches_gpipe(tmp_path):
    # Engine-level interleaved dense TRAINING (closes the last scoping
    # gap): a virtual-stage placement trains through the table-driven
    # schedule and reproduces the gpipe engine's trajectory on the same
    # data/seed (the schedules are numerically interchangeable).
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.data.datasets import real_digits
    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params
    from tpu_dist_nn.train.trainer import TrainConfig

    params = init_fcnn(jax.random.key(20), [64, 24, 16, 12, 10])
    path = tmp_path / "m.json"
    save_model(spec_from_params(params, ["relu"] * 3 + ["softmax"]), path)
    tr, te = real_digits("train"), real_digits("test")
    cfg = TrainConfig(epochs=2, batch_size=64)

    eng_g = Engine.up(path, [1, 1, 1, 1])
    h_g = eng_g.train(tr, cfg, eval_data=te)
    eng_i = Engine.up(path, [1, 1, 1, 1], virtual_stages=2, data_parallel=2)
    assert eng_i.placement()["virtual_stages"] == 2
    h_i = eng_i.train(tr, cfg, eval_data=te)
    for a, b in zip(h_g, h_i):
        assert abs(a["loss"] - b["loss"]) < 1e-4
        assert abs(a["eval"]["accuracy"] - b["eval"]["accuracy"]) < 1e-6

    # The trained interleaved engine exports and re-serves correctly.
    out = tmp_path / "trained.json"
    eng_i.export(out)
    ref = eng_i.infer(te.x)
    got = Engine.up(out).infer(te.x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-7)

    # 1f1b is meaningless on a virtual placement: clear error.
    with pytest.raises(ValueError, match="1f1b.*interleaved|interleaved.*1f1b"):
        eng_i.train(tr, cfg, schedule="1f1b")
    # And interleaved without the placement points at --virtual-stages.
    with pytest.raises(ValueError, match="virtual_stages"):
        eng_g.train(tr, cfg, schedule="interleaved")


def test_interleaved_forward_single_device_self_loopback():
    # S=1, v>1: every chunk hand-off is device-LOCAL, riding the SELF
    # loopback channel. Regression for the channel-major receive
    # tables: the legacy abuf_write view is empty for self hops, so an
    # executor reading only the fwd wire would silently consume zeros
    # for every chunk after the first (wrong outputs, no error).
    from tpu_dist_nn.parallel.interleaved import make_interleaved_forward

    mesh = build_mesh(MeshSpec(stage=1, data=1))
    fwd = make_interleaved_forward(mesh, lambda p, st, x: x * p["k"],
                                   num_virtual=2, num_microbatches=2)
    params = {"k": jnp.asarray([[[2.0], [3.0]]])}  # (S=1, v=2, 1)
    xs = jnp.asarray([[1.0, 2.0, 3.0, 4.0], [5.0, 6.0, 7.0, 8.0]])
    out = jax.jit(lambda p, x: fwd(x, p, {}))(params, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(xs) * 6.0)
