"""Interleaved (virtual-stage) 1F1B: schedule compiler + executor parity.

The schedule tables are verified structurally at build time
(schedule_table.verify_tables replays them symbolically); these tests
add the numerical layer — the executor's (loss, grads) must equal plain
single-chip AD of the same model — plus bubble-optimality and layout
round-trip checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    init_transformer,
    lm_loss,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.schedule_table import build_interleaved_1f1b
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_lm_interleaved_grad,
    shard_blocks_interleaved,
    unshard_blocks_interleaved,
)


@pytest.mark.parametrize(
    "S,v,M",
    [(2, 1, 4), (2, 2, 4), (4, 2, 8), (3, 2, 5), (2, 3, 1), (1, 2, 3)],
)
def test_schedule_tables_build_and_verify(S, v, M):
    tb = build_interleaved_1f1b(S, v, M)  # verify_tables runs inside
    assert tb.ticks >= 2 * M * v
    # Stash is bounded by chunks in flight, far below the M*v total ops.
    assert tb.stash_slots <= S * v + S


def test_megatron_order_hits_optimal_bubble():
    """With M % S == 0 the bubble must be the interleaved optimum
    2(S-1) chunk-ticks — v times less than contiguous-chunk 1F1B."""
    for S, v, M in [(2, 2, 4), (4, 2, 8), (4, 4, 8)]:
        tb = build_interleaved_1f1b(S, v, M)
        assert tb.bubble_ticks == 2 * (S - 1), (S, v, M, tb.bubble_ticks)


def test_shard_blocks_interleaved_round_trip():
    cfg = TransformerConfig(
        vocab_size=17, d_model=8, n_heads=2, n_layers=8, d_ff=16, max_seq_len=8
    )
    params = init_transformer(jax.random.key(0), cfg)
    staged = shard_blocks_interleaved(params["blocks"], 2, 2)
    assert jax.tree.leaves(staged)[0].shape[:3] == (2, 2, 2)
    back = unshard_blocks_interleaved(staged)
    for k in params["blocks"]:
        np.testing.assert_array_equal(back[k], params["blocks"][k])


@pytest.mark.parametrize("S,v,M,remat", [(2, 2, 4, False), (2, 2, 4, True), (2, 1, 2, False)])
def test_interleaved_lm_grads_match_single_chip(S, v, M, remat):
    cfg = TransformerConfig(
        vocab_size=29, d_model=16, n_heads=2, n_layers=S * v * 1, d_ff=32,
        max_seq_len=10, remat=remat,
    )
    mesh = build_mesh(MeshSpec(stage=S, data=2))
    params = init_transformer(jax.random.key(1), cfg)
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (M * 2 * 2, 11)), jnp.int32
    )

    loss_ref, grads_ref = jax.jit(
        jax.value_and_grad(lambda p, t: lm_loss(p, t, cfg))
    )(params, tokens)

    params_il = dict(params, blocks=shard_blocks_interleaved(params["blocks"], S, v))
    vag = jax.jit(make_pipeline_lm_interleaved_grad(mesh, cfg, v, M))
    loss_il, grads_il = vag(params_il, tokens)
    grads_il = dict(grads_il, blocks=unshard_blocks_interleaved(grads_il["blocks"]))

    np.testing.assert_allclose(float(loss_il), float(loss_ref), rtol=1e-5)
    flat_ref = jax.tree.flatten_with_path(grads_ref)[0]
    flat_il = jax.tree.flatten_with_path(grads_il)[0]
    for (path_r, leaf_r), (path_i, leaf_i) in zip(flat_ref, flat_il):
        assert path_r == path_i
        np.testing.assert_allclose(
            np.asarray(leaf_i), np.asarray(leaf_r), rtol=5e-4, atol=1e-6,
            err_msg=str(path_r),
        )


def test_interleaved_dense_chain_matches_gpipe():
    """Dense padded-chain chunks on the table executor: loss/grads match
    the GPipe-AD path run over the same V-chunk pipeline on V devices'
    worth of stages collapsed to S devices x v virtual."""
    import optax

    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.one_f_one_b import compiled_interleaved_dense_grad
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params, compiled_pipeline
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.train.pipeline_trainer import (
        make_pipeline_train_step,
        prepare_pipeline_batch,
    )

    S, v, M, data = 2, 2, 4, 2
    dims = [12, 10, 8, 6, 4]
    model = random_model(dims, seed=2)
    params = build_pipeline_params(partition_model(model, [1, 1, 1, 1]))  # V=4 chunks
    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, dims[0])).astype(np.float32)
    y = rng.integers(0, dims[-1], size=32)

    # Reference: GPipe-AD over a 4-stage mesh (the same 4 chunks, one per device).
    mesh_v = build_mesh(MeshSpec(stage=4, data=2))
    xs, labels, mask = prepare_pipeline_batch(params.meta, x, y, M, 2)
    apply = compiled_pipeline(mesh_v, params.meta, M, True, jnp.float32)

    def loss_fn(w):
        logits = apply(w, jnp.asarray(xs))
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels.reshape(-1)[:, None], axis=-1)[:, 0]
        return -(ll * mask.reshape(-1)).sum() / mask.sum()

    loss_ref, grads_ref = jax.jit(jax.value_and_grad(loss_fn))(params.weights)

    # Interleaved: same 4 chunks on 2 devices x 2 virtual.
    mesh_s = build_mesh(MeshSpec(stage=S, data=data))
    run = compiled_interleaved_dense_grad(mesh_s, params.meta, v, M, jnp.float32)
    loss_il, grads_il = run(
        params.weights, jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(mask)
    )

    np.testing.assert_allclose(float(loss_il), float(loss_ref), rtol=1e-5)
    w_mask, b_mask = params.meta.grad_masks()
    np.testing.assert_allclose(
        np.asarray(grads_il.w) * w_mask, np.asarray(grads_ref.w) * w_mask,
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(grads_il.b) * b_mask, np.asarray(grads_ref.b) * b_mask,
        rtol=1e-4, atol=1e-6,
    )

    # Full optimizer step through make_pipeline_train_step.
    opt = optax.adam(1e-3)
    step = make_pipeline_train_step(
        mesh_s, params.meta, M, opt, schedule="interleaved", num_virtual=v
    )
    w2, _, loss2 = step(
        params.weights, opt.init(params.weights),
        jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(mask),
    )
    np.testing.assert_allclose(float(loss2), float(loss_ref), rtol=1e-5)


def test_interleaved_dense_chunk_count_mismatch():
    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.one_f_one_b import compiled_interleaved_dense_grad
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model

    params = build_pipeline_params(
        partition_model(random_model([8, 6, 4], seed=0), [1, 1])
    )
    mesh = build_mesh(MeshSpec(stage=2, data=2))
    with pytest.raises(ValueError, match="distribution"):
        compiled_interleaved_dense_grad(mesh, params.meta, 2, 4, jnp.float32)
