"""Tiny-Transformer family: forward semantics, causality, pipeline parity,
training-loss descent, text data pipeline (BASELINE configs[4])."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_dist_nn.data.text import (
    VOCAB_SIZE,
    decode,
    encode,
    lm_batches,
    lm_sequences,
    load_corpus,
    synthetic_wikitext,
)
from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    block_apply,
    forward,
    init_transformer,
    lm_loss,
    num_params,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_lm_forward,
    make_pipeline_lm_loss,
    shard_blocks,
    unshard_blocks,
)
from tpu_dist_nn.train.lm_trainer import (
    LMTrainConfig,
    evaluate_lm,
    make_lm_train_step,
    train_lm,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq_len=32
)


def _params(cfg=CFG, seed=0):
    return init_transformer(jax.random.key(seed), cfg)


def _tokens(cfg=CFG, batch=4, t=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, t)), jnp.int32)


class TestForward:
    def test_shapes(self):
        params = _params()
        logits = forward(params, _tokens(), CFG)
        assert logits.shape == (4, 16, CFG.vocab_size)
        assert np.all(np.isfinite(np.asarray(logits)))

    def test_scan_matches_python_loop(self):
        """The scanned stack equals applying blocks one by one."""
        params = _params()
        tokens = _tokens()
        got = forward(params, tokens, CFG)

        from tpu_dist_nn.models.transformer import embed, unembed

        x = embed(params, tokens)
        for i in range(CFG.n_layers):
            block = jax.tree.map(lambda a: a[i], params["blocks"])
            x = block_apply(block, x, CFG)
        want = unembed(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_causality(self):
        """Perturbing future tokens must not change past logits."""
        params = _params()
        tokens = _tokens()
        base = np.asarray(forward(params, tokens, CFG))
        perturbed = tokens.at[:, 10:].set((tokens[:, 10:] + 1) % CFG.vocab_size)
        got = np.asarray(forward(params, perturbed, CFG))
        np.testing.assert_allclose(got[:, :10], base[:, :10], atol=1e-5)
        assert np.abs(got[:, 10:] - base[:, 10:]).max() > 1e-4

    def test_loss_near_uniform_at_init(self):
        """Random init ≈ uniform predictions: CE ≈ log(vocab)."""
        loss = float(lm_loss(_params(), _tokens(t=32), CFG))
        assert abs(loss - np.log(CFG.vocab_size)) < 1.0


class TestPipeline:
    @pytest.mark.parametrize("stages,data", [(4, 1), (2, 2), (2, 4)])
    def test_pipeline_matches_single_chip(self, stages, data):
        mesh = build_mesh(MeshSpec(stage=stages, data=data))
        params = _params()
        tokens = _tokens(batch=8)
        want = np.asarray(forward(params, tokens, CFG))

        fwd = make_pipeline_lm_forward(mesh, CFG, stages, num_microbatches=2)
        staged = dict(params, blocks=shard_blocks(params["blocks"], stages))
        got = np.asarray(jax.jit(fwd)(staged, tokens))
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_shard_roundtrip(self):
        blocks = _params()["blocks"]
        rt = unshard_blocks(shard_blocks(blocks, 2))
        for a, b in zip(jax.tree.leaves(blocks), jax.tree.leaves(rt)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_pipeline_gradients_match_single_chip(self):
        """Backward through ppermute/scan == single-chip gradients."""
        mesh = build_mesh(MeshSpec(stage=4, data=2))
        params = _params()
        tokens = _tokens(batch=8, t=17)

        # jit the grads: eager op-by-op execution never hits the
        # persistent compile cache and dominated suite wall-clock.
        g_single = jax.jit(jax.grad(lm_loss), static_argnums=2)(params, tokens, CFG)

        from tpu_dist_nn.parallel.transformer_pipeline import make_pipeline_lm_loss

        loss_fn = make_pipeline_lm_loss(mesh, CFG, 4, num_microbatches=2)
        staged = dict(params, blocks=shard_blocks(params["blocks"], 4))
        g_pipe = jax.jit(jax.grad(loss_fn))(staged, tokens)
        g_pipe = dict(g_pipe, blocks=unshard_blocks(g_pipe["blocks"]))

        flat_s, _ = jax.tree.flatten(g_single)
        flat_p, _ = jax.tree.flatten(g_pipe)
        for s, p in zip(flat_s, flat_p):
            np.testing.assert_allclose(
                np.asarray(s), np.asarray(p), atol=5e-4, rtol=1e-3
            )


class TestTraining:
    def test_loss_descends_on_copy_task(self):
        """Repetitive data: a few Adam steps should cut the loss hard."""
        cfg = TransformerConfig(
            vocab_size=16, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=32,
        )
        params = init_transformer(jax.random.key(0), cfg)
        step = make_lm_train_step(cfg, optax.adam(3e-3))
        opt_state = optax.adam(3e-3).init(params)
        pattern = np.tile(np.arange(8, dtype=np.int32), 5)[:33]
        tokens = jnp.asarray(np.tile(pattern, (8, 1)))
        first = None
        for _ in range(30):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first * 0.5

    def test_train_lm_pipelined_runs_and_descends(self):
        mesh = build_mesh(MeshSpec(stage=2, data=2))
        cfg = TransformerConfig(
            vocab_size=VOCAB_SIZE, d_model=32, n_heads=2, n_layers=2,
            d_ff=64, max_seq_len=64,
        )
        params = init_transformer(jax.random.key(1), cfg)
        text = synthetic_wikitext(30_000, seed=1)
        rows = lm_sequences(encode(text), seq_len=32)
        tc = LMTrainConfig(steps=20, batch_size=8, seq_len=32, log_every=5)
        params, history = train_lm(
            params, cfg, lm_batches(rows, 8, seed=0, epochs=None), tc,
            mesh=mesh, num_stages=2, num_microbatches=2,
        )
        assert history[-1]["loss"] < history[0]["loss"]
        assert params["blocks"]["w_qkv"].shape[0] == cfg.n_layers  # unstaged

    def test_evaluate_lm(self):
        cfg = TransformerConfig(
            vocab_size=VOCAB_SIZE, d_model=16, n_heads=2, n_layers=1,
            d_ff=32, max_seq_len=64,
        )
        params = init_transformer(jax.random.key(0), cfg)
        rows = lm_sequences(encode(synthetic_wikitext(20_000)), 32)
        m = evaluate_lm(params, cfg, rows[:32], batch_size=8)
        # Random init on bytes: ≈ log(256) nats = 8 bits/byte.
        assert 4.0 < m["loss_nats_per_token"] < 7.0
        assert m["perplexity"] > 50


class TestTextData:
    def test_encode_decode_roundtrip(self):
        s = "Hello = WikiText = \n naïve café"
        assert decode(encode(s)) == s

    def test_synthetic_deterministic(self):
        assert synthetic_wikitext(5000, seed=3) == synthetic_wikitext(5000, seed=3)
        assert synthetic_wikitext(5000, seed=3) != synthetic_wikitext(5000, seed=4)

    def test_load_corpus_prefers_vendored_real_then_explicit(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.delenv("TDN_WIKITEXT_PATH", raising=False)
        # Default: the VENDORED real corpus (committed with the package)
        # wins over the synthetic generator.
        text, source = load_corpus()
        assert source.endswith("realtext_corpus.txt") and len(text) > 5_000_000
        assert "GNU GENERAL PUBLIC LICENSE" in text  # real bytes, not Zipf
        # An explicit WikiText-style file still takes precedence.
        f = tmp_path / "wiki.train.tokens"
        f.write_text("real corpus text here")
        monkeypatch.setenv("TDN_WIKITEXT_PATH", str(f))
        text, source = load_corpus()
        assert source == str(f) and text == "real corpus text here"

    def test_load_corpus_synthetic_fallback_is_gated(self, monkeypatch):
        from tpu_dist_nn.data import text as text_mod

        monkeypatch.delenv("TDN_WIKITEXT_PATH", raising=False)
        missing = text_mod._VENDORED_CORPUS.with_name("nope.txt")
        monkeypatch.setattr(text_mod, "_VENDORED_CORPUS", missing)
        monkeypatch.setattr(text_mod, "_VENDORED_CORPUS_R3", missing)
        monkeypatch.setattr(text_mod, "_DEFAULT_PATHS", ())
        text, source = text_mod.load_corpus(synthetic_chars=1000)
        assert source == "synthetic" and len(text) == 1000
        with pytest.raises(ValueError, match="allow_synthetic"):
            text_mod.load_corpus(allow_synthetic=False)

    def test_lm_sequences_and_batches(self):
        rows = lm_sequences(np.arange(100, dtype=np.int32), seq_len=9)
        assert rows.shape == (10, 10)
        batches = list(lm_batches(rows, 4, seed=0, epochs=2))
        assert len(batches) == 4 and batches[0].shape == (4, 10)

    def test_num_params_counts(self):
        assert num_params(_params()) > 4 * (3 * 32 * 96)


class TestMixedPrecision:
    def test_bf16_loss_close_to_f32_and_grads_finite(self):
        import dataclasses

        cfg = TransformerConfig(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32,
        )
        cfg16 = dataclasses.replace(cfg, compute_dtype="bfloat16")
        params = init_transformer(jax.random.key(0), cfg)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (4, 32)), jnp.int32
        )
        l32 = float(lm_loss(params, tokens, cfg))
        l16 = float(lm_loss(params, tokens, cfg16))
        # bf16 has ~3 decimal digits; losses agree loosely.
        assert abs(l32 - l16) / l32 < 0.05
        g = jax.jit(jax.grad(lm_loss), static_argnums=2)(params, tokens, cfg16)
        for leaf in jax.tree.leaves(g):
            assert leaf.dtype == jnp.float32  # masters stay f32
            assert bool(jnp.all(jnp.isfinite(leaf)))

    def test_bf16_trains(self):
        import dataclasses

        cfg = dataclasses.replace(
            TransformerConfig(
                vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
                max_seq_len=16,
            ),
            compute_dtype="bfloat16",
        )
        params = init_transformer(jax.random.key(0), cfg)
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 32, (8, 16)), jnp.int32
        )
        opt = optax.adam(1e-2)
        state = opt.init(params)

        @jax.jit
        def step(p, s):
            loss, g = jax.value_and_grad(lambda q: lm_loss(q, tokens, cfg))(p)
            up, s = opt.update(g, s)
            return optax.apply_updates(p, up), s, loss

        first = None
        for _ in range(30):
            params, state, loss = step(params, state)
            first = first if first is not None else float(loss)
        assert float(loss) < first


class TestLMCheckpointResume:
    def test_resume_matches_straight_through(self, tmp_path):
        from tpu_dist_nn.checkpoint import CheckpointManager

        cfg = TransformerConfig(
            vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
            max_seq_len=16,
        )
        rows = lm_sequences(
            np.random.default_rng(0).integers(0, 32, 4000).astype(np.int32), 16
        )
        tc = LMTrainConfig(steps=6, batch_size=4, log_every=2)
        params0 = init_transformer(jax.random.key(1), cfg)

        # Straight through: 6 steps, no interruption.
        ref, _ = train_lm(
            params0, cfg, lm_batches(rows, 4, seed=9, epochs=None), tc
        )

        # Interrupted: 3 steps (saved), then resume to 6 from disk.
        ck1 = CheckpointManager(tmp_path / "ck", keep=5)
        tc3 = LMTrainConfig(steps=3, batch_size=4, log_every=1)
        train_lm(
            params0, cfg, lm_batches(rows, 4, seed=9, epochs=None), tc3,
            checkpoints=ck1, checkpoint_every=1,
        )
        ck2 = CheckpointManager(tmp_path / "ck", keep=5)
        resumed, _ = train_lm(
            params0, cfg, lm_batches(rows, 4, seed=9, epochs=None), tc,
            checkpoints=ck2, checkpoint_every=100,
        )
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(resumed)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
            )


def test_bf16_applies_to_pipelined_path():
    # --bf16 with stages > 1 must actually cast: probe the compiled HLO
    # for bf16 dot ops.
    import dataclasses

    cfg = dataclasses.replace(
        TransformerConfig(
            vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
            max_seq_len=16,
        ),
        compute_dtype="bfloat16",
    )
    mesh = build_mesh(MeshSpec(stage=2, data=1))
    params = init_transformer(jax.random.key(0), cfg)
    params = dict(params, blocks=shard_blocks(params["blocks"], 2))
    loss_fn = make_pipeline_lm_loss(mesh, cfg, 2, 2)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (4, 16)), jnp.int32
    )
    text = jax.jit(loss_fn).lower(params, tokens).as_text()
    assert "bf16" in text
    assert np.isfinite(float(loss_fn(params, tokens)))


def test_resume_rejects_mismatched_stage_layout(tmp_path):
    from tpu_dist_nn.checkpoint import CheckpointManager
    from tpu_dist_nn.utils.errors import InvalidArgumentError

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16,
    )
    rows = lm_sequences(
        np.random.default_rng(0).integers(0, 32, 2000).astype(np.int32), 16
    )
    params = init_transformer(jax.random.key(0), cfg)
    tc = LMTrainConfig(steps=2, batch_size=4, log_every=1)
    mesh = build_mesh(MeshSpec(stage=2, data=1))
    ck = CheckpointManager(tmp_path / "ck", keep=2)
    train_lm(
        params, cfg, lm_batches(rows, 4, seed=0, epochs=None), tc,
        mesh=mesh, num_stages=2, num_microbatches=2,
        checkpoints=ck, checkpoint_every=1,
    )
    # Resuming single-chip (unstaged layout) must fail fast, not deep
    # inside jit.
    ck2 = CheckpointManager(tmp_path / "ck", keep=2)
    with pytest.raises(InvalidArgumentError, match="different placement"):
        train_lm(
            params, cfg, lm_batches(rows, 4, seed=0, epochs=None), tc,
            checkpoints=ck2,
        )


def test_remat_gradients_match_baseline():
    # jax.checkpoint must change memory, not math: grads bit-match the
    # non-remat forward (same ops modulo recompute).
    import dataclasses as dc

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=3, d_ff=32,
        max_seq_len=16,
    )
    cfg_r = dc.replace(cfg, remat=True)
    params = init_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (4, 16)), jnp.int32
    )
    g0 = jax.jit(jax.grad(lambda p: lm_loss(p, tokens, cfg)))(params)
    g1 = jax.jit(jax.grad(lambda p: lm_loss(p, tokens, cfg_r)))(params)
    paths0 = jax.tree_util.tree_flatten_with_path(g0)[0]
    paths1 = jax.tree_util.tree_flatten_with_path(g1)[0]
    assert len(paths0) == len(paths1) > 4
    for (k0, a), (k1, b) in zip(paths0, paths1):
        assert k0 == k1
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7,
            err_msg=str(k0),
        )


def test_remat_pipelined_matches_single_chip():
    import dataclasses as dc

    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_lm_loss,
        shard_blocks,
    )

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=4, d_ff=32,
        max_seq_len=16, remat=True,
    )
    params = init_transformer(jax.random.key(1), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, (4, 16)), jnp.int32
    )
    single = float(lm_loss(params, tokens, cfg))
    mesh = build_mesh(MeshSpec(stage=2))
    loss_fn = make_pipeline_lm_loss(mesh, cfg, 2, num_microbatches=2)
    params_pp = dict(params, blocks=shard_blocks(params["blocks"], 2))
    piped = float(jax.jit(loss_fn)(params_pp, tokens))
    assert abs(single - piped) < 2e-5
    g = jax.jit(jax.grad(loss_fn))(params_pp, tokens)
    assert float(jnp.abs(jax.tree.leaves(g)[0]).sum()) > 0
