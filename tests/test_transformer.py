"""Tiny-Transformer family: forward semantics, causality, pipeline parity,
training-loss descent, text data pipeline (BASELINE configs[4])."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_dist_nn.data.text import (
    VOCAB_SIZE,
    decode,
    encode,
    lm_batches,
    lm_sequences,
    load_corpus,
    synthetic_wikitext,
)
from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    block_apply,
    forward,
    init_transformer,
    lm_loss,
    num_params,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_lm_forward,
    shard_blocks,
    unshard_blocks,
)
from tpu_dist_nn.train.lm_trainer import (
    LMTrainConfig,
    evaluate_lm,
    make_lm_train_step,
    train_lm,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq_len=32
)


def _params(cfg=CFG, seed=0):
    return init_transformer(jax.random.key(seed), cfg)


def _tokens(cfg=CFG, batch=4, t=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, t)), jnp.int32)


class TestForward:
    def test_shapes(self):
        params = _params()
        logits = forward(params, _tokens(), CFG)
        assert logits.shape == (4, 16, CFG.vocab_size)
        assert np.all(np.isfinite(np.asarray(logits)))

    def test_scan_matches_python_loop(self):
        """The scanned stack equals applying blocks one by one."""
        params = _params()
        tokens = _tokens()
        got = forward(params, tokens, CFG)

        from tpu_dist_nn.models.transformer import embed, unembed

        x = embed(params, tokens)
        for i in range(CFG.n_layers):
            block = jax.tree.map(lambda a: a[i], params["blocks"])
            x = block_apply(block, x, CFG)
        want = unembed(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_causality(self):
        """Perturbing future tokens must not change past logits."""
        params = _params()
        tokens = _tokens()
        base = np.asarray(forward(params, tokens, CFG))
        perturbed = tokens.at[:, 10:].set((tokens[:, 10:] + 1) % CFG.vocab_size)
        got = np.asarray(forward(params, perturbed, CFG))
        np.testing.assert_allclose(got[:, :10], base[:, :10], atol=1e-5)
        assert np.abs(got[:, 10:] - base[:, 10:]).max() > 1e-4

    def test_loss_near_uniform_at_init(self):
        """Random init ≈ uniform predictions: CE ≈ log(vocab)."""
        loss = float(lm_loss(_params(), _tokens(t=32), CFG))
        assert abs(loss - np.log(CFG.vocab_size)) < 1.0


class TestPipeline:
    @pytest.mark.parametrize("stages,data", [(4, 1), (2, 2), (2, 4)])
    def test_pipeline_matches_single_chip(self, stages, data):
        mesh = build_mesh(MeshSpec(stage=stages, data=data))
        params = _params()
        tokens = _tokens(batch=8)
        want = np.asarray(forward(params, tokens, CFG))

        fwd = make_pipeline_lm_forward(mesh, CFG, stages, num_microbatches=2)
        staged = dict(params, blocks=shard_blocks(params["blocks"], stages))
        got = np.asarray(jax.jit(fwd)(staged, tokens))
        np.testing.assert_allclose(got, want, atol=2e-4)

    def test_shard_roundtrip(self):
        blocks = _params()["blocks"]
        rt = unshard_blocks(shard_blocks(blocks, 2))
        for a, b in zip(jax.tree.leaves(blocks), jax.tree.leaves(rt)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_pipeline_gradients_match_single_chip(self):
        """Backward through ppermute/scan == single-chip gradients."""
        mesh = build_mesh(MeshSpec(stage=4, data=2))
        params = _params()
        tokens = _tokens(batch=8, t=17)

        g_single = jax.grad(lm_loss)(params, tokens, CFG)

        from tpu_dist_nn.parallel.transformer_pipeline import make_pipeline_lm_loss

        loss_fn = make_pipeline_lm_loss(mesh, CFG, 4, num_microbatches=2)
        staged = dict(params, blocks=shard_blocks(params["blocks"], 4))
        g_pipe = jax.grad(loss_fn)(staged, tokens)
        g_pipe = dict(g_pipe, blocks=unshard_blocks(g_pipe["blocks"]))

        flat_s, _ = jax.tree.flatten(g_single)
        flat_p, _ = jax.tree.flatten(g_pipe)
        for s, p in zip(flat_s, flat_p):
            np.testing.assert_allclose(
                np.asarray(s), np.asarray(p), atol=5e-4, rtol=1e-3
            )


class TestTraining:
    def test_loss_descends_on_copy_task(self):
        """Repetitive data: a few Adam steps should cut the loss hard."""
        cfg = TransformerConfig(
            vocab_size=16, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=32,
        )
        params = init_transformer(jax.random.key(0), cfg)
        step = make_lm_train_step(cfg, optax.adam(3e-3))
        opt_state = optax.adam(3e-3).init(params)
        pattern = np.tile(np.arange(8, dtype=np.int32), 5)[:33]
        tokens = jnp.asarray(np.tile(pattern, (8, 1)))
        first = None
        for _ in range(30):
            params, opt_state, loss = step(params, opt_state, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first * 0.5

    def test_train_lm_pipelined_runs_and_descends(self):
        mesh = build_mesh(MeshSpec(stage=2, data=2))
        cfg = TransformerConfig(
            vocab_size=VOCAB_SIZE, d_model=32, n_heads=2, n_layers=2,
            d_ff=64, max_seq_len=64,
        )
        params = init_transformer(jax.random.key(1), cfg)
        text = synthetic_wikitext(30_000, seed=1)
        rows = lm_sequences(encode(text), seq_len=32)
        tc = LMTrainConfig(steps=20, batch_size=8, seq_len=32, log_every=5)
        params, history = train_lm(
            params, cfg, lm_batches(rows, 8, seed=0, epochs=None), tc,
            mesh=mesh, num_stages=2, num_microbatches=2,
        )
        assert history[-1]["loss"] < history[0]["loss"]
        assert params["blocks"]["w_qkv"].shape[0] == cfg.n_layers  # unstaged

    def test_evaluate_lm(self):
        cfg = TransformerConfig(
            vocab_size=VOCAB_SIZE, d_model=16, n_heads=2, n_layers=1,
            d_ff=32, max_seq_len=64,
        )
        params = init_transformer(jax.random.key(0), cfg)
        rows = lm_sequences(encode(synthetic_wikitext(20_000)), 32)
        m = evaluate_lm(params, cfg, rows[:32], batch_size=8)
        # Random init on bytes: ≈ log(256) nats = 8 bits/byte.
        assert 4.0 < m["loss_nats_per_token"] < 7.0
        assert m["perplexity"] > 50


class TestTextData:
    def test_encode_decode_roundtrip(self):
        s = "Hello = WikiText = \n naïve café"
        assert decode(encode(s)) == s

    def test_synthetic_deterministic(self):
        assert synthetic_wikitext(5000, seed=3) == synthetic_wikitext(5000, seed=3)
        assert synthetic_wikitext(5000, seed=3) != synthetic_wikitext(5000, seed=4)

    def test_load_corpus_fallback_and_file(self, tmp_path, monkeypatch):
        monkeypatch.delenv("TDN_WIKITEXT_PATH", raising=False)
        text, source = load_corpus(synthetic_chars=1000)
        assert source == "synthetic" and len(text) == 1000
        f = tmp_path / "wiki.train.tokens"
        f.write_text("real corpus text here")
        monkeypatch.setenv("TDN_WIKITEXT_PATH", str(f))
        text, source = load_corpus()
        assert source == str(f) and text == "real corpus text here"

    def test_lm_sequences_and_batches(self):
        rows = lm_sequences(np.arange(100, dtype=np.int32), seq_len=9)
        assert rows.shape == (10, 10)
        batches = list(lm_batches(rows, 4, seed=0, epochs=2))
        assert len(batches) == 4 and batches[0].shape == (4, 10)

    def test_num_params_counts(self):
        assert num_params(_params()) > 4 * (3 * 32 * 96)
