"""Pipelined serving fast path (ISSUE 2): double-buffered batcher,
zero-copy staging, AOT bucket warm, launch-shape compile-cache keying.

The real Engine needs jax's mesh API (jax.sharding.AxisType), which
this container's jax may lack — engine-path tests either build a
mesh-free single-chip engine by hand (exercising the REAL
infer_async/fetch/warm_buckets code on the plain dense path) or
skip-gate on the mesh API. Batcher mechanics run against controlled
fake engines, the same convention as test_serving's _SlowEngine.
"""

import dataclasses
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.serving.server import _Batcher


def _mesh_available() -> bool:
    try:
        from jax.sharding import AxisType  # noqa: F401

        return True
    except ImportError:
        return False


class _Handle:
    def __init__(self, value):
        self.value = value


class AsyncFakeEngine:
    """Models JAX async dispatch: infer_async returns a handle after a
    host-side staging cost; fetch (the one host sync) pays the device
    time. ``per_row=True`` scales both costs with the batch's rows (so
    coalescing cannot amortize them away — the regime where pipelining
    pays). Gate lets tests hold a batch 'on the device' deliberately."""

    def __init__(self, dim=8, dispatch_seconds=0.0, fetch_seconds=0.0,
                 per_row=False):
        self.model = dataclasses.make_dataclass("M", ["input_dim"])(dim)
        self.dispatch_seconds = dispatch_seconds
        self.fetch_seconds = fetch_seconds
        self.per_row = per_row
        self.gate = threading.Event()
        self.gate.set()  # open unless a test closes it
        self.fetch_entered = threading.Event()
        self.dispatched_rows: list[list[float]] = []

    def _cost(self, seconds, n):
        if seconds:
            time.sleep(seconds * n if self.per_row else seconds)

    def infer_async(self, x):
        x = np.asarray(x)
        self._cost(self.dispatch_seconds, len(x))
        self.dispatched_rows.append(x[:, 0].tolist())
        return _Handle(x * 2.0)

    def fetch(self, handle):
        self.fetch_entered.set()
        self.gate.wait(10.0)
        self._cost(self.fetch_seconds, len(handle.value))
        return handle.value


def _mesh_free_engine(sizes=(8, 6, 4)):
    """A REAL Engine on the plain single-chip dense path, constructed
    without build_mesh (unavailable on this jax): every attribute
    _infer_impl/infer_async/fetch/warm_buckets touch is set the way
    __init__ would."""
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.models.fcnn import params_from_spec
    from tpu_dist_nn.testing.factories import random_model

    model = random_model(list(sizes), seed=0)
    e = Engine.__new__(Engine)
    e.model = model
    e._pp = e._hp = e._plan = e._q = e._q_pp = None
    e.int8_auto_disabled = False
    e._params = params_from_spec(model, jnp.float32)
    e.pipelined = False
    e.data_sharded = False
    e.dtype = jnp.float32
    e._np_dtype = np.dtype(jnp.float32)
    e._seen_infer_shapes = set()
    e._warm_buckets = set()
    e.num_microbatches = 4
    return e


# ------------------------------------------------------- batcher overlap


def test_batches_launch_while_prior_fetch_in_flight():
    # The tentpole behavior: with the fetch of batch 1 held open, the
    # dispatch stage must still assemble and LAUNCH batch 2 — launches
    # advance while a prior batch is materializing.
    eng = AsyncFakeEngine()
    eng.gate.clear()
    b = _Batcher(eng, submit_timeout=10.0)
    outs: dict[int, np.ndarray] = {}

    def client(i):
        outs[i] = b.submit(np.full((1, 8), float(i)))

    try:
        t1 = threading.Thread(target=client, args=(1,))
        t1.start()
        assert eng.fetch_entered.wait(5.0)  # batch 1 is 'on the device'
        t2 = threading.Thread(target=client, args=(2,))
        t3 = threading.Thread(target=client, args=(3,))
        t2.start(), t3.start()
        # Batch 2 (rows 2+3, coalesced) must LAUNCH while batch 1's
        # fetch is still blocked — poll the launch counter, not sleep.
        deadline = time.monotonic() + 5.0
        while len(eng.dispatched_rows) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(eng.dispatched_rows) >= 2, (
            "no overlap: second batch never launched while the first "
            "was in flight"
        )
        assert b.batches_total >= 2
        eng.gate.set()
        for t in (t1, t2, t3):
            t.join(timeout=5.0)
        # Fan-out stayed correct under the overlap: each request got
        # exactly its own rows back, in its own slot.
        for i in (1, 2, 3):
            np.testing.assert_array_equal(outs[i], np.full((1, 8), 2.0 * i))
        assert b.overlapped_total >= 1
        assert b.inflight_batches == 0 and b.inflight_rows == 0
    finally:
        eng.gate.set()
        b.close()


def test_pipeline_depth_bounds_outstanding_launches():
    # pipeline_depth is a hard launch-ahead bound: with the drain gated
    # shut and depth=2, exactly 2 batches may be launched-but-undrained;
    # a 3rd must wait for a slot, not pile device work unboundedly.
    eng = AsyncFakeEngine()
    eng.gate.clear()
    b = _Batcher(eng, submit_timeout=10.0, pipeline_depth=2)
    threads = [
        threading.Thread(
            target=lambda i=i: b.submit(np.full((1, 8), float(i)))
        )
        for i in range(4)
    ]
    try:
        threads[0].start()
        assert eng.fetch_entered.wait(5.0)
        for t in threads[1:]:
            t.start()
            time.sleep(0.05)  # force each into its own batch
        deadline = time.monotonic() + 2.0
        while len(eng.dispatched_rows) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        time.sleep(0.15)  # would-be 3rd launch gets every chance to leak
        assert len(eng.dispatched_rows) == 2, eng.dispatched_rows
        assert b.inflight_batches == 2
        eng.gate.set()
        for t in threads:
            t.join(timeout=5.0)
        assert len(eng.dispatched_rows) >= 3  # freed slots drained the rest
    finally:
        eng.gate.set()
        b.close()


def test_ordering_and_error_fanout_survive_concurrency():
    # Per-request ordering and error isolation across many concurrent
    # submitters: wrong-width requests fail with the engine's dispatch
    # error while every well-formed request gets its own rows.
    from concurrent.futures import ThreadPoolExecutor

    from tpu_dist_nn.utils.errors import InvalidArgumentError

    class WidthCheckingEngine(AsyncFakeEngine):
        def infer_async(self, x):
            if np.asarray(x).shape[1] != 8:
                raise InvalidArgumentError("expected (N, 8)")
            return super().infer_async(x)

    eng = WidthCheckingEngine(fetch_seconds=0.002)
    b = _Batcher(eng, submit_timeout=10.0)
    try:
        def call(i):
            if i % 5 == 4:
                with pytest.raises(InvalidArgumentError):
                    b.submit(np.full((1, 5), float(i)))
                return None
            return b.submit(np.full((2, 8), float(i)))

        with ThreadPoolExecutor(max_workers=10) as ex:
            outs = list(ex.map(call, range(20)))
        for i, out in enumerate(outs):
            if i % 5 == 4:
                assert out is None
            else:
                np.testing.assert_array_equal(out, np.full((2, 8), 2.0 * i))
    finally:
        b.close()


def test_abandoned_requests_discarded_at_pop():
    # The discard-at-pop contract survives the two-stage split: a
    # request that timed out while the dispatch stage was busy must
    # never be computed once the stage recovers.
    from tpu_dist_nn.utils.errors import DeadlineExceededError

    release = threading.Event()
    seen: list[list[float]] = []

    def wedged_run(xs):
        release.wait(10.0)
        seen.append(np.asarray(xs)[:, 0].tolist())
        return np.asarray(xs)

    b = _Batcher(None, run_fn=wedged_run, submit_timeout=10.0)
    try:
        t1 = threading.Thread(target=lambda: b.submit(np.zeros((1, 8))))
        t1.start()
        time.sleep(0.05)  # let request 1 wedge inside the dispatch fn
        with pytest.raises(DeadlineExceededError):
            b.submit(np.full((1, 8), 7.0), timeout=0.1)
        release.set()
        out = b.submit(np.full((1, 8), 3.0), timeout=5.0)
        np.testing.assert_array_equal(out, np.full((1, 8), 3.0))
        t1.join(timeout=5.0)
        assert not any(7.0 in rows for rows in seen), seen
    finally:
        release.set()
        b.close()


def test_close_drains_both_stages():
    # Everything submitted before close() must complete through BOTH
    # stages; a submit after close() is UNAVAILABLE; no batch is left
    # in flight.
    from tpu_dist_nn.utils.errors import UnavailableError

    eng = AsyncFakeEngine(fetch_seconds=0.02)
    b = _Batcher(eng, submit_timeout=10.0)
    outs: dict[int, np.ndarray] = {}
    threads = [
        threading.Thread(
            target=lambda i=i: outs.__setitem__(
                i, b.submit(np.full((1, 8), float(i)))
            )
        )
        for i in range(6)
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5.0
    while b.requests_total < 6 and time.monotonic() < deadline:
        time.sleep(0.002)
    b.close()
    for t in threads:
        t.join(timeout=5.0)
    assert sorted(outs) == list(range(6))
    for i, out in outs.items():
        np.testing.assert_array_equal(out, np.full((1, 8), 2.0 * i))
    assert b.inflight_batches == 0 and b.inflight_rows == 0
    with pytest.raises(UnavailableError):
        b.submit(np.zeros((1, 8)))


# ---------------------------------------------------- zero-copy staging


def test_staging_pads_to_bucket_zeroes_tail_and_reuses_buffer():
    eng = AsyncFakeEngine()
    b = _Batcher(eng)
    try:
        group = [
            {"x": np.full((2, 4), 1.0)},
            {"x": np.full((3, 4), 2.0)},
        ]
        xs, key, buf = b._stage(group)
        assert xs.shape == (8, 4)  # 5 rows -> pow2 bucket 8
        np.testing.assert_array_equal(xs[:2], 1.0)
        np.testing.assert_array_equal(xs[2:5], 2.0)
        np.testing.assert_array_equal(xs[5:], 0.0)  # pad tail zeroed
        b._release(key, buf)
        # Same bucket again: the SAME buffer comes back (no per-batch
        # allocation), previous garbage overwritten in place.
        xs2, key2, buf2 = b._stage(group)
        assert buf2 is buf and key2 == key
        np.testing.assert_array_equal(xs2[5:], 0.0)
    finally:
        b.close()


def test_staging_single_request_on_bucket_is_zero_copy():
    eng = AsyncFakeEngine()
    b = _Batcher(eng)
    try:
        x = np.zeros((4, 8))  # already a pow2 bucket
        xs, key, buf = b._stage([{"x": x}])
        assert xs is x and buf is None  # launched as-is, nothing staged
    finally:
        b.close()


def test_decode_matrix_lands_in_requested_dtype():
    from tpu_dist_nn.serving.wire import decode_matrix, encode_matrix

    rng = np.random.default_rng(3)
    x = rng.normal(size=(6, 5))
    out = decode_matrix(encode_matrix(x), dtype=np.float32)
    assert out.dtype == np.float32
    np.testing.assert_array_equal(out, x.astype(np.float32))
    # Default stays the reference's exact float64 wire contract.
    np.testing.assert_array_equal(decode_matrix(encode_matrix(x)), x)


# --------------------------------------- engine async path + warm state


def test_engine_infer_async_fetch_matches_infer_and_defers_sync():
    import jax

    e = _mesh_free_engine()
    x = np.random.default_rng(1).uniform(0, 1, (3, 8))
    pending = e.infer_async(x)
    # The handle holds a DEVICE array: the host sync (np.asarray)
    # happens at fetch, not inside the launch critical section.
    assert isinstance(pending.value, jax.Array)
    out = e.fetch(pending)
    np.testing.assert_allclose(out, e.infer(x), rtol=1e-6)


def test_warm_buckets_ladder_gauge_and_no_misses_after_warm():
    from tpu_dist_nn.obs.registry import REGISTRY

    e = _mesh_free_engine()
    # Non-pow2 max warms through the CEILING bucket: a 5-row coalesced
    # batch pads to 8, so 8 must be warm too.
    assert e.warm_buckets(5) == [1, 2, 4, 8]
    assert e.warm_bucket_count == 4
    assert REGISTRY.get("tdn_engine_warm_buckets").labels().value == 4.0
    # Idempotent: a second warm compiles nothing new.
    assert e.warm_buckets(8) == []
    # After warm, bucket-shaped traffic never eats a compile: the miss
    # counter must not move.
    misses = REGISTRY.get("tdn_engine_compile_cache_misses_total")
    before = misses.labels().value
    for n in (1, 2, 4, 8):
        e.infer(np.zeros((n, 8), np.float32))
    assert misses.labels().value == before


def test_compile_cache_proxy_keys_on_launch_shape_plain_path():
    from tpu_dist_nn.obs.registry import REGISTRY

    e = _mesh_free_engine()
    misses = REGISTRY.get("tdn_engine_compile_cache_misses_total")
    hits = REGISTRY.get("tdn_engine_compile_cache_hits_total")
    m0, h0 = misses.labels().value, hits.labels().value
    e.infer(np.zeros((3, 8)))
    assert (misses.labels().value, hits.labels().value) == (m0 + 1, h0)
    e.infer(np.zeros((3, 8)))
    assert (misses.labels().value, hits.labels().value) == (m0 + 1, h0 + 1)
    assert (3, 8) in e._seen_infer_shapes


@pytest.mark.skipif(not _mesh_available(),
                    reason="installed jax lacks the engine's mesh API")
def test_compile_cache_proxy_counts_padded_launch_shape_data_sharded():
    # The satellite fix: the data-sharded path pads rows to the shard
    # count before jit sees them, so 3 rows and 4 rows on a 2-shard
    # mesh are the SAME compiled program — the second call must be a
    # cache hit, not a phantom miss.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.obs.registry import REGISTRY
    from tpu_dist_nn.testing.factories import random_model

    engine = Engine.up(random_model([6, 5, 4], seed=0), data_parallel=2,
                       warmup=False)
    misses = REGISTRY.get("tdn_engine_compile_cache_misses_total")
    engine.infer(np.zeros((3, 6)))  # launches padded (4, 6): miss
    before = misses.labels().value
    engine.infer(np.zeros((4, 6)))  # same launch shape: hit
    assert misses.labels().value == before
    engine.down()


def test_engine_single_cast_straight_to_engine_dtype():
    # _infer_impl must not stage a float64 copy: float32 input reaches
    # the launch unconverted (the old path went f64 -> f32 for every
    # batch, a full extra matrix per launch).
    e = _mesh_free_engine()
    x64 = np.random.default_rng(2).uniform(0, 1, (4, 8))
    out64 = e.infer(x64)
    out32 = e.infer(x64.astype(np.float32))
    np.testing.assert_allclose(out64, out32, rtol=1e-6)
    out, _mat, _launch, release = e._infer_impl(x64.astype(np.float32))
    assert out.dtype == jnp.float32
    # Matching dtype means no staging buffer was drawn from the pool.
    assert release is None


def test_cli_warmup_verb_reports_warm_state(monkeypatch, capsys):
    # `tdn warmup`: bring up, warm the ladder, report — engine bring-up
    # is stubbed with the mesh-free real engine (Engine.up needs the
    # mesh API this container's jax lacks; warm_buckets itself is real).
    import json

    import tpu_dist_nn.cli as cli

    eng = _mesh_free_engine()
    eng.setup_seconds = 0.0
    eng.placement = lambda: {"devices": 1}  # instance shadow: no mesh_spec
    eng.down = lambda: None
    monkeypatch.setattr(cli, "_engine_from_args", lambda args, **kw: eng)
    rc = cli.main(["warmup", "--config", "unused.json", "--rows", "8"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["warmed_buckets"] == [1, 2, 4, 8]
    assert out["warm_bucket_count"] == 4
    assert out["persists_across_processes"] == bool(
        out["persistent_cache_dir"]
    )


# ------------------------------------------------------ bench A/B smoke


def test_bench_overlap_smoke_overlapped_at_least_serial():
    # The quick-tier regression gate (ISSUE 2 CI satellite): the
    # double-buffered batcher must not lose to the serial loop on the
    # same workload, and overlap must actually occur. A controlled
    # async-cost engine with PER-ROW dispatch and fetch costs (so
    # coalescing cannot amortize them away — the regime pipelining
    # targets) makes the expected margin ~2x: serial pays
    # dispatch+fetch per row, the pipeline pays max(dispatch, fetch).
    # The >= assertion is therefore robust to CI box jitter.
    from bench import overlap_bench

    eng = AsyncFakeEngine(dim=8, dispatch_seconds=0.001,
                          fetch_seconds=0.001, per_row=True)
    ab = overlap_bench(
        None, clients=6, rpcs_per_client=8, rows_per_rpc=2,
        engine=eng, warm_rows=0,
    )
    assert ab["overlapped"]["overlap_ratio"] > 0, ab
    assert ab["overlapped"]["rows_per_sec"] >= ab["serial"]["rows_per_sec"], ab
    # The serial control arm must really be serial.
    assert ab["serial"]["overlapped_batches"] == 0, ab
