"""Ring attention / sequence parallelism: parity with full attention,
gradient flow, combined data+seq meshes."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    dot_product_attention,
    forward,
    init_transformer,
    lm_loss,
)
from tpu_dist_nn.parallel.mesh import (
    AXIS_DATA,
    AXIS_SEQ,
    MeshSpec,
    build_mesh,
)
from tpu_dist_nn.parallel.ring_attention import (
    make_seq_parallel_lm_forward,
    make_seq_parallel_lm_loss,
    ring_attention,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq_len=64
)


def _qkv(b=2, t=32, h=4, dh=8, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.normal(size=(b, t, h, dh)), jnp.float32) for _ in range(3)
    )


def _ring_apply(mesh, q, k, v, causal):
    fn = jax.shard_map(
        functools.partial(ring_attention, causal=causal),
        mesh=mesh,
        in_specs=(P(None, AXIS_SEQ), P(None, AXIS_SEQ), P(None, AXIS_SEQ)),
        out_specs=P(None, AXIS_SEQ),
    )
    return np.asarray(fn(q, k, v))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    @pytest.mark.parametrize("seq_devices", [2, 4, 8])
    def test_matches_full_attention(self, causal, seq_devices):
        mesh = build_mesh(MeshSpec(seq=seq_devices))
        q, k, v = _qkv()
        want = np.asarray(dot_product_attention(q, k, v, causal=causal))
        got = _ring_apply(mesh, q, k, v, causal)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)

    def test_single_device_degenerates(self):
        mesh = build_mesh(MeshSpec(seq=1))
        q, k, v = _qkv(t=16)
        want = np.asarray(dot_product_attention(q, k, v, causal=True))
        got = _ring_apply(mesh, q, k, v, True)
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)

    def test_gradients_match(self):
        """d(sum(out))/d(q,k,v) through the ring == through full attention."""
        mesh = build_mesh(MeshSpec(seq=4))
        q, k, v = _qkv(t=16)

        def full_loss(q, k, v):
            return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

        ring = jax.shard_map(
            functools.partial(ring_attention, causal=True),
            mesh=mesh,
            in_specs=(P(None, AXIS_SEQ),) * 3,
            out_specs=P(None, AXIS_SEQ),
        )

        def ring_loss(q, k, v):
            return jnp.sum(ring(q, k, v) ** 2)

        g_full = jax.jit(jax.grad(full_loss, argnums=(0, 1, 2)))(q, k, v)
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
        for gf, gr in zip(g_full, g_ring):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gf), atol=5e-5, rtol=1e-3
            )


class TestSeqParallelLM:
    @pytest.mark.parametrize("spec", [MeshSpec(seq=4), MeshSpec(seq=2, data=2),
                                      MeshSpec(seq=2, data=4)])
    def test_forward_matches_single_chip(self, spec):
        mesh = build_mesh(spec)
        params = init_transformer(jax.random.key(0), CFG)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (4, 32)), jnp.int32)
        want = np.asarray(forward(params, tokens, CFG))
        fwd = make_seq_parallel_lm_forward(mesh, CFG)
        got = np.asarray(jax.jit(fwd)(params, tokens))
        np.testing.assert_allclose(got, want, atol=3e-4, rtol=1e-3)

    def test_indivisible_seq_raises(self):
        mesh = build_mesh(MeshSpec(seq=4))
        fwd = make_seq_parallel_lm_forward(mesh, CFG)
        params = init_transformer(jax.random.key(0), CFG)
        tokens = jnp.zeros((2, 30), jnp.int32)
        with pytest.raises(ValueError, match="not divisible"):
            fwd(params, tokens)

    def test_loss_matches_single_chip(self):
        mesh = build_mesh(MeshSpec(seq=4, data=2))
        params = init_transformer(jax.random.key(1), CFG)
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (4, 33)), jnp.int32)
        # Single-chip lm_loss scores tokens[:, :-1] -> targets[:, 1:];
        # the seq-parallel loss feeds the full (divisible) sequence and
        # masks internally — compare against the same formulation.
        T = 32
        loss_fn = make_seq_parallel_lm_loss(mesh, CFG)
        got = float(loss_fn(params, tokens[:, : T]))

        logits = forward(params, tokens[:, :T], CFG)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(
            logp, tokens[:, 1:T, None], axis=-1
        )[..., 0]
        want = float(-jnp.mean(ll))
        assert abs(got - want) < 1e-4

    def test_loss_gradients_flow(self):
        mesh = build_mesh(MeshSpec(seq=2, data=2))
        params = init_transformer(jax.random.key(2), CFG)
        rng = np.random.default_rng(2)
        tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (4, 32)), jnp.int32)
        loss_fn = make_seq_parallel_lm_loss(mesh, CFG)
        grads = jax.jit(jax.grad(loss_fn))(params, tokens)
        gnorm = sum(float(jnp.sum(g**2)) for g in jax.tree.leaves(grads))
        assert np.isfinite(gnorm) and gnorm > 0


def test_ring_remat_grads_match():
    import dataclasses as dc

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16,
    )
    params = init_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (4, 16)), jnp.int32
    )
    mesh = build_mesh(MeshSpec(seq=2, data=4))
    g0 = jax.jit(jax.grad(make_seq_parallel_lm_loss(mesh, cfg)))(params, tokens)
    cfg_r = dc.replace(cfg, remat=True)
    g1 = jax.jit(jax.grad(make_seq_parallel_lm_loss(mesh, cfg_r)))(params, tokens)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism
# ---------------------------------------------------------------------------


def test_ulysses_forward_matches_single_chip():
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        forward,
        init_transformer,
    )
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.ring_attention import make_seq_parallel_lm_forward

    cfg = TransformerConfig(
        vocab_size=23, d_model=16, n_heads=4, n_layers=2, d_ff=32, max_seq_len=16
    )
    mesh = build_mesh(MeshSpec(seq=2, data=2))
    params = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    ref = forward(params, tokens, cfg)
    out = make_seq_parallel_lm_forward(mesh, cfg, mode="ulysses")(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=1e-6)


def test_ulysses_grads_match_single_chip():
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
        lm_loss,
    )
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.ring_attention import make_seq_parallel_lm_loss

    cfg = TransformerConfig(
        vocab_size=23, d_model=16, n_heads=4, n_layers=2, d_ff=32, max_seq_len=17
    )
    mesh = build_mesh(MeshSpec(seq=2, data=2))
    params = init_transformer(jax.random.key(1), cfg)
    rng = np.random.default_rng(1)
    rows = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)

    sp_loss = make_seq_parallel_lm_loss(mesh, cfg, mode="ulysses")
    loss_sp, grads_sp = jax.jit(jax.value_and_grad(sp_loss))(params, rows)
    # Single-chip reference with the same mask-position-0 convention.
    def ref_loss(p, t):
        from tpu_dist_nn.models.transformer import forward as fwd

        logits = fwd(p, t, cfg)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, t[:, 1:][..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    loss_ref, grads_ref = jax.jit(jax.value_and_grad(ref_loss))(params, rows)
    np.testing.assert_allclose(float(loss_sp), float(loss_ref), rtol=1e-5)
    for (pa, ga), (pb, gb) in zip(
        jax.tree.flatten_with_path(grads_sp)[0],
        jax.tree.flatten_with_path(grads_ref)[0],
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(ga), np.asarray(gb), rtol=5e-4, atol=1e-6, err_msg=str(pa)
        )


def test_ulysses_rejects_indivisible_heads():
    import jax

    from tpu_dist_nn.models.transformer import TransformerConfig
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.ring_attention import make_seq_parallel_lm_forward

    cfg = TransformerConfig(
        vocab_size=23, d_model=18, n_heads=3, n_layers=1, d_ff=24, max_seq_len=16
    )
    mesh = build_mesh(MeshSpec(seq=2, data=2))
    with pytest.raises(ValueError, match="divisible"):
        make_seq_parallel_lm_forward(mesh, cfg, mode="ulysses")
