"""Fleet observability plane (ISSUE 9): cross-replica trace stitching,
the embedded time-series ring, SLO burn-rate tracking, and `tdn top`.

The stitched-trace smoke runs a REAL 2-process loopback fleet: two
subprocess replicas (lightweight fake engines — no jax import in the
children) behind an in-parent router, so the stitched document
genuinely joins spans recorded by different processes' tracers. SLO
burn behavior is driven deterministically through testing/faults.py
delays and virtual clocks on the ring/tracker.
"""

import json
import logging
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tests.test_batcher_pipeline import AsyncFakeEngine
from tpu_dist_nn.obs import start_http_server
from tpu_dist_nn.obs.collect import merge_profiles, stitch_chrome_traces
from tpu_dist_nn.obs.exposition import (
    parse_prometheus_text,
    parsed_histogram_quantile,
    split_series,
)
from tpu_dist_nn.obs.log import _TokenBucket, get_logger
from tpu_dist_nn.obs.registry import REGISTRY, Registry, histogram_quantile
from tpu_dist_nn.obs.slo import (
    SLOTracker,
    availability_objective,
    latency_objective,
)
from tpu_dist_nn.obs.timeseries import TimeSeriesRing
from tpu_dist_nn.obs.trace import Tracer
from tpu_dist_nn.serving import CircuitBreaker, GrpcClient, ReplicaPool
from tpu_dist_nn.serving.router import (
    admin_routes,
    router_health,
    serve_router,
)
from tpu_dist_nn.serving.server import serve_engine
from tpu_dist_nn.testing import faults


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=5.0
    ) as r:
        return r.read()


# ------------------------------------------------- histogram quantiles


def test_histogram_quantile_known_distributions():
    # Exact small case: one observation per bucket.
    edges = (1.0, 2.0, 3.0)
    counts = [1, 1, 1, 1]  # 0.5, 1.5, 2.5, +Inf
    assert histogram_quantile(edges, counts, 0.25) == pytest.approx(1.0)
    assert histogram_quantile(edges, counts, 0.5) == pytest.approx(2.0)
    # q=1.0 lands in +Inf: clamps to the top finite edge.
    assert histogram_quantile(edges, counts, 1.0) == pytest.approx(3.0)
    # Empty histogram: no estimate, never a crash.
    assert histogram_quantile(edges, [0, 0, 0, 0], 0.99) is None
    with pytest.raises(ValueError):
        histogram_quantile(edges, counts, 1.5)

    # Uniform[0, 10) against unit buckets: every quantile is within
    # one bucket width of truth.
    reg = Registry()
    h = reg.histogram("tdn_q_test_seconds", "t",
                      buckets=[float(i) for i in range(1, 11)])
    rng = np.random.default_rng(0)
    values = rng.uniform(0.0, 10.0, 5000)
    child = h.labels()
    for v in values:
        child.observe(float(v))
    for q in (0.1, 0.5, 0.9, 0.99):
        est = child.quantile(q)
        truth = float(np.quantile(values, q))
        assert abs(est - truth) <= 1.0, (q, est, truth)
    # Metric-level convenience matches the child.
    assert h.quantile(0.5) == child.quantile(0.5)


def test_scrape_side_quantile_matches_registry_side():
    reg = Registry()
    h = reg.histogram("tdn_q_par_seconds", "t", labels=("method",))
    rng = np.random.default_rng(1)
    for v in rng.exponential(0.01, 2000):
        h.labels(method="Process").observe(float(v))
    from tpu_dist_nn.obs.exposition import render

    parsed = parse_prometheus_text(render(reg))
    for q in (0.5, 0.99):
        scrape = parsed_histogram_quantile(
            parsed, "tdn_q_par_seconds", q, method="Process"
        )
        assert scrape == pytest.approx(
            h.quantile(q, method="Process"), rel=1e-9
        )
    # No matching series -> None, not a crash.
    assert parsed_histogram_quantile(
        parsed, "tdn_q_par_seconds", 0.5, method="Generate"
    ) is None


def test_split_series_round_trip():
    assert split_series('tdn_x{a="1",b="with space"}') == (
        "tdn_x", {"a": "1", "b": "with space"}
    )
    assert split_series("tdn_x") == ("tdn_x", {})


# ------------------------------------------------------ timeseries ring


def test_timeseries_ring_windows_deltas_and_reset():
    reg = Registry()
    c = reg.counter("tdn_rpc_requests_total", "t", labels=("method",))
    g = reg.gauge("tdn_batcher_pending_rows", "t", labels=("method",))
    ring = TimeSeriesRing(resolution=1.0, retention=10.0, registry=reg)
    t0 = 1000.0
    c.labels(method="Process").inc(10)
    g.labels(method="Process").set(3)
    ring.collect(now=t0)
    c.labels(method="Process").inc(40)
    ring.collect(now=t0 + 5)
    key = 'tdn_rpc_requests_total{method="Process"}'
    assert ring.delta(key, window=100, now=t0 + 5) == (40.0, 5.0)
    # Window that opens between the samples still uses the point at or
    # before its start as the baseline.
    assert ring.delta(key, window=3, now=t0 + 5)[0] == 40.0
    # Gauges ride along for /timeseries and tdn top.
    series = ring.series(family="tdn_batcher_pending_rows")
    assert series['tdn_batcher_pending_rows{method="Process"}'][-1][1] == 3.0
    # Retention: the ring holds at most retention/resolution points.
    for i in range(30):
        ring.record(key, 50 + i, now=t0 + 6 + i)
    assert len(ring.series()[key]) <= 10
    # Counter reset (replica restart): delta restarts at the new value.
    ring.record(key, 2.0, now=t0 + 40)
    assert ring.delta(key, window=100, now=t0 + 40)[0] == 2.0


def test_timeseries_ring_seeds_series_born_mid_window():
    """A labeled error counter whose FIRST increment is the incident
    must be visible to windowed deltas immediately (the lazy-child
    corollary of the registry's unlabeled-counter rule)."""
    reg = Registry()
    e = reg.counter("tdn_rpc_errors_total", "t", labels=("method", "code"))
    ring = TimeSeriesRing(resolution=1.0, retention=60.0, registry=reg)
    ring.collect(now=1000.0)  # no error children exist yet
    e.labels(method="Process", code="INTERNAL").inc(7)
    ring.collect(now=1005.0)
    # Keys use the family's declared label order: (method, code).
    key = 'tdn_rpc_errors_total{method="Process",code="INTERNAL"}'
    assert ring.delta(key, window=30, now=1005.0)[0] == 7.0


def test_timeseries_endpoint_smoke():
    """Quick-tier smoke: GET /timeseries serves the ring's JSON (and
    404s with a reason before a ring is attached)."""
    reg = Registry()
    c = reg.counter("tdn_rpc_requests_total", "t", labels=("method",))
    c.labels(method="Process").inc(5)
    srv = start_http_server(0, host="127.0.0.1", registry=reg)
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.port, "/timeseries")
        assert exc.value.code == 404
        ring = TimeSeriesRing(resolution=0.5, retention=60.0, registry=reg)
        ring.collect()
        srv.attach(timeseries=ring)
        doc = json.loads(_get(srv.port, "/timeseries"))
        assert doc["resolution_seconds"] == 0.5
        assert "tdn_rpc_requests_total" in doc["families"]
        key = 'tdn_rpc_requests_total{method="Process"}'
        assert doc["series"][key][-1][1] == 5.0
        filt = json.loads(_get(
            srv.port, "/timeseries?family=tdn_rpc_requests_total&window=60"
        ))
        assert set(filt["series"]) == {key}
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.port, "/timeseries?window=bogus")
        assert exc.value.code == 400
    finally:
        srv.close()


# ---------------------------------------------------------------- SLO


class _RecordingLogger:
    def __init__(self):
        self.events = []

    def warning(self, event, **fields):
        self.events.append((event, fields))


def test_slo_burn_rate_rises_under_injected_latency_and_recovers():
    """The acceptance scenario, end to end over a real loopback server:
    a deterministic injected delay (testing/faults.py) pushes p99 past
    the objective -> tdn_slo_burn_rate{window="fast"} > 1 within the
    fast window and a slo.burn event fires; removing the fault recovers
    the fast window and the budget accounting."""
    engine = AsyncFakeEngine(dim=8)
    # Call-indexed fault schedule (the batcher binds infer_async at
    # construction, so the plan wraps it up front): launch 1 is the
    # clean baseline, launches 2-9 hold 80ms >> the 25ms objective,
    # everything after is clean again — the injected latency fault and
    # its removal, bit-reproducible.
    plan = faults.FaultPlan(
        at={n: faults.delay(0.08) for n in range(2, 10)}
    )
    engine.infer_async = faults.wrap(engine.infer_async, plan)
    server, port = serve_engine(engine, 0, host="127.0.0.1")
    client = GrpcClient(f"127.0.0.1:{port}")
    ring = TimeSeriesRing(resolution=1.0, retention=600.0)
    slog = _RecordingLogger()
    tracker = SLOTracker(ring, [
        latency_objective(
            "process_latency", "tdn_batch_wait_seconds", 0.025,
            q=0.99, match={"method": "Process"},
        ),
    ], fast_window=30.0, slow_window=300.0, logger=slog)
    t0 = 10_000.0
    try:
        client.process(np.ones((1, 8)))  # families exist pre-baseline
        ring.collect(now=t0)
        for _ in range(8):
            client.process(np.ones((1, 8)))
        assert plan.fired >= 8
        ring.collect(now=t0 + 10)
        doc = tracker.evaluate(now=t0 + 10)
        obj = doc["objectives"][0]
        fast = obj["windows"]["fast"]
        assert fast["total"] >= 8
        assert fast["burn_rate"] > 1.0, fast
        assert obj["burning"]
        assert obj["error_budget_remaining"] < 1.0
        budget_during = obj["error_budget_remaining"]
        assert [e for e, _ in slog.events] == ["slo.burn"]
        assert REGISTRY.get("tdn_slo_burn_rate").labels(
            slo="process_latency", window="fast"
        ).value > 1.0
        # Fault removed (the schedule ends at launch 9): fast traffic
        # refills the fast window, burn drops under 1, and the
        # slow-window budget accounting recovers as good traffic
        # dilutes the incident.
        for _ in range(60):
            client.process(np.ones((1, 8)))
        ring.collect(now=t0 + 100)
        doc = tracker.evaluate(now=t0 + 100)
        obj = doc["objectives"][0]
        assert obj["windows"]["fast"]["burn_rate"] < 1.0, obj["windows"]
        assert obj["windows"]["fast"]["total"] >= 60
        assert not obj["burning"]
        # Once the slow (compliance) window slides past the incident,
        # the budget itself recovers.
        for _ in range(20):
            client.process(np.ones((1, 8)))
        ring.collect(now=t0 + 450)
        doc = tracker.evaluate(now=t0 + 450)
        obj = doc["objectives"][0]
        assert obj["windows"]["slow"]["bad"] == pytest.approx(0.0, abs=0.5)
        assert obj["error_budget_remaining"] > budget_during
        assert obj["error_budget_remaining"] == pytest.approx(1.0, abs=0.05)
    finally:
        client.close()
        server.stop(0)


def test_slo_endpoint_and_gauges_smoke():
    """Quick-tier smoke: GET /slo serves the tracker's status (404
    with a hint before attachment) and the tdn_slo_* gauges land on
    /metrics."""
    reg = Registry()
    total = reg.counter("tdn_rpc_requests_total", "t", labels=("method",))
    errors = reg.counter("tdn_rpc_errors_total", "t",
                         labels=("method", "code"))
    ring = TimeSeriesRing(resolution=1.0, retention=600.0, registry=reg)
    total.labels(method="Process").inc(1)
    ring.collect(now=2000.0)
    total.labels(method="Process").inc(100)
    errors.labels(method="Process", code="INTERNAL").inc(2)
    ring.collect(now=2010.0)
    tracker = SLOTracker(ring, [
        availability_objective(
            "availability", 0.999,
            total_family="tdn_rpc_requests_total",
            bad_family="tdn_rpc_errors_total",
        ),
    ], fast_window=60.0, slow_window=600.0, registry=reg,
        logger=_RecordingLogger())
    tracker.evaluate(now=2010.0)
    srv = start_http_server(0, host="127.0.0.1", registry=reg)
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.port, "/slo")
        assert exc.value.code == 404
        srv.attach(slo=tracker)
        doc = json.loads(_get(srv.port, "/slo"))
        obj = doc["objectives"][0]
        assert obj["name"] == "availability"
        assert obj["windows"]["fast"]["bad"] == 2.0
        assert obj["windows"]["fast"]["burn_rate"] > 1.0
        parsed = parse_prometheus_text(_get(srv.port, "/metrics").decode())
        assert parsed[
            'tdn_slo_burn_rate{slo="availability",window="fast"}'
        ] > 1.0
        assert (
            'tdn_slo_error_budget_remaining{slo="availability"}' in parsed
        )
    finally:
        srv.close()


def test_slo_burn_rate_limit_is_per_objective():
    """Two simultaneously-burning objectives must BOTH alert: the
    slo.burn token bucket is per objective, so a continuously-burning
    latency SLO cannot starve the availability SLO's events."""
    reg = Registry()
    total = reg.counter("tdn_rpc_requests_total", "t", labels=("method",))
    errors = reg.counter("tdn_rpc_errors_total", "t",
                         labels=("method", "code"))
    h = reg.histogram("tdn_batch_wait_seconds", "t", labels=("method",))
    ring = TimeSeriesRing(resolution=1.0, retention=600.0, registry=reg)
    total.labels(method="Process").inc(1)
    h.labels(method="Process").observe(0.001)
    ring.collect(now=3000.0)
    for _ in range(50):
        total.labels(method="Process").inc()
        h.labels(method="Process").observe(0.5)  # >> objective
    errors.labels(method="Process", code="INTERNAL").inc(20)
    ring.collect(now=3010.0)
    tracker = SLOTracker(ring, [
        latency_objective("lat", "tdn_batch_wait_seconds", 0.025,
                          match={"method": "Process"}),
        availability_objective(
            "avail", 0.999, total_family="tdn_rpc_requests_total",
            bad_family="tdn_rpc_errors_total"),
    ], fast_window=60.0, slow_window=600.0, registry=reg)
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    slo_logger = logging.getLogger("tpu_dist_nn.obs.slo")
    handler = _Capture()
    slo_logger.addHandler(handler)
    old_level = slo_logger.level
    slo_logger.setLevel(logging.WARNING)
    try:
        for _ in range(4):  # past the per-objective burst of 2
            tracker.evaluate(now=3010.0)
    finally:
        slo_logger.removeHandler(handler)
        slo_logger.setLevel(old_level)
    lat_alerts = [r for r in records if "slo=lat" in r]
    avail_alerts = [r for r in records if "slo=avail" in r]
    assert len(lat_alerts) >= 2 and len(avail_alerts) >= 2, records


def test_slo_flag_validation_fails_fast():
    from tpu_dist_nn.cli import main

    assert main(["up", "--config", "/nonexistent.json",
                 "--slo-availability", "1.5"]) == 2
    assert main(["up", "--config", "/nonexistent.json",
                 "--slo-latency-p99-ms", "-3"]) == 2
    # Valid objective but nowhere to evaluate/serve it: silently-inert
    # flags are rejected, not ignored.
    assert main(["up", "--config", "/nonexistent.json",
                 "--slo-availability", "0.999"]) == 2
    assert main(["up", "--config", "/nonexistent.json",
                 "--metrics-port", "0",
                 "--slo-availability", "0.999"]) == 2  # no --grpc-port


# --------------------------------------------------- trace_id filtering


def test_trace_endpoint_trace_id_filter():
    tracer = Tracer(sample_rate=1.0)
    with tracer.start("rpc.Process") as a:
        pass
    with tracer.start("rpc.Process") as b:
        pass
    assert a.trace_id != b.trace_id
    srv = start_http_server(0, host="127.0.0.1", registry=Registry())
    srv._tracer = tracer
    try:
        doc = json.loads(_get(srv.port, f"/trace?trace_id={a.trace_id}"))
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert spans and all(
            e["args"]["trace_id"] == a.trace_id for e in spans
        )
        full = json.loads(_get(srv.port, "/trace"))
        assert len([e for e in full["traceEvents"]
                    if e.get("ph") == "X"]) == 2
    finally:
        srv.close()


# ------------------------------------------------------ trace stitching


def _chrome_doc(pid, spans):
    evs = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": f"tdn[{pid}]"}}]
    for name, ts, dur, trace_id, span_id in spans:
        evs.append({
            "ph": "X", "cat": "tdn", "name": name, "ts": ts, "dur": dur,
            "pid": pid, "tid": 1,
            "args": {"trace_id": trace_id, "span_id": span_id},
        })
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


def test_stitch_dedupes_filters_and_lanes_replica_restart():
    """Unit coverage for the stitcher, including the boot_id-changes-
    mid-trace shape: one source address contributing spans from TWO
    pids (a restart between scrapes) must yield two lanes, both named
    by the source."""
    router = _chrome_doc(100, [
        ("rpc.Process", 0, 100, "T1", "r-root"),
        ("router.forward", 10, 80, "T1", "r-fwd"),
    ])
    # One replica, restarted mid-trace: old boot's span and new boot's
    # span arrive under the same source label with different pids.
    replica = {"traceEvents": (
        _chrome_doc(200, [("rpc.Process", 20, 30, "T1", "a-old")])
        ["traceEvents"]
        + _chrome_doc(300, [("rpc.Process", 60, 20, "T1", "a-new")])
        ["traceEvents"]
    )}
    # A loopback endpoint re-exporting the router's span: deduped.
    dup = _chrome_doc(100, [("rpc.Process", 0, 100, "T1", "r-root")])
    st = stitch_chrome_traces(
        {"router": router, "replica 127.0.0.1:5101": replica, "dup": dup}
    )
    meta = st["metadata"]
    assert meta["deduped_events"] == 1
    lanes = {ln["name"]: ln for ln in meta["lanes"]}
    assert "router" in lanes
    assert "replica 127.0.0.1:5101" in lanes
    assert "replica 127.0.0.1:5101 #2" in lanes
    assert lanes["replica 127.0.0.1:5101"]["source_pid"] == 200
    assert lanes["replica 127.0.0.1:5101 #2"]["source_pid"] == 300
    spans = [e for e in st["traceEvents"] if e.get("ph") == "X"]
    assert {e["args"]["trace_id"] for e in spans} == {"T1"}
    assert len(spans) == 4  # r-root, r-fwd, a-old, a-new — no dup
    # trace_id filter drops other traces entirely.
    other = _chrome_doc(400, [("rpc.Process", 0, 10, "T2", "b1")])
    st2 = stitch_chrome_traces({"router": router, "o": other},
                               trace_id="T1")
    assert all(
        e["args"]["trace_id"] == "T1"
        for e in st2["traceEvents"] if e.get("ph") == "X"
    )


# The subprocess replica: a REAL serve_engine + /metrics endpoint with
# its own process-wide tracer, but no jax import (the fake engine is
# numpy-only), so startup is sub-second.
_CHILD = r"""
import json, threading
import numpy as np
from tpu_dist_nn.serving.server import serve_engine
from tpu_dist_nn.obs import start_http_server

class _M:
    input_dim = 8

class _Eng:
    model = _M()
    def infer_async(self, x):
        return np.asarray(x, dtype=np.float64) * 2.0
    def fetch(self, h):
        return h

srv, port = serve_engine(_Eng(), 0, host="127.0.0.1")
ms = start_http_server(0, host="127.0.0.1")
print(json.dumps({"grpc_port": port, "metrics_port": ms.port}),
      flush=True)
threading.Event().wait()
"""


def _spawn_replica():
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd="/root/repo",
    )
    line = proc.stdout.readline()
    if not line:
        err = proc.stderr.read()
        proc.kill()
        raise RuntimeError(f"replica failed to start: {err[-800:]}")
    ports = json.loads(line)
    return proc, ports["grpc_port"], ports["metrics_port"]


def test_two_process_loopback_stitched_trace():
    """Quick-tier acceptance smoke: a request routed through a
    2-replica loopback fleet yields ONE stitched Chrome trace with the
    router's router.forward span and the serving replica's rpc.*
    subtree under the same trace_id, via `tdn trace --aggregate`, with
    lanes named by process."""
    from tpu_dist_nn.cli import main

    procs = []
    pool = rsrv = metrics = client = None
    targets = []
    try:
        grpc_targets, metrics_targets = [], []
        for _ in range(2):
            proc, gport, mport = _spawn_replica()
            procs.append(proc)
            grpc_targets.append(f"127.0.0.1:{gport}")
            metrics_targets.append(f"127.0.0.1:{mport}")
        targets = grpc_targets
        for t in targets:
            CircuitBreaker.evict(t)
        pool = ReplicaPool(grpc_targets, metrics_targets, seed=0)
        rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
        metrics = start_http_server(
            0, host="127.0.0.1", health_fn=router_health(pool),
            routes=admin_routes(pool),
        )
        client = GrpcClient(f"127.0.0.1:{rport}", timeout=15.0,
                            breaker=None)
        for i in range(4):
            out = client.process(np.full((1, 8), float(i)))
            np.testing.assert_allclose(out, np.full((1, 8), 2.0 * i))

        out_path = "/tmp/_tdn_stitched_trace_test.json"
        rc = main(["trace", "--target", f"127.0.0.1:{metrics.port}",
                   "--aggregate", "-o", out_path])
        assert rc == 0
        with open(out_path) as f:
            doc = json.load(f)
        lane_names = {
            e["pid"]: e["args"]["name"]
            for e in doc["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert "router" in lane_names.values()
        assert sum(
            1 for n in lane_names.values() if n.startswith("replica ")
        ) == 2, lane_names
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        by_trace = {}
        for e in spans:
            by_trace.setdefault(e["args"]["trace_id"], []).append(e)
        stitched = [
            tid for tid, evs in by_trace.items()
            if any(e["name"] == "router.forward"
                   and lane_names[e["pid"]] == "router" for e in evs)
            and any(e["name"].startswith("rpc.")
                    and lane_names[e["pid"]].startswith("replica ")
                    for e in evs)
        ]
        assert stitched, (
            f"no trace contains both the router.forward span and a "
            f"replica-lane rpc.* span: lanes={lane_names}, "
            f"traces={list(by_trace)}"
        )
        # The server-side twin: /trace/fleet on the router's endpoint.
        fleet = json.loads(_get(metrics.port, "/trace/fleet"))
        assert fleet["metadata"]["stitched_sources"][0].startswith(
            ("replica", "router")
        )
        assert len(fleet["metadata"]["lanes"]) == 3
        # One stitched trace can be pulled alone via ?trace_id=.
        one = json.loads(_get(
            metrics.port, f"/trace/fleet?trace_id={stitched[0]}"
        ))
        one_spans = [e for e in one["traceEvents"] if e.get("ph") == "X"]
        assert one_spans and {
            e["args"]["trace_id"] for e in one_spans
        } == {stitched[0]}
    finally:
        if client is not None:
            client.close()
        if metrics is not None:
            metrics.close()
        if rsrv is not None:
            rsrv.stop(0)
        if pool is not None:
            pool.close()
        for proc in procs:
            proc.kill()
        for t in targets:
            CircuitBreaker.evict(t)


# ------------------------------------------------------- profile merge


def test_fleet_profile_merge_recomputes_shares_and_keeps_router_lane():
    def pdoc(stage_rows, traces=4, wall=1.0):
        return {"traces": traces, "methods": {"Process": {
            "traces": traces, "wall_seconds_total": wall, "share_sum": 1.0,
            "stages": [
                {"stage": s, "count": c, "total_s": t, "share": t / wall,
                 "p50_s": p50, "p99_s": p99, "max_s": p99}
                for s, c, t, p50, p99 in stage_rows
            ],
            "slowest": [{"trace_id": "T", "wall_s": wall, "stages": {}}],
        }}}

    router = pdoc([("router.forward", 4, 0.6, 0.1, 0.2),
                   ("handler", 4, 0.4, 0.05, 0.1)], wall=1.0)
    replica = pdoc([("fetch", 4, 2.0, 0.3, 0.9),
                    ("handler", 4, 1.0, 0.15, 0.3)], wall=3.0)
    merged = merge_profiles({"router": router, "replica a": replica})
    m = merged["methods"]["Process"]
    assert m["traces"] == 8
    stages = {s["stage"]: s for s in m["stages"]}
    assert set(stages) == {"router.forward", "fetch", "handler"}
    assert m["share_sum"] == pytest.approx(1.0, abs=0.01)
    # Sums are exact; p99 is the fleet-worst source; p50 count-weighted.
    assert stages["handler"]["count"] == 8
    assert stages["handler"]["total_s"] == pytest.approx(1.4)
    assert stages["handler"]["p99_s"] == 0.3
    assert stages["handler"]["p50_s"] == pytest.approx(0.1)
    assert merged["sources"] == {"router": 4, "replica a": 4}
    assert [s["source"] for s in m["slowest"]] == ["replica a", "router"]


# ------------------------------------------------ log limiter threading


def test_log_rate_limiter_under_concurrent_emitters():
    """The token bucket's accounting must stay exact when hammered from
    many threads: allowed count bounded by burst + rate * elapsed, and
    every denial either reported as `suppressed` on a later emit or
    still pending in the bucket state."""
    bucket = _TokenBucket(rate=50.0, burst=20)
    allowed = []
    reported = []
    lock = threading.Lock()
    n_threads, per_thread = 8, 300
    start = threading.Barrier(n_threads)
    t0 = time.monotonic()

    def worker():
        start.wait()
        mine_allowed, mine_reported = 0, 0
        for _ in range(per_thread):
            ok, suppressed = bucket.allow(("log", "event"))
            if ok:
                mine_allowed += 1
                mine_reported += suppressed
            else:
                assert suppressed == 0
        with lock:
            allowed.append(mine_allowed)
            reported.append(mine_reported)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    elapsed = time.monotonic() - t0
    total = n_threads * per_thread
    n_allowed = sum(allowed)
    assert n_allowed >= 20  # the burst always gets through
    assert n_allowed <= 20 + 50.0 * elapsed + n_threads, (
        n_allowed, elapsed
    )
    # Conservation: every denied call is either already reported on a
    # subsequent allowed emit or still pending in the bucket.
    pending = bucket._state[("log", "event")][2]
    assert sum(reported) + pending == total - n_allowed


def test_structured_logger_concurrent_emit_keeps_records_bounded():
    logger = logging.getLogger("tdn.test.fleet_obs.limiter")
    logger.setLevel(logging.INFO)
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = _Capture()
    logger.addHandler(handler)
    try:
        slog = get_logger("tdn.test.fleet_obs.limiter", rate=1.0, burst=5)
        threads = [
            threading.Thread(target=lambda: [
                slog.warning("storm.event", i=i) for i in range(200)
            ])
            for _ in range(6)
        ]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        elapsed = time.monotonic() - t0
        assert 1 <= len(records) <= 5 + elapsed + 6
    finally:
        logger.removeHandler(handler)


# -------------------------------------------------------------- tdn top


def test_top_render_frame_rows_slo_and_sparkline():
    from tpu_dist_nn.obs.top import render_frame, sparkline

    state = {
        "target": "127.0.0.1:9100", "fleet": True, "at": 0.0,
        "rows": [
            {"source": "router", "state": "", "rps": 120.5,
             "p50_ms": 1.2, "p99_ms": 9.9, "pending": 0.0, "slots": 0.0,
             "occupancy": 0.0, "prefix_hit": None, "spark": [1, 2, 9]},
            {"source": "replica 127.0.0.1:5101", "state": "active",
             "breaker": "open", "rps": None, "p50_ms": None,
             "p99_ms": None, "pending": 4.0, "slots": 6.0,
             "occupancy": 0.77, "prefix_hit": 0.5, "spark": None},
            {"source": "replica dead", "error": "unreachable (x)"},
        ],
        "slo": {"objectives": [{
            "name": "latency", "objective": "p99 <= 100ms",
            "burning": True, "error_budget_remaining": 0.1,
            "windows": {"fast": {"burn_rate": 3.2},
                        "slow": {"burn_rate": 0.9}},
        }]},
    }
    frame = render_frame(state, color=False)
    assert "router" in frame and "replica 127.0.0.1:5101" in frame
    assert "active/open" in frame
    assert "unreachable (x)" in frame
    assert "p99 <= 100ms" in frame and "3.20" in frame
    assert sparkline([0, 0, 0], width=4) != "    "  # flat-but-nonzero
    assert sparkline([], width=4) == "    "


def test_cli_top_single_endpoint_iterations(capsys):
    reg = REGISTRY
    fam = reg.counter("tdn_rpc_requests_total", "t", labels=("method",))
    fam.labels(method="Process").inc(3)
    srv = start_http_server(0, host="127.0.0.1")
    try:
        from tpu_dist_nn.cli import main

        rc = main(["top", "--target", f"127.0.0.1:{srv.port}",
                   "--iterations", "2", "--interval", "0.05",
                   "--no-color"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "tdn top" in out and f"127.0.0.1:{srv.port}" in out
        assert "[single]" in out
        assert "no SLOs declared" in out
    finally:
        srv.close()


def test_cli_top_unreachable_is_user_error():
    from tpu_dist_nn.cli import main

    rc = main(["top", "--target", "127.0.0.1:1", "--iterations", "1",
               "--no-color", "--timeout", "0.5"])
    assert rc == 2


# --------------------------------------------------------- bench gate


def test_bench_gate_slo_metrics_skip_and_gate():
    sys.path.insert(0, "/root/repo/tools")
    try:
        import bench_gate
    finally:
        sys.path.pop(0)

    def round_doc(p99=None, avail=None):
        doc = {"backend": "cpu", "value": 100000.0, "serving": {}}
        if p99 is not None:
            doc["serving"]["slo"] = {
                "latency": {"measured_p99_ms": p99},
                "availability": {"measured": avail},
            }
        return doc

    # Pre-ISSUE-9 previous round: the slo rows skip, nothing fails.
    verdict = bench_gate.compare(round_doc(), round_doc(16.0, 1.0))
    rows = {m["metric"]: m for m in verdict["metrics"]}
    assert "skipped" in rows["slo_process_p99_ms"]
    assert "skipped" in rows["slo_availability"]
    assert not verdict["regressions"]
    # Regressed p99 and availability both fail the enforced gate.
    verdict = bench_gate.compare(
        round_doc(16.0, 1.0), round_doc(40.0, 0.9)
    )
    assert "slo_process_p99_ms" in verdict["regressions"]
    assert "slo_availability" in verdict["regressions"]
    # Improvement never fails.
    verdict = bench_gate.compare(
        round_doc(16.0, 0.99), round_doc(8.0, 1.0)
    )
    assert not verdict["regressions"]
