"""REAL multi-process tests: two OS processes, one JAX job, gloo CPU
collectives over a localhost coordinator.

The rest of the suite emulates multi-chip inside one process
(``--xla_force_host_platform_device_count``); these tests are the
multi-HOST layer on top — the part the reference gets from Docker
networking (run_grpc_fcnn.py:83-155) and this framework gets from
``jax.distributed`` + DCN. They catch the one bug virtual devices
cannot: feeding process-local batches into a global-mesh step, which
trains N silently-diverging models instead of one (each worker asserts
identical losses across hosts, and the parent asserts parity with a
single-process run on the same global data).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

WORKER = Path(__file__).with_name("multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_pair(scenario: str, timeout: float = 420.0) -> list[dict]:
    """Launch the scenario in 2 fresh worker processes; return their RESULTs."""
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"
    }
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_DEFAULT_MATMUL_PRECISION"] = "highest"
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), scenario, str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    results = []
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
        lines = [l for l in out.splitlines() if l.startswith("RESULT ")]
        assert lines, f"no RESULT line:\n{out[-3000:]}"
        results.append(json.loads(lines[-1][len("RESULT "):]))
    return sorted(results, key=lambda r: r["pid"])


def test_two_process_collectives():
    r0, r1 = _run_pair("collectives")
    assert r0["sum"] == r0["expect"] == r1["sum"]


@pytest.mark.parametrize("scenario", ["train_pipelined", "train_pipelined_1f1b"])
def test_two_process_pipelined_training_in_sync(scenario):
    r0, r1 = _run_pair(scenario)
    # Both hosts must be the SAME model at every step (the whole point:
    # without the global-batch feed each host trains its own model and
    # these diverge immediately)...
    assert r0["losses"] == r1["losses"], (r0, r1)
    assert r0["w_digest"] == pytest.approx(r1["w_digest"], rel=1e-6)
    assert r0["eval_acc"] == r1["eval_acc"]
    # ...training for real (finite, decreasing), and in the same quality
    # band as single-process training on the same global data (exact
    # step parity is checked by test_two_process_step_parity — the loop
    # shuffles per-stripe, so batch compositions legitimately differ).
    assert all(np.isfinite(r0["losses"])) and r0["losses"][-1] < r0["losses"][0]
    ref = _single_process_reference(schedule="1f1b" if "1f1b" in scenario else "gpipe")
    assert abs(r0["losses"][-1] - ref["losses"][-1]) < 0.25, (r0, ref)


def test_two_process_step_parity():
    """One fixed-batch step across 2 hosts == the single-process step
    (loss and grads are row-partition-invariant)."""
    r0, r1 = _run_pair("step_parity")
    assert r0["loss"] == r1["loss"]
    ref = _single_process_step_reference()
    np.testing.assert_allclose(r0["loss"], ref["loss"], rtol=1e-5)
    np.testing.assert_allclose(r0["w_digest"], ref["w_digest"], rtol=1e-5)


def test_two_process_lm_pipeline_in_sync():
    r0, r1 = _run_pair("train_lm_pipelined")
    assert r0["losses"] == r1["losses"], (r0, r1)
    assert r0["tok_digest"] == pytest.approx(r1["tok_digest"], rel=1e-6)
    # Losses must be finite and decreasing-ish (training, not noise).
    assert all(np.isfinite(r0["losses"]))
    assert r0["losses"][-1] < r0["losses"][0]


def test_two_process_lm_3d_in_sync():
    # PP x TP x DP on the real 2-process topology, under BOTH wire
    # layouts: the production mesh (data outermost — the DCN carries
    # the data all-reduce) and a stage-outermost mesh (the DCN carries
    # every inter-stage ppermute). Hosts agree with each other AND the
    # two layouts agree with each other.
    r0, r1 = _run_pair("train_lm_3d")
    for name in ("dcn_data", "dcn_stage"):
        assert r0[f"losses_{name}"] == r1[f"losses_{name}"], (name, r0, r1)
        assert r0[f"tok_digest_{name}"] == pytest.approx(
            r1[f"tok_digest_{name}"], rel=1e-6
        )
        assert all(np.isfinite(r0[f"losses_{name}"]))
        assert r0[f"losses_{name}"][-1] < r0[f"losses_{name}"][0]
    # Wire placement must not change the math.
    assert r0["losses_dcn_data"] == pytest.approx(
        r0["losses_dcn_stage"], rel=1e-5
    )


@pytest.mark.parametrize("scenario", ["train_lm_zero1", "train_lm_fsdp"])
def test_two_process_zero_fsdp_in_sync(scenario):
    r0, r1 = _run_pair(scenario)
    assert r0["losses"] == r1["losses"], (r0, r1)
    assert r0["tok_digest"] == pytest.approx(r1["tok_digest"], rel=1e-6)
    assert all(np.isfinite(r0["losses"])) and r0["losses"][-1] < r0["losses"][0]


def test_two_process_crosshost_pipeline_inference():
    """Stage axis spanning both processes with data=1: the replicated-
    batch path (no striping possible) must serve identical outputs."""
    r0, r1 = _run_pair("pipeline_infer_crosshost")
    assert r0["digest"] == pytest.approx(r1["digest"], rel=1e-7)
    assert r0["row0"] == r1["row0"]
    # Softmax outputs: rows sum to ~1 (sanity that real values flowed).
    assert sum(r0["row0"]) == pytest.approx(1.0, abs=1e-4)


def test_two_process_checkpoint_resume_without_shared_fs():
    r0, r1 = _run_pair("checkpoint_resume")
    assert r0["n_files"] == 1 and r1["n_files"] == 0  # process 0 writes alone
    assert r0["step"] == r1["step"] == 5
    # Host 1 resumed from the BROADCAST state, not its (empty) disk.
    assert r0["w_digest"] == r1["w_digest"] == pytest.approx(3.0 * 28.0)
    assert r0["marker"] == r1["marker"] == 7.0


def test_two_process_zero1_checkpoint_resume_without_shared_fs():
    """Sharded (ZeRO-1) training state round-trips across hosts: saving
    gathers, resuming broadcasts — with the template's opt-state leaves
    non-addressable on host 1 — and a retention violation raises on
    BOTH processes instead of hanging one in the collective."""
    r0, r1 = _run_pair("checkpoint_resume_zero1")
    assert r0["step"] == r1["step"] == 7
    # Host 1 resumed from the broadcast payload; digests match the
    # state that was saved, identically on both hosts.
    assert r0["tok_digest"] == pytest.approx(r0["saved_tok_digest"], rel=1e-6)
    assert r1["tok_digest"] == pytest.approx(r0["tok_digest"], rel=1e-6)
    assert r0["retention_raised"] and r1["retention_raised"]


def test_two_process_checkpoint_io_failure_fails_everyone():
    """Process 0's write failure is broadcast: both processes raise the
    same ValueError instead of host 1 hanging in the next collective."""
    r0, r1 = _run_pair("checkpoint_io_failure_agreed")
    assert r0["first_ok"] and r1["first_ok"]
    assert r0["raised"] and r1["raised"]


def _single_process_step_reference() -> dict:
    import optax

    from tests.multihost_worker import _global_dataset
    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.train.pipeline_trainer import (
        make_pipeline_train_step,
        prepare_pipeline_batch,
    )
    import jax.numpy as jnp

    mesh = build_mesh(MeshSpec(stage=2, data=4))
    model = random_model([12, 10, 6], seed=0)
    params = build_pipeline_params(partition_model(model, [1, 1]))
    full = _global_dataset()
    xs, labels, mask = prepare_pipeline_batch(
        params.meta, full.x[:32], full.y[:32], 4, 4
    )
    opt = optax.adam(1e-2)
    step = make_pipeline_train_step(mesh, params.meta, 4, opt)
    w, _, loss = step(
        params.weights, opt.init(params.weights),
        jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(mask),
    )
    return {"loss": float(loss), "w_digest": float(np.abs(np.asarray(w.w)).sum())}


def _single_process_reference(schedule: str) -> dict:
    """The same training run on this process's 8 virtual devices."""
    from tests.multihost_worker import _global_dataset
    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.train.pipeline_trainer import TrainConfig, train_pipelined

    mesh = build_mesh(MeshSpec(stage=2, data=4))
    model = random_model([12, 10, 6], seed=0)
    params = build_pipeline_params(partition_model(model, [1, 1]))
    full = _global_dataset()
    cfg = TrainConfig(epochs=2, batch_size=32, learning_rate=1e-2, seed=0)
    params, history = train_pipelined(
        params, mesh, full, cfg, num_microbatches=4, schedule=schedule
    )
    w = np.asarray(params.weights.w)
    return {
        "losses": [round(h["loss"], 6) for h in history],
        "w_digest": float(np.abs(w).sum()),
    }
