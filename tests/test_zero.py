"""ZeRO-1 optimizer-state sharding: trajectory parity + actual sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_dist_nn.models.transformer import TransformerConfig, init_transformer
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.zero import make_zero_lm_train_step, zero_opt_shardings
from tpu_dist_nn.train.lm_trainer import make_lm_train_step

CFG = TransformerConfig(
    vocab_size=64, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_seq_len=16
)


def _tokens(b, key=0):
    return jnp.asarray(
        np.random.default_rng(key).integers(0, CFG.vocab_size, (b, 16)),
        jnp.int32,
    )


def test_zero1_matches_unsharded_trajectory():
    mesh = build_mesh(MeshSpec(data=8))
    params = init_transformer(jax.random.key(0), CFG)
    optimizer = optax.adam(1e-3)

    base_step = make_lm_train_step(CFG, optimizer)
    zero_step = make_zero_lm_train_step(mesh, CFG, optimizer, params)

    p0, o0 = params, optimizer.init(params)
    p1, o1 = params, optimizer.init(params)
    for i in range(6):
        tokens = _tokens(16, key=i)
        p0, o0, l0 = base_step(p0, o0, tokens)
        p1, o1, l1 = zero_step(p1, o1, tokens)
        # The loss trajectory is the parity gate: grads reduce in a
        # different order (reduce-scatter vs single-device sum), and
        # Adam's early near-sign updates amplify that float noise into
        # O(lr) param wiggle — so params only match to the lr scale.
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-4)
    for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-3
        )


def test_opt_state_actually_sharded():
    mesh = build_mesh(MeshSpec(data=8))
    params = init_transformer(jax.random.key(0), CFG)
    optimizer = optax.adam(1e-3)
    step = make_zero_lm_train_step(mesh, CFG, optimizer, params)
    _, opt_state, _ = step(params, optimizer.init(params), _tokens(16))
    sharded = [
        leaf for leaf in jax.tree.leaves(opt_state)
        if hasattr(leaf, "sharding")
        and any(s is not None for s in leaf.sharding.spec)
    ]
    assert sharded, "no optimizer-state leaf ended up sharded"
    # A sharded leaf's per-device shard is 1/8 of the leaf.
    leaf = max(sharded, key=lambda l: l.size)
    shard = leaf.addressable_shards[0].data
    assert shard.size == leaf.size // 8


def test_shardings_prefer_largest_divisible_axis():
    mesh = build_mesh(MeshSpec(data=8))

    class Box:
        def __init__(self, shape):
            self.shape = shape
            self.ndim = len(shape)

    sh = zero_opt_shardings(
        {"a": Box((2, 128, 48)), "b": Box((3, 5)), "c": Box(())}, mesh
    )
    assert tuple(sh["a"].spec) == (None, "data", None)
    assert tuple(sh["b"].spec) == ()
    assert tuple(sh["c"].spec) == ()


def test_sharded_init_never_materializes_replicated_moments():
    mesh = build_mesh(MeshSpec(data=8))
    params = init_transformer(jax.random.key(0), CFG)
    optimizer = optax.adam(1e-3)
    step = make_zero_lm_train_step(mesh, CFG, optimizer, params)
    opt_state = step.init_opt_state(params)
    sharded = [
        leaf for leaf in jax.tree.leaves(opt_state)
        if hasattr(leaf, "sharding")
        and any(s is not None for s in leaf.sharding.spec)
    ]
    assert sharded, "init produced no sharded moment leaves"
    leaf = max(sharded, key=lambda l: l.size)
    assert leaf.addressable_shards[0].data.size == leaf.size // 8
    # And the step consumes it directly.
    _, opt_state, loss = step(params, opt_state, _tokens(16))
    assert float(loss) > 0


def test_fsdp_params_and_moments_sharded_and_learning():
    from tpu_dist_nn.parallel.zero import make_fsdp_lm_train_step

    mesh = build_mesh(MeshSpec(data=8))
    params = init_transformer(jax.random.key(0), CFG)
    optimizer = optax.adam(1e-3)
    step = make_fsdp_lm_train_step(mesh, CFG, optimizer, params)
    opt_state = step.init_opt_state(params)
    losses = []
    p = params
    for i in range(6):
        p, opt_state, loss = step(p, opt_state, _tokens(16, key=i % 2))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # Params came out actually sharded (1/8 shards on the big leaves).
    leaves = [l for l in jax.tree.leaves(p)
              if hasattr(l, "sharding")
              and any(s is not None for s in l.sharding.spec)]
    assert leaves, "no param leaf is sharded under FSDP"
    big = max(leaves, key=lambda l: l.size)
    assert big.addressable_shards[0].data.size == big.size // 8


def test_fsdp_matches_unsharded_loss_trajectory():
    from tpu_dist_nn.parallel.zero import make_fsdp_lm_train_step

    mesh = build_mesh(MeshSpec(data=8))
    params = init_transformer(jax.random.key(0), CFG)
    optimizer = optax.adam(1e-3)
    base_step = make_lm_train_step(CFG, optimizer)
    fsdp_step = make_fsdp_lm_train_step(mesh, CFG, optimizer, params)
    p0, o0 = params, optimizer.init(params)
    p1, o1 = params, fsdp_step.init_opt_state(params)
    for i in range(5):
        tokens = _tokens(16, key=i)
        p0, o0, l0 = base_step(p0, o0, tokens)
        p1, o1, l1 = fsdp_step(p1, o1, tokens)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-4)


def test_fsdp_composes_with_bf16_and_remat():
    import dataclasses as dc

    from tpu_dist_nn.parallel.zero import make_fsdp_lm_train_step

    cfg = dc.replace(CFG, compute_dtype="bfloat16", remat=True)
    mesh = build_mesh(MeshSpec(data=8))
    params = init_transformer(jax.random.key(0), cfg)
    optimizer = optax.adam(1e-3)
    step = make_fsdp_lm_train_step(mesh, cfg, optimizer, params)
    opt_state = step.init_opt_state(params)
    p = params
    losses = []
    for i in range(4):
        p, opt_state, loss = step(p, opt_state, _tokens(16, key=i % 2))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # Master params remain f32 (bf16 is the compute cast, not storage).
    assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(p))


def test_sp_zero1_matches_sp_only_trajectory():
    # SP x ZeRO-1 (the composition --seq-parallel --zero1 used to
    # reject): sharding the optimizer state over the data axis of a
    # (seq, data) mesh must not change the sequence-parallel loss
    # trajectory, and the moments must actually shard.
    from tpu_dist_nn.parallel.zero import make_sp_sharded_lm_train_step
    from tpu_dist_nn.train.lm_trainer import make_seq_parallel_lm_train_step

    mesh = build_mesh(MeshSpec(seq=4, data=2))
    params = init_transformer(jax.random.key(1), CFG)
    optimizer = optax.adam(1e-3)

    sp_step = make_seq_parallel_lm_train_step(mesh, CFG, optimizer)
    z_step = make_sp_sharded_lm_train_step(mesh, CFG, optimizer, params)

    p0, o0 = params, optimizer.init(params)
    p1, o1 = params, optimizer.init(params)
    for i in range(4):
        tokens = _tokens(8, key=10 + i)
        p0, o0, l0 = sp_step(p0, o0, tokens)
        p1, o1, l1 = z_step(p1, o1, tokens)
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-4)

    # Moments genuinely sharded over data (not replicated copies).
    mu = o1[0].mu["blocks"]["w_qkv"]
    assert not mu.sharding.is_fully_replicated


def test_sp_fsdp_params_sharded_and_learning():
    # SP x FSDP: params AND moments sharded over data while the loss
    # runs the ring decomposition over seq.
    from tpu_dist_nn.parallel.zero import make_sp_sharded_lm_train_step

    mesh = build_mesh(MeshSpec(seq=2, data=4))
    params = init_transformer(jax.random.key(2), CFG)
    optimizer = optax.adam(1e-2)
    step = make_sp_sharded_lm_train_step(
        mesh, CFG, optimizer, params, shard_params=True
    )
    opt_state = step.init_opt_state(params)
    p, o = params, opt_state
    losses = []
    for i in range(4):
        p, o, loss = step(p, o, _tokens(8, key=20 + i))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert not p["blocks"]["w_qkv"].sharding.is_fully_replicated
    assert not o[0].mu["blocks"]["w_qkv"].sharding.is_fully_replicated


def test_cli_lm_sp_zero1(capsys):
    # The previously rejected flag combination end to end.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--seq-parallel", "4", "--data-parallel", "2",
        "--zero1",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out
