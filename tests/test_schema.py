"""Schema round-trip and partitioning tests (SURVEY.md §4 implication (d))."""

import json

import numpy as np
import pytest

from tpu_dist_nn.core.schema import (
    ModelSpec,
    StageSpec,
    load_examples,
    load_model,
    partition_model,
    save_examples,
    save_model,
    stage_port,
    validate_distribution,
)
from tpu_dist_nn.testing.factories import random_model

SAMPLE_CONFIG = {
    # Shape of config_sample.json: per-neuron weights/bias/activation.
    "layers": [
        {
            "type": "hidden",
            "nodes": 3,
            "neurons": [
                {"weights": [0.1, 0.2], "bias": 0.3, "activation": "relu"},
                {"weights": [0.4, 0.5], "bias": 0.6, "activation": "relu"},
                {"weights": [0.7, 0.8], "bias": 0.9, "activation": "relu"},
            ],
        },
        {
            "type": "output",
            "nodes": 2,
            "neurons": [
                {"weights": [1.0, 1.1, 1.2], "bias": 0.5, "activation": "softmax"},
                {"weights": [1.5, 1.3, 1.1], "bias": 0.8, "activation": "softmax"},
            ],
        },
    ]
}


def test_neuron_weight_transpose_rule():
    model = ModelSpec.from_json_dict(SAMPLE_CONFIG)
    l0 = model.layers[0]
    # Neuron rows stacked then transposed → (in_dim, out_dim) (grpc_node.py:51).
    assert l0.weights.shape == (2, 3)
    np.testing.assert_allclose(l0.weights[:, 0], [0.1, 0.2])
    np.testing.assert_allclose(l0.weights[:, 2], [0.7, 0.8])
    assert l0.biases.tolist() == [0.3, 0.6, 0.9]
    assert l0.activation == "relu"
    assert model.layers[1].activation == "softmax"
    assert model.input_dim == 2 and model.output_dim == 2


def test_model_json_round_trip(tmp_path):
    model = random_model([7, 5, 3], seed=3)
    model.metadata["inference_metrics"] = {"accuracy": 0.9685}
    p = tmp_path / "m.json"
    save_model(model, p)
    loaded = load_model(p)
    assert len(loaded.layers) == 2
    for a, b in zip(model.layers, loaded.layers):
        np.testing.assert_allclose(a.weights, b.weights)
        np.testing.assert_allclose(a.biases, b.biases)
        assert a.activation == b.activation
        assert a.type_tag == b.type_tag
    assert loaded.metadata["inference_metrics"] == {"accuracy": 0.9685}


def test_examples_round_trip(tmp_path):
    inputs = np.random.default_rng(0).uniform(size=(4, 6))
    labels = np.array([1, 0, 3, 2], dtype=np.int32)
    p = tmp_path / "ex.json"
    save_examples(inputs, labels, p)
    li, ll = load_examples(p)
    np.testing.assert_allclose(li, inputs)
    np.testing.assert_array_equal(ll, labels)


def test_examples_nested_inputs_flattened(tmp_path):
    p = tmp_path / "ex.json"
    p.write_text(json.dumps({"examples": [{"input": [[0.5, 0.8], [0.6, 0.2]], "label": 5}]}))
    inputs, labels = load_examples(p)
    assert inputs.shape == (1, 4)
    assert labels[0] == 5


def test_distribution_validation():
    # sum(layer_distribution) == len(layers) (run_grpc_fcnn.py:182-183).
    validate_distribution([1, 2], 3)
    with pytest.raises(ValueError):
        validate_distribution([1, 1], 3)
    with pytest.raises(ValueError):
        validate_distribution([-1, 4], 3)


def test_partition_model():
    model = random_model([8, 6, 4, 2], seed=1)
    stages = partition_model(model, [2, 1])
    assert len(stages) == 2
    assert [len(s.layers) for s in stages] == [2, 1]
    assert stages[0].expected_input_dim == 8
    assert stages[1].expected_input_dim == 4
    assert stages[0].name == "fcnn_node_0"
    # Port formula parity: 5100 + 100*i + 1 (run_grpc_fcnn.py:221).
    assert stages[0].port == 5201 - 100  # 5101
    assert stage_port(2) == 5301


def test_partition_empty_stage_is_identity():
    model = random_model([8, 6, 4], seed=2)
    stages = partition_model(model, [2, 0, 0])
    assert stages[1].layers == [] and stages[1].output_dim == 4
    assert stages[2].expected_input_dim == 4


def test_stage_json_round_trip():
    model = random_model([5, 4, 3], seed=4)
    stage = partition_model(model, [2])[0]
    obj = stage.to_stage_json()
    assert set(obj) == {"layer_0", "layer_1", "expected_input_dim"}
    back = StageSpec.from_stage_json(obj, index=0)
    assert len(back.layers) == 2
    assert back.expected_input_dim == 5
    np.testing.assert_allclose(back.layers[0].weights, stage.layers[0].weights)


def test_empty_stage_json_round_trip():
    model = random_model([5, 4, 3], seed=4)
    stage = partition_model(model, [0, 2])[0]
    back = StageSpec.from_stage_json(stage.to_stage_json(), index=0)
    assert back.layers == [] and back.expected_input_dim == 5
    # The bare layer_N format without the dim extension stays rejected.
    with pytest.raises(ValueError, match="expected_input_dim"):
        StageSpec.from_stage_json({}, index=0)


def test_chain_dim_mismatch_raises():
    model = random_model([5, 4, 3], seed=5)
    model.layers[1].weights = np.zeros((9, 3))
    model.layers[1].biases = np.zeros(3)
    with pytest.raises(ValueError):
        partition_model(model, [1, 1])


def test_shipped_sample_configs_load_and_run():
    """The repo ships config samples (reference C12,
    config/config_sample.json:1-33) usable exactly as the README
    quickstart shows: load, forward via the oracle, sane softmax out."""
    from pathlib import Path

    from tpu_dist_nn.testing.oracle import oracle_forward_batch

    root = Path(__file__).resolve().parents[1]
    model = load_model(root / "config" / "config_sample.json")
    x, labels = load_examples(
        root / "config" / "example_inputs" / "example_inputs_sample.json"
    )
    assert model.input_dim == x.shape[1]
    out = oracle_forward_batch(model, x)
    assert out.shape == (len(x), model.output_dim)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-6)
    assert len(labels) == len(x)
    assert all(0 <= int(l) < model.output_dim for l in labels)
