"""JAX forward vs. float64 numpy oracle (SURVEY.md §4 implication (a)).

The oracle mirrors manual_nn.forward_pass; the jit path must match to
f32 tolerance on sample-scale and MNIST-scale models.
"""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist_nn.core.activations import apply_activation
from tpu_dist_nn.models.fcnn import (
    forward,
    forward_logits,
    init_fcnn,
    params_from_spec,
    spec_from_params,
)
from tpu_dist_nn.testing.factories import random_inputs, random_model
from tpu_dist_nn.testing.oracle import oracle_forward, oracle_forward_batch


def test_forward_matches_oracle_small():
    model = random_model([6, 5, 4, 3], seed=7)
    x = random_inputs(9, 6)
    params = params_from_spec(model)
    got = np.asarray(jax.jit(forward)(params, jnp.asarray(x, jnp.float32)))
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_forward_matches_oracle_mnist_shape():
    # The exported/served model shape: 784-32-16-10 (notebook cell 8).
    model = random_model([784, 32, 16, 10], seed=8)
    x = random_inputs(32, 784)
    params = params_from_spec(model)
    got = np.asarray(jax.jit(forward)(params, jnp.asarray(x, jnp.float32)))
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=5e-4, atol=1e-5)
    # Softmax outputs sum to 1.
    np.testing.assert_allclose(got.sum(-1), np.ones(32), rtol=1e-5)


def test_forward_float64_exact():
    # With x64 enabled the jit path agrees with the float64 oracle tightly.
    model = random_model([12, 8, 4], seed=9)
    x = random_inputs(5, 12)
    jax.config.update("jax_enable_x64", True)
    try:
        params = params_from_spec(model, dtype=jnp.float64)
        got = np.asarray(forward(params, jnp.asarray(x, jnp.float64)))
    finally:
        jax.config.update("jax_enable_x64", False)
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=1e-12, atol=1e-14)


def test_activations_match_oracle_names():
    x = jnp.asarray(np.linspace(-3, 3, 24).reshape(4, 6), jnp.float32)
    for name, ref in [
        ("relu", lambda v: np.maximum(0, v)),
        ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
        ("linear", lambda v: v),
        ("tanh", np.tanh),
    ]:
        got = np.asarray(apply_activation(x, name))
        np.testing.assert_allclose(got, ref(np.asarray(x, np.float64)), rtol=1e-5, atol=1e-6)
    # Unknown activation falls back to linear (grpc_node.py:72-73).
    np.testing.assert_allclose(np.asarray(apply_activation(x, "mystery")), np.asarray(x))


def test_softmax_stability():
    x = jnp.asarray([[1000.0, 1000.0, 999.0]], jnp.float32)
    out = np.asarray(apply_activation(x, "softmax"))
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-6)


def test_logits_mode_skips_final_activation():
    model = random_model([6, 4, 3], seed=10)
    params = params_from_spec(model)
    x = jnp.asarray(random_inputs(3, 6), jnp.float32)
    probs = jax.jit(forward)(params, x)
    logits = jax.jit(forward_logits)(params, x)
    np.testing.assert_allclose(
        np.asarray(jax.nn.softmax(logits, axis=-1)), np.asarray(probs), rtol=1e-5, atol=1e-7
    )


def test_init_and_export_round_trip():
    params = init_fcnn(jax.random.key(0), [20, 16, 10])
    spec = spec_from_params(params, ["relu", "softmax"])
    assert spec.layers[0].type_tag == "hidden"
    assert spec.layers[-1].type_tag == "output"
    x = random_inputs(4, 20)
    got = np.asarray(forward(params, jnp.asarray(x, jnp.float32)))
    want = oracle_forward_batch(spec, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_dim_mismatch_raises_in_oracle():
    model = random_model([6, 4, 3], seed=11)
    try:
        oracle_forward(model, np.zeros(5))
    except ValueError as e:
        assert "Dimension mismatch" in str(e)
    else:
        raise AssertionError("expected ValueError")
