"""Flight recorder (ISSUE 11): anomaly-triggered incident bundles.

Coverage map:
* unit: the incident store's LRU bound, bundle capture/zip schema, the
  log ring's window/level filters, each detector's trigger + the
  recorder's per-detector cooldown, the /trace since= cursor, and the
  fleet /slo merge;
* crash path: SUBPROCESS tests where an injected unhandled exception
  and a SIGABRT each leave a valid bundle on disk whose manifest names
  the crash;
* loopback smoke (quick tier): a deterministic faults.py delay pushes
  p99 past the objective -> the burn detector fires -> a bundle exists
  and contains a trace with the faulted span;
* fleet drill (quick tier, the acceptance scenario): a 2-replica
  loopback fleet under a deterministic fault storm trips the burn
  detector ON THE ROUTER, which captures a stitched fleet bundle in
  one detector tick; `tdn incident ls/show/pull` and `tdn debug
  bundle` drive the same store over HTTP;
* overhead: the armed-vs-disarmed serving A/B (bench.py) shows no
  measurable hot-path cost and zero spurious captures.
"""

import io
import json
import logging
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request
import zipfile

import numpy as np
import pytest

from tests.test_batcher_pipeline import AsyncFakeEngine
from tpu_dist_nn.obs import start_http_server
from tpu_dist_nn.obs.collect import merge_slo, merge_timeseries
from tpu_dist_nn.obs.incident import (
    BreakerOpenDetector,
    DrainFailoverDetector,
    FlightRecorder,
    IncidentStore,
    SLOBurnDetector,
    SpikeDetector,
    capture_bundle,
    default_detectors,
    incident_routes,
)
from tpu_dist_nn.obs.log import LOG_RING, LogRing, get_logger
from tpu_dist_nn.obs.registry import REGISTRY, Registry
from tpu_dist_nn.obs.slo import SLOTracker, latency_objective
from tpu_dist_nn.obs.timeseries import TimeSeriesRing
from tpu_dist_nn.obs.trace import Tracer
from tpu_dist_nn.serving import CircuitBreaker, GrpcClient, ReplicaPool
from tpu_dist_nn.serving.router import (
    admin_routes,
    router_health,
    serve_router,
)
from tpu_dist_nn.serving.server import serve_engine
from tpu_dist_nn.testing import faults


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10.0
    ) as r:
        return r.read()


def _zip_names(data: bytes) -> list[str]:
    return zipfile.ZipFile(io.BytesIO(data)).namelist()


def _zip_json(data: bytes, name: str):
    return json.loads(zipfile.ZipFile(io.BytesIO(data)).read(name))


# ------------------------------------------------------------- log ring


def test_log_ring_bounded_window_and_level():
    ring = LogRing(capacity=4)
    t0 = time.time()
    for i in range(6):
        ring.append({"ts": t0 + i, "level": "info", "event": f"e{i}",
                     "fields": {}})
    assert len(ring) == 4
    assert ring.dropped_total == 2
    assert [r["event"] for r in ring.snapshot()] == ["e2", "e3", "e4", "e5"]
    ring.append({"ts": t0 + 100, "level": "error", "event": "boom",
                 "fields": {}})
    # Minimum-severity filter: warning returns warnings AND errors.
    assert [r["event"] for r in ring.snapshot(level="warning")] == ["boom"]
    assert len(ring.snapshot(level="info")) == 4
    # Window keeps the recent tail; limit keeps the newest N.
    recent = ring.snapshot(window=time.time() - (t0 + 99))
    assert [r["event"] for r in recent] == ["boom"]
    assert [r["event"] for r in ring.snapshot(limit=2)] == ["e5", "boom"]
    with pytest.raises(ValueError):
        ring.snapshot(level="bogus")


def test_structured_logger_feeds_process_ring_and_logs_endpoint():
    logger_name = "tdn.test.incident.ring"
    logging.getLogger(logger_name).setLevel(logging.INFO)
    slog = get_logger(logger_name)
    marker = f"incident.ring_marker_{os.getpid()}"
    slog.info(marker, a=1, trace="none")
    events = [r["event"] for r in LOG_RING.snapshot(level="info")]
    assert marker in events
    srv = start_http_server(0, host="127.0.0.1", registry=Registry())
    try:
        doc = json.loads(_get(srv.port, "/logs?level=info"))
        assert doc["capacity"] == LOG_RING.capacity
        assert any(r["event"] == marker for r in doc["records"])
        # level filter excludes it at error severity
        doc2 = json.loads(_get(srv.port, "/logs?level=error&limit=5"))
        assert all(r["event"] != marker for r in doc2["records"])
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.port, "/logs?window=bogus")
        assert exc.value.code == 400
    finally:
        srv.close()


# ---------------------------------------------------------------- store


def test_incident_store_prunes_oldest_past_max(tmp_path):
    store = IncidentStore(str(tmp_path), max_incidents=3)
    # A foreign zip in the directory (an operator's pulled copy) must
    # neither list as an incident nor cost a max_incidents slot.
    (tmp_path / "pulled_copy.zip").write_bytes(b"PK\x05\x06" + b"\0" * 18)
    for i in range(5):
        iid, data = capture_bundle(f"trig{i}", "r", tracer=Tracer(),
                                   registry=Registry())
        store.save(iid, data)
        time.sleep(0.02)  # distinct mtimes: prune order is arrival order
    ids = store.ids()
    assert len(ids) == 3
    triggers = [m["trigger"] for m in store.list()]
    assert triggers == ["trig4", "trig3", "trig2"]  # newest first
    assert (tmp_path / "pulled_copy.zip").exists()  # never pruned
    # Reads: manifest + bytes round-trip, unknown id degrades to None.
    assert store.manifest(ids[0])["trigger"] in ("trig2", "trig3", "trig4")
    assert store.read("nonexistent") is None
    assert store.manifest("nonexistent") is None
    with pytest.raises(ValueError):
        IncidentStore(str(tmp_path), max_incidents=0)


def test_capture_bundle_sections_and_manifest():
    tracer = Tracer(sample_rate=1.0)
    with tracer.start("rpc.Process") as sp:
        sp.set("row_count", 3)
    reg = Registry()
    reg.counter("tdn_x_total", "t").inc(2)
    ring = TimeSeriesRing(resolution=1.0, retention=60.0, registry=reg)
    ring.collect(now=1000.0)
    iid, data = capture_bundle(
        "unit.test", "because", {"k": "v"},
        tracer=tracer, registry=reg, ring=ring,
    )
    names = _zip_names(data)
    for required in ("manifest.json", "trace.json", "profile.json",
                     "metrics.txt", "timeseries.json", "logs.json"):
        assert required in names, names
    m = _zip_json(data, "manifest.json")
    assert m["incident_id"] == iid
    assert m["trigger"] == "unit.test"
    assert m["reason"] == "because"
    assert m["details"] == {"k": "v"}
    assert m["pid"] == os.getpid()
    assert "python" in m["versions"]
    assert sorted(m["sections"]) == m["sections"]
    tr = _zip_json(data, "trace.json")
    assert any(e.get("name") == "rpc.Process"
               for e in tr["traceEvents"] if e.get("ph") == "X")
    assert "tdn_x_total 2" in zipfile.ZipFile(
        io.BytesIO(data)
    ).read("metrics.txt").decode()


def test_capture_bundle_salvages_past_broken_section():
    class _BrokenRing:
        resolution = 1.0
        retention = 60.0

        def series(self, window=None):
            raise RuntimeError("ring exploded")

    iid, data = capture_bundle("unit.broken", tracer=Tracer(),
                               registry=Registry(), ring=_BrokenRing())
    m = _zip_json(data, "manifest.json")
    assert "timeseries.json" in m["section_errors"]
    assert "trace.json" in m["sections"]  # the rest survived


# ------------------------------------------------------------ detectors


class _FakeSLO:
    def __init__(self, burn, total=10.0):
        self._burn = burn
        self._total = total

    def status(self):
        return {"objectives": [{
            "name": "latency", "objective": "p99 <= 25ms",
            "windows": {"fast": {"burn_rate": self._burn,
                                 "total": self._total}},
        }]}


def test_slo_burn_detector_fires_and_cooldown_bounds_recaptures(tmp_path):
    store = IncidentStore(str(tmp_path))
    rec = FlightRecorder(
        store, detectors=[SLOBurnDetector()], tracer=Tracer(),
        registry=Registry(), slo=_FakeSLO(burn=4.2), cooldown=100.0,
    )
    assert rec.check(now=0.0)  # fires
    assert rec.check(now=50.0) == []  # inside the cooldown
    assert rec.check(now=150.0)  # past it: the incident re-captures
    assert len(store.ids()) == 2
    m = store.list()[0]
    assert m["trigger"] == "slo.burn"
    assert "4.2" in m["reason"]
    # Zero-traffic windows never fire (burn of nothing is not a burn).
    rec2 = FlightRecorder(store, detectors=[SLOBurnDetector()],
                          tracer=Tracer(), registry=Registry(),
                          slo=_FakeSLO(burn=9.9, total=0.0))
    assert rec2.check(now=0.0) == []


def test_spike_detector_reads_ring_deltas_with_exclude():
    reg = Registry()
    c = reg.counter("tdn_router_requests_total", "t",
                    labels=("replica", "outcome"))
    ring = TimeSeriesRing(resolution=1.0, retention=600.0, registry=reg)
    c.labels(replica="a", outcome="ok").inc(50)
    ring.collect(now=1000.0)
    rec = FlightRecorder(None, tracer=Tracer(), registry=reg, ring=ring)
    det = SpikeDetector("router.error_spike", "tdn_router_requests_total",
                        window=60.0, min_count=5.0,
                        exclude={"outcome": "ok"})
    # 100 MORE ok outcomes: excluded, no spike.
    c.labels(replica="a", outcome="ok").inc(100)
    ring.collect(now=1010.0)
    assert det.check(rec, now=1010.0) is None
    # 6 UNAVAILABLE outcomes inside the window: spike.
    c.labels(replica="a", outcome="UNAVAILABLE").inc(6)
    ring.collect(now=1020.0)
    reason = det.check(rec, now=1020.0)
    assert reason is not None and "+6" in reason


def test_breaker_open_detector_is_edge_triggered():
    reg = Registry()
    g = reg.gauge("tdn_breaker_state", "t", labels=("target",))
    rec = FlightRecorder(None, tracer=Tracer(), registry=reg)
    det = BreakerOpenDetector()
    g.labels(target="127.0.0.1:5101").set(0.0)
    assert det.check(rec) is None
    g.labels(target="127.0.0.1:5101").set(2.0)  # OPEN
    reason = det.check(rec)
    assert reason is not None and "127.0.0.1:5101" in reason
    # Still open next tick: same incident, no re-fire.
    assert det.check(rec) is None
    # Close then re-open: a NEW incident.
    g.labels(target="127.0.0.1:5101").set(0.0)
    assert det.check(rec) is None
    g.labels(target="127.0.0.1:5101").set(2.0)
    assert det.check(rec) is not None


def test_drain_failover_detector_sees_pool_transitions():
    class _FakePool:
        transitions_total = 0

        def snapshot(self):
            return [{"target": "t1", "state": "draining"}]

    pool = _FakePool()
    rec = FlightRecorder(None, tracer=Tracer(), registry=Registry(),
                         pool=pool)
    det = DrainFailoverDetector()
    assert det.check(rec) is None  # baseline tick
    pool.transitions_total = 2
    reason = det.check(rec)
    assert reason is not None and "draining" in reason
    assert det.check(rec) is None  # no further movement


def test_recorder_survives_broken_detector(tmp_path):
    class _Broken:
        name = "broken"

        def check(self, rec, now=None):
            raise RuntimeError("detector bug")

    store = IncidentStore(str(tmp_path))
    rec = FlightRecorder(
        store, detectors=[_Broken(), SLOBurnDetector()], tracer=Tracer(),
        registry=Registry(), slo=_FakeSLO(burn=2.0),
    )
    captured = rec.check(now=0.0)
    assert len(captured) == 1  # the healthy detector still ran
    assert store.list()[0]["trigger"] == "slo.burn"


def test_debug_bundle_route_persist_contract(tmp_path):
    """?persist=1 saves to the store and serves the saved bytes;
    without a store it is a 409 with the --incident-dir hint, never a
    silently unpersisted 200."""
    routes = incident_routes(FlightRecorder(
        IncidentStore(str(tmp_path)), tracer=Tracer(), registry=Registry(),
    ))
    status, ctype, data = routes["/debug/bundle"]("persist=1&reason=x")
    assert status == 200 and ctype == "application/zip"
    store = IncidentStore(str(tmp_path))
    assert len(store.ids()) == 1
    assert store.read(store.ids()[0]) == data
    assert store.manifest(store.ids()[0])["trigger"] == "manual"
    # Plain capture does not persist.
    status, ctype, _ = routes["/debug/bundle"]("")
    assert status == 200 and len(store.ids()) == 1
    storeless = incident_routes(FlightRecorder(
        None, tracer=Tracer(), registry=Registry(),
    ))
    status, ctype, body = storeless["/debug/bundle"]("persist=1")
    assert status == 409 and b"--incident-dir" in body


# -------------------------------------------------------- since= cursor


def test_tracer_since_cursor_incremental_snapshots():
    tracer = Tracer(sample_rate=1.0)
    with tracer.start("rpc.Process"):
        pass
    doc1 = tracer.chrome_trace()
    cursor = doc1["cursor"]
    assert cursor >= 1
    assert len([e for e in doc1["traceEvents"]
                if e.get("ph") == "X"]) == 1
    # Nothing new: an incremental pull is empty (exemplars included —
    # the slow trace kept in an exemplar slot must not re-send).
    doc2 = tracer.chrome_trace(since=cursor)
    assert [e for e in doc2["traceEvents"] if e.get("ph") == "X"] == []
    with tracer.start("rpc.Generate"):
        pass
    doc3 = tracer.chrome_trace(since=cursor)
    spans = [e for e in doc3["traceEvents"] if e.get("ph") == "X"]
    assert [s["name"] for s in spans] == ["rpc.Generate"]
    assert doc3["cursor"] == cursor + 1


def test_trace_endpoint_since_param_and_cli_flag(tmp_path, capsys):
    tracer = Tracer(sample_rate=1.0)
    for _ in range(3):
        with tracer.start("rpc.Process"):
            pass
    srv = start_http_server(0, host="127.0.0.1", registry=Registry())
    srv._tracer = tracer
    try:
        full = json.loads(_get(srv.port, "/trace"))
        cur = full["cursor"]
        assert len([e for e in full["traceEvents"]
                    if e.get("ph") == "X"]) == 3
        incr = json.loads(_get(srv.port, f"/trace?since={cur}"))
        assert [e for e in incr["traceEvents"] if e.get("ph") == "X"] == []
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(srv.port, "/trace?since=bogus")
        assert exc.value.code == 400
        # The CLI consumer: --since pulls incrementally and prints the
        # cursor to pass back next poll.
        from tpu_dist_nn.cli import main

        out_path = str(tmp_path / "incr.json")
        rc = main(["trace", "--target", f"127.0.0.1:{srv.port}",
                   "--since", str(cur), "-o", out_path])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert summary["spans"] == 0
        assert summary["cursor"] == cur
    finally:
        srv.close()


# ------------------------------------------------------- fleet SLO merge


def _slo_doc(bad_fast, total_fast, bad_slow=0.0, total_slow=0.0,
             kind="availability", q_ms=None):
    obj = {
        "name": "avail" if kind == "availability" else "lat",
        "kind": kind,
        "objective": "availability >= 0.999" if kind == "availability"
        else "p99 <= 100ms",
        "budget_fraction": 0.001 if kind == "availability" else 0.01,
        "family": "f",
        "windows": {
            "fast": {"seconds": 300, "bad": bad_fast, "total": total_fast,
                     "bad_fraction": bad_fast / max(total_fast, 1),
                     "burn_rate": 0.0,
                     **({"measured_quantile_ms": q_ms}
                        if q_ms is not None else {})},
            "slow": {"seconds": 3600, "bad": bad_slow, "total": total_slow,
                     "bad_fraction": 0.0, "burn_rate": 0.0},
        },
        "error_budget_remaining": 1.0,
        "burning": False,
    }
    return {"fast_window_seconds": 300, "slow_window_seconds": 3600,
            "objectives": [obj]}


def test_merge_slo_recomputes_burn_from_summed_counts():
    # Busy replica burning hard + idle replica coasting: the fleet
    # verdict must reflect the SUM (2 bad / 1000 total), not an
    # average of per-source rates.
    merged = merge_slo({
        "replica a": _slo_doc(2.0, 990.0, 2.0, 990.0),
        "replica b": _slo_doc(0.0, 10.0, 0.0, 10.0),
    })
    obj = merged["objectives"][0]
    fast = obj["windows"]["fast"]
    assert fast["bad"] == 2.0 and fast["total"] == 1000.0
    assert fast["bad_fraction"] == pytest.approx(0.002)
    assert fast["burn_rate"] == pytest.approx(2.0)  # 0.002 / 0.001
    assert fast["measured_availability"] == pytest.approx(0.998)
    assert obj["burning"] is True
    assert sorted(obj["sources"]) == ["replica a", "replica b"]
    # Latency quantile: fleet-worst source, named in merged_estimates.
    lat = merge_slo({
        "a": _slo_doc(1.0, 100.0, kind="latency", q_ms=40.0),
        "b": _slo_doc(1.0, 100.0, kind="latency", q_ms=212.0),
    })
    assert lat["objectives"][0]["windows"]["fast"][
        "measured_quantile_ms"] == 212.0
    assert "fleet-worst" in lat["merged_estimates"]["measured_quantile_ms"]


def test_merge_timeseries_keeps_series_per_source():
    merged = merge_timeseries({
        "router": {"resolution_seconds": 5.0, "families": ["f"],
                   "series": {"f{}": [[1, 2]]}},
        "replica a": {"resolution_seconds": 5.0, "families": ["f", "g"],
                      "series": {"f{}": [[1, 7]]}},
    })
    assert merged["families"] == ["f", "g"]
    assert merged["series"]["f{}"] == {
        "router": [[1, 2]], "replica a": [[1, 7]],
    }


# ------------------------------------------------------------ crash path

_CRASH_CHILD = r"""
import sys, signal
from tpu_dist_nn.obs.incident import (FlightRecorder, IncidentStore,
                                      install_crash_hook)
from tpu_dist_nn.obs.trace import Tracer

store = IncidentStore(sys.argv[1], max_incidents=5)
tracer = Tracer(sample_rate=1.0)
with tracer.start("rpc.Process"):
    pass
rec = FlightRecorder(store, tracer=tracer)
install_crash_hook(rec)
print("armed", flush=True)
if sys.argv[2] == "exc":
    raise RuntimeError("injected crash for the flight recorder")
signal.raise_signal(signal.SIGABRT)
"""


def _run_crash_child(tmp_path, mode):
    proc = subprocess.run(
        [sys.executable, "-c", _CRASH_CHILD, str(tmp_path), mode],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
    )
    assert "armed" in proc.stdout, proc.stderr[-800:]
    return proc


def test_crash_unhandled_exception_leaves_valid_bundle(tmp_path):
    proc = _run_crash_child(tmp_path, "exc")
    assert proc.returncode == 1  # the process still died
    assert "RuntimeError" in proc.stderr  # previous excepthook still ran
    store = IncidentStore(str(tmp_path))
    ids = store.ids()
    assert len(ids) == 1
    m = store.manifest(ids[0])
    assert m["trigger"] == "crash.exception"
    assert "RuntimeError: injected crash" in m["reason"]
    assert "injected crash for the flight recorder" in \
        m["details"]["traceback"]
    data = store.read(ids[0])
    tr = _zip_json(data, "trace.json")
    assert any(e.get("name") == "rpc.Process"
               for e in tr["traceEvents"] if e.get("ph") == "X")


def test_crash_sigabrt_leaves_valid_bundle_then_dies_by_signal(tmp_path):
    proc = _run_crash_child(tmp_path, "abrt")
    # The handler captured, restored SIG_DFL, and re-raised: the
    # process status is the real SIGABRT death, not a swallowed one.
    assert proc.returncode == -signal.SIGABRT
    store = IncidentStore(str(tmp_path))
    ids = store.ids()
    assert len(ids) == 1
    m = store.manifest(ids[0])
    assert m["trigger"] == "crash.signal"
    assert m["reason"] == "SIGABRT"
    # faulthandler armed into the store directory for harder deaths.
    assert (tmp_path / "faulthandler.log").exists()


# ------------------------------------------------- loopback burn smoke


class _RecordingLogger:
    def __init__(self):
        self.events = []

    def warning(self, event, **fields):
        self.events.append((event, fields))


def test_burn_detector_captures_bundle_with_faulted_span(tmp_path):
    """Quick-tier acceptance smoke: deterministic faults.py delay
    pushes p99 past the objective -> the burn detector fires on the
    (manually driven) sampler tick -> a bundle exists on disk whose
    manifest names slo.burn and whose trace contains the faulted
    request's spans."""
    engine = AsyncFakeEngine(dim=8)
    plan = faults.FaultPlan(at={n: faults.delay(0.08)
                                for n in range(2, 10)})
    engine.infer_async = faults.wrap(engine.infer_async, plan)
    server, port = serve_engine(engine, 0, host="127.0.0.1")
    client = GrpcClient(f"127.0.0.1:{port}")
    ring = TimeSeriesRing(resolution=1.0, retention=600.0)
    tracker = SLOTracker(ring, [
        latency_objective("process_latency", "tdn_batch_wait_seconds",
                          0.025, q=0.99, match={"method": "Process"}),
    ], fast_window=30.0, slow_window=300.0, logger=_RecordingLogger())
    store = IncidentStore(str(tmp_path))
    rec = FlightRecorder(store, detectors=default_detectors(),
                         ring=ring, slo=tracker)
    # Virtual nows ANCHORED at wall time: the ring/SLO windows are
    # driven deterministically, while the bundle's wall-clock window
    # bracket (capture_bundle reads time.time()) still sees the points.
    t0 = time.time()
    try:
        client.process(np.ones((1, 8)))  # families exist pre-baseline
        ring.collect(now=t0)
        tracker.evaluate(now=t0)
        assert rec.check() == []  # armed, quiet: nothing fires
        for _ in range(8):
            client.process(np.ones((1, 8)))
        assert plan.fired >= 8
        ring.collect(now=t0 + 10)
        tracker.evaluate(now=t0 + 10)
        captured = rec.check()
        assert len(captured) == 1, captured
        m = store.manifest(captured[0])
        assert m["trigger"] == "slo.burn"
        assert "process_latency" in m["reason"]
        data = store.read(captured[0])
        names = _zip_names(data)
        for required in ("trace.json", "logs.json", "timeseries.json",
                         "slo.json", "profile.json", "metrics.txt"):
            assert required in names, names
        tr = _zip_json(data, "trace.json")
        spans = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
        # The faulted requests' spans survived into the bundle: a
        # fetch (where the injected delay sat) over the 80ms hold.
        slow = [e for e in spans
                if e["name"] in ("fetch", "launch")
                and e.get("dur", 0) >= 0.07 * 1e6]
        assert slow, [(e["name"], e.get("dur")) for e in spans][:20]
        ts = _zip_json(data, "timeseries.json")
        assert any(k.startswith("tdn_batch_wait_seconds")
                   for k in ts["series"])
        slo_doc = _zip_json(data, "slo.json")
        assert slo_doc["objectives"][0]["burning"] is True
    finally:
        client.close()
        server.stop(0)


# --------------------------------------------------------- fleet drill

# A subprocess replica with a DETERMINISTIC fault storm baked in:
# every launch holds 60ms, far past the router's 10ms p99 objective.
# Real serve_engine + /metrics endpoint, no jax import: sub-second boot
# (the test_fleet_obs child pattern).
_STORM_CHILD = r"""
import json, threading, time
import numpy as np
from tpu_dist_nn.serving.server import serve_engine
from tpu_dist_nn.obs import start_http_server

class _M:
    input_dim = 8

class _Eng:
    model = _M()
    def infer_async(self, x):
        time.sleep(0.06)  # the deterministic fault storm
        return np.asarray(x, dtype=np.float64) * 2.0
    def fetch(self, h):
        return h

srv, port = serve_engine(_Eng(), 0, host="127.0.0.1")
ms = start_http_server(0, host="127.0.0.1")
print(json.dumps({"grpc_port": port, "metrics_port": ms.port}),
      flush=True)
threading.Event().wait()
"""


def _spawn_storm_replica():
    proc = subprocess.Popen(
        [sys.executable, "-c", _STORM_CHILD],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd="/root/repo",
    )
    line = proc.stdout.readline()
    if not line:
        err = proc.stderr.read()
        proc.kill()
        raise RuntimeError(f"replica failed to start: {err[-800:]}")
    ports = json.loads(line)
    return proc, ports["grpc_port"], ports["metrics_port"]


def test_fleet_drill_burn_trips_router_recorder_stitched_bundle(
    tmp_path, capsys,
):
    """The ISSUE-11 acceptance drill: on a 2-replica loopback fleet, a
    deterministic fault storm trips the burn detector on the ROUTER,
    which captures a stitched fleet bundle within one detector tick;
    `tdn incident show` names the trigger and the bundle contains the
    cross-replica exemplar trace, the logs ring, and the timeseries
    window; `tdn debug bundle` captures the fleet on demand."""
    from tpu_dist_nn.cli import main

    procs = []
    pool = rsrv = metrics = client = None
    targets = []
    try:
        grpc_targets, metrics_targets = [], []
        for _ in range(2):
            proc, gport, mport = _spawn_storm_replica()
            procs.append(proc)
            grpc_targets.append(f"127.0.0.1:{gport}")
            metrics_targets.append(f"127.0.0.1:{mport}")
        targets = grpc_targets
        for t in targets:
            CircuitBreaker.evict(t)
        pool = ReplicaPool(grpc_targets, metrics_targets, seed=0)
        rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
        ring = TimeSeriesRing(resolution=1.0, retention=600.0)
        tracker = SLOTracker(ring, [
            latency_objective("router_latency",
                              "tdn_router_request_seconds", 0.010,
                              q=0.99),
        ], fast_window=30.0, slow_window=300.0,
            logger=_RecordingLogger())
        store = IncidentStore(str(tmp_path), max_incidents=10)
        recorder = FlightRecorder(
            store, detectors=[SLOBurnDetector()], ring=ring,
            slo=tracker, pool=pool, fleet_timeout=15.0,
        )
        metrics = start_http_server(
            0, host="127.0.0.1", health_fn=router_health(pool),
            routes=admin_routes(pool, recorder),
        )
        client = GrpcClient(f"127.0.0.1:{rport}", timeout=20.0,
                            breaker=None)
        t0 = time.time()  # anchored: see the burn-smoke note
        client.process(np.ones((1, 8)))  # family exists pre-baseline
        ring.collect(now=t0)
        tracker.evaluate(now=t0)
        assert recorder.check() == []  # armed + quiet baseline
        for i in range(8):  # the storm: every request ~60ms >> 10ms
            client.process(np.full((1, 8), float(i)))
        ring.collect(now=t0 + 10)
        tracker.evaluate(now=t0 + 10)
        captured = recorder.check()  # ONE detector tick captures
        assert len(captured) == 1, captured
        iid = captured[0]
        m = store.manifest(iid)
        assert m["trigger"] == "slo.burn"
        assert m["fleet"] is True
        assert len(m["replicas"]) == 2
        assert all("error" not in r for r in m["replicas"]), m["replicas"]
        data = store.read(iid)
        names = _zip_names(data)
        assert "trace_fleet.json" in names
        assert "logs.json" in names and "timeseries.json" in names
        assert sum(1 for n in names if n.startswith("replicas/")) == 2
        # The stitched fleet trace: router.forward on the router lane
        # and an rpc.* span on a replica lane sharing ONE trace id —
        # the cross-replica evidence of the exact slow requests.
        fleet = _zip_json(data, "trace_fleet.json")
        lane_names = {
            e["pid"]: e["args"]["name"]
            for e in fleet["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert "router" in lane_names.values()
        assert any(n.startswith("replica ") for n in lane_names.values())
        by_trace = {}
        for e in fleet["traceEvents"]:
            if e.get("ph") == "X":
                by_trace.setdefault(e["args"]["trace_id"], []).append(e)
        stitched = [
            tid for tid, evs in by_trace.items()
            if any(e["name"] == "router.forward"
                   and lane_names[e["pid"]] == "router" for e in evs)
            and any(e["name"].startswith("rpc.")
                    and lane_names[e["pid"]].startswith("replica ")
                    for e in evs)
        ]
        assert stitched, (lane_names, list(by_trace))
        # Replica timeseries windows rode along inside each sub-bundle.
        rep_zips = [n for n in names if n.startswith("replicas/")]
        sub = zipfile.ZipFile(io.BytesIO(data)).read(rep_zips[0])
        assert "trace.json" in _zip_names(sub)

        # ---- the CLI surface against the router's metrics endpoint.
        target = f"127.0.0.1:{metrics.port}"
        assert main(["incident", "ls", "--target", target]) == 0
        out = capsys.readouterr().out
        assert iid in out and "slo.burn" in out
        assert main(["incident", "show", iid, "--target", target]) == 0
        shown = json.loads(capsys.readouterr().out)
        assert shown["trigger"] == "slo.burn"
        pull_path = str(tmp_path / "pulled.zip")
        assert main(["incident", "pull", iid, "--target", target,
                     "-o", pull_path]) == 0
        capsys.readouterr()
        with open(pull_path, "rb") as f:
            assert f.read() == data
        # Manual fleet capture: tdn debug bundle -> a fresh stitched
        # bundle without any detector involved.
        manual_path = str(tmp_path / "manual.zip")
        assert main(["debug", "bundle", "--target", target,
                     "-o", manual_path]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["replicas"] and len(summary["replicas"]) == 2
        with open(manual_path, "rb") as f:
            manual = f.read()
        assert "trace_fleet.json" in _zip_names(manual)
        # GET /incidents lists it all for scrapers too.
        listing = json.loads(_get(metrics.port, "/incidents"))
        assert any(x.get("incident_id") == iid
                   for x in listing["incidents"])
    finally:
        if client is not None:
            client.close()
        if metrics is not None:
            metrics.close()
        if rsrv is not None:
            rsrv.stop(0)
        if pool is not None:
            pool.close()
        for proc in procs:
            proc.kill()
        for t in targets:
            CircuitBreaker.evict(t)


# ----------------------------------------------------- flag validation


def test_cli_incident_flag_validation_fails_fast():
    from tpu_dist_nn.cli import main

    # --incident-dir without --metrics-port: the detectors would have
    # no sampler to ride — rejected, not silently inert.
    assert main(["up", "--config", "/nonexistent.json",
                 "--incident-dir", "/tmp/x"]) == 2
    # ... and without a serving path on this command.
    assert main(["up", "--config", "/nonexistent.json",
                 "--metrics-port", "0", "--incident-dir", "/tmp/x"]) == 2
    assert main(["up", "--config", "/nonexistent.json",
                 "--grpc-port", "0", "--metrics-port", "0",
                 "--incident-dir", "/tmp/x", "--incident-max", "0"]) == 2
    assert main(["router", "--replicas", "h:1",
                 "--incident-dir", "/tmp/x"]) == 2  # no metrics port
    assert main(["lm", "--incident-dir", "/tmp/x", "--metrics-port",
                 "0"]) == 2  # no --serve-generate


# ------------------------------------------------------ overhead smoke


def test_incident_overhead_smoke_armed_within_noise():
    """Quick-tier A/B: serving rps with the recorder ARMED (detectors
    ticking, nothing firing) within noise of disarmed, and zero
    spurious captures — capture is free until it fires. The bound is
    generous for a loaded CI box; bench_gate --history gates the real
    drift across rounds."""
    import bench

    res = bench.incident_overhead_bench(
        clients=4, rpcs_per_client=6, per_row_ms=4.0, repeats=2,
    )
    assert res["captures_during_armed_arm"] == 0
    assert res["ratio"] >= 0.8, res
    # The round artifact carries the pair for the history gate.
    assert set(res) >= {"armed_rps", "disarmed_rps", "ratio"}


def test_bench_gate_incident_ratio_skip_and_fail():
    sys.path.insert(0, "/root/repo/tools")
    try:
        import bench_gate
    finally:
        sys.path.pop(0)

    def round_doc(ratio=None):
        doc = {"backend": "cpu", "value": 100000.0, "serving": {}}
        if ratio is not None:
            doc["serving"]["incident_overhead"] = {"ratio": ratio}
        return doc

    # Pre-ISSUE-11 previous round: the row skips, nothing fails.
    verdict = bench_gate.compare(round_doc(), round_doc(1.0))
    rows = {m["metric"]: m for m in verdict["metrics"]}
    assert "skipped" in rows["incident_armed_ratio"]
    assert not verdict["regressions"]
    # An armed arm that got >5% slower than disarmed-relative history
    # fails the enforced gate.
    verdict = bench_gate.compare(round_doc(1.0), round_doc(0.9))
    assert "incident_armed_ratio" in verdict["regressions"]
    verdict = bench_gate.compare(round_doc(0.97), round_doc(1.0))
    assert not verdict["regressions"]
