"""Optimizer factory: defaults reproduce bare Adam; controls behave."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_dist_nn.train.optimizers import build_optimizer


def _params():
    return {"w": jnp.asarray([[1.0, -2.0], [3.0, 0.5]])}


def _grads(scale=1.0):
    return {"w": jnp.asarray([[10.0, -20.0], [30.0, 5.0]]) * scale}


def test_default_is_exactly_adam():
    opt = build_optimizer(1e-3)
    ref = optax.adam(1e-3)
    p = _params()
    s0, s1 = opt.init(p), ref.init(p)
    u0, _ = opt.update(_grads(), s0, p)
    u1, _ = ref.update(_grads(), s1, p)
    for a, b in zip(jax.tree.leaves(u0), jax.tree.leaves(u1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_clip_norm_bounds_update_magnitude():
    opt = build_optimizer(1e-3, clip_norm=1.0)
    p = _params()
    s = opt.init(p)
    big, _ = opt.update(_grads(1e6), s, p)
    small, _ = opt.update(_grads(1e-6), opt.init(p), p)
    # Adam normalizes scale anyway on step 1; the real check is that the
    # clipped-gradient path produces finite, bounded updates for a 1e6
    # gradient (unclipped Adam is fine too — so compare the *clipped
    # gradient* directly through the transform chain's first stage).
    clip = optax.clip_by_global_norm(1.0)
    g, _ = clip.update(_grads(1e6), clip.init(p))
    norm = jnp.sqrt(sum(jnp.sum(x**2) for x in jax.tree.leaves(g)))
    np.testing.assert_allclose(float(norm), 1.0, rtol=1e-6)
    assert all(jnp.isfinite(x).all() for x in jax.tree.leaves(big))
    assert all(jnp.isfinite(x).all() for x in jax.tree.leaves(small))


def test_warmup_ramps_learning_rate():
    opt = build_optimizer(1.0, warmup_steps=10)
    p = _params()
    s = opt.init(p)
    # Step 0 should apply ~0 lr: params barely move.
    u, s = opt.update(_grads(), s, p)
    first = max(float(jnp.abs(x).max()) for x in jax.tree.leaves(u))
    for _ in range(15):
        u, s = opt.update(_grads(), s, p)
    late = max(float(jnp.abs(x).max()) for x in jax.tree.leaves(u))
    assert first < 0.2 * late


def test_cosine_decays_to_zero():
    opt = build_optimizer(1.0, schedule="cosine", warmup_steps=2,
                          total_steps=20)
    p = _params()
    s = opt.init(p)
    mags = []
    for _ in range(20):
        u, s = opt.update(_grads(), s, p)
        mags.append(max(float(jnp.abs(x).max()) for x in jax.tree.leaves(u)))
    assert mags[-1] < 0.1 * max(mags)


def test_weight_decay_uses_adamw():
    opt = build_optimizer(1e-1, weight_decay=0.1)
    p = _params()
    s = opt.init(p)
    zero_g = jax.tree.map(jnp.zeros_like, _grads())
    u, _ = opt.update(zero_g, s, p)
    # With zero grads, AdamW still decays toward zero: update opposes w.
    assert float(jnp.sum(u["w"] * p["w"])) < 0


def test_validation():
    with pytest.raises(ValueError, match="schedule"):
        build_optimizer(1e-3, schedule="triangle")
    with pytest.raises(ValueError, match="total_steps"):
        build_optimizer(1e-3, schedule="cosine", total_steps=None)
    with pytest.raises(ValueError, match="clip_norm"):
        build_optimizer(1e-3, clip_norm=-1)


def test_trainer_integration_with_controls():
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.models.fcnn import init_fcnn
    from tpu_dist_nn.train.trainer import TrainConfig, train_fcnn

    data = synthetic_mnist(256, dim=32, num_classes=4)
    params = init_fcnn(jax.random.key(0), [32, 16, 4])
    cfg = TrainConfig(
        learning_rate=3e-3, epochs=3, batch_size=64, clip_norm=1.0,
        warmup_steps=2, lr_schedule="cosine",
    )
    _, history = train_fcnn(params, data, cfg)
    assert history[-1]["loss"] < history[0]["loss"]


def test_negative_weight_decay_rejected():
    with pytest.raises(ValueError, match="weight_decay"):
        build_optimizer(1e-3, weight_decay=-0.01)


def test_pipelined_weight_decay_preserves_identity_fillers():
    # AdamW's decay bypasses gradient masking; the update mask must
    # keep the pass-through structure (w=1 diagonals of filler blocks)
    # bit-intact or padded stages silently scale activations.
    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pipeline import build_pipeline_params
    from tpu_dist_nn.testing.factories import random_model
    from tpu_dist_nn.testing.oracle import oracle_forward_batch
    from tpu_dist_nn.train.pipeline_trainer import train_pipelined
    from tpu_dist_nn.train.trainer import TrainConfig
    from tpu_dist_nn.parallel.pipeline import extract_model, pipeline_forward

    # Uneven widths force padding + (with an empty stage) identity fill.
    model = random_model([20, 12, 6, 4], seed=0)
    params = build_pipeline_params(partition_model(model, [1, 1, 0, 1]))
    mesh = build_mesh(MeshSpec(stage=4))
    data = synthetic_mnist(128, dim=20, num_classes=4, seed=1)
    cfg = TrainConfig(learning_rate=1e-3, epochs=3, batch_size=32,
                      weight_decay=0.1)
    trained, _ = train_pipelined(params, mesh, data, cfg, num_microbatches=2)

    # The pipelined forward of the trained weights must agree with the
    # oracle on the exported model — broken fillers would diverge.
    exported = extract_model(trained, model, [1, 1, 0, 1])
    x = data.x[:16]
    got = np.asarray(pipeline_forward(mesh, trained, x, num_microbatches=2))
    want = oracle_forward_batch(exported, x)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_grad_accum_matches_large_batch():
    # k micro-steps of batch B with grad_accum=k == 1 step of batch k*B
    # (grad averaging) — exact trajectory parity.
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import make_lm_train_step

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16,
    )
    params = init_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (8, 16)), jnp.int32
    )

    big = build_optimizer(1e-2)
    p_big, s_big = params, big.init(params)
    step_big = make_lm_train_step(cfg, big)
    p_big, s_big, _ = step_big(p_big, s_big, tokens)

    acc = build_optimizer(1e-2, grad_accum=2)
    p_acc, s_acc = params, acc.init(params)
    step_acc = make_lm_train_step(cfg, acc)
    for half in (tokens[:4], tokens[4:]):
        p_acc, s_acc, _ = step_acc(p_acc, s_acc, half)

    # Mean-of-half-means == full mean up to float reassociation; Adam's
    # rsqrt then amplifies that ~1e-7 grad noise to a few % of lr at
    # near-zero-gradient coordinates — compare at the lr scale.
    for orig, a, b in zip(
        jax.tree.leaves(params), jax.tree.leaves(p_big), jax.tree.leaves(p_acc)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)
        assert not np.array_equal(np.asarray(b), np.asarray(orig))


def test_grad_accum_no_update_until_k_steps():
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import make_lm_train_step

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq_len=16,
    )
    params = init_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 32, (4, 16)), jnp.int32
    )
    opt = build_optimizer(1e-2, grad_accum=3)
    step = make_lm_train_step(cfg, opt)
    p, s, _ = step(params, opt.init(params), tokens)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_grad_accum_validation():
    with pytest.raises(ValueError, match="grad_accum"):
        build_optimizer(1e-3, grad_accum=0)


def test_grad_accum_unit_conversion_and_validation():
    import warnings

    # Micro-step units convert internally: this was a crash when the
    # caller pre-scaled total but not warmup.
    opt = build_optimizer(1e-3, schedule="cosine", warmup_steps=60,
                          total_steps=200, grad_accum=4)
    assert opt is not None
    with pytest.raises(ValueError, match="no optimizer update"):
        build_optimizer(1e-3, total_steps=2, grad_accum=4)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        build_optimizer(1e-3, total_steps=10, grad_accum=4)
    assert any("never apply" in str(x.message) for x in w)
