"""Observability subsystem: registry, exposition, /metrics + /healthz
endpoint, serving/trainer instrumentation, and the `tdn metrics` verb.

The loopback acceptance path (ISSUE 1): a served engine with the
metrics endpoint enabled must expose non-zero
``tdn_rpc_requests_total``, a populated ``tdn_batch_rows`` histogram,
and a ``/healthz`` that mirrors ``Engine.health()``. Engine-backed
variants are gated on the installed jax supporting the engine's mesh
API; fake-engine variants cover the same wiring everywhere.
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpu_dist_nn.obs import (
    REGISTRY,
    Registry,
    bridge_latency_stats,
    parse_prometheus_text,
    render,
    start_http_server,
)
from tpu_dist_nn.obs.registry import POW2_BUCKETS


def _engine_available() -> bool:
    """The seed's Engine/mesh layer needs jax.sharding.AxisType (and
    jax.shard_map); on older jax every Engine.up fails at import —
    those variants skip rather than re-report a known environment gap."""
    try:
        from jax.sharding import AxisType  # noqa: F401

        return True
    except ImportError:
        return False


def _get(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode()


class FakeEngine:
    """input_dim + infer + health — all serve_engine and the metrics
    wiring require (the _SlowEngine pattern from test_serving)."""

    def __init__(self, dim=8):
        self.model = dataclasses.make_dataclass("M", ["input_dim"])(dim)
        self.downed = False

    def infer(self, x):
        return np.asarray(x) * 3.0

    def health(self):
        return {"ready": not self.downed, "devices": 1, "pipelined": False}


# ---------------------------------------------------------------- registry


def test_counter_gauge_histogram_basics():
    r = Registry()
    c = r.counter("tdn_t_total", "c", labels=("method",))
    c.labels(method="A").inc()
    c.labels(method="A").inc(2)
    c.labels(method="B").inc()
    assert c.labels(method="A").value == 3
    assert c.labels(method="B").value == 1
    g = r.gauge("tdn_t_gauge", "g")
    g.set(7)
    g.inc()
    g.dec(0.5)
    assert g.labels().value == 7.5
    h = r.histogram("tdn_t_seconds", "h", buckets=(0.1, 1.0))
    for v in (0.05, 0.1, 0.5, 3.0):
        h.observe(v)
    child = h.labels()
    assert child.counts == [2, 1, 1]  # le=0.1 gets the boundary value
    assert child.value == 4 and child.sum == pytest.approx(3.65)


def test_registry_get_or_create_and_conflicts():
    r = Registry()
    a = r.counter("tdn_same_total", "x", labels=("m",))
    b = r.counter("tdn_same_total", "ignored", labels=("m",))
    assert a is b  # module-level sites converge on one family
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("tdn_same_total", "y", labels=("m",))
    with pytest.raises(ValueError, match="already registered"):
        r.counter("tdn_same_total", "z", labels=("other",))
    with pytest.raises(ValueError, match="invalid metric"):
        r.counter("bad name")
    with pytest.raises(ValueError, match="expected labels"):
        a.labels(wrong="x")
    with pytest.raises(ValueError, match="use"):
        a.inc()  # labeled family has no default child


def test_kind_misuse_is_rejected():
    r = Registry()
    c = r.counter("tdn_k_total", "c")
    with pytest.raises(ValueError, match="not valid"):
        c.observe(1.0)
    with pytest.raises(ValueError, match="not valid"):
        c.set(1.0)
    with pytest.raises(ValueError, match=">= 0"):
        c.inc(-1)
    h = r.histogram("tdn_k_seconds", "h")
    with pytest.raises(ValueError, match="not valid"):
        h.inc()
    with pytest.raises(ValueError, match="increasing"):
        r.histogram("tdn_k_bad", "h", buckets=(1.0, 1.0))


def test_latency_stats_bridge_keeps_callers_working():
    from tpu_dist_nn.utils.profiling import LatencyStats

    r = Registry()
    stats = bridge_latency_stats(LatencyStats("probe"), registry=r)
    stats.record(0.2)
    with stats.time():
        pass
    # Existing surface unchanged...
    assert len(stats) == 2 and stats.summary()["count"] == 2
    # ...and every span landed in the bridged histogram too.
    child = r.get("tdn_probe_seconds").labels()
    assert child.value == 2 and child.sum >= 0.2


# --------------------------------------------------------------- exposition


def test_render_text_format_and_round_trip():
    r = Registry()
    c = r.counter("tdn_req_total", "requests", labels=("method",))
    c.labels(method="Process").inc(5)
    h = r.histogram("tdn_rows", "rows", buckets=(1.0, 8.0))
    h.observe(1)
    h.observe(4)
    h.observe(100)
    text = render(r)
    assert "# TYPE tdn_req_total counter" in text
    assert "# HELP tdn_req_total requests" in text
    assert '# TYPE tdn_rows histogram' in text
    parsed = parse_prometheus_text(text)
    assert parsed['tdn_req_total{method="Process"}'] == 5
    assert parsed['tdn_rows_bucket{le="1"}'] == 1
    assert parsed['tdn_rows_bucket{le="8"}'] == 2
    assert parsed['tdn_rows_bucket{le="+Inf"}'] == 3
    assert parsed["tdn_rows_count"] == 3
    assert parsed["tdn_rows_sum"] == 105
    assert parsed["__type__:tdn_rows"] == "histogram"


def test_render_survives_non_finite_values():
    # A diverged-loss NaN gauge must not make the whole endpoint
    # unscrapable: the text format has NaN/+Inf literals.
    r = Registry()
    g = r.gauge("tdn_nan_gauge", "g", labels=("k",))
    g.labels(k="nan").set(float("nan"))
    g.labels(k="inf").set(float("inf"))
    g.labels(k="ninf").set(float("-inf"))
    text = render(r)
    assert 'tdn_nan_gauge{k="nan"} NaN' in text
    assert 'tdn_nan_gauge{k="inf"} +Inf' in text
    assert 'tdn_nan_gauge{k="ninf"} -Inf' in text


def test_histogram_bucket_conflict_is_rejected():
    r = Registry()
    r.histogram("tdn_b_seconds", "h", buckets=(1.0, 2.0))
    with pytest.raises(ValueError, match="buckets"):
        r.histogram("tdn_b_seconds", "h", buckets=(1.0, 5.0))
    # Re-registration without explicit buckets keeps the first schema.
    again = r.histogram("tdn_b_seconds", "h")
    assert again.buckets == (1.0, 2.0)


def test_unlabeled_families_render_at_zero_before_first_event():
    # An error counter must exist at 0 from registration: a series
    # born at its first increment is invisible to rate()/increase()
    # alerting for exactly the event that mattered.
    r = Registry()
    r.counter("tdn_zero_errors_total", "errors")
    r.histogram("tdn_zero_seconds", "spans", buckets=(1.0,))
    parsed = parse_prometheus_text(render(r))
    assert parsed["tdn_zero_errors_total"] == 0
    assert parsed["tdn_zero_seconds_count"] == 0
    # Labeled families stay lazy (open-ended label space).
    r2 = Registry()
    r2.counter("tdn_lazy_total", "c", labels=("m",))
    assert "tdn_lazy_total" not in render(r2)


def test_render_escapes_label_values():
    r = Registry()
    c = r.counter("tdn_esc_total", "e", labels=("path",))
    c.labels(path='a"b\\c\nd').inc()
    text = render(r)
    assert r'a\"b\\c\nd' in text


def test_http_endpoint_metrics_healthz_404():
    r = Registry()
    r.counter("tdn_http_total", "c").inc()
    health = {"ready": True, "devices": 8}
    server = start_http_server(
        0, host="127.0.0.1", registry=r, health_fn=lambda: dict(health)
    )
    try:
        body = _get(f"http://127.0.0.1:{server.port}/metrics")
        assert "tdn_http_total 1" in body
        hz = json.loads(_get(f"http://127.0.0.1:{server.port}/healthz"))
        assert hz == health
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"http://127.0.0.1:{server.port}/nope")
        assert e.value.code == 404
        # Not ready -> 503 with the health body (load balancers gate on
        # the status, humans read the JSON).
        health["ready"] = False
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"http://127.0.0.1:{server.port}/healthz")
        assert e.value.code == 503
        assert json.loads(e.value.read().decode())["ready"] is False
    finally:
        server.close()


def test_http_endpoint_default_health_is_liveness():
    server = start_http_server(0, host="127.0.0.1", registry=Registry())
    try:
        hz = json.loads(_get(f"http://127.0.0.1:{server.port}/healthz"))
        assert hz == {"ready": True}
    finally:
        server.close()


# ------------------------------------------------- serving instrumentation


def test_loopback_serving_metrics_and_healthz():
    """The ISSUE 1 acceptance path on the always-available engine fake:
    RPCs through the coalescing server populate the request counter and
    the rows histogram; /healthz mirrors engine.health()."""
    from tpu_dist_nn.serving import GrpcClient, serve_engine

    engine = FakeEngine(dim=8)
    server, port = serve_engine(engine, 0, host="127.0.0.1", coalesce=True)
    metrics = start_http_server(0, host="127.0.0.1", health_fn=engine.health)
    before = parse_prometheus_text(
        _get(f"http://127.0.0.1:{metrics.port}/metrics")
    )
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        for i in range(3):
            out = client.process(np.full((2, 8), float(i)))
            assert out.shape == (2, 8)
        client.close()
        after = parse_prometheus_text(
            _get(f"http://127.0.0.1:{metrics.port}/metrics")
        )

        def delta(key):
            return after.get(key, 0) - before.get(key, 0)

        assert delta('tdn_rpc_requests_total{method="Process"}') >= 3
        assert delta('tdn_batcher_submits_total{method="Process"}') >= 3
        assert delta('tdn_batch_rows_count{method="Process"}') >= 1
        assert delta('tdn_batch_rows_sum{method="Process"}') >= 6
        assert delta('tdn_batch_wait_seconds_count{method="Process"}') >= 3
        assert delta('tdn_batch_launches_total{method="Process"}') >= 1
        # Histogram buckets exist on the pow2 grid.
        assert after["__type__:tdn_batch_rows"] == "histogram"
        hz = json.loads(_get(f"http://127.0.0.1:{metrics.port}/healthz"))
        assert hz == engine.health() and hz["ready"] is True
        # Teardown flips /healthz to 503 — the same object the load
        # balancer would drain on.
        engine.downed = True
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(f"http://127.0.0.1:{metrics.port}/healthz")
        assert e.value.code == 503
    finally:
        server.stop(0)
        metrics.close()


def test_rpc_error_counter_on_invalid_argument():
    import grpc

    from tpu_dist_nn.serving import GrpcClient, serve_engine

    engine = FakeEngine(dim=8)
    server, port = serve_engine(engine, 0, host="127.0.0.1", coalesce=True)
    key = 'tdn_rpc_errors_total{method="Process",code="INVALID_ARGUMENT"}'
    before = parse_prometheus_text(render(REGISTRY)).get(key, 0)
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        with pytest.raises(grpc.RpcError) as e:
            client.process(np.zeros((1, 5)))  # engine wants 8 features
        assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        client.close()
        after = parse_prometheus_text(render(REGISTRY)).get(key, 0)
        assert after == before + 1
    finally:
        server.stop(0)


def test_runtime_sampler_gauges():
    from tpu_dist_nn.obs import RuntimeSampler
    from tpu_dist_nn.serving.server import _Batcher

    class Eng:
        def infer(self, x):
            return np.asarray(x)

    r = Registry()
    batcher = _Batcher(Eng(), method="Process")
    try:
        sampler = RuntimeSampler(interval=30.0, registry=r)
        sampler.add_batcher(batcher, method="Process")
        batcher.submit(np.zeros((3, 4)))
        sampler.sample_once()
        text = parse_prometheus_text(render(r))
        assert text['tdn_batcher_queue_depth{method="Process"}'] == 0
        assert text['tdn_batcher_coalesce_ratio{method="Process"}'] >= 1.0
        assert text["tdn_host_rss_bytes"] > 0
        # start() publishes immediately; stop() joins the thread.
        sampler.start()
        sampler.stop()
    finally:
        batcher.close()


def test_sampler_survives_broken_source():
    from tpu_dist_nn.obs import RuntimeSampler

    class Broken:
        @property
        def _pending(self):
            raise RuntimeError("boom")

        requests_total = 0
        batches_total = 0

    r = Registry()
    sampler = RuntimeSampler(interval=30.0, registry=r)
    sampler.add_batcher(Broken())
    with pytest.raises(RuntimeError):
        sampler.sample_once()  # direct call propagates (test visibility)
    sampler.start()  # the thread wrapper must swallow and keep serving
    time.sleep(0.05)
    sampler.stop()


# -------------------------------------------------------- engine + trainers


@pytest.mark.skipif(not _engine_available(),
                    reason="installed jax lacks the engine's mesh API")
def test_engine_infer_metrics_and_compile_cache(tmp_path):
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.testing.factories import random_model

    path = tmp_path / "model.json"
    save_model(random_model([6, 5, 4], seed=0), path)
    engine = Engine.up(str(path))
    snap = parse_prometheus_text(render(REGISTRY))
    engine.infer(np.zeros((3, 6)))
    engine.infer(np.zeros((3, 6)))  # same shape: compile-cache hit
    after = parse_prometheus_text(render(REGISTRY))
    assert (
        after["tdn_engine_infer_seconds_count"]
        - snap.get("tdn_engine_infer_seconds_count", 0)
    ) == 2
    assert (
        after["tdn_engine_infer_rows_total"]
        - snap.get("tdn_engine_infer_rows_total", 0)
    ) == 6
    assert (
        after["tdn_engine_compile_cache_hits_total"]
        - snap.get("tdn_engine_compile_cache_hits_total", 0)
    ) >= 1
    engine.down()


def test_lm_trainer_publishes_step_metrics():
    import jax

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq_len=16,
    )
    params = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, 32, (2, 9)) for _ in range(4)]
    snap = parse_prometheus_text(render(REGISTRY))
    _, history = train_lm(
        params, cfg, iter(batches),
        LMTrainConfig(steps=4, batch_size=2, seq_len=8, log_every=2),
    )
    assert history  # sanity: the loop logged
    after = parse_prometheus_text(render(REGISTRY))
    key = 'tdn_train_steps_total{trainer="lm"}'
    assert after[key] - snap.get(key, 0) == 4
    tkey = 'tdn_train_tokens_total{trainer="lm"}'
    assert after[tkey] - snap.get(tkey, 0) == 4 * 2 * 8
    assert 'tdn_train_loss{trainer="lm"}' in after
    assert after['__type__:tdn_train_step_seconds'] == "histogram"


def test_lm_trainer_rejects_misaligned_checkpoint_every(tmp_path):
    # ADVICE r5: with steps_per_call=K>1 a checkpoint cadence off the
    # group grid was silently thinned to group boundaries — now it is
    # rejected up front, mirroring the log_every contract.
    import jax

    from tpu_dist_nn.checkpoint import CheckpointManager
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq_len=16,
    )
    params = init_transformer(jax.random.key(0), cfg)
    rng = np.random.default_rng(0)
    batches = [rng.integers(0, 32, (2, 9)) for _ in range(4)]
    with pytest.raises(ValueError, match="checkpoint_every"):
        train_lm(
            params, cfg, iter(batches),
            LMTrainConfig(steps=4, batch_size=2, seq_len=8, log_every=2,
                          steps_per_call=2),
            checkpoints=CheckpointManager(tmp_path / "ck"),
            checkpoint_every=3,
        )


# ------------------------------------------------------------- CLI surface


def test_cli_metrics_scrape_pretty_and_raw(capsys):
    from tpu_dist_nn.cli import main as cli_main

    r = REGISTRY
    r.counter("tdn_cli_demo_total", "demo").inc(4)
    server = start_http_server(0, host="127.0.0.1",
                               health_fn=lambda: {"ready": True})
    try:
        rc = cli_main(["metrics", "--target", f"127.0.0.1:{server.port}"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "[counter] tdn_cli_demo_total = 4" in out
        assert "healthz" in out and '"ready": true' in out
        rc = cli_main([
            "metrics", "--target", f"127.0.0.1:{server.port}", "--raw",
        ])
        out = capsys.readouterr().out
        assert rc == 0 and "# TYPE tdn_cli_demo_total counter" in out
    finally:
        server.close()


def test_cli_error_path_frees_metrics_port(capsys):
    # A command that fails AFTER --metrics-port bound (here: train_lm's
    # log_every % steps_per_call validation) must not leak the bound
    # port — main()'s drain closes it, so an immediate rerun can bind.
    import socket

    from tpu_dist_nn.cli import main as cli_main

    port = _free_port()
    args = [
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "16", "--d-model", "16", "--heads", "2",
        "--layers", "1", "--steps-per-call", "3", "--log-every", "50",
        "--metrics-port", str(port),
    ]
    assert cli_main(args) == 2
    assert "log_every" in capsys.readouterr().err
    s = socket.socket()
    try:
        s.bind(("127.0.0.1", port))  # leak would raise EADDRINUSE
    finally:
        s.close()
    # Busy port itself is a clean user error, not a traceback.
    blocker = socket.socket()
    blocker.bind(("127.0.0.1", port))
    blocker.listen(1)
    try:
        assert cli_main(args) == 2
        assert "could not bind" in capsys.readouterr().err
    finally:
        blocker.close()


def test_cli_metrics_connection_error_is_user_error(capsys):
    from tpu_dist_nn.cli import main as cli_main

    rc = cli_main(["metrics", "--target", "127.0.0.1:1", "--timeout", "0.5"])
    assert rc == 2
    assert "could not fetch" in capsys.readouterr().err


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.skipif(not _engine_available(),
                    reason="installed jax lacks the engine's mesh API")
def test_cli_up_metrics_port_end_to_end(tmp_path):
    """The full --metrics-port acceptance path: `tdn up --grpc-port
    --metrics-port` serves /metrics next to the gRPC endpoint; RPC
    traffic shows up in tdn_rpc_requests_total and tdn_batch_rows, and
    /healthz mirrors Engine.health()."""
    from tpu_dist_nn.cli import main as cli_main
    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.serving import GrpcClient
    from tpu_dist_nn.testing.factories import random_model

    path = tmp_path / "model.json"
    save_model(random_model([8, 6, 4], seed=1), path)
    gport, mport = _free_port(), _free_port()
    t = threading.Thread(
        target=cli_main,
        args=([
            "--platform", "cpu", "up", "--config", str(path),
            "--grpc-port", str(gport), "--metrics-port", str(mport),
            "--serve-warm-rows", "0", "--serve-seconds", "30",
        ],),
        daemon=True,
    )
    t.start()
    deadline = time.monotonic() + 60
    out = None
    client = GrpcClient(f"127.0.0.1:{gport}", timeout=10.0)
    while time.monotonic() < deadline:
        try:
            out = client.process(np.zeros((2, 8)))
            break
        except Exception:
            time.sleep(0.5)
    assert out is not None, "server never came up"
    client.process(np.ones((3, 8)))
    client.close()
    parsed = parse_prometheus_text(_get(f"http://127.0.0.1:{mport}/metrics"))
    assert parsed['tdn_rpc_requests_total{method="Process"}'] >= 2
    assert parsed['tdn_batch_rows_count{method="Process"}'] >= 1
    hz = json.loads(_get(f"http://127.0.0.1:{mport}/healthz"))
    assert hz["ready"] is True and "devices" in hz


def test_cli_lm_metrics_port_with_serving():
    """`tdn lm --metrics-port --serve-generate`: training counters from
    the run plus Generate-side serving counters on one endpoint."""
    from tpu_dist_nn.cli import main as cli_main
    from tpu_dist_nn.serving import GrpcClient

    gport, mport = _free_port(), _free_port()
    t = threading.Thread(
        target=cli_main,
        args=([
            "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
            "--seq-len", "24", "--d-model", "16", "--heads", "2",
            "--layers", "1", "--serve-generate", str(gport),
            "--serve-prompt-len", "8", "--serve-new-tokens", "4",
            "--temperature", "0", "--serve-seconds", "30",
            "--eval-batches", "2", "--metrics-port", str(mport),
        ],),
        daemon=True,
    )
    t.start()
    client = GrpcClient(f"127.0.0.1:{gport}", timeout=15.0)
    prompts = np.full((2, 8), 5)
    deadline = time.monotonic() + 90
    out = None
    while time.monotonic() < deadline:
        try:
            out = client.generate(prompts)
            break
        except Exception:
            time.sleep(1.0)
    client.close()
    assert out is not None, "generation endpoint never came up"
    parsed = parse_prometheus_text(_get(f"http://127.0.0.1:{mport}/metrics"))
    assert parsed['tdn_train_steps_total{trainer="lm"}'] >= 2
    assert parsed['tdn_rpc_requests_total{method="Generate"}'] >= 1
    assert parsed['tdn_batch_rows_count{method="Generate"}'] >= 1


# ------------------------------------------------------------- hot path cost


def test_instrumentation_is_cheap():
    """The acceptance bar is <1% on bench throughput; the structural
    guarantee is that one update is a dict-free float add. This guard
    only catches pathological regressions (e.g. rendering or locking
    on the update path) — 50k updates must stay well under a second
    even on a loaded 1-core runner."""
    r = Registry()
    c = r.counter("tdn_cheap_total", "c", labels=("m",))
    child = c.labels(m="x")
    h = r.histogram("tdn_cheap_rows", "h", buckets=POW2_BUCKETS)
    hchild = h.labels()
    t0 = time.monotonic()
    for _ in range(50_000):
        child.inc()
        hchild.observe(17)
    dt = time.monotonic() - t0
    assert dt < 1.0, f"50k updates took {dt:.3f}s"


# ------------------------------------------------- exposition conformance


def test_prometheus_exposition_conformance():
    """Text-format 0.0.4 conformance for the WHOLE process registry —
    the guard that keeps every newly added gauge scrape-compatible:
    HELP + TYPE lines precede every family's samples, histogram
    families expose exactly ``_bucket``/``_sum``/``_count`` with a
    cumulative le ladder whose ``+Inf`` equals ``_count``, and every
    series line matches the exposition grammar (incl. label escaping).
    """
    import re

    # Import every built-in instrumentation site so their families are
    # registered, then run one sampler pass so gauges materialize.
    import tpu_dist_nn.api.engine  # noqa: F401
    import tpu_dist_nn.serving.continuous  # noqa: F401
    import tpu_dist_nn.serving.resilience  # noqa: F401
    import tpu_dist_nn.serving.server  # noqa: F401
    import tpu_dist_nn.train.lm_trainer  # noqa: F401
    import tpu_dist_nn.train.trainer  # noqa: F401
    from tpu_dist_nn.obs.runtime import RuntimeSampler

    RuntimeSampler().sample_once()
    for m in REGISTRY.collect():
        assert m.help, f"{m.name}: every family must carry HELP text"

    # A label value exercising the escaping rules rides along.
    esc = REGISTRY.counter(
        "tdn_conformance_escape_total", "escaping probe", labels=("path",)
    )
    esc.labels(path='a"b\\c\nd').inc()

    text = render(REGISTRY)
    series_re = re.compile(
        r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\.)*")*\})?$'
    )
    seen_type: dict[str, str] = {}
    seen_help: set[str] = set()
    # histogram family -> labelset -> {"buckets": [(le, v)], suffixes}
    hists: dict[str, dict[str, dict]] = {}
    for line in text.splitlines():
        if line.startswith("# HELP "):
            seen_help.add(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert name not in seen_type, f"duplicate TYPE for {name}"
            seen_type[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment line: {line}"
        series, _, value = line.rpartition(" ")
        float(value)  # every sample value parses (incl. NaN/+Inf)
        m = series_re.match(series)
        assert m, f"series does not match the exposition grammar: {line}"
        base = m.group("name")
        family = base
        suffix = None
        for sfx in ("_bucket", "_sum", "_count"):
            stem = base[: -len(sfx)] if base.endswith(sfx) else None
            if stem and seen_type.get(stem) == "histogram":
                family, suffix = stem, sfx
                break
        assert family in seen_type, (
            f"sample before (or without) its TYPE line: {line}"
        )
        assert family in seen_help, (
            f"sample before (or without) its HELP line: {line}"
        )
        kind = seen_type[family]
        if kind == "histogram":
            assert suffix is not None, (
                f"histogram family {family} exposed a bare series: {line}"
            )
            labels = series[len(base):]
            pairs = re.findall(
                r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"', labels
            )
            key = tuple(sorted((k, v) for k, v in pairs if k != "le"))
            st = hists.setdefault(family, {}).setdefault(
                key, {"buckets": [], "sum": None, "count": None}
            )
            if suffix == "_bucket":
                le = re.search(r'le="([^"]*)"', labels)
                assert le, f"_bucket series without le label: {line}"
                st["buckets"].append((le.group(1), float(value)))
            elif suffix == "_sum":
                st["sum"] = float(value)
            else:
                st["count"] = float(value)
        else:
            assert suffix is None
            if kind == "counter":
                assert base.endswith("_total") or base.endswith("_info"), (
                    f"counter {base} should end in _total"
                )
    # Histogram ladders: cumulative, +Inf present and equal to _count.
    assert hists, "no histogram families rendered"
    for family, labelsets in hists.items():
        for key, st in labelsets.items():
            assert st["sum"] is not None, f"{family}{key}: missing _sum"
            assert st["count"] is not None, f"{family}{key}: missing _count"
            assert st["buckets"], f"{family}{key}: no buckets"
            assert st["buckets"][-1][0] == "+Inf", (
                f"{family}{key}: ladder must end at +Inf"
            )
            values = [v for _, v in st["buckets"]]
            assert values == sorted(values), (
                f"{family}{key}: bucket counts must be cumulative"
            )
            assert values[-1] == st["count"], (
                f"{family}{key}: +Inf bucket must equal _count"
            )
    # The new ISSUE-6 gauge family is registered and conformant.
    assert seen_type.get("tdn_int8_speedup_ratio") == "gauge"
