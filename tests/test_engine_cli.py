"""Engine + CLI surface tests (the reference's L3/L4 behaviors)."""

import json

import numpy as np
import pytest

from tpu_dist_nn.api.engine import Engine
from tpu_dist_nn.cli import main as cli_main
from tpu_dist_nn.core.schema import load_model, save_examples, save_model
from tpu_dist_nn.data.datasets import synthetic_mnist
from tpu_dist_nn.testing.factories import random_inputs, random_model
from tpu_dist_nn.testing.oracle import oracle_forward_batch
from tpu_dist_nn.train.trainer import TrainConfig


@pytest.fixture
def model_file(tmp_path):
    model = random_model([12, 10, 8, 4], seed=0)
    p = tmp_path / "model.json"
    save_model(model, p)
    return p


@pytest.fixture
def inputs_file(tmp_path):
    x = random_inputs(20, 12, seed=1)
    y = np.random.default_rng(2).integers(0, 4, 20)
    p = tmp_path / "inputs.json"
    save_examples(x, y, p)
    return p


def test_engine_up_single_chip(model_file):
    engine = Engine.up(model_file)
    assert engine.setup_seconds is not None
    place = engine.placement()
    assert place["num_stages"] == 1 and not place["pipelined"]
    out = engine.infer(random_inputs(5, 12))
    assert out.shape == (5, 4)
    np.testing.assert_allclose(out.sum(-1), np.ones(5), rtol=1e-5)


def test_engine_up_pipelined_matches_oracle(model_file):
    engine = Engine.up(model_file, [1, 1, 1], num_microbatches=2)
    assert engine.placement()["pipelined"]
    x = random_inputs(9, 12, seed=3)
    got = engine.infer(x)
    want = oracle_forward_batch(load_model(model_file), x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_engine_data_parallel_single_stage(model_file):
    # Pure DP: batch sharded over 4 devices, params replicated.
    engine = Engine.up(model_file, [3], data_parallel=4)
    assert engine.data_sharded and not engine.pipelined
    x = random_inputs(10, 12, seed=7)  # not divisible by 4 -> padded
    got = engine.infer(x)
    want = oracle_forward_batch(load_model(model_file), x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_engine_collapses_when_too_many_stages(model_file):
    # 99 stages can't fit 8 devices -> single-chip executor.
    model = load_model(model_file)
    engine = Engine.up(model, [1, 1, 1], data_parallel=99)
    assert not engine.pipelined
    assert engine.placement()["num_stages"] == 1


def test_engine_distribution_from_metadata(model_file, tmp_path):
    model = load_model(model_file)
    model.metadata["layer_distribution"] = [1, 2]
    p = tmp_path / "with_dist.json"
    save_model(model, p)
    engine = Engine.up(p)
    assert engine.distribution == [1, 2]
    assert engine.placement()["num_stages"] == 2


def test_engine_invalid_distribution(model_file):
    with pytest.raises(ValueError):
        Engine.up(model_file, [1, 1])


def test_engine_run_inference_chunked(model_file, inputs_file):
    from tpu_dist_nn.core.schema import load_examples

    engine = Engine.up(model_file)
    x, y = load_examples(inputs_file)
    result = engine.run_inference(x, labels=y, batch_size=8)
    assert result.outputs.shape == (20, 4)
    assert len(result.batch_seconds) == 3  # ceil(20/8)
    assert result.metrics is not None and 0 <= result.metrics["accuracy"] <= 1


def test_engine_train_and_export_round_trip(tmp_path):
    data = synthetic_mnist(400, num_classes=4, dim=16, noise=0.25, seed=0)
    train, test = data.split(0.8, seed=1)
    model = random_model([16, 12, 4], seed=4, scale=1.0)
    engine = Engine.up(model, [1, 1], num_microbatches=2)
    history = engine.train(train, TrainConfig(epochs=30, batch_size=32), eval_data=test)
    assert history[-1]["loss"] < history[0]["loss"]
    out_path = tmp_path / "trained.json"
    engine.export(out_path, metrics=history[-1]["eval"])
    reloaded = load_model(out_path)
    assert reloaded.metadata["layer_distribution"] == [1, 1]
    assert "inference_metrics" in reloaded.metadata
    # Reloaded weights reproduce the engine's own outputs.
    x = test.x[:6]
    np.testing.assert_allclose(
        engine.infer(x), oracle_forward_batch(reloaded, x), rtol=1e-4, atol=1e-5
    )


def test_cli_infer_single_and_batch(model_file, inputs_file, capsys):
    rc = cli_main(["infer", "2", "--config", str(model_file), "--inputs", str(inputs_file)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Inference time" in out and "Output:" in out

    rc = cli_main([
        "infer", "--config", str(model_file), "--inputs", str(inputs_file),
        "--batch-size", "8", "--port", "5101", "--timeout", "10",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Total inference time" in out and "Correct predictions" in out


def test_cli_up_smoke(model_file, inputs_file, capsys):
    rc = cli_main(["up", "--config", str(model_file), "--inputs", str(inputs_file),
                   "--distribution", "1,1,1"])
    assert rc == 0
    lines = [json.loads(l) for l in capsys.readouterr().out.strip().splitlines()]
    assert lines[0]["ready"] is True and lines[0]["placement"]["num_stages"] == 3
    assert len(lines[1]["smoke_inference"]) == 4


def test_cli_train_synthetic(tmp_path, capsys):
    out_file = tmp_path / "m.json"
    rc = cli_main([
        "train", "--layers", "16,8,4", "--data", "synthetic",
        "--num-examples", "300", "--epochs", "2", "--batch-size", "32",
        "--out", str(out_file),
    ])
    assert rc == 0
    trained = load_model(out_file)
    assert trained.layer_sizes == [16, 8, 4]
    assert "inference_metrics" in trained.metadata


def test_cli_oracle(model_file, inputs_file, capsys):
    rc = cli_main(["oracle", "--config", str(model_file), "--inputs", str(inputs_file)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Average inference time" in out


def test_cli_lm_trains_and_reports_metrics(capsys):
    # Tiny-transformer LM verb: single-chip and pipelined, metrics JSON
    # on stdout (BASELINE configs[4] driver surface).
    rc = cli_main([
        "lm", "--d-model", "16", "--heads", "2", "--layers", "2",
        "--seq-len", "16", "--steps", "4", "--batch-size", "4",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    metrics = json.loads(out)
    assert metrics["perplexity"] > 1
    assert 0 < metrics["bits_per_byte"] < 10

    rc = cli_main([
        "lm", "--d-model", "16", "--heads", "2", "--layers", "2",
        "--seq-len", "16", "--steps", "2", "--batch-size", "4",
        "--stages", "2", "--microbatches", "2",
    ])
    assert rc == 0
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert metrics["perplexity"] > 1


def test_engine_step_latency_probe(model_file):
    # The BASELINE "p50 per-stage pipeline step latency" metric.
    engine = Engine.up(model_file, [1, 1, 1])
    summary = engine.step_latency(batch_size=16, iters=5)
    assert summary["count"] == 5
    assert summary["num_stages"] == 3
    assert summary["p50_per_stage_s"] == pytest.approx(
        summary["p50_s"] / 3
    )
    engine.down()


def test_cli_lm_moe_single_and_expert_parallel(capsys):
    rc = cli_main([
        "lm", "--d-model", "16", "--heads", "2", "--layers", "1",
        "--seq-len", "16", "--steps", "3", "--batch-size", "4",
        "--experts", "2",
    ])
    assert rc == 0
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert metrics["perplexity"] > 1

    rc = cli_main([
        "lm", "--d-model", "16", "--heads", "2", "--layers", "1",
        "--seq-len", "16", "--steps", "3", "--batch-size", "4",
        "--experts", "2", "--expert-parallel", "2",
    ])
    assert rc == 0
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert metrics["perplexity"] > 1


def test_cli_lm_moe_stages_rejects_seq_parallel():
    # MoE x PP is implemented (round 4 — tests/test_pipeline_ep.py
    # covers the combination end to end); the remaining rejection is
    # MoE with --seq-parallel, stages or not.
    rc = cli_main([
        "lm", "--experts", "2", "--stages", "2", "--seq-parallel", "2",
        "--steps", "1",
    ])
    assert rc != 0
    # An indivisible layer count must fail fast, before any training.
    rc = cli_main([
        "lm", "--experts", "2", "--stages", "3", "--layers", "4",
        "--steps", "1",
    ])
    assert rc != 0


def test_cli_lm_moe_data_parallel_without_ep(capsys):
    # --experts with --data-parallel alone shards the batch over the
    # data axis (expert axis = 1) instead of silently running single-chip.
    rc = cli_main([
        "lm", "--d-model", "16", "--heads", "2", "--layers", "1",
        "--seq-len", "16", "--steps", "2", "--batch-size", "4",
        "--experts", "2", "--data-parallel", "2",
    ])
    assert rc == 0
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert metrics["perplexity"] > 1


def test_cli_lm_sample_bytes(capsys):
    rc = cli_main([
        "lm", "--d-model", "16", "--heads", "2", "--layers", "1",
        "--seq-len", "32", "--steps", "2", "--batch-size", "4",
        "--sample-bytes", "8", "--temperature", "0",
    ])
    assert rc == 0
    metrics = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # 8 bytes decode to at most 8 chars (multi-byte UTF-8 collapses).
    assert isinstance(metrics["sample"], str) and 0 < len(metrics["sample"]) <= 8


def test_serve_loop_tears_down(model_file):
    # The orchestrator supervisor-loop parity (run_grpc_fcnn.py:326-344):
    # bounded run for the test, then a clean, idempotent teardown.
    from tpu_dist_nn.cli import _serve_loop
    from tpu_dist_nn.utils.errors import UnavailableError

    engine = Engine.up(model_file)
    _serve_loop(engine, max_seconds=0.3)
    with pytest.raises(UnavailableError):
        engine.infer(np.zeros((1, 12)))


def test_engine_idempotent_relaunch(model_file):
    # The reference's clean-teardown / stateless-relaunch contract
    # (run_grpc_fcnn.py:329-344 + stale-resource sweep on next launch):
    # down() then up() from the same JSON reproduces identical outputs,
    # and down() twice is harmless.
    x = random_inputs(6, 12, seed=5)
    e1 = Engine.up(model_file, [1, 1, 1])
    first = e1.run_inference(x).outputs
    e1.down()
    e1.down()  # idempotent
    assert not e1.health()["ready"]
    from tpu_dist_nn.utils.errors import UnavailableError

    with pytest.raises(UnavailableError):
        e1.run_inference(x)
    e2 = Engine.up(model_file, [1, 1, 1])
    second = e2.run_inference(x).outputs
    np.testing.assert_allclose(
        np.asarray(first), np.asarray(second), rtol=1e-6
    )
    e2.down()


def test_cli_lm_seq_parallel(capsys):
    # Ring-attention training from the CLI: seq axis 2 x data 4.
    rc = cli_main([
        "lm", "--d-model", "16", "--heads", "2", "--layers", "1",
        "--seq-len", "15", "--steps", "3", "--batch-size", "8",
        "--seq-parallel", "2", "--data-parallel", "4",
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["final_train_loss"] > 0


def test_cli_lm_seq_parallel_rejections(capsys):
    # MoE x SP is supported flat AND three-axis on gpipe since round 5
    # (test_expert_parallel.py); the remaining rejection is the
    # SCHEDULED three-axis product, named explicitly.
    assert cli_main([
        "lm", "--experts", "2", "--seq-parallel", "2", "--stages", "2",
        "--schedule", "1f1b",
    ]) == 2
    assert "gpipe" in capsys.readouterr().err
    assert cli_main([
        "lm", "--seq-parallel", "2", "--seq-len", "16", "--steps", "1",
    ]) == 2
    assert "divisible" in capsys.readouterr().err


def test_cli_metrics_out(tmp_path, capsys):
    out = tmp_path / "metrics.jsonl"
    rc = cli_main([
        "train", "--layers", "12,8,4", "--num-examples", "200",
        "--epochs", "2", "--batch-size", "32",
        "--metrics-out", str(out),
    ])
    assert rc == 0
    records = [json.loads(l) for l in out.read_text().splitlines()]
    assert records[0] == {"run": "begin"}  # per-invocation marker
    epochs = records[1:]
    assert len(epochs) == 2
    assert {"epoch", "loss", "seconds"} <= set(epochs[0])


def test_cli_train_conv_config_pipelined(tmp_path, capsys):
    # A conv+MLP model JSON through `tdn train --config` with a hetero
    # placement: trains, exports, and the export re-serves.
    import jax as _jax

    from tpu_dist_nn.core.schema import save_model
    from tpu_dist_nn.models.network import init_conv_mlp

    model = init_conv_mlp(
        _jax.random.key(0), in_shape=(6, 6, 1), conv_filters=(3,),
        hidden=(8,), num_classes=3,
    )
    mp = tmp_path / "conv.json"
    save_model(model, mp)
    out = tmp_path / "trained.json"
    rc = cli_main([
        "train", "--config", str(mp), "--num-examples", "200",
        "--epochs", "2", "--batch-size", "32",
        "--distribution", "2,1,1", "--out", str(out),
    ])
    assert rc == 0
    assert out.exists()
    from tpu_dist_nn.core.schema import load_model

    trained = load_model(out)
    assert [type(l).__name__ for l in trained.layers] == \
        [type(l).__name__ for l in model.layers]
    # The export actually re-serves: infer on it end-to-end.
    from tpu_dist_nn.core.schema import save_examples

    xp = tmp_path / "ex.json"
    save_examples(
        np.random.default_rng(0).uniform(0, 1, (4, model.input_dim)),
        np.array([0, 1, 2, 0]), xp,
    )
    rc = cli_main(["infer", "--config", str(out), "--inputs", str(xp)])
    assert rc == 0
    assert "Total inference time" in capsys.readouterr().out


def test_cli_doctor(capsys):
    rc = cli_main(["doctor"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["healthy"] and report["oracle_parity"]
    assert len(report["devices"]) == 8
