"""Profiling/tracing subsystem tests (SURVEY.md §5: the reference has
ad-hoc monotonic timers only; the build adds device traces + percentile
counters)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_dist_nn.utils.profiling import (
    LatencyStats,
    annotate,
    capture_trace,
    host_span,
    timed,
)


def test_latency_stats_summary():
    stats = LatencyStats("step")
    for s in [0.1, 0.2, 0.3, 0.4]:
        stats.record(s)
    out = stats.summary()
    assert out["count"] == 4
    np.testing.assert_allclose(out["total_s"], 1.0)
    np.testing.assert_allclose(out["p50_s"], 0.25)
    np.testing.assert_allclose(out["mean_s"], 0.25)
    assert out["min_s"] == 0.1 and out["max_s"] == 0.4
    np.testing.assert_allclose(stats.percentile(50), 0.25)


def test_latency_stats_empty_and_timer():
    stats = LatencyStats("empty")
    assert stats.summary() == {"name": "empty", "count": 0}
    with pytest.raises(ValueError):
        stats.percentile(50)
    with stats.time():
        pass
    assert len(stats) == 1 and stats.samples_s[0] >= 0


def test_latency_stats_window_bounds_growth():
    # The long-lived-serving satellite (ISSUE 3): a window cap turns
    # the sample list into a sliding window — unbounded record()
    # traffic retains at most `window` samples, percentiles cover the
    # most recent ones, and summary() says so.
    stats = LatencyStats("serve", window=4)
    for s in [9.0, 9.0, 9.0, 0.1, 0.2, 0.3, 0.4]:
        stats.record(s)
    assert len(stats) == 4
    assert list(stats.samples_s) == [0.1, 0.2, 0.3, 0.4]
    out = stats.summary()
    assert out["window"] == 4 and out["count"] == 4
    np.testing.assert_allclose(out["p50_s"], 0.25)  # the 9s are gone
    np.testing.assert_allclose(out["total_s"], 1.0)
    np.testing.assert_allclose(stats.percentile(50), 0.25)
    # with-timer and empty-summary behavior carry the cap too.
    empty = LatencyStats("e", window=2)
    assert empty.summary() == {"name": "e", "count": 0, "window": 2}
    with empty.time():
        pass
    assert len(empty) == 1
    # Seed samples beyond the window truncate to the newest, like any
    # other overflow.
    seeded = LatencyStats("s", [1.0, 2.0, 3.0], 2)
    assert list(seeded.samples_s) == [2.0, 3.0]
    with pytest.raises(ValueError):
        LatencyStats("bad", window=0)


def test_latency_stats_uncapped_behavior_unchanged():
    stats = LatencyStats("default")
    for s in [0.1, 0.2]:
        stats.record(s)
    out = stats.summary()
    assert "window" not in out and out["count"] == 2
    assert isinstance(stats.samples_s, list)


def test_timed_span():
    with timed() as t:
        assert t["seconds"] is None
    assert t["seconds"] >= 0


def test_annotate_inside_jit():
    """annotate() must be legal inside traced code (named_scope)."""

    @jax.jit
    def f(x):
        with annotate("double"):
            return x * 2

    np.testing.assert_array_equal(np.asarray(f(jnp.arange(4))), [0, 2, 4, 6])


def test_host_span_runs():
    with host_span("client_batch"):
        pass


def test_capture_trace_writes_profile(tmp_path):
    """A trace capture around a jitted call produces profile artifacts."""
    with capture_trace(tmp_path):
        jax.block_until_ready(jax.jit(lambda x: x @ x)(jnp.eye(8)))
    produced = list(tmp_path.rglob("*"))
    assert any(p.is_file() for p in produced), "no trace files written"


def test_inference_result_latency_summary():
    from tpu_dist_nn.api.engine import InferenceResult

    r = InferenceResult(np.zeros((4, 2)), 1.0, [0.2, 0.4])
    s = r.latency_summary()
    assert s["count"] == 2
    np.testing.assert_allclose(s["p50_s"], 0.3)
