"""Degradation ladder (ISSUE 15): the shared scheduling core both
schedulers rebase on — class-priority admission, per-class shed
watermarks, burn-rate tightening, deadline-aware expiry, retry-after
backoff hints — plus decode-slot preemption in the continuous
scheduler (bit-identical resume) and the 2x-overload chaos drill.

Conventions follow test_resilience.py: no sleeps over ~0.05s on unit
paths, deterministic fake kernels for scheduling-policy tests, the
real toy transformer only where bit-parity is the claim.
"""

import threading
import time

import numpy as np
import pytest

from tpu_dist_nn.obs.registry import REGISTRY, Registry
from tpu_dist_nn.serving import (
    GrpcClient,
    RetryPolicy,
    serve_engine,
)
from tpu_dist_nn.serving.sched_core import (
    DEFAULT_CLASS_WATERMARKS,
    SLO_CLASSES,
    AdmissionGovernor,
    SchedCore,
    normalize_class,
    validate_class_watermarks,
)
from tpu_dist_nn.utils.errors import (
    DeadlineExceededError,
    ResourceExhaustedError,
    UnavailableError,
)
from tests.test_batcher_pipeline import AsyncFakeEngine


def _counter(name, **labels):
    m = REGISTRY.get(name)
    if m is None:
        return 0.0
    return m.labels(**labels).value


def _item(rows=1, cls="standard", width=4):
    return {
        "x": np.zeros((rows, width)), "done": threading.Event(),
        "out": None, "err": None, "abandoned": False,
        "t_submit": time.monotonic(), "slo_class": cls,
        "ctx": None,
    }


# --------------------------------------------------------------- classes


def test_normalize_class_degrades_unknown_to_standard():
    assert normalize_class("critical") == "critical"
    assert normalize_class(" Best_Effort ") == "best_effort"
    assert normalize_class(None) == "standard"
    assert normalize_class("platinum") == "standard"
    assert normalize_class(7) == "standard"


def test_validate_class_watermarks_contract():
    full = validate_class_watermarks({"best_effort": 0.25})
    assert full["best_effort"] == 0.25
    assert full["critical"] == DEFAULT_CLASS_WATERMARKS["critical"]
    with pytest.raises(ValueError, match="unknown SLO class"):
        validate_class_watermarks({"platinum": 0.5})
    with pytest.raises(ValueError, match=r"\[0, 1\]"):
        validate_class_watermarks({"standard": 1.5})


def test_pop_order_is_class_priority_fifo_within_class():
    core = SchedCore("Process")
    order = ["best_effort", "standard", "critical", "best_effort",
             "critical", "standard"]
    items = [_item(cls=c) for c in order]
    for it in items:
        core.admit(it, timeout=None)
    with core.cond:
        batch, rows = core.pop_group(max_rows=100)
    assert rows == 6
    got = [it["slo_class"] for it in batch]
    assert got == ["critical", "critical", "standard", "standard",
                   "best_effort", "best_effort"]
    # FIFO within class: the earlier critical pops first.
    assert batch[0] is items[2] and batch[1] is items[4]


def test_class_watermark_sheds_best_effort_first():
    core = SchedCore("Process", max_pending_rows=8,
                     class_watermarks={"best_effort": 0.5})
    core.admit(_item(rows=4, cls="standard"), None)
    # 4 pending: best_effort's watermark is 4 -> 4 + 1 > 4 sheds...
    with pytest.raises(ResourceExhaustedError, match="best_effort"):
        core.admit(_item(rows=1, cls="best_effort"), None)
    # ...while standard/critical still fit under the full watermark.
    core.admit(_item(rows=1, cls="standard"), None)
    core.admit(_item(rows=1, cls="critical"), None)
    assert core.shed_total == 1
    assert core.pending_rows == 6
    by_cls = core.pending_by_class()
    assert by_cls["standard"] == 5 and by_cls["critical"] == 1


def test_oversized_admitted_when_queue_empty_per_class():
    core = SchedCore("Process", max_pending_rows=4,
                     class_watermarks={"best_effort": 0.5})
    # The watermark bounds backlog, not request size — even for the
    # class that sheds first.
    core.admit(_item(rows=16, cls="best_effort"), None)
    assert core.pending_rows == 16


def test_shed_error_carries_retry_after_from_drain_rate():
    core = SchedCore("Generate", max_pending_rows=4)
    core.admit(_item(rows=4), None)
    # No drain observed yet: the hint pins the cap (backlog not moving).
    with pytest.raises(ResourceExhaustedError) as e:
        core.admit(_item(rows=1), None)
    assert e.value.retry_after_ms == 5000
    # 4 rows pending at ~100 rows/s drains in ~40ms.
    for _ in range(10):
        core.note_drained(10)
    hint = core.retry_after_ms()
    assert 40 <= hint <= 1000  # span is clamped to >= 0.25s
    with pytest.raises(ResourceExhaustedError) as e:
        core.admit(_item(rows=1), None)
    assert e.value.retry_after_ms == hint != 5000


def test_pressure_tightens_one_class_at_a_time():
    core = SchedCore("Process")  # NO max_pending_rows: unbounded queue
    core.admit(_item(cls="standard"), None)
    core.admit(_item(cls="best_effort"), None)  # level 0: admitted
    core.pressure = 1
    with pytest.raises(ResourceExhaustedError):
        core.admit(_item(cls="best_effort"), None)
    core.admit(_item(cls="standard"), None)  # level 1 spares standard
    core.pressure = 2
    with pytest.raises(ResourceExhaustedError):
        core.admit(_item(cls="standard"), None)
    core.admit(_item(cls="critical"), None)  # critical never tightens
    assert core.shed_total == 2


def test_pressure_sheds_even_against_an_empty_queue():
    # The empty-queue exemption belongs to the ROW watermark only: a
    # tightened class sheds unconditionally, else the dispatch loop
    # draining the whole queue per pop would re-admit most best_effort
    # traffic between launches while the SLO burns.
    core = SchedCore("Process", max_pending_rows=8)
    core.pressure = 1
    assert not core.has_pending()
    with pytest.raises(ResourceExhaustedError):
        core.admit(_item(cls="best_effort"), None)
    core.admit(_item(cls="standard"), None)  # the watermark path keeps
    #                                          its empty-queue edge


def test_governor_hysteresis_raises_and_lowers_one_class_at_a_time():
    class FakeTracker:
        def __init__(self):
            self.burning = False

        def status(self):
            return {"objectives": [{"burning": self.burning}]}

    tracker = FakeTracker()
    core = SchedCore("Process")
    gov = AdmissionGovernor(tracker, [core], raise_after=2, lower_after=3)
    assert gov.tick() == 0
    tracker.burning = True
    assert gov.tick() == 0       # one breaching tick is not a trend
    assert gov.tick() == 1       # raise_after=2 -> tighten best_effort
    assert core.pressure == 1
    assert gov.tick() == 0 or True  # streak reset; keep ticking
    gov.tick()
    assert gov.level == 2        # two more breaching ticks -> standard
    gov.tick()
    assert gov.level == 2        # max_level caps at 2 (critical never)
    tracker.burning = False
    for _ in range(3):
        gov.tick()
    assert gov.level == 1        # lower_after=3 calm ticks -> one step
    for _ in range(3):
        gov.tick()
    assert gov.level == 0 and core.pressure == 0


def test_sampler_ticks_governor_and_class_pending_gauge():
    from tpu_dist_nn.obs import RuntimeSampler

    class FakeTracker:
        def status(self):
            return {"objectives": [{"burning": True}]}

    core = SchedCore("Process")
    gov = AdmissionGovernor(FakeTracker(), [core], raise_after=1)
    reg = Registry()
    sampler = RuntimeSampler(interval=30.0, registry=reg)

    class FakeBatcher:
        _pending = []
        pending_rows = 0
        inflight_rows = 0
        requests_total = 0
        batches_total = 0

        def pending_by_class(self):
            return {"critical": 2, "standard": 0, "best_effort": 5}

    sampler.add_batcher(FakeBatcher(), method="Process")
    sampler.add_admission_governor(gov)
    sampler.sample_once()
    assert core.pressure == 1
    g = reg.get("tdn_sched_class_pending_rows")
    assert g.labels(method="Process", slo_class="best_effort").value == 5.0
    assert g.labels(method="Process", slo_class="critical").value == 2.0


# ---------------------------------------------------------------- expiry


def test_expired_entry_fails_deadline_exceeded_at_pop_without_launch():
    core = SchedCore("Process", submit_timeout=30.0)
    live = _item(cls="standard")
    dead = _item(cls="best_effort")
    core.admit(live, timeout=30.0)
    core.admit(dead, timeout=0.01)  # caller budget ~gone already
    before = _counter("tdn_batcher_expired_total", method="Process",
                      slo_class="best_effort")
    time.sleep(0.03)
    with core.cond:
        batch, rows = core.pop_group(max_rows=100)
    core.drain_deferred()
    # The expired entry never joins a launch; its waiter gets the
    # deadline verdict immediately.
    assert batch == [live] and rows == 1
    assert dead["done"].is_set()
    assert isinstance(dead["err"], DeadlineExceededError)
    assert "not launched" in str(dead["err"])
    assert core.expired_total == 1
    assert core.pending_rows == 0
    assert _counter("tdn_batcher_expired_total", method="Process",
                    slo_class="best_effort") == before + 1


def test_expired_row_fails_at_bind_time_row_granular():
    core = SchedCore("Generate")
    dead = _item(rows=2, cls="standard")
    dead["next_row"] = 0
    core.admit(dead, timeout=0.01)
    time.sleep(0.03)
    with core.cond:
        assert core.pop_row() is None
    assert isinstance(dead["err"], DeadlineExceededError)
    assert core.pending_rows == 0


def test_close_sweep_fails_leftovers_unavailable_once():
    core = SchedCore("Process")
    items = [_item(cls=c) for c in ("critical", "best_effort")]
    for it in items:
        core.admit(it, None)
    core.close_begin()
    with pytest.raises(UnavailableError):
        core.admit(_item(), None)
    core.sweep_leftovers()
    for it in items:
        assert it["done"].is_set()
        assert isinstance(it["err"], UnavailableError)
    assert core.pending_rows == 0
    core.sweep_leftovers()  # idempotent on an empty queue


# ----------------------------------------------------- retry-after wire


def test_retry_policy_backoff_floor_spreads_above_hint():
    p = RetryPolicy(base_delay=0.001, max_delay=0.01, seed=3)
    draws = [p.backoff(1, floor=0.2) for _ in range(50)]
    assert all(0.2 <= d <= 0.25 for d in draws), draws[:5]
    assert len(set(draws)) > 1, "floor must keep jitter, not pin it"
    # No floor: the plain capped-jitter draw.
    assert 0.0 <= p.backoff(1) <= 0.001


def test_shed_reply_carries_retry_after_and_client_honors_floor():
    import grpc

    eng = AsyncFakeEngine(dim=8)
    eng.gate.clear()  # wedge the fetch so the queue holds
    server, port = serve_engine(
        eng, 0, host="127.0.0.1", coalesce=True, max_pending_rows=4,
        submit_timeout=10.0, pipeline_depth=1,
    )
    clients, threads = [], []
    try:
        def call(value):
            c = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                           retry=None, breaker=None)
            clients.append(c)
            return c.process(np.full((2, 8), value))

        def _bg(fn):
            out = {}

            def run():
                try:
                    out["val"] = fn()
                except Exception as e:  # noqa: BLE001 — inspected
                    out["err"] = e

            t = threading.Thread(target=run, daemon=True)
            t.start()
            return t, out

        t1, o1 = _bg(lambda: call(1.0))
        assert eng.fetch_entered.wait(5.0)
        t2, o2 = _bg(lambda: call(2.0))
        t3, o3 = _bg(lambda: call(3.0))
        deadline = time.monotonic() + 5.0
        while (server.batcher.pending_rows < 4
               and time.monotonic() < deadline):
            time.sleep(0.005)
        threads.extend([t1, t2, t3])

        # A no-retry client sees the shed WITH the backoff hint in
        # trailing metadata (parsed onto the error).
        c4 = GrpcClient(f"127.0.0.1:{port}", timeout=10.0,
                        retry=None, breaker=None)
        clients.append(c4)
        with pytest.raises(grpc.RpcError) as e:
            c4.process(np.full((2, 8), 4.0))
        assert e.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert e.value.retry_after_ms is not None
        assert e.value.retry_after_ms >= 50

        # A retrying client treats the shed as retryable and floors
        # its backoff at the hint: with the queue still wedged, both
        # retries shed too and the elapsed time proves the floor held
        # (hint is 5000ms cap here — no drain observed — so bound the
        # test by budget instead: the retry must NOT fire hot).
        sleeps = []
        policy = RetryPolicy(max_attempts=2, base_delay=0.001,
                             max_delay=0.002, seed=0,
                             sleep=lambda s: sleeps.append(s))
        c5 = GrpcClient(f"127.0.0.1:{port}", timeout=30.0,
                        retry=policy, breaker=None)
        clients.append(c5)
        with pytest.raises(grpc.RpcError) as e:
            c5.process(np.full((2, 8), 5.0))
        assert e.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert len(sleeps) == 1, "shed must be retried (once)"
        assert sleeps[0] >= 5.0, (
            "backoff must be floored at the server hint, not the "
            f"client's 2ms cap (slept {sleeps[0]})"
        )
    finally:
        eng.gate.set()
        for t in threads:
            t.join(timeout=10.0)
        server.stop(0)
        for c in clients:
            c.close()


# ------------------------------------------------- schedulers share it


def test_both_schedulers_ride_one_core_implementation():
    from tpu_dist_nn.serving.continuous import ContinuousScheduler
    from tpu_dist_nn.serving.server import _Batcher

    b = _Batcher(AsyncFakeEngine(dim=4), max_pending_rows=8)
    s = ContinuousScheduler(
        None, None, slots=1, prompt_len=4, max_new_tokens=2,
        prefill_fn=lambda *a: (np.int32(1), a[1]),
        step_fn=lambda p, c, pos, act, tok, k: (np.asarray(tok) + 1, c),
        max_pending_rows=8,
    )
    try:
        assert type(b._core) is SchedCore
        assert type(s._sched_core) is SchedCore
        # The delegated legacy surface reads through to ONE ledger.
        for sched in (b, s):
            assert sched.pending_rows == 0
            assert sched.shed_total == 0
            assert sched.requests_total == 0
            assert sched._pending == []
    finally:
        b.close()
        s.close()


# ------------------------------------------------------------ preemption


def _fake_sched(step_cost=0.0, **kw):
    from tpu_dist_nn.serving.continuous import ContinuousScheduler

    def fake_prefill(params, cache, slot, tokens, start, key):
        if step_cost:
            time.sleep(step_cost)
        return np.int32(1), cache

    def fake_step(params, cache, pos, active, tok, key):
        if step_cost:
            time.sleep(step_cost)
        return np.asarray(tok) + 1, cache

    kw.setdefault("slots", 1)
    kw.setdefault("prompt_len", 4)
    kw.setdefault("max_new_tokens", 8)
    return ContinuousScheduler(
        None, None, prefill_fn=fake_prefill, step_fn=fake_step, **kw
    )


def test_critical_preempts_lowest_class_resident_and_rebinds():
    sched = _fake_sched(step_cost=0.01, slots=1)
    outs = {}

    def submit(name, cls):
        outs[name] = sched.submit(
            np.zeros((1, 4), np.int32), slo_class=cls, timeout=30.0
        )

    try:
        t_victim = threading.Thread(
            target=submit, args=("victim", "best_effort")
        )
        t_victim.start()
        deadline = time.monotonic() + 5.0
        # Wait until the victim is mid-decode (>= 2 tokens generated).
        while time.monotonic() < deadline:
            occ = sched._occupant[0]
            if occ is not None and len(occ["tokens"]) >= 2:
                break
            time.sleep(0.001)
        t_crit = threading.Thread(target=submit, args=("crit", "critical"))
        t_crit.start()
        # The critical must evict the best_effort resident and own the
        # slot while the victim waits in the resume queue.
        deadline = time.monotonic() + 5.0
        seen_crit_resident = False
        while time.monotonic() < deadline:
            occ = sched._occupant[0]
            if (occ is not None
                    and occ["item"].get("slo_class") == "critical"):
                seen_crit_resident = True
                break
            time.sleep(0.001)
        assert seen_crit_resident, "critical never took the slot"
        assert sched.preempted_total == 1
        t_crit.join(30)
        t_victim.join(30)
        # Fake kernels are deterministic (prefill samples 1, each step
        # +1): an unpreempted run yields exactly 1..8 — the preempted
        # and replayed victim must bit-match it.
        expected = np.concatenate(
            [np.zeros(4, np.int64), np.arange(1, 9)]
        )
        np.testing.assert_array_equal(outs["victim"][0], expected)
        np.testing.assert_array_equal(outs["crit"][0], expected)
        assert _counter("tdn_gen_preemptions_total",
                        slo_class="best_effort") >= 1
    finally:
        sched.close()


def test_preempted_greedy_generate_bit_matches_unpreempted():
    """The acceptance anchor: preempt a real-model greedy decode
    mid-stream, resume it (prompt re-prefill + forced-token replay),
    and the final sequence is BIT-identical to the run that was never
    preempted."""
    import jax

    from tpu_dist_nn.models.generate import generate
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.serving.continuous import ContinuousScheduler

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=3, d_ff=64,
        max_seq_len=24,
    )
    params = init_transformer(jax.random.key(11), cfg)
    T, N = 8, 10
    rng = np.random.default_rng(5)
    victim_prompt = rng.integers(0, cfg.vocab_size, (1, T))
    crit_prompt = rng.integers(0, cfg.vocab_size, (1, T))
    oracle = np.asarray(
        generate(params, cfg, victim_prompt.astype(np.int32), N)
    )

    sched = ContinuousScheduler(
        params, cfg, slots=1, prompt_len=T, max_new_tokens=N,
    )
    outs = {}

    def submit(name, prompt, cls):
        outs[name] = sched.submit(prompt, slo_class=cls, timeout=60.0)

    try:
        tv = threading.Thread(
            target=submit, args=("victim", victim_prompt, "best_effort")
        )
        tv.start()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            occ = sched._occupant[0]
            if occ is not None and 2 <= len(occ["tokens"]) < N:
                break
            time.sleep(0.0005)
        tc = threading.Thread(
            target=submit, args=("crit", crit_prompt, "critical")
        )
        tc.start()
        tc.join(60)
        tv.join(60)
        assert sched.preempted_total >= 1, "preemption never fired"
        np.testing.assert_array_equal(
            outs["victim"][0, T:], oracle[0],
            err_msg="preempted-and-resumed greedy decode must "
                    "bit-match the unpreempted run",
        )
    finally:
        sched.close()


def test_preemption_never_evicts_critical_for_critical():
    sched = _fake_sched(step_cost=0.01, slots=1)
    outs = []

    def submit(cls):
        outs.append(
            sched.submit(np.zeros((1, 4), np.int32), slo_class=cls,
                         timeout=30.0)
        )

    try:
        t1 = threading.Thread(target=submit, args=("critical",))
        t1.start()
        deadline = time.monotonic() + 5.0
        while sched._occupant[0] is None and time.monotonic() < deadline:
            time.sleep(0.001)
        t2 = threading.Thread(target=submit, args=("critical",))
        t2.start()
        t1.join(30)
        t2.join(30)
        assert sched.preempted_total == 0
        assert len(outs) == 2
    finally:
        sched.close()


# ------------------------------------------------------- overload drill


def test_overload_drill_critical_holds_best_effort_absorbs():
    """The satellite chaos test: 2x sustained admission on the paced
    fake engine — critical completes 100%, best_effort absorbs every
    shed, and critical's p99 stays within the degradation target of
    its uncontended baseline."""
    import bench

    r = bench.slo_class_bench(seconds=0.8)
    over = r["overloaded"]
    # Every critical arrival completed (none shed, none errored).
    assert "critical" not in over["sheds"]
    assert not over["errors"]
    assert over["per_class"]["critical"]["completed"] > 0
    # best_effort absorbed >= 90% of the sheds (the acceptance bar).
    assert r["shed_total"] > 0
    assert r["best_effort_shed_share"] >= 0.9
    # Preemption actually fired under the overload.
    assert r["preempted"] > 0
    # p99 target with a noise allowance above the 1.3x acceptance bar
    # (the bench records the honest number; bench_gate holds the
    # cross-round line on slo_class_critical_p99_ms).
    assert r["critical_p99_ratio"] is not None
    assert r["critical_p99_ratio"] <= 1.35, r


# ----------------------------------------------------- router class hop


def test_router_forwards_class_and_server_labels_it():
    from tpu_dist_nn.obs.registry import REGISTRY as _REG
    from tpu_dist_nn.serving.pool import ReplicaPool
    from tpu_dist_nn.serving.router import serve_router

    eng = AsyncFakeEngine(dim=8)
    server, port = serve_engine(eng, 0, host="127.0.0.1", coalesce=True)
    pool = ReplicaPool([f"127.0.0.1:{port}"], scrape_interval=30.0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    wait = _REG.get("tdn_sched_class_wait_seconds")
    before = wait.labels(method="Process", slo_class="critical").value
    try:
        c = GrpcClient(f"127.0.0.1:{rport}", timeout=10.0,
                       retry=None, breaker=None, slo_class="critical")
        out = c.process(np.ones((2, 8)))
        np.testing.assert_array_equal(out, np.full((2, 8), 2.0))
        c.close()
        # The class label landed SERVER-side: x-tdn-class crossed the
        # router hop intact.
        after = wait.labels(method="Process", slo_class="critical").value
        assert after == before + 1
    finally:
        rsrv.stop(0)
        pool.close()
        server.stop(0)


def test_shed_retry_after_hint_crosses_the_router_hop():
    import grpc

    from tpu_dist_nn.serving.pool import ReplicaPool
    from tpu_dist_nn.serving.router import serve_router

    eng = AsyncFakeEngine(dim=8)
    eng.gate.clear()  # wedge the fetch so the replica's queue holds
    server, port = serve_engine(
        eng, 0, host="127.0.0.1", coalesce=True, max_pending_rows=4,
        submit_timeout=10.0, pipeline_depth=1,
    )
    pool = ReplicaPool([f"127.0.0.1:{port}"], scrape_interval=30.0)
    rsrv, rport = serve_router(pool, 0, host="127.0.0.1")
    clients, threads = [], []
    try:
        def call(value):
            c = GrpcClient(f"127.0.0.1:{rport}", timeout=10.0,
                           retry=None, breaker=None)
            clients.append(c)
            return c.process(np.full((2, 8), value))

        def start(value):
            t = threading.Thread(target=lambda: call(value), daemon=True)
            t.start()
            threads.append(t)

        start(1.0)
        assert eng.fetch_entered.wait(5.0)
        start(2.0)
        start(3.0)
        deadline = time.monotonic() + 5.0
        while (server.batcher.pending_rows < 4
               and time.monotonic() < deadline):
            time.sleep(0.005)
        c4 = GrpcClient(f"127.0.0.1:{rport}", timeout=10.0,
                        retry=None, breaker=None)
        clients.append(c4)
        with pytest.raises(grpc.RpcError) as e:
            c4.process(np.full((2, 8), 4.0))
        # The replica's shed verdict AND its drain-rate hint both
        # crossed the router hop.
        assert e.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert e.value.retry_after_ms is not None
        assert e.value.retry_after_ms >= 50
    finally:
        eng.gate.set()
        for t in threads:
            t.join(timeout=10.0)
        rsrv.stop(0)
        pool.close()
        server.stop(0)
        for c in clients:
            c.close()


def test_hedge_skipped_for_best_effort_class():
    from tpu_dist_nn.serving.router import HedgePolicy, Router

    calls = []

    class FakeLatency:
        def samples(self):
            class Child:
                value = 1000

                def quantile(self, q):
                    return 0.05

            return [(("Process",), Child())]

    hedge = HedgePolicy(p99_ratio=2.0, latency=FakeLatency())

    class FakeBreaker:
        state = "closed"

        def record_success(self):
            pass

        def record_failure(self):
            pass

    class FakeRep:
        target = "fake:1"
        breaker = FakeBreaker()

        def call(self, method, payload, timeout=None, metadata=None):
            calls.append(("plain", metadata))
            return b"ok"

        def call_future(self, *a, **k):
            raise AssertionError("hedged path must not fire")

    class FakePool:
        def place(self, session_key=None, exclude=None):
            return FakeRep()

        def begin(self, rep):
            pass

        def done(self, rep):
            pass

        def replicas(self):
            return []

        def pin(self, *a):
            pass

    router = Router(FakePool(), hedge=hedge)

    class Ctx:
        trace_id = "t"
        sampled = False

        @staticmethod
        def header():
            return "h"

    class Span:
        ctx = Ctx()

        @staticmethod
        def annotate(msg):
            pass

    # best_effort: the plain forward runs even though hedging applies
    # to the method and has latency history.
    reply, err, rep, hedged = router._forward(
        "Process", b"x", FakeRep(), None, [], Span(), 1, set(),
        slo_class="best_effort",
    )
    assert reply == b"ok" and not hedged
    assert calls and calls[0][0] == "plain"


# -------------------------------------------------------- goodput pads


def test_goodput_replay_and_dead_waiter_pads_conserve():
    from tpu_dist_nn.obs.goodput import GoodputTracker, LMFlopModel

    reg = Registry()
    gp = GoodputTracker(registry=reg)
    model = LMFlopModel(2, 16, 32, 64, 12)
    # Decode step with a replaying lane: useful + pads == slots * step.
    gp.record_decode_step(model, [4, 5], 1, 1, replay_slots=1)
    snap = gp.snapshot()
    sf = model.step_flops()
    assert snap["pad_reasons"]["preempt_replay"] == sf
    assert snap["flops"]["total"] == 5 * sf
    assert (snap["flops"]["useful"] + snap["flops"]["pad"]
            == snap["flops"]["total"])
    # Static generate with a dead waiter: its full ride is pad.
    reg2 = Registry()
    gp2 = GoodputTracker(registry=reg2)
    out = np.zeros((4, 12), np.int64)
    gp2.record_static_generate(model, out, 3, 4, 8, None, dead_rows=1)
    snap2 = gp2.snapshot()
    per_row = model.chunk_flops(8) + 3 * sf  # prefill + (12-8-1) steps
    assert snap2["pad_reasons"]["dead_waiter"] == per_row
    assert snap2["flops"]["total"] == 4 * per_row
    assert (snap2["flops"]["useful"] + snap2["flops"]["pad"]
            == snap2["flops"]["total"])


# ------------------------------------------------------------ gate rule


def test_bench_gate_slo_class_critical_p99_skip_and_fail(tmp_path):
    import json
    import sys

    sys.path.insert(0, "tools")
    try:
        import bench_gate
    finally:
        sys.path.pop(0)

    def round_doc(p99=None):
        doc = {"backend": "cpu", "value": 100.0}
        if p99 is not None:
            doc["serving"] = {"slo_classes": {"critical_p99_ms": p99}}
        return doc

    # Absent in the older round -> per-metric skip, not a failure.
    verdict = bench_gate.compare(round_doc(), round_doc(60.0))
    rows = {r["metric"]: r for r in verdict["metrics"]}
    assert "skipped" in rows["slo_class_critical_p99_ms"], \
        "rounds predating ISSUE 15 must skip, not fail"
    assert "slo_class_critical_p99_ms" not in verdict["regressions"]
    # Lower is better: a 50% p99 blowup is a regression...
    verdict = bench_gate.compare(round_doc(60.0), round_doc(90.0))
    assert "slo_class_critical_p99_ms" in verdict["regressions"]
    # ...and an improvement passes.
    verdict = bench_gate.compare(round_doc(60.0), round_doc(40.0))
    assert "slo_class_critical_p99_ms" not in verdict["regressions"]
