"""REAL-data end-to-end: the vendored handwritten-digit set.

Round-2 verdict: every accuracy number in the repo was synthetic (the
sandbox has no egress for MNIST). These tests close that gap with the
vendored UCI handwritten digits (tpu_dist_nn/data/digits — 1,797 real
8x8 scans by 43 writers, tools/make_digits_idx.py): train with the
native recipe, hit the BASELINE ≥97 % bar on a REAL held-out split,
export to the reference JSON schema, and serve the trained model over
the wire format — the reference's own capability chain (notebook cells
8-10 -> run_grpc_fcnn -> run_grpc_inference accuracy check,
run_grpc_inference.py:185-211) on genuine data.
"""

import numpy as np
import pytest

from tpu_dist_nn.data.datasets import real_digits


def test_real_digits_load_shapes_and_content():
    tr = real_digits("train")
    te = real_digits("test")
    assert tr.x.shape == (1438, 64) and te.x.shape == (359, 64)
    assert tr.num_classes == 10
    # Real pixel data: full intensity range after /255 normalize.
    assert tr.x.min() == 0.0 and tr.x.max() == 1.0
    # Stratified split: every class present in both splits in ~equal
    # proportion (each class is ~10% of this set).
    for split in (tr, te):
        counts = np.bincount(split.y, minlength=10)
        assert counts.min() > 0.8 * len(split) / 10

    # Not synthetic garbage: nearest-centroid on raw pixels should
    # already separate real digit scans far above chance.
    centroids = np.stack([tr.x[tr.y == c].mean(0) for c in range(10)])
    pred = np.argmin(
        ((te.x[:, None, :] - centroids[None]) ** 2).sum(-1), axis=1
    )
    assert (pred == te.y).mean() > 0.8


@pytest.fixture(scope="module")
def trained_digits_model():
    """Train the reference's torch shape at digits scale (64-128-64-10,
    generate_mnist_pytorch.py:25-27 analogue) with the native recipe."""
    import jax

    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params
    from tpu_dist_nn.train.trainer import (
        TrainConfig,
        evaluate_fcnn,
        train_fcnn,
    )

    tr, te = real_digits("train"), real_digits("test")
    params = init_fcnn(jax.random.key(0), [64, 128, 64, 10])
    params, history = train_fcnn(
        params,
        tr,
        TrainConfig(
            epochs=40, batch_size=64, lr_schedule="cosine",
            warmup_steps=50,
        ),
    )
    metrics = evaluate_fcnn(params, te)
    model = spec_from_params(
        params, ["relu", "relu", "softmax"],
        metadata={"inference_metrics": metrics},
    )
    return model, metrics, te


def test_native_training_beats_baseline_target_on_real_data(
    trained_digits_model,
):
    # BASELINE.md north star: >=97 % accuracy via the native training
    # path. The reference's own exported model recorded 0.9685 (cell 9).
    # On this REAL held-out split the native recipe reaches ~0.98.
    _, metrics, _ = trained_digits_model
    assert metrics["accuracy"] >= 0.97
    assert metrics["f1_score"] >= 0.97


def test_real_model_exports_serves_and_scores(trained_digits_model, tmp_path):
    # Export -> JSON schema -> Engine -> wire serving -> accuracy on the
    # real held-out digits matches the in-process eval exactly.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import load_model, save_model
    from tpu_dist_nn.serving import GrpcClient, serve_engine
    from tpu_dist_nn.testing.oracle import oracle_forward_batch

    model, metrics, te = trained_digits_model
    path = tmp_path / "digits_model.json"
    save_model(model, path)
    reloaded = load_model(path)
    assert reloaded.metadata["inference_metrics"]["accuracy"] == metrics["accuracy"]

    # Oracle (float64 numpy, manual_nn.py analogue) agrees with the
    # served engine on real inputs.
    engine = Engine.up(path)
    server, port = serve_engine(engine, 0)
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        out = client.process(te.x.astype(np.float64))
        want = oracle_forward_batch(reloaded, te.x)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)
        served_acc = (np.argmax(out, -1) == te.y).mean()
        assert served_acc == pytest.approx(metrics["accuracy"], abs=1e-9)
    finally:
        server.stop(0)


def test_real_digits_through_pipelined_placement(trained_digits_model, tmp_path):
    # The trained real-data model through the padded SPMD pipeline
    # (distribution [2, 1]: uneven widths + a filler slot) agrees with
    # the single-program path on every real held-out digit.
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.core.schema import save_model

    model, _, te = trained_digits_model
    path = tmp_path / "digits_model.json"
    save_model(model, path)
    ref = Engine.up(path).infer(te.x)
    got = Engine.up(path, [2, 1]).infer(te.x)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_real_text_lm_record():
    """The artifacts/real_text_r04 derivation, reduced for CI: train the
    byte-level Tiny-Transformer on the VENDORED real corpus (NOT the
    synthetic fallback — allow_synthetic=False makes this test fail
    rather than silently record synthetic numbers) and require real
    learning: held-out loss well under the ln(256)=5.55-nat random
    baseline and a falling train curve."""
    import jax
    import optax

    from tpu_dist_nn.data.text import (
        encode,
        lm_batches,
        lm_sequences,
        load_corpus,
    )
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import evaluate_lm, make_lm_train_step

    text, source = load_corpus(allow_synthetic=False)
    assert source.endswith("realtext_corpus.txt")
    assert "GNU GENERAL PUBLIC LICENSE" in text  # real bytes

    cfg = TransformerConfig(
        vocab_size=256, d_model=48, n_heads=4, n_layers=2, d_ff=96,
        max_seq_len=64,
    )
    rows = lm_sequences(encode(text), seq_len=64)
    split = int(len(rows) * 0.95)
    train_rows, eval_rows = rows[:split], rows[split:]
    params = init_transformer(jax.random.key(0), cfg)
    optimizer = optax.adam(2e-3)
    step = make_lm_train_step(cfg, optimizer)
    opt_state = optimizer.init(params)
    losses = []
    for i, batch in enumerate(lm_batches(train_rows, 16, seed=0, epochs=None)):
        if i >= 60:
            break
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0] - 0.5, (losses[0], losses[-1])
    metrics = evaluate_lm(params, cfg, eval_rows, batch_size=16)
    # Random-guess byte entropy is 5.55 nats; real learning on real
    # text must land far below it even at CI scale.
    assert metrics["loss_nats_per_token"] < 4.0, metrics
    assert metrics["perplexity"] < 55, metrics


def test_cli_train_digits_end_to_end(tmp_path):
    # `tdn train --data digits` (vendored real data) trains, evals on
    # the real held-out split, and exports — the CLI leg of the
    # real-data story. Short run: the recipe itself is asserted by
    # test_native_training_beats_baseline_target_on_real_data.
    from tpu_dist_nn.cli import main
    from tpu_dist_nn.core.schema import load_model

    out = tmp_path / "digits.json"
    rc = main([
        "train", "--data", "digits", "--epochs", "3",
        "--out", str(out),
    ])
    assert rc == 0
    model = load_model(out)
    # The untouched default --layers adapts to the 64-dim digits.
    assert model.layer_sizes == [64, 32, 16, 10]
    assert "inference_metrics" in model.metadata


def test_cli_train_digits_dim_mismatch_is_clear_error(capsys):
    from tpu_dist_nn.cli import main

    rc = main(["train", "--data", "digits", "--layers", "784,32,10",
               "--epochs", "1"])
    assert rc == 2
    err = capsys.readouterr().err
    assert "64" in err and "--layers" in err


def test_cli_platform_cpu_flag(tmp_path):
    # --platform cpu pins the host backend without a probe (and is the
    # documented escape hatch when the tunneled accelerator hangs).
    from tpu_dist_nn import cli

    rc = cli.main(["--platform", "cpu", "train", "--data", "digits",
                   "--epochs", "1", "--out", str(tmp_path / "m.json")])
    assert rc == 0


def test_realtext_corpus_supports_valid_heldout_at_scale():
    # VERDICT r4 missing item 3: the vendored corpus must sustain a
    # VALID held-out split at the scale configs (seq 1024, batch 16) —
    # enough eval rows for a full batch, and no verbatim paragraph
    # shared between the train head and the eval tail (the dedup +
    # fixed-seed document shuffle in tools/make_text_corpus.py).
    import hashlib
    import json
    import re

    from tpu_dist_nn.data.text import encode, lm_sequences, load_corpus

    text, source = load_corpus(allow_synthetic=False)
    assert source.endswith("realtext_corpus.txt")
    raw = len(text.encode())
    assert raw >= 5_000_000, f"corpus too small for scale eval: {raw}"

    # The committed manifest matches the committed corpus bytes.
    from pathlib import Path

    manifest = json.loads(
        (Path(source).parent / "realtext_manifest.json").read_text()
    )
    sha = hashlib.sha256(Path(source).read_bytes()).hexdigest()
    assert manifest["sha256"] == sha, "manifest out of date vs corpus"

    # The CLI's split (cli.py: rows[:95%], rows[95%:]) at the 85M
    # config's shape leaves >= one full eval batch.
    rows = lm_sequences(encode(text), seq_len=1024)
    split = max(1, int(len(rows) * 0.95))
    eval_rows = rows[split:]
    assert len(eval_rows) >= 16, (
        f"eval tail {len(eval_rows)} rows < batch 16 at seq 1024"
    )

    # No normalized paragraph appears in both sides of the split
    # (dedup guarantees it corpus-wide; this checks the property the
    # eval actually depends on, on the byte boundary the split uses).
    # Tokens are UTF-8 BYTES (encode()), so the boundary must slice the
    # byte stream — indexing the decoded str would shift past the end
    # and make the tail empty (vacuous check).
    boundary = split * 1025
    data = text.encode()
    assert 0 < boundary < len(data)
    head = data[:boundary].decode("utf-8", "replace")
    tail = data[boundary:].decode("utf-8", "replace")
    ws = re.compile(r"\s+")

    def para_hashes(part):
        out = set()
        for para in re.split(r"\n\s*\n", part):
            norm = ws.sub(" ", para).strip().lower()
            if len(norm) >= 80:  # short fragments can straddle chunks
                out.add(hashlib.sha1(norm.encode()).hexdigest())
        return out

    overlap = para_hashes(head) & para_hashes(tail)
    assert not overlap, f"{len(overlap)} paragraphs leak across the split"
