"""Training-path tests: single-chip recipe, pipelined backward, export."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_dist_nn.core.schema import load_model, partition_model
from tpu_dist_nn.data.datasets import synthetic_mnist
from tpu_dist_nn.models.fcnn import init_fcnn, forward_logits, params_from_spec
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.pipeline import (
    build_pipeline_params,
    extract_model,
    pipeline_forward,
)
from tpu_dist_nn.testing.factories import random_model
from tpu_dist_nn.train import (
    TrainConfig,
    cross_entropy,
    evaluate_fcnn,
    export_model,
    make_pipeline_train_step,
    prepare_pipeline_batch,
    train_fcnn,
    train_pipelined,
)
from tpu_dist_nn.train.trainer import _split_params

# Small, fast synthetic task for CPU tests.
DIM, CLASSES = 24, 4


def _data(n=600, seed=0):
    return synthetic_mnist(n, num_classes=CLASSES, dim=DIM, noise=0.25, seed=seed)


def test_single_chip_training_learns():
    data = _data()
    train, test = data.split(0.8, seed=1)
    params = init_fcnn(jax.random.key(0), [DIM, 32, CLASSES])
    params, history = train_fcnn(
        params, train, TrainConfig(epochs=25, batch_size=32), eval_data=test
    )
    assert history[-1]["loss"] < history[0]["loss"] * 0.5
    assert history[-1]["eval"]["accuracy"] > 0.9
    # Activation ids untouched by the optimizer.
    assert int(params[0]["act"]) == 1 and int(params[-1]["act"]) == 3


def test_data_parallel_training_matches_single_chip():
    """train_fcnn over a data-axis mesh == single-device training: the
    batch shards over the data axis (grads all-reduced by XLA), so the
    trajectory must match to float tolerance, not just in quality."""
    data = _data()
    cfg = TrainConfig(epochs=3, batch_size=32, seed=2)
    params = init_fcnn(jax.random.key(1), [DIM, 16, CLASSES])

    ref, ref_hist = train_fcnn(params, data, cfg)

    mesh = build_mesh(MeshSpec(data=8))
    got, hist = train_fcnn(params, data, cfg, mesh=mesh)
    np.testing.assert_allclose(
        [h["loss"] for h in hist], [h["loss"] for h in ref_hist], rtol=1e-5
    )
    for a, b in zip(got, ref):
        np.testing.assert_allclose(
            np.asarray(a["w"]), np.asarray(b["w"]), rtol=1e-4, atol=1e-6
        )


def test_engine_data_parallel_training_uses_mesh():
    from tpu_dist_nn.api.engine import Engine
    from tpu_dist_nn.models.fcnn import spec_from_params

    data = _data()
    params = init_fcnn(jax.random.key(2), [DIM, 16, CLASSES])
    model = spec_from_params(params, ["relu", "softmax"])
    eng = Engine.up(model, [2], data_parallel=4)
    assert eng.data_sharded
    history = eng.train(data, TrainConfig(epochs=4, batch_size=32))
    assert history[-1]["loss"] < history[0]["loss"]
    # Serving still works on the data-sharded placement post-train.
    out = eng.infer(data.x[:16])
    assert out.shape == (16, CLASSES)


def test_pipelined_training_matches_single_chip_gradients():
    # The pipelined backward must produce the same grads as the plain
    # forward on identical weights (SURVEY.md §7 hard part 2).
    model = random_model([12, 10, 8, 4], seed=3)
    data_x = np.random.default_rng(0).uniform(size=(16, 12)).astype(np.float32)
    data_y = np.random.default_rng(1).integers(0, 4, 16).astype(np.int32)

    # Single-chip grads.
    params = params_from_spec(model)
    wb, acts = _split_params(params)

    def loss_single(wb_):
        ps = [{"w": p["w"], "b": p["b"], "act": a} for p, a in zip(wb_, acts)]
        return cross_entropy(forward_logits(ps, jnp.asarray(data_x)), jnp.asarray(data_y))

    g_single = jax.grad(loss_single)(wb)

    # Pipelined grads via one train step with SGD lr so update = -lr*grad.
    import optax

    mesh = build_mesh(MeshSpec(stage=3))
    stages = partition_model(model, [1, 1, 1])
    pp = build_pipeline_params(stages)
    lr = 1.0
    step = make_pipeline_train_step(mesh, pp.meta, 2, optax.sgd(lr))
    xs, labels, mask = prepare_pipeline_batch(pp.meta, data_x, data_y, 2, 1)
    new_w, _, loss = step(
        pp.weights, optax.sgd(lr).init(pp.weights),
        jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(mask),
    )
    g_pipe = jax.tree.map(lambda a, b: np.asarray(a - b) / -lr, new_w, pp.weights)

    # Compare per original layer block.
    np.testing.assert_allclose(float(loss), float(loss_single(wb)), rtol=1e-5)
    for s in range(3):
        np.testing.assert_allclose(
            g_pipe.w[s, 0, : model.layers[s].in_dim, : model.layers[s].out_dim],
            np.asarray(g_single[s]["w"]),
            rtol=1e-4, atol=1e-6,
        )
        np.testing.assert_allclose(
            g_pipe.b[s, 0, : model.layers[s].out_dim],
            np.asarray(g_single[s]["b"]),
            rtol=1e-4, atol=1e-6,
        )
    # Identity filler and padding regions got exactly zero update.
    pad_delta = np.asarray(g_pipe.w)[0, 0, 12:, :]
    np.testing.assert_array_equal(pad_delta, 0)


def test_pipelined_training_learns_and_exports(tmp_path):
    data = _data(400, seed=5)
    train, test = data.split(0.8, seed=2)
    model = random_model([DIM, 16, 8, CLASSES], seed=6, scale=1.0)
    stages = partition_model(model, [1, 1, 1])
    pp = build_pipeline_params(stages)
    mesh = build_mesh(MeshSpec(stage=3, data=2))
    pp, history = train_pipelined(
        pp, mesh, train,
        TrainConfig(epochs=60, batch_size=48),
        num_microbatches=2, eval_data=test,
    )
    assert history[-1]["loss"] < history[0]["loss"]
    assert history[-1]["eval"]["accuracy"] > 0.9

    # Export the trained pipeline back to the JSON schema and verify the
    # reloaded model reproduces the pipelined outputs.
    trained = extract_model(pp, model, [1, 1, 1])
    path = tmp_path / "trained.json"
    export_model(
        params_from_spec(trained),
        [l.activation for l in trained.layers],
        path,
        metrics=history[-1]["eval"],
    )
    reloaded = load_model(path)
    assert reloaded.metadata["inference_metrics"]["accuracy"] > 0.8
    got = np.asarray(
        pipeline_forward(mesh, pp, test.x[:8], num_microbatches=2)
    )
    from tpu_dist_nn.testing.oracle import oracle_forward_batch

    want = oracle_forward_batch(reloaded, test.x[:8])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_metrics_match_sklearn():
    import pytest

    sk = pytest.importorskip("sklearn.metrics")
    f1_score, precision_score, recall_score = sk.f1_score, sk.precision_score, sk.recall_score
    from tpu_dist_nn.train.metrics import classification_metrics

    rng = np.random.default_rng(0)
    y_true = rng.integers(0, 5, 300)
    y_pred = rng.integers(0, 5, 300)
    got = classification_metrics(y_pred, y_true, 5)
    np.testing.assert_allclose(
        got["precision"], precision_score(y_true, y_pred, average="weighted"), rtol=1e-9
    )
    np.testing.assert_allclose(
        got["recall"], recall_score(y_true, y_pred, average="weighted"), rtol=1e-9
    )
    np.testing.assert_allclose(
        got["f1_score"], f1_score(y_true, y_pred, average="weighted"), rtol=1e-9
    )


def test_evaluate_fcnn_runs():
    data = _data(100, seed=9)
    params = init_fcnn(jax.random.key(1), [DIM, 8, CLASSES])
    m = evaluate_fcnn(params, data)
    assert set(m) == {"accuracy", "precision", "recall", "f1_score"}


def test_training_rejects_dataset_smaller_than_batch():
    # drop_remainder=True with no full batch used to crash with an
    # obscure "Need at least one array to stack"; now a structured
    # InvalidArgumentError fails fast (reference fail-fast contract).
    import pytest

    from tpu_dist_nn.utils.errors import InvalidArgumentError

    data = _data(n=16)
    params = init_fcnn(jax.random.key(0), [DIM, 8, CLASSES])
    with pytest.raises(InvalidArgumentError, match="no full batch"):
        train_fcnn(params, data, TrainConfig(epochs=1, batch_size=64))


# ---- LM loop device-residency (VERDICT r5: steps_per_call + donation)


def _lm_setup():
    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        init_transformer,
    )

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16,
    )
    params = init_transformer(jax.random.key(3), cfg)
    rows = np.random.default_rng(7).integers(0, 32, (64, 17)).astype(np.int32)

    def batches():
        rng = np.random.default_rng(11)
        while True:
            yield rows[rng.integers(0, len(rows), 4)]

    return cfg, params, batches


def test_steps_per_call_matches_single_step_trajectory():
    # K steps per device call is ONE lax.scan over the same step body:
    # the loss trajectory must be identical to the per-step loop —
    # including a shorter final group (steps=6, K=4 -> groups of 4+2).
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg, params, batches = _lm_setup()
    histories = []
    # log_every must land on group boundaries (the fetch-barrier
    # timing contract), so each arm uses a compatible cadence; k=4
    # with steps=6 still exercises the shorter final group (4+2).
    for k, log_every in ((1, 4), (4, 4), (2, 2)):
        tcfg = LMTrainConfig(
            steps=6, batch_size=4, seq_len=16, log_every=log_every,
            steps_per_call=k,
        )
        p, history = train_lm(params, cfg, batches(), tcfg)
        histories.append({h["step"]: h["loss"] for h in history})
    assert list(histories[0]) == list(histories[1]) == [4, 6]
    assert list(histories[2]) == [2, 4, 6]
    for s in (4, 6):
        np.testing.assert_allclose(histories[0][s], histories[1][s],
                                   rtol=1e-6)
        np.testing.assert_allclose(histories[0][s], histories[2][s],
                                   rtol=1e-6)


def test_train_lm_does_not_invalidate_caller_params():
    # The built-in steps donate their buffers; train_lm must copy the
    # incoming pytree first so the CALLER's params survive (a donated
    # buffer raises on access after the first step).
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg, params, batches = _lm_setup()
    leaf_before = np.asarray(params["tok_embed"]).copy()
    tcfg = LMTrainConfig(steps=2, batch_size=4, seq_len=16, log_every=1)
    trained, _ = train_lm(params, cfg, batches(), tcfg)
    np.testing.assert_array_equal(np.asarray(params["tok_embed"]), leaf_before)
    assert not np.array_equal(
        np.asarray(trained["tok_embed"]), leaf_before
    )


def test_steps_per_call_rejections():
    import pytest as _pytest

    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg, params, batches = _lm_setup()
    mesh = build_mesh(MeshSpec(stage=2))
    tcfg = LMTrainConfig(
        steps=2, batch_size=4, seq_len=16, steps_per_call=2,
    )
    with _pytest.raises(ValueError, match="steps_per_call"):
        train_lm(params, cfg, batches(), tcfg, mesh=mesh, num_stages=2,
                 num_microbatches=2)
    with _pytest.raises(ValueError, match="globalizer"):
        train_lm(params, cfg, batches(), tcfg,
                 globalize=lambda b: jnp.asarray(b))
    # Mid-group log timestamps are not fetch barriers: reject the
    # cadence instead of recording dishonest timing.
    bad = LMTrainConfig(
        steps=4, batch_size=4, seq_len=16, log_every=3, steps_per_call=2,
    )
    with _pytest.raises(ValueError, match="multiple of"):
        train_lm(params, cfg, batches(), bad)
    with _pytest.raises(ValueError, match="steps_per_call"):
        train_lm(params, cfg, batches(),
                 LMTrainConfig(steps=2, batch_size=4, seq_len=16,
                               steps_per_call=0))


def test_cli_lm_steps_per_call(capsys):
    # The flag end-to-end: grouped device calls, same reporting shape.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "5", "--batch-size", "4",
        "--seq-len", "24", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--steps-per-call", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert '"final_train_loss"' in out


def test_steps_per_call_resume_realigns_to_step_grid(tmp_path):
    # Resume from a checkpoint whose step is NOT a multiple of K: the
    # first post-resume group must shorten so later groups land back on
    # the global grid (log boundaries stay fetch barriers), and the
    # trajectory must match an unbroken run exactly.
    from tpu_dist_nn.checkpoint import CheckpointManager
    from tpu_dist_nn.train.lm_trainer import LMTrainConfig, train_lm

    cfg, params, batches = _lm_setup()
    ref_cfg = LMTrainConfig(
        steps=8, batch_size=4, seq_len=16, log_every=4, steps_per_call=4,
    )
    _, ref_hist = train_lm(params, cfg, batches(), ref_cfg)

    mgr = CheckpointManager(tmp_path)
    # Interrupted run: 3 completed steps checkpointed (3 % 4 != 0).
    pre_cfg = LMTrainConfig(
        steps=3, batch_size=4, seq_len=16, log_every=1, steps_per_call=1,
    )
    train_lm(params, cfg, batches(), pre_cfg, checkpoints=mgr,
             checkpoint_every=3)
    assert mgr.latest_step() == 3
    _, hist = train_lm(params, cfg, batches(), ref_cfg, checkpoints=mgr)
    by_step = {h["step"]: h["loss"] for h in hist}
    ref_by_step = {h["step"]: h["loss"] for h in ref_hist}
    assert set(by_step) == {4, 8}  # grid preserved across the resume
    for s, loss in by_step.items():
        np.testing.assert_allclose(loss, ref_by_step[s], rtol=1e-6)
