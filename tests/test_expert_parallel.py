"""Expert parallelism (MoE): routing, shard round-trips, and exact
parity of the all_to_all EP path vs the grouped single-chip oracle on
the 8-device virtual mesh (SURVEY.md §4 test strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.parallel.expert_parallel import (
    MoEConfig,
    ep_shard_blocks,
    ep_unshard_blocks,
    init_moe_transformer,
    make_ep_lm_forward,
    moe_ffn_apply,
    moe_forward,
    moe_lm_loss,
    route_top1,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh

CFG = MoEConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
    max_seq_len=32, n_experts=4, capacity_factor=1.5,
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), jnp.int32)


def test_route_top1_dispatch_shapes_and_capacity():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((24, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    dispatch, combine, aux = route_top1(x, w, capacity=3)
    assert dispatch.shape == (24, 4, 3)
    # Each token goes to at most one (expert, slot); each slot holds at
    # most one token.
    assert float(jnp.max(jnp.sum(dispatch, axis=(1, 2)))) <= 1.0
    assert float(jnp.max(jnp.sum(dispatch, axis=0))) <= 1.0
    # Combine weights are the gate prob where dispatched.
    assert float(jnp.max(combine)) <= 1.0
    assert float(aux) > 0


def test_route_top1_drops_overflow_tokens():
    # All tokens prefer the same expert -> only `capacity` survive.
    x = jnp.ones((10, 4), jnp.float32)
    w = jnp.zeros((4, 3), jnp.float32).at[:, 1].set(5.0)
    dispatch, combine, _ = route_top1(x, w, capacity=4)
    assert float(jnp.sum(dispatch)) == 4.0
    assert float(jnp.sum(dispatch[:, 1])) == 4.0


def test_moe_ffn_dropped_tokens_pass_through_residual():
    # Capacity factor so small that most tokens are dropped: the FFN
    # contribution for dropped tokens must be exactly zero.
    cfg = MoEConfig(
        vocab_size=16, d_model=8, n_heads=2, n_layers=1, d_ff=16,
        max_seq_len=8, n_experts=2, capacity_factor=0.1,
    )
    params = init_moe_transformer(jax.random.key(0), cfg)
    block = jax.tree.map(lambda a: a[0], params["blocks"])
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 8, 8)), jnp.float32)
    y, _ = moe_ffn_apply(block, x, cfg)
    contributions = jnp.abs(y).sum(-1).ravel()
    assert int(jnp.sum(contributions == 0)) > 0  # some dropped
    assert int(jnp.sum(contributions > 0)) > 0  # some routed


def test_ep_shard_roundtrip():
    params = init_moe_transformer(jax.random.key(0), CFG)
    staged = ep_shard_blocks(params["blocks"], 2)
    assert staged["w_up"].shape == (2, CFG.n_layers, 2, CFG.d_model, CFG.d_ff)
    back = ep_unshard_blocks(staged)
    for k, v in params["blocks"].items():
        np.testing.assert_array_equal(np.asarray(v), np.asarray(back[k]))


def test_ep_shard_rejects_indivisible():
    params = init_moe_transformer(jax.random.key(0), CFG)
    with pytest.raises(ValueError, match="not divisible"):
        ep_shard_blocks(params["blocks"], 3)


@pytest.mark.parametrize("data,ep", [(2, 4), (4, 2), (1, 4)])
def test_ep_forward_matches_grouped_oracle(data, ep):
    mesh = build_mesh(MeshSpec(data=data, expert=ep))
    params = init_moe_transformer(jax.random.key(2), CFG)
    tokens = _tokens(batch=8, seq=16, seed=3)

    logits_ref, _ = moe_forward(params, tokens, CFG, n_groups=data * ep)
    fwd = make_ep_lm_forward(mesh, CFG)
    params_ep = dict(params, blocks=ep_shard_blocks(params["blocks"], ep))
    logits_ep = jax.jit(fwd)(params_ep, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_ref), np.asarray(logits_ep), rtol=2e-5, atol=2e-5
    )


def test_ep_loss_and_grad_match_oracle():
    data, ep = 2, 4
    mesh = build_mesh(MeshSpec(data=data, expert=ep))
    params = init_moe_transformer(jax.random.key(4), CFG)
    tokens = _tokens(batch=8, seq=17, seed=5)  # T-1 = 16 after shift

    loss_fn = make_ep_lm_forward(mesh, CFG, with_loss=True)
    params_ep = dict(params, blocks=ep_shard_blocks(params["blocks"], ep))
    loss_ep = jax.jit(loss_fn)(params_ep, tokens)
    loss_ref = moe_lm_loss(params, tokens, CFG, n_groups=data * ep)
    np.testing.assert_allclose(
        float(loss_ref), float(loss_ep), rtol=1e-5, atol=1e-6
    )

    g = jax.jit(jax.grad(loss_fn))(params_ep, tokens)
    g_flat = jax.tree.leaves(g)
    assert all(bool(jnp.all(jnp.isfinite(leaf))) for leaf in g_flat)
    # Router must receive gradient (it only gets one through the
    # combine weights — a classic silent-breakage point).
    assert float(jnp.max(jnp.abs(g["blocks"]["w_router"]))) > 0


def test_moe_lm_loss_decreases_under_adam():
    import optax

    cfg = MoEConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq_len=16, n_experts=2, capacity_factor=2.0,
    )
    params = init_moe_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (8, 16)), jnp.int32
    )
    opt = optax.adam(1e-2)
    state = opt.init(params)

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(
            lambda q: moe_lm_loss(q, tokens, cfg)
        )(p)
        updates, s = opt.update(g, s)
        return optax.apply_updates(p, updates), s, loss

    first = None
    for _ in range(30):
        params, state, loss = step(params, state)
        first = first if first is not None else float(loss)
    assert float(loss) < first


def test_topk_k1_identical_to_top1():
    from tpu_dist_nn.parallel.expert_parallel import route_top1, route_topk

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(8, 4)), jnp.float32)
    d0, c0, a0 = route_top1(x, w, capacity=12)
    d1, c1, a1 = route_topk(x, w, capacity=12, k=1)
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    assert float(a0) == float(a1)


def test_top2_routes_two_experts_with_normalized_gates():
    from tpu_dist_nn.parallel.expert_parallel import route_topk

    rng = np.random.default_rng(1)
    S, D, E = 16, 8, 4
    x = jnp.asarray(rng.normal(size=(S, D)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(D, E)), jnp.float32)
    # Ample capacity: nothing dropped.
    d, c, _ = route_topk(x, w, capacity=S, k=2)
    d, c = np.asarray(d), np.asarray(c)
    # Every token dispatched to exactly 2 slots, total gate 1.
    np.testing.assert_array_equal(d.sum(axis=(1, 2)), np.full(S, 2.0))
    np.testing.assert_allclose(c.sum(axis=(1, 2)), np.ones(S), rtol=1e-6)
    # The two chosen experts are the argmax-2 of the router.
    probs = np.asarray(jax.nn.softmax(x @ w, axis=-1))
    for s in range(S):
        chosen = set(np.nonzero(d[s].sum(-1))[0])
        assert chosen == set(np.argsort(probs[s])[-2:])


def test_top2_respects_capacity_rank_order():
    from tpu_dist_nn.parallel.expert_parallel import route_topk

    # All tokens prefer expert 0 then expert 1 (fixed logits).
    S, E, cap = 6, 3, 2
    x = jnp.ones((S, 1), jnp.float32)
    w = jnp.asarray([[3.0, 2.0, -5.0]], jnp.float32)
    d, c, _ = route_topk(x, w, capacity=cap, k=2)
    d = np.asarray(d)
    # Expert 0 holds exactly cap rank-0 tokens; expert 1 exactly cap
    # rank-1 tokens; slots never exceed capacity and never collide.
    assert d[:, 0].sum() == cap and d[:, 1].sum() == cap
    assert (d.sum(axis=0) <= 1.0 + 1e-6).all()  # one token per slot


def test_ep_sharded_top2_matches_grouped_oracle():
    from tpu_dist_nn.parallel.expert_parallel import (
        MoEConfig,
        ep_shard_blocks,
        init_moe_transformer,
        make_ep_lm_forward,
        moe_forward,
    )
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh

    ep, dp = 2, 2
    cfg = MoEConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16, n_experts=4, capacity_factor=2.0, router_top_k=2,
    )
    params = init_moe_transformer(jax.random.key(0), cfg)
    mesh = build_mesh(MeshSpec(expert=ep, data=dp))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (ep * dp * 2, 16)), jnp.int32
    )
    want, _ = moe_forward(params, tokens, cfg, n_groups=ep * dp)
    params_ep = dict(params, blocks=ep_shard_blocks(params["blocks"], ep))
    fwd = make_ep_lm_forward(mesh, cfg)
    got = fwd(params_ep, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_top2_training_learns():
    import optax

    from tpu_dist_nn.parallel.expert_parallel import (
        MoEConfig,
        init_moe_transformer,
    )
    from tpu_dist_nn.train.lm_trainer import make_moe_lm_train_step

    cfg = MoEConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16, n_experts=4, router_top_k=2,
    )
    params = init_moe_transformer(jax.random.key(1), cfg)
    step = make_moe_lm_train_step(cfg, optax.adam(3e-3))
    opt_state = optax.adam(3e-3).init(params)
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, 32, (8, 16)), jnp.int32
    )
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_capacity_scales_with_top_k_and_k_validated():
    from tpu_dist_nn.parallel.expert_parallel import MoEConfig

    base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
                max_seq_len=16, n_experts=4, capacity_factor=1.25)
    c1 = MoEConfig(**base, router_top_k=1)
    c2 = MoEConfig(**base, router_top_k=2)
    assert c2.capacity(256) == 2 * c1.capacity(256)
    with pytest.raises(ValueError, match="router_top_k"):
        MoEConfig(**dict(base, n_experts=1), router_top_k=2)


def test_moe_remat_matches_no_remat():
    # --remat now composes with MoE (the old rejection's reason — "the
    # MoE forward is not scan-based" — stopped being true when the
    # block stack became a lax.scan): per-block rematerialization must
    # not change the loss or grads, on the single-chip oracle AND the
    # EP-sharded path.
    import jax

    from tpu_dist_nn.parallel.expert_parallel import (
        MoEConfig,
        ep_shard_blocks,
        init_moe_transformer,
        make_ep_lm_forward,
        moe_lm_loss,
    )
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh

    base = dict(vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
                max_seq_len=16, n_experts=4)
    cfg = MoEConfig(**base)
    cfg_r = MoEConfig(**base, remat=True)
    params = init_moe_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 32, (8, 17)), jnp.int32
    )

    v0, g0 = jax.jit(jax.value_and_grad(
        lambda p, t: moe_lm_loss(p, t, cfg)
    ))(params, tokens)
    v1, g1 = jax.jit(jax.value_and_grad(
        lambda p, t: moe_lm_loss(p, t, cfg_r)
    ))(params, tokens)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)

    # Remat's behavioral surface is the BACKWARD: grads must agree on
    # the sharded paths too (checkpoint around the all_to_all dispatch).
    mesh = build_mesh(MeshSpec(expert=2, data=4))
    params_ep = dict(params, blocks=ep_shard_blocks(params["blocks"], 2))
    l0 = make_ep_lm_forward(mesh, cfg, with_loss=True)
    l1 = make_ep_lm_forward(mesh, cfg_r, with_loss=True)
    v0, g0 = jax.jit(jax.value_and_grad(l0))(params_ep, tokens)
    v1, g1 = jax.jit(jax.value_and_grad(l1))(params_ep, tokens)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)

    # Pipeline x EP under remat: the third newly wrapped scan body.
    from tpu_dist_nn.parallel.expert_parallel import (
        make_pipeline_ep_lm_loss,
        shard_blocks_pp_ep,
    )

    mesh_pp = build_mesh(MeshSpec(stage=2, expert=2, data=2))
    params_pp = dict(params, blocks=shard_blocks_pp_ep(params["blocks"], 2, 2))
    p0 = make_pipeline_ep_lm_loss(mesh_pp, cfg, 2, 1)
    p1 = make_pipeline_ep_lm_loss(mesh_pp, cfg_r, 2, 1)
    v0, g0 = jax.jit(jax.value_and_grad(p0))(params_pp, tokens)
    v1, g1 = jax.jit(jax.value_and_grad(p1))(params_pp, tokens)
    np.testing.assert_allclose(float(v0), float(v1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_sp_ep_loss_and_grads_match_grouped_oracle():
    # Long-context MoE (round 4, previously a documented
    # non-composition): sequence parallelism x expert parallelism on a
    # (seq=2, expert=2, data=2) mesh. Oracle: single-chip MoE forward
    # whose FFN routes within (batch slice x seq slice) groups —
    # moe_ffn_apply(n_groups=data*expert, n_seq_groups=seq) — plus the
    # sp masking convention for the CE.
    from tpu_dist_nn.models.transformer import masked_next_token_ce
    from tpu_dist_nn.parallel.expert_parallel import make_sp_ep_lm_loss

    mesh = build_mesh(MeshSpec(seq=2, expert=2, data=2))
    params = init_moe_transformer(jax.random.key(31), CFG)
    tokens = _tokens(batch=8, seq=16, seed=32)

    loss_sp = make_sp_ep_lm_loss(mesh, CFG, mode="ring")
    params_ep = dict(params, blocks=ep_shard_blocks(params["blocks"], 2))
    v_sp, g_sp = jax.jit(jax.value_and_grad(loss_sp))(params_ep, tokens)

    def oracle_loss(p, t):
        ffn = lambda block, h: moe_ffn_apply(  # noqa: E731
            block, h, CFG, n_groups=4, n_seq_groups=2
        )
        logits, aux = moe_forward(p, t, CFG, ffn_fn=ffn)
        return (
            masked_next_token_ce(logits, t)
            + CFG.router_aux_weight * aux
        )

    v_ref, g_ref = jax.jit(jax.value_and_grad(oracle_loss))(params, tokens)
    np.testing.assert_allclose(float(v_ref), float(v_sp), rtol=1e-5)

    g_blocks = ep_unshard_blocks(g_sp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_sp[k]), rtol=5e-4, atol=1e-5,
            err_msg=k,
        )


def test_sp_ep_ulysses_and_train_step_and_cli(capsys):
    import optax

    from tpu_dist_nn.cli import main
    from tpu_dist_nn.parallel.expert_parallel import make_sp_ep_lm_loss
    from tpu_dist_nn.train.lm_trainer import make_sp_moe_lm_train_step

    mesh = build_mesh(MeshSpec(seq=2, expert=2, data=2))
    params = init_moe_transformer(jax.random.key(33), CFG)
    params_ep = dict(params, blocks=ep_shard_blocks(params["blocks"], 2))
    tokens = _tokens(batch=8, seq=16, seed=34)

    # Ulysses mode agrees with the ring on the same shards.
    v_ring = float(jax.jit(make_sp_ep_lm_loss(mesh, CFG, "ring"))(
        params_ep, tokens
    ))
    v_uly = float(jax.jit(make_sp_ep_lm_loss(mesh, CFG, "ulysses"))(
        params_ep, tokens
    ))
    np.testing.assert_allclose(v_ring, v_uly, rtol=1e-5)

    optimizer = optax.adam(1e-2)
    step = make_sp_moe_lm_train_step(mesh, CFG, optimizer)
    new_params, _, loss = step(params_ep, optimizer.init(params_ep), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_up"]),
        np.asarray(params_ep["blocks"]["w_up"]),
    )

    # End to end: tdn lm --experts --seq-parallel (previously rejected).
    rc = main([
        "--platform", "cpu", "lm", "--steps", "2", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--experts", "2", "--expert-parallel", "2",
        "--seq-parallel", "2", "--data-parallel", "2",
    ])
    assert rc == 0
    assert "perplexity" in capsys.readouterr().out
    # MoE x SP x PP composes since round 5 (gpipe; the default) — only
    # the scheduled three-axis variants stay bounded
    # (test_pp_sp_ep_ulysses_matches_ring_and_cli asserts both sides).
    assert main([
        "--platform", "cpu", "lm", "--steps", "1", "--batch-size", "4",
        "--seq-len", "15", "--d-model", "16", "--heads", "2",
        "--layers", "2", "--experts", "2", "--seq-parallel", "2",
        "--stages", "2",
    ]) == 0


def test_ep_tp_loss_and_grads_match_grouped_oracle():
    # TP-INSIDE-EXPERTS (round 5; previously rejected as "expert banks
    # are already sharded"): flat (model=2, expert=2, data=2) mesh,
    # each expert's FFN Megatron-split over `model` (column-parallel
    # up, row-parallel down + one psum). Must equal the flat EP math —
    # i.e. the grouped oracle with n_groups = data*expert — exactly
    # (modulo the psum's float reassociation).
    from tpu_dist_nn.parallel.expert_parallel import make_ep_tp_lm_loss

    mesh = build_mesh(MeshSpec(model=2, expert=2, data=2))
    params = init_moe_transformer(jax.random.key(41), CFG)
    tokens = _tokens(batch=8, seq=17, seed=42)

    loss_tp = make_ep_tp_lm_loss(mesh, CFG)
    params_ep = dict(params, blocks=ep_shard_blocks(params["blocks"], 2))
    v_tp, g_tp = jax.jit(jax.value_and_grad(loss_tp))(params_ep, tokens)
    v_ref, g_ref = jax.jit(
        jax.value_and_grad(
            lambda p, t: moe_lm_loss(p, t, CFG, n_groups=4)
        )
    )(params, tokens)
    np.testing.assert_allclose(float(v_tp), float(v_ref), rtol=1e-5)
    g_blocks = ep_unshard_blocks(g_tp["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g_tp[k]), rtol=5e-4,
            atol=1e-5, err_msg=k,
        )


def test_ep_tp_rejects_indivisible_ff():
    from tpu_dist_nn.parallel.expert_parallel import make_ep_tp_lm_loss

    mesh = build_mesh(MeshSpec(model=3, expert=2))
    import dataclasses

    bad = dataclasses.replace(CFG, d_ff=64)  # 64 % 3 != 0
    with pytest.raises(ValueError, match="d_ff"):
        make_ep_tp_lm_loss(mesh, bad)


def test_pp_sp_ep_loss_and_grads_match_grouped_oracle():
    # THREE-AXIS MoE (round 5; the cell round 4 left eagerly rejected):
    # pipeline x sequence x expert parallelism, gpipe schedule, on a
    # (stage=2, seq=2, expert=2) mesh. Oracle: single-chip MoE forward
    # with (batch slice x seq slice) routing groups —
    # moe_ffn_apply(n_groups=M*expert, n_seq_groups=seq) — and the sp
    # masking convention for the CE (full rows, final position
    # unscored).
    from tpu_dist_nn.models.transformer import masked_next_token_ce
    from tpu_dist_nn.parallel.expert_parallel import (
        make_pipeline_sp_ep_lm_loss,
        shard_blocks_pp_ep,
        unshard_blocks_pp_ep,
    )

    mesh = build_mesh(MeshSpec(stage=2, seq=2, expert=2))
    params = init_moe_transformer(jax.random.key(51), CFG)
    M = 2
    tokens = _tokens(batch=4, seq=16, seed=52)  # full rows

    loss3 = make_pipeline_sp_ep_lm_loss(
        mesh, CFG, num_stages=2, num_microbatches=M, mode="ring"
    )
    params_pp = dict(
        params, blocks=shard_blocks_pp_ep(params["blocks"], 2, 2)
    )
    v3, g3 = jax.jit(jax.value_and_grad(loss3))(params_pp, tokens)

    def oracle(p, t):
        ffn = lambda block, h: moe_ffn_apply(  # noqa: E731
            block, h, CFG, n_groups=M * 2, n_seq_groups=2
        )
        logits, aux = moe_forward(p, t, CFG, ffn_fn=ffn)
        return masked_next_token_ce(logits, t) + CFG.router_aux_weight * aux

    v_ref, g_ref = jax.jit(jax.value_and_grad(oracle))(params, tokens)
    np.testing.assert_allclose(float(v3), float(v_ref), rtol=1e-5)
    g_blocks = unshard_blocks_pp_ep(g3["blocks"])
    for k in g_ref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(g_ref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5, err_msg=k,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(g_ref[k]), np.asarray(g3[k]), rtol=5e-4,
            atol=1e-5, err_msg=k,
        )


def test_pp_sp_ep_ulysses_matches_ring_and_cli(capsys):
    # Ulysses mode agrees with the ring on identical shards, and the
    # CLI drives the three-axis cell end to end; scheduled variants
    # stay bounded with an explicit message (gpipe only).
    from tpu_dist_nn.cli import main
    from tpu_dist_nn.parallel.expert_parallel import (
        make_pipeline_sp_ep_lm_loss,
        shard_blocks_pp_ep,
    )

    mesh = build_mesh(MeshSpec(stage=2, seq=2, expert=2))
    params = init_moe_transformer(jax.random.key(53), CFG)
    params_pp = dict(
        params, blocks=shard_blocks_pp_ep(params["blocks"], 2, 2)
    )
    tokens = _tokens(batch=4, seq=16, seed=54)
    v_ring = float(jax.jit(make_pipeline_sp_ep_lm_loss(
        mesh, CFG, 2, 2, "ring"
    ))(params_pp, tokens))
    v_uly = float(jax.jit(make_pipeline_sp_ep_lm_loss(
        mesh, CFG, 2, 2, "ulysses"
    ))(params_pp, tokens))
    np.testing.assert_allclose(v_ring, v_uly, rtol=1e-5)

    rc = main([
        "--platform", "cpu", "lm", "--steps", "1", "--batch-size", "8",
        "--seq-len", "15", "--d-model", "32", "--heads", "4",
        "--layers", "4", "--experts", "4", "--stages", "2",
        "--seq-parallel", "2", "--expert-parallel", "2",
        "--microbatches", "2",
    ])
    assert rc == 0
    assert "final_train_loss" in capsys.readouterr().out
    # Scheduled three-axis variants are bounded, not silent.
    rc = main([
        "--platform", "cpu", "lm", "--steps", "1", "--batch-size", "8",
        "--seq-len", "15", "--experts", "4", "--stages", "2",
        "--seq-parallel", "2", "--schedule", "1f1b",
    ])
    assert rc != 0
    assert "gpipe" in capsys.readouterr().err


def test_ep_tp_cli_and_bounded_products(capsys):
    # `tdn lm --experts --tensor-parallel` end to end, and the bounded
    # products (x --stages, x --seq-parallel) reject with the
    # documented message rather than silently.
    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "lm", "--steps", "1", "--batch-size", "8",
        "--seq-len", "16", "--d-model", "32", "--heads", "4",
        "--layers", "2", "--experts", "4", "--tensor-parallel", "2",
        "--expert-parallel", "2",
    ])
    assert rc == 0
    assert "final_train_loss" in capsys.readouterr().out
    rc = main([
        "--platform", "cpu", "lm", "--steps", "1", "--experts", "4",
        "--tensor-parallel", "2", "--stages", "2",
    ])
    assert rc != 0
    assert "out of scope" in capsys.readouterr().err
