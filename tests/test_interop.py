"""Torch interop: state dict ↔ ModelSpec parity (reference C8 toolchain,
generate_mnist_pytorch.py:68-103 — the exporter, made real)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from tpu_dist_nn.core.schema import load_model  # noqa: E402
from tpu_dist_nn.interop import (  # noqa: E402
    model_from_torch_state_dict,
    model_to_torch_state_dict,
)
from tpu_dist_nn.testing.factories import random_model  # noqa: E402
from tpu_dist_nn.testing.oracle import oracle_forward_batch  # noqa: E402


def _torch_fcnn(sizes):
    layers = []
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        layers.append(torch.nn.Linear(a, b))
        if i < len(sizes) - 2:
            layers.append(torch.nn.ReLU())
    return torch.nn.Sequential(*layers)


def test_torch_forward_parity():
    # The reference's torch model size (generate_mnist_pytorch.py:25-27)
    # at test scale: torch softmax(logits) == oracle forward.
    torch.manual_seed(0)
    net = _torch_fcnn([20, 12, 8, 5])
    model = model_from_torch_state_dict(net.state_dict())
    assert model.layer_sizes == [20, 12, 8, 5]
    assert [l.activation for l in model.layers] == ["relu", "relu", "softmax"]
    assert model.layers[-1].type_tag == "output"

    x = np.random.default_rng(0).uniform(0, 1, (9, 20)).astype(np.float32)
    with torch.no_grad():
        want = torch.softmax(net(torch.from_numpy(x)), dim=1).numpy()
    got = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-7)


def test_torch_round_trip():
    model = random_model([7, 6, 4], seed=5)
    state = model_to_torch_state_dict(model)
    back = model_from_torch_state_dict(
        state, [l.activation for l in model.layers]
    )
    for a, b in zip(model.layers, back.layers):
        np.testing.assert_allclose(a.weights, b.weights)
        np.testing.assert_allclose(a.biases, b.biases)
        assert a.activation == b.activation


def test_state_dict_prefix_and_non_linear_keys_ignored():
    torch.manual_seed(1)
    net = torch.nn.Sequential(
        torch.nn.Linear(6, 5), torch.nn.LayerNorm(5), torch.nn.Linear(5, 3)
    )
    model = model_from_torch_state_dict(net.state_dict())
    # LayerNorm's 1-D weight/bias are skipped; two Linears imported.
    assert model.layer_sizes == [6, 5, 3]


def test_conv_state_dict_rejected():
    net = torch.nn.Conv2d(3, 8, 3)
    with pytest.raises(ValueError, match="conv"):
        model_from_torch_state_dict(net.state_dict())


def test_activation_count_mismatch():
    net = _torch_fcnn([4, 3, 2])
    with pytest.raises(ValueError, match="activations"):
        model_from_torch_state_dict(net.state_dict(), ["relu"])


def test_broken_chain_rejected():
    state = {
        "a.weight": torch.zeros(3, 4), "a.bias": torch.zeros(3),
        "b.weight": torch.zeros(2, 9), "b.bias": torch.zeros(2),
    }
    with pytest.raises(ValueError, match="chain"):
        model_from_torch_state_dict(state)


def test_cli_import_torch(tmp_path):
    from tpu_dist_nn.cli import main

    torch.manual_seed(2)
    net = _torch_fcnn([10, 6, 4])
    pt = tmp_path / "net.pt"
    torch.save(net.state_dict(), pt)
    out = tmp_path / "model.json"
    assert main(["import-torch", "--state-dict", str(pt), "--out", str(out)]) == 0
    model = load_model(out)
    assert model.layer_sizes == [10, 6, 4]

    x = np.random.default_rng(1).uniform(0, 1, (5, 10)).astype(np.float32)
    with torch.no_grad():
        want = torch.softmax(net(torch.from_numpy(x)), dim=1).numpy()
    np.testing.assert_allclose(
        oracle_forward_batch(model, x), want, rtol=1e-5, atol=1e-7
    )


def test_conv1d_state_dict_rejected():
    net = torch.nn.Conv1d(3, 8, 5)
    with pytest.raises(ValueError, match="conv-style"):
        model_from_torch_state_dict(net.state_dict())


def test_unknown_activation_rejected_at_import():
    net = _torch_fcnn([4, 3, 2])
    with pytest.raises(ValueError, match="unknown activations"):
        model_from_torch_state_dict(net.state_dict(), ["relu", "softmx"])


def test_activation_names_stripped():
    net = _torch_fcnn([4, 3, 2])
    model = model_from_torch_state_dict(net.state_dict(), ["relu ", " Softmax"])
    assert [l.activation for l in model.layers] == ["relu", "softmax"]
