"""3D parallelism composition: pipeline (stage) x Megatron tensor
(model) x data — exact parity vs the single-chip transformer on the
8-device virtual mesh, plus grad flow through both psum and ppermute."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    forward,
    init_transformer,
    lm_loss,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_tp_lm_forward,
    make_pipeline_tp_lm_loss,
    shard_blocks_pp_tp,
    unshard_blocks_pp_tp,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq_len=16
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), jnp.int32)


def test_pp_tp_shard_roundtrip():
    params = init_transformer(jax.random.key(0), CFG)
    staged = shard_blocks_pp_tp(params["blocks"], CFG, num_stages=2, n_tp=2)
    assert staged["w_qkv"].shape[:3] == (2, 2, 2)  # (S, N, L/S)
    assert staged["ln1_g"].shape[:2] == (2, 2)  # (S, L/S)
    back = unshard_blocks_pp_tp(staged, CFG)
    for k, v in params["blocks"].items():
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(back[k]), rtol=1e-6, atol=1e-7
        )


@pytest.mark.parametrize("stage,model,data", [(2, 2, 2), (4, 2, 1), (2, 4, 1)])
def test_pp_tp_forward_matches_single_chip(stage, model, data):
    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=data))
    params = init_transformer(jax.random.key(1), CFG)
    tokens = _tokens(batch=8, seq=16, seed=2)

    ref = forward(params, tokens, CFG)
    fwd = make_pipeline_tp_lm_forward(
        mesh, CFG, num_stages=stage, num_microbatches=2
    )
    params_3d = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, stage, model)
    )
    out = jax.jit(fwd)(params_3d, tokens)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-5, atol=2e-5
    )


def test_pp_tp_loss_and_grads_match_single_chip():
    stage, model = 2, 2
    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=2))
    params = init_transformer(jax.random.key(3), CFG)
    tokens = _tokens(batch=8, seq=16, seed=4)

    loss_fn = make_pipeline_tp_lm_loss(
        mesh, CFG, num_stages=stage, num_microbatches=2
    )
    params_3d = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, stage, model)
    )
    loss_3d = jax.jit(loss_fn)(params_3d, tokens)
    loss_ref = lm_loss(params, tokens, CFG)
    np.testing.assert_allclose(float(loss_ref), float(loss_3d), rtol=1e-5)

    # Gradients: unshard the 3D block grads and compare to single-chip.
    g3d = jax.jit(jax.grad(loss_fn))(params_3d, tokens)
    gref = jax.jit(jax.grad(lm_loss), static_argnums=2)(params, tokens, CFG)
    g_blocks = unshard_blocks_pp_tp(g3d["blocks"], CFG)
    for k in gref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5,
        )
    np.testing.assert_allclose(
        np.asarray(gref["tok_embed"]), np.asarray(g3d["tok_embed"]),
        rtol=5e-4, atol=1e-5,
    )
