"""3D parallelism composition: pipeline (stage) x Megatron tensor
(model) x data — exact parity vs the single-chip transformer on the
8-device virtual mesh, plus grad flow through both psum and ppermute."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    forward,
    init_transformer,
    lm_loss,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.transformer_pipeline import (
    make_pipeline_tp_lm_forward,
    make_pipeline_tp_lm_loss,
    shard_blocks_pp_tp,
    unshard_blocks_pp_tp,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64, max_seq_len=16
)


def _tokens(batch, seq, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, seq)), jnp.int32)


def test_pp_tp_shard_roundtrip():
    params = init_transformer(jax.random.key(0), CFG)
    staged = shard_blocks_pp_tp(params["blocks"], CFG, num_stages=2, n_tp=2)
    assert staged["w_qkv"].shape[:3] == (2, 2, 2)  # (S, N, L/S)
    assert staged["ln1_g"].shape[:2] == (2, 2)  # (S, L/S)
    back = unshard_blocks_pp_tp(staged, CFG)
    for k, v in params["blocks"].items():
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(back[k]), rtol=1e-6, atol=1e-7
        )


@pytest.mark.parametrize("stage,model,data", [(2, 2, 2), (4, 2, 1), (2, 4, 1)])
def test_pp_tp_forward_matches_single_chip(stage, model, data):
    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=data))
    params = init_transformer(jax.random.key(1), CFG)
    tokens = _tokens(batch=8, seq=16, seed=2)

    ref = forward(params, tokens, CFG)
    fwd = make_pipeline_tp_lm_forward(
        mesh, CFG, num_stages=stage, num_microbatches=2
    )
    params_3d = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, stage, model)
    )
    out = jax.jit(fwd)(params_3d, tokens)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), rtol=2e-5, atol=2e-5
    )


def test_pp_tp_loss_and_grads_match_single_chip():
    stage, model = 2, 2
    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=2))
    params = init_transformer(jax.random.key(3), CFG)
    tokens = _tokens(batch=8, seq=16, seed=4)

    loss_fn = make_pipeline_tp_lm_loss(
        mesh, CFG, num_stages=stage, num_microbatches=2
    )
    params_3d = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, stage, model)
    )
    loss_3d = jax.jit(loss_fn)(params_3d, tokens)
    loss_ref = lm_loss(params, tokens, CFG)
    np.testing.assert_allclose(float(loss_ref), float(loss_3d), rtol=1e-5)

    # Gradients: unshard the 3D block grads and compare to single-chip.
    g3d = jax.jit(jax.grad(loss_fn))(params_3d, tokens)
    gref = jax.jit(jax.grad(lm_loss), static_argnums=2)(params, tokens, CFG)
    g_blocks = unshard_blocks_pp_tp(g3d["blocks"], CFG)
    for k in gref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5,
        )
    np.testing.assert_allclose(
        np.asarray(gref["tok_embed"]), np.asarray(g3d["tok_embed"]),
        rtol=5e-4, atol=1e-5,
    )


def test_pp_tp_1f1b_grads_match_single_chip():
    # 1F1B x Megatron TP (the r2 restriction lifted): the memory-flat
    # schedule with psum-bearing stage bodies must reproduce
    # jax.value_and_grad of the single-chip LM loss. The tick predicate
    # is model-invariant, so the block psums pair correctly inside the
    # schedule's lax.switch (one_f_one_b.make_1f1b docstring).
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_tp_lm_1f1b_grad,
    )

    stage, model = 2, 2
    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=2))
    params = init_transformer(jax.random.key(5), CFG)
    tokens = _tokens(batch=8, seq=16, seed=6)

    vag = make_pipeline_tp_lm_1f1b_grad(
        mesh, CFG, num_stages=stage, num_microbatches=2
    )
    params_3d = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, stage, model)
    )
    loss_3d, g3d = jax.jit(vag)(params_3d, tokens)
    loss_ref, gref = jax.jit(
        jax.value_and_grad(lm_loss), static_argnums=2
    )(params, tokens, CFG)
    np.testing.assert_allclose(float(loss_ref), float(loss_3d), rtol=1e-5)

    g_blocks = unshard_blocks_pp_tp(g3d["blocks"], CFG)
    for k in gref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(gref[k]), np.asarray(g3d[k]), rtol=5e-4, atol=1e-5,
        )


def test_pp_tp_1f1b_train_step_runs():
    # Trainer-level composition: make_pipeline_lm_train_step with
    # tensor_parallel > 1 and the 1f1b schedule takes an optimizer step
    # on the Megatron layout (loss finite, params move, layout stable).
    import optax

    from tpu_dist_nn.train.lm_trainer import make_pipeline_lm_train_step

    stage, model = 2, 2
    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=2))
    params = init_transformer(jax.random.key(7), CFG)
    params_3d = dict(
        params, blocks=shard_blocks_pp_tp(params["blocks"], CFG, stage, model)
    )
    optimizer = optax.adam(1e-2)
    step = make_pipeline_lm_train_step(
        mesh, CFG, stage, 2, optimizer, schedule="1f1b",
        tensor_parallel=model,
    )
    tokens = _tokens(batch=8, seq=16, seed=8)
    new_params, _, loss = step(params_3d, optimizer.init(params_3d), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert new_params["blocks"]["w_qkv"].shape == params_3d["blocks"]["w_qkv"].shape
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_qkv"]),
        np.asarray(params_3d["blocks"]["w_qkv"]),
    )


def test_interleaved_tp_shard_roundtrip():
    from tpu_dist_nn.parallel.transformer_pipeline import (
        shard_blocks_interleaved_tp,
        unshard_blocks_interleaved_tp,
    )

    params = init_transformer(jax.random.key(9), CFG)
    staged = shard_blocks_interleaved_tp(
        params["blocks"], CFG, num_stages=2, num_virtual=2, n_tp=2
    )
    # L=4 layers, V=4 chunks of 1 layer: sharded (S, v, N, L/V, ...),
    # replicated (S, v, L/V, ...).
    assert staged["w_qkv"].shape[:4] == (2, 2, 2, 1)
    assert staged["ln1_g"].shape[:3] == (2, 2, 1)
    back = unshard_blocks_interleaved_tp(staged, CFG)
    for k, v in params["blocks"].items():
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(back[k]), rtol=1e-6, atol=1e-7
        )


@pytest.mark.parametrize("stage,model,data,v", [(2, 2, 2, 2), (2, 4, 1, 2)])
def test_interleaved_tp_grads_match_single_chip(stage, model, data, v):
    # Interleaved x Megatron TP (the last schedule x sharding hole, r3
    # VERDICT weak 4 closed): the table-driven virtual-stage executor
    # with psum-bearing chunk bodies must reproduce jax.value_and_grad
    # of the single-chip LM loss at the 1F1B x TP tolerances. Legal
    # because the per-tick lax.switch branch is chosen by [device, tick]
    # tables invariant over the model axis.
    from tpu_dist_nn.parallel.transformer_pipeline import (
        make_pipeline_tp_lm_interleaved_grad,
        shard_blocks_interleaved_tp,
        unshard_blocks_interleaved_tp,
    )

    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=data))
    params = init_transformer(jax.random.key(5), CFG)
    tokens = _tokens(batch=8, seq=16, seed=6)

    vag = make_pipeline_tp_lm_interleaved_grad(
        mesh, CFG, num_virtual=v, num_microbatches=2
    )
    params_3d = dict(
        params,
        blocks=shard_blocks_interleaved_tp(params["blocks"], CFG, stage, v, model),
    )
    loss_3d, g3d = jax.jit(vag)(params_3d, tokens)
    loss_ref, gref = jax.jit(
        jax.value_and_grad(lm_loss), static_argnums=2
    )(params, tokens, CFG)
    np.testing.assert_allclose(float(loss_ref), float(loss_3d), rtol=1e-5)

    g_blocks = unshard_blocks_interleaved_tp(g3d["blocks"], CFG)
    for k in gref["blocks"]:
        np.testing.assert_allclose(
            np.asarray(gref["blocks"][k]), np.asarray(g_blocks[k]),
            rtol=5e-4, atol=1e-5,
        )
    for k in ("tok_embed", "pos_embed", "lnf_g", "lnf_b"):
        np.testing.assert_allclose(
            np.asarray(gref[k]), np.asarray(g3d[k]), rtol=5e-4, atol=1e-5,
        )


def test_interleaved_tp_train_step_runs():
    # Trainer-level composition: schedule="interleaved" with
    # tensor_parallel > 1 (previously an explicit rejection) takes an
    # optimizer step on the interleaved-TP layout.
    import optax

    from tpu_dist_nn.parallel.transformer_pipeline import (
        shard_blocks_interleaved_tp,
    )
    from tpu_dist_nn.train.lm_trainer import make_pipeline_lm_train_step

    stage, model, v = 2, 2, 2
    mesh = build_mesh(MeshSpec(stage=stage, model=model, data=2))
    params = init_transformer(jax.random.key(7), CFG)
    params_3d = dict(
        params,
        blocks=shard_blocks_interleaved_tp(params["blocks"], CFG, stage, v, model),
    )
    optimizer = optax.adam(1e-2)
    step = make_pipeline_lm_train_step(
        mesh, CFG, stage, 2, optimizer, schedule="interleaved",
        num_virtual=v, tensor_parallel=model,
    )
    tokens = _tokens(batch=8, seq=16, seed=8)
    new_params, _, loss = step(params_3d, optimizer.init(params_3d), tokens)
    assert np.isfinite(float(loss)) and float(loss) > 0
    assert new_params["blocks"]["w_qkv"].shape == params_3d["blocks"]["w_qkv"].shape
    assert not np.allclose(
        np.asarray(new_params["blocks"]["w_qkv"]),
        np.asarray(params_3d["blocks"]["w_qkv"]),
    )
