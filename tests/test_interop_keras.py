"""Keras/TF interop: saved model ↔ ModelSpec parity (reference C9
toolchain, generate_mnist_tensorflow.py:14-27 with the exporter at
:41-78 — made real, closing SURVEY.md §2.1's one unmatched row)."""

import numpy as np
import pytest

keras = pytest.importorskip("keras")

from tpu_dist_nn.core.schema import load_model  # noqa: E402
from tpu_dist_nn.interop import (  # noqa: E402
    model_from_keras,
    model_from_keras_file,
    model_to_keras,
)
from tpu_dist_nn.testing.factories import random_model  # noqa: E402
from tpu_dist_nn.testing.oracle import oracle_forward_batch  # noqa: E402


def _keras_fcnn(sizes, activations=None):
    """The reference's Keras recipe shape at test scale
    (generate_mnist_tensorflow.py:14-19): Dense relu stack + softmax."""
    n = len(sizes) - 1
    if activations is None:
        activations = ["relu"] * (n - 1) + ["softmax"]
    return keras.Sequential(
        [keras.layers.Input(shape=(sizes[0],))]
        + [
            keras.layers.Dense(out, activation=act)
            for out, act in zip(sizes[1:], activations)
        ]
    )


def test_keras_forward_parity():
    net = _keras_fcnn([20, 12, 8, 5])
    model = model_from_keras(net)
    assert model.layer_sizes == [20, 12, 8, 5]
    assert [l.activation for l in model.layers] == ["relu", "relu", "softmax"]
    assert model.layers[-1].type_tag == "output"

    x = np.random.default_rng(0).uniform(0, 1, (9, 20)).astype(np.float32)
    want = np.asarray(net(x))
    got = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_keras_round_trip():
    model = random_model([7, 6, 4], seed=5)
    back = model_from_keras(model_to_keras(model))
    for a, b in zip(model.layers, back.layers):
        # float32 is Keras's storage dtype; exact once both sides cast.
        np.testing.assert_allclose(
            a.weights.astype(np.float32), b.weights, rtol=0, atol=0
        )
        np.testing.assert_allclose(
            a.biases.astype(np.float32), b.biases, rtol=0, atol=0
        )
        assert a.activation == b.activation


def test_keras_file_round_trip(tmp_path):
    net = _keras_fcnn([10, 6, 4])
    path = tmp_path / "net.keras"
    net.save(path)
    model = model_from_keras_file(str(path))
    assert model.layer_sizes == [10, 6, 4]
    x = np.random.default_rng(1).uniform(0, 1, (5, 10)).astype(np.float32)
    np.testing.assert_allclose(
        oracle_forward_batch(model, x), np.asarray(net(x)),
        rtol=1e-5, atol=1e-6,
    )


def test_keras_flatten_and_dropout_skipped():
    net = keras.Sequential([
        keras.layers.Input(shape=(6,)),
        keras.layers.Dense(5, activation="relu"),
        keras.layers.Dropout(0.5),
        keras.layers.Dense(3, activation="softmax"),
    ])
    model = model_from_keras(net)
    assert model.layer_sizes == [6, 5, 3]


def test_keras_conv_rejected():
    net = keras.Sequential([
        keras.layers.Input(shape=(8, 8, 3)),
        keras.layers.Conv2D(4, 3),
    ])
    with pytest.raises(ValueError, match="Dense"):
        model_from_keras(net)


def test_keras_unsupported_activation_rejected():
    net = _keras_fcnn([4, 3, 2], activations=["tanh", "softmax"])
    with pytest.raises(ValueError, match="tanh"):
        model_from_keras(net)


def test_keras_activation_override_validated():
    net = _keras_fcnn([4, 3, 2])
    with pytest.raises(ValueError, match="unknown activations"):
        model_from_keras(net, ["relu", "softmx"])
    model = model_from_keras(net, ["sigmoid", "linear"])
    assert [l.activation for l in model.layers] == ["sigmoid", "linear"]


def test_cli_import_keras(tmp_path):
    from tpu_dist_nn.cli import main

    net = _keras_fcnn([10, 6, 4])
    path = tmp_path / "net.keras"
    net.save(path)
    out = tmp_path / "model.json"
    assert main(["import-keras", "--model", str(path), "--out", str(out)]) == 0
    model = load_model(out)
    assert model.layer_sizes == [10, 6, 4]
    x = np.random.default_rng(1).uniform(0, 1, (5, 10)).astype(np.float32)
    np.testing.assert_allclose(
        oracle_forward_batch(model, x), np.asarray(net(x)),
        rtol=1e-5, atol=1e-6,
    )


def test_keras_dense_no_bias_imports_with_zero_bias():
    # Dense(use_bias=False) has a single 2-D weight; the schema always
    # carries a bias, so it imports with zeros (ADVICE r2).
    net = keras.Sequential(
        [
            keras.layers.Input((10,)),
            keras.layers.Dense(6, activation="relu", use_bias=False),
            keras.layers.Dense(4, activation="softmax"),
        ]
    )
    model = model_from_keras(net)
    assert model.layer_sizes == [10, 6, 4]
    np.testing.assert_array_equal(model.layers[0].biases, np.zeros(6))
    x = np.random.default_rng(2).uniform(0, 1, (5, 10)).astype(np.float32)
    np.testing.assert_allclose(
        oracle_forward_batch(model, x), np.asarray(net(x)),
        rtol=1e-5, atol=1e-6,
    )
