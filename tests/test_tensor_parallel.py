"""Tensor parallelism: Megatron transformer blocks + column-parallel FCNN
chains match their single-chip counterparts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.models.fcnn import forward as fcnn_forward, init_fcnn
from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    forward,
    init_transformer,
)
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.tensor_parallel import (
    make_tp_fcnn_forward,
    make_tp_lm_forward,
    tp_shard_blocks,
    tp_shard_fcnn,
    tp_unshard_blocks,
)

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=3, d_ff=64, max_seq_len=32
)


def _tokens(batch=4, t=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, CFG.vocab_size, (batch, t)), jnp.int32)


class TestTransformerTP:
    def test_shard_roundtrip(self):
        blocks = init_transformer(jax.random.key(0), CFG)["blocks"]
        for n in (2, 4):
            rt = tp_unshard_blocks(tp_shard_blocks(blocks, CFG, n), CFG)
            for key in blocks:
                np.testing.assert_allclose(
                    np.asarray(blocks[key]), np.asarray(rt[key]), atol=0,
                    err_msg=key,
                )

    @pytest.mark.parametrize("spec", [MeshSpec(model=2), MeshSpec(model=4),
                                      MeshSpec(model=2, data=2)])
    def test_forward_matches_single_chip(self, spec):
        mesh = build_mesh(spec)
        params = init_transformer(jax.random.key(1), CFG)
        tokens = _tokens()
        want = np.asarray(forward(params, tokens, CFG))
        params_tp = dict(
            params, blocks=tp_shard_blocks(params["blocks"], CFG, spec.model)
        )
        fwd = make_tp_lm_forward(mesh, CFG)
        got = np.asarray(jax.jit(fwd)(params_tp, tokens))
        np.testing.assert_allclose(got, want, atol=3e-4, rtol=1e-3)

    def test_indivisible_heads_raise(self):
        blocks = init_transformer(jax.random.key(0), CFG)["blocks"]
        with pytest.raises(ValueError, match="n_heads"):
            tp_shard_blocks(blocks, CFG, 3)

    def test_gradients_flow(self):
        mesh = build_mesh(MeshSpec(model=4, data=2))
        params = init_transformer(jax.random.key(2), CFG)
        params_tp = dict(params, blocks=tp_shard_blocks(params["blocks"], CFG, 4))
        fwd = make_tp_lm_forward(mesh, CFG)

        def loss(p, t):
            return jnp.mean(fwd(p, t) ** 2)

        grads = jax.jit(jax.grad(loss))(params_tp, _tokens())
        gnorm = sum(float(jnp.sum(g**2)) for g in jax.tree.leaves(grads))
        assert np.isfinite(gnorm) and gnorm > 0


class TestFcnnTP:
    @pytest.mark.parametrize("n", [2, 4])
    def test_matches_single_chip_ragged_widths(self, n):
        """784-128-64-10-style ragged widths (10 needs padding for n=4)."""
        sizes = [20, 16, 12, 10]
        params = init_fcnn(jax.random.key(0), sizes)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.uniform(-1, 1, (8, 20)), jnp.float32)
        want = np.asarray(fcnn_forward(params, x))

        mesh = build_mesh(MeshSpec(model=n, data=2))
        params_tp, true_dims = tp_shard_fcnn(params, n)
        assert true_dims == (16, 12, 10)
        fwd = make_tp_fcnn_forward(mesh, true_dims)
        got = np.asarray(jax.jit(fwd)(params_tp, x))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(got.sum(-1), 1.0, atol=1e-5)  # softmax rows


def test_tp_remat_grads_match():
    import dataclasses as dc

    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_seq_len=16,
    )
    params = init_transformer(jax.random.key(0), cfg)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (4, 16)), jnp.int32
    )
    mesh = build_mesh(MeshSpec(model=2, data=4))
    params_tp = dict(params, blocks=tp_shard_blocks(params["blocks"], cfg, 2))

    def loss(c):
        fwd = make_tp_lm_forward(mesh, c)
        return lambda p, t: jnp.mean(fwd(p, t) ** 2)

    g0 = jax.jit(jax.grad(loss(cfg)))(params_tp, tokens)
    g1 = jax.jit(jax.grad(loss(dc.replace(cfg, remat=True))))(params_tp, tokens)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
