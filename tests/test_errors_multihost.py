"""Structured errors, engine health/relaunch, multi-host topology tests
(SURVEY.md §5: failure detection = fail fast, propagate, clean restart)."""

import numpy as np
import pytest

from tpu_dist_nn.api.engine import Engine
from tpu_dist_nn.parallel.multihost import (
    current_topology,
    initialize_multihost,
)
from tpu_dist_nn.testing.factories import random_model
from tpu_dist_nn.utils.errors import (
    FrameworkError,
    InternalError,
    InvalidArgumentError,
    UnavailableError,
    check_input_dim,
)


def test_error_codes_match_reference_status_names():
    assert InvalidArgumentError.code == "INVALID_ARGUMENT"
    assert InternalError.code == "INTERNAL"
    assert UnavailableError.code == "UNAVAILABLE"
    # Migrating client code can catch stdlib types (grpc_node.py raised
    # through ValueError-shaped paths).
    assert issubclass(InvalidArgumentError, ValueError)
    assert issubclass(InternalError, RuntimeError)


def test_check_input_dim_messages():
    check_input_dim(4, 4)
    with pytest.raises(InvalidArgumentError, match=r"\[stage 2\] Expected input dimension 4, got 7"):
        check_input_dim(4, 7, stage=2)


def test_engine_dim_mismatch_is_invalid_argument():
    model = random_model([6, 5, 3], seed=0)
    engine = Engine.up(model, warmup=False)
    with pytest.raises(InvalidArgumentError):
        engine.infer(np.zeros((2, 9)))
    with pytest.raises(InvalidArgumentError):
        engine.infer(np.zeros(9))


def test_engine_down_then_unavailable_then_relaunch():
    """down() → UNAVAILABLE; relaunch from the same spec serves again —
    the reference's clean-teardown/stateless-relaunch contract."""
    model = random_model([6, 5, 3], seed=0)
    engine = Engine.up(model, warmup=False)
    want = engine.infer(np.zeros((1, 6)))
    engine.down()
    engine.down()  # idempotent
    with pytest.raises(UnavailableError):
        engine.infer(np.zeros((1, 6)))
    relaunched = Engine.up(model, warmup=False)
    np.testing.assert_array_equal(relaunched.infer(np.zeros((1, 6))), want)


def test_engine_health_probe():
    model = random_model([6, 5, 3], seed=0)
    engine = Engine.up(model, warmup=False)
    status = engine.health()
    assert status["ready"] and status["probe_ok"]
    engine.down()
    assert engine.health()["ready"] is False


def test_framework_error_catch_all():
    with pytest.raises(FrameworkError):
        raise UnavailableError("nope")


def test_single_process_topology_noop():
    topo = initialize_multihost()
    assert topo.num_processes == 1
    assert topo.process_id == 0
    assert not topo.is_multihost
    assert topo.local_device_count == topo.global_device_count == 8
    assert current_topology() == topo


def test_cli_multihost_noop_and_oracle_path(tmp_path, capsys):
    # Without --coordinator the init is a single-process no-op; the
    # oracle subcommand (which doesn't register the multihost args)
    # must also pass through _init_multihost's getattr defaults.
    from tpu_dist_nn.cli import main as cli_main
    from tpu_dist_nn.core.schema import save_examples, save_model
    from tpu_dist_nn.testing.factories import random_inputs, random_model

    model = random_model([6, 4, 3], seed=0)
    mp = tmp_path / "m.json"
    save_model(model, mp)
    xp = tmp_path / "x.json"
    save_examples(random_inputs(2, 6, seed=1), np.array([0, 1]), xp)
    assert cli_main(["oracle", "--config", str(mp), "--inputs", str(xp)]) == 0
    assert "Average inference time" in capsys.readouterr().out


def test_cli_rejects_host_flags_without_coordinator(capsys):
    from tpu_dist_nn.cli import main as cli_main

    rc = cli_main(["lm", "--steps", "1", "--num-hosts", "4", "--host-id", "1"])
    assert rc == 2
    assert "--coordinator" in capsys.readouterr().err
