"""Pallas fused dense kernels: parity with the jnp chain (interpret mode
on CPU; the same kernels compile to Mosaic on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.kernels import fcnn_fused_forward, fused_dense
from tpu_dist_nn.kernels.fused_dense import chain_fits_vmem
from tpu_dist_nn.models.fcnn import forward, init_fcnn


def _xw(m, k, n, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.normal(size=(m, k)), jnp.float32),
        jnp.asarray(rng.normal(size=(k, n)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(n,)) * 0.1, jnp.float32),
    )


class TestFusedDense:
    @pytest.mark.parametrize("activation", ["linear", "relu", "sigmoid",
                                            "tanh", "gelu", "softmax"])
    def test_matches_jnp(self, activation):
        from tpu_dist_nn.core.activations import apply_activation

        x, w, b = _xw(32, 24, 16)
        want = np.asarray(apply_activation(x @ w + b, activation))
        got = np.asarray(fused_dense(x, w, b, activation=activation))
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_tiled_grid(self):
        """M and N larger than the block sizes exercise the grid."""
        x, w, b = _xw(300, 64, 200, seed=1)
        want = np.asarray(jnp.maximum(x @ w + b, 0))
        got = np.asarray(
            fused_dense(x, w, b, activation="relu", block_m=128, block_n=128)
        )
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_shape_mismatch_raises(self):
        x, w, b = _xw(8, 12, 6)
        with pytest.raises(ValueError, match="shape mismatch"):
            fused_dense(x, w, jnp.zeros((7,), jnp.float32))


class TestFusedChain:
    def test_matches_unfused_mnist_shape(self):
        """The reference's torch model size (784-128-64-10)."""
        params = init_fcnn(jax.random.key(0), [784, 128, 64, 10])
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.uniform(0, 1, (256, 784)), jnp.float32)
        want = np.asarray(forward(params, x))
        got = np.asarray(fcnn_fused_forward(params, x))
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)
        np.testing.assert_allclose(got.sum(-1), 1.0, atol=1e-5)

    def test_batch_tiling_with_remainder(self):
        params = init_fcnn(jax.random.key(1), [12, 8, 4])
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(70, 12)), jnp.float32)  # 70 % 32 != 0
        want = np.asarray(forward(params, x))
        got = np.asarray(fcnn_fused_forward(params, x, block_b=32))
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)

    def test_vmem_budget_fallback(self):
        """Oversized chains fall back to the jnp path, same numbers."""
        params = init_fcnn(jax.random.key(2), [1024, 1024])
        assert chain_fits_vmem(params)  # 4 MB of weights fits the budget
        big = init_fcnn(jax.random.key(2), [2048, 2048, 1024])
        # (2048*2048 + 2048*1024) * 4B ≈ 25 MB > 8 MB budget
        assert not chain_fits_vmem(big)
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(4, 2048)), jnp.float32)
        want = np.asarray(forward(big, x))
        got = np.asarray(fcnn_fused_forward(big, x))
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)

    def test_explicit_activation_names(self):
        params = init_fcnn(jax.random.key(3), [10, 8, 6],
                           activations=["tanh", "sigmoid"])
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(16, 10)), jnp.float32)
        want = np.asarray(forward(params, x))
        got = np.asarray(
            fcnn_fused_forward(params, x, activations=["tanh", "sigmoid"])
        )
        np.testing.assert_allclose(got, want, atol=2e-5, rtol=1e-4)
