"""Pipelined executor vs. oracle on a virtual multi-device CPU mesh.

The TPU analogue of the reference's end-to-end validation topology
(N containers on one box, SURVEY.md §4): N virtual devices on one host,
stage hand-off via ppermute instead of gRPC.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_dist_nn.core.schema import partition_model
from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
from tpu_dist_nn.parallel.pipeline import (
    build_pipeline_params,
    pipeline_forward,
    pipeline_spec_summary,
)
from tpu_dist_nn.testing.factories import random_inputs, random_model
from tpu_dist_nn.testing.oracle import oracle_forward_batch


def _run(model, distribution, mesh_spec, n=12, microbatches=1, logits=False):
    stages = partition_model(model, distribution)
    params = build_pipeline_params(stages)
    mesh = build_mesh(mesh_spec)
    x = random_inputs(n, model.input_dim, seed=42)
    out = pipeline_forward(
        mesh, params, x, num_microbatches=microbatches, logits=logits
    )
    return np.asarray(out), x


def test_four_stage_pipeline_matches_oracle():
    # 784-32-16-10-ish shape at test scale: uneven widths across stages.
    model = random_model([20, 12, 8, 6, 4], seed=0)
    got, x = _run(model, [1, 1, 1, 1], MeshSpec(stage=4), n=16, microbatches=4)
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_pipeline_with_multiple_layers_per_stage():
    model = random_model([10, 9, 8, 7, 6, 5], seed=1)
    got, x = _run(model, [2, 2, 1], MeshSpec(stage=3), n=8, microbatches=2)
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_stage_count_must_match_mesh():
    model = random_model([10, 8, 6], seed=2)
    stages = partition_model(model, [1, 1])
    params = build_pipeline_params(stages)
    mesh = build_mesh(MeshSpec(stage=4))
    with pytest.raises(ValueError):
        pipeline_forward(mesh, params, random_inputs(4, 10))


def test_data_times_stage_mesh():
    # DP x PP on the same 8 virtual devices: data=2, stage=4.
    model = random_model([20, 12, 8, 6, 4], seed=3)
    got, x = _run(
        model, [1, 1, 1, 1], MeshSpec(stage=4, data=2), n=24, microbatches=3
    )
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_uneven_batch_padding():
    model = random_model([12, 8, 4], seed=4)
    got, x = _run(model, [1, 1], MeshSpec(stage=2), n=7, microbatches=3)
    want = oracle_forward_batch(model, x)
    assert got.shape == (7, 4)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_single_stage_pipeline():
    model = random_model([12, 8, 4], seed=5)
    got, x = _run(model, [2], MeshSpec(stage=1), n=6)
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_empty_stage_is_identity():
    model = random_model([12, 8, 4], seed=6)
    got, x = _run(model, [1, 0, 1], MeshSpec(stage=3), n=6, microbatches=2)
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_logits_variant():
    model = random_model([12, 8, 4], seed=7)
    stages = partition_model(model, [1, 1])
    params = build_pipeline_params(stages)
    mesh = build_mesh(MeshSpec(stage=2))
    x = random_inputs(6, 12, seed=9)
    probs = np.asarray(pipeline_forward(mesh, params, x))
    logits = np.asarray(pipeline_forward(mesh, params, x, logits=True))
    np.testing.assert_allclose(
        np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1)), probs,
        rtol=1e-5, atol=1e-7,
    )


def test_input_dim_validation():
    # The per-forward dim check of the reference (grpc_node.py:83-84),
    # surfaced host-side before compile (SURVEY.md §7 hard part 5).
    model = random_model([12, 8, 4], seed=8)
    stages = partition_model(model, [1, 1])
    params = build_pipeline_params(stages)
    mesh = build_mesh(MeshSpec(stage=2))
    with pytest.raises(ValueError, match="expected input"):
        pipeline_forward(mesh, params, random_inputs(4, 11))


def test_summary():
    model = random_model([20, 12, 8, 6, 4], seed=10)
    params = build_pipeline_params(partition_model(model, [2, 2]))
    s = pipeline_spec_summary(params)
    assert s == {
        "num_stages": 2,
        "layers_per_stage": 2,
        "padded_width": 20,
        "input_dim": 20,
        "output_dim": 4,
    }


def test_eight_stage_pipeline_one_layer_per_core():
    # BASELINE configs[2]: 8-layer MLP, 8-stage pipeline, one dense
    # layer per core — the full virtual mesh as a pure pipeline axis.
    model = random_model([24, 20, 18, 16, 14, 12, 10, 8, 6], seed=11)
    got, x = _run(
        model, [1] * 8, MeshSpec(stage=8), n=16, microbatches=4
    )
    want = oracle_forward_batch(model, x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


def test_eight_stage_training_learns_fashion():
    # End-to-end on the fashion-texture synthetic data: the deep-MLP
    # pipeline must actually train (loss drops) over 8 stages.
    from tpu_dist_nn.data.datasets import synthetic_fashion_mnist
    from tpu_dist_nn.train.pipeline_trainer import train_pipelined
    from tpu_dist_nn.train.trainer import TrainConfig

    data = synthetic_fashion_mnist(256, num_classes=4, dim=24, seed=2)
    model = random_model([24, 20, 18, 16, 14, 12, 10, 8, 4], seed=12)
    params = build_pipeline_params(partition_model(model, [1] * 8))
    mesh = build_mesh(MeshSpec(stage=8))
    cfg = TrainConfig(learning_rate=3e-3, epochs=4, batch_size=64, seed=0)
    trained, history = train_pipelined(
        params, mesh, data, cfg, num_microbatches=2
    )
    assert history[-1]["loss"] < history[0]["loss"]


def test_fuzz_random_models_and_distributions():
    # Randomized widths, stage packings (including empty stages), batch
    # sizes, microbatch counts, and dp degrees — all must match the
    # float64 oracle. The fixed cases above pin known shapes; this
    # sweeps the space.
    rng = np.random.default_rng(7)
    for trial in range(10):
        depth = int(rng.integers(1, 6))
        sizes = [int(rng.integers(2, 24)) for _ in range(depth + 1)]
        model = random_model(sizes, seed=100 + trial)
        # Random packing of `depth` layers into `stages` slots.
        stages = int(rng.integers(1, 5))
        dist = [0] * stages
        for _ in range(depth):
            dist[int(rng.integers(0, stages))] += 1
        # stage*data <= 8 always fits the virtual mesh (build_mesh
        # takes a device subset), so no fix-up needed — stages=3 is
        # genuinely part of the sweep.
        data = int(rng.choice([1, 2]))
        micro = int(rng.choice([1, 2, 3]))
        n = int(rng.integers(1, 20))
        got, x = _run(
            model, dist, MeshSpec(stage=stages, data=data),
            n=n, microbatches=micro,
        )
        want = oracle_forward_batch(model, x)
        np.testing.assert_allclose(
            got, want, rtol=2e-5, atol=1e-6,
            err_msg=f"trial {trial}: sizes={sizes} dist={dist} "
                    f"data={data} micro={micro} n={n}",
        )
