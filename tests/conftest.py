"""Test harness config: run everything on an 8-device virtual CPU mesh.

The TPU analogue of the reference's "N containers on one box" topology
(SURVEY.md §4): multi-device behavior is exercised without hardware via
``--xla_force_host_platform_device_count``.

Note: the environment's sitecustomize imports jax at interpreter startup
(registering the live TPU backend), so setting JAX_PLATFORMS here is too
late — instead we flip the platform with ``jax.config.update`` before
any backend is initialized, and extend XLA_FLAGS (read at backend init,
not at import).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Keep f32 matmuls exact on CPU so oracle-parity tolerances are
# meaningful. NOT under TDN_TEST_TPU=1: the hardware gates measure the
# chip's default-precision MXU path, which this would mask.
if os.environ.get("TDN_TEST_TPU", "0") != "1":
    os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

# TDN_TEST_TPU=1 leaves the live backend in place so the hardware-gated
# tests (test_tpu_hardware.py) can run against the real chip. Only that
# module is meant to run under the flag: the rest of the suite assumes
# the 8-device CPU topology and CPU-exact matmul tolerances.
if os.environ.get("TDN_TEST_TPU", "0") != "1":
    jax.config.update("jax_platforms", "cpu")
# Persistent XLA compile cache: the suite's wall time is dominated by
# recompiling the same shard_map/scan programs every run. Per-user path
# so shared machines don't collide on ownership.
import tempfile  # noqa: E402

_user = os.environ.get("USER") or os.environ.get("LOGNAME") or str(os.getuid())
# The cache key includes a CPU-feature fingerprint: XLA:CPU AOT entries
# compiled on a machine with different vector extensions SIGILL/abort
# when loaded on this one (observed round 5 — "+prefer-no-scatter is
# not supported on the host machine" followed by a fatal abort mid
# suite), and /tmp can outlive a box swap on shared infrastructure.
import hashlib  # noqa: E402

try:
    with open("/proc/cpuinfo") as _f:
        _flags = next(
            (ln for ln in _f if ln.startswith("flags")), ""
        )
    _fp = hashlib.sha1(_flags.encode()).hexdigest()[:8]
except OSError:
    _fp = "nofp"
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(tempfile.gettempdir(), f"tdn_jax_cache_{_user}_{_fp}"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import pytest  # noqa: E402

# ---------------------------------------------------------------------
# Quick tier (VERDICT r4 weak item 6: the full suite costs 12-35 min
# depending on box load; driver/judge boxes need a fast gate).
#
#   python -m pytest tests/ -m quick -q        # every family, < 5 min
#   python -m pytest tests/ -q                 # the full suite
#
# Curated representatives per module: the core parity/behavior test of
# each family plus its cheapest validation test, chosen from the
# round-5 `--durations=0` run. An entry is a bare test name (all
# parametrizations) or an exact id with brackets (that one case). "*"
# marks every test in the module (used for the TPU-gated hardware
# module, which skips without hardware either way).
# tests/test_quick_tier.py asserts every module has an entry and every
# entry resolves, so the list cannot rot silently.
QUICK_TESTS = {
    "test_autoscale": [
        # ISSUE 12 acceptance smokes: the 2->3->2 loopback scale
        # drill under a faults.py-paced burst (zero dropped), the
        # one-tick burn->spawn control-loop anchor, hedging's
        # first-reply-wins contract + the loopback straggler rescue,
        # the POST /router/scale override, and the bench_gate
        # skip/fail contract for autoscale_replica_seconds_ratio.
        "test_autoscale_smoke_fleet_scales_up_and_back_down",
        "test_synthetic_burn_scales_up_within_one_tick",
        "test_hedge_fires_once_first_reply_wins_loser_cancelled",
        "test_hedge_rescues_straggler_over_loopback_wire",
        "test_manual_scale_override_via_post_route_and_status_route",
        "test_bench_gate_autoscale_ratio_skip_and_fail"],
    "test_batcher_pipeline": [
        "test_batches_launch_while_prior_fetch_in_flight",
        "test_warm_buckets_ladder_gauge_and_no_misses_after_warm",
        "test_bench_overlap_smoke_overlapped_at_least_serial"],
    "test_checkpoint": ["test_async_manager_saves_and_restores",
                        "test_manager_latest_and_retention",
                        "test_resume_noop_when_complete"],
    "test_continuous": [
        "test_continuous_matches_static_greedy_tokens",
        "test_serve_continuous_loopback_parity_and_counters",
        "test_gen_ab_smoke_continuous_beats_static",
        # ISSUE 7: prefix-cache bit parity is the correctness anchor,
        # the shared-prefix A/B smoke the perf gate.
        "test_prefix_cache_greedy_bit_parity_including_eos",
        "test_gen_prefix_smoke_cache_on_beats_off"],
    "test_conv": ["test_conv_forward_matches_oracle",
                  "test_engine_routes_conv_model"],
    "test_conv_kernel": ["test_conv_matches_lax[stride1-same]",
                         "test_shape_mismatch_rejected"],
    "test_data": ["test_synthetic_dataset_shapes_and_range"],
    "test_engine_cli": ["test_cli_up_smoke", "test_cli_oracle"],
    "test_errors_multihost": [
        "test_engine_down_then_unavailable_then_relaunch"],
    "test_examples": ["test_centralized_experiments_on_real_digits"],
    "test_expert_parallel": ["test_ep_forward_matches_grouped_oracle[4-2]",
                             "test_top2_training_learns"],
    "test_fastloader": ["test_gather_rows_threads_and_big_batch"],
    "test_fleet_obs": [
        # ISSUE 9 quick smokes: /slo + /timeseries endpoints and the
        # 2-process loopback stitched trace (single trace_id, spans
        # from both processes, lanes named by process).
        "test_slo_endpoint_and_gauges_smoke",
        "test_timeseries_endpoint_smoke",
        "test_two_process_loopback_stitched_trace"],
    "test_flash_attention": ["test_forward_matches_reference[32-False]",
                             "test_rejects_mismatched_shapes"],
    "test_incident": [
        # ISSUE 11 acceptance smokes: the loopback burn->bundle path,
        # the 2-replica stitched fleet drill (+ tdn incident/debug
        # CLI), both crash-path subprocess proofs, and the armed-vs-
        # disarmed overhead A/B with its bench_gate contract.
        "test_burn_detector_captures_bundle_with_faulted_span",
        "test_fleet_drill_burn_trips_router_recorder_stitched_bundle",
        "test_crash_unhandled_exception_leaves_valid_bundle",
        "test_crash_sigabrt_leaves_valid_bundle_then_dies_by_signal",
        "test_incident_overhead_smoke_armed_within_noise",
        "test_bench_gate_incident_ratio_skip_and_fail"],
    "test_forward_parity": ["test_forward_matches_oracle_small",
                            "test_softmax_stability"],
    "test_generate": ["test_greedy_generation_matches_teacher_forced_oracle",
                      "test_pipeline_generate_matches_single_chip",
                      "test_tp_generate_greedy_matches_single_chip"],
    # ISSUE 14: goodput conservation on the loopback wire (odd rows
    # forced into pow2 buckets, useful+pad==total exactly, /goodput
    # shares sum to 1), iteration-level continuous accounting + prefix
    # savings, the timeseries families across a counter reset, the tdn
    # top MFU/pad column in both modes + the --iterations CI path, the
    # bench_gate serving_mfu/serving_pad_ratio contract, and the
    # armed-vs-disarmed accounting overhead A/B.
    "test_goodput": [
        "test_loopback_serving_pad_accounting_exact",
        "test_continuous_scheduler_conservation_and_prefix_savings",
        "test_static_generate_accounting_eos_frozen_exact",
        "test_timeseries_goodput_families_and_counter_reset",
        "test_top_renders_mfu_pad_columns_fleet_and_single",
        "test_cli_top_iterations_reads_goodput_from_live_endpoint",
        "test_bench_gate_serving_mfu_and_pad_ratio_skip_and_fail",
        "test_goodput_overhead_smoke_accounting_within_noise",
        "test_peak_calibration_is_shared_with_bench"],
    "test_graft_entry": ["test_entry_is_jittable",
                         "test_dryrun_multichip_odd_device_count"],
    "test_hetero_pipeline": ["test_forward_matches_single_program"],
    "test_interleaved": ["test_schedule_tables_build_and_verify",
                         "test_interleaved_lm_grads_match_single_chip"],
    # ISSUE 19 acceptance smokes: the bit-flip fingerprint detector,
    # the numeric guard's row-level failover bit-parity anchor, canary
    # golden stability across prober restarts, the full quarantine
    # lifecycle against two real replicas (detect -> drain-refusal ->
    # evidence -> reverify-readmit -> strikes -> break-glass), the
    # spot-check tamper arbitration, and the end-to-end quick-scaled
    # corruption drill.
    "test_integrity": [
        "test_array_checksum_and_fingerprint_detect_bitflip",
        "test_guard_partial_rows_failover_bit_parity",
        "test_canary_golden_stable_across_prober_restarts",
        "test_quarantine_lifecycle_detect_drain_refusal_evidence_reverify",
        "test_spotcheck_tamper_mismatch_arbitrates_to_guilty_replica",
        "test_corruption_drill_scenario_quarantines_exactly_one"],
    "test_interop": ["test_torch_round_trip", "test_torch_forward_parity"],
    "test_interop_keras": ["test_keras_forward_parity",
                           "test_keras_round_trip"],
    "test_kernels": ["test_matches_jnp[relu]", "test_shape_mismatch_raises"],
    "test_multihost_real": ["test_two_process_collectives"],
    "test_native_codec": ["test_examples_roundtrip_and_parity",
                          "test_fuzz_model_roundtrip_native_vs_python"],
    "test_obs": ["test_counter_gauge_histogram_basics",
                 "test_render_text_format_and_round_trip",
                 "test_loopback_serving_metrics_and_healthz",
                 "test_prometheus_exposition_conformance"],
    "test_optimizers": ["test_default_is_exactly_adam",
                        "test_warmup_ramps_learning_rate",
                        "test_grad_accum_no_update_until_k_steps"],
    "test_pipeline": ["test_four_stage_pipeline_matches_oracle",
                      "test_input_dim_validation"],
    "test_pipeline_1f1b": [
        "test_1f1b_matches_gpipe_grads[dims4-distribution4-3-1-1-3]",
        "test_1f1b_rejects_unknown_schedule"],
    "test_pipeline_ep": ["test_pp_ep_validates_batch_divisibility",
                         "test_pp_ep_shard_roundtrip",
                         "test_pp_ep_1f1b_grads_match_grouped_oracle[2-2-1-2]"],
    "test_pipeline_sp": ["test_pp_sp_forward_matches_single_chip[2-2-2-ulysses]",
                         "test_pp_sp_validates_divisibility",
                         "test_ring_collective_rotation_matches_ppermute"],
    "test_pipeline_tp": ["test_pp_tp_forward_matches_single_chip[2-2-2]",
                         "test_pp_tp_shard_roundtrip"],
    "test_pipeline_tp_sp": [
        "test_pp_tp_sp_1f1b_grads_match_single_chip[ulysses]"],
    "test_profile": [
        # The ISSUE-6 quick-tier smokes: loopback /profile shares sum
        # to the measured root wall, and tools/bench_gate.py runs the
        # checked-in r04->r05 pair report-only plus a synthetic failing
        # pair in enforce mode.
        "test_loopback_profile_process_shares_sum_to_wall",
        "test_bench_gate_report_only_on_checked_in_rounds",
        "test_bench_gate_enforce_fails_synthetic_regression",
        # ISSUE 10: best-of-history mode must fail the checked-in
        # r02->r05 host-fed drift that pairwise diffing waved through.
        "test_bench_gate_history_fails_checked_in_host_fed_drift"],
    "test_profiling": ["test_latency_stats_summary",
                       "test_annotate_inside_jit"],
    "test_quantized": ["test_weight_quantization_roundtrip_error_bounded",
                       "test_quantized_forward_close_to_f32",
                       "test_quantize_honors_metadata_distribution"],
    # ISSUE 18 acceptance smokes: generator determinism, the
    # incident-bundle -> WorkloadTrace -> replay round trip (exact mix
    # + per-decile arrival fidelity over a live loopback fleet), the
    # seeded-probability fault mode, the stream-resume bound at its
    # exact boundary, one quick-scaled scenario verdict, and the
    # bench_gate scenario_pass_ratio skip/fail contract.
    "test_replay": [
        "test_generators_deterministic_and_well_formed",
        "test_fault_plan_probability_mode_deterministic_under_seed",
        "test_bundle_round_trip_exact_mix_and_arrival_deciles",
        "test_stream_resume_bound_boundary_and_overflow_counter",
        "test_scenario_quick_smoke_deterministic_verdict",
        "test_bench_gate_scenario_pass_ratio_skip_and_fail"],
    "test_router": [
        # ISSUE 8: the loopback p2c smoke (spread + tdn_router_*
        # family on /metrics), the breaker-registry-eviction
        # regression, and the router_rps gate skip/fail contract.
        "test_router_loopback_spreads_load_and_exposes_metrics",
        "test_pool_remove_evicts_breaker_registry_for_reused_address",
        "test_bench_gate_router_rps_skip_and_fail"],
    "test_resilience": [
        "test_chaos_smoke_quick_tier_recovers_via_retries",
        "test_breaker_cycle_closed_open_half_open_closed",
        "test_shed_at_watermark_surfaces_resource_exhausted"],
    # ISSUE 15 acceptance smokes: the 2x-overload degradation drill
    # (critical completes, best_effort absorbs the sheds), the
    # real-model preemption bit-parity anchor, class-watermark sheds
    # + deadline expiry on the shared core, the retry-after floor over
    # a real loopback shed, the router class hop, and the bench_gate
    # slo_class_critical_p99_ms skip/fail contract.
    "test_sched_core": [
        "test_overload_drill_critical_holds_best_effort_absorbs",
        "test_preempted_greedy_generate_bit_matches_unpreempted",
        "test_class_watermark_sheds_best_effort_first",
        "test_expired_entry_fails_deadline_exceeded_at_pop_without_launch",
        "test_shed_reply_carries_retry_after_and_client_honors_floor",
        "test_router_forwards_class_and_server_labels_it",
        "test_bench_gate_slo_class_critical_p99_skip_and_fail"],
    "test_real_data": ["test_real_digits_load_shapes_and_content",
                       "test_realtext_corpus_supports_valid_heldout_at_scale",
                       "test_cli_train_digits_end_to_end"],
    "test_ring_attention": ["test_matches_full_attention",
                            "test_gradients_match"],
    "test_schema": ["test_model_json_round_trip",
                    "test_shipped_sample_configs_load_and_run"],
    "test_serving": ["test_codec_round_trip",
                     "test_grpc_round_trip_matches_local",
                     "test_serve_generate_single_chip_and_validation"],
    # ISSUE 16 streaming smokes: frame codec + TokenStream channel
    # invariants (pure host logic, milliseconds), the loopback
    # router-hop stream (first token delivered BEFORE retirement,
    # tokens bit-identical to unary through the same hop), and the
    # hedging exemption contract.
    "test_stream": [
        "test_frame_codec_roundtrips_and_rejects_garbage",
        "test_token_stream_cursor_dedupes_replayed_prefix",
        "test_stream_first_token_before_retirement_through_router",
        "test_hedge_policy_rejects_generate_stream"],
    # ISSUE 13: the tdn lint gate in both directions — zero
    # non-baselined findings on the shipped tree, exit 1 on a planted
    # violation, each rule firing on its fixture with the exact id and
    # line — plus the bench_gate report-header integration.
    "test_tdnlint": [
        "test_rule_fires_on_violating_fixture",
        "test_rule_silent_on_clean_twin",
        "test_shipped_tree_is_clean_via_tdn_lint_cli",
        "test_tdn_lint_exits_nonzero_on_planted_violation",
        "test_bench_gate_report_only_mentions_lint_status"],
    "test_tensor_parallel": ["test_forward_matches_single_chip[spec1]",
                             "test_shard_roundtrip"],
    "test_tpu_hardware": ["*"],
    # ISSUE 10: the codec fast lane's correctness anchor (byte-exact
    # scalar/vectorized equivalence + fuzz agreement), the decode-into-
    # staging path through a real batcher, the codec A/B perf smoke,
    # and the loopback fast-path counter check.
    "test_wire_codec": [
        "test_encode_vectorized_matches_scalar_bytes_exactly",
        "test_decode_fuzz_fast_and_scalar_agree_on_mutated_bytes",
        "test_batcher_stages_wire_matrices_straight_into_bucket_buffer",
        "test_bench_wire_smoke_vectorized_beats_scalar",
        "test_loopback_serving_round_trip_rides_fast_path"],
    "test_trace": ["test_chrome_trace_export_schema",
                   "test_loopback_round_trip_is_one_trace_tree",
                   "test_sampling_rate_edge_cases"],
    "test_train": ["test_single_chip_training_learns",
                   "test_train_lm_does_not_invalidate_caller_params"],
    "test_transformer": ["test_loss_descends_on_copy_task",
                         "test_pipeline_matches_single_chip",
                         "test_load_corpus_prefers_vendored_real_then_explicit"],
    "test_zb_v": ["test_zb_v_tables_build_and_verify",
                  "test_zb_v_beats_same_granularity_schedules",
                  "test_zb_v_grads_match_single_chip[2-2-2]"],
    "test_zero": ["test_opt_state_actually_sharded",
                  "test_shardings_prefer_largest_divisible_axis"],
    "test_zero_bubble": ["test_zb_tables_build_and_verify",
                         "test_zb_halves_the_1f1b_bubble",
                         "test_zb_train_step_runs",
                         "test_zb_stash_grads_match_single_chip[2-1-4]"],
    "test_split_backward": ["*"],
    "test_quick_tier": ["*"],
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "quick: fast representative tier — every family in < 5 min "
        "(run with `-m quick`; see conftest.QUICK_TESTS)",
    )


def pytest_collection_modifyitems(config, items):
    for item in items:
        module = os.path.basename(str(item.fspath))[:-3]
        entries = QUICK_TESTS.get(module, ())
        name = item.name
        bare = name.split("[")[0]
        for entry in entries:
            if entry == "*" or entry == name or (
                "[" not in entry and entry == bare
            ):
                item.add_marker(pytest.mark.quick)
                break
