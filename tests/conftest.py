"""Test harness config: run everything on an 8-device virtual CPU mesh.

The TPU analogue of the reference's "N containers on one box" topology
(SURVEY.md §4): multi-device behavior is exercised without hardware via
``--xla_force_host_platform_device_count``.

Note: the environment's sitecustomize imports jax at interpreter startup
(registering the live TPU backend), so setting JAX_PLATFORMS here is too
late — instead we flip the platform with ``jax.config.update`` before
any backend is initialized, and extend XLA_FLAGS (read at backend init,
not at import).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Keep f32 matmuls exact on CPU so oracle-parity tolerances are
# meaningful. NOT under TDN_TEST_TPU=1: the hardware gates measure the
# chip's default-precision MXU path, which this would mask.
if os.environ.get("TDN_TEST_TPU", "0") != "1":
    os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

# TDN_TEST_TPU=1 leaves the live backend in place so the hardware-gated
# tests (test_tpu_hardware.py) can run against the real chip. Only that
# module is meant to run under the flag: the rest of the suite assumes
# the 8-device CPU topology and CPU-exact matmul tolerances.
if os.environ.get("TDN_TEST_TPU", "0") != "1":
    jax.config.update("jax_platforms", "cpu")
# Persistent XLA compile cache: the suite's wall time is dominated by
# recompiling the same shard_map/scan programs every run. Per-user path
# so shared machines don't collide on ownership.
import tempfile  # noqa: E402

_user = os.environ.get("USER") or os.environ.get("LOGNAME") or str(os.getuid())
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(tempfile.gettempdir(), f"tdn_jax_cache_{_user}"),
)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
