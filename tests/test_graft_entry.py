"""The driver hooks must stay importable and runnable on a CPU mesh."""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_is_jittable():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (64, 10)


def test_dryrun_multichip_eight_devices():
    graft.dryrun_multichip(8)


def test_dryrun_multichip_odd_device_count():
    graft.dryrun_multichip(1)
