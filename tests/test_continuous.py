"""Continuous-batching decode scheduler (serving/continuous.py):
static-scheduler output parity, slot reuse, per-request budgets,
admission/close semantics, observability, the loopback endpoint, the
staggered-arrival static-vs-continuous A/B smoke, and the KV-reuse
layer — prefix-cache bit parity / refcount lifecycle / COW isolation,
chunked-prefill parity, mid-prefill faults, drain with half-prefilled
slots, and the shared-prefix A/B smoke."""

import threading
import time

import jax
import jax.numpy as jnp  # noqa: F401 — parity helpers
import numpy as np
import pytest

from tpu_dist_nn.models.generate import generate
from tpu_dist_nn.models.transformer import (
    TransformerConfig,
    init_transformer,
)
from tpu_dist_nn.serving.continuous import ContinuousScheduler

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=4, n_layers=3, d_ff=64, max_seq_len=48
)
PARAMS = init_transformer(jax.random.key(11), CFG)
T, N = 8, 10


def _prompts(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, CFG.vocab_size, (n, T))


def _sched(**kw):
    kw.setdefault("slots", 4)
    kw.setdefault("prompt_len", T)
    kw.setdefault("max_new_tokens", N)
    return ContinuousScheduler(PARAMS, CFG, **kw)


def _fake_sched(step_cost=0.0, chunk_cost=0.0, **kw):
    """Cost-model scheduler (no device work): the deterministic arm of
    the admission/close/shed/prefix-lifecycle tests. ``chunk_cost`` is
    per prefill-chunk TOKEN (chunked prefill pays proportionally to
    the tokens it actually runs)."""

    def fake_prefill(params, cache, slot, tokens, start, key):
        if chunk_cost:
            time.sleep(chunk_cost * tokens.shape[1])
        return np.int32(1), cache

    def fake_step(params, cache, pos, active, tok, key):
        if step_cost:
            time.sleep(step_cost)
        return np.asarray(tok) + 1, cache

    kw.setdefault("slots", 2)
    kw.setdefault("prompt_len", T)
    kw.setdefault("max_new_tokens", N)
    return ContinuousScheduler(
        None, None, prefill_fn=fake_prefill, step_fn=fake_step, **kw
    )


# ------------------------------------------------------------ parity


def test_continuous_matches_static_greedy_tokens():
    # The acceptance core: temperature=0 outputs are identical between
    # the two schedulers — INCLUDING eos early-retire/pad semantics —
    # with more rows than slots (so queueing + slot reuse are on the
    # path) and requests arriving both as one multi-row submit and as
    # concurrent single rows.
    prompts = _prompts(6, seed=1)
    base = np.asarray(generate(PARAMS, CFG, prompts, N))
    eos = int(base[0, N // 2])
    ref = np.asarray(generate(PARAMS, CFG, prompts, N, eos_id=eos))
    want = np.concatenate([prompts, ref], axis=1)

    sched = _sched(slots=4, eos_id=eos)
    try:
        out = sched.submit(prompts)
        np.testing.assert_array_equal(out, want)
        # Same prompts again as concurrent one-row requests.
        outs = [None] * 6

        def call(i):
            outs[i] = sched.submit(prompts[i:i + 1])

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(6)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for i in range(6):
            np.testing.assert_array_equal(outs[i][0], want[i])
        assert sched.retired_total == 12
        assert sched.rows_total == 12
    finally:
        sched.close()


def test_slot_reuse_does_not_leak_stale_kv():
    # One slot, sequential occupants: every sequence must equal its
    # fresh single-row decode — occupant k's K/V cannot contaminate
    # occupant k+1 (the prefill overwrites the slot's full extent and
    # attention masks beyond the frontier).
    prompts = _prompts(3, seed=2)
    sched = _sched(slots=1)
    try:
        for i in range(3):
            out = sched.submit(prompts[i:i + 1])
            ref = np.asarray(generate(PARAMS, CFG, prompts[i:i + 1], N))
            np.testing.assert_array_equal(out[0, T:], ref[0])
    finally:
        sched.close()


def test_per_request_budget_caps_and_pads():
    prompts = _prompts(2, seed=3)
    ref = np.asarray(generate(PARAMS, CFG, prompts, N))
    sched = _sched(slots=2, eos_id=None)
    try:
        out = sched.submit(prompts, max_new_tokens=3)
        # The 3 requested tokens match the full decode's first 3; the
        # rest of the static-width row is pad (0 without an eos_id).
        np.testing.assert_array_equal(out[:, T:T + 3], ref[:, :3])
        assert (out[:, T + 3:] == 0).all()
        with pytest.raises(ValueError, match="max_new_tokens"):
            sched.submit(prompts, max_new_tokens=N + 1)
        with pytest.raises(ValueError, match="shape"):
            sched.submit(np.zeros((1, T + 2), np.int32))
    finally:
        sched.close()


def test_zero_row_submit_returns_empty_without_touching_the_loop():
    # A (0, T) submit must answer immediately (the static batcher
    # round-trips empty matrices too) — queueing it would hand the loop
    # a rowless item that corrupts the pending ledger and kills the
    # scheduler thread.
    sched = _sched(slots=2)
    try:
        out = sched.submit(np.zeros((0, T), np.int32))
        assert out.shape == (0, T + N)
        assert sched.pending_rows == 0 and sched.requests_total == 0
        # The scheduler is still fully alive for real work.
        ref = np.asarray(generate(PARAMS, CFG, _prompts(1, seed=12), N))
        np.testing.assert_array_equal(
            sched.submit(_prompts(1, seed=12))[0, T:], ref[0]
        )
    finally:
        sched.close()


def test_sampled_generation_fresh_and_in_vocab():
    # temperature > 0: repeated identical prompts draw fresh
    # continuations (per-event key folds), everything stays in-vocab.
    prompts = np.full((2, T), 5)
    sched = _sched(slots=2, temperature=1.0, seed=3)
    try:
        a = sched.submit(prompts)
        b = sched.submit(prompts)
        assert not np.array_equal(a, b)
        assert (a[:, T:] >= 0).all() and (a[:, T:] < CFG.vocab_size).all()
    finally:
        sched.close()


def test_scheduler_validates_contract_at_construction():
    with pytest.raises(ValueError, match="slots"):
        _sched(slots=0)
    with pytest.raises(ValueError, match="max_seq_len"):
        _sched(max_new_tokens=CFG.max_seq_len)
    with pytest.raises(ValueError, match="top_k"):
        _sched(temperature=0.0, top_k=5)
    with pytest.raises(ValueError, match="eos_id"):
        _sched(eos_id=CFG.vocab_size)
    with pytest.raises(ValueError, match="together"):
        ContinuousScheduler(
            None, None, slots=1, prompt_len=T, max_new_tokens=N,
            prefill_fn=lambda *a: None,
        )


# ------------------------------------------------------------ observability


def test_metrics_ttft_occupancy_and_sampler_gauges():
    from tpu_dist_nn.obs import RuntimeSampler
    from tpu_dist_nn.obs.registry import REGISTRY

    def total(name, label=None):
        m = REGISTRY.get(name)
        if m is None:
            return 0.0
        # samples() keys are label-VALUE tuples ((value,) here).
        return float(sum(
            c.value for k, c in m.samples()
            if label is None or tuple(k) == (label,)
        ))

    tok0 = total("tdn_gen_tokens_total")
    eos_retired0 = total("tdn_gen_requests_retired_total", "eos")
    max_retired0 = total("tdn_gen_requests_retired_total", "max_tokens")
    prompts = _prompts(4, seed=4)
    base = np.asarray(generate(PARAMS, CFG, prompts, N))
    eos = int(base[0, N // 2])
    sched = _sched(slots=2, eos_id=eos)
    try:
        sched.submit(prompts)
        # TTFT recorded per row, and the histogram family moved.
        assert len(sched.ttft_recent) == 4
        m = REGISTRY.get("tdn_gen_ttft_seconds")
        assert m is not None
        # Retire reasons: row 0 hit the stop token, so the eos counter
        # moved; tokens counter moved by every emitted token.
        assert total("tdn_gen_requests_retired_total", "eos") > eos_retired0
        assert total("tdn_gen_requests_retired_total",
                     "max_tokens") >= max_retired0
        assert total("tdn_gen_tokens_total") > tok0
        # The runtime sampler publishes the slot gauges.
        sampler = RuntimeSampler()
        sampler.add_generation_scheduler(sched)
        sampler.add_batcher(sched, method="Generate")
        sampler.sample_once()
        occ = REGISTRY.get("tdn_gen_slot_occupancy_ratio")
        assert occ is not None
        vals = {tuple(k): c.value for k, c in occ.samples()}
        assert 0.0 < list(vals.values())[0] <= 1.0
        assert REGISTRY.get("tdn_gen_slots_active") is not None
        assert sched.slot_steps_total <= sched.steps_total * sched.slots
    finally:
        sched.close()


def test_traced_request_records_prefill_and_decode_spans():
    from tpu_dist_nn.obs.trace import TRACER

    span = TRACER.start("rpc.Generate")
    assert span.ctx.sampled
    sched = _sched(slots=2)
    try:
        sched.submit(_prompts(1, seed=5), ctx=span.ctx)
    finally:
        span.end()
        sched.close()
    names = {
        s.name for s in TRACER.snapshot()
        if s.trace_id == span.ctx.trace_id
    }
    assert {"queue_wait", "prefill", "decode.step", "decode"} <= names


# ------------------------------------------------------------ admission


def test_shed_at_watermark_and_oversized_admitted_when_empty():
    from tpu_dist_nn.utils.errors import ResourceExhaustedError

    # One slow slot: the first request occupies it for ~budget * cost
    # seconds, so later arrivals deterministically queue behind it.
    sched = _fake_sched(step_cost=0.05, slots=1, max_pending_rows=2)
    outs, errs = [], []

    def call(rows):
        try:
            outs.append(sched.submit(rows))
        except Exception as e:  # noqa: BLE001 — collected
            errs.append(e)

    try:
        t1 = threading.Thread(target=call, args=(_prompts(1, seed=6),))
        t1.start()
        deadline = time.monotonic() + 5
        while sched.rows_total < 1 and time.monotonic() < deadline:
            time.sleep(0.001)  # row 1 resident in the slot
        # 3 rows against an EMPTY queue: oversized vs the watermark but
        # admitted anyway (the watermark bounds backlog, not size).
        t2 = threading.Thread(target=call, args=(_prompts(3, seed=7),))
        t2.start()
        deadline = time.monotonic() + 5
        while sched.pending_rows < 3 and time.monotonic() < deadline:
            time.sleep(0.001)
        # Now the queue is past the watermark: the next submit sheds.
        with pytest.raises(ResourceExhaustedError, match="watermark"):
            sched.submit(_prompts(1, seed=8))
        assert sched.shed_total == 1
        t1.join(30)
        t2.join(30)
        assert len(outs) == 2 and not errs
    finally:
        sched.close()


def test_close_fails_pending_over_and_post_close_submit_raises():
    from tpu_dist_nn.utils.errors import UnavailableError

    sched = _fake_sched(step_cost=0.05, slots=1)
    errs, oks = [], []

    def caller(i):
        try:
            oks.append(sched.submit(_prompts(1, seed=i)))
        except Exception as e:  # noqa: BLE001 — collected
            errs.append(e)

    threads = [
        threading.Thread(target=caller, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    time.sleep(0.08)  # first request resident, rest pending
    sched.close()
    for t in threads:
        t.join(20)
    # Resident work finished; still-pending waiters failed over.
    assert len(oks) >= 1
    assert len(errs) >= 1
    assert all(isinstance(e, UnavailableError) for e in errs)
    with pytest.raises(UnavailableError):
        sched.submit(_prompts(1, seed=9))


# ------------------------------------------------------------ endpoint


def test_serve_continuous_loopback_parity_and_counters():
    from tpu_dist_nn.serving import GrpcClient, serve_lm_generate

    prompts = _prompts(5, seed=10)
    base = np.asarray(generate(PARAMS, CFG, prompts, 6))
    eos = int(base[0, 2])
    ref = np.asarray(generate(PARAMS, CFG, prompts, 6, eos_id=eos))
    server, port = serve_lm_generate(
        PARAMS, CFG, 0, max_new_tokens=6, prompt_len=T, host="127.0.0.1",
        gen_slots=3, eos_id=eos, warm_rows=1,
    )
    try:
        assert server.scheduler is not None  # auto => continuous
        client = GrpcClient(f"127.0.0.1:{port}")
        out = client.generate(prompts)
        np.testing.assert_array_equal(out[:, :T], prompts)
        np.testing.assert_array_equal(out[:, T:], ref)
        s = server.scheduler
        assert s.rows_total == 5 and s.retired_total == 5
        assert s.steps_total == s.batches_total > 0
        client.close()
    finally:
        server.stop(0)
    # stop() closed the scheduler: its loop thread is gone.
    assert not server.scheduler._thread.is_alive()


def test_serve_scheduler_flag_validation():
    from tpu_dist_nn.serving import serve_lm_generate

    with pytest.raises(ValueError, match="single-chip"):
        serve_lm_generate(
            PARAMS, CFG, 0, max_new_tokens=4, prompt_len=T,
            num_stages=2, scheduler="continuous", host="127.0.0.1",
        )
    with pytest.raises(ValueError, match="scheduler"):
        serve_lm_generate(
            PARAMS, CFG, 0, max_new_tokens=4, prompt_len=T,
            scheduler="orca", host="127.0.0.1",
        )
    with pytest.raises(ValueError, match="eos_id"):
        serve_lm_generate(
            PARAMS, CFG, 0, max_new_tokens=4, prompt_len=T,
            num_stages=2, eos_id=3, host="127.0.0.1",
        )
    # coalesce=False keeps its documented lock-path meaning: auto
    # resolves to static (server.batcher is None), and an EXPLICIT
    # continuous request rejects the combination.
    with pytest.raises(ValueError, match="coalesce"):
        serve_lm_generate(
            PARAMS, CFG, 0, max_new_tokens=4, prompt_len=T,
            scheduler="continuous", coalesce=False, host="127.0.0.1",
        )
    server, _port = serve_lm_generate(
        PARAMS, CFG, 0, max_new_tokens=4, prompt_len=T,
        coalesce=False, host="127.0.0.1",
    )
    try:
        assert server.scheduler is None and server.batcher is None
    finally:
        server.stop(0)


def test_cli_lm_flags_validated_eagerly():
    from tpu_dist_nn.cli import main

    # Bad eos byte id fails before any training happens.
    assert main([
        "--platform", "cpu", "lm", "--steps", "1", "--eos-id", "300",
    ]) != 0
    # Continuous x pipelined serving is rejected up front.
    assert main([
        "--platform", "cpu", "lm", "--steps", "1",
        "--serve-generate", "0", "--serve-stages", "2",
        "--scheduler", "continuous",
    ]) != 0
    # eos through the pipelined serve placement is rejected up front.
    assert main([
        "--platform", "cpu", "lm", "--steps", "1",
        "--serve-generate", "0", "--serve-stages", "2",
        "--eos-id", "0",
    ]) != 0


def test_cli_warmup_lm_generation_kernels(capsys):
    import json

    from tpu_dist_nn.cli import main

    rc = main([
        "--platform", "cpu", "warmup", "--lm", "--d-model", "16",
        "--heads", "2", "--layers", "2", "--seq-len", "24",
        "--gen-slots", "2", "--serve-prompt-len", "6",
        "--serve-new-tokens", "4",
    ])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["warmed_kernels"] == [
        "prefill_chunk_into_cache", "decode_step_slots"
    ]
    assert report["gen_slots"] == 2
    # Without --lm, the engine path still requires --config.
    assert main(["--platform", "cpu", "warmup"]) != 0


# ------------------------------------------------ prefix cache + chunking


def _shared_prefix_prompts(n, header_len, seed=20):
    """Prompts sharing an exact ``header_len``-token header with unique
    tails — the workload shape the prefix pool exists for."""
    rng = np.random.default_rng(seed)
    header = rng.integers(0, CFG.vocab_size, header_len)
    return np.stack([
        np.concatenate([header, rng.integers(0, CFG.vocab_size, T - header_len)])
        for _ in range(n)
    ]).astype(np.int32)


def test_prefix_cache_greedy_bit_parity_including_eos():
    # THE acceptance anchor: temperature=0 outputs bit-identical with
    # prefix cache + chunked prefill ON vs OFF — including EOS
    # early-retire/pad semantics — on prompts that actually share a
    # header (so the ON arm really serves hits, asserted below), with
    # more rows than slots so queueing and slot reuse are on the path.
    prompts = _shared_prefix_prompts(6, header_len=4)
    base = np.asarray(generate(PARAMS, CFG, prompts, N))
    eos = int(base[0, N // 2])
    want = np.asarray(generate(PARAMS, CFG, prompts, N, eos_id=eos))

    off = _sched(slots=2, eos_id=eos)
    on = _sched(slots=2, eos_id=eos, prefix_cache_blocks=3, prefill_chunk=4)
    try:
        out_off = off.submit(prompts)
        # Sequential single-row submits on the ON arm so later rows
        # deterministically hit the tiers the first row inserted.
        rows_on = [on.submit(prompts[i:i + 1])[0] for i in range(6)]
        np.testing.assert_array_equal(out_off[:, T:], want)
        for i in range(6):
            np.testing.assert_array_equal(rows_on[i][T:], want[i])
        assert on.prefix_hits_total >= 4  # rows 2.. hit the header tier
        assert on.prefix_misses_total >= 1
        assert off.prefix_hits_total == 0 and off.prefix_blocks == 0
    finally:
        off.close()
        on.close()


def test_chunked_prefill_parity_with_monolithic():
    # Chunk sizes that divide T, don't divide T, and exceed T must all
    # produce the monolithic scheduler's exact greedy tokens.
    prompts = _prompts(3, seed=21)
    ref = np.asarray(generate(PARAMS, CFG, prompts, N))
    for chunk in (1, 3, T, T + 5):
        sched = _sched(slots=2, prefill_chunk=chunk)
        try:
            out = sched.submit(prompts)
            np.testing.assert_array_equal(out[:, T:], ref)
        finally:
            sched.close()


def test_cow_isolation_decode_never_mutates_shared_block():
    # A hit COPIES the pool block into the request slot; the decoding
    # request then writes only its own slot. The block's bytes must be
    # bit-identical before and after other requests decode FROM it —
    # and a later hit must still produce exact outputs.
    prompts = _shared_prefix_prompts(3, header_len=6, seed=22)
    prompts[1:] = prompts[0]  # identical prompts: deepest-tier hits
    ref = np.asarray(generate(PARAMS, CFG, prompts[:1], N))
    sched = _sched(slots=1, prefix_cache_blocks=1, prefill_chunk=4)
    try:
        out0 = sched.submit(prompts[0:1])
        np.testing.assert_array_equal(out0[0, T:], ref[0])
        assert sched.prefix_blocks_used == 1
        block_slot = sched.slots  # pool block 0 lives at slot index S
        k_before = np.asarray(sched._cache["k"][:, block_slot]).copy()
        v_before = np.asarray(sched._cache["v"][:, block_slot]).copy()
        out1 = sched.submit(prompts[1:2])  # hit: COW copy + decode
        np.testing.assert_array_equal(out1[0, T:], ref[0])
        assert sched.prefix_hits_total == 1
        np.testing.assert_array_equal(
            np.asarray(sched._cache["k"][:, block_slot]), k_before
        )
        np.testing.assert_array_equal(
            np.asarray(sched._cache["v"][:, block_slot]), v_before
        )
        out2 = sched.submit(prompts[2:3])  # still exact after reuse
        np.testing.assert_array_equal(out2[0, T:], ref[0])
    finally:
        sched.close()


def test_prefix_pool_refcount_lifecycle():
    from tpu_dist_nn.serving.continuous import PrefixCachePool

    pool = PrefixCachePool(2)
    b0, ev = pool.insert(b"aa", 4)
    assert (b0, ev) == (0, False) and pool.used == 1
    # A hit takes a reference; a referenced block is never evicted.
    hit = pool.lookup([(4, b"aa")])
    assert hit == (0, 4) and pool.refs(0) == 1 and pool.hits_total == 1
    b1, _ = pool.insert(b"bb", 4)
    assert b1 == 1
    blk, ev = pool.insert(b"cc", 4)  # full: only refcount-0 "bb" evicts
    assert ev and blk == 1 and pool.evictions_total == 1
    assert pool.lookup([(4, b"bb")]) is None  # evicted
    assert pool.misses_total == 1
    pool.release(0)  # release "aa"
    assert pool.refs(0) == 0
    blk, ev = pool.insert(b"dd", 4)  # now "aa" (LRU refcount-0) evicts
    assert ev and blk == 0
    with pytest.raises(AssertionError):
        pool.release(0)  # unreferenced: double-release is a bug
    # All blocks referenced -> insertion skipped, no eviction.
    pool.lookup([(4, b"cc")])
    pool.lookup([(4, b"dd")])
    assert pool.insert(b"ee", 4) == (None, False)
    with pytest.raises(AssertionError):
        pool.clear()  # live refs: clear would strand them
    pool.release(1)
    pool.release(0)
    pool.clear()
    assert pool.used == 0 and pool.hits_total == 3  # counters survive


def test_prefix_metrics_counters_and_sampler_gauge():
    from tpu_dist_nn.obs import RuntimeSampler
    from tpu_dist_nn.obs.registry import REGISTRY

    def total(name):
        m = REGISTRY.get(name)
        return 0.0 if m is None else float(
            sum(c.value for _, c in m.samples())
        )

    hits0 = total("tdn_prefix_cache_hits_total")
    miss0 = total("tdn_prefix_cache_misses_total")
    sched = _fake_sched(slots=1, prefix_cache_blocks=1, prefill_chunk=4)
    try:
        p = _prompts(1, seed=23)
        sched.submit(p)           # miss + tier insert
        sched.submit(p)           # deepest-tier hit
        assert sched.prefix_misses_total == 1
        assert sched.prefix_hits_total == 1
        assert sched.prefix_blocks_used == 1
        assert 0.0 < sched.prefix_hit_ratio < 1.0
        assert total("tdn_prefix_cache_hits_total") == hits0 + 1
        assert total("tdn_prefix_cache_misses_total") == miss0 + 1
        sampler = RuntimeSampler()
        sampler.add_generation_scheduler(sched)
        sampler.add_batcher(sched, method="Generate")
        sampler.sample_once()
        g = REGISTRY.get("tdn_prefix_cache_blocks_used")
        assert g is not None
        assert [c.value for _, c in g.samples()] == [1.0]
    finally:
        sched.close()


def test_prefill_chunk_spans_recorded_and_profiled():
    from tpu_dist_nn.obs.profile import profile_snapshot
    from tpu_dist_nn.obs.trace import TRACER

    span = TRACER.start("rpc.Generate")
    sched = _sched(slots=1, prefill_chunk=3)
    try:
        sched.submit(_prompts(1, seed=24), ctx=span.ctx)
    finally:
        span.end()
        sched.close()
    mine = [
        s for s in TRACER.snapshot() if s.trace_id == span.ctx.trace_id
    ]
    names = {s.name for s in mine}
    assert {"queue_wait", "prefill", "prefill.chunk", "decode.step",
            "decode"} <= names
    # ceil(8 / 3) chunks, each its own span, joined to the request trace.
    assert sum(1 for s in mine if s.name == "prefill.chunk") == 3
    # The /profile stage table picks the new span up as a stage.
    prof = profile_snapshot(TRACER)
    stages = {
        s["stage"] for s in prof["methods"]["Generate"]["stages"]
    }
    assert "prefill.chunk" in stages


def test_mid_prefill_fault_frees_slot_and_releases_ref():
    from tpu_dist_nn.testing import faults
    from tpu_dist_nn.utils.errors import InternalError

    # T=8, chunk=3: request 1 runs chunks 1-3 (inserting tiers 3 and
    # 6); request 2 hits tier 6 and its single suffix chunk is call 4
    # — which the plan faults. The fault must fail ONLY that request,
    # free its slot, and release its block reference so the pool can
    # evict again.
    sched = _fake_sched(slots=1, prefix_cache_blocks=2, prefill_chunk=3)
    sched.prefill_hook = faults.FaultPlan(at={4: faults.internal()}).fire
    p = _prompts(1, seed=25)
    try:
        sched.submit(p)
        assert sched.prefix_blocks_used == 2
        with pytest.raises(InternalError):
            sched.submit(p)
        assert sched.prefix_hits_total == 1
        assert sched.inflight_rows == 0  # slot freed
        assert all(
            sched._pool.refs(b) == 0 for b in range(sched.prefix_blocks)
        )  # the hit's reference was released
        # The scheduler keeps serving (call 5+ passes).
        out = sched.submit(p)
        assert out.shape == (1, T + N)
        assert sched.prefix_hits_total == 2
    finally:
        sched.close()


def test_drain_with_half_prefilled_slot_completes():
    # close() must let a slot that is MID-PREFILL finish its remaining
    # chunks and decode (the GracefulDrain in-flight contract), not
    # strand or fail it.
    sched = _fake_sched(chunk_cost=0.03, slots=1, prefill_chunk=2)
    outs, errs = [], []

    def caller():
        try:
            outs.append(sched.submit(_prompts(1, seed=26)))
        except Exception as e:  # noqa: BLE001 — collected
            errs.append(e)

    t = threading.Thread(target=caller)
    t.start()
    deadline = time.monotonic() + 5
    while sched.inflight_rows < 1 and time.monotonic() < deadline:
        time.sleep(0.002)  # bound to a slot, prefill still chunking
    assert sched.inflight_rows == 1
    sched.close(timeout=30.0)
    t.join(30)
    assert not errs and len(outs) == 1
    assert outs[0].shape == (1, T + N)
    assert sched.retired_total == 1


def test_scheduler_validates_prefix_chunk_contract():
    with pytest.raises(ValueError, match="prefill_chunk"):
        _fake_sched(prefill_chunk=0)
    with pytest.raises(ValueError, match="prefix_cache_blocks"):
        _fake_sched(prefix_cache_blocks=-1)
    # No cacheable tier: chunk spans the whole prompt, so the pool
    # could never hit — fail fast instead of reserving dead blocks.
    with pytest.raises(ValueError, match="cacheable tier"):
        _fake_sched(prefix_cache_blocks=1, prefill_chunk=T)
    # copy_fn only makes sense alongside the other injected kernels.
    with pytest.raises(ValueError, match="copy_fn"):
        ContinuousScheduler(
            PARAMS, CFG, slots=1, prompt_len=T, max_new_tokens=N,
            copy_fn=lambda cache, src, dst: cache,
        )


def test_serve_rejects_prefix_flags_on_static_scheduler():
    from tpu_dist_nn.serving import serve_lm_generate

    with pytest.raises(ValueError, match="continuous-scheduler"):
        serve_lm_generate(
            PARAMS, CFG, 0, max_new_tokens=4, prompt_len=T,
            scheduler="static", prefix_cache_blocks=2, host="127.0.0.1",
        )
    with pytest.raises(ValueError, match="continuous-scheduler"):
        serve_lm_generate(
            PARAMS, CFG, 0, max_new_tokens=4, prompt_len=T,
            coalesce=False, prefill_chunk=4, host="127.0.0.1",
        )


def test_serve_loopback_with_prefix_cache_exact_and_accounted():
    from tpu_dist_nn.serving import GrpcClient, serve_lm_generate

    prompts = _shared_prefix_prompts(4, header_len=6, seed=27)
    ref = np.asarray(generate(PARAMS, CFG, prompts, 6))
    server, port = serve_lm_generate(
        PARAMS, CFG, 0, max_new_tokens=6, prompt_len=T, host="127.0.0.1",
        gen_slots=2, warm_rows=1, prefix_cache_blocks=2, prefill_chunk=4,
    )
    try:
        client = GrpcClient(f"127.0.0.1:{port}")
        out = np.vstack([
            client.generate(prompts[i:i + 1]) for i in range(4)
        ])
        np.testing.assert_array_equal(out[:, T:], ref)
        s = server.scheduler
        assert s.prefix_hits_total >= 2  # shared header served from pool
        assert s.prefix_blocks_used >= 1
        client.close()
    finally:
        server.stop(0)


# ------------------------------------------------------------ A/B smoke


def test_gen_ab_smoke_continuous_beats_static():
    """The quick-tier CI gate for ISSUE 5's acceptance criterion, in
    the controlled per-step-cost regime (both arms pay an identical
    deterministic per-decode-step cost, so the measured delta is pure
    scheduling policy): under staggered arrivals with mixed budgets,
    continuous batching must beat the run-to-completion control arm on
    throughput AND p99 latency — and report TTFT."""
    from bench import gen_ab_bench

    # Structural expectation (not a timing race): on a 4-wide device,
    # run-to-completion needs >= ceil(16/4) batches x 33 step-costs
    # = 528ms of decode, while iteration-level scheduling needs
    # ~(8*2 + 8*32)/4 steps + 16 prefills ~ 84 step-costs = 336ms —
    # a >= 1.5x structural margin before any convoy penalty, which is
    # what makes the >= assertions robust to CI box jitter.
    ab = gen_ab_bench(
        None, slots=4, requests=16, prompt_len=T, max_new=32,
        short_budget=2, arrival_gap_s=0.005, controlled_step_cost=0.004,
    )
    c, s = ab["continuous"], ab["static"]
    assert c["rps"] >= s["rps"], ab
    assert c["p99_ms"] < s["p99_ms"], ab
    # TTFT is measured and (continuous) decoupled from full latency.
    assert c["ttft_p50_ms"] < c["p50_ms"]
    assert s["ttft_p99_ms"] == s["p99_ms"]  # run-to-completion
    assert c["retired"] == 16
    assert 0.0 < c["slot_occupancy"] <= 1.0


def test_gen_prefix_smoke_cache_on_beats_off():
    """The quick-tier CI gate for ISSUE 7's acceptance criterion, in
    the controlled per-token-cost regime (prefill cost proportional to
    the tokens actually run, identical on both arms, so the measured
    delta is pure KV-reuse policy): on the shared-prefix workload,
    prefix-cache + chunked-prefill ON must beat OFF on throughput AND
    TTFT p99, serve a real hit ratio, and hold TTFT p99 FLATTER as
    prompt length grows (the chunked-prefill claim — the uncached
    remainder is constant by construction)."""
    from bench import gen_prefix_bench

    # Structural expectation (not a timing race): prompts share all but
    # 4 tail tokens, so once the pool is warm a hit prefills <= chunk+
    # tail tokens where the OFF arm prefills all T — at T=32 that is
    # ~32 vs ~12 step-costs of prefill per request, a >= 2x margin on
    # the prefill share before any decode-stall effect.
    ab = gen_prefix_bench(
        None, slots=4, requests=12, prompt_lens=(16, 32), tail_tokens=4,
        chunk=8, blocks=4, max_new=8, arrival_gap_s=0.004,
        controlled_cost_per_token=0.002,
    )
    assert ab["rps"] >= ab["off_rps"], ab
    assert ab["ttft_p99_ms"] < ab["off_ttft_p99_ms"], ab
    assert ab["prefix_hit_ratio"] > 0.5, ab
    # Flatness: the ON arm's TTFT p99 grows STRICTLY slower with prompt
    # length than the control's.
    assert ab["ttft_growth_on"] < ab["ttft_growth_off"], ab
    per = ab["per_prompt_len"]
    for T_ in per:
        assert per[T_]["on"]["prefix_hit_ratio"] > 0.5, per[T_]
