"""Scenario engine (ISSUE 18: obs/replay.py + scenarios/ + tdn replay):
seeded workload generators (bit-deterministic), the incident-bundle ->
WorkloadTrace -> replay round trip (exact request mix, session pinning,
per-decile arrival fidelity), FaultPlan's seeded-probability mode, the
stream-resume metadata bound at its exact boundary (router ledger +
replica backstop), a quick-scaled scenario verdict smoke, and the
bench_gate scenario_pass_ratio skip/fail contract."""

import os
import sys

import grpc
import numpy as np
import pytest

from tpu_dist_nn.obs import replay as R
from tpu_dist_nn.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------- generators


def test_generators_deterministic_and_well_formed():
    # Same seed -> byte-identical trace JSON; different seed differs.
    # Arrivals are sorted and stay inside the declared duration for
    # every registered generator (the scenario files lean on both).
    for gen in sorted(R.GENERATORS):
        a = R.make_workload(gen, seed=42, requests=24, duration=4.0)
        b = R.make_workload(gen, seed=42, requests=24, duration=4.0)
        assert a.to_json() == b.to_json(), gen
        c = R.make_workload(gen, seed=43, requests=24, duration=4.0)
        assert a.to_json() != c.to_json(), gen
        assert len(a.requests) == 24, gen
        arrivals = [r.arrival_s for r in a.requests]
        assert arrivals == sorted(arrivals), gen
        assert all(0.0 <= t <= 4.0 for t in arrivals), gen


def test_trace_json_and_file_round_trip(tmp_path):
    t = R.make_workload("heavy_tail", seed=5, requests=12, duration=2.0,
                        stream_fraction=0.5)
    again = R.WorkloadTrace.from_json(t.to_json())
    assert again.mix() == t.mix()
    # to_json rounds arrival offsets (microsecond-ish) — deciles agree
    # to far better than the 5 ms decile floor.
    assert again.inter_arrival_deciles() == pytest.approx(
        t.inter_arrival_deciles(), abs=1e-5)
    p = str(tmp_path / "trace.json")
    t.save(p)
    assert R.WorkloadTrace.load(p).to_json() == t.to_json()
    # Heavy-tail really is heavy-tailed: prompt lengths spread past
    # the minimum, and the streaming fraction survived.
    lens = {r.prompt_len for r in t.requests}
    assert len(lens) > 1
    assert any(r.stream for r in t.requests)


# --------------------------------------------- FaultPlan seeded p mode


def test_fault_plan_probability_mode_deterministic_under_seed():
    def sequence(seed, calls=80):
        plan = faults.FaultPlan(p=0.2, fault=faults.unavailable(),
                                seed=seed)
        return [plan.next_fault() is not None for _ in range(calls)]

    a, b = sequence(7), sequence(7)
    assert a == b, "same seed must reproduce the same storm"
    assert any(a) and not all(a)
    assert sequence(8) != a, "different seed, different storm"
    # Mixed plan: at= hits land exactly where named, and the rng draw
    # happens on EVERY call, so the probabilistic hits are the same
    # whether or not a deterministic hit already decided the call.
    mixed = faults.FaultPlan(at={3: faults.delay(0.0)}, p=0.2,
                             fault=faults.unavailable(), seed=7)
    got = [mixed.next_fault() for _ in range(80)]
    assert got[2] is not None and got[2].kind == "delay"
    assert [f is not None for f in got[:2]] == a[:2]
    assert [f is not None for f in got[3:]] == a[3:]


def test_fault_plan_p_validation():
    with pytest.raises(ValueError):
        faults.FaultPlan(p=1.5, fault=faults.unavailable())
    with pytest.raises(ValueError):
        faults.FaultPlan(p=0.1)  # p= needs fault=


# ------------------------------------------- capture -> replay fidelity


def test_bundle_round_trip_exact_mix_and_arrival_deciles():
    # The acceptance core: drive a seeded mixed-class workload at a
    # live loopback fleet, capture a REAL incident bundle, extract the
    # WorkloadTrace back out of trace.json — the request mix must match
    # EXACTLY (methods, classes, shapes, sessions, streams) and every
    # inter-arrival decile must land within 10%.
    from tpu_dist_nn.obs.incident import capture_bundle
    from tpu_dist_nn.obs.trace import TRACER

    original = R.make_workload("mixed_class", seed=9, requests=16,
                               duration=2.5, sessions=4)
    fleet = R.LoopbackFleet(replicas=2, per_row_ms=0.5)
    try:
        fleet.start()
        cursor = TRACER.chrome_trace(limit=1)["cursor"]
        report = R.replay(original, fleet.target, speed=1.0)
        doc = TRACER.chrome_trace(since=cursor)
        _, bundle = capture_bundle(
            "test_round_trip", reason="round-trip test",
            tracer=R._FrozenTracer(doc),
        )
    finally:
        fleet.stop()
    assert report["ok"] == len(original.requests)
    # The replay driver itself paced faithfully (sent-vs-trace decile
    # error is part of every replay report).
    assert report["arrival"]["max_decile_error"] <= 0.10
    extracted = R.trace_from_bundle(bundle)
    assert extracted.source.startswith("bundle:")
    assert extracted.mix() == original.mix()
    errs = R.decile_errors(original.inter_arrival_deciles(),
                           extracted.inter_arrival_deciles())
    assert errs and max(errs) <= 0.10

    # Session pinning survives the wire: per-session request counts in
    # the extracted trace equal the original's.
    def per_session(t):
        out = {}
        for r in t.requests:
            out[r.session] = out.get(r.session, 0) + 1
        return out

    assert per_session(extracted) == per_session(original)


def test_capture_attrs_survive_fleet_trace_stitching():
    # The capture satellite end-to-end at the doc level: handler root
    # spans' request attrs ride chrome-trace args VERBATIM through
    # stitch_chrome_traces, so a router's stitched trace_fleet.json is
    # just as replayable as a single process's trace.json.
    from tpu_dist_nn.obs.collect import stitch_chrome_traces
    from tpu_dist_nn.obs.trace import TRACER

    original = R.make_workload("mixed_class", seed=21, requests=10,
                               duration=1.5, sessions=3)
    fleet = R.LoopbackFleet(replicas=2, per_row_ms=0.5)
    try:
        fleet.start()
        cursor = TRACER.chrome_trace(limit=1)["cursor"]
        R.replay(original, fleet.target, speed=2.0)
        doc = TRACER.chrome_trace(since=cursor)
    finally:
        fleet.stop()
    stitched = stitch_chrome_traces({"router:9100": doc})
    extracted = R.trace_from_chrome(stitched)
    assert extracted.mix() == original.mix()


# ------------------------------------------------ stream-resume bound


def test_stream_resume_bound_boundary_and_overflow_counter():
    # Exactly AT the bound the metadata-borne resume path still works
    # (the router's failover ledger and the replica both accept 1024
    # tokens); ONE past it the router refuses with a clear OUT_OF_RANGE
    # + the overflow counter, and the replica backstops hand-rolled
    # clients with the same status.
    from tpu_dist_nn.serving.router import ROUTER_STREAM_RESUME_OVERFLOW
    from tpu_dist_nn.serving.wire import (
        GENERATE_STREAM_METHOD,
        STREAM_RESUME_HEADER,
        STREAM_RESUME_MAX_TOKENS,
        decode_frame,
        encode_matrix,
    )

    def drain(call_iter):
        toks = []
        for f in call_iter:
            kind, data = decode_frame(f)
            if kind == "tokens":
                toks.extend(data)
        return toks

    extra = 6
    fleet = R.LoopbackFleet(
        replicas=1, max_new_tokens=STREAM_RESUME_MAX_TOKENS + extra,
        per_token_ms=0.0, prefill_ms=0.0,
    )
    try:
        fleet.start()
        prompt = encode_matrix(
            np.zeros((1, fleet.prompt_len), dtype=np.int64))
        at_bound = ",".join(["1"] * STREAM_RESUME_MAX_TOKENS)
        past_bound = at_bound + ",1"
        ch = grpc.insecure_channel(fleet.target)
        stream = ch.unary_stream(GENERATE_STREAM_METHOD,
                                 request_serializer=bytes,
                                 response_deserializer=bytes)
        # 1024 delivered tokens: resume accepted, only the unseen
        # suffix flows.
        toks = drain(stream(
            prompt, timeout=20.0,
            metadata=((STREAM_RESUME_HEADER, at_bound),)))
        assert len(toks) == extra
        # 1025: the router abandons failover-resume loudly.
        before = sum(
            c.value for _, c in ROUTER_STREAM_RESUME_OVERFLOW.samples())
        with pytest.raises(grpc.RpcError) as ei:
            drain(stream(
                prompt, timeout=20.0,
                metadata=((STREAM_RESUME_HEADER, past_bound),)))
        assert ei.value.code() == grpc.StatusCode.OUT_OF_RANGE
        assert "restart the stream" in ei.value.details()
        after = sum(
            c.value for _, c in ROUTER_STREAM_RESUME_OVERFLOW.samples())
        assert after == before + 1
        ch.close()
        # Replica backstop: the bound holds even without the router in
        # front (a hand-rolled client talking straight to a replica).
        ch2 = grpc.insecure_channel(fleet.targets[0])
        direct = ch2.unary_stream(GENERATE_STREAM_METHOD,
                                  request_serializer=bytes,
                                  response_deserializer=bytes)
        with pytest.raises(grpc.RpcError) as ei:
            drain(direct(
                prompt, timeout=20.0,
                metadata=((STREAM_RESUME_HEADER, past_bound),)))
        assert ei.value.code() == grpc.StatusCode.OUT_OF_RANGE
        assert str(STREAM_RESUME_MAX_TOKENS) in ei.value.details()
        ch2.close()
    finally:
        fleet.stop()


# -------------------------------------------------- scenario verdicts


def test_scenario_quick_smoke_deterministic_verdict():
    # The quick-tier replay smoke: one checked-in scenario at quick
    # scale produces a machine-readable PASS verdict, and the verdict
    # is deterministic where it must be (request mix under the seed).
    path = os.path.join(REPO, "scenarios", "diurnal_baseline.json")
    v = R.run_scenario_file(path, quick_scale=0.4)
    assert v["passed"] is True
    assert v["scenario"] == "diurnal_baseline" and v["seed"] == 101
    assert v["objectives"], "SLO verdicts must be embedded"
    for o in v["objectives"]:
        assert o["passed"] == (o["burn_rate"] <= 1.0)
    v2 = R.run_scenario_file(path, quick_scale=0.4)
    assert v2["workload"] == v["workload"], "seeded mix must reproduce"


def test_scenario_dir_has_full_matrix():
    # The checked-in matrix the bench embeds: at least 8 cells, at
    # least 3 distinct generators, at least 2 with fault crossings,
    # and at least one bundle-derived (capture) cell.
    paths = R.scenario_paths(os.path.join(REPO, "scenarios"))
    assert len(paths) >= 8
    gens, faulted, captured = set(), 0, 0
    for p in paths:
        spec = R.load_scenario(p)
        wl = spec["workload"]
        if "capture" in wl:
            captured += 1
            gens.add(wl["capture"]["generator"])
        else:
            gens.add(wl["generator"])
        if spec.get("fleet", {}).get("faults") or spec.get("chaos"):
            faulted += 1
    assert len(gens) >= 3
    assert faulted >= 2
    assert captured >= 1


def test_bench_gate_scenario_pass_ratio_skip_and_fail():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_gate
    finally:
        sys.path.pop(0)

    def round_doc(ratio=None):
        doc = {"backend": "cpu", "value": 100000.0, "serving": {}}
        if ratio is not None:
            doc["serving"]["scenarios"] = {"pass_ratio": ratio}
        return doc

    # Pre-ISSUE-18 previous round: the row skips, nothing fails.
    verdict = bench_gate.compare(round_doc(), round_doc(1.0))
    rows = {m["metric"]: m for m in verdict["metrics"]}
    assert "skipped" in rows["scenario_pass_ratio"]
    assert not verdict["regressions"]
    # A cell newly failing its SLO verdict drops the ratio past the
    # threshold and fails the enforced gate.
    verdict = bench_gate.compare(round_doc(1.0), round_doc(0.75))
    assert "scenario_pass_ratio" in verdict["regressions"]
    verdict = bench_gate.compare(round_doc(1.0), round_doc(1.0))
    assert not verdict["regressions"]
