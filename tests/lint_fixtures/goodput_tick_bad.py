"""tick-purity fixture (violating twin, goodput flavor): the goodput
tracker's tick is a RuntimeSampler callback (``add_goodput``) — peak
calibration is a real matmul-and-wait and must never ride it. This
twin proves the add_goodput registration verb is in the analyzer's
tick protocol, so an accounting hook can never regress the PR-13
gate silently."""

import time


class GoodputPlane:
    def tick(self):
        self._recalibrate_peak()

    def _recalibrate_peak(self):
        time.sleep(0.2)  # <- violation


def wire(sampler):
    sampler.add_goodput(GoodputPlane())
