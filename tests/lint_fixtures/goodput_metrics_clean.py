"""metric-series-lifecycle fixture (clean twin, goodput flavor): the
shipped goodput families key on CLOSED label spaces (``kind`` in
{useful, pad}, ``path`` in {batcher, gen, engine}) — no churn, no
lifecycle obligation; a per-replica fleet exporter retires departed
replicas' series."""


class FleetGoodputExporter:
    def __init__(self, reg):
        # Closed label spaces: no remove needed, and none demanded.
        self._flops = reg.counter(
            "tdn_goodput_flops_total", "useful vs pad model FLOPs",
            labels=("kind",),
        )
        self._pad = reg.gauge(
            "tdn_pad_ratio", "pad share per accounting path",
            labels=("path",),
        )
        # Churning label space: retired on membership changes.
        self._mfu = reg.gauge(
            "tdn_mfu_ratio_per_replica",
            "per-replica MFU scraped from the fleet",
            labels=("replica",),
        )

    def publish(self, target, value):
        self._mfu.labels(replica=target).set(value)

    def retire(self, target):
        self._mfu.remove(replica=target)
