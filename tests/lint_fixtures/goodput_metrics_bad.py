"""metric-series-lifecycle fixture (violating twin, goodput flavor):
a goodput exporter keyed per REPLICA with no series retirement — fleet
churn would grow the label set forever. (The real goodput families key
on ``kind``/``path`` — closed label spaces — exactly so they carry no
lifecycle obligation; the clean twin shows both shapes.)"""


class FleetGoodputExporter:
    def __init__(self, reg):
        self._mfu = reg.gauge(  # <- violation
            "tdn_mfu_ratio_per_replica",
            "per-replica MFU scraped from the fleet",
            labels=("replica",),
        )

    def publish(self, target, value):
        self._mfu.labels(replica=target).set(value)
