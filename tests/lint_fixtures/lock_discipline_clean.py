"""lock-discipline fixture (clean twin): every access under the lock,
plus the ``# caller-holds:`` escape for helpers whose callers lock."""

import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.balance = 0  # guarded-by: _lock

    def deposit(self, amount):
        with self._lock:
            self._apply(amount)

    def _apply(self, amount):  # caller-holds: _lock
        self.balance += amount

    def peek(self):
        with self._lock:
            return self.balance
