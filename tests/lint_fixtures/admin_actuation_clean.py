"""admin-actuation fixture (clean twin): reads mount on GET, the
state-changing verb moves to the POST surface."""


def admin_routes(pool):
    def replicas(query):
        return 200, "application/json", b"[]\n"

    return {"/router/replicas": replicas}


def admin_post_routes(pool):
    def drain(query):
        ok = pool.drain("127.0.0.1:5101")
        return 200, "application/json", (
            b'{"ok": true}\n' if ok else b'{"ok": false}\n'
        )

    return {"/router/drain": drain}


def mount(server, pool):
    server.add_routes(admin_routes(pool))
    server.add_post_routes(admin_post_routes(pool))
