"""jit-purity fixture (clean twin): randomness through jax.random with
an explicit key, per-call output through jax.debug.print, timing done
by the CALLER around the compiled function."""

import jax
import jax.numpy as jnp


@jax.jit
def noisy_step(x, key):
    jitter = jax.random.uniform(key)
    jax.debug.print("stepping {x}", x=x)
    return x * jitter


@jax.jit
def counting_step(x, calls):
    return x, calls + jnp.ones_like(calls)
