"""admin-actuation fixture (violating twin): a state-changing verb on
a GET route — the PR 12 bug where a scraper sweeping the admin surface
could drain the fleet."""


def admin_routes(pool):
    def replicas(query):
        return 200, "application/json", b"[]\n"

    def drain(query):
        ok = pool.drain("127.0.0.1:5101")  # <- violation
        return 200, "application/json", (
            b'{"ok": true}\n' if ok else b'{"ok": false}\n'
        )

    return {"/router/replicas": replicas, "/router/drain": drain}


def mount(server, pool):
    server.add_routes(admin_routes(pool))
