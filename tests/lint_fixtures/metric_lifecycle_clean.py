"""metric-series-lifecycle fixture (clean twin): the defining module
retires a departed target's series on the membership-churn path."""

from tpu_dist_nn.obs.registry import REGISTRY

OUTSTANDING = REGISTRY.gauge(
    "fixture_replica_outstanding",
    "requests in flight per replica",
    labels=("replica",),
)


def on_request(target):
    OUTSTANDING.labels(replica=target).inc()


def on_replica_removed(target):
    OUTSTANDING.remove(replica=target)
