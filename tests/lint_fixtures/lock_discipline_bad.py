"""lock-discipline fixture (violating twin): a guarded attribute read
outside its lock — the pool/respawn race class PRs 8/12 hand-caught."""

import threading


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self.balance = 0  # guarded-by: _lock

    def deposit(self, amount):
        with self._lock:
            self.balance += amount

    def peek(self):
        return self.balance  # <- violation
