"""tick-purity fixture (clean twin): the tick only DECIDES; blocking
actuation runs on its own thread (the Autoscaler._spawn_one pattern)."""

import threading
import time


class Autopilot:
    def tick(self):
        threading.Thread(
            target=self._actuate, name="autopilot-actuate", daemon=True
        ).start()

    def _actuate(self):
        time.sleep(0.5)  # off the tick: runs on the actuation thread


def wire(sampler):
    sampler.add_autoscaler(Autopilot())
