"""metric-series-lifecycle fixture (violating twin): a replica-labeled
family with no pruning — membership churn grows the label set forever
and departed replicas keep exposing their stale last value."""

from tpu_dist_nn.obs.registry import REGISTRY

OUTSTANDING = REGISTRY.gauge(  # <- violation
    "fixture_replica_outstanding",
    "requests in flight per replica",
    labels=("replica",),
)


def on_request(target):
    OUTSTANDING.labels(replica=target).inc()
