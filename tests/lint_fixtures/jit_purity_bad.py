"""jit-purity fixture (violating twin): host-side effects inside a
jitted function run ONCE at trace time and are baked into the compiled
program — the classic silent-wrongness class for kernels."""

import random
import time

import jax

_CALLS = 0


@jax.jit
def noisy_step(x):
    print("stepping", x)  # <- violation
    jitter = random.random()  # <- violation
    t0 = time.time()  # <- violation
    return x * jitter + t0


@jax.jit
def counting_step(x):
    global _CALLS  # <- violation
    _CALLS += 1
    return x
