"""tick-purity fixture (violating twin): blocking actuation directly
on the RuntimeSampler tick — the sampler thread carries the SLO,
autoscale, and incident planes, so one sleep stalls them all."""

import time


class Autopilot:
    def tick(self):
        self._actuate()

    def _actuate(self):
        time.sleep(0.5)  # <- violation


def wire(sampler):
    sampler.add_autoscaler(Autopilot())
