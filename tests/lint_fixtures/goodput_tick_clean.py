"""tick-purity fixture (clean twin, goodput flavor): the real
GoodputTracker shape — calibration happens ONCE at configure time
(engine/scheduler construction), the tick only does ledger math and
gauge sets."""

import time


class GoodputPlane:
    def ensure_peak(self):
        # Configure-time calibration: a real measurement, but never
        # reachable from the sampler tick.
        time.sleep(0.2)

    def tick(self):
        self._mfu = 0.0


def wire(sampler):
    plane = GoodputPlane()
    plane.ensure_peak()
    sampler.add_goodput(plane)
