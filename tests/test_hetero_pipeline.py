"""Heterogeneous (conv/pool/dense) pipeline: per-stage device placement
with non-uniform inter-stage shapes — parity vs the single-program
executor, Engine integration, and guards."""

import jax
import numpy as np
import pytest

from tpu_dist_nn.api.engine import Engine
from tpu_dist_nn.models.network import (
    build_network,
    init_conv_mlp,
    network_forward,
)
from tpu_dist_nn.parallel.hetero_pipeline import HeteroPipeline
from tpu_dist_nn.utils.errors import InvalidArgumentError


@pytest.fixture(scope="module")
def conv_model():
    return init_conv_mlp(
        jax.random.key(0),
        in_shape=(8, 8, 3),
        conv_filters=(4, 8),
        hidden=(16,),
        num_classes=4,
    )


def _x(model, n=12, seed=1):
    rng = np.random.default_rng(seed)
    return rng.uniform(0, 1, (n, model.input_dim)).astype(np.float32)


def test_forward_matches_single_program(conv_model):
    x = _x(conv_model)
    plan, params = build_network(conv_model)
    want = np.asarray(network_forward(plan, params, x))

    n_layers = len(conv_model.layers)
    hp = HeteroPipeline(conv_model, [2, 2, n_layers - 4])
    got = hp.forward(x)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)
    # Microbatched path, ragged tail.
    got_mb = hp.forward(x, microbatch_size=5)
    np.testing.assert_allclose(got_mb, want, rtol=2e-5, atol=1e-6)


def test_stage_devices_are_distinct(conv_model):
    hp = HeteroPipeline(conv_model, [2, len(conv_model.layers) - 2])
    summary = hp.placement_summary()
    assert summary["num_stages"] == 2
    assert summary["stage_devices"][0] != summary["stage_devices"][1]
    assert summary["stage_kinds"][0][0] == "conv2d"


def test_rejects_more_stages_than_devices(conv_model):
    with pytest.raises(ValueError, match="devices"):
        HeteroPipeline(conv_model, [1] * len(conv_model.layers),
                       devices=jax.devices()[:2])


def test_engine_places_conv_pipeline(conv_model):
    n_layers = len(conv_model.layers)
    engine = Engine.up(conv_model, [2, n_layers - 2])
    place = engine.placement()
    assert place["pipelined"] and place["num_stages"] == 2
    assert "stage_devices" in place

    x = _x(conv_model)
    plan, params = build_network(conv_model)
    want = np.asarray(network_forward(plan, params, x))
    np.testing.assert_allclose(engine.infer(x), want, rtol=2e-5, atol=1e-6)

    assert engine.health()["probe_ok"]
    # Empty batch: (0, out_dim), matching every other executor.
    empty = engine.infer(np.zeros((0, conv_model.input_dim)))
    assert empty.shape == (0, 4)
    engine.down()
    from tpu_dist_nn.utils.errors import UnavailableError

    with pytest.raises(UnavailableError):
        engine.infer(x)


def test_engine_trains_hetero_placed_conv_model(conv_model):
    # train() must work regardless of placement: the hetero engine now
    # trains THROUGH the pipeline (per-stage VJPs) and keeps serving
    # the trained weights from the same placement.
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.train.trainer import TrainConfig

    data = synthetic_mnist(
        200, num_classes=4, dim=conv_model.input_dim, noise=0.3, seed=3
    )
    engine = Engine.up(conv_model, [2, len(conv_model.layers) - 2])
    history = engine.train(data, TrainConfig(epochs=2, batch_size=32))
    assert history[-1]["loss"] < history[0]["loss"]
    # Still hetero-placed and serving the TRAINED weights.
    assert "stage_devices" in engine.placement()
    plan_params = engine._hp.stages[0]["params"][0]["w"]
    want = np.asarray(engine.model.layers[0].weights, np.float32)
    np.testing.assert_allclose(np.asarray(plan_params), want, rtol=1e-6)


def test_hetero_pipeline_training_matches_single_program(conv_model):
    # VERDICT r1 weak item 6: conv training through the pipeline. The
    # pipelined schedule (per-stage VJPs, microbatch-mean grads,
    # per-stage Adam) must reproduce the single-program trainer's loss
    # stream and final weights to float tolerance — same loop, same
    # shuffle seeds, same optimizer recipe; only WHERE compute runs
    # differs.
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.parallel.hetero_pipeline import HeteroPipeline, train_hetero
    from tpu_dist_nn.train.trainer import TrainConfig, train_network

    data = synthetic_mnist(
        192, num_classes=4, dim=conv_model.input_dim, noise=0.3, seed=5
    )
    cfg = TrainConfig(epochs=2, batch_size=24, seed=7)

    plan, params = build_network(conv_model)
    ref_params, ref_hist = train_network(plan, params, data, cfg)

    hp = HeteroPipeline(conv_model, [2, 2, len(conv_model.layers) - 4])
    params_list, hist = train_hetero(hp, data, cfg, num_microbatches=3)

    ref_losses = [h["loss"] for h in ref_hist]
    losses = [h["loss"] for h in hist]
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    flat = [p for sp in params_list for p in sp]
    for got, want in zip(flat, ref_params):
        for key in got:
            np.testing.assert_allclose(
                np.asarray(got[key]), np.asarray(want[key]),
                rtol=5e-4, atol=5e-6,
            )
    # The trained weights are installed back into the serving placement.
    x = _x(conv_model)
    np.testing.assert_allclose(
        hp.forward(x),
        np.asarray(network_forward(plan, ref_params, x)),
        rtol=5e-4, atol=5e-6,
    )


def test_hetero_training_global_norm_clipping_matches_single_program(conv_model):
    # clip_norm spans the stages: the hetero step computes the FULL-
    # model gradient norm from per-stage pieces, so a clipped pipelined
    # run must match the single-program clipped trainer. A tight clip
    # forces the clipping branch to actually fire every step.
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.models.network import build_network
    from tpu_dist_nn.parallel.hetero_pipeline import HeteroPipeline, train_hetero
    from tpu_dist_nn.train.trainer import TrainConfig, train_network

    data = synthetic_mnist(96, num_classes=4, dim=conv_model.input_dim, seed=1)
    cfg = TrainConfig(epochs=2, batch_size=24, seed=4, clip_norm=0.05)

    plan, params = build_network(conv_model)
    ref_params, ref_hist = train_network(plan, params, data, cfg)

    hp = HeteroPipeline(conv_model, [2, len(conv_model.layers) - 2])
    params_list, hist = train_hetero(hp, data, cfg, num_microbatches=2)
    np.testing.assert_allclose(
        [h["loss"] for h in hist], [h["loss"] for h in ref_hist], rtol=1e-4
    )
    flat = [p for sp in params_list for p in sp]
    for got, want in zip(flat, ref_params):
        for key in got:
            np.testing.assert_allclose(
                np.asarray(got[key]), np.asarray(want[key]),
                rtol=5e-4, atol=5e-6,
            )


def test_hetero_training_checkpoint_resume(conv_model, tmp_path):
    # Epoch-level save/resume through the pipelined trainer: a fresh
    # pipeline resumed from the checkpoint continues to the same result.
    from tpu_dist_nn.checkpoint import CheckpointManager
    from tpu_dist_nn.data.datasets import synthetic_mnist
    from tpu_dist_nn.parallel.hetero_pipeline import HeteroPipeline, train_hetero
    from tpu_dist_nn.train.trainer import TrainConfig

    data = synthetic_mnist(96, num_classes=4, dim=conv_model.input_dim, seed=2)
    cfg = TrainConfig(epochs=2, batch_size=24, seed=3)

    hp_full = HeteroPipeline(conv_model, [2, len(conv_model.layers) - 2])
    full, _ = train_hetero(hp_full, data, cfg, num_microbatches=2)

    d = tmp_path / "ck"
    hp_a = HeteroPipeline(conv_model, [2, len(conv_model.layers) - 2])
    train_hetero(
        hp_a, data, TrainConfig(epochs=1, batch_size=24, seed=3),
        checkpoints=CheckpointManager(d), num_microbatches=2,
    )
    hp_b = HeteroPipeline(conv_model, [2, len(conv_model.layers) - 2])
    resumed, _ = train_hetero(
        hp_b, data, cfg, checkpoints=CheckpointManager(d), num_microbatches=2,
    )
    for got_sp, want_sp in zip(resumed, full):
        for got, want in zip(got_sp, want_sp):
            for key in got:
                np.testing.assert_allclose(
                    np.asarray(got[key]), np.asarray(want[key]),
                    rtol=1e-5, atol=1e-7,
                )


def test_microbatched_forward_dispatch_overlaps_stages():
    # VERDICT r2 weak item 4: the claimed cross-stage overlap of the
    # microbatched hetero forward, asserted. The host must issue the
    # whole chunk x stage schedule well before results complete
    # (async dispatch): if each stage call blocked, dispatch time would
    # equal the blocked control arm. Wide dense stages make each stage
    # call compute-bound so the ratio is meaningful.
    from tpu_dist_nn.parallel.hetero_pipeline import (
        HeteroPipeline,
        measure_dispatch_overlap,
    )
    from tpu_dist_nn.testing.factories import random_model

    model = random_model([768, 768, 768, 10], seed=0)
    hp = HeteroPipeline(model, [1, 1, 1])
    x = np.random.default_rng(0).uniform(0, 1, (4096, 768)).astype(np.float32)
    m = measure_dispatch_overlap(hp, x, microbatch_size=512)
    assert m["num_chunks"] == 8 and m["num_stages"] == 3
    # Host issues all 24 stage programs in well under the serialized
    # cost (measured ~0.3 on the 1-core box; 0.7 leaves jitter room).
    assert m["dispatch_ratio"] < 0.7, m
    # And the async path is never slower than serialized dispatch.
    assert m["total_s"] < m["blocked_s"] * 1.2, m
