"""Reproducer: ring attention computes wrong values inside the 1F1B
schedule's ``lax.switch`` branches — ROOT CAUSE: ``lax.ppermute``
lowers to collective-permute, whose rendezvous requires EVERY partition
to execute the instruction; devices in a different branch never reach
it.

Context (round 4): composing the 1F1B pipeline schedule with sequence
parallelism works exactly with the Ulysses (all_to_all) decomposition
but NOT with the ring (ppermute K/V rotation), even though the
disjoint-axis rule appears to cover both — the tick predicate is
seq-invariant, so every seq peer takes the same branch at the same
tick, exactly the argument that makes Megatron TP psums work there.

The refinement the failure teaches: branch-safety needs BOTH same-
branch peers AND group-local participation in the collective's
lowering. ``psum``/``all_gather``/``all_to_all`` rendezvous only their
replica group — peers in other branches are irrelevant — while
collective-permute's rendezvous spans every partition in the program.
The smallest demonstration (run separately; it ABORTS the process by
design) is a 2x2 (stage, seq) mesh where stage 0 runs a seq-ppermute
inside one ``lax.cond`` branch and stage 1 takes the other:

    def device_fn(x):
        s = lax.axis_index("stage")
        return lax.cond(
            s == 0,
            lambda v: lax.ppermute(v, "seq", [(0, 1), (1, 0)]),
            lambda v: v * 1.0,
            x,
        )
    # XLA CPU aborts: "collective permute RendezvousKey{...
    # num_local_participants=4 ...} Expected 4 threads to join the
    # rendezvous, but only 2 of them arrived on time."

In the FULL schedule the mismatch does not hang — later ticks' ring
executions from other stages arrive at the same rendezvous — it
silently mis-pairs and produces wrong values. Two observed modes,
demonstrated by this script:

1. ``seq=1`` (the rotation degenerates to a SELF-permute, still a
   collective-permute instruction): the first microbatch's activations
   reach the schedule's tail correctly, every later microbatch's
   arrive as ZEROS. (An UNROLLED ring that skips the final rotation —
   zero ppermutes at N=1 — is exact, isolating the collective.)
2. ``seq>1``: attention outputs are wrong for every microbatch (the
   tail sees |y| magnitudes ~40% off), scan or unrolled alike.

Consequences in the framework: the scheduled executors' own stage
wires ride unconditional ppermutes OUTSIDE the switch (by design);
the in-schedule ring swaps the ppermute rotation for the GROUP-LOCAL
reduce-scatter rotation
(`ring_attention._rotate_one_hop_group_local` — its rendezvous covers
only the seq peers, all in the same branch at the same tick), which
this script demonstrates is exact in the identical position where
ppermute mis-pairs. Run:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python tools/repro_ring_1f1b.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


def main() -> int:
    jax.config.update("jax_platforms", "cpu")

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        dot_product_attention,
        embed,
        init_transformer,
        maybe_remat,
    )
    from tpu_dist_nn.parallel.mesh import AXIS_DATA, AXIS_SEQ, MeshSpec, build_mesh
    from tpu_dist_nn.parallel.one_f_one_b import make_1f1b
    from tpu_dist_nn.parallel.ring_attention import _sp_attn_fn
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=16,
    )
    rng = np.random.default_rng(12)
    tokens = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    params = cfg.cast_params(init_transformer(jax.random.key(11), cfg))
    blocks = shard_blocks(params["blocks"], 2)
    apply = maybe_remat(cfg)
    B, T, M = 8, 16, 2
    ep = {"tok_embed": params["tok_embed"], "pos_embed": params["pos_embed"]}
    xs = embed(ep, tokens).reshape(M, B // M, T, cfg.d_model)
    tgt = jnp.zeros((M, B // M, T), jnp.int32)
    tp = {"tok_embed": params["tok_embed"], "lnf_g": params["lnf_g"],
          "lnf_b": params["lnf_b"]}

    def mk_stage(attn):
        def stage_fn(sb, _st, x):
            def body(c, b):
                return apply(b, c, cfg, attn), None

            return lax.scan(body, x, sb)[0]

        return stage_fn

    def diag_tail(_tp, y, _tgt_f, mask_f):
        # |y| of the microbatch whose mask is live: a probe for WHAT the
        # tail actually received, independent of loss math.
        return jnp.abs(y).sum() * jnp.sign(mask_f.sum())

    def probe(seq, attn, label):
        mesh = build_mesh(MeshSpec(stage=2, seq=seq, data=1))
        mapped = make_1f1b(
            mesh, mk_stage(attn), diag_tail, 2, M,
            microbatch_spec=P(AXIS_DATA, AXIS_SEQ, None),
            aux_spec=P(None, AXIS_DATA, AXIS_SEQ),
        )
        vals = []
        for i in range(M):
            m = jnp.zeros((M, B // M, T), jnp.float32).at[i].set(1.0)
            loss, *_ = mapped(xs, blocks, {}, tp, (tgt, m))
            vals.append(float(loss))
        print(f"  {label}: per-microbatch |y| at the tail = "
              f"{[round(v, 2) for v in vals]}")
        return np.asarray(vals)

    def ring_unrolled(q, k, v, *, causal, axis_name=AXIS_SEQ):
        """Ring attention with a PYTHON loop instead of lax.scan, and
        no rotation after the last block — at N=1 this issues ZERO
        ppermutes (isolating the collective from the scan): exact. At
        N>1 it still issues branch-local ppermutes: still wrong."""
        out_dtype = q.dtype
        _B, Tq, _H, Dh = q.shape
        N = lax.psum(1, axis_name)
        idx = lax.axis_index(axis_name)
        scale = 1.0 / np.sqrt(Dh)
        q32 = q.astype(jnp.float32)
        q_pos = idx * Tq + jnp.arange(Tq)
        ring_perm = [(i, (i + 1) % N) for i in range(N)]
        m = jnp.swapaxes(q32[..., 0], 1, 2) * 0.0 - jnp.inf
        l = jnp.swapaxes(q32[..., 0], 1, 2) * 0.0
        acc = q32 * 0.0
        k_blk, v_blk = k, v
        for s in range(N):
            kv_idx = (idx - s) % N
            scores = jnp.einsum(
                "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
            ) * scale
            if causal:
                k_pos = kv_idx * k_blk.shape[1] + jnp.arange(k_blk.shape[1])
                mask = k_pos[None, :] <= q_pos[:, None]
                scores = jnp.where(mask[None, None], scores, -jnp.inf)
            block_m = jnp.max(scores, axis=-1)
            new_m = jnp.maximum(m, block_m)
            safe_m = jnp.where(jnp.isneginf(new_m), 0.0, new_m)
            p = jnp.exp(scores - safe_m[..., None])
            corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - safe_m))
            l = l * corr + jnp.sum(p, axis=-1)
            acc = acc * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
                "bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32)
            )
            m = new_m
            if s < N - 1:
                k_blk = lax.ppermute(k_blk, axis_name, ring_perm)
                v_blk = lax.ppermute(v_blk, axis_name, ring_perm)
        return (acc / l.transpose(0, 2, 1)[..., None]).astype(out_dtype)

    print("expected (plain attention, any seq): ~[1231.32, 1388.74]")
    ok = probe(1, dot_product_attention, "seq=1 plain    ")
    probe(1, _sp_attn_fn("ring"), "seq=1 ring      (mode 1: zeros)")
    probe(2, _sp_attn_fn("ring"), "seq=2 ring      (mode 2: wrong)")
    un1 = probe(1, ring_unrolled, "seq=1 UNROLLED  (0 ppermutes: exact)")
    probe(2, ring_unrolled, "seq=2 UNROLLED  (ppermutes: still wrong)")
    uly = probe(2, _sp_attn_fn("ulysses"), "seq=2 ulysses   (exact)")
    # THE FIX: the same ring with the group-local reduce-scatter
    # rotation — exact in the exact position ppermute mis-pairs in.
    safe = probe(
        2, _sp_attn_fn("ring", in_schedule=True),
        "seq=2 ring/GROUP-LOCAL rotation (exact — the fix)",
    )
    # Tolerance, not exact equality: reduction order varies with
    # backend/thread configuration at float32.
    assert np.allclose(uly, ok, rtol=1e-4), (
        "ulysses should be exact — reproducer assumptions broken"
    )
    assert np.allclose(un1, ok, rtol=1e-4), (
        "unrolled N=1 (zero ppermutes) should be exact"
    )
    assert np.allclose(safe, ok, rtol=1e-4), (
        "group-local-rotation ring should be exact in-schedule"
    )
    return 0


def rendezvous_proof() -> int:
    """``--rendezvous``: the smallest demonstration of the root cause.

    WARNING: this ABORTS the process by design — XLA's CPU rendezvous
    times out waiting for the partitions that took the other branch:

        collective permute RendezvousKey{... num_local_participants=4
        ...} Expected 4 threads to join the rendezvous, but only 2 of
        them arrived on time.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    jax.config.update("jax_platforms", "cpu")
    mesh = jax.make_mesh((2, 2), ("stage", "seq"))

    def device_fn(x):
        s = lax.axis_index("stage")
        return lax.cond(
            s == 0,
            lambda v: lax.ppermute(v, "seq", [(0, 1), (1, 0)]),
            lambda v: v * 1.0,
            x,
        )

    f = jax.shard_map(device_fn, mesh=mesh, in_specs=P("stage", "seq"),
                      out_specs=P("stage", "seq"))
    print("issuing a seq-ppermute inside a branch only stage 0 takes; "
          "expect the rendezvous abort within ~60s ...")
    print(f(jnp.arange(8.0).reshape(4, 2)))  # never returns cleanly
    return 1  # pragma: no cover — reaching here would disprove the claim


if __name__ == "__main__":
    import sys as _sys

    raise SystemExit(
        rendezvous_proof() if "--rendezvous" in _sys.argv else main()
    )
