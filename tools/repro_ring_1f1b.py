"""Minimal reproducer: ring attention computes wrong values inside the
1F1B schedule's ``lax.switch`` branches.

Context (round 4): composing the 1F1B pipeline schedule with sequence
parallelism works exactly with the Ulysses (all_to_all) decomposition
but NOT with the ring (ppermute-in-scan K/V rotation), even though the
disjoint-axis rule says both should be legal — the tick predicate is
seq-invariant, so every seq peer takes the same branch at the same
tick, exactly the argument that makes Megatron TP psums work there
(probe-verified, parity-tested).

Two reproduced failure modes, both isolated to the ring:

1. ``seq=1`` (the ring degenerates to a SELF-permute): the first
   microbatch's activations reach the schedule's tail correctly, every
   later microbatch's arrive as ZEROS.
2. ``seq>1``: attention outputs are wrong for every microbatch (the
   tail sees |y| magnitudes ~40% off).

Substituting plain attention or Ulysses — same mesh, same specs, same
schedule — gives exact results, so the executor's bookkeeping is not
the suspect; the interaction is specific to a ``ppermute`` inside a
``lax.scan`` inside a ``lax.switch`` branch inside the schedule's
outer ``lax.scan`` under ``shard_map``. Until that interaction is
understood (JAX/XLA level?), ``make_pipeline_sp_lm_1f1b_grad`` rejects
``mode="ring"`` — rejecting beats silently training on wrong
gradients. Run this script to reproduce both modes:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python tools/repro_ring_1f1b.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P


def main() -> int:
    jax.config.update("jax_platforms", "cpu")

    from tpu_dist_nn.models.transformer import (
        TransformerConfig,
        dot_product_attention,
        embed,
        init_transformer,
        maybe_remat,
    )
    from tpu_dist_nn.parallel.mesh import AXIS_DATA, AXIS_SEQ, MeshSpec, build_mesh
    from tpu_dist_nn.parallel.one_f_one_b import make_1f1b
    from tpu_dist_nn.parallel.ring_attention import _sp_attn_fn
    from tpu_dist_nn.parallel.transformer_pipeline import shard_blocks

    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
        max_seq_len=16,
    )
    rng = np.random.default_rng(12)
    tokens = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    params = cfg.cast_params(init_transformer(jax.random.key(11), cfg))
    blocks = shard_blocks(params["blocks"], 2)
    apply = maybe_remat(cfg)
    B, T, M = 8, 16, 2
    ep = {"tok_embed": params["tok_embed"], "pos_embed": params["pos_embed"]}
    xs = embed(ep, tokens).reshape(M, B // M, T, cfg.d_model)
    tgt = jnp.zeros((M, B // M, T), jnp.int32)
    tp = {"tok_embed": params["tok_embed"], "lnf_g": params["lnf_g"],
          "lnf_b": params["lnf_b"]}

    def mk_stage(attn):
        def stage_fn(sb, _st, x):
            def body(c, b):
                return apply(b, c, cfg, attn), None

            return lax.scan(body, x, sb)[0]

        return stage_fn

    def diag_tail(_tp, y, _tgt_f, mask_f):
        # |y| of the microbatch whose mask is live: a probe for WHAT the
        # tail actually received, independent of loss math.
        return jnp.abs(y).sum() * jnp.sign(mask_f.sum())

    def probe(seq, attn, label):
        mesh = build_mesh(MeshSpec(stage=2, seq=seq, data=1))
        mapped = make_1f1b(
            mesh, mk_stage(attn), diag_tail, 2, M,
            microbatch_spec=P(AXIS_DATA, AXIS_SEQ, None),
            aux_spec=P(None, AXIS_DATA, AXIS_SEQ),
        )
        vals = []
        for i in range(M):
            m = jnp.zeros((M, B // M, T), jnp.float32).at[i].set(1.0)
            loss, *_ = mapped(xs, blocks, {}, tp, (tgt, m))
            vals.append(float(loss))
        print(f"  {label}: per-microbatch |y| at the tail = "
              f"{[round(v, 2) for v in vals]}")
        return np.asarray(vals)

    print("expected (plain attention, any seq): ~[1231.32, 1388.74]")
    ok = probe(1, dot_product_attention, "seq=1 plain    ")
    probe(1, _sp_attn_fn("ring"), "seq=1 ring      (mode 1: zeros)")
    probe(2, _sp_attn_fn("ring"), "seq=2 ring      (mode 2: wrong)")
    uly = probe(2, _sp_attn_fn("ulysses"), "seq=2 ulysses   (exact)")
    # Tolerance, not exact equality: reduction order varies with
    # backend/thread configuration at float32.
    assert np.allclose(uly, ok, rtol=1e-4), (
        "ulysses should be exact — reproducer assumptions broken"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
