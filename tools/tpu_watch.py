"""Tunnel-watch: poll the TPU backend all session; capture proof when up.

Rounds 1-2 recorded zero TPU numbers because the tunneled backend was
down at the single moment bench ran (VERDICT r2 weak item 1: "probes
run once at bench time" — no mechanism to catch the tunnel when it
returns). This tool is that mechanism: a bounded background poll of
``utils/backend.py``'s subprocess probe, and the moment the backend
answers it runs, in order,

  1. ``python bench.py``                    -> artifacts/BENCH_tpu_{tag}.json
  2. ``TDN_TEST_TPU=1 pytest tests/test_tpu_hardware.py``
                                            -> artifacts/tpu_hardware_{tag}.log
  3. ``python tools/tpu_capture.py``        -> artifacts/tpu_pipeline_{tag}.json
                                               + profiler trace dir

then ``git commit``s the artifacts (bounded retries around a concurrent
index.lock). Every probe attempt is appended to
``artifacts/tpu_watch_{tag}.log`` with a timestamp, so even an
all-session-down round leaves committed evidence of the polling (the
round-2 ``tpu_probe_r02.txt`` pattern, now automatic).

Each capture step runs in a SUBPROCESS with its own timeout: the
backend is known to hang rather than fail (utils/backend.py docstring),
and a probe success only proves it answered once.

Usage:  python tools/tpu_watch.py --tag r03 --interval 240 --hours 11
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def _append(path: str, line: str) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "a") as f:
        f.write(line.rstrip("\n") + "\n")


def _run(cmd, timeout, env=None, log=None):
    """Run a capture step; returns (rc, stdout, stderr); rc=124 on timeout."""
    merged = dict(os.environ, **(env or {}))
    try:
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout,
            cwd=REPO, env=merged,
        )
        return out.returncode, out.stdout, out.stderr
    except subprocess.TimeoutExpired as e:
        return 124, (e.stdout or ""), (e.stderr or "")


def _git_commit(paths: list[str], message: str, watch_log: str) -> None:
    """add+commit with retries: the build session commits concurrently."""
    for attempt in range(10):
        add = subprocess.run(
            ["git", "add", "--"] + paths, cwd=REPO,
            capture_output=True, text=True,
        )
        if add.returncode == 0:
            commit = subprocess.run(
                ["git", "commit", "-m", message], cwd=REPO,
                capture_output=True, text=True,
            )
            if commit.returncode == 0:
                _append(watch_log, f"{_now()} committed: {message}")
                return
            err = commit.stderr + commit.stdout
        else:
            err = add.stderr
        if "index.lock" not in err and "nothing to commit" not in err:
            _append(watch_log, f"{_now()} git failed: {err.strip()[-200:]}")
        if "nothing to commit" in err:
            return
        time.sleep(30)
    _append(watch_log, f"{_now()} giving up on git commit ({message})")


def capture_all(tag: str, watch_log: str) -> bool:
    """Backend is up: run the three captures; True if all artifacts landed."""
    art = os.path.join(REPO, "artifacts")
    os.makedirs(art, exist_ok=True)
    produced: list[str] = []
    ok = True

    # 1. The headline bench (full MFU path; probe inside is quick now).
    rc, out, err = _run([sys.executable, "bench.py"], timeout=900)
    bench_path = os.path.join(art, f"BENCH_tpu_{tag}.json")
    line = next(
        (ln for ln in out.splitlines() if ln.startswith("{")), None
    )
    with open(bench_path, "w") as f:
        f.write((line or json.dumps({"error": f"rc={rc}", "stderr": err[-500:]})) + "\n")
    produced.append(bench_path)
    bench_on_tpu = bool(line) and rc == 0 and "cpu-fallback" not in line
    ok &= bench_on_tpu
    _append(watch_log, f"{_now()} bench rc={rc} on_tpu={bench_on_tpu}")

    # 2. The five hardware parity gates.
    rc, out, err = _run(
        [sys.executable, "-m", "pytest", "tests/test_tpu_hardware.py",
         "-q", "--no-header"],
        timeout=1200,
        env={"TDN_TEST_TPU": "1"},
    )
    hw_path = os.path.join(art, f"tpu_hardware_{tag}.log")
    with open(hw_path, "w") as f:
        f.write(f"# {_now()} TDN_TEST_TPU=1 pytest tests/test_tpu_hardware.py -q"
                f" (rc={rc})\n")
        f.write(out[-8000:])
        if err:
            f.write("\n--- stderr ---\n" + err[-2000:])
    produced.append(hw_path)
    hw_green = rc == 0 and " passed" in out and "skipped" not in out
    ok &= hw_green
    _append(watch_log, f"{_now()} hardware gates rc={rc} green={hw_green}")

    # 3. Pipelined step latency (the BASELINE p50 metric) + device trace.
    trace_dir = os.path.join(art, f"trace_{tag}")
    rc, out, err = _run(
        [sys.executable, "tools/tpu_capture.py", "--trace-dir", trace_dir],
        timeout=900,
    )
    cap_path = os.path.join(art, f"tpu_pipeline_{tag}.json")
    line = next((ln for ln in out.splitlines() if ln.startswith("{")), None)
    with open(cap_path, "w") as f:
        f.write((line or json.dumps({"error": f"rc={rc}", "stderr": err[-500:]})) + "\n")
    produced.append(cap_path)
    ok &= rc == 0
    _append(watch_log, f"{_now()} capture rc={rc}")
    # Commit the trace only if it stayed small (plugins/profile/*.pb).
    trace_bytes = sum(
        os.path.getsize(os.path.join(r, f))
        for r, _, fs in os.walk(trace_dir) for f in fs
    ) if os.path.isdir(trace_dir) else 0
    if 0 < trace_bytes < 20 * 1024 * 1024:
        produced.append(trace_dir)
    _append(watch_log, f"{_now()} trace bytes={trace_bytes}")

    produced.append(watch_log)
    _git_commit(
        produced,
        f"Real-TPU artifacts ({tag}): bench, hardware gates, "
        "pipeline latency + trace",
        watch_log,
    )

    # 4. Round-5 scale suite (85M MFU A/B + trace, 25.5M valid-eval
    # re-derivation, seq-8192) when the runner exists — AFTER the
    # steps-1-3 commit so a mid-suite tunnel drop cannot cost them.
    scale_runner = os.path.join(REPO, "tools", "tpu_scale_r05.py")
    if os.path.isfile(scale_runner):
        rc, out, err = _run(
            [sys.executable, scale_runner, "--budget", "2700"],
            timeout=3000,
        )
        _append(watch_log, f"{_now()} scale suite rc={rc} "
                           f"{(out.splitlines() or [''])[-1][:200]}")
        ok &= rc == 0
        _git_commit(
            [os.path.join(REPO, "artifacts", "tpu_scale_r05"), watch_log],
            f"Real-TPU scale suite ({tag}): 85M MFU A/B, 25.5M valid "
            "eval, seq-8192",
            watch_log,
        )
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tag", default="r03")
    ap.add_argument("--interval", type=float, default=240.0,
                    help="seconds between probe attempts")
    ap.add_argument("--hours", type=float, default=11.0)
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    args = ap.parse_args()

    sys.path.insert(0, REPO)
    from tpu_dist_nn.utils.backend import probe_default_backend

    watch_log = os.path.join(REPO, "artifacts", f"tpu_watch_{args.tag}.log")
    deadline = time.monotonic() + args.hours * 3600
    _append(watch_log, f"{_now()} tunnel-watch start (interval "
                       f"{args.interval:.0f}s, {args.hours:.1f}h budget)")
    attempt = 0
    while time.monotonic() < deadline:
        attempt += 1
        msgs: list[str] = []
        probed = probe_default_backend(
            timeout=args.probe_timeout, tries=1, log=msgs.append,
        )
        if probed is not None and probed[0] != "cpu":
            _append(watch_log,
                    f"{_now()} attempt {attempt}: backend UP "
                    f"({probed[0]}/{probed[1]}) — capturing")
            if capture_all(args.tag, watch_log):
                _append(watch_log, f"{_now()} all captures green; exiting")
                return 0
            _append(watch_log,
                    f"{_now()} captures incomplete; continuing to poll")
        else:
            why = "; ".join(msgs) or "resolved to cpu"
            _append(watch_log, f"{_now()} attempt {attempt}: down ({why})")
        time.sleep(max(0.0, min(args.interval,
                                deadline - time.monotonic())))
    _append(watch_log, f"{_now()} deadline reached; backend never answered")
    return 1


if __name__ == "__main__":
    sys.exit(main())
