"""Vendor the UCI handwritten-digits set as IDX files (real data, no egress).

Round-2 verdict: every accuracy number in the repo was synthetic because
real MNIST needs network egress. This closes the real-data gap with the
one real handwritten-digit dataset already ON the box: scikit-learn's
bundled copy of the UCI ML "Optical Recognition of Handwritten Digits"
test set — 1,797 genuine 8x8 grayscale scans of digits written by 43
people (sklearn.datasets.load_digits; shipped as package data inside
sklearn, `sklearn/datasets/data/digits.csv.gz`). It is NOT MNIST — the
full-MNIST ≥97 % recipe stays a one-command run for a connected machine
(docs/MNIST.md) — but it is real handwriting, so accuracy on its held-out
split is a real generalization number, unlike the synthetic sets.

Output: gzipped IDX files (the MNIST wire format, SURVEY C12 analogue;
parsed by data/datasets.py:load_idx_images) under
``tpu_dist_nn/data/digits/``:

    train-images-idx3-ubyte.gz / train-labels-idx1-ubyte.gz   (1438)
    t10k-images-idx3-ubyte.gz  / t10k-labels-idx1-ubyte.gz    (359)

Pixels are rescaled 0..16 -> 0..255 uint8 (round(v * 255/16), injective
on the 17 integer levels — a lossless linear recode, not resampling) so
the files behave exactly like MNIST IDX: uint8 intensities normalized
by /255 at load. The split is a deterministic stratified 80/20
(seed 0): every class keeps its proportion in the held-out set.

Deterministic: re-running reproduces the committed bytes.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

OUT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tpu_dist_nn", "data", "digits",
)


def write_idx_images(path: str, imgs: np.ndarray) -> None:
    """imgs: (N, rows, cols) uint8 -> IDX3, gzipped (mtime=0: stable bytes)."""
    n, rows, cols = imgs.shape
    payload = struct.pack(">IIII", 0x0803, n, rows, cols) + imgs.tobytes()
    with open(path, "wb") as f:
        f.write(gzip.compress(payload, mtime=0))


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    payload = struct.pack(">II", 0x0801, len(labels)) + labels.astype(
        np.uint8
    ).tobytes()
    with open(path, "wb") as f:
        f.write(gzip.compress(payload, mtime=0))


def main() -> int:
    from sklearn.datasets import load_digits

    bunch = load_digits()
    x = bunch.images  # (1797, 8, 8) float, integer values 0..16
    y = bunch.target.astype(np.uint8)
    assert x.min() >= 0 and x.max() <= 16
    imgs = np.round(x * (255.0 / 16.0)).astype(np.uint8)

    # Stratified 80/20: per class, a seeded shuffle, last 20% held out.
    rng = np.random.default_rng(0)
    train_idx, test_idx = [], []
    for c in range(10):
        idx = np.flatnonzero(y == c)
        idx = idx[rng.permutation(len(idx))]
        k = int(round(len(idx) * 0.8))
        train_idx.append(idx[:k])
        test_idx.append(idx[k:])
    train_idx = np.sort(np.concatenate(train_idx))
    test_idx = np.sort(np.concatenate(test_idx))

    os.makedirs(OUT_DIR, exist_ok=True)
    write_idx_images(
        os.path.join(OUT_DIR, "train-images-idx3-ubyte.gz"), imgs[train_idx]
    )
    write_idx_labels(
        os.path.join(OUT_DIR, "train-labels-idx1-ubyte.gz"), y[train_idx]
    )
    write_idx_images(
        os.path.join(OUT_DIR, "t10k-images-idx3-ubyte.gz"), imgs[test_idx]
    )
    write_idx_labels(
        os.path.join(OUT_DIR, "t10k-labels-idx1-ubyte.gz"), y[test_idx]
    )
    print(
        f"wrote {len(train_idx)} train / {len(test_idx)} test real digits "
        f"to {OUT_DIR}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
