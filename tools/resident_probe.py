"""Honest device-resident throughput: chained-in-jit + fetch-barrier.

This is the tool that DISCOVERED the tunneled platform's two timing
pathologies (2026-07-31, first live-TPU session):

* ``jax.block_until_ready`` does not block — it returned in ~60 us
  while fetching the same result's value took 59 s (the silently-
  queued backlog draining). Only a value readback is a true barrier.
* Identical executions are replayed from a server-side cache: the
  first fetch of a program took 59 s, identical re-runs 0.23 s.

Methodology (shared with bench.py's ``_time_resident``):

* ``--iters`` data-dependent passes inside ONE jit — the loop carry
  perturbs the next input, so XLA cannot hoist, overlap, or elide
  iterations;
* every timed call carries a distinct ``seed`` input (numerically an
  exact identity: ``+ seed * 1e-30`` rounds away in f32) to bust any
  input-digest replay cache;
* every sample is closed by ``np.asarray`` of a scalar output, and the
  dispatch+fetch RTT floor (timed on a trivial seeded program) is
  subtracted.

Compares, per pass over the flagship FCNN (784-128-64-10):

  f32 XLA chain | f32 fused Pallas chain | int8 jnp | int8 fused Pallas

Emits one JSON line. Run on any backend (CPU fallback works, slower).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=60000)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--init-timeout", type=float, default=90.0)
    args = ap.parse_args()

    import os

    import jax

    from tpu_dist_nn.utils.backend import init_watchdog

    def _hung():
        print(json.dumps({"error": "backend init hung"}), flush=True)
        os._exit(2)

    with init_watchdog(args.init_timeout, _hung):
        devices = jax.devices()
    backend = jax.default_backend()

    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from tpu_dist_nn.kernels.fused_dense import _fcnn_fused_call
    from tpu_dist_nn.kernels.quantized import (
        fcnn_quantized_forward,
        forward_quantized,
        quantize_fcnn,
    )
    from tpu_dist_nn.models.fcnn import forward, init_fcnn

    params = init_fcnn(jax.random.key(0), [784, 128, 64, 10])
    qp = quantize_fcnn(params)
    acts = ("relu", "relu", "softmax")
    shapes = tuple((p["w"].shape, p["b"].shape) for p in params)
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.uniform(0.0, 1.0, (args.n, 784)).astype(np.float32)
    )
    x = jax.device_put(x)

    paths = {
        "f32_xla": lambda bx: forward(params, bx),
        "f32_fused": lambda bx: _fcnn_fused_call(
            shapes, acts, 512, None, bx,
            *[t for q in params for t in (q["w"], q["b"])],
        ),
        "int8_jnp": lambda bx: forward_quantized(qp, bx, acts),
        "int8_fused": lambda bx: fcnn_quantized_forward(
            qp, bx, activations=acts
        ),
    }

    # RTT floor: dispatch + scalar fetch of a trivial seeded program.
    @jax.jit
    def _trivial(seed):
        return seed * jnp.float32(2.0) + jnp.float32(1.0)

    np.asarray(_trivial(jnp.float32(0.5)))  # compile
    floor_times = []
    for i in range(5):
        t0 = time.monotonic()
        np.asarray(_trivial(jnp.float32(1000.0 + i)))
        floor_times.append(time.monotonic() - t0)
    rtt_floor = min(floor_times)

    seed_counter = [float(np.random.default_rng().integers(1 << 20))]

    def chained(fn, iters):
        @jax.jit
        def run(bx, seed):
            def body(_, carry):
                eps, acc = carry
                out = fn(bx + eps)
                s = out.reshape(-1)[0]
                return s * jnp.float32(1e-30), acc + s

            out0 = fn(bx + seed * jnp.float32(1e-30))
            s0 = out0.reshape(-1)[0]
            _, acc = lax.fori_loop(
                0, iters, body, (s0 * jnp.float32(1e-30), s0)
            )
            return acc

        return run

    results = {}
    for name, fn in paths.items():
        try:
            run = chained(fn, args.iters)

            def timed():
                seed_counter[0] += 1.0
                s = jnp.float32(seed_counter[0])
                t0 = time.monotonic()
                np.asarray(run(x, s))  # value fetch = true barrier
                return time.monotonic() - t0

            timed()  # compile
            best = min(timed() for _ in range(args.reps))
            per_iter = max(
                (best - rtt_floor) / (args.iters + 1), 1e-12
            )
            results[name] = {
                "per_pass_s": round(per_iter, 9),
                "samples_per_sec": round(args.n / per_iter, 1),
            }
        except Exception as e:  # pragma: no cover - backend-specific
            print(f"# {name} failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
            results[name] = None

    def ratio(a, b):
        if results.get(a) and results.get(b):
            return round(
                results[a]["samples_per_sec"] / results[b]["samples_per_sec"],
                4,
            )
        return None

    print(json.dumps({
        "backend": backend,
        "device_kind": devices[0].device_kind,
        "n": args.n,
        "iters_chained": args.iters,
        "rtt_floor_s": round(rtt_floor, 6),
        "method": ("fori_loop chained in one jit, seeded against replay "
                   "cache, closed by value fetch, RTT floor subtracted"),
        "paths": results,
        "fused_vs_xla": ratio("f32_fused", "f32_xla"),
        "int8_fused_vs_f32_fused": ratio("int8_fused", "f32_fused"),
        "int8_jnp_vs_f32_xla": ratio("int8_jnp", "f32_xla"),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
