#!/usr/bin/env python
"""Bench regression gate: diff the newest BENCH round against its
predecessor and FAIL on a >5% hot-path regression.

The per-round ``BENCH_r*.json`` diffs have existed since round 2 and
caught nothing, because nothing enforced them — host-fed throughput
decayed 233k -> 199k samples/s across r02->r05 with every round green.
This tool is the enforcement half of the perf-attribution layer
(``tpu_dist_nn/obs/profile.py``): it gates the serving hot-path
metrics, and when one regresses it folds the ``/profile`` per-stage
breakdown into the report so the failure names WHERE the time went,
not just that it went.

Gated metrics (docs/PERF.md "Regression gate"):

    host_fed_samples_per_sec        parsed.value                 higher
    device_resident_samples_per_sec parsed.device_resident_...   higher
    serving_rps                     serving.coalesced.rps        higher
    generate_rps                    serving.generate.requests_per_s
                                                                 higher
    generate_ttft_p99_ms            serving.generate.ttft_p99_ms lower
    gen_prefix_rps                  serving.generate_prefix.rps  higher
    gen_prefix_ttft_p99_ms          serving.generate_prefix.ttft_p99_ms
                                                                 lower
    router_rps                      serving.router.rps           higher
    slo_process_p99_ms              serving.slo.latency.measured_p99_ms
                                                                 lower
    slo_availability                serving.slo.availability.measured
                                                                 higher
    incident_armed_ratio            serving.incident_overhead.ratio
                                                                 higher
    autoscale_replica_seconds_ratio serving.autoscale.replica_seconds_ratio
                                                                 lower
    serving_mfu                     serving.goodput.mfu          higher
    serving_pad_ratio               serving.goodput.pad_ratio    lower
    slo_class_critical_p99_ms       serving.slo_classes.critical_p99_ms
                                                                 lower
    gen_stream_ttft_p50_ms          serving.generate_stream.ttft_p50_ms
                                                                 lower

Rules:

* A metric regresses when it moves more than ``--threshold`` (default
  5%) in its BAD direction; improvements never fail.
* A metric absent from either round is skipped (reported as such) —
  older rounds predate some series.
* Rounds from DIFFERENT backends skip the whole gate with exit 0: a
  cpu-fallback round against a real-TPU round is not a regression
  signal, it is a hardware change (the rule that keeps the gate honest
  on boxes whose TPU tunnel flaps).
* ``--report-only`` prints the identical report but always exits 0 —
  the mode the quick tier runs against the checked-in r04->r05 pair
  (which carries a real ~10% serving_rps regression; the enforced gate
  exists so the NEXT one cannot land silently).
* ``--history 'BENCH_r*.json'`` gates the current round against the
  BEST historical value of each metric (same-backend rounds only)
  instead of just the previous round. Pairwise diffing is blind to
  slow drift: host-fed throughput lost ~3%/round across r02->r05 —
  under the pairwise 5% threshold every single time — compounding to
  −15% vs the r02 best. Best-of-history is the anti-boiling-frog
  mode: each metric's high-water mark is the bar, so a trajectory of
  individually-green regressions still fails. Invalid/failed rounds
  (r01's error record) are skipped, as are rounds from other backends
  (per-ROUND here, not whole-gate: history legitimately spans a
  backend flap; only same-backend rounds say anything about the
  current one).

Exit codes: 0 pass/skip/report-only, 1 enforced regression, 2 usage.

Usage:
    python tools/bench_gate.py                          # newest pair
    python tools/bench_gate.py --current BENCH_r05.json \\
        --previous BENCH_r04.json
    python tools/bench_gate.py --threshold 0.05 --report-only
    python tools/bench_gate.py --profile http://host:9100/profile
    python tools/bench_gate.py --history 'BENCH_r*.json'  # best-of-history
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import urllib.request

DEFAULT_THRESHOLD = 0.05

# (label, path into the parsed bench doc, direction). "higher" means
# higher is better (throughput); "lower" means lower is better (TTFT).
GATED_METRICS = (
    ("host_fed_samples_per_sec", ("value",), "higher"),
    ("device_resident_samples_per_sec",
     ("device_resident_samples_per_sec",), "higher"),
    ("serving_rps", ("serving", "coalesced", "rps"), "higher"),
    ("generate_rps", ("serving", "generate", "requests_per_s"), "higher"),
    ("generate_ttft_p99_ms", ("serving", "generate", "ttft_p99_ms"),
     "lower"),
    # Shared-prefix workload (prefix cache + chunked prefill ON): the
    # KV-reuse win must not regress once landed. Absent in rounds that
    # predate the section -> per-metric skip.
    ("gen_prefix_rps", ("serving", "generate_prefix", "rps"), "higher"),
    ("gen_prefix_ttft_p99_ms",
     ("serving", "generate_prefix", "ttft_p99_ms"), "lower"),
    # Multi-replica router (controlled-regime 3-replica rps): the
    # fleet's scaling win must not regress once landed. Absent in
    # rounds that predate the section -> per-metric skip.
    ("router_rps", ("serving", "router", "rps"), "higher"),
    # SLO summary block (ISSUE 9): the serving run scored against the
    # fixed p99/availability objectives bench.py declares. Gated like
    # any other family — absent in pre-ISSUE-9 rounds -> per-metric
    # skip; a later round that blows the measured p99 or availability
    # past threshold fails the gate.
    ("slo_process_p99_ms",
     ("serving", "slo", "latency", "measured_p99_ms"), "lower"),
    ("slo_availability",
     ("serving", "slo", "availability", "measured"), "higher"),
    # Flight-recorder overhead (ISSUE 11): armed/disarmed serving rps
    # ratio with no detector firing — must stay ~1.0 (capture is free
    # until it fires). Absent in pre-ISSUE-11 rounds -> per-metric
    # skip.
    ("incident_armed_ratio",
     ("serving", "incident_overhead", "ratio"), "higher"),
    # Fleet autopilot (ISSUE 12): autoscaled / static-peak
    # replica-seconds over the synthetic diurnal load — the capacity
    # bill of holding the SLO, lower is better. Absent in pre-ISSUE-12
    # rounds -> per-metric skip.
    ("autoscale_replica_seconds_ratio",
     ("serving", "autoscale", "replica_seconds_ratio"), "lower"),
    # Goodput plane (ISSUE 14): the serving window's measured MFU
    # (analytic useful FLOPs over resolved peak — higher is better)
    # and its structural-pad FLOP share (bucket pad rows, idle slots —
    # lower is better). Absent in pre-ISSUE-14 rounds -> per-metric
    # skip.
    ("serving_mfu", ("serving", "goodput", "mfu"), "higher"),
    ("serving_pad_ratio", ("serving", "goodput", "pad_ratio"), "lower"),
    # Degradation ladder (ISSUE 15): the critical class's p99 under
    # the 2x mixed-class overload A/B — the latency the SLO pages on
    # when the fleet is saturated, lower is better (the ROADMAP
    # target: holds ~flat while best_effort absorbs the sheds).
    # Absent in pre-ISSUE-15 rounds -> per-metric skip.
    ("slo_class_critical_p99_ms",
     ("serving", "slo_classes", "critical_p99_ms"), "lower"),
    # Streaming plane (ISSUE 16): client-observed streamed TTFT
    # (submit -> first GenerateStream token frame on the wire) through
    # the loopback serving endpoint — the latency streaming exists to
    # surface, lower is better. Absent in pre-ISSUE-16 rounds ->
    # per-metric skip.
    ("gen_stream_ttft_p50_ms",
     ("serving", "generate_stream", "ttft_p50_ms"), "lower"),
    # Scenario matrix (ISSUE 18): fraction of the checked-in
    # scenarios/*.json cells (workload x chaos, SLO-scored by the
    # replay engine) that pass — higher is better; a cell newly
    # failing its SLO verdict shows up here as a ratio drop. Absent
    # in pre-ISSUE-18 rounds -> per-metric skip.
    ("scenario_pass_ratio",
     ("serving", "scenarios", "pass_ratio"), "higher"),
    # Silent-corruption defense plane (ISSUE 19): armed/disarmed
    # serving rps ratio with the numeric guard + spot-checking +
    # canary probes all ON and nothing corrupt — detection must stay
    # ~free (the <5% budget), higher is better. Absent in pre-ISSUE-19
    # rounds -> per-metric skip.
    ("integrity_armed_ratio",
     ("serving", "integrity_overhead", "ratio"), "higher"),
)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def load_round(path: str) -> dict:
    """A BENCH_r*.json's parsed payload (the driver wraps the bench
    JSON line under "parsed"; a bare bench dump is accepted too)."""
    with open(path) as f:
        doc = json.load(f)
    parsed = doc.get("parsed") if isinstance(doc, dict) else None
    if isinstance(parsed, dict):
        return parsed
    if isinstance(doc, dict) and "value" in doc:
        return doc
    raise ValueError(f"{path}: not a BENCH round (no 'parsed' payload)")


def find_rounds(bench_dir: str) -> list[tuple[int, str]]:
    out = []
    for name in os.listdir(bench_dir):
        m = _ROUND_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(bench_dir, name)))
    return sorted(out)


def resolve_pair(args) -> tuple[str, str]:
    """(current_path, previous_path) from flags or discovery: newest
    round in --dir, previous from its recorded ``prev_bench.file`` (the
    lineage the bench itself wrote) else the next-lower round file."""
    if args.current and args.previous:
        return args.current, args.previous
    rounds = find_rounds(args.dir)
    if args.current:
        cur_path = args.current
    else:
        # With an explicit --previous only the current round needs
        # discovery; without one the previous must be discoverable too.
        need = 1 if args.previous else 2
        if len(rounds) < need:
            raise FileNotFoundError(
                f"need {need} BENCH_r*.json round(s) in {args.dir!r} "
                f"(found {len(rounds)})"
            )
        cur_path = rounds[-1][1]
    if args.previous:
        return cur_path, args.previous
    cur = load_round(cur_path)
    prev_name = (cur.get("prev_bench") or {}).get("file")
    if prev_name:
        prev_path = os.path.join(args.dir, prev_name)
        if os.path.exists(prev_path):
            return cur_path, prev_path
    m = _ROUND_RE.search(os.path.basename(cur_path))
    if m:
        below = [p for n, p in rounds if n < int(m.group(1))]
        if below:
            return cur_path, below[-1]
    raise FileNotFoundError(
        f"no previous round found for {cur_path!r} (pass --previous)"
    )


def _dig(doc: dict, path: tuple) -> float | None:
    node = doc
    for key in path:
        if not isinstance(node, dict) or node.get(key) is None:
            return None
        node = node[key]
    try:
        return float(node)
    except (TypeError, ValueError):
        return None


def compare(prev: dict, cur: dict,
            threshold: float = DEFAULT_THRESHOLD) -> dict:
    """The gate verdict for one round pair.

    Returns ``{"skipped": reason}`` on a backend mismatch, else
    ``{"metrics": [...], "regressions": [labels]}`` where each metric
    row carries prev/cur/regression fraction (positive = worse) or a
    per-metric skip reason.
    """
    prev_backend = str(prev.get("backend"))
    cur_backend = str(cur.get("backend"))
    if prev_backend != cur_backend:
        return {
            "skipped": (
                f"backend changed between rounds ({prev_backend!r} -> "
                f"{cur_backend!r}); cross-backend deltas are hardware "
                "changes, not regressions"
            ),
        }
    metrics = []
    regressions = []
    for label, path, direction in GATED_METRICS:
        p, c = _dig(prev, path), _dig(cur, path)
        if p is None or c is None:
            metrics.append({
                "metric": label,
                "skipped": "absent in "
                + ("both rounds" if p is None and c is None
                   else "previous round" if p is None else "current round"),
            })
            continue
        if p <= 0:
            metrics.append({
                "metric": label,
                "skipped": f"previous value not positive ({p})",
            })
            continue
        # regression fraction: positive = moved the BAD way.
        reg = (p - c) / p if direction == "higher" else (c - p) / p
        row = {
            "metric": label, "previous": p, "current": c,
            "direction": direction, "regression": round(reg, 4),
            "failed": reg > threshold,
        }
        metrics.append(row)
        if row["failed"]:
            regressions.append(label)
    return {"metrics": metrics, "regressions": regressions,
            "threshold": threshold, "backend": cur_backend}


def resolve_history(args) -> tuple[str, list[tuple[str, dict]]]:
    """(current_path, [(name, parsed), ...]) for ``--history`` mode.

    The glob expands relative to ``--dir``; the current round is
    ``--current`` (or the highest-numbered match), history is every
    OTHER lower-numbered valid round. A ``--current`` whose name does
    not parse as a round number (a fresh un-numbered local run) is
    gated against EVERY matched round — for a fresh run the whole
    checked-in history IS the bar; a stderr note says so, since gating
    an old commit's fresh bench against a glob holding newer rounds
    would otherwise silently include the future. Rounds that fail to
    load or carry no payload (a failed round's error record — r01)
    are skipped with a stderr note, never fatal: the round after a
    failure is exactly when the gate matters.
    """
    import glob as _glob

    pattern = args.history
    if not os.path.isabs(pattern) and os.path.dirname(pattern) == "":
        pattern = os.path.join(args.dir, pattern)
    matches = []
    for p in _glob.glob(pattern):
        m = _ROUND_RE.search(os.path.basename(p))
        if m:
            matches.append((int(m.group(1)), p))
    matches.sort()
    if not matches:
        raise FileNotFoundError(f"no BENCH_r*.json match {pattern!r}")
    if args.current:
        cur_path = args.current
        m = _ROUND_RE.search(os.path.basename(cur_path))
        cur_round = int(m.group(1)) if m else None
        if cur_round is None:
            print(
                f"# --current {cur_path!r} is not a numbered round; "
                "gating it against EVERY round in the glob (make sure "
                "none postdates the build under test)",
                file=sys.stderr,
            )
    else:
        cur_round, cur_path = matches[-1]
    history = []
    for n, p in matches:
        if os.path.abspath(p) == os.path.abspath(cur_path):
            continue
        if cur_round is not None and n >= cur_round:
            continue
        try:
            history.append((os.path.basename(p), load_round(p)))
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"# skipping invalid round {p}: {e}", file=sys.stderr)
    if not history:
        raise FileNotFoundError(
            f"no valid historical rounds behind {cur_path!r} in {pattern!r}"
        )
    return cur_path, history


def compare_history(history: list[tuple[str, dict]], cur: dict,
                    threshold: float = DEFAULT_THRESHOLD) -> dict:
    """Best-of-history verdict: each gated metric regresses when the
    current value is more than ``threshold`` past the BEST same-backend
    historical value in its bad direction (max for higher-is-better,
    min for lower-is-better). Compounding sub-threshold drift therefore
    fails against the high-water mark even though every pairwise diff
    stayed green. Metric rows carry ``best``/``best_round``."""
    cur_backend = str(cur.get("backend"))
    usable = [(name, doc) for name, doc in history
              if str(doc.get("backend")) == cur_backend]
    if not usable:
        return {
            "skipped": (
                f"no historical rounds share the current backend "
                f"({cur_backend!r}); cross-backend deltas are hardware "
                "changes, not regressions"
            ),
        }
    metrics = []
    regressions = []
    for label, path, direction in GATED_METRICS:
        c = _dig(cur, path)
        hist_vals = [(name, _dig(doc, path)) for name, doc in usable]
        hist_vals = [(name, v) for name, v in hist_vals
                     if v is not None and v > 0]
        if c is None or not hist_vals:
            metrics.append({
                "metric": label,
                "skipped": (
                    "absent in current round" if c is None
                    else "absent (or not positive) in every same-backend "
                         "historical round"
                ),
            })
            continue
        pick = max if direction == "higher" else min
        best_round, best = pick(hist_vals, key=lambda nv: nv[1])
        reg = (best - c) / best if direction == "higher" else (c - best) / best
        row = {
            "metric": label, "previous": best, "current": c,
            "best_round": best_round, "direction": direction,
            "regression": round(reg, 4), "failed": reg > threshold,
        }
        metrics.append(row)
        if row["failed"]:
            regressions.append(label)
    return {"metrics": metrics, "regressions": regressions,
            "threshold": threshold, "backend": cur_backend,
            "mode": "best-of-history",
            "history_rounds": [name for name, _ in usable]}


def load_profile(source: str | None) -> dict | None:
    """A /profile breakdown for attribution: an http(s) URL (a live
    ``--metrics-port`` endpoint), a JSON file path, or None. Fetch
    failures degrade to None — attribution is garnish, the verdict
    never depends on it."""
    if not source:
        return None
    try:
        if source.startswith(("http://", "https://")):
            with urllib.request.urlopen(source, timeout=5.0) as resp:
                return json.loads(resp.read())
        with open(source) as f:
            return json.load(f)
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        print(f"# profile attribution unavailable ({e!r})", file=sys.stderr)
        return None


def attribution_lines(profile: dict | None) -> list[str]:
    """Top stage shares per method — where the regressed time goes."""
    if not profile:
        return []
    lines = ["where the time goes (/profile stage shares):"]
    for method in sorted(profile.get("methods", {})):
        m = profile["methods"][method]
        tops = ", ".join(
            f"{s['stage']} {s['share'] * 100:.1f}% "
            f"(p99 {s['p99_s'] * 1e3:.2f}ms)"
            for s in m.get("stages", ())[:4]
        )
        lines.append(
            f"  {method}: {m.get('traces', 0)} traces — {tops}"
        )
    return lines


def lint_status_line() -> str:
    """One-line tdnlint verdict for the report header: regression
    reports and invariant drift surface in one place. Fail-safe — a
    missing or broken analyzer reports itself, never breaks the gate."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        # tdnlint lives right next to this script: a plain import with
        # tools/ on the path (sys.modules dedupes against any loader
        # that registered the package first).
        if here not in sys.path:
            sys.path.insert(0, here)
        import tdnlint

        target = os.path.join(os.path.dirname(here), "tpu_dist_nn")
        result = tdnlint.run_lint(
            [target], baseline_path=tdnlint.DEFAULT_BASELINE
        )
        new = len(result["new"])
        if new:
            return (f"lint: {new} non-baselined finding"
                    f"{'s' if new != 1 else ''} — run `tdn lint` "
                    "(docs/STATIC_ANALYSIS.md)")
        return (f"lint: clean ({len(result['baselined'])} baselined, "
                f"{result['suppressed_total']} suppressed)")
    except Exception as e:  # noqa: BLE001 — the gate must keep gating
        return f"lint: unavailable ({e!r})"


def render_report(verdict: dict, cur_path: str, prev_path: str,
                  profile: dict | None = None,
                  report_only: bool = False,
                  lint_status: str | None = None) -> str:
    lines = [
        f"bench gate: {os.path.basename(prev_path)} -> "
        f"{os.path.basename(cur_path)}"
        + (" [report-only]" if report_only else ""),
    ]
    if lint_status:
        lines.append(lint_status)
    if "skipped" in verdict:
        lines.append(f"SKIP: {verdict['skipped']}")
        return "\n".join(lines)
    lines.append(
        f"backend: {verdict['backend']}  threshold: "
        f"{verdict['threshold'] * 100:.0f}%"
    )
    for row in verdict["metrics"]:
        if "skipped" in row:
            lines.append(f"  SKIP {row['metric']:<34} {row['skipped']}")
            continue
        arrow = "v" if row["regression"] > 0 else "^"
        mark = "FAIL" if row["failed"] else " ok "
        best = (
            f"  (best: {row['best_round']})" if row.get("best_round") else ""
        )
        lines.append(
            f"  {mark} {row['metric']:<34} {row['previous']:>12.1f} -> "
            f"{row['current']:>12.1f}  {arrow}{abs(row['regression']) * 100:.1f}%"
            f"{best}"
        )
    if verdict["regressions"]:
        lines.append(
            f"REGRESSED past {verdict['threshold'] * 100:.0f}%: "
            + ", ".join(verdict["regressions"])
        )
        lines.extend(attribution_lines(profile))
        if not profile:
            lines.append(
                "  (no /profile attribution attached — rerun with "
                "--profile <url-or-json> against a serving run to see "
                "which stage ate the time)"
            )
    else:
        lines.append("all gated metrics within threshold")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fail a PR that regresses the serving hot path >5% "
                    "between BENCH rounds",
    )
    ap.add_argument("--current", help="current round BENCH_r*.json "
                                      "(default: newest in --dir)")
    ap.add_argument("--previous",
                    help="previous round (default: the current round's "
                         "recorded prev_bench.file, else next-lower round)")
    ap.add_argument("--history", default=None, metavar="GLOB",
                    help="gate the current round against the BEST same-"
                         "backend historical value of each metric across "
                         "every round matching GLOB (e.g. 'BENCH_r*.json'; "
                         "relative patterns expand under --dir) — catches "
                         "sub-threshold drift that compounds across rounds")
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default .)")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD,
                    help="regression fraction that fails the gate "
                         "(default 0.05 = 5%%)")
    ap.add_argument("--report-only", action="store_true",
                    help="print the identical report but always exit 0 "
                         "(the known-regressed-pair mode)")
    ap.add_argument("--profile", default=None,
                    help="a /profile URL or saved JSON for per-stage "
                         "attribution on failure")
    ap.add_argument("--json", action="store_true",
                    help="also print the machine verdict as one JSON line")
    args = ap.parse_args(argv)
    if not 0 < args.threshold < 1:
        print(f"error: --threshold must be in (0, 1), got {args.threshold}",
              file=sys.stderr)
        return 2
    try:
        if args.history:
            if args.previous:
                print("error: --history and --previous are exclusive "
                      "(best-of-history picks its own bar)", file=sys.stderr)
                return 2
            cur_path, history = resolve_history(args)
            cur = load_round(cur_path)
            verdict = compare_history(history, cur, args.threshold)
            prev_path = f"best-of-{len(history)}-rounds"
        else:
            cur_path, prev_path = resolve_pair(args)
            cur, prev = load_round(cur_path), load_round(prev_path)
            verdict = compare(prev, cur, args.threshold)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    # Attribution source priority: an explicit --profile (live /profile
    # endpoint or saved JSON), else the breakdown bench.py embeds in
    # the current round's serving section.
    profile = load_profile(args.profile) or (
        (cur.get("serving") or {}).get("profile")
    )
    print(render_report(
        verdict, cur_path, prev_path, profile,
        report_only=args.report_only,
        # The lint header rides report-only mode (the PR-report/CI
        # summary path); enforce mode stays a pure perf verdict.
        lint_status=lint_status_line() if args.report_only else None,
    ))
    if args.json:
        print(json.dumps({
            "current": os.path.basename(cur_path),
            "previous": os.path.basename(prev_path),
            "report_only": args.report_only,
            **verdict,
        }))
    if verdict.get("regressions") and not args.report_only:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
