"""Targeted probe: int8 jnp chain vs the Pallas int8 chain, by shape.

Ran live on the tunneled TPU v5 lite to settle the width-gate question
raised in review (kernels/quantized.py): where exactly does the Pallas
whole-chain kernel stop paying? Results in
artifacts/tpu_r04/int8_crossover.jsonl — no sharp crossover at uniform
widths (0.9-1.5x band), decisive jnp win only when interior dims sit
below the 128-lane MXU tile; a narrow classifier head does not matter.
Timing: fetch-barrier + anti-replay (see bench.py::_time_resident).
"""
import time, json, sys
import jax, jax.numpy as jnp, numpy as np
from jax import lax
from tpu_dist_nn.kernels.quantized import (fcnn_quantized_forward, forward_quantized, quantize_fcnn)
from tpu_dist_nn.models.fcnn import init_fcnn

@jax.jit
def _trivial(seed): return seed * jnp.float32(2.0) + jnp.float32(1.0)
np.asarray(_trivial(jnp.float32(0.5)))
def t_once(f):
    t0=time.monotonic(); f(); return time.monotonic()-t0
floor = min(t_once(lambda i=i: np.asarray(_trivial(jnp.float32(1000.0+i)))) for i in range(5))
sc=[float(np.random.default_rng().integers(1<<20))]
def measure(fn, x, iters):
    for _ in range(4):
        @jax.jit
        def run(bx, seed, _k=iters):
            def body(_, c):
                eps, acc = c
                out = fn(bx + eps); s = out.reshape(-1)[0].astype(jnp.float32)
                return (s*jnp.float32(1e-30)).astype(bx.dtype), acc+s
            o0 = fn(bx + (seed*jnp.float32(1e-30)).astype(bx.dtype))
            s0 = o0.reshape(-1)[0].astype(jnp.float32)
            _, acc = lax.fori_loop(0, _k, body, ((s0*jnp.float32(1e-30)).astype(bx.dtype), s0))
            return acc
        def timed():
            sc[0]+=1.0; s=jnp.float32(sc[0])
            t0=time.monotonic(); np.asarray(run(x,s)); return time.monotonic()-t0
        timed()
        best = min(timed() for _ in range(3))
        sig = best-floor
        if sig >= 0.1: return sig/(iters+1), iters
        per = max(sig, 0.002)/(iters+1); iters = min(int(0.25/per), iters*20)
    return None, iters
batch=8192
out={}
for w in (128, 192, 256, 384, 512):
    params = init_fcnn(jax.random.key(0), [w,w,w,w])
    qp = quantize_fcnn(params); acts=("relu","relu","softmax")
    x = jax.device_put(jnp.asarray(np.random.default_rng(1).uniform(0,1,(batch,w)), jnp.float32))
    r={}
    for name, fn in (("jnp", lambda bx,q=qp: forward_quantized(q,bx,acts)),
                     ("pallas", lambda bx,q=qp: fcnn_quantized_forward(q,bx,activations=acts,prefer_kernel=True))):
        try: t,_ = measure(fn, x, 200)
        except Exception as e: t=None; print(f"# w={w} {name}: {e}", file=sys.stderr)
        r[name]= round(t,9) if t else None
    r["pallas_vs_jnp"] = round(r["jnp"]/r["pallas"],3) if r["jnp"] and r["pallas"] else None
    out[w]=r; print(json.dumps({w:r}), flush=True)
# head-shape check: wide hidden, narrow head
for dims in ([1024,1024,1024,10], [512,512,512,10]):
    params = init_fcnn(jax.random.key(0), dims)
    qp = quantize_fcnn(params); acts=("relu","relu","softmax")
    x = jax.device_put(jnp.asarray(np.random.default_rng(1).uniform(0,1,(batch,dims[0])), jnp.float32))
    r={}
    for name, fn in (("jnp", lambda bx,q=qp: forward_quantized(q,bx,acts)),
                     ("pallas", lambda bx,q=qp: fcnn_quantized_forward(q,bx,activations=acts,prefer_kernel=True))):
        try: t,_ = measure(fn, x, 200)
        except Exception as e: t=None; print(f"# {dims} {name}: {e}", file=sys.stderr)
        r[name]= round(t,9) if t else None
    r["pallas_vs_jnp"] = round(r["jnp"]/r["pallas"],3) if r["jnp"] and r["pallas"] else None
    print(json.dumps({str(dims):r}), flush=True)
