"""Capture real-TPU evidence: pipelined step latency + profiler trace.

Run ON the live backend (no CPU forcing) by tools/tpu_watch.py the
moment the tunneled TPU answers a probe. Emits ONE JSON line on stdout:

    {"backend", "device_kind", "n_devices", "pipeline": {p50_s, ...},
     "stage_compute": {p50_s, ...}, "trace_dir"}

``pipeline`` is the BASELINE.md metric — p50 per-stage pipeline step
latency — measured as the wall-clock of one full pipelined forward
(GPipe schedule, ``parallel/pipeline.py``) divided by its step count
T = M + S - 1; ``stage_compute`` is the single-stage dense-chain step
on its own. On a single-chip host the mesh is (data=1, stage=n_devices)
so the schedule, ppermute hops and all, is exactly what a pod slice
runs — with n_devices=1 the hop is a no-op but the schedule/trace
structure is identical.

A ``jax.profiler`` trace of one pipelined step lands in ``--trace-dir``
(TensorBoard/Perfetto format) with the per-stage ``named_scope`` labels
from parallel/gpipe.py:58-61 — the trace-level analogue of the
reference's per-hop RPC timers (run_grpc_inference.py:139-148).

Backend init is bounded by the same watchdog as bench.py (the tunneled
backend is known to hang, not fail; utils/backend.py): exit code 2
means "init hung", letting the caller keep polling.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trace-dir", default="artifacts/trace")
    ap.add_argument("--init-timeout", type=float, default=90.0)
    ap.add_argument("--batch", type=int, default=8192)
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--reps", type=int, default=30)
    args = ap.parse_args()

    import jax

    from tpu_dist_nn.utils.backend import init_watchdog

    def _hung():
        print(json.dumps({"error": "backend init hung"}), flush=True)
        os._exit(2)

    with init_watchdog(args.init_timeout, _hung):
        devices = jax.devices()
    backend = jax.default_backend()
    kind = devices[0].device_kind

    import jax.numpy as jnp
    import numpy as np

    from tpu_dist_nn.core.schema import partition_model
    from tpu_dist_nn.models.fcnn import init_fcnn, spec_from_params
    from tpu_dist_nn.parallel.mesh import MeshSpec, build_mesh
    from tpu_dist_nn.parallel.pipeline import (
        build_pipeline_params,
        compiled_pipeline,
        pad_batch,
    )
    from tpu_dist_nn.utils.profiling import LatencyStats, capture_trace

    # The flagship model at the reference's torch shape
    # (generate_mnist_pytorch.py:25-27), pipelined over every local
    # device: 3 stages when 3+ devices exist, else what fits.
    n_dev = len(devices)
    n_stages = min(3, n_dev)
    params = init_fcnn(jax.random.key(0), [784, 128, 64, 10])
    model = spec_from_params(params, ["relu", "relu", "softmax"])
    dist = {1: [3], 2: [2, 1], 3: [1, 1, 1]}[n_stages]
    stages = partition_model(model, dist)
    pp = build_pipeline_params(stages)
    mesh = build_mesh(MeshSpec(stage=n_stages))

    M = args.microbatches
    xs, _ = pad_batch(pp.meta, jnp.asarray(
        np.random.default_rng(0).uniform(0, 1, (args.batch, 784)),
        jnp.float32), M, 1, jnp.float32)
    run = compiled_pipeline(mesh, pp.meta, M, False, jnp.float32)
    jax.block_until_ready(run(pp.weights, xs))  # compile

    T = M + pp.meta.num_stages - 1  # schedule steps per forward
    full = LatencyStats("pipelined_forward")
    per_step = LatencyStats("pipeline_step")
    for _ in range(args.reps):
        t0 = time.monotonic()
        jax.block_until_ready(run(pp.weights, xs))
        dt = time.monotonic() - t0
        full.record(dt)
        per_step.record(dt / T)

    # Single-stage compute on its own (no schedule): the per-stage
    # cost floor the p50 step latency is judged against.
    from tpu_dist_nn.models.fcnn import forward

    bx = jnp.asarray(
        np.random.default_rng(1).uniform(0, 1, (args.batch // M, 784)),
        jnp.float32,
    )
    fwd = jax.jit(forward)
    jax.block_until_ready(fwd(params, bx))
    stage = LatencyStats("stage_compute")
    for _ in range(args.reps):
        with stage.time():
            jax.block_until_ready(fwd(params, bx))

    os.makedirs(args.trace_dir, exist_ok=True)
    with capture_trace(args.trace_dir):
        jax.block_until_ready(run(pp.weights, xs))

    print(json.dumps({
        "backend": backend,
        "device_kind": kind,
        "n_devices": n_dev,
        "n_stages": n_stages,
        "num_microbatches": M,
        "batch": args.batch,
        "schedule_steps": T,
        "pipelined_forward": full.summary(),
        "pipeline_step": per_step.summary(),
        "stage_compute": stage.summary(),
        "trace_dir": args.trace_dir,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
