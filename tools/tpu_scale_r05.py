"""Round-5 TPU scale suite: close the real-workload MFU gap.

VERDICT r4 item 1: the synthetic dense step reached 0.9651 MFU while
the 85M LM trained at ~0.21 — with the suspects named (per-step host
dispatch, non-donated f32 master params re-allocated every step, XLA
attention below the flash crossover). Round 5 landed the fixes in the
trainer (``--steps-per-call`` K-step lax.scan superbatches; donated
(params, opt_state) buffers — train/lm_trainer.py); this runner is the
hardware half: during a tunnel window it

1. re-runs the 85M config (d768/h12/L12, seq 1024, bf16+remat) with
   steps-per-call 1 vs 10 — the dispatch-overhead A/B — and computes
   steady-state model-flops MFU from the metrics JSONL, whose
   per-entry ``seconds`` are now TRUE value-fetch barriers (each
   history entry fetches its loss; the r4 timing-forensics rule);
2. captures a short profiler trace of the same step;
3. re-derives the 25.5M config (d512/h8/L8, seq 512) on the NEW 8 MB
   corpus — the first scale run with a VALID held-out perplexity
   (r4's eval degenerated: 12 rows < batch 16 on the 238 KB corpus);
4. runs the queued seq-8192 long-context config (flash-attention
   training path, T >= FLASH_MIN_SEQ).

Every leg is a bounded subprocess of the REAL CLI (``tdn lm``) with
``--platform tpu`` so a dropped tunnel waits/fails instead of silently
degrading to host CPU (the r4 seq-8192 lesson). Writes
``artifacts/tpu_scale_r05/{metrics_*.jsonl, RECORD.json, trace_85m/}``.

MFU accounting (same formula as artifacts/tpu_scale_r04/RECORD.json):
model flops/step = 6*N*tokens + 12*L*B*T^2*d (attention, fwd+bwd
triple-count), peak = 197 TF bf16 (v5e).

Usage: python tools/tpu_scale_r05.py [--skip-8k] [--budget 1800]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts", "tpu_scale_r05")
PEAK_TFLOPS_V5E = 197.0


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ"
    )


def _run_cli(args: list[str], timeout: float,
             extra_env: dict | None = None) -> tuple[int, str, str]:
    cmd = [sys.executable, "-m", "tpu_dist_nn.cli", "--platform", "tpu",
           "lm"] + args
    env = dict(os.environ, **(extra_env or {}))
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        out = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO,
            env=env,
        )
        return out.returncode, out.stdout, out.stderr
    except subprocess.TimeoutExpired as e:
        return 124, str(e.stdout or ""), str(e.stderr or "")


def _read_history(path: str) -> list[dict]:
    rows = []
    try:
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if "step" in rec and "seconds" in rec:
                    rows.append(rec)
    except OSError:
        pass
    return rows


def _final_report(path: str) -> dict | None:
    try:
        with open(path) as f:
            for line in f:
                rec = json.loads(line)
                if "final_report" in rec:
                    return rec["final_report"]
    except OSError:
        pass
    return None


def steady_state(history: list[dict], skip_frac: float = 0.45) -> dict | None:
    """s/step between the first post-warmup entry and the last.

    Entries' ``seconds`` are value-fetch barriers (each fetched its
    loss), so deltas between them are honest wall time.
    """
    if len(history) < 3:
        return None
    j = max(1, int(len(history) * skip_frac))
    a, b = history[j], history[-1]
    dsteps = b["step"] - a["step"]
    if dsteps <= 0 or b["seconds"] <= a["seconds"]:
        return None
    return {
        "from_step": a["step"], "to_step": b["step"],
        "seconds": round(b["seconds"] - a["seconds"], 4),
        "s_per_step": round((b["seconds"] - a["seconds"]) / dsteps, 6),
    }


def model_flops_per_step(n_params: int, batch: int, seq: int, d_model: int,
                         n_layers: int) -> float:
    tokens = batch * seq
    return 6.0 * n_params * tokens + 12.0 * n_layers * batch * seq**2 * d_model


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=2700.0,
                    help="overall wall budget (s); later legs are "
                         "skipped when exceeded (sized for 3 85M arms "
                         "+ trace + 25.5M + seq-8192 with cold "
                         "compiles)")
    ap.add_argument("--skip-8k", action="store_true")
    ap.add_argument("--steps-85m", type=int, default=220)
    args = ap.parse_args()
    os.makedirs(ART, exist_ok=True)
    t_start = time.monotonic()
    record: dict = {
        "date": _now(), "round": 5,
        "task": "real-workload MFU (VERDICT r4 item 1): 85M LM with "
                "donated buffers + steps-per-call superbatches, "
                "25.5M re-derivation with VALID held-out eval on the "
                "8 MB corpus, queued seq-8192 long-context run",
        "corpus": "tpu_dist_nn/data/corpus/realtext_corpus.txt "
                  "(8.0 MB, realtext_manifest.json)",
        "peak_tflops": PEAK_TFLOPS_V5E,
    }

    def left() -> float:
        return args.budget - (time.monotonic() - t_start)

    # ---- Leg 1: 85M MFU, steps-per-call A/B -------------------------
    # Deliverable arms run FIRST (spc 1 vs 10 — the dispatch suspect);
    # the two PROBE arms run LAST, after every deliverable leg, so a
    # tight window can never starve a deliverable for a probe:
    # spc10_flash forces the flash kernel at seq 1024 (the attention
    # suspect — the r4 sweep said XLA wins below T=3072 at small
    # shapes, re-verified at the 85M config itself) and
    # spc10_noremat_b8 drops remat at batch 8 (remat's recomputed
    # forward inflates step time by ~1/3 without appearing in model
    # flops, so model-flops MFU understates the chip where HBM permits
    # no-remat). Per-arm batch/remat make the flops/tokens accounting
    # per-arm; the run-level fields describe the baseline arms only.
    n85 = 86_039_040
    record["run_85m"] = {
        "baseline_config": "d768/h12/L12 byte vocab, seq 1024, "
                           "batch 16, bf16 + remat, donated buffers "
                           "(per-arm batch/remat/flops recorded on "
                           "each arm)",
        "arms": {},
    }

    # arm: (name, steps_per_call, batch, remat, extra_env)
    def run_arm(arm_name, k, batch, remat, extra_env):
        if left() < 300:
            record["run_85m"]["arms"][arm_name] = {"skipped": "budget"}
            return
        metrics = os.path.join(ART, f"metrics_85m_{arm_name}.jsonl")
        rc, out, err = _run_cli(
            ["--d-model", "768", "--heads", "12", "--layers", "12",
             "--seq-len", "1024", "--steps", str(args.steps_85m),
             "--batch-size", str(batch), "--bf16",
             *(["--remat"] if remat else []),
             "--lr", "3e-4", "--lr-schedule", "cosine",
             "--warmup-steps", "20", "--steps-per-call", str(k),
             "--log-every", "10", "--metrics-out", metrics],
            timeout=min(left(), 900), extra_env=extra_env,
        )
        hist = _read_history(metrics)
        ss = steady_state(hist)
        arm = {
            "rc": rc, "cmd_steps_per_call": k, "batch": batch,
            "remat": remat,
            "model_flops_per_step": model_flops_per_step(
                n85, batch, 1024, 768, 12
            ),
            "steady_state": ss,
            "final_report": _final_report(metrics),
        }
        if extra_env:
            arm["env"] = extra_env
        if ss:
            tf = arm["model_flops_per_step"] / ss["s_per_step"] / 1e12
            arm["model_tflops_steady"] = round(tf, 2)
            arm["mfu"] = round(tf / PEAK_TFLOPS_V5E, 4)
            arm["tokens_per_sec"] = round(batch * 1024 / ss["s_per_step"])
        if rc != 0:
            arm["stderr_tail"] = err[-500:]
        record["run_85m"]["arms"][arm_name] = arm
        _flush(record)

    for spec in (("spc1", 1, 16, True, None), ("spc10", 10, 16, True, None)):
        run_arm(*spec)

    # ---- Leg 2: short profiler trace of the 85M step ----------------
    if left() > 240:
        trace_dir = os.path.join(ART, "trace_85m")
        rc, out, err = _run_cli(
            ["--d-model", "768", "--heads", "12", "--layers", "12",
             "--seq-len", "1024", "--steps", "16", "--batch-size", "16",
             "--bf16", "--remat", "--lr", "3e-4",
             "--steps-per-call", "4", "--log-every", "4",
             "--profile-dir", trace_dir],
            timeout=min(left(), 600),
        )
        tb = sum(
            os.path.getsize(os.path.join(r, f))
            for r, _, fs in os.walk(trace_dir) for f in fs
        ) if os.path.isdir(trace_dir) else 0
        record["trace_85m"] = {"rc": rc, "trace_bytes": tb}
        _flush(record)

    # ---- Leg 3: 25.5M with VALID held-out eval ----------------------
    if left() > 240:
        n25 = 25_543_168  # d512/h8/L8 byte-vocab param count (r4 record)
        metrics = os.path.join(ART, "metrics_25m.jsonl")
        rc, out, err = _run_cli(
            ["--d-model", "512", "--heads", "8", "--layers", "8",
             "--seq-len", "512", "--steps", "600", "--batch-size", "32",
             "--bf16", "--lr", "3e-4", "--lr-schedule", "cosine",
             "--warmup-steps", "40", "--steps-per-call", "10",
             "--log-every", "20", "--metrics-out", metrics],
            timeout=min(left(), 900),
        )
        hist = _read_history(metrics)
        ss = steady_state(hist)
        leg = {
            "rc": rc,
            "steady_state": ss,
            "final_report": _final_report(metrics),
            "eval_note": "eval_split must be 'held-out' now: the 8 MB "
                         "corpus leaves ~780 eval rows at seq 512 "
                         "(r4: 'full-dataset', train overlap)",
        }
        if ss:
            leg["tokens_per_sec"] = round(32 * 512 / ss["s_per_step"])
        if rc != 0:
            leg["stderr_tail"] = err[-500:]
        record["run_25m"] = leg
        _flush(record)

    # ---- Leg 4: queued seq-8192 long-context run --------------------
    if not args.skip_8k and left() > 240:
        metrics = os.path.join(ART, "metrics_seq8k.jsonl")
        rc, out, err = _run_cli(
            ["--d-model", "256", "--heads", "8", "--layers", "4",
             "--seq-len", "8192", "--steps", "60", "--batch-size", "2",
             "--bf16", "--remat", "--lr", "3e-4", "--warmup-steps", "10",
             "--log-every", "10", "--metrics-out", metrics],
            timeout=min(left(), 900),
        )
        hist = _read_history(metrics)
        ss = steady_state(hist)
        leg = {
            "rc": rc, "steady_state": ss,
            "final_report": _final_report(metrics),
            "note": "flash training path (T=8192 >= FLASH_MIN_SEQ); "
                    "the r4 attempt degraded to host CPU when the "
                    "tunnel dropped and was aborted",
        }
        if ss:
            leg["tokens_per_sec"] = round(2 * 8192 / ss["s_per_step"])
        if rc != 0:
            leg["stderr_tail"] = err[-500:]
        record["run_seq8k"] = leg
        _flush(record)

    # ---- Probe arms LAST (never at a deliverable's expense) ---------
    for spec in (
        ("spc10_flash", 10, 16, True, {"TDN_FLASH_MIN_SEQ": "1024"}),
        ("spc10_noremat_b8", 10, 8, False, None),
    ):
        run_arm(*spec)

    # Green only if every DELIVERABLE leg that ran succeeded, the
    # headline arm produced an MFU, and no deliverable was
    # budget-skipped (a dead-tunnel or half-finished run must exit
    # nonzero so the watcher keeps retrying in later windows). The
    # flash-forced arm is a PROBE: its rc is recorded but a failure at
    # the never-before-exercised T=1024 training shape must not force
    # endless re-runs of an otherwise complete suite.
    deliverables = [
        record.get("run_85m", {}).get("arms", {}).get("spc1"),
        record.get("run_85m", {}).get("arms", {}).get("spc10"),
        record.get("trace_85m"), record.get("run_25m"),
        record.get("run_seq8k"),
    ]
    # Absent legs (budget ran out before they were attempted) and
    # {"skipped": "budget"} arms both lack rc == 0, so one test covers
    # every not-actually-done shape.
    rcs = [leg.get("rc") if isinstance(leg, dict) else None
           for leg in deliverables]
    mfu = record.get("run_85m", {}).get("arms", {}).get("spc10", {}).get("mfu")
    ok = all(rc == 0 for rc in rcs) and mfu is not None
    record["ok"] = ok
    _flush(record)
    print(json.dumps({
        "ok": ok, "leg_rcs": rcs, "mfu_spc10": mfu,
        "record": os.path.join(ART, "RECORD.json"),
    }))
    return 0 if ok else 1


def _flush(record: dict) -> None:
    with open(os.path.join(ART, "RECORD.json"), "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")


if __name__ == "__main__":
    sys.exit(main())
