"""Shape sweep on live hardware: where do the Pallas kernels win?

The first honest live-TPU measurements (artifacts/tpu_r04/
resident_probe.json) showed XLA's own fusion beating the hand-written
fused/int8 Pallas chains ~3x at the flagship's tiny widths
(784-128-64-10). This sweep maps the crossover: dense chains at
growing widths (f32 XLA vs fused Pallas vs int8 jnp vs int8 Pallas)
and attention at growing sequence lengths (XLA dot-product attention
vs the flash kernel, forward and forward+grad) — so kernel selection
can be gated on measured wins, not assumptions.

Timing: the fetch-barrier + anti-replay methodology proven in
bench.py::_time_resident (block_until_ready does not block on the
tunneled platform; identical executions replay from a cache).

Emits one JSON line per configuration plus a trailing summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--init-timeout", type=float, default=90.0)
    ap.add_argument("--target-s", type=float, default=0.4,
                    help="target chained-compute seconds per timed call")
    ap.add_argument("--only", choices=("dense", "attn"), default=None)
    args = ap.parse_args()

    import jax

    from tpu_dist_nn.utils.backend import init_watchdog

    def _hung():
        print(json.dumps({"error": "backend init hung"}), flush=True)
        os._exit(2)

    with init_watchdog(args.init_timeout, _hung):
        devices = jax.devices()
    backend = jax.default_backend()
    kind = devices[0].device_kind

    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from tpu_dist_nn.kernels.fused_dense import _fcnn_fused_call
    from tpu_dist_nn.kernels.flash_attention import flash_attention
    from tpu_dist_nn.kernels.quantized import (
        fcnn_quantized_forward,
        forward_quantized,
        quantize_fcnn,
    )
    from tpu_dist_nn.models.fcnn import forward, init_fcnn

    # RTT floor (see bench.py::_rtt_floor).
    @jax.jit
    def _trivial(seed):
        return seed * jnp.float32(2.0) + jnp.float32(1.0)

    np.asarray(_trivial(jnp.float32(0.5)))
    floor = min(
        _timeit(lambda: np.asarray(_trivial(jnp.float32(1000.0 + i))))
        for i in range(5)
    )
    seed_counter = [float(np.random.default_rng().integers(1 << 20))]

    def measure(fn, x, iters):
        """Per-pass seconds for fn(x) via seeded chained fori_loop.

        Auto-calibrates: if the chained signal lands under 0.1 s above
        the RTT floor, scales ``iters`` up (recompiling) until it
        clears, so fast paths at small shapes aren't refused and slow
        paths don't over-run.
        """
        for _attempt in range(4):
            @jax.jit
            def run(bx, seed, _k=iters):
                def body(_, carry):
                    eps, acc = carry
                    out = fn(bx + eps)
                    s = out.reshape(-1)[0].astype(jnp.float32)
                    return (s * jnp.float32(1e-30)).astype(bx.dtype), acc + s

                out0 = fn(bx + (seed * jnp.float32(1e-30)).astype(bx.dtype))
                s0 = out0.reshape(-1)[0].astype(jnp.float32)
                _, acc = lax.fori_loop(
                    0, _k, body,
                    ((s0 * jnp.float32(1e-30)).astype(bx.dtype), s0),
                )
                return acc

            def timed():
                seed_counter[0] += 1.0
                s = jnp.float32(seed_counter[0])
                t0 = time.monotonic()
                np.asarray(run(x, s))
                return time.monotonic() - t0

            timed()  # compile
            best = min(timed() for _ in range(args.reps))
            signal = best - floor
            if signal >= 0.1:
                return signal / (iters + 1), iters
            # Estimate per-pass from what we saw (floor jitter makes
            # tiny signals unreliable: assume at least 2 ms of signal)
            per = max(signal, 0.002) / (iters + 1)
            iters = min(int(0.25 / per), iters * 20)
        return None, iters

    records = []

    # ---- dense chains: width sweep, depth 3, batch 8192 ----
    batch = 8192
    widths = (512, 1024, 2048, 4096) if args.only in (None, "dense") else ()
    for width in widths:
        dims = [width, width, width, width]
        params = init_fcnn(jax.random.key(0), dims)
        qp = quantize_fcnn(params)
        acts = ("relu", "relu", "softmax")
        shapes = tuple((p["w"].shape, p["b"].shape) for p in params)
        x = jax.device_put(jnp.asarray(
            np.random.default_rng(1).uniform(0, 1, (batch, width)),
            jnp.float32))

        flops = 2 * batch * sum(
            a * b for a, b in ((width, width),) * 3
        )
        # iters sized so chained compute ~ target_s, assuming >=10 TFLOPS
        guess = max(8, min(400, int(args.target_s / (flops / 10e12))))

        paths = {
            "f32_xla": lambda bx, p=params: forward(p, bx),
            "f32_fused": lambda bx, s=shapes, p=params: _fcnn_fused_call(
                s, acts, 512, None, bx,
                *[t for q in p for t in (q["w"], q["b"])]),
            "int8_jnp": lambda bx, q=qp: forward_quantized(q, bx, acts),
            "int8_fused": lambda bx, q=qp: fcnn_quantized_forward(
                q, bx, activations=acts),
        }
        rec = {"kind": "dense", "width": width, "depth": 3, "batch": batch}
        for name, fn in paths.items():
            try:
                t, used = measure(fn, x, guess)
            except Exception as e:
                print(f"# dense w={width} {name}: {type(e).__name__}: {e}",
                      file=sys.stderr)
                t, used = None, guess
            rec[name] = (
                {"per_pass_s": round(t, 9), "iters": used,
                 "tflops": round(flops / t / 1e12, 2)}
                if t else None
            )
        records.append(rec)
        print(json.dumps(rec), flush=True)

    # ---- attention: seq sweep, bf16, (B, T, H, Dh) ----
    B, H, Dh = 4, 8, 64
    seqs = (1024, 2048, 4096) if args.only in (None, "attn") else ()
    for T in seqs:
        q = jax.random.normal(jax.random.key(3), (B, T, H, Dh), jnp.bfloat16)
        k = jax.random.normal(jax.random.key(4), (B, T, H, Dh), jnp.bfloat16)
        v = jax.random.normal(jax.random.key(5), (B, T, H, Dh), jnp.bfloat16)
        scale = 1.0 / float(np.sqrt(Dh))

        def xla_attn(qq, kk, vv):
            # (B, T, H, Dh) -> heads-major einsum attention, causal
            logits = jnp.einsum("bqhd,bkhd->bhqk", qq, kk) * scale
            mask = jnp.tril(jnp.ones((T, T), bool))
            logits = jnp.where(mask[None, None], logits.astype(jnp.float32),
                               -jnp.inf)
            probs = jax.nn.softmax(logits, axis=-1).astype(qq.dtype)
            return jnp.einsum("bhqk,bkhd->bqhd", probs, vv)

        # attention FLOPs: 2*B*H*T^2*Dh (QK) * 2 (PV), causal halves
        flops = 2 * 2 * B * H * T * T * Dh // 2
        guess = max(4, min(200, int(args.target_s / (flops / 20e12))))

        paths = {
            "attn_xla": lambda qq: xla_attn(qq, k, v),
            "attn_flash": lambda qq: flash_attention(qq, k, v, causal=True),
            "attn_xla_grad": lambda qq: jax.grad(
                lambda z: xla_attn(z, k, v).astype(jnp.float32).sum()
            )(qq),
            "attn_flash_grad": lambda qq: jax.grad(
                lambda z: flash_attention(
                    z, k, v, causal=True).astype(jnp.float32).sum()
            )(qq),
        }
        rec = {"kind": "attention", "B": B, "T": T, "H": H, "Dh": Dh,
               "causal": True}
        for name, fn in paths.items():
            try:
                t, used = measure(fn, q, guess)
            except Exception as e:
                print(f"# attn T={T} {name}: {type(e).__name__}: {e}",
                      file=sys.stderr)
                t, used = None, guess
            fl = flops * (2.5 if "grad" in name else 1.0)  # bwd ~ 2.5x fwd
            rec[name] = (
                {"per_pass_s": round(t, 9), "iters": used,
                 "tflops": round(fl / t / 1e12, 2)}
                if t else None
            )
        records.append(rec)
        print(json.dumps(rec), flush=True)

    def _ratio(rec, a, b):
        if rec.get(a) and rec.get(b):
            return round(rec[b]["per_pass_s"] / rec[a]["per_pass_s"], 3)
        return None

    summary = {
        "backend": backend, "device_kind": kind,
        "rtt_floor_s": round(floor, 6),
        "dense_fused_speedup_vs_xla": {
            str(r["width"]): _ratio(r, "f32_fused", "f32_xla")
            for r in records if r["kind"] == "dense"},
        "dense_int8jnp_speedup_vs_xla": {
            str(r["width"]): _ratio(r, "int8_jnp", "f32_xla")
            for r in records if r["kind"] == "dense"},
        "attn_flash_speedup_vs_xla": {
            str(r["T"]): _ratio(r, "attn_flash", "attn_xla")
            for r in records if r["kind"] == "attention"},
        "attn_flash_grad_speedup_vs_xla": {
            str(r["T"]): _ratio(r, "attn_flash_grad", "attn_xla_grad")
            for r in records if r["kind"] == "attention"},
    }
    print(json.dumps(summary), flush=True)
    return 0


def _timeit(fn):
    t0 = time.monotonic()
    fn()
    return time.monotonic() - t0


if __name__ == "__main__":
    sys.exit(main())
