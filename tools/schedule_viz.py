"""Render a compiled schedule table as ASCII — one row per device, one
column per tick — for debugging table builders and documenting what
each schedule actually does.

Glyphs: ``.`` idle, ``F`` forward, ``B`` combined backward, ``b``
split input-grad (BWD_B), ``w`` split weight-grad (BWD_W); the digit
row below each device row is the op's local chunk slot. Routing
annotations (``send_rev``): lowercase suffix ``<`` = this op's output
rides the OPPOSITE ring, ``o`` = self loopback (the ZB-V apex).

Usage:

    PYTHONPATH=. python tools/schedule_viz.py --schedule zb-v --stages 4 --microbatches 4
    PYTHONPATH=. python tools/schedule_viz.py --schedule zb --stages 4 --virtual 2 --microbatches 8
"""

from __future__ import annotations

import argparse

from tpu_dist_nn.parallel.schedule_table import (
    BWD,
    BWD_B,
    BWD_W,
    FWD,
    ScheduleTables,
    build_interleaved_1f1b,
    build_zb_v,
    build_zero_bubble,
)

GLYPH = {FWD: "F", BWD: "B", BWD_B: "b", BWD_W: "w"}


def render(tb: ScheduleTables, *, chunks: bool = True) -> str:
    lines = [
        f"placement={tb.placement}  S={tb.num_devices}  V={tb.num_chunks}  "
        f"M={tb.num_microbatches}  ticks={tb.ticks}  "
        f"bubble={tb.bubble_ticks} chunk-ticks  "
        f"slots: stash={tb.stash_slots} abuf={tb.abuf_slots} "
        f"gbuf={tb.gbuf_slots} dybuf={tb.dybuf_slots}"
    ]
    rev = tb.send_rev_or_default()
    for s in range(tb.num_devices):
        ops = []
        for t in range(tb.ticks):
            g = GLYPH.get(int(tb.op[s, t]), ".")
            if g != "." and rev[s, t] == 1:
                g += "<"
            elif g != "." and rev[s, t] == 2:
                g += "o"
            ops.append(g.ljust(2))
        lines.append(f"dev {s}: " + "".join(ops))
        if chunks:
            cs = [
                (str(int(tb.chunk[s, t])) if tb.op[s, t] != 0 else " ").ljust(2)
                for t in range(tb.ticks)
            ]
            lines.append("chunk: " + "".join(cs))
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--schedule", choices=["interleaved", "zb", "zb-v"],
                    default="zb-v")
    ap.add_argument("--stages", type=int, default=4)
    ap.add_argument("--virtual", type=int, default=1,
                    help="chunks per device (interleaved/zb; zb-v is 2 "
                         "by placement)")
    ap.add_argument("--microbatches", type=int, default=4)
    ap.add_argument("--no-chunks", action="store_true")
    args = ap.parse_args()
    if args.schedule == "zb-v":
        tb = build_zb_v(args.stages, args.microbatches)
    elif args.schedule == "zb":
        tb = build_zero_bubble(args.stages, args.virtual, args.microbatches)
    else:
        tb = build_interleaved_1f1b(args.stages, args.virtual, args.microbatches)
    print(render(tb, chunks=not args.no_chunks))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
